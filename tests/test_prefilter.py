"""Proxy conflict pre-filter (ISSUE 17): the decaying committed-write
summary, its strictly-conservative contract, the resolver feedback loop,
and the in-sim oracle differential.

The load-bearing property is conservative-only: the filter may MISS
conflicts (decay, eviction, truncation — all fine, the resolver still
convicts), but must NEVER reject a transaction the resolver would have
committed. Unit tests prove each forgetting path only produces false
negatives; sim tests drive hot-keyspace contention so the filter
actually fires, and every pre-rejection is differentially re-proven
against authoritative history (a false rejection raises inside the sim).
"""

import pytest

from foundationdb_tpu.client import management
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.conflict.prefilter import ConflictPrefilter, _strinc
from foundationdb_tpu.net.sim import Endpoint, Sim
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.workloads import ConflictRangeWorkload, run_workloads
from foundationdb_tpu.workloads.readwrite import ReadWriteWorkload


# -- unit: summary mechanics ---------------------------------------------------


def test_strinc():
    assert _strinc(b"a") == b"b"
    assert _strinc(b"ab") == b"ac"
    assert _strinc(b"a\xff") == b"b"  # carry pops the 0xff tail
    assert _strinc(b"\xff\xff") is None  # open-ended: no successor


def _pf(**kw):
    return ConflictPrefilter(Knobs(**kw))


def test_check_requires_exact_overlap_at_newer_version():
    pf = _pf()
    pf.feed([(100, [(b"k/a", b"k/b")])])
    # overlap + older snapshot → reject
    assert pf.check(50, [(b"k/a", b"k/a\x00")])
    # snapshot at/after the committed version → commit-safe, no reject
    assert not pf.check(100, [(b"k/a", b"k/a\x00")])
    assert not pf.check(150, [(b"k/a", b"k/a\x00")])
    # disjoint read (half-open: end is exclusive) → no reject
    assert not pf.check(50, [(b"k/b", b"k/c")])
    assert not pf.check(50, [(b"k/0", b"k/a")])
    # empty read set (blind write) can never be rejected
    assert not pf.check(50, [])


def test_wide_ranges_take_the_side_list():
    pf = _pf(PREFILTER_PREFIX_LEN=4)
    pf.feed([(100, [(b"aaaa0", b"zzzz9")])])  # spans many prefixes
    assert len(pf.wide) == 1 and not pf.buckets
    assert pf.check(50, [(b"mmmm", b"mmmm\x00")])  # middle of the span
    assert not pf.check(150, [(b"mmmm", b"mmmm\x00")])


def test_floor_advance_forgets_conservatively():
    pf = _pf()
    pf.feed([(100, [(b"k/a", b"k/b")]), (300, [(b"k/c", b"k/d")])])
    assert pf.check(50, [(b"k/a", b"k/b")])
    pf.note_floor(200)  # resolver forgot everything <= 200
    # the v=100 entry is gone: the reject turns into a (safe) miss
    assert not pf.check(50, [(b"k/a", b"k/b")])
    assert pf.check(50, [(b"k/c", b"k/d")])  # v=300 survives
    assert pf._ranges_decayed == 1
    # feeds at/below the floor are ignored (already forgotten history)
    pf.feed([(150, [(b"k/e", b"k/f")])])
    assert not pf.check(50, [(b"k/e", b"k/f")])


def test_eviction_only_forgets():
    pf = _pf(PREFILTER_BUCKET_ENTRIES=2, PREFILTER_MAX_BUCKETS=2,
             PREFILTER_WIDE_RANGES=1)
    # bucket-entry eviction: 3rd entry in one bucket pops the oldest
    pf.feed([(10, [(b"k/a", b"k/b")]), (20, [(b"k/b", b"k/c")]),
             (30, [(b"k/c", b"k/d")])])
    assert not pf.check(5, [(b"k/a", b"k/b")])  # evicted → miss, not wrong
    assert pf.check(5, [(b"k/c", b"k/d")])
    # whole-bucket eviction under the bucket cap
    pf.feed([(40, [(b"m/a", b"m/b")]), (50, [(b"n/a", b"n/b")])])
    assert len(pf.buckets) <= 2
    # wide-list overflow keeps the newest
    pf.feed([(60, [(b"a0", b"z9")]), (70, [(b"b0", b"y9")])])
    assert len(pf.wide) == 1 and pf.wide[0][2] == 70
    assert pf._ranges_decayed > 0


def test_reset_forgets_everything():
    pf = _pf()
    pf.feed([(100, [(b"k/a", b"k/b")])], version_floor=0)
    pf.reset(floor=500)
    assert not pf.check(50, [(b"k/a", b"k/b")])
    assert pf.floor == 500 and not pf.buckets and not pf.wide


# -- sim: feedback loop + differential oracle ----------------------------------


def _hot_cluster(seed, knobs=None, keyspace=10, actors=8, txns=25):
    sim = Sim(seed=seed, knobs=knobs)
    sim.activate()
    cluster = DynamicCluster(
        sim,
        ClusterConfig(n_proxies=2, n_resolvers=2, n_tlogs=1, n_storage=2),
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    wl = ReadWriteWorkload(
        db, sim.loop.random.fork(), actors=actors, txns_per_actor=txns,
        reads_per_txn=4, writes_per_txn=2, keyspace=keyspace, prefix=b"hot/",
    )
    return sim, cluster, db, wl


def _status(sim, cluster, db, workloads):
    async def body():
        await run_workloads(workloads)
        return await management.get_status(cluster.coordinators, db.client)

    return sim.run_until_done(spawn(body()), 1800.0)


def test_prefilter_fires_under_contention_and_oracle_holds():
    """Hot keyspace → the summary learns committed ranges from resolver
    feedback and pre-rejects doomed txns; every rejection re-proven by
    the differential oracle; the status/abort-rate surface populates."""
    sim, cluster, db, wl = _hot_cluster(seed=1701)
    doc = _status(sim, cluster, db, [wl])
    wld = doc["workload"]
    pre = wld["prefiltered"]["counter"]
    assert pre > 0, wld
    assert wld["prefilter"]["checks"]["counter"] >= pre
    assert wld["prefilter"]["feedback_ranges"]["counter"] > 0
    assert 0.0 < wld["abort_rate"] <= 1.0
    # the oracle actually audited those rejections — zero violations
    assert sim.prefilter_oracle.rejections_checked >= pre
    assert not sim.prefilter_oracle.violations


def test_prefilter_knob_off_is_inert():
    sim, cluster, db, wl = _hot_cluster(
        seed=1701, knobs=Knobs(PROXY_CONFLICT_PREFILTER=False)
    )
    doc = _status(sim, cluster, db, [wl])
    wld = doc["workload"]
    assert wld["prefiltered"]["counter"] == 0
    assert wld["prefilter"]["checks"]["counter"] == 0
    assert sim.prefilter_oracle.rejections_checked == 0
    # abort-rate surface works without the filter too
    assert wld["abort_rate"] > 0.0


def test_conflict_oracle_workload_exact_with_prefilter():
    """ConflictRangeWorkload asserts EXACT conflict counts — a false
    rejection (or a filter-induced missed conflict) fails it."""
    sim, cluster, db, _ = _hot_cluster(seed=77)
    wl = ConflictRangeWorkload(
        db, sim.loop.random.fork(), rounds=12, keyspace=16
    )
    _status(sim, cluster, db, [wl])
    assert not sim.prefilter_oracle.violations


def test_journal_pressure_shrinks_summary_zero_false_rejections():
    """Pinned-seed shrink test (ISSUE 17 satellite): a tiny resolver
    journal forces the version floor to jump under capacity pressure
    (the same mechanism a rollback/failover replay uses), the feedback
    propagates the jump, and the proxy summaries shrink with it — with
    zero false rejections throughout, proven by the differential."""
    knobs = Knobs(CONFLICT_JOURNAL_CAPACITY=4)
    sim, cluster, db, wl = _hot_cluster(seed=424, knobs=knobs)
    _status(sim, cluster, db, [wl])
    # find the live proxies' prefilters and check the floor advanced
    # (the journal's capacity evictions must have pushed it up)
    floors = []
    for p in sim.processes.values():
        wk = getattr(p, "worker", None)
        if wk is None or not p.alive:
            continue
        for h in wk.roles.values():
            if h.kind == "proxy" and getattr(h.obj, "prefilter", None):
                floors.append(h.obj.prefilter.floor)
    assert floors and max(floors) > 0, floors
    assert not sim.prefilter_oracle.violations


def test_prefilter_survives_recovery_chaos():
    """Attrition-style chaos (a proxy/resolver death forces recovery;
    replacement proxies start with EMPTY summaries, replacement
    resolvers replay the journal): the differential must stay clean."""
    from foundationdb_tpu.workloads import AttritionWorkload

    sim, cluster, db, wl = _hot_cluster(seed=99, actors=6, txns=20)
    chaos = AttritionWorkload(
        db, sim.loop.random.fork(), sim=sim, kills=2, interval=3.0,
        protect=set(cluster.coordinators),
    )
    _status(sim, cluster, db, [wl, chaos])
    assert not sim.prefilter_oracle.violations


def test_prefilter_span_attributed_in_critical_path():
    """A pre-rejected transaction's self-time lands on the
    Proxy.prefilter stage in the span waterfall (satellite 2)."""
    from foundationdb_tpu.runtime.trace import TraceLog, set_trace_log
    from foundationdb_tpu.tools import trace_analyze as ta

    log = TraceLog()
    set_trace_log(log)
    try:
        sim, cluster, db, wl = _hot_cluster(seed=1701)
        sim.knobs.TRACE_SAMPLE_RATE = 1.0
        doc = _status(sim, cluster, db, [wl])
        assert doc["workload"]["prefiltered"]["counter"] > 0
        spans = [e for e in log.events if e.get("Type") == "Span"]
        pf_spans = [s for s in spans if s.get("Name") == "Proxy.prefilter"]
        assert pf_spans, "no Proxy.prefilter spans at sample rate 1.0"
        # nested under the commit: parent chain gives the stage a home
        cp = ta.critical_path(log.events, root_prefix="Client.commit")
        stages = {
            s["stage"]
            for agg in cp.values()
            for s in agg.get("stages", [])
        }
        assert "Proxy.prefilter" in stages, stages
    finally:
        set_trace_log(TraceLog())


def test_cli_status_renders_prefilter_and_abort_rate():
    """`cli status` shows the abort rate on the Workload line and a
    Prefilter line once the filter has fired (satellite 1 + tentpole)."""
    from foundationdb_tpu.tools.cli import FdbCli

    sim, cluster, db, wl = _hot_cluster(seed=1701)
    cli = FdbCli(db, cluster.coordinators)

    async def body():
        await run_workloads([wl])
        return await cli.execute("status")

    out = sim.run_until_done(spawn(body()), 1800.0)
    assert "abort rate" in out, out
    assert "Prefilter:" in out and "pre-rejected" in out, out


# -- satellite 4: bindingtester byte-identical with the knob both ways ---------


def test_bindingtester_byte_identical_knob_both_ways():
    from tests.test_bindingtester import run_model, run_real

    seed, n_ops = 4217, 120
    stream, (data_on, log_on) = run_real(
        seed, n_ops, knobs=Knobs(PROXY_CONFLICT_PREFILTER=True)
    )
    _, (data_off, log_off) = run_real(
        seed, n_ops, knobs=Knobs(PROXY_CONFLICT_PREFILTER=False)
    )
    data_model, log_model = run_model(stream)
    assert data_on == data_off == data_model
    assert log_on == log_off == log_model
