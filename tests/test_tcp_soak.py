"""TCP chaos soak (verdict r3 next-step #1's 'TCP soak variant'): real OS
processes, repeated kill+restart rounds with datadir resurrection, every
key ever written verified each round. CI runs a short soak; longer runs
via `python -m foundationdb_tpu.tools.tcp_soak N`."""

from foundationdb_tpu.tools.tcp_soak import soak


def test_tcp_soak_two_rounds():
    soak(rounds=2, seed=1, keys_per_round=5)
