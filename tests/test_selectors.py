"""Key-selector subsystem: the KeySelector type, the storage getKey
endpoint (offset walks, shard-boundary continuation), the client findKey
loop and RYW overlay resolution, selector-endpoint ranges, and the
oracle-checked selector fuzz workload under the deterministic sim."""

import bisect

import pytest

from foundationdb_tpu.client import Database, KeySelector
from foundationdb_tpu.client.transaction import strinc
from foundationdb_tpu.kv.selector import SELECTOR_END, as_selector, resolve
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.server import Cluster, ClusterConfig
from foundationdb_tpu.workloads import SelectorFuzzWorkload, run_workloads

KS = KeySelector


# -- pure resolution semantics -------------------------------------------------


def test_constructors_and_offsets():
    ks = [b"a", b"b", b"c", b"d"]
    assert resolve(ks, KS.first_greater_or_equal(b"b")) == b"b"
    assert resolve(ks, KS.first_greater_than(b"b")) == b"c"
    assert resolve(ks, KS.last_less_than(b"b")) == b"a"
    assert resolve(ks, KS.last_less_or_equal(b"b")) == b"b"
    # anchors between keys
    assert resolve(ks, KS.first_greater_or_equal(b"bb")) == b"c"
    assert resolve(ks, KS.last_less_or_equal(b"bb")) == b"b"
    # offset arithmetic pages through the keyspace
    assert resolve(ks, KS.first_greater_or_equal(b"a") + 2) == b"c"
    assert resolve(ks, KS.last_less_or_equal(b"d") - 1) == b"c"
    # clamps: past-begin -> b"", past-end -> SELECTOR_END
    assert resolve(ks, KS.last_less_than(b"a")) == b""
    assert resolve(ks, KS.first_greater_than(b"d")) == SELECTOR_END
    assert resolve(ks, KS.first_greater_or_equal(b"a") - 10) == b""
    assert resolve(ks, KS.first_greater_or_equal(b"a") + 10) == SELECTOR_END
    # system keys are invisible to walks
    assert resolve(ks + [b"\xff/sys"], KS.first_greater_than(b"d")) == SELECTOR_END


def test_resolution_matches_bisect_bruteforce(rng):
    keys = sorted({b"%03d" % rng.randrange(200) for _ in range(60)})
    for _ in range(500):
        anchor = b"%03d" % rng.randrange(200)
        or_equal = rng.random() < 0.5
        offset = rng.randrange(-5, 6)
        sel = KeySelector(anchor, or_equal, offset)
        k, off = sel.normalized()
        i = bisect.bisect_left(keys, k) - 1 + off
        want = b"" if i < 0 else (SELECTOR_END if i >= len(keys) else keys[i])
        assert resolve(keys, sel) == want


def test_as_selector_coerces_bare_keys():
    sel = as_selector(b"k")
    assert (sel.key, sel.or_equal, sel.offset) == (b"k", False, 1)
    assert as_selector(sel) is sel


# -- cluster harness -----------------------------------------------------------


def _cluster(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(**cfg))
    db = Database(sim, cluster.proxy_addrs)
    return sim, cluster, db


# keys on both sides of the 2-team shard split at 0x80
CROSS_SHARD_KEYS = sorted(
    bytes([b]) + b"k%02d" % i for b in (0x20, 0x70, 0x90, 0xE0) for i in range(5)
)


async def _seed_keys(db, keys):
    async def body(tr):
        for k in keys:
            tr.set(k, b"v" + k)

    await db.run(body)


def test_get_key_cross_shard_walks():
    """Offset walks crossing the team split follow the storage getKey
    partial-resolution protocol shard to shard (findKey)."""
    sim, _cl, db = _cluster(seed=3, n_storage=4, replication=2)

    async def go():
        await _seed_keys(db, CROSS_SHARD_KEYS)
        tr = db.transaction()
        sk = CROSS_SHARD_KEYS
        for anchor in [sk[0], sk[3], sk[9], sk[10], sk[19], b"\x80", b"", b"\xf0"]:
            for off in (-25, -3, -1, 0, 1, 2, 8, 25):
                for or_equal in (False, True):
                    sel = KeySelector(anchor, or_equal, off)
                    got = await tr.get_key(sel, snapshot=True)
                    assert got == resolve(sk, sel), (anchor, or_equal, off)
        return True

    assert sim.run_until_done(spawn(go()), 600.0)


def test_get_key_ryw_overlay_shifts_resolution():
    """Uncommitted sets insert keys into the walk; clears remove them."""
    sim, _cl, db = _cluster(seed=5, n_storage=2, replication=1)

    async def go():
        keys = [b"m%02d" % i for i in range(6)]
        await _seed_keys(db, keys)
        tr = db.transaction()
        tr.set(b"m025", b"inserted")  # between m02 and m03
        tr.clear(b"m04")
        view = sorted(set(keys) - {b"m04"} | {b"m025"})
        for anchor in (b"m00", b"m02", b"m025", b"m03", b"m05", b"zz"):
            for off in (-7, -2, 0, 1, 3, 7):
                sel = KS.first_greater_or_equal(anchor) + off
                got = await tr.get_key(sel, snapshot=True)
                assert got == resolve(view, sel), (anchor, off)
        # atomic-chain keys surface in walks too (merged-path coverage)
        from foundationdb_tpu.kv.mutations import MutationType

        tr.atomic_op(MutationType.ADD, b"m015", b"\x01" + b"\x00" * 7)
        got = await tr.get_key(KS.first_greater_than(b"m01"), snapshot=True)
        assert got == b"m015"
        return True

    assert sim.run_until_done(spawn(go()), 600.0)


def test_get_key_conflict_spans_are_serializable():
    """A non-snapshot get_key conflict-protects the observed span: a
    write landing inside it between read and commit must conflict."""
    from foundationdb_tpu.errors import NotCommitted

    sim, _cl, db = _cluster(seed=7)

    async def go():
        await _seed_keys(db, [b"c01", b"c05"])
        tr = db.transaction()
        got = await tr.get_key(KS.first_greater_or_equal(b"c02"))
        assert got == b"c05"

        # an overlapping write commits first: c03 lands inside (c02, c05]
        async def intruder(t):
            t.set(b"c03", b"x")

        await db.run(intruder)
        tr.set(b"out/marker", b"y")
        try:
            await tr.commit()
            raise AssertionError("selector read did not conflict")
        except NotCommitted:
            pass
        return True

    assert sim.run_until_done(spawn(go()), 600.0)


def test_selector_endpoint_get_range():
    sim, _cl, db = _cluster(seed=11, n_storage=4, replication=2)

    async def go():
        await _seed_keys(db, CROSS_SHARD_KEYS)
        sk = CROSS_SHARD_KEYS
        tr = db.transaction()
        rows = await tr.get_range(
            KS.first_greater_or_equal(sk[2]), KS.first_greater_or_equal(sk[7])
        )
        assert [k for k, _ in rows] == sk[2:7]
        # selector/byte mix, reverse + limit, and an inverted (empty) range
        rows = await tr.get_range(sk[1], KS.first_greater_than(sk[4]))
        assert [k for k, _ in rows] == sk[1:5]
        rows = await tr.get_range(
            KS.last_less_than(sk[8]), KS.first_greater_than(sk[12]),
            limit=3, reverse=True,
        )
        assert [k for k, _ in rows] == [sk[12], sk[11], sk[10]]
        rows = await tr.get_range(
            KS.first_greater_or_equal(sk[9]), KS.first_greater_or_equal(sk[2])
        )
        assert rows == []
        return True

    assert sim.run_until_done(spawn(go()), 600.0)


# -- reverse-limited reads stay bounded (the 1<<30 fallback is gone) -----------


def test_reverse_limited_read_bounded_engine_reads():
    """A reverse-limited scan over a shard far larger than the limit must
    complete with engine reads proportional to the limit, not the shard
    (storage.py's old `want = 1 << 30` fallback)."""
    from foundationdb_tpu.runtime.futures import AsyncVar
    from foundationdb_tpu.server.storage import StorageServer

    sim = Sim(seed=1)
    sim.activate()
    ss = StorageServer(tag=0, log_config=AsyncVar(None), disk=sim.disk("d0"))
    n = 5000
    for i in range(n):
        ss.engine.set(b"r%06d" % i, b"v%d" % i)
    ss.version.set(10)
    ss.data.oldest_version = 10
    ss.data.latest_version = 10

    seen_limits = []
    real_read_range = ss.engine.read_range

    def spy(begin, end, limit=1 << 30, reverse=False):
        seen_limits.append(limit)
        return real_read_range(begin, end, limit=limit, reverse=reverse)

    ss.engine.read_range = spy
    rows = ss._read_range_merged(b"", b"\xff", 10, limit=25, reverse=True)
    assert [k for k, _ in rows] == [b"r%06d" % i for i in range(n - 1, n - 26, -1)]
    assert seen_limits, "reverse read never touched the engine"
    assert max(seen_limits) < 1000, (
        f"reverse-limited read requested {max(seen_limits)} engine rows "
        f"for a 25-row limit (unbounded fallback is back?)"
    )
    # tombstone-heavy window: chunks double but stay far below the shard
    for i in range(n - 200, n):
        ss.data.set(b"r%06d" % i, None if i % 2 else b"w", 10)
    seen_limits.clear()
    rows = ss._read_range_merged(b"", b"\xff", 10, limit=25, reverse=True)
    assert len(rows) == 25
    assert max(seen_limits) < 2000


def test_reverse_windows_through_client():
    """End-to-end reverse-limited range read through the client path."""
    sim, _cl, db = _cluster(seed=13)

    async def go():
        keys = [b"w%03d" % i for i in range(120)]
        await _seed_keys(db, keys)

        async def read(tr):
            return await tr.get_range(b"w", b"x", limit=7, reverse=True)

        rows = await db.run(read)
        assert [k for k, _ in rows] == sorted(keys, reverse=True)[:7]
        return True

    assert sim.run_until_done(spawn(go()), 600.0)


# -- oracle-checked fuzz under the deterministic sim ---------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_selector_fuzz_workload(seed):
    """Acceptance gate: the selector fuzz workload runs green under the
    deterministic sim across seeds, on a multi-team shape so walks cross
    shard boundaries."""
    sim = Sim(seed=seed)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(n_storage=4, replication=2))
    db = Database(sim, cluster.proxy_addrs)
    w = SelectorFuzzWorkload(db, sim.loop.random.fork(), transactions=10)
    sim.run_until_done(spawn(run_workloads([w])), 1800.0)


def test_selector_fuzz_workload_chaos():
    """Fuzz survives buggify (tiny replies, stale caches, slow replicas):
    the findKey continuation and merged windows under adversity."""
    sim = Sim(seed=4, chaos=True)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(n_storage=4, replication=2))
    db = Database(sim, cluster.proxy_addrs)
    w = SelectorFuzzWorkload(db, sim.loop.random.fork(), transactions=6)
    sim.run_until_done(spawn(run_workloads([w])), 1800.0)
