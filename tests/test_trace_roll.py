"""TraceLog size-based rolling + the trace-analyze consumer + the
system-monitor memory fix (ISSUE 5 satellites)."""

import os

from foundationdb_tpu.runtime.monitor import memory_kb
from foundationdb_tpu.runtime.trace import SevInfo, SevWarn, TraceLog
from foundationdb_tpu.tools.trace_analyze import analyze, format_summary, load_events


def _spam(log, n, event="Spam", sev=SevInfo):
    for i in range(n):
        log.log(sev, event, float(i) / 10, "p0", Fill="x" * 40, Seq=i)


def test_trace_log_rolls_at_size(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    log = TraceLog(path, max_file_bytes=2000, keep_files=3)
    _spam(log, 200)
    log.close()
    # ~100 B/event * 200 over a 2 KB threshold: several rolls, bounded set
    assert log.rolls >= 3
    assert os.path.exists(path)
    rolled = log.rolled_paths()
    assert len(rolled) == 3
    assert not os.path.exists(path + ".4"), "rolled set must stay bounded"
    for p in rolled:
        assert os.path.getsize(p) >= 2000  # each rolled file hit the threshold
    # the live file is below the threshold again
    assert os.path.getsize(path) < 2000


def test_trace_log_roll_keeps_latest_events_in_order(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    log = TraceLog(path, max_file_bytes=1500, keep_files=2)
    _spam(log, 120)
    log.close()
    events = load_events(path, keep_files=2)
    # oldest rolls are pruned, but the surviving stream is contiguous and
    # ends with the last event written
    seqs = [e["Seq"] for e in events]
    assert seqs == list(range(seqs[0], 120))


def test_trace_analyze_summary(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    log = TraceLog(path, max_file_bytes=1 << 20, keep_files=2)
    _spam(log, 30)
    _spam(log, 5, event="SlowTask", sev=SevWarn)
    log.log(
        SevInfo, "ProxyMetrics", 1.0, "p0", ID="px0", txnCommitOut=10, Elapsed=5.0
    )
    log.log(
        SevInfo, "ProxyMetrics", 6.0, "p0", ID="px0", txnCommitOut=25, Elapsed=5.0
    )
    log.close()
    summary = analyze(load_events(path), top=5)
    assert summary["events"] == 37
    assert summary["top_types"][0] == ("Spam", 30)
    assert dict(summary["top_warn_types"])["SlowTask"] == 5
    tl = summary["timelines"]["ProxyMetrics#px0"]
    assert tl["points"] == 2
    assert tl["first"]["txnCommitOut"] == 10 and tl["last"]["txnCommitOut"] == 25
    text = format_summary(summary)
    assert "SlowTask" in text and "ProxyMetrics#px0" in text


def test_memory_kb_reports_current_and_peak():
    cur, peak = memory_kb()
    assert cur > 0 and peak > 0
    # ru_maxrss is the high-water mark: current RSS can never legitimately
    # sit far above it (small slack for /proc-vs-rusage unit jitter)
    assert cur <= peak * 1.1
