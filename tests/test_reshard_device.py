"""Differential test: on-device resharding (grid.reshard_device) must
preserve the MVCC step function exactly — verified against the host
resharder and against continued verdict parity with the oracle."""

import random

import numpy as np
import pytest

from foundationdb_tpu.conflict import grid as G
from foundationdb_tpu.conflict.api import CommitTransaction, Verdict
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet


def _mk_batch(rnd, n_txns, keyspace, snap):
    txs = []
    for _ in range(n_txns):
        a = rnd.randrange(keyspace)
        b = a + 1 + rnd.randrange(5)
        c = rnd.randrange(keyspace)
        d = c + 1 + rnd.randrange(5)
        txs.append(
            CommitTransaction(
                read_snapshot=snap,
                read_conflict_ranges=[(b"%06d" % a, b"%06d" % b)],
                write_conflict_ranges=[(b"%06d" % c, b"%06d" % d)],
            )
        )
    return txs


def _state_function(state):
    """Materialize the full step function as {code: version} plus pivot
    list, for equivalence checks."""
    grid = np.asarray(state.grid)
    count = np.asarray(state.count)
    L = grid.shape[-1] - 1
    out = []
    for b in range(grid.shape[0]):
        for s in range(int(count[b])):
            out.append((tuple(int(x) for x in grid[b, s, :L]), int(grid[b, s, L])))
    # coalesce equal adjacent steps: representation may differ (bucket
    # pivots inject redundant boundaries), the FUNCTION must not
    out.sort()
    coalesced = []
    for k, v in out:
        if coalesced and coalesced[-1][1] == v:
            continue
        coalesced.append((k, v))
    return coalesced


def test_reshard_device_preserves_step_function():
    rnd = random.Random(5)
    cs = TpuConflictSet(key_width=8, capacity=1 << 10)
    for i in range(12):
        txs = _mk_batch(rnd, 40, 4000, i)
        cs.detect_batch(txs, i + 20, max(i - 6, 0))

    before = _state_function(cs._state)
    for n_buckets in (cs._B, cs._B * 2, max(cs._B // 2, 8)):
        new_state, pressure = G.reshard_device(cs._state, n_buckets, cs._S)
        if int(pressure) > cs._S:
            # legitimate overflow (too few buckets for the live rows):
            # the caller retries with more buckets; the state is unusable
            assert n_buckets < cs._B
            continue
        after = _state_function(new_state)
        assert after == before, f"step function changed at B={n_buckets}"
        # pivot invariants: slot 0 of live buckets is the pivot; pivots
        # strictly increasing over live buckets
        piv = np.asarray(new_state.pivots)
        cnt = np.asarray(new_state.count)
        grid = np.asarray(new_state.grid)
        live = [b for b in range(n_buckets) if cnt[b] > 0]
        for b in live:
            assert (grid[b, 0, :-1] == piv[b]).all()
        keys = [tuple(piv[b]) for b in live]
        assert keys == sorted(set(keys))


def test_reshard_device_mid_run_keeps_verdict_parity():
    rnd = random.Random(9)
    oracle = OracleConflictSet()
    cs = TpuConflictSet(key_width=8, capacity=1 << 10)
    for i in range(20):
        txs = _mk_batch(rnd, 30, 2000, i)
        want = oracle.detect_batch(list(txs), i + 30, max(i - 8, 0))
        got = cs.detect_batch(txs, i + 30, max(i - 8, 0))
        assert [Verdict(v) for v in got] == want, f"batch {i}"
        if i % 5 == 4:
            # force a rebalance between batches
            cs._reshard(cs._state)


def test_append_workload_floods_one_gap_and_recovers():
    """Regression: a batch writing many brand-new keys into a single gap
    (append workload past the last boundary) overflows the staging plane;
    recovery must escalate to a host reshard whose pivots include the key
    SAMPLE — a device rebalance over live boundaries alone cannot split
    that gap and would spin forever."""
    cs = TpuConflictSet(key_width=8, capacity=256)
    oracle = OracleConflictSet()

    def batch(keys, snap):
        return [
            CommitTransaction(
                read_snapshot=snap,
                write_conflict_ranges=[(k, k + b"\x00")],
            )
            for k in keys
        ]

    b1 = batch([b"a%02d" % i for i in range(20)], 0)
    b2 = batch([b"z%02d" % i for i in range(2 * cs._S)], 1)
    b3 = batch([b"z%02d" % i for i in range(2 * cs._S)], 1)
    b3[0].read_conflict_ranges = [(b"z00", b"z99")]
    for i, b in enumerate((b1, b2, b3)):
        got = cs.detect_batch(b, i + 2, 0)
        want = oracle.detect_batch(list(b), i + 2, 0)
        assert [Verdict(v) for v in got] == want, i
