"""flowlint: per-rule fixtures, suppressions, baseline, and the tier-1 gate.

Layout mirrors the analyzer's contract:

- every shipped rule has >= 1 minimal snippet it MUST flag and >= 1
  near-miss it MUST NOT (a registry-coverage test makes adding a rule
  without fixtures fail);
- suppression comments and the baseline round-trip through the real
  engine over a synthetic tree;
- the gate: the real tree has ZERO unsuppressed findings, the baseline
  is non-empty and non-stale (deleting an entry that guards a live site
  fails here), and the whole analysis stays under 10s of wall time so it
  never eats the tier-1 budget.
"""

import json
import time

import pytest

from foundationdb_tpu.tools.flowlint import (
    all_rules,
    format_baseline,
    lint,
    lint_source,
    load_config,
)
from foundationdb_tpu.tools.flowlint.core import DEFAULT_ROOT

SIM = "foundationdb_tpu/runtime/mod.py"  # a sim-reachable relpath for fixtures


def rule_hits(src, rule, relpath=SIM):
    return [f for f in lint_source(src, relpath=relpath) if f.rule == rule]


# ---------------------------------------------------------------------------
# Per-module rule fixtures: (flagged source, near-miss source)

FIXTURES = {
    "det-wall-clock": (
        "import time\n"
        "def f():\n"
        "    return time.time()\n",
        # a bare REFERENCE is dependency injection, not a clock read
        "import time\n"
        "def f(now_fn=time.perf_counter):\n"
        "    return now_fn\n",
    ),
    "det-sleep": (
        "import time as t\n"
        "def f():\n"
        "    t.sleep(1)\n",
        "from ..runtime.futures import delay\n"
        "async def f():\n"
        "    await delay(1)\n",
    ),
    "det-entropy": (
        "import os as _os\n"
        "def seed():\n"
        "    return _os.urandom(8)\n",
        "def seed(loop):\n"
        "    return loop.random.random_int(0, 1 << 30)\n",
    ),
    "det-unseeded-random": (
        "import random\n"
        "def f():\n"
        "    return random.random()\n",
        # seeded instance construction is the approved shape
        "import random\n"
        "def f(seed):\n"
        "    return random.Random(seed).random()\n",
    ),
    "actor-dropped-future": (
        "async def work():\n"
        "    return 1\n"
        "def boot():\n"
        "    work()\n",
        "async def work():\n"
        "    return 1\n"
        "async def main():\n"
        "    await work()\n"
        "def boot(process):\n"
        "    process.spawn(work())\n",
    ),
    "actor-blocking-call": (
        "import time\n"
        "async def f():\n"
        "    time.sleep(0.1)\n",
        # sync helpers may sleep (det-sleep polices sim scope separately)
        "import time\n"
        "def f():\n"
        "    time.sleep(0.1)\n",
    ),
    "actor-cancelled-swallow": (
        "async def f(fut):\n"
        "    try:\n"
        "        await fut\n"
        "    except Exception:\n"
        "        pass\n",
        "async def f(fut):\n"
        "    try:\n"
        "        await fut\n"
        "    except Cancelled:\n"
        "        raise\n"
        "    except Exception:\n"
        "        pass\n",
    ),
    "actor-unbounded-retry": (
        # error-swallowing while-True retry with no pacing: spins hot
        "async def f(ep):\n"
        "    while True:\n"
        "        try:\n"
        "            return await ep()\n"
        "        except Cancelled:\n"
        "            raise\n"
        "        except Exception:\n"
        "            pass\n",
        # same loop with backoff between attempts: the approved shape
        "from ..runtime.futures import delay\n"
        "async def f(ep):\n"
        "    while True:\n"
        "        try:\n"
        "            return await ep()\n"
        "        except Cancelled:\n"
        "            raise\n"
        "        except Exception:\n"
        "            await delay(0.5)\n",
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_flags_and_near_miss(rule):
    flagged, near_miss = FIXTURES[rule]
    assert rule_hits(flagged, rule), f"{rule}: must flag the minimal snippet"
    assert not rule_hits(near_miss, rule), f"{rule}: must pass the near-miss"


def test_every_shipped_rule_has_a_fixture():
    """Adding a rule without fixture coverage fails here first — the
    project-scope rules have their flag/near-miss pairs in
    test_collection_audit.py (they need a multi-file tree)."""
    PROJECT_RULES_TESTED_ELSEWHERE = {"reg-role-metrics", "reg-endpoint-span"}
    ids = {r.id for r in all_rules()}
    covered = set(FIXTURES) | PROJECT_RULES_TESTED_ELSEWHERE
    assert ids == covered, (
        f"rules without fixtures: {ids - covered}; "
        f"fixtures without rules: {covered - ids}"
    )


# ---------------------------------------------------------------------------
# More near-misses worth pinning

def test_dropped_bare_spawn_flagged_but_held_spawn_passes():
    flagged = (
        "from ..runtime.futures import spawn\n"
        "async def work():\n"
        "    return 1\n"
        "def boot():\n"
        "    spawn(work())\n"
    )
    held = (
        "from ..runtime.futures import spawn\n"
        "async def work():\n"
        "    return 1\n"
        "def boot(actors):\n"
        "    actors.add(spawn(work()))\n"
    )
    assert [f.detail for f in rule_hits(flagged, "actor-dropped-future")] == ["spawn"]
    assert not rule_hits(held, "actor-dropped-future")


def test_dropped_self_method_coroutine_in_init():
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        self.warm_up()\n"
        "    async def warm_up(self):\n"
        "        return 1\n"
    )
    hits = rule_hits(src, "actor-dropped-future")
    assert [f.detail for f in hits] == ["self.warm_up"]
    assert hits[0].scope == "C.__init__"


def test_unbounded_retry_accepts_bounds_and_exits():
    """The retry rule keys on error-driven repetition: bounded for-loops,
    the client's on_error backoff idiom, and handlers that exit the loop
    all pass; only the hot-spin shape flags."""
    bounded_for = (
        "async def f(ep):\n"
        "    for _attempt in range(5):\n"
        "        try:\n"
        "            return await ep()\n"
        "        except Exception:\n"
        "            pass\n"
    )
    on_error_idiom = (
        "async def f(db, body):\n"
        "    tr = db.transaction()\n"
        "    while True:\n"
        "        try:\n"
        "            return await body(tr)\n"
        "        except Cancelled:\n"
        "            raise\n"
        "        except Exception as e:\n"
        "            await tr.on_error(e)\n"
    )
    handler_exits = (
        "async def f(ep):\n"
        "    while True:\n"
        "        try:\n"
        "            return await ep()\n"
        "        except Exception:\n"
        "            break\n"
    )
    server_loop = (  # not a retry loop: no error swallowed around the await
        "async def f(var):\n"
        "    while True:\n"
        "        await var.on_change()\n"
    )
    for src in (bounded_for, on_error_idiom, handler_exits, server_loop):
        assert not rule_hits(src, "actor-unbounded-retry"), src


def test_cancelled_swallow_requires_an_await_in_try():
    src = (
        "async def f(x):\n"
        "    try:\n"
        "        y = x + 1\n"
        "    except Exception:\n"
        "        y = 0\n"
        "    return y\n"
    )
    assert not rule_hits(src, "actor-cancelled-swallow")


def test_cancelled_swallow_reraise_passes():
    src = (
        "async def f(fut):\n"
        "    try:\n"
        "        await fut\n"
        "    except Exception:\n"
        "        raise\n"
    )
    assert not rule_hits(src, "actor-cancelled-swallow")


def test_host_only_manifest_exempts_determinism_not_ad_hoc():
    src = "import time\ndef f():\n    return time.time()\n"
    host = "foundationdb_tpu/tools/tcp_soak.py"  # in the checked-in manifest
    assert rule_hits(src, "det-wall-clock", relpath=SIM)
    assert not rule_hits(src, "det-wall-clock", relpath=host)
    # the manifest is config, not rule code
    assert host in load_config()["host_only"]


# ---------------------------------------------------------------------------
# Suppressions

def test_inline_disable_suppresses_only_named_rule_on_that_line():
    base = "import time\ndef f():\n    return time.time(){}\n"
    assert rule_hits(base.format(""), "det-wall-clock")
    assert not rule_hits(
        base.format("  # flowlint: disable=det-wall-clock"), "det-wall-clock"
    )
    # naming a different rule does not suppress
    assert rule_hits(
        base.format("  # flowlint: disable=det-sleep"), "det-wall-clock"
    )


def test_file_level_disable():
    src = (
        "# flowlint: disable-file=det-wall-clock\n"
        "import time\n"
        "def f():\n"
        "    return time.time()\n"
        "def g():\n"
        "    return time.monotonic()\n"
    )
    assert not rule_hits(src, "det-wall-clock")


# ---------------------------------------------------------------------------
# Baseline round-trip through the real engine over a synthetic tree

def _mini_tree(tmp_path, baseline_entries=None):
    pkg = tmp_path / "foundationdb_tpu" / "runtime"
    pkg.mkdir(parents=True)
    (pkg / "mod.py").write_text(
        "import time\n"
        "def f():\n"
        "    return time.time()\n"
    )
    config = {
        "include": ["foundationdb_tpu"],
        "exclude": ["scratch", "tests"],
        "sim_scope": ["foundationdb_tpu"],
        "host_only": {},
        "baseline": "baseline.json",
        "role_exempt": [],
        "span_roles": [],
        "worker_module": "foundationdb_tpu/server/worker.py",
    }
    if baseline_entries is not None:
        (tmp_path / "baseline.json").write_text(
            json.dumps({"entries": baseline_entries})
        )
    return config


def test_baseline_round_trip(tmp_path):
    config = _mini_tree(tmp_path)
    first = lint(root=tmp_path, config=config)
    assert len(first.failing) == 1 and first.failing[0].rule == "det-wall-clock"

    # write the baseline exactly as --write-baseline would
    (tmp_path / "baseline.json").write_text(
        format_baseline(first.failing, {first.failing[0].key: "known wall read"})
    )
    second = lint(root=tmp_path, config=config)
    assert second.clean
    assert [f.key for f in second.baselined] == [first.failing[0].key]
    assert not second.stale_baseline

    # deleting the entry resurrects the finding (the acceptance property)
    third = lint(root=tmp_path, config=config, baseline={})
    assert [f.key for f in third.failing] == [first.failing[0].key]


def test_stale_baseline_entries_are_reported(tmp_path):
    config = _mini_tree(
        tmp_path, baseline_entries={"foundationdb_tpu/gone.py::f::det-sleep::time.sleep": "?"}
    )
    res = lint(root=tmp_path, config=config)
    assert res.stale_baseline == [
        "foundationdb_tpu/gone.py::f::det-sleep::time.sleep"
    ]


def test_baseline_key_is_line_churn_stable(tmp_path):
    config = _mini_tree(tmp_path)
    key0 = lint(root=tmp_path, config=config).failing[0].key
    mod = tmp_path / "foundationdb_tpu" / "runtime" / "mod.py"
    mod.write_text("# a new leading comment shifts every line\n" + mod.read_text())
    assert lint(root=tmp_path, config=config).failing[0].key == key0


# ---------------------------------------------------------------------------
# The tier-1 gate over the real tree

def test_tree_is_flowlint_clean_within_budget():
    t0 = time.perf_counter()
    result = lint()
    elapsed = time.perf_counter() - t0
    assert not result.parse_errors, result.parse_errors
    assert not result.failing, "unsuppressed flowlint findings:\n" + "\n".join(
        f.format() for f in result.failing
    )
    # grandfathered sites stay visible and guarded: the baseline is real
    # (delete an entry guarding a live site and `failing` catches it above),
    # and it carries no dead keys
    assert result.baselined, "baseline.json no longer guards any live site"
    assert not result.stale_baseline, (
        "stale baseline entries (sites gone — prune): "
        + ", ".join(result.stale_baseline)
    )
    # inline disables in the tree are load-bearing too (RealLoop's clock,
    # the kernel backends' host timings, the span allowlist)
    assert len(result.disabled) >= 3
    assert result.files > 100
    assert elapsed < 10.0, f"flowlint took {elapsed:.1f}s — over the tier-1 budget"


def test_host_only_manifest_points_at_real_files():
    config = load_config()
    for rel in config["host_only"]:
        assert (DEFAULT_ROOT / rel).exists(), f"host_only manifest rot: {rel}"


def test_cli_json_output_is_machine_readable():
    from foundationdb_tpu.tools.cli import _run_lint

    rc, out = _run_lint(["--json"])
    doc = json.loads(out)
    assert rc == 0 and doc["clean"] is True
    assert set(doc["per_rule"]) == {r.id for r in all_rules()}
