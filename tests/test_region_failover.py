"""Region failover drill (VERDICT r4 missing #5): kill the ENTIRE
primary region; force_failover promotes the remote mirror to primary and
clients continue with zero acked-write loss (the drill converges the
mirror first — the sim durability oracle enforces the no-loss claim at
the failover recovery itself)."""

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.client.management import force_failover
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster

from tests.test_multi_region import wait_remote_converged


def make(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = DynamicCluster(
        sim,
        ClusterConfig(remote_dc="dc1", **cfg),
        n_coordinators=3,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    return sim, cluster, db


def primary_addrs(sim):
    """Every live worker process NOT in the remote dc (the primary
    region's hosts, whatever roles they ended up with)."""
    out = []
    for addr, p in sim.processes.items():
        if not p.alive or getattr(p, "worker", None) is None:
            continue
        if p.locality.dc != "dc1":
            out.append(addr)
    return out


def test_failover_promotes_mirror_with_zero_acked_loss():
    sim, cluster, db = make(seed=41)

    async def go():
        rows = {}
        for i in range(20):
            k, v = b"fo%03d" % i, b"v%d" % i

            async def put(tr, k=k, v=v):
                tr.set(k, v)

            await db.run(put)
            rows[k] = v

        # converge the mirror so the failover loses nothing acked (the
        # drill's contract; the recovery's durability-oracle check aborts
        # the sim otherwise)
        assert await wait_remote_converged(sim, db, rows, b"fo", b"fp")

        # the primary region dies wholesale
        for addr in primary_addrs(sim):
            sim.kill_process(addr)

        # a fresh client (the old one may be parked on dead proxies)
        db2 = Database.from_coordinators(sim, cluster.coordinators)
        await force_failover(cluster.coordinators, db2.client, "dc1")

        # clients continue against the promoted region: new writes work
        for i in range(20, 30):
            k, v = b"fo%03d" % i, b"v%d" % i

            async def put(tr, k=k, v=v):
                tr.set(k, v)

            await db2.run(put)
            rows[k] = v

        # and nothing acked before the failover was lost
        tr = db2.transaction()
        got = dict(await tr.get_range(b"fo", b"fp", limit=1000))
        assert got == rows, (
            f"{len(got)} rows vs {len(rows)} expected; "
            f"missing={sorted(set(rows) - set(got))[:5]}"
        )
        return True

    assert sim.run_until_done(spawn(go()), 900.0)


def test_failover_survives_subsequent_recovery():
    """After promotion, the cluster is a normal single-region database:
    a later master kill recovers in the promoted region and data holds."""
    sim, cluster, db = make(seed=42)

    async def go():
        rows = {}
        for i in range(10):
            k, v = b"sr%03d" % i, b"v%d" % i

            async def put(tr, k=k, v=v):
                tr.set(k, v)

            await db.run(put)
            rows[k] = v
        assert await wait_remote_converged(sim, db, rows, b"sr", b"ss")
        for addr in primary_addrs(sim):
            sim.kill_process(addr)
        db2 = Database.from_coordinators(sim, cluster.coordinators)
        await force_failover(cluster.coordinators, db2.client, "dc1")

        async def put2(tr):
            tr.set(b"sr900", b"post")

        await db2.run(put2)
        rows[b"sr900"] = b"post"

        # now kill the PROMOTED region's master host: a normal recovery
        # must follow inside dc1
        victim = None
        for addr, p in sim.processes.items():
            w = getattr(p, "worker", None)
            if w is not None and p.alive and any(
                h.kind == "master" for h in w.roles.values()
            ):
                victim = addr
                break
        assert victim is not None
        sim.kill_process(victim)

        for i in range(901, 905):
            k, v = b"sr%03d" % i, b"x"

            async def put3(tr, k=k, v=v):
                tr.set(k, v)

            await db2.run(put3)
            rows[k] = v
        tr = db2.transaction()
        got = dict(await tr.get_range(b"sr", b"st", limit=1000))
        assert got == rows
        return True

    assert sim.run_until_done(spawn(go()), 900.0)


def test_lossy_failover_keeps_relayed_prefix_and_continues():
    """force_recovery_with_data_loss semantics: with the relay stalled,
    commits the routers never relayed are FORFEITED — the failover still
    completes, keeps the relayed prefix, and serves new traffic."""
    sim, cluster, db = make(seed=44)

    async def go():
        async def put(tr, k, v=b"v"):
            tr.set(k, v)

        for i in range(5):
            await db.run(lambda tr, i=i: put(tr, b"nl%03d" % i))
        # stall the relay, then keep writing (acked but never relayed)
        prim = primary_addrs(sim)
        remote = [
            a
            for a, p in sim.processes.items()
            if p.alive and p.locality.dc == "dc1"
        ]
        for a in prim:
            for b in remote:
                sim.clog_pair(a, b, 60.0)
        for i in range(5, 25):
            await db.run(lambda tr, i=i: put(tr, b"nl%03d" % i))
        for addr in prim:
            sim.kill_process(addr)
        db2 = Database.from_coordinators(sim, cluster.coordinators)
        await force_failover(cluster.coordinators, db2.client, "dc1")
        await db2.run(lambda tr: put(tr, b"nl900", b"post"))
        tr = db2.transaction()
        rows = dict(await tr.get_range(b"nl", b"nm", limit=100))
        # the pre-clog prefix and the post-failover write survive; the
        # stalled tail is gone (permitted loss, lowered oracle watermark)
        for i in range(5):
            assert b"nl%03d" % i in rows, i
        assert rows[b"nl900"] == b"post"
        assert len(rows) < 26, "stalled tail unexpectedly survived"
        return True

    assert sim.run_until_done(spawn(go()), 600.0)
