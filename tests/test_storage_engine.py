"""Epoch-batched storage engine battery (ISSUE 15).

Covers the tentpole's guarantees end to end: (a) the EpochVersionedMap is
a drop-in for the legacy map — a shared op stream fuzzes both against
per-version dict snapshots AND against each other, through clears,
compaction and rollback; (b) snapshot-pinned reads are byte-identical to
the legacy path in a full RYW + selector + reverse + atomics differential
with STORAGE_EPOCH_BATCHING both ways, and the bindingtester oracle stays
green both ways; (c) pins clamp the durability horizon (scan leases keep
multi-chunk scans alive across advances; the pin-lag cap invalidates
overstayers; a rollback invalidates pins above its boundary — TOO_OLD,
never cut-off data); (d) bulk ingest is O(N log N), not N·O(n) insort
(the keys_moved counter discipline); (e) forget_before visits only
touched keys; (f) DiskQueue group commit coalesces concurrent fsyncs;
(g) the storage-epoch-stall chaos site fires under a pinned seed and the
flowlint role_required_counters key keeps the metrics surface lit.
"""

import random

import pytest

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.errors import TransactionTooOld
from foundationdb_tpu.kv.engine import KeyValueStoreMemory
from foundationdb_tpu.kv.mutations import MutationType
from foundationdb_tpu.kv.selector import KeySelector
from foundationdb_tpu.kv.versioned_map import EpochVersionedMap, VersionedMap
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn, wait_for_all
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.server import Cluster, ClusterConfig
from foundationdb_tpu.server.interfaces import GetKeyValuesRequest


# -- (a) EpochVersionedMap vs snapshots AND vs the legacy map ------------------


def _fuzz_ops(seed, rounds=250):
    rng = random.Random(seed)
    keys = [b"k%02d" % i for i in range(40)]
    version = 0
    out = []
    for _ in range(rounds):
        version += rng.randint(1, 3)
        entries, clears = {}, []
        for _ in range(rng.randint(1, 5)):
            op = rng.random()
            if op < 0.55:
                entries[rng.choice(keys)] = b"v%d" % rng.randint(0, 999)
            elif op < 0.85:
                a, b = sorted((rng.choice(keys), rng.choice(keys)))
                clears.append((a, b))
                for k in [k for k in entries if a <= k < b]:
                    del entries[k]
            else:
                entries[rng.choice(keys)] = None  # atomic compare-and-clear
        out.append((version, entries, clears))
    return out


def _apply_legacy(m, version, entries, clears):
    """Replay an epoch onto the legacy map in the normalized order the
    epoch builder guarantees (clears first, then final entries)."""
    for a, b in clears:
        m.clear_range(a, b, version)
    for k, v in entries.items():
        if v is None:
            m.clear_range(k, k + b"\x00", version)
        else:
            m.set(k, v, version)


def test_epoch_map_fuzz_vs_snapshots_and_legacy():
    ops = _fuzz_ops(11)
    em, lm = EpochVersionedMap(), VersionedMap()
    model: dict = {}
    snapshots = {0: {}}
    for version, entries, clears in ops:
        em.apply_epoch(version, dict(entries), list(clears))
        _apply_legacy(lm, version, entries, clears)
        for a, b in clears:
            for k in [k for k in model if a <= k < b]:
                del model[k]
        for k, v in entries.items():
            if v is None:
                model.pop(k, None)
            else:
                model[k] = v
        snapshots[version] = dict(model)
    versions = sorted(snapshots)
    for v in versions:
        expect = sorted(snapshots[v].items())
        assert em.range(b"", b"\xff", v) == expect, f"epoch at {v}"
        assert lm.range(b"", b"\xff", v) == expect, f"legacy at {v}"
    # point reads incl. presence semantics agree between the maps
    for v in versions[:: max(1, len(versions) // 20)]:
        for k in (b"k00", b"k13", b"k27", b"k39", b"zz"):
            assert em.get(k, v) == lm.get(k, v), (k, v)
    # compaction (engine-less: keeps the pre-horizon base) preserves reads
    horizon = versions[len(versions) // 2]
    em.forget_before(horizon)
    lm.forget_before(horizon)
    for v in versions:
        if v >= horizon:
            assert em.range(b"", b"\xff", v) == sorted(snapshots[v].items())
            assert lm.range(b"", b"\xff", v) == sorted(snapshots[v].items())
    # rollback discards the tail on both
    boundary = versions[3 * len(versions) // 4]
    em.rollback_after(boundary)
    lm.rollback_after(boundary)
    assert em.latest_version == lm.latest_version == boundary
    for v in versions:
        if horizon <= v <= boundary:
            assert em.range(b"", b"\xff", v) == sorted(snapshots[v].items())


def test_epoch_map_drop_known_falls_through():
    """drop_known compaction drops whole superseded epochs; unknown keys
    report known=False so the storage server falls to the engine."""
    em = EpochVersionedMap()
    em.apply_epoch(10, {b"a": b"1", b"b": b"2"})
    em.apply_epoch(20, {b"a": b"3"}, [(b"b", b"c")])
    em.forget_before(20, drop_known=True)
    assert em.get_with_presence(b"a", 20) == (False, None)
    assert em.get_with_presence(b"b", 20) == (False, None)
    em.apply_epoch(30, {b"a": b"4"})
    assert em.get_with_presence(b"a", 30) == (True, b"4")
    assert em.get_with_presence(b"a", 25) == (False, None)


def test_epoch_map_range_tombstone_masks_without_materializing():
    em = EpochVersionedMap()
    em.apply_epoch(10, {b"m%03d" % i: b"v" for i in range(50)})
    em.apply_epoch(20, {}, [(b"m000", b"m040")])
    # one tombstone, not 40 materialized entries
    assert len(em._clears) == 1
    assert [k for k, _ in em.range(b"", b"\xff", 20)] == [
        b"m%03d" % i for i in range(40, 50)
    ]
    assert [k for k, _ in em.range(b"", b"\xff", 10)] == [
        b"m%03d" % i for i in range(50)
    ]
    overlay, clears = em.window_view(b"", b"\xff", 20)
    assert clears == [(b"m000", b"m040")]


# -- (c) pins: clamped compaction, rollback, pin-lag cap -----------------------


def test_pinned_snapshot_clamps_forget_and_rollback_invalidates():
    em = EpochVersionedMap()
    em.apply_epoch(10, {b"a": b"1"})
    em.apply_epoch(20, {b"a": b"2"})
    em.apply_epoch(30, {b"a": b"3"})
    snap = em.snapshot(20)
    em.forget_before(30)
    # the pin held the horizon at 20: the snapshot still reads
    assert em.oldest_version == 20
    assert snap.get(b"a") == b"2"
    snap.release()
    em.forget_before(30)
    assert em.oldest_version == 30
    # drop_known (engine-backed) semantics: the drain runs exactly TO the
    # pinned version, so the pin's reads fall through to engine state at
    # that same version — the window only reports absence-with-consistency
    em2 = EpochVersionedMap()
    em2.apply_epoch(10, {b"b": b"1"})
    em2.apply_epoch(20, {b"b": b"2"})
    snap2 = em2.snapshot(20)
    em2.forget_before(40, drop_known=True)  # clamped to the pin
    assert em2.oldest_version == 20 and snap2.valid
    assert snap2.get_with_presence(b"b") == (False, None)  # engine's turn
    # a pin above a rollback boundary holds cut-off versions: TOO_OLD
    em.apply_epoch(40, {b"a": b"4"})
    doomed = em.snapshot(40)
    ok = em.snapshot(30)
    em.rollback_after(30)
    with pytest.raises(TransactionTooOld):
        doomed.get(b"a")
    assert ok.get(b"a") == b"3"


def test_forced_advance_past_pin_goes_too_old():
    """The storage server's pin-lag cap: forget_before past a pin version
    invalidates the pin instead of serving through compacted layers."""
    em = EpochVersionedMap()
    for v in range(10, 60, 10):
        em.apply_epoch(v, {b"a": b"v%d" % v})
    snap = em.snapshot(20)
    # the map-level clamp holds...
    em.forget_before(50, drop_known=True)
    assert em.oldest_version == 20 and snap.valid
    # ...until the owner force-advances (cap exceeded): it invalidates
    # the pin first, then the advance proceeds
    snap.invalidated = True
    em.forget_before(50, drop_known=True)
    assert em.oldest_version == 50
    with pytest.raises(TransactionTooOld):
        snap.get(b"a")


def test_storage_clamp_to_pins_honors_lease_and_cap():
    from foundationdb_tpu.runtime.futures import AsyncVar
    from foundationdb_tpu.server.storage import StorageServer

    sim = Sim(seed=5)
    sim.activate()
    ss = StorageServer(tag=0, log_config=AsyncVar(None))
    assert ss._epoch_mode
    ss.version.set(20_000_000)
    ss.knobs.STORAGE_PIN_MAX_LAG_VERSIONS = 100_000_000
    # a scan lease below the target clamps the advance to it
    ss._note_scan_lease(4_000_000)
    assert ss._clamp_to_pins(6_000_000) == 4_000_000
    # ...but never beyond the pin-lag cap behind the tip: a 12M cap under
    # the 20M tip floors the advance at 8M over the 4M lease
    ss.knobs.STORAGE_PIN_MAX_LAG_VERSIONS = 12_000_000
    assert ss._clamp_to_pins(9_000_000) == 8_000_000
    # lease expiry releases the clamp
    ss.knobs.STORAGE_PIN_MAX_LAG_VERSIONS = 100_000_000

    async def sleep():
        await delay(ss.knobs.STORAGE_SNAPSHOT_LEASE + 1)
        return True

    assert sim.run_until_done(spawn(sleep()), 60.0)
    assert ss._clamp_to_pins(6_000_000) == 6_000_000


def test_scan_lease_keeps_chunked_scan_alive_across_advances():
    """A chunked read that saw `more` holds its version: the follow-up
    chunks still serve after durability advances that would have pushed a
    lease-less reader TOO_OLD (the fetchKeys/backup-page regime)."""
    knobs = Knobs(
        MAX_READ_TRANSACTION_LIFE_VERSIONS=400_000,  # ~0.4 s window
        STORAGE_DURABILITY_LAG=0.05,
    )
    sim = Sim(seed=9, knobs=knobs)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(n_storage=1, replication=1))
    db = Database(sim, cluster.proxy_addrs)
    ss = cluster.storages[0]
    keys = [b"scan/%03d" % i for i in range(40)]

    async def go():
        async def fill(tr):
            for k in keys:
                tr.set(k, b"v" + k)

        await db.run(fill)
        tr = db.transaction()
        version = await tr.get_read_version()
        got = []
        lo = b"scan/"
        while True:
            reply = await ss.get_key_values(
                GetKeyValuesRequest(
                    begin=lo, end=b"scan0", version=version, limit=8
                )
            )
            got.extend(reply.data)
            if not reply.more:
                break
            lo = reply.data[-1][0] + b"\x00"
            # push the version tip well past the old window between
            # chunks: only the scan lease keeps `version` servable
            for i in range(3):
                async def bump(tr2, i=i):
                    tr2.set(b"bump/%d" % i, b"x")

                await db.run(bump)
            await delay(0.4)
        assert [k for k, _ in got] == keys
        assert ss.durable_version <= version
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    assert ss.stats.counters["snapshotsPinned"].value > 0


# -- (d) bulk ingest: O(N log N), not N x O(n) insort --------------------------


def test_engine_bulk_ingest_epoch_merge_not_quadratic():
    sim = Sim(seed=3)
    sim.activate()
    engine = KeyValueStoreMemory(sim.disk("m"), "bulk-test")
    # existing rows ABOVE the fresh prefix: every legacy insort of a
    # bulk/ key shifts all of them
    for i in range(2000):
        engine.set(b"z/%06d" % i, b"old")
    engine.keys_moved = 0
    n = 2000
    fresh = {b"bulk/%06d" % i: b"v" for i in range(n)}
    engine.apply_epoch(fresh)
    epoch_moved = engine.keys_moved
    # one merge pass: linear in (existing + new), nowhere near N * n
    assert epoch_moved <= 4 * (n + 2000), epoch_moved
    assert len(engine) == n + 2000
    # the same load through per-key set() pays the quadratic insort
    engine2 = KeyValueStoreMemory(sim.disk("m"), "bulk-test-2")
    for i in range(2000):
        engine2.set(b"z/%06d" % i, b"old")
    engine2.keys_moved = 0
    for k, v in fresh.items():
        engine2.set(k, v)
    assert engine2.keys_moved >= n * 2000  # each insert shifted the z/ block
    assert engine2._keys == engine._keys


def test_engine_apply_epoch_matches_sequential_and_recovers():
    """apply_epoch's normalized clears-then-entries order reproduces the
    sequential result, dirty tracking stays exact, and the op log replays
    to the same state after a reboot."""
    sim = Sim(seed=4)
    sim.activate()
    engine = KeyValueStoreMemory(sim.disk("m2"), "ep")
    engine.track_dirty = True
    engine.apply_epoch({b"a": b"1", b"b": b"2", b"c": b"3"})
    engine.take_dirty()
    engine.apply_epoch({b"b": b"9", b"d": b"4", b"a": None}, [(b"c", b"e")])
    added, removed = engine.take_dirty()
    assert sorted(added) == [b"d"] and sorted(removed) == [b"a", b"c"]
    assert engine.read_range(b"", b"\xff") == [(b"b", b"9"), (b"d", b"4")]

    async def commit_and_recover():
        await engine.commit()
        fresh = KeyValueStoreMemory(sim.disk("m2"), "ep")
        await fresh.recover()
        return fresh.read_range(b"", b"\xff")

    rows = sim.run_until_done(spawn(commit_and_recover()), 60.0)
    assert rows == [(b"b", b"9"), (b"d", b"4")]


def test_map_bulk_ingest_epoch_merge_not_quadratic():
    em = EpochVersionedMap()
    em.apply_epoch(10, {b"z/%06d" % i: b"old" for i in range(2000)})
    em.keys_moved = 0
    em.apply_epoch(20, {b"bulk/%06d" % i: b"v" for i in range(2000)})
    assert em.keys_moved <= 4 * 4000, em.keys_moved


# -- (e) forget_before visits only touched keys --------------------------------


@pytest.mark.parametrize("cls", [VersionedMap, EpochVersionedMap])
def test_forget_before_visits_only_touched_keys(cls):
    m = cls()
    for i in range(1000):
        m.set(b"cold/%04d" % i, b"v", 10)
    m.forget_before(20)  # pops the cold keys' touch-log entries
    m.forget_visits = 0
    m.set(b"hot/a", b"1", 30)
    m.set(b"hot/b", b"2", 40)
    m.set(b"hot/a", b"3", 50)
    m.forget_before(45)
    # only the two hot keys were visited — not the 1000 cold ones
    assert m.forget_visits <= 2, m.forget_visits
    assert m.get(b"cold/0500", 45) == b"v"
    assert m.get(b"hot/a", 45) == b"1"
    assert m.get(b"hot/a", 50) == b"3"


# -- (f) DiskQueue group commit ------------------------------------------------


def test_diskqueue_group_commit_coalesces_fsyncs():
    from foundationdb_tpu.kv.diskqueue import DiskQueue

    sim = Sim(seed=6)
    sim.activate()
    dq = DiskQueue(sim.disk("gq"), "gq")

    async def one(i):
        dq.push(b"entry-%02d" % i)
        await dq.commit()
        return True

    async def go():
        # a first commit opens the file so the burst measures pure commits
        dq.push(b"seed")
        await dq.commit()
        base = dq.commits
        oks = await wait_for_all([spawn(one(i)) for i in range(24)])
        assert all(oks)
        return dq.commits - base

    rounds = sim.run_until_done(spawn(go()), 60.0)
    # 24 concurrent committers coalesced into a bounded number of
    # write+fsync rounds; everyone else joined a group
    assert rounds < 24 and dq.group_joins > 0, (rounds, dq.group_joins)

    async def recover():
        fresh = DiskQueue(sim.disk("gq"), "gq")
        return [p for _off, p in await fresh.recover()]

    payloads = sim.run_until_done(spawn(recover()), 60.0)
    assert payloads == [b"seed"] + [b"entry-%02d" % i for i in range(24)]


# -- (b) byte-identical differential with the knob both ways -------------------


def _battery(epoch: bool, durable: bool = False):
    """RYW + selectors + reverse ranges + atomics + committed clears,
    read back through every path; returns all read results."""
    knobs = Knobs(STORAGE_EPOCH_BATCHING=epoch)
    if durable:
        knobs.MAX_READ_TRANSACTION_LIFE_VERSIONS = 1_000_000
    sim = Sim(seed=7, knobs=knobs)
    sim.activate()
    if durable:
        from foundationdb_tpu.server.cluster import DynamicCluster

        cluster = DynamicCluster(
            sim, ClusterConfig(n_storage=1, n_tlogs=1, n_proxies=1)
        )
        db = Database.from_coordinators(sim, cluster.coordinators)
    else:
        cluster = Cluster(sim, ClusterConfig(n_storage=2, replication=1))
        db = Database(sim, cluster.proxy_addrs)
    out = []

    async def go():
        async def fill(tr):
            for i in range(30):
                tr.set(b"d%03d" % i, b"base%d" % i)
            tr.set(b"ctr", (7).to_bytes(8, "little"))

        await db.run(fill)
        if durable:
            await delay(8.0)  # rows drop to the engine; index builds

        # committed clear + atomic chain
        async def mutate(tr):
            tr.clear_range(b"d020", b"d025")
            tr.atomic_op(MutationType.ADD, b"ctr", (5).to_bytes(8, "little"))
            tr.atomic_op(MutationType.ADD, b"ctr", (1).to_bytes(8, "little"))
            tr.atomic_op(
                MutationType.BYTE_MAX, b"d001", b"zzz"
            )
            tr.atomic_op(
                MutationType.COMPARE_AND_CLEAR, b"d002", b"base2"
            )

        await db.run(mutate)

        tr = db.transaction()
        # RYW overlay over committed state
        tr.set(b"d005", b"mine")
        tr.atomic_op(MutationType.ADD, b"ctr", (100).to_bytes(8, "little"))
        tr.clear_range(b"d010", b"d013")
        out.append(
            await wait_for_all(
                [spawn(tr.get(b"d%03d" % i)) for i in range(28)]
                + [spawn(tr.get(b"ctr"))]
            )
        )
        sels = [
            KeySelector.first_greater_or_equal(b"d006"),
            KeySelector.last_less_than(b"d010"),
            KeySelector.last_less_or_equal(b"d022"),
            KeySelector.first_greater_than(b"d029"),
        ]
        out.append(await wait_for_all([spawn(tr.get_key(s)) for s in sels]))
        rfuts = [
            spawn(tr.get_range(b"d000", b"d030", limit=9)),
            spawn(tr.get_range(b"d004", b"d026")),
            spawn(tr.get_range(b"d000", b"d030", limit=6, reverse=True)),
            spawn(tr.get_range(b"a", b"\xff")),
            spawn(
                tr.get_range(KeySelector.first_greater_than(b"d002"), b"d009")
            ),
        ]
        out.append(await wait_for_all(rfuts))
        await tr.commit()

        tr2 = db.transaction()
        out.append(
            await wait_for_all(
                [spawn(tr2.get(b"d%03d" % i)) for i in (1, 2, 5, 11, 22)]
                + [spawn(tr2.get(b"ctr"))]
                + [spawn(tr2.get_range(b"d000", b"d030", reverse=True, limit=40))]
            )
        )
        return True

    assert sim.run_until_done(spawn(go()), 600.0)
    return out


def test_epoch_results_byte_identical_to_legacy():
    assert _battery(True) == _battery(False)


def test_epoch_results_byte_identical_to_legacy_durable_engine():
    assert _battery(True, durable=True) == _battery(False, durable=True)


@pytest.mark.parametrize("epoch", [True, False])
def test_bindingtester_oracle_with_epoch_knob(epoch):
    from test_bindingtester import run_model, run_real

    stream, (data_real, log_real) = run_real(
        seed=33, n_ops=400, knobs=Knobs(STORAGE_EPOCH_BATCHING=epoch)
    )
    data_model, log_model = run_model(stream)
    assert list(data_real) == list(data_model)
    assert list(log_real) == list(log_model)


# -- (g) chaos site + lint surface + mixed soak --------------------------------


def test_storage_epoch_stall_site_fires_under_pinned_seed():
    """The durability-drain stall site is reachable by the ordinary
    buggify machinery (the chaos soak arms it organically); under the
    pinned seed it fires and the cluster keeps serving."""
    from foundationdb_tpu.server.cluster import DynamicCluster

    fired = set()
    for seed in (4, 5):  # both fire independently; either proves the site
        sim = Sim(seed=seed, chaos=True)
        sim.activate()
        cluster = DynamicCluster(
            sim, ClusterConfig(n_storage=1, n_tlogs=1, n_proxies=1)
        )
        db = Database.from_coordinators(sim, cluster.coordinators)

        async def go(db=db):
            for i in range(30):
                async def body(tr, i=i):
                    tr.set(b"k%03d" % i, b"v")

                await db.run(body)
                await delay(0.3)

            async def check(tr):
                return await tr.get(b"k000")

            return await db.run(check)

        assert sim.run_until_done(spawn(go()), 600.0) == b"v"
        fired |= {t for _f, t in sim.buggify.fired if isinstance(t, str)}
    assert "storage-epoch-stall" in fired, fired


def test_flowlint_role_required_counters_guards_surface():
    """Dropping a counter the config pins must flag reg-role-metrics —
    the status/cli storage-engine surface cannot silently go dark."""
    from foundationdb_tpu.tools.flowlint import lint, load_config

    config = load_config()
    assert "epochsApplied" in config["role_required_counters"]["storage"]
    # the real tree is clean against the real manifest (lint gate covers
    # it too); a name the class does NOT register must flag
    config["role_required_counters"] = {"storage": ["definitelyMissingCtr"]}
    result = lint(config=config)
    hits = [
        f
        for f in result.failing
        if f.rule == "reg-role-metrics" and "definitelyMissingCtr" in f.detail
    ]
    assert hits, "missing required counter did not flag"


def test_status_and_cli_surface_storage_engine():
    """The epoch counters flow storage.metrics → status
    workload.storage_engine → the `cli status` "Storage engine:" line."""
    from foundationdb_tpu.client import management
    from foundationdb_tpu.server.cluster import DynamicCluster
    from foundationdb_tpu.tools.cli import FdbCli

    sim = Sim(seed=2)
    sim.activate()
    cluster = DynamicCluster(
        sim, ClusterConfig(n_storage=1, n_tlogs=1, n_proxies=1)
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    cli = FdbCli(db, cluster.coordinators)

    async def go():
        for i in range(12):
            async def body(tr, i=i):
                tr.set(b"s%03d" % i, b"v")
                if i == 5:
                    tr.clear_range(b"s000", b"s003")

            await db.run(body)

        async def read(tr):
            return await tr.get(b"s011")

        await db.run(read)
        await delay(6.0)  # metrics poll interval
        doc = await management.get_status(cluster.coordinators, db.client)
        text = await cli.execute("status")
        return doc, text

    doc, text = sim.run_until_done(spawn(go()), 600.0)
    se = doc["workload"]["storage_engine"]
    assert se["epochs_applied"]["counter"] > 0
    assert se["epoch_mutations"]["counter"] >= 12
    assert se["range_tombstones"]["counter"] >= 1
    assert se["snapshots_pinned"]["counter"] > 0
    assert "Storage engine:" in text, text
    assert "range tombstones" in text


def test_mixed_soak_smoke_flat_read_p95():
    """Tier-1-sized slice of the sustained mixed soak (clients + bulkload
    + backup concurrently): probes keep landing and the last-third read
    p95 stays in family with the first while ingest runs hot."""
    from foundationdb_tpu.tools.soak import mixed_soak

    out = mixed_soak(seed=1, duration=6.0)
    assert out["probe_samples"] >= 8
    assert out["storage_engine"]["epochs_applied"] > 0
    assert out["storage_engine"]["snapshots_pinned"] > 0
    thirds = [p for p in out["read_p95_by_third"] if p is not None]
    assert len(thirds) >= 2
    assert thirds[-1] <= 3 * thirds[0], out["read_p95_by_third"]
