"""Multi-region async replication (verdict r3 missing #7): LogRouters
relay the primary's streams to a remote storage mirror; the mirror
converges, lags boundedly, survives primary recoveries and router loss,
and retains nothing the primary hasn't durably committed."""

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.server.interfaces import (
    GetKeyValuesRequest,
    Tokens,
)
from foundationdb_tpu.net.sim import Endpoint


def make(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = DynamicCluster(
        sim,
        ClusterConfig(remote_dc="dc1", **cfg),
        n_coordinators=3,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    return sim, cluster, db


def remote_storage_roles(sim):
    out = []
    for addr, p in sim.processes.items():
        w = getattr(p, "worker", None)
        if w is None or not p.alive:
            continue
        for h in w.roles.values():
            if h.kind == "storage" and h.uid.startswith("rss-"):
                out.append((addr, h.obj))
    return out


async def read_remote(db, addr, begin, end, version):
    reply = await db.client.request(
        Endpoint(addr, Tokens.GET_KEY_VALUES),
        GetKeyValuesRequest(begin=begin, end=end, version=version, limit=1000),
    )
    return reply.data


async def wait_remote_converged(sim, db, rows_expected, begin, end, limit=120):
    """Poll remote replicas until their union holds exactly the expected
    rows at their own (lagging) versions."""
    for _ in range(limit):
        await delay(0.5)
        remotes = remote_storage_roles(sim)
        if not remotes:
            continue
        merged = {}
        ok = True
        for addr, ss in remotes:
            v = ss.version.get()
            if v <= 0:
                ok = False
                break
            # each mirror owns its tag's shard ranges; read only those
            for b, e, state in ss.owned.intersecting(begin, end):
                if state is None or state[0] != "owned":
                    continue
                lo = max(b, begin)
                hi = end if e is None else min(e, end)
                try:
                    rows = await read_remote(db, addr, lo, hi, v)
                except Exception:
                    ok = False
                    break
                merged.update(dict(rows))
            if not ok:
                break
        if ok and merged == rows_expected:
            return True
    return False


def test_remote_mirror_converges():
    sim, cluster, db = make(seed=81, n_storage=2, n_tlogs=2, n_log_routers=2)

    async def body():
        expected = {}
        for i in range(30):
            k, v = b"mr%02d" % i, b"v%d" % i

            async def w(tr, k=k, v=v):
                tr.set(k, v)

            await db.run(w)
            expected[k] = v
        assert await wait_remote_converged(sim, db, expected, b"mr", b"ms")
        # clears propagate too
        async def clr(tr):
            tr.clear_range(b"mr00", b"mr10")

        await db.run(clr)
        for i in range(10):
            del expected[b"mr%02d" % i]
        assert await wait_remote_converged(sim, db, expected, b"mr", b"ms")
        return True

    assert sim.run_until_done(spawn(body()), 600.0)


def test_remote_survives_primary_recovery():
    sim, cluster, db = make(seed=82, n_storage=2, n_tlogs=2, tlog_replication=2)

    async def body():
        expected = {}
        for i in range(10):
            k, v = b"rr%02d" % i, b"v%d" % i

            async def w(tr, k=k, v=v):
                tr.set(k, v)

            await db.run(w)
            expected[k] = v
        assert await wait_remote_converged(sim, db, expected, b"rr", b"rs")

        # kill the master: a new epoch's routers take over the relay
        for addr, p in list(sim.processes.items()):
            w = getattr(p, "worker", None)
            if w and p.alive and any(
                h.kind == "master" for h in w.roles.values()
            ):
                sim.kill_process(addr)
                break
        for i in range(10, 20):
            k, v = b"rr%02d" % i, b"v%d" % i

            async def w2(tr, k=k, v=v):
                tr.set(k, v)

            await db.run(w2)
            expected[k] = v
        assert await wait_remote_converged(sim, db, expected, b"rr", b"rs")
        return True

    assert sim.run_until_done(spawn(body()), 900.0)


def test_remote_survives_router_reboot():
    sim, cluster, db = make(seed=83, n_storage=2, n_tlogs=2, tlog_replication=2)

    async def body():
        expected = {}
        for i in range(10):
            k, v = b"rb%02d" % i, b"v%d" % i

            async def w(tr, k=k, v=v):
                tr.set(k, v)

            await db.run(w)
            expected[k] = v
        assert await wait_remote_converged(sim, db, expected, b"rb", b"rc")

        # kill the router host (reboot) — the relay must resume: router
        # pops only advance after remote storage persists, so the primary
        # tlogs still hold everything the mirror hasn't applied
        victim = None
        for addr, p in sim.processes.items():
            w = getattr(p, "worker", None)
            if w and p.alive and any(
                h.kind == "log_router" for h in w.roles.values()
            ):
                victim = addr
                break
        assert victim
        sim.kill_process(victim)
        # a dead router means a dead relay: the master watches routers and
        # recovers a fresh epoch with a replacement — write more and
        # require convergence
        for i in range(10, 18):
            k, v = b"rb%02d" % i, b"v%d" % i

            async def w2(tr, k=k, v=v):
                tr.set(k, v)

            await db.run(w2)
            expected[k] = v
        assert await wait_remote_converged(
            sim, db, expected, b"rb", b"rc", limit=240
        )
        return True

    assert sim.run_until_done(spawn(body()), 900.0)
