"""Restart tests: durability across process reboots.

The analog of the reference's tests/restarting/ class (SaveAndKill +
-r simulation --restarting): acknowledged commits must survive kills and
reboots of the processes holding them, because tlogs and storage servers
now persist through DiskQueue / the memory engine onto the machine's
simulated disk (which drops unsynced writes on kill —
AsyncFileNonDurable semantics).
"""

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster


def make(seed=0, n_coordinators=1, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = DynamicCluster(
        sim, ClusterConfig(**cfg), n_coordinators=n_coordinators
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    return sim, cluster, db


def run(sim, coro, limit=600.0):
    return sim.run_until_done(spawn(coro), limit)


async def put(db, key, value):
    async def body(tr):
        tr.set(key, value)

    await db.run(body)


async def get(db, key):
    async def body(tr):
        return await tr.get(key)

    return await db.run(body)


def workers_hosting(sim, kind):
    out = []
    for addr, p in sim.processes.items():
        w = getattr(p, "worker", None)
        if w and p.alive and any(h.kind == kind for h in w.roles.values()):
            out.append(addr)
    return out


def test_tlog_reboot_preserves_single_copy():
    """tlog_replication=1: the ONLY copy of recent commits lives in one
    tlog's DiskQueue. Kill + reboot that worker; recovery must lock the
    recovered tlog and keep every acknowledged write."""
    sim, cluster, db = make(
        seed=41, n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=1,
    )

    async def body():
        for i in range(20):
            await put(db, b"t%02d" % i, b"v%d" % i)
        victims = workers_hosting(sim, "tlog")
        assert victims
        sim.kill_process(victims[0], reboot_in=1.5)
        for i in range(20, 30):
            await put(db, b"t%02d" % i, b"v%d" % i)
        for i in range(30):
            assert await get(db, b"t%02d" % i) == b"v%d" % i, i

    run(sim, body())


def test_storage_reboot_recovers_and_catches_up():
    """replication=1: the storage server's engine + the retained tlog tail
    must reconstruct everything after a reboot."""
    sim, cluster, db = make(
        seed=42, n_proxies=1, n_resolvers=1, n_tlogs=2, n_storage=1,
        tlog_replication=2,
    )

    async def body():
        for i in range(20):
            await put(db, b"s%02d" % i, b"v%d" % i)
        # let a durability cycle run so some data is in the engine
        await delay(2.0)
        victims = workers_hosting(sim, "storage")
        assert victims
        sim.kill_process(victims[0], reboot_in=1.0)
        # reads retry across the outage and then come from the recovered SS
        for i in range(20):
            assert await get(db, b"s%02d" % i) == b"v%d" % i, i
        for i in range(20, 25):
            await put(db, b"s%02d" % i, b"v%d" % i)
        for i in range(25):
            assert await get(db, b"s%02d" % i) == b"v%d" % i, i

    run(sim, body())


def test_full_cluster_restart():
    """Kill every worker (staggered reboots); the cluster must re-form from
    coordinated state + disks with all acknowledged data intact."""
    sim, cluster, db = make(
        seed=43,
        n_proxies=2,
        n_resolvers=1,
        n_tlogs=2,
        n_storage=2,
        replication=2,
        tlog_replication=2,
        n_coordinators=3,
    )

    async def body():
        for i in range(25):
            await put(db, b"r%02d" % i, b"v%d" % i)
        rng = sim.loop.random
        for addr, p in list(sim.processes.items()):
            if getattr(p, "worker", None) is not None and p.alive:
                sim.kill_process(addr, reboot_in=1.0 + rng.random01() * 2.0)
        # everything must come back
        for i in range(25):
            assert await get(db, b"r%02d" % i) == b"v%d" % i, i
        for i in range(25, 30):
            await put(db, b"r%02d" % i, b"v%d" % i)
        for i in range(30):
            assert await get(db, b"r%02d" % i) == b"v%d" % i, i

    run(sim, body())
