"""Cluster-wide status pipeline (ISSUE 5): latency probes, workload/qos
sections, and TPU conflict-kernel metrics end-to-end.

One sim cluster (TPU backend on the CPU twin, tiny CONFLICT_SET_CAPACITY)
serves every assertion: `status json` carries populated `latency_probe`,
`workload`, `qos`, and per-resolver kernel sections with sane value
ranges, and a flood of brand-new keys forces overflow replays that must
surface in BOTH `resolver.metrics` and the status document."""

from foundationdb_tpu.client import management
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Endpoint, Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster


def test_status_pipeline_end_to_end():
    sim = Sim(seed=61)
    sim.activate()
    # tiny device index: the key floods below must outgrow some bucket's
    # slot budget and pay an overflow replay (the knob now actually
    # reaches the backend through the resolver)
    sim.knobs.CONFLICT_SET_CAPACITY = 16
    cluster = DynamicCluster(
        sim,
        ClusterConfig(
            n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=2,
            conflict_backend="tpu1",
        ),
        n_coordinators=1,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def body():
        # normal traffic for the workload/qos counters
        for i in range(25):

            async def w(tr, i=i):
                tr.set(b"sp%02d" % i, b"v")

            await db.run(w)

        # key floods with fresh prefixes: each lands past the previously
        # sampled pivots, concentrating >S2 staged rows in one bucket
        for prefix in (b"ov", b"pw", b"qx"):

            async def flood(tr, prefix=prefix):
                for i in range(150):
                    tr.set(prefix + b"%04d" % i, b"x")

            await db.run(flood)

        # let probes + per-role metric trace loops fire a few times
        await delay(8.0)
        doc = await management.get_status(cluster.coordinators, db.client)

        # resolver.metrics endpoint (the role's own wire answer) must show
        # the same replay counter the status doc aggregates
        direct = {}
        for addr, p in sim.processes.items():
            w = getattr(p, "worker", None)
            if w is None or not p.alive:
                continue
            for uid, h in w.roles.items():
                if h.kind == "resolver":
                    direct[uid] = await db.client.request(
                        Endpoint(addr, f"resolver.metrics#{uid}"), None
                    )
        return doc, direct

    doc, direct = sim.run_until_done(spawn(body()), 900.0)

    # -- latency_probe: timed GRV/read/commit with sane sim-time ranges
    probe = doc["latency_probe"]
    assert probe["probes_completed"] > 0
    for leg in ("grv_seconds", "read_seconds", "commit_seconds"):
        assert 0 < probe[leg] < 5.0, (leg, probe)
    for leg in ("grv", "read", "commit"):
        stats = probe[leg + "_stats"]
        assert stats["count"] > 0 and 0 < stats["p50"] < 5.0, (leg, stats)

    # -- workload: tps/ops aggregated from proxy + storage counters
    wl = doc["workload"]
    assert wl["transactions"]["committed"]["counter"] >= 28
    assert wl["transactions"]["started"]["counter"] > 0
    assert wl["operations"]["writes"]["counter"] >= 25 + 3 * 150
    assert wl["operations"]["bytes_written"]["counter"] > 0
    assert wl["operations"]["reads"]["counter"] >= 0
    # abort rate + prefilter surface (ISSUE 17): present and sane even
    # on an uncontended run
    assert 0.0 <= wl["abort_rate"] <= 1.0
    assert wl["prefiltered"]["counter"] >= 0
    assert wl["prefilter"]["checks"]["counter"] >= 0

    # -- tlog durability (ISSUE 18): the section must aggregate the
    # actual tlog roles' counters (kind is the lowercase recruit kind),
    # so a cluster that committed transactions shows fsync rounds
    tl = wl["tlog"]
    assert tl["fsync_rounds"] > 0, tl
    assert tl["fsync_seconds"] >= 0 and tl["group_joins"] >= 0, tl
    assert tl["pipeline_depth"] >= 0, tl

    # -- qos: totals + ratekeeper rate + durability-lag roll-up
    qos = doc["qos"]
    assert qos["transactions_committed_total"] >= 28
    assert qos.get("released_transactions_per_second", 0) > 0
    assert qos["worst_storage_durability_lag_versions"] >= 0
    assert qos["limiting"] in ("workload", "storage_durability_lag")

    # -- per-resolver kernel sections with occupancy + forced replays
    assert doc["resolvers"], doc.keys()
    replay_total = 0
    for uid, snap in doc["resolvers"].items():
        assert snap["resolveBatchIn"] > 0
        k = snap["kernel"]
        assert k["txns"] >= 28
        assert k["jitCacheMisses"] > 0
        assert k["hostToDeviceBytes"] > 0 and k["deviceToHostBytes"] > 0
        occ = k["occupancy"]
        assert 0 < occ["liveRows"] <= occ["bucketCount"] * occ["slotCapacity"]
        assert 0 <= occ["fillFraction"] <= 1.0
        assert k["encodeSeconds"]["count"] > 0
        assert k["collectSeconds"]["count"] > 0
        replay_total += k["overflowReplays"]
    assert replay_total > 0, "key floods should have forced an overflow replay"

    # -- the role's own resolver.metrics endpoint agrees
    assert direct
    assert sum(s["kernel"]["overflowReplays"] for s in direct.values()) > 0
    for s in direct.values():
        assert s["kernel"]["occupancy"]["liveRows"] > 0

    # machine/process sections carry both memory views (current + peak)
    assert doc["processes"]
    for sm in doc["processes"].values():
        assert sm["MemoryKB"] > 0
        assert sm["PeakMemoryKB"] > 0
