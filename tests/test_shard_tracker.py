"""DD shard machinery (verdict r3 missing #3): byte-sampled shard sizes,
split of hot shards, merge of cold same-team neighbors, move throttling."""

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.server.interfaces import GetKeyServersRequest, Tokens


def make(seed=0, knobs=None, **cfg):
    sim = Sim(seed=seed, knobs=knobs)
    sim.activate()
    cluster = DynamicCluster(sim, ClusterConfig(**cfg), n_coordinators=1)
    db = Database.from_coordinators(sim, cluster.coordinators)
    return sim, cluster, db


async def walk(db):
    out = []
    key = b""
    while True:
        reply = await db._proxy_request(
            Tokens.GET_KEY_SERVERS, GetKeyServersRequest(key=key)
        )
        out.append((reply.begin, reply.end, tuple(sorted(reply.tags))))
        if reply.end is None:
            return out
        key = reply.end


def test_bulk_load_splits_shards():
    knobs = Knobs(
        DD_SHARD_MAX_BYTES=4096,
        DD_SHARD_MIN_BYTES=512,
        DD_TRACKER_INTERVAL=0.5,
    )
    sim, cluster, db = make(
        seed=71, knobs=knobs, n_storage=2, replication=2, n_tlogs=1
    )

    async def body():
        # ~40 KB of data into what starts as ONE shard per team
        for batch in range(20):

            async def w(tr, batch=batch):
                for i in range(10):
                    k = b"bulk/%03d/%02d" % (batch, i)
                    tr.set(k, b"x" * 200)

            await db.run(w)
        before = await walk(db)
        # let the tracker split (one structural change per interval)
        for _ in range(60):
            await delay(1.0)
            shards = await walk(db)
            if len(shards) >= 4:
                break
        shards = await walk(db)
        assert len(shards) > len(before), (before, shards)
        assert len(shards) >= 4, shards
        # boundaries tile; every shard kept the same (only) team
        for (b1, e1, _t1), (b2, _e2, _t2) in zip(shards, shards[1:]):
            assert e1 == b2
        # data still fully readable and balanced-ish: no shard holds
        # everything
        async def count(tr):
            return len(await tr.get_range(b"bulk/", b"bulk0"))

        assert await db.run(count) == 200
        from foundationdb_tpu.net.sim import Endpoint

        sizes = []
        for begin, end, tags in shards:
            reply = await db._proxy_request(
                Tokens.GET_KEY_SERVERS, GetKeyServersRequest(key=begin)
            )
            m = await db.client.request(
                Endpoint(reply.team[0], Tokens.GET_SHARD_METRICS),
                (begin, end if end is not None else None),
            )
            sizes.append(m["bytes"])
        big = [s for s in sizes if s > 0]
        assert len(big) >= 2, sizes  # bytes spread across >1 shard
        return True

    assert sim.run_until_done(spawn(body()), 600.0)


def test_clear_merges_shards():
    knobs = Knobs(
        DD_SHARD_MAX_BYTES=4096,
        DD_SHARD_MIN_BYTES=2048,
        DD_TRACKER_INTERVAL=0.5,
    )
    sim, cluster, db = make(
        seed=72, knobs=knobs, n_storage=2, replication=2, n_tlogs=1
    )

    async def body():
        for batch in range(20):

            async def w(tr, batch=batch):
                for i in range(10):
                    tr.set(b"m/%03d/%02d" % (batch, i), b"x" * 200)

            await db.run(w)
        for _ in range(60):
            await delay(1.0)
            if len(await walk(db)) >= 4:
                break
        split_count = len(await walk(db))
        assert split_count >= 4

        # clear the data: the cold shards must merge back down
        async def clr(tr):
            tr.clear_range(b"m/", b"m0")

        await db.run(clr)
        for _ in range(90):
            await delay(1.0)
            if len(await walk(db)) <= split_count - 2:
                break
        merged = await walk(db)
        assert len(merged) <= split_count - 2, (split_count, merged)
        for (b1, e1, _t1), (b2, _e2, _t2) in zip(merged, merged[1:]):
            assert e1 == b2
        return True

    assert sim.run_until_done(spawn(body()), 600.0)
