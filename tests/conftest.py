"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on virtual CPU devices (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip). Must run before any
jax import, hence os.environ at module scope.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import random

import pytest


@pytest.fixture
def rng():
    return random.Random(0)
