"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on virtual CPU devices (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip). Must run before any
jax import, hence os.environ at module scope.
"""

import os

# Hard override: the shell environment points JAX at the axon TPU tunnel
# (JAX_PLATFORMS=axon); tests must never touch the single shared chip.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import random

import pytest


def pytest_configure(config):
    import jax

    # The axon TPU plugin (registered by sitecustomize at interpreter start)
    # hangs backend init whenever the tunnel relay is busy or wedged — and
    # xla_bridge initializes every registered platform, not just the ones in
    # JAX_PLATFORMS. Drop its factory so CPU tests can never touch it.
    import jax._src.xla_bridge as xb

    xb._backend_factories.pop("axon", None)
    # A pytest entry-point plugin may have imported jax before this conftest,
    # freezing jax_platforms from the original env — override via config.
    jax.config.update("jax_platforms", "cpu")

    # Persistent XLA compilation cache: this box has one CPU core, and cold
    # compiles dominate test time otherwise.
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


@pytest.fixture
def rng():
    return random.Random(0)
