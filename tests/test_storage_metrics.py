"""Keyspace telemetry (ISSUE 20): byte-sampled size estimates, read-hot
ranges, waitMetrics push sizing, and the metrics-history ring.

Acceptance battery: sampled estimates within ±20% of exact on a pinned
seed, same-seed sim runs producing byte-identical sample sets and
hot-range verdicts, a skewed 90%-to-one-prefix workload surfacing that
prefix top-1 in `workload.hot_ranges` / `cli hotranges`, a DD sizing
round issuing ZERO full-range scans while samples are armed (and falling
back to scans when sampling is off), the flowlint counter pins, the <3%
sampling+history overhead gate on the smoke readwrite shape, and the
soak drawing `randomize_storage_metrics` at the very end of the knob
sequence."""

import json
import pathlib
import re
import time

from foundationdb_tpu.client import management
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Endpoint, Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.rng import DeterministicRandom
from foundationdb_tpu.runtime.timeseries import MetricsHistory
from foundationdb_tpu.runtime.trace import TraceLog, set_trace_log
from foundationdb_tpu.server import Cluster
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.server.interfaces import (
    GetKeyServersRequest,
    Tokens,
    WaitMetricsRequest,
)
from foundationdb_tpu.server.storage_metrics import (
    StorageServerMetrics,
    derive_metrics_seed,
)
from foundationdb_tpu.tools.cli import FdbCli


def _bare_metrics(factor=200, seed=11):
    """A StorageServerMetrics outside any server: a sim is activated only
    so now() has a (frozen) clock for the bandwidth windows."""
    sim = Sim(seed=seed)
    sim.activate()
    knobs = Knobs(STORAGE_BYTE_SAMPLE_FACTOR=factor)
    return sim, StorageServerMetrics(knobs, seed=seed * 7 + 1)


async def _walk(db):
    out = []
    key = b""
    while True:
        reply = await db._proxy_request(
            Tokens.GET_KEY_SERVERS, GetKeyServersRequest(key=key)
        )
        out.append((reply.begin, reply.end, tuple(sorted(reply.tags))))
        if reply.end is None:
            return out
        key = reply.end


# -- (a) estimate accuracy + determinism --------------------------------------


def test_sampled_estimate_within_20pct_of_exact():
    """±20% accuracy on the pinned seed: mixed value sizes straddling the
    sample factor, full-range and sub-range estimates, and clears that
    take their weight back out."""
    _sim, m = _bare_metrics(factor=200, seed=11)
    rng = DeterministicRandom(42)
    exact = {}
    for i in range(3000):
        key = b"k/%05d" % i
        vlen = rng.random_int(1, 400)
        m.on_set(key, vlen)
        exact[key] = len(key) + vlen
    total = sum(exact.values())
    est = m.sample_bytes(b"k/", b"k0")
    assert abs(est - total) / total <= 0.20, (est, total)
    sub_total = sum(v for k, v in exact.items() if b"k/01" <= k < b"k/02")
    sub_est = m.sample_bytes(b"k/01", b"k/02")
    assert abs(sub_est - sub_total) / sub_total <= 0.20, (sub_est, sub_total)
    # clear-range removes the cleared weight; estimate tracks the shrink
    m.on_clear_range(b"k/02", b"k/03")
    assert m.sample_bytes(b"k/02", b"k/03") == 0
    remaining = sum(v for k, v in exact.items() if not b"k/02" <= k < b"k/03")
    est2 = m.sample_bytes(b"k/", b"k0")
    assert abs(est2 - remaining) / remaining <= 0.20, (est2, remaining)


def test_factor_one_is_exact_and_overwrites_do_not_double_count():
    _sim, m = _bare_metrics(factor=1, seed=2)
    m.on_set(b"a", 100)
    m.on_set(b"a", 10)  # overwrite: old weight dropped first
    m.on_set(b"b", 50)
    assert m.sample_bytes(b"", None) == (1 + 10) + (1 + 50)
    m.on_clear_key(b"b")
    assert m.sample_bytes(b"", None) == 11
    assert m.sample_entries() == 1


def test_derive_metrics_seed_is_identity_and_loop_stable():
    sim = Sim(seed=9)
    sim.activate()
    a = derive_metrics_seed("ss-1", 0)
    b = derive_metrics_seed("ss-1", 0)
    c = derive_metrics_seed("ss-2", 0)
    d = derive_metrics_seed("ss-1", 1)
    assert a == b
    assert len({a, c, d}) == 3
    # deriving the seed must not consume the sim's own rng stream
    before = sim.loop.random.random01()
    sim2 = Sim(seed=9)
    sim2.activate()
    derive_metrics_seed("ss-1", 0)
    assert sim2.loop.random.random01() == before


def _run_sampled_once(seed):
    """One full sim run (client → proxy → tlog → storage apply path);
    returns everything the sampler accumulated."""
    sim = Sim(seed=seed)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(n_proxies=1, n_resolvers=1))
    db = Database(sim, cluster.proxy_addrs)
    ss = cluster.storages[0]

    async def go():
        for base in range(0, 120, 20):

            async def w(tr, base=base):
                for i in range(20):
                    tr.set(b"d/%04d" % (base + i), b"v" * 90)

            await db.run(w)
        for i in range(60):

            async def r(tr, i=i):
                return await tr.get(b"d/%04d" % ((i * 7) % 120))

            await db.run(r)
        return True

    assert sim.run_until_done(spawn(go()), 600.0)
    verdicts = [
        (h["begin"], h["end"], h["read_bytes"], h["bytes"])
        for h in ss.metrics.read_hot_ranges(8)
    ]
    return dict(ss.metrics._sample), dict(ss.metrics._read), verdicts


def test_same_seed_runs_produce_byte_identical_samples_and_verdicts():
    """PR 6/9 determinism discipline: the sampling RNG is derived, never
    drawn from the sim stream — two same-seed runs agree byte-for-byte on
    the sample set, the read sample, and the hot-range verdicts."""
    assert _run_sampled_once(9) == _run_sampled_once(9)


# -- (b) waitMetrics: immediate, parked push, re-arm, sampling-off ------------


def test_wait_metrics_immediate_parked_push_and_rearm():
    _sim, m = _bare_metrics(factor=1, seed=3)  # p=1: exact arithmetic
    # estimate (0) already outside [5, 10] → immediate reply
    f = m.wait_metrics(b"a", b"b", 5, 10)
    assert f.is_ready()
    assert f.get()["sampled"] and f.get()["bytes"] == 0
    # inside [0, 100] → parked; covered writes push it across
    f2 = m.wait_metrics(b"a", b"b", 0, 100)
    assert not f2.is_ready() and m.wait_active() == 1
    m.on_set(b"a1", 40)  # 42 bytes, still inside the band
    m.on_set(b"zz", 500)  # outside [a, b): must not count
    assert not f2.is_ready()
    m.on_set(b"a2", 70)  # 42 + 72 = 114 > 100 → crossing fires the push
    assert f2.is_ready() and m.wait_active() == 0
    assert f2.get()["bytes"] == 114
    # a re-arm for the same range displaces (and settles) the older sub
    f3 = m.wait_metrics(b"a", b"b", 0, 10_000)
    f4 = m.wait_metrics(b"a", b"b", 0, 10_000)
    assert f3.is_ready()  # displaced, settled with a fresh estimate
    assert not f4.is_ready() and m.wait_active() == 1


def test_wait_metrics_endpoint_unsupported_when_sampling_off():
    knobs = Knobs(STORAGE_METRICS_SAMPLING=False)
    sim = Sim(seed=5, knobs=knobs)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(n_proxies=1, n_resolvers=1))
    db = Database(sim, cluster.proxy_addrs)
    ss = cluster.storages[0]

    async def go():
        async def w(tr):
            tr.set(b"k1", b"x" * 300)

        await db.run(w)
        return await db.client.request(
            Endpoint(ss.process.address, Tokens.WAIT_METRICS),
            WaitMetricsRequest(b"", None, -1, -1),
        )

    rep = sim.run_until_done(spawn(go()), 600.0)
    assert rep == {"unsupported": True}
    assert ss.metrics.sample_entries() == 0  # sampler really is inert


# -- (c) skewed workload → status / cli surfaces ------------------------------


def test_skewed_reads_surface_hot_range_in_status_and_cli():
    """90% of reads land on a 6-key hot/ prefix inside a 200-key cold/
    bulk: the hot range must rank top-1 in workload.hot_ranges, the
    byte_sampling evidence block must be live, and the `cli status` /
    `cli hotranges` / `cli metrics` surfaces must render it."""
    sim = Sim(seed=3)
    sim.activate()
    cluster = DynamicCluster(
        sim, ClusterConfig(n_storage=1, n_tlogs=1, n_proxies=1)
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    cli = FdbCli(db, cluster.coordinators)
    rng = DeterministicRandom(3)
    hot_keys = [b"hot/%03d" % i for i in range(6)]

    async def go():
        for base in range(0, 200, 20):

            async def w(tr, base=base):
                for i in range(20):
                    tr.set(b"cold/%05d" % (base + i), bytes(100))

            await db.run(w)

        async def wh(tr):
            for k in hot_keys:
                tr.set(k, bytes(256))

        await db.run(wh)
        for _ in range(300):
            key = (
                rng.random_choice(hot_keys)
                if rng.random01() < 0.9
                else b"cold/%05d" % rng.random_int(0, 200)
            )

            async def r(tr, key=key):
                return await tr.get(key)

            await db.run(r)
        await delay(6.0)  # metrics + history poll cadence
        doc = await management.get_status(cluster.coordinators, db.client)
        stext = await cli.execute("status")
        htext = await cli.execute("hotranges")
        mlist = await cli.execute("metrics")
        mtext = await cli.execute("metrics storage epochsApplied")
        return doc, stext, htext, mlist, mtext

    doc, stext, htext, mlist, mtext = sim.run_until_done(spawn(go()), 600.0)
    hot = doc["workload"]["hot_ranges"]
    assert hot, doc["workload"].get("byte_sampling")
    r0 = hot[0]
    # top-1 names the hot shard: its range intersects the hot/ prefix
    assert r0["begin"] < "hot0" and r0["end"] > "hot/", hot
    assert r0["density"] >= 2.0 and r0["read_bytes"] > 0
    assert r0["storage"]  # attributed to a storage server
    bs = doc["workload"]["byte_sampling"]
    assert bs["sample_entries"] > 0
    assert bs["bytes_sampled"]["counter"] > 0
    assert bs["hot_range_checks"]["counter"] > 0
    # cli surfaces
    assert "Hot ranges:" in stext, stext
    assert "hot range" in htext and "Byte sample:" in htext, htext
    assert "storage" in mlist, mlist
    assert "storage.epochsApplied over" in mtext, mtext


# -- (d) DD sizing: waitMetrics push replaces the scan ------------------------


def _count_scans(monkeypatch):
    from foundationdb_tpu.server.storage import StorageServer

    calls = []
    orig = StorageServer.get_shard_metrics

    async def counted(self, req):
        calls.append(req)
        return await orig(self, req)

    monkeypatch.setattr(StorageServer, "get_shard_metrics", counted)
    return calls


def _bulk_load_until_split(seed, knobs):
    sim = Sim(seed=seed, knobs=knobs)
    sim.activate()
    cluster = DynamicCluster(
        sim,
        ClusterConfig(n_storage=2, replication=2, n_tlogs=1),
        n_coordinators=1,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def body():
        for batch in range(20):

            async def w(tr, batch=batch):
                for i in range(10):
                    tr.set(b"bulk/%03d/%02d" % (batch, i), b"x" * 200)

            await db.run(w)
        shards = []
        for _ in range(60):
            await delay(1.0)
            shards = await _walk(db)
            if len(shards) >= 4:
                break
        assert len(shards) >= 4, shards
        await delay(6.0)  # let the CC metrics poll pick up the counters
        return await management.get_status(cluster.coordinators, db.client)

    return sim.run_until_done(spawn(body()), 600.0)


def test_dd_sizing_issues_zero_scans_when_samples_armed(monkeypatch):
    """The satellite-1 regression: with sampling on (default), a whole
    bulk-load-to-split sizing sequence must complete on waitMetrics
    pushes alone — zero storage.getShardMetrics full-range scans — and
    the pushes must actually have fired."""
    calls = _count_scans(monkeypatch)
    knobs = Knobs(
        DD_SHARD_MAX_BYTES=4096,
        DD_SHARD_MIN_BYTES=512,
        DD_TRACKER_INTERVAL=0.5,
    )
    doc = _bulk_load_until_split(71, knobs)
    assert not calls, f"DD fell back to {len(calls)} full-range scans"
    bs = doc["workload"]["byte_sampling"]
    assert bs["wait_metrics_fired"]["counter"] > 0, bs


def test_dd_falls_back_to_scan_when_sampling_off(monkeypatch):
    """The no-sample fallback stays alive: sampling disabled → the
    waitMetrics endpoint reports unsupported and DD sizes (and still
    splits) through the scan path."""
    calls = _count_scans(monkeypatch)
    knobs = Knobs(
        STORAGE_METRICS_SAMPLING=False,
        DD_SHARD_MAX_BYTES=4096,
        DD_SHARD_MIN_BYTES=512,
        DD_TRACKER_INTERVAL=0.5,
    )
    _bulk_load_until_split(71, knobs)
    assert calls, "sampling off but DD never scanned — sizing went dark"


# -- (e) metrics-history ring + timeline tooling ------------------------------


def test_metrics_history_ring_bounds_filtering_and_roundtrip():
    h = MetricsHistory(3)
    h.record(1.0, {"a": 1, "flag": True, "s": "x", "lst": [1, 2]})
    h.record(2.0, {"a": 2, "b": 5.5})
    assert h.names() == ["a", "b"]
    assert h.series("a") == [(1.0, 1), (2.0, 2)]
    h.record(3.0, {"a": 3})
    h.record(4.0, {"a": 4})
    assert len(h) == 3  # capacity evicts the oldest point
    assert h.series("a") == [(2.0, 2), (3.0, 3), (4.0, 4)]
    d = h.to_dict()
    json.dumps(d)  # wire/JSON-safe by construction
    assert MetricsHistory.from_dict(d).to_dict() == d


def test_trace_analyze_timeline_series_and_sparkline():
    from foundationdb_tpu.tools import trace_analyze as ta

    assert ta.sparkline([]) == ""
    assert ta.sparkline([7, 7]) == "▁▁"
    s = ta.sparkline([0, 1, 2, 3])
    assert len(s) == 4 and s[0] == "▁" and s[-1] == "█"
    events = [
        {"Type": "StorageMetrics", "ID": "ss0", "Time": 1.0,
         "epochsApplied": 1, "Severity": 10, "flag": True, "name": "x"},
        {"Type": "StorageMetrics", "ID": "ss0", "Time": 2.0,
         "epochsApplied": 3},
        {"Type": "GetValue", "Time": 1.5, "n": 9},  # not *Metrics
        {"Type": "ProxyMetrics", "Machine": "p0", "Time": 1.0, "commits": 2},
    ]
    tls = ta.timeline_series(events)
    assert tls["StorageMetrics#ss0"]["epochsApplied"] == [(1.0, 1), (2.0, 3)]
    assert not any("GetValue" in k for k in tls)
    assert "Severity" not in tls["StorageMetrics#ss0"]  # meta filtered
    only = ta.timeline_series(events, counter="commits")
    assert list(only) == ["ProxyMetrics#p0"]
    text = ta.format_timeline(tls)
    assert "epochsApplied" in text and "(2 pts)" in text, text
    assert "no *Metrics events" in ta.format_timeline({})


# -- (f) flowlint counter pins ------------------------------------------------

_WORKER = """\
class Worker:
    def _make_widget(self, h):
        from .widget import Widget
        w = Widget()
        return w
"""

_ROLE = """\
from ..runtime.stats import CounterCollection

class Widget:
    def __init__(self):
        self.stats = CounterCollection("widget")
        self._c_a = self.stats.counter("bytesSampled")
        self._c_b = self.stats.counter("waitMetricsFired")

    def register_instance(self, process):
        process.register(f"widget.metrics#{id(self)}", self._metrics)

    async def _metrics(self, _req):  # flowlint: disable=reg-endpoint-span
        return self.stats.snapshot()
"""


def test_flowlint_pins_storage_telemetry_counters(tmp_path):
    """Satellite 2: the five telemetry counters are pinned in the real
    config, and the reg-role-metrics rule flags a dropped pin with the
    exact `<Class>-counter-<name>` detail (fixture flag + near-miss)."""
    from foundationdb_tpu.tools.flowlint import lint, load_config

    pinned = set(load_config()["role_required_counters"]["storage"])
    assert {
        "bytesSampled",
        "sampleEntries",
        "hotRangeChecks",
        "waitMetricsActive",
        "waitMetricsFired",
    } <= pinned, pinned

    def run(role_src):
        pkg = tmp_path / "foundationdb_tpu" / "server"
        pkg.mkdir(parents=True, exist_ok=True)
        (pkg / "worker.py").write_text(_WORKER)
        (pkg / "widget.py").write_text(role_src)
        return lint(
            root=tmp_path,
            config={
                "include": ["foundationdb_tpu"],
                "exclude": [],
                "sim_scope": [],
                "host_only": {},
                "baseline": "baseline.json",
                "worker_module": "foundationdb_tpu/server/worker.py",
                "role_exempt": [],
                "span_roles": [],
                "role_required_counters": {
                    "widget": ["bytesSampled", "waitMetricsFired"]
                },
            },
        )

    res = run(_ROLE)
    assert not res.failing, [f.format() for f in res.failing]
    dropped = _ROLE.replace(
        '        self._c_b = self.stats.counter("waitMetricsFired")\n', ""
    )
    res = run(dropped)
    assert any(
        f.rule == "reg-role-metrics"
        and f.detail == "Widget-counter-waitMetricsFired"
        for f in res.failing
    ), [f.format() for f in res.failing]
    assert not any(
        f.detail == "Widget-counter-bytesSampled" for f in res.failing
    )


# -- (g) overhead gate + soak wiring ------------------------------------------


def test_telemetry_overhead_under_three_percent_on_smoke_readwrite():
    """Satellite 6: byte/read sampling + the history loop cost <3% wall
    time on the smoke readwrite shape (same best-of-3 interleaved harness
    as the PR 9 profiler gate)."""
    from foundationdb_tpu.workloads import run_workloads
    from foundationdb_tpu.workloads.readwrite import ReadWriteWorkload

    def one_run(enabled):
        set_trace_log(TraceLog())
        sim = Sim(
            seed=3,
            knobs=Knobs(
                STORAGE_METRICS_SAMPLING=enabled,
                METRICS_HISTORY_ENABLED=enabled,
            ),
        )
        sim.activate()
        cluster = Cluster(sim, ClusterConfig(n_proxies=1, n_resolvers=1))
        db = Database(sim, cluster.proxy_addrs)
        w = ReadWriteWorkload(
            db,
            DeterministicRandom(3),
            actors=5,
            txns_per_actor=8,
            reads_per_txn=9,
            writes_per_txn=1,
            keyspace=500,
        )

        async def go():
            await run_workloads([w])
            return True

        t0 = time.perf_counter()
        assert sim.run_until_done(spawn(go()), 600.0)
        return time.perf_counter() - t0

    on, off = [], []
    for _ in range(3):
        off.append(one_run(False))
        on.append(one_run(True))
    assert min(on) <= min(off) * 1.03 + 0.02, (on, off)


def test_soak_draws_storage_metrics_last_and_reports_armed():
    """Satellite 4: randomize_storage_metrics is the VERY end of the soak
    knob-draw sequence (pinned seeds from earlier PRs reproduce), and the
    summary reports what it armed."""
    from foundationdb_tpu.tools import soak as soak_mod

    src = pathlib.Path(soak_mod.__file__).read_text()
    draws = re.findall(r"knobs\.randomize_(\w+)\(", src)
    assert draws and draws[-1] == "storage_metrics", draws

    out = soak_mod.run_one(1)
    armed = out["storage_metrics_armed"]
    assert set(armed) == {
        "sampling",
        "byte_sample_factor",
        "wait_metrics_sizing",
        "history_interval",
        "history_samples",
    }, armed
