"""Actor-runtime semantics tests — the dsltest analog (fdbrpc/dsltest.actor.cpp):
futures/promises, streams, combinators, cancellation, priorities, determinism."""

import pytest

from foundationdb_tpu.runtime.futures import (
    ActorCollection,
    AsyncVar,
    Cancelled,
    Future,
    Promise,
    PromiseStream,
    delay,
    spawn,
    timeout,
    wait_for_all,
    wait_for_any,
    yield_now,
)
from foundationdb_tpu.runtime.loop import EventLoop, TaskPriority, set_loop


@pytest.fixture
def loop():
    l = EventLoop(seed=1)
    set_loop(l)
    yield l
    set_loop(None)


def run(loop, fut, limit=1e6):
    loop.run(until=limit, stop_when=fut.is_ready)
    return fut.get()


def test_promise_future_basics(loop):
    p = Promise()

    async def reader():
        return await p.future

    f = spawn(reader())
    loop.run(until=0)
    assert not f.is_ready()
    p.send(42)
    assert run(loop, f) == 42


def test_delay_advances_virtual_time(loop):
    async def sleeper():
        t0 = loop.now()
        await delay(5.0)
        return loop.now() - t0

    assert run(loop, spawn(sleeper())) == pytest.approx(5.0)


def test_error_propagation(loop):
    async def boom():
        await yield_now()
        raise ValueError("x")

    async def catcher():
        try:
            await spawn(boom())
        except ValueError as e:
            return str(e)

    assert run(loop, spawn(catcher())) == "x"


def test_cancellation_reaches_actor(loop):
    witness = []

    async def victim():
        try:
            await delay(100)
        except Cancelled:
            witness.append("cancelled")
            raise

    f = spawn(victim())
    loop.run(until=1)

    async def killer():
        f.cancel()
        await yield_now()

    run(loop, spawn(killer()))
    loop.run(until=2)
    assert witness == ["cancelled"]
    assert f.is_error()


def test_stream_fifo_and_blocking(loop):
    s = PromiseStream()
    got = []

    async def consumer():
        for _ in range(3):
            got.append(await s.next())
        return got

    f = spawn(consumer())

    async def producer():
        s.send(1)
        await delay(1)
        s.send(2)
        s.send(3)

    spawn(producer())
    assert run(loop, f) == [1, 2, 3]


def test_wait_for_any_and_timeout(loop):
    async def slow():
        await delay(10)
        return "slow"

    async def use_timeout():
        return await timeout(spawn(slow()), 1.0, default="timed out")

    assert run(loop, spawn(use_timeout())) == "timed out"

    async def fast_enough():
        async def quick():
            await delay(0.1)
            return "ok"

        return await timeout(spawn(quick()), 1.0)

    assert run(loop, spawn(fast_enough())) == "ok"


def test_async_var_wakes_waiters(loop):
    v = AsyncVar(0)

    async def watcher():
        while v.get() < 3:
            await v.on_change()
        return v.get()

    f = spawn(watcher())

    async def bumper():
        for i in range(1, 4):
            await delay(1)
            v.set(i)

    spawn(bumper())
    assert run(loop, f) == 3


def test_actor_collection_propagates_errors(loop):
    ac = ActorCollection()

    async def fine():
        await delay(1)

    async def bad():
        await delay(2)
        raise RuntimeError("role died")

    ac.add(spawn(fine()))
    ac.add(spawn(bad()))
    loop.run(until=5)
    assert ac.error.is_error()
    with pytest.raises(RuntimeError):
        ac.error.get()


def test_priority_ordering_same_time(loop):
    order = []
    loop.call_at(1.0, lambda: order.append("low"), TaskPriority.LOW)
    loop.call_at(1.0, lambda: order.append("high"), TaskPriority.TLOG_COMMIT)
    loop.call_at(1.0, lambda: order.append("mid"), TaskPriority.DEFAULT)
    loop.run()
    assert order == ["high", "mid", "low"]


def test_determinism_same_seed_same_schedule():
    def one_run(seed):
        l = EventLoop(seed)
        set_loop(l)
        trace = []

        async def chatter(name):
            for _ in range(5):
                await delay(l.random.random01())
                trace.append((round(l.now(), 9), name))

        for n in ["a", "b", "c"]:
            spawn(chatter(n))
        l.run()
        set_loop(None)
        return trace

    assert one_run(7) == one_run(7)
    assert one_run(7) != one_run(8)
