"""KV core: mutations, atomic ops, VersionedMap, KeyRangeMap.

Differential style mirrors the reference's oracle-based workloads
(fdbserver/workloads/MemoryKeyValueStore.h): the VersionedMap is fuzzed
against per-version dict snapshots.
"""

import random

from foundationdb_tpu.kv import KeyRangeMap, VersionedMap
from foundationdb_tpu.kv.atomic import apply_atomic
from foundationdb_tpu.kv.mutations import MutationType as MT


# -- atomic ops ---------------------------------------------------------------


def test_add_little_endian():
    assert apply_atomic(MT.ADD, b"\x01\x00", b"\x01\x00") == b"\x02\x00"
    assert apply_atomic(MT.ADD, b"\xff\x00", b"\x01\x00") == b"\x00\x01"
    # wraps modulo 2^(8*len(param))
    assert apply_atomic(MT.ADD, b"\xff\xff", b"\x01\x00") == b"\x00\x00"
    # missing key: operand added to zero
    assert apply_atomic(MT.ADD, None, b"\x05") == b"\x05"
    # existing longer than operand: truncated to operand length
    assert apply_atomic(MT.ADD, b"\x01\x02\x03", b"\x01") == b"\x02"


def test_bitwise():
    assert apply_atomic(MT.AND, b"\x0f", b"\x3c") == b"\x0c"
    assert apply_atomic(MT.AND, None, b"\xff") == b"\xff"  # doAndV2: absent → operand
    assert apply_atomic(MT.OR, b"\x0f", b"\x30") == b"\x3f"
    assert apply_atomic(MT.XOR, b"\xff", b"\x0f") == b"\xf0"


def test_min_max():
    assert apply_atomic(MT.MAX, b"\x05", b"\x03") == b"\x05"
    assert apply_atomic(MT.MIN, b"\x05", b"\x03") == b"\x03"
    assert apply_atomic(MT.MAX, None, b"\x03") == b"\x03"
    assert apply_atomic(MT.MIN, None, b"\x03") == b"\x03"
    # little-endian comparison: b"\x00\x01" (256) > b"\x02\x00" (2)
    assert apply_atomic(MT.MAX, b"\x00\x01", b"\x02\x00") == b"\x00\x01"
    assert apply_atomic(MT.BYTE_MAX, b"aa", b"ab") == b"ab"
    assert apply_atomic(MT.BYTE_MIN, b"aa", b"ab") == b"aa"
    assert apply_atomic(MT.BYTE_MIN, None, b"zz") == b"zz"


def test_append_and_cas():
    assert apply_atomic(MT.APPEND_IF_FITS, b"ab", b"cd") == b"abcd"
    assert apply_atomic(MT.APPEND_IF_FITS, None, b"x") == b"x"
    assert apply_atomic(MT.COMPARE_AND_CLEAR, b"v", b"v") is None
    assert apply_atomic(MT.COMPARE_AND_CLEAR, b"v", b"w") == b"v"


# -- VersionedMap -------------------------------------------------------------


def test_versioned_map_basics():
    m = VersionedMap()
    m.set(b"a", b"1", 10)
    m.set(b"b", b"2", 10)
    m.set(b"a", b"3", 20)
    assert m.get(b"a", 10) == b"1"
    assert m.get(b"a", 15) == b"1"
    assert m.get(b"a", 20) == b"3"
    assert m.get(b"b", 20) == b"2"
    assert m.get(b"c", 20) is None
    m.clear_range(b"a", b"b", 30)
    assert m.get(b"a", 30) is None
    assert m.get(b"a", 25) == b"3"
    assert m.get(b"b", 30) == b"2"


def test_versioned_map_range():
    m = VersionedMap()
    for i in range(10):
        m.set(b"k%02d" % i, b"v%d" % i, 5)
    m.clear_range(b"k03", b"k06", 10)
    assert [k for k, _ in m.range(b"k00", b"k99", 5)] == [b"k%02d" % i for i in range(10)]
    got = [k for k, _ in m.range(b"k00", b"k99", 10)]
    assert got == [b"k00", b"k01", b"k02", b"k06", b"k07", b"k08", b"k09"]
    got = m.range(b"k00", b"k99", 10, limit=2, reverse=True)
    assert [k for k, _ in got] == [b"k09", b"k08"]


def test_versioned_map_forget():
    m = VersionedMap()
    m.set(b"a", b"1", 10)
    m.set(b"a", b"2", 20)
    m.clear_range(b"a", b"b", 30)
    m.set(b"c", b"3", 30)
    m.forget_before(25)
    assert m.get(b"a", 25) == b"2"
    assert m.get(b"a", 30) is None
    m.forget_before(35)
    # tombstoned key fully below the window is gone; live key remains
    assert m.get(b"a", 35) is None
    assert m.get(b"c", 35) == b"3"
    assert list(m) == [b"c"]


def test_versioned_map_fuzz_vs_snapshots():
    rng = random.Random(7)
    m = VersionedMap()
    model: dict[bytes, bytes] = {}
    snapshots: dict[int, dict[bytes, bytes]] = {0: {}}
    version = 0
    keys = [b"k%02d" % i for i in range(30)]
    for _ in range(300):
        version += rng.randint(1, 3)
        for _ in range(rng.randint(1, 4)):
            op = rng.random()
            if op < 0.6:
                k, v = rng.choice(keys), b"v%d" % rng.randint(0, 999)
                m.set(k, v, version)
                model[k] = v
            else:
                a, b = sorted((rng.choice(keys), rng.choice(keys)))
                m.clear_range(a, b, version)
                for k in [k for k in model if a <= k < b]:
                    del model[k]
        snapshots[version] = dict(model)
    # every snapshot readable at its version
    versions = sorted(snapshots)
    for v in versions:
        expect = sorted(snapshots[v].items())
        got = m.range(b"", b"\xff", v)
        assert got == expect, f"at version {v}"
    # compaction preserves reads at-or-above the horizon
    horizon = versions[len(versions) // 2]
    m.forget_before(horizon)
    for v in versions:
        if v >= horizon:
            assert m.range(b"", b"\xff", v) == sorted(snapshots[v].items())


# -- KeyRangeMap --------------------------------------------------------------


def test_keyrange_map():
    m = KeyRangeMap(default=0)
    assert m[b"anything"] == 0
    m.insert(b"b", b"d", 1)
    m.insert(b"c", b"e", 2)
    assert m[b"a"] == 0
    assert m[b"b"] == 1
    assert m[b"c"] == 2
    assert m[b"d"] == 2
    assert m[b"e"] == 0
    rs = list(m.ranges())
    assert rs == [(b"", b"b", 0), (b"b", b"c", 1), (b"c", b"e", 2), (b"e", None, 0)]
    # clipped intersection
    hits = m.intersecting(b"bb", b"dd")
    assert hits == [(b"bb", b"c", 1), (b"c", b"dd", 2)]
    # to-infinity insert + coalesce
    m.insert(b"e", None, 2)
    m.coalesce()
    assert list(m.ranges()) == [(b"", b"b", 0), (b"b", b"c", 1), (b"c", None, 2)]


def test_keyrange_map_fuzz_vs_dict():
    rng = random.Random(3)
    m = KeyRangeMap(default=-1)
    probe = [bytes([c]) + bytes([d]) for c in range(97, 107) for d in range(97, 107)]
    model = {p: -1 for p in probe}
    for i in range(200):
        a, b = sorted(rng.sample(probe, 2))
        m.insert(a, b, i)
        for p in probe:
            if a <= p < b:
                model[p] = i
    for p in probe:
        assert m[p] == model[p]
