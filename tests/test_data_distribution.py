"""DataDistribution: automatic re-replication after storage failure, and
Ratekeeper admission control.

The analog of the reference's RemoveServersSafely/ConsistencyCheck spirit:
kill a storage server; DD (in the master) must rebuild the affected teams
on healthy servers via MoveKeys; all data stays readable at full
replication.
"""

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.server.interfaces import GetKeyServersRequest, Tokens


def make(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = DynamicCluster(sim, ClusterConfig(**cfg))
    db = Database.from_coordinators(sim, cluster.coordinators)
    return sim, cluster, db


def run(sim, coro, limit=600.0):
    return sim.run_until_done(spawn(coro), limit)


async def put(db, key, value):
    async def body(tr):
        tr.set(key, value)

    await db.run(body)


async def get(db, key):
    async def body(tr):
        return await tr.get(key)

    return await db.run(body)


async def walk_shards(db):
    out, key = [], b""
    while True:
        r = await db._proxy_request(
            Tokens.GET_KEY_SERVERS, GetKeyServersRequest(key=key)
        )
        out.append((r.begin, r.end, tuple(r.tags)))
        if r.end is None:
            return out
        key = r.end


def test_dd_rereplicates_after_storage_death():
    sim, cluster, db = make(
        seed=31,
        n_proxies=1,
        n_resolvers=1,
        n_tlogs=2,
        n_storage=4,
        replication=2,
        tlog_replication=2,
    )

    async def body():
        for i in range(40):
            await put(db, b"%02x-key" % (i * 6), b"v%d" % i)  # spread shards

        # kill the storage server with tag 3 (its worker, no reboot)
        victim = None
        for addr, p in sim.processes.items():
            w = getattr(p, "worker", None)
            if w and p.alive:
                for h in w.roles.values():
                    if h.kind == "storage" and h.obj.tag == 3:
                        victim = addr
        assert victim
        sim.kill_process(victim)

        # DD must notice and rebuild every team containing tag 3
        deadline = 60.0
        start = sim.loop.now()
        while True:
            await delay(2.0)
            shards = await walk_shards(db)
            if all(3 not in tags and len(tags) == 2 for _b, _e, tags in shards):
                break
            assert sim.loop.now() - start < deadline, shards

        # all data still present, served at full replication
        db.invalidate_cache(b"\x00")
        db._locations = type(db._locations)(default=None)
        for i in range(40):
            assert await get(db, b"%02x-key" % (i * 6)) == b"v%d" % i, i

    run(sim, body())


def test_ratekeeper_reports_rate():
    sim, cluster, db = make(
        seed=32, n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=1
    )

    async def body():
        await put(db, b"a", b"1")
        # find the live proxy and check its admission gate engaged (a
        # getRate reply arrived: per-class rates installed)
        await delay(2.0)
        rates = [
            h.obj.admission.rates
            for p in sim.processes.values()
            if getattr(p, "worker", None)
            for h in p.worker.roles.values()
            if h.kind == "proxy" and not h.obj.failed
        ]
        assert rates and all(r is not None for r in rates), rates
        for r in rates:
            assert set(r) == {"batch", "default", "immediate"}, r
            # healthy cluster: every class granted a positive rate
            assert all(v > 0 for v in r.values()), r
        assert await get(db, b"a") == b"1"

    run(sim, body())
