"""Ratekeeper-grade admission control (ISSUE 13): priority classes,
per-tenant token buckets, shed-don't-collapse overload behavior.

Batteries:
- compute_rates: the pure multi-signal controller (every signal throttles;
  kernel DEGRADED tightens; shed order batch -> default -> immediate);
- GrvAdmission unit behavior on a deterministic sim loop (starvation,
  tenant fair-share, deadline shedding, proxy-death wakeup, Cancelled
  cleanup — the GRV gate wakeup satellite);
- client plumbing (priority/tenant on the envelope, bounded throttle
  backoff — the regression test alongside flowlint's
  actor-unbounded-retry rule);
- end-to-end: a DynamicCluster overload run that sheds instead of
  collapsing, with the evidence visible in the status document's qos
  section; live-membership discovery by the Ratekeeper.
"""

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.errors import GrvThrottled
from foundationdb_tpu.net.sim import BrokenPromise, Sim
from foundationdb_tpu.runtime.futures import delay, spawn, wait_for_all
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.stats import CounterCollection
from foundationdb_tpu.server.admission import (
    PRIORITY_BATCH,
    PRIORITY_DEFAULT,
    PRIORITY_IMMEDIATE,
    GrvAdmission,
    coerce_priority,
)
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.server.data_distribution import Ratekeeper, compute_rates


def make(seed=0, knob_overrides=None, **cfg):
    knobs = Knobs(**(knob_overrides or {}))
    sim = Sim(seed=seed, knobs=knobs)
    sim.activate()
    cluster = DynamicCluster(sim, ClusterConfig(**cfg))
    db = Database.from_coordinators(sim, cluster.coordinators)
    return sim, cluster, db


def run(sim, coro, limit=600.0):
    return sim.run_until_done(spawn(coro), limit)


HEALTHY = {
    "version_lag": 0,
    "durability_lag": 0,
    "tlog_queue_bytes": 0,
    "busy_fraction": 0.1,
    "band_overrun": 0.0,
    "kernel_state": "HEALTHY",
}


# -- pure controller -----------------------------------------------------------


def test_compute_rates_healthy_full_rates():
    k = Knobs()
    rates, limiting = compute_rates(k, dict(HEALTHY))
    assert limiting == "workload"
    assert rates["default"] == k.RK_MAX_TPS
    assert rates["batch"] == k.RK_MAX_TPS
    assert rates["immediate"] == k.RK_MAX_TPS


def test_compute_rates_kernel_degraded_tightens():
    """A DEGRADED conflict kernel must tighten admission instead of
    queueing resolve batches into the dispatch deadline."""
    k = Knobs()
    healthy, _ = compute_rates(k, dict(HEALTHY))
    degraded, limiting = compute_rates(
        k, dict(HEALTHY, kernel_state="DEGRADED")
    )
    assert limiting == "kernel_degraded"
    assert degraded["default"] == healthy["default"] * k.RK_KERNEL_DEGRADED_FACTOR
    # batch bites twice (sheds first)
    assert degraded["batch"] < degraded["default"]
    # immediate unaffected by DEGRADED (failover still serves)
    assert degraded["immediate"] == healthy["immediate"]
    failed, _ = compute_rates(k, dict(HEALTHY, kernel_state="FAILED"))
    assert failed["default"] < degraded["default"]
    assert failed["immediate"] < healthy["immediate"]


def test_compute_rates_each_signal_throttles():
    k = Knobs()
    cases = {
        "storage_version_lag": dict(
            HEALTHY, version_lag=(k.RK_LAG_TARGET + k.RK_LAG_MAX) // 2
        ),
        "storage_durability_lag": dict(
            HEALTHY,
            durability_lag=(k.RK_DURABILITY_LAG_TARGET + k.RK_DURABILITY_LAG_MAX)
            // 2,
        ),
        "tlog_queue": dict(
            HEALTHY,
            tlog_queue_bytes=(k.RK_TLOG_QUEUE_TARGET + k.RK_TLOG_QUEUE_MAX) // 2,
        ),
        "run_loop_busy": dict(
            HEALTHY,
            busy_fraction=(k.RK_BUSY_FRACTION_TARGET + k.RK_BUSY_FRACTION_MAX)
            / 2,
        ),
        "latency_bands": dict(
            HEALTHY,
            band_overrun=(k.RK_BAND_OVERRUN_TARGET + k.RK_BAND_OVERRUN_MAX) / 2,
        ),
    }
    for expect, sig in cases.items():
        rates, limiting = compute_rates(k, sig)
        assert limiting == expect, (expect, limiting)
        assert rates["default"] < k.RK_MAX_TPS, expect
        # shed order: batch throttles at least as hard as default
        assert rates["batch"] <= rates["default"], expect
        # immediate unaffected by ordinary duress
        assert rates["immediate"] == k.RK_MAX_TPS, expect


def test_compute_rates_floors_and_immediate_mvcc_danger():
    k = Knobs()
    # everything past max: default floors, batch goes to zero
    sig = dict(
        HEALTHY,
        version_lag=k.RK_LAG_MAX,
        durability_lag=k.RK_DURABILITY_LAG_MAX * 2,
        tlog_queue_bytes=k.RK_TLOG_QUEUE_MAX * 2,
    )
    rates, _ = compute_rates(k, sig)
    assert rates["default"] == k.RK_MAX_TPS * k.RK_RATE_FLOOR
    assert rates["batch"] == 0.0
    # immediate starts draining only past RK_LAG_MAX (MVCC danger zone)
    assert rates["immediate"] == k.RK_MAX_TPS
    sig["version_lag"] = (
        k.RK_LAG_MAX + k.MAX_READ_TRANSACTION_LIFE_VERSIONS
    ) // 2
    rates, _ = compute_rates(k, sig)
    assert rates["immediate"] < k.RK_MAX_TPS
    # unknown signals (None) are treated as healthy, not as overload
    rates, limiting = compute_rates(k, {})
    assert limiting == "workload" and rates["default"] == k.RK_MAX_TPS


def test_coerce_priority():
    assert coerce_priority("batch") == PRIORITY_BATCH
    assert coerce_priority("immediate") == PRIORITY_IMMEDIATE
    assert coerce_priority("nonsense") == PRIORITY_DEFAULT
    assert coerce_priority(None) == PRIORITY_DEFAULT
    assert coerce_priority(99) == PRIORITY_IMMEDIATE
    assert coerce_priority(-3) == PRIORITY_BATCH


# -- GrvAdmission unit behavior ------------------------------------------------


def _admission(sim, **knob_overrides):
    for k, v in knob_overrides.items():
        setattr(sim.knobs, k, v)
    stats = CounterCollection("Proxy", "t")
    adm = GrvAdmission(sim.knobs, stats)
    p = sim.new_process("adm-test")
    p.spawn(adm.pump())
    return adm, stats


def test_batch_flood_cannot_starve_immediate():
    """Starvation acceptance: with batch granted 0 and a deep batch
    queue parked, immediate-class requests are admitted promptly while
    every batch waiter sheds (batch 100% shed, immediate p95 bounded)."""
    sim = Sim(seed=3)
    sim.activate()
    adm, _stats = _admission(sim)
    adm.set_rates({"batch": 0.0, "default": 1000.0, "immediate": 1000.0})

    from foundationdb_tpu.runtime.loop import now

    results = {"batch": [], "immediate": []}

    async def one(cls, bucket):
        t0 = now()
        try:
            await adm.admit(cls, "")
            results[bucket].append(("ok", now() - t0))
        except GrvThrottled:
            results[bucket].append(("shed", now() - t0))

    async def body():
        floods = [spawn(one(PRIORITY_BATCH, "batch")) for _ in range(40)]
        await delay(0.01)  # the flood parks first
        probes = [spawn(one(PRIORITY_IMMEDIATE, "immediate")) for _ in range(10)]
        await wait_for_all(floods + probes)

    sim.run_until_done(spawn(body()), 60.0)
    assert all(r[0] == "shed" for r in results["batch"]), results["batch"][:3]
    assert all(r[0] == "ok" for r in results["immediate"])
    # immediate admitted promptly (well under its own queue deadline)
    worst = max(r[1] for r in results["immediate"])
    assert worst < sim.knobs.RK_GRV_QUEUE_TIMEOUT, worst
    # batch shed AT its deadline, not after an unbounded park
    batch_deadline = sim.knobs.RK_GRV_QUEUE_TIMEOUT * 0.5
    assert all(r[1] <= batch_deadline + 0.1 for r in results["batch"])


def test_tenant_fair_share_hot_tenant_cannot_starve_cold():
    sim = Sim(seed=4)
    sim.activate()
    adm, _stats = _admission(sim, RK_TENANT_MAX_SHARE=0.25)
    # default class: plenty of class tokens; the TENANT share is the
    # scarce resource (25% of 40/s = 10/s per tenant)
    adm.set_rates({"batch": 0.0, "default": 40.0, "immediate": 40.0})

    results = {"hot": [], "cold": []}

    async def one(tenant, bucket):
        try:
            await adm.admit(PRIORITY_DEFAULT, tenant)
            results[bucket].append("ok")
        except GrvThrottled:
            results[bucket].append("shed")

    async def body():
        hot = [spawn(one("hot", "hot")) for _ in range(60)]
        await delay(0.005)  # hot tenant's flood parks first
        cold = [spawn(one("cold", "cold")) for _ in range(5)]
        await wait_for_all(hot + cold)

    sim.run_until_done(spawn(body()), 60.0)
    # the cold tenant rides its own bucket: everything admitted even
    # though 60 hot waiters arrived first (no head-of-line starvation)
    assert results["cold"] == ["ok"] * 5, results["cold"]
    # the hot tenant is capped at its share: most of the flood sheds
    assert results["hot"].count("shed") > 0
    snap = adm._tenant_snapshot()
    assert snap["hot"]["throttled"] > 0
    assert snap["cold"]["admitted"] == 5


def test_queue_overflow_sheds_on_arrival():
    sim = Sim(seed=5)
    sim.activate()
    adm, stats = _admission(sim, RK_GRV_QUEUE_MAX=4)
    adm.set_rates({"batch": 0.0, "default": 0.5, "immediate": 1.0})

    sheds = []

    async def one(i):
        try:
            await adm.admit(PRIORITY_DEFAULT, "")
        except GrvThrottled as e:
            sheds.append((i, str(e)))

    async def body():
        await wait_for_all([spawn(one(i)) for i in range(12)])

    sim.run_until_done(spawn(body()), 60.0)
    # 4 park (then shed at deadline), the rest shed immediately on a
    # full queue; nothing hangs
    assert len(sheds) >= 8
    assert any("queue full" in s for _i, s in sheds)
    assert stats.counters["grvThrottled"].value >= 8


def test_parked_waiters_observe_proxy_death_promptly():
    """The GRV gate wakeup satellite: fail_all must error every parked
    waiter with BrokenPromise in zero additional sim time."""
    sim = Sim(seed=6)
    sim.activate()
    adm, _stats = _admission(sim)
    adm.set_rates({"batch": 0.0, "default": 0.0, "immediate": 0.0})

    from foundationdb_tpu.runtime.loop import now

    outcomes = []

    async def one():
        t0 = now()
        try:
            await adm.admit(PRIORITY_DEFAULT, "")
            outcomes.append(("ok", now() - t0))
        except BrokenPromise:
            outcomes.append(("dead", now() - t0))
        except GrvThrottled:
            outcomes.append(("shed", now() - t0))

    async def body():
        waiters = [spawn(one()) for _ in range(8)]
        await delay(0.05)  # all parked, well before the 0.5s deadline
        adm.fail_all()
        await wait_for_all(waiters)

    sim.run_until_done(spawn(body()), 60.0)
    assert [o[0] for o in outcomes] == ["dead"] * 8, outcomes
    # promptly: at the fail_all instant, not at the queue deadline
    assert all(o[1] < 0.1 for o in outcomes), outcomes
    # a dead gate admits nothing but also blocks nothing (the caller's
    # _check_alive raises): admit() must not hang after failure
    post = []

    async def after():
        await adm.admit(PRIORITY_DEFAULT, "")
        post.append("through")

    sim.run_until_done(spawn(after()), 60.0)
    assert post == ["through"]


def test_cancelled_waiter_is_cleaned_up():
    sim = Sim(seed=7)
    sim.activate()
    adm, _stats = _admission(sim)
    adm.set_rates({"batch": 0.0, "default": 2.0, "immediate": 2.0})

    async def parked():
        await adm.admit(PRIORITY_DEFAULT, "")
        raise AssertionError("cancelled waiter must not be admitted")

    async def body():
        w = spawn(parked())
        await delay(0.01)
        assert adm.has_waiters()
        w.cancel()
        await delay(0.01)
        assert not adm.has_waiters()  # entry dropped, not ghost-admitted
        # the pump keeps serving later arrivals
        await adm.admit(PRIORITY_DEFAULT, "")
        return True

    assert sim.run_until_done(spawn(body()), 60.0)


# -- client plumbing -----------------------------------------------------------


def test_throttle_retry_backoff_is_bounded():
    """Regression alongside flowlint's actor-unbounded-retry: a client
    hammered with grv_throttled keeps a BOUNDED backoff (<=
    CLIENT_MAX_RETRY_DELAY) and grv_throttled is retryable."""
    sim, _cluster, db = make(seed=8, n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=1)

    async def body():
        assert GrvThrottled.retryable
        tr = db.transaction(priority="batch", tenant="t-0")
        waits = []
        from foundationdb_tpu.runtime.loop import now

        for _ in range(12):
            t0 = now()
            await tr.on_error(GrvThrottled())
            waits.append(now() - t0)
            # options survive the reset inside on_error
            assert tr.priority == PRIORITY_BATCH and tr.tenant == "t-0"
        cap = db.knobs.CLIENT_MAX_RETRY_DELAY
        assert max(waits) <= cap + 1e-6, waits
        # it actually backs off (grows toward the cap, no busy spin)
        assert waits[-1] > waits[0]
        return True

    assert run(sim, body())


def test_priority_and_tenant_reach_status():
    """End-to-end plumbing: per-class admitted counters and per-tenant
    roll-ups reach the status document's qos section; the ratekeeper
    publishes per-class released rates."""
    sim, cluster, db = make(
        seed=9, n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=1
    )

    async def put(priority, tenant, key):
        async def body(tr):
            tr.set_priority(priority)
            tr.set_tenant(tenant)
            await tr.get(key)  # a read forces the GRV (and admission)
            tr.set(key, b"v")

        await db.run(body)

    async def body():
        for i in range(3):
            await put("batch", "tenant-a", b"a%d" % i)
            await put("default", "tenant-b", b"b%d" % i)
            await put("immediate", "", b"c%d" % i)
        await delay(2.0)  # let rate grants and metric intervals land
        from foundationdb_tpu.client import management

        doc = await management.get_status(cluster.coordinators, db.client)
        qos = doc["qos"]
        adm = qos["admitted_per_class"]
        assert adm["batch"]["counter"] >= 3, adm
        assert adm["default"]["counter"] >= 3, adm
        assert adm["immediate"]["counter"] >= 3, adm  # + probes/DD
        assert "throttled_total" in qos
        assert set(qos["released_per_class"]) == {
            "batch", "default", "immediate",
        }
        assert qos["limiting"]
        tenants = qos.get("tenants") or {}
        assert "tenant-a" in tenants and "tenant-b" in tenants, tenants
        assert tenants["tenant-a"]["admitted"] >= 3
        # ratekeeper role surface: its own metrics endpoint answers
        from foundationdb_tpu.net.sim import Endpoint

        info = None
        for p in sim.processes.values():
            if any(t.startswith("ratekeeper.metrics#") for t in p.endpoints):
                info = p
                break
        assert info is not None, "no ratekeeper.metrics endpoint registered"
        token = next(
            t for t in info.endpoints if t.startswith("ratekeeper.metrics#")
        )
        snap = await db.client.request(Endpoint(info.address, token), None)
        assert snap["name"] == "Ratekeeper"
        assert set(snap["rates"]) == {"batch", "default", "immediate"}
        assert snap["controlLoops"] > 0
        return True

    assert run(sim, body())


def test_ratekeeper_discovers_live_membership():
    """Satellite 1: a Ratekeeper constructed with an EMPTY storage seed
    list still sees every storage server (and the tlog/kernel signals)
    through the CC's live worker registry — storage recruited after boot
    is visible to lag monitoring."""
    sim, cluster, db = make(
        seed=10, n_proxies=1, n_resolvers=1, n_tlogs=2, n_storage=2
    )

    async def body():
        async def touch(tr):
            tr.set(b"k", b"v")

        await db.run(touch)
        await delay(1.0)
        # find the live CC (worker registry owner)
        from foundationdb_tpu.server.interfaces import Tokens

        cc_addr = next(
            a
            for a, p in sim.processes.items()
            if Tokens.CC_GET_WORKERS in p.endpoints
        )

        class _MasterStub:
            last_assigned = 0

        rk = Ratekeeper(
            sim.new_process("rk-probe"),
            _MasterStub(),
            [],  # empty seed: discovery must come from the registry
            sim.knobs,
            "probe",
            cc_address=cc_addr,
            n_proxies=1,
        )
        sig = await rk._poll_signals()
        assert sig is not None
        assert sig["storage_count"] == 2, sig
        assert sig["durability_lag"] is not None
        assert sig["tlog_queue_bytes"] is not None  # tlog metrics seen
        assert sig["kernel_state"] is not None  # resolver kernel health
        return True

    assert run(sim, body())


# -- end-to-end overload -------------------------------------------------------


def test_overload_sheds_and_does_not_collapse():
    """Scaled-down overload acceptance: offered load far above a tiny
    pinned capacity. The cluster sheds (grv_throttled observed at the
    clients and counted in qos), admitted traffic keeps committing, and
    the immediate-class latency probe keeps measuring (zero errors after
    overload starts)."""
    sim, cluster, db = make(
        seed=11,
        # tiny capacity so a handful of actors is a real overload
        knob_overrides=dict(RK_MAX_TPS=60.0, RK_GRV_QUEUE_TIMEOUT=0.2),
        n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=1,
    )

    stats = {"commits": 0, "sheds": 0}

    async def flood(i, priority, tenant):
        from foundationdb_tpu.errors import FdbError

        for j in range(12):
            async def body(tr, i=i, j=j):
                tr.set_priority(priority)
                tr.set_tenant(tenant)
                await tr.get(b"ov/%d/%d" % (i, j))
                tr.set(b"ov/%d/%d" % (i, j), b"x")

            try:
                await db.run(body, max_retries=3)
            except (FdbError, BrokenPromise):
                stats["sheds"] += 1
            else:
                stats["commits"] += 1

    async def body():
        await delay(2.0)  # let the first rate grant land (gating on)
        floods = [
            spawn(flood(i, "batch" if i % 2 else "default", f"t{i % 2}"))
            for i in range(8)
        ]
        await wait_for_all(floods)
        await delay(1.5)
        from foundationdb_tpu.client import management

        doc = await management.get_status(cluster.coordinators, db.client)
        qos = doc["qos"]
        # shed, not collapsed: commits landed AND throttles were counted
        assert stats["commits"] > 0, stats
        assert qos["throttled_total"] > 0, (stats, qos)
        # shed order: batch sheds at least as much as default
        tpc = qos["throttled_per_class"]
        assert tpc["batch"] >= tpc["default"], tpc
        assert tpc["immediate"] == 0, tpc
        # the probe (immediate class) kept measuring through the overload
        probe = doc["latency_probe"]
        assert probe.get("grv_seconds") is not None
        assert probe["probes_completed"] > 0
        return True

    assert run(sim, body())
