"""Bindingtester-style API conformance (bindingtester.py analog).

Seeded stack-machine instruction streams run against BOTH the real
client (on a simulated cluster, instructions stored in the database per
the spec) and the serial-MVCC model oracle; the logged stacks and final
data states must match item for item. A chaos tier re-runs streams under
buggify + clogging and checks the machine survives with a consistent
final state.
"""

import pytest

from foundationdb_tpu.bindings import ModelDatabase, StackMachine
from foundationdb_tpu.bindings.generator import StreamGenerator, store_instructions
from foundationdb_tpu.client import Database
from foundationdb_tpu.layers import tuple as T
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.server import Cluster, ClusterConfig

INS_PREFIX = b"bt/i"
DATA_PREFIX = b"bt/d/"
RESULT_PREFIX = b"bt/r/"


def run_real(seed, n_ops, chaos=False, knobs=None, **cfg):
    sim = Sim(seed=seed, chaos=chaos, knobs=knobs)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(**cfg))
    db = Database(sim, cluster.proxy_addrs)
    gen = StreamGenerator(seed, data_prefix=DATA_PREFIX)
    stream = gen.generate(n_ops, result_prefix=RESULT_PREFIX, machine_prefix=INS_PREFIX)

    async def go():
        await store_instructions(db, INS_PREFIX, stream)
        machine = StackMachine(db, INS_PREFIX)
        await machine.run_from_db()

        async def read_all(tr):
            data = await tr.get_range(DATA_PREFIX, DATA_PREFIX + b"\xff")
            log = await tr.get_range(RESULT_PREFIX, RESULT_PREFIX + b"\xff")
            return data, log

        return await db.run(read_all)

    return stream, sim.run_until_done(spawn(go()), 3600.0)


def run_model(stream):
    """The oracle side: same machine, model database. The instruction
    rows are stored in the model too (as the spec stores them in the real
    database): selector walks navigate the WHOLE keyspace, so both sides
    must hold identical key sets for resolution parity."""
    from foundationdb_tpu.net.sim import Sim

    sim = Sim(seed=0)  # an event loop for the async surface
    sim.activate()
    db = ModelDatabase()

    async def go():
        await store_instructions(db, INS_PREFIX, stream)
        machine = StackMachine(db, INS_PREFIX)
        await machine.run_stream(stream)
        data = sorted(
            (k, v) for k, v in db.data.items() if k.startswith(DATA_PREFIX)
        )
        log = sorted(
            (k, v) for k, v in db.data.items() if k.startswith(RESULT_PREFIX)
        )
        return data, log

    return sim.run_until_done(spawn(go()), 3600.0)


@pytest.mark.parametrize("seed", range(50))
def test_conformance_seeded_streams(seed):
    stream, (data_real, log_real) = run_real(seed, 1000)
    data_model, log_model = run_model(stream)
    assert list(data_real) == list(data_model), (
        f"seed {seed}: final data diverged "
        f"(real {len(data_real)} rows, model {len(data_model)})"
    )
    assert list(log_real) == list(log_model), (
        f"seed {seed}: logged stacks diverged "
        f"(real {len(log_real)} items, model {len(log_model)})"
    )


def test_conformance_long_stream():
    """One 1K-op stream, multi-proxy multi-resolver cluster."""
    stream, (data_real, log_real) = run_real(
        99, 1000, n_proxies=2, n_resolvers=2
    )
    data_model, log_model = run_model(stream)
    assert list(data_real) == list(data_model)
    assert list(log_real) == list(log_model)


def test_conformance_commit_path_knobs_both_ways():
    """ISSUE 18 acceptance: the commit-path fast paths (compiled wire
    codec, slab-settled futures, pipelined tlog fsync) are pure perf —
    the same seeded stream must yield byte-identical final data and stack
    logs with all three knobs forced on and forced off, and both must
    match the model oracle."""
    from foundationdb_tpu.net import wire
    from foundationdb_tpu.runtime import futures as rt_futures
    from foundationdb_tpu.runtime.knobs import Knobs

    results = {}
    for legacy in (False, True):
        knobs = Knobs()
        knobs.WIRE_COMPILED_CODEC = not legacy
        knobs.FUTURE_SLAB_SETTLE = not legacy
        knobs.TLOG_FSYNC_PIPELINE = not legacy
        # sim clusters read TLOG_FSYNC_PIPELINE off sim.knobs; the codec
        # and settle paths are process-global toggles
        wire.set_compiled_codec(not legacy)
        rt_futures.set_slab_settle(not legacy)
        try:
            stream, (data, log) = run_real(7, 600, knobs=knobs)
        finally:
            wire.set_compiled_codec(True)
            rt_futures.set_slab_settle(True)
        results[legacy] = (stream, list(data), list(log))
    assert results[False][1] == results[True][1], (
        "final data diverged between fast and legacy commit paths"
    )
    assert results[False][2] == results[True][2], (
        "stack logs diverged between fast and legacy commit paths"
    )
    data_model, log_model = run_model(results[False][0])
    assert results[False][1] == list(data_model)
    assert results[False][2] == list(log_model)


def test_error_tuples_surface_conflicts():
    """A forced conflict between two named transactions must surface as
    the packed ('ERROR', '1020') tuple on BOTH sides at the same stream
    position."""
    stream = [
        ("NEW_TRANSACTION",),
        # tr A (default name) reads k
        ("PUSH", DATA_PREFIX + b"k"),
        ("GET",),
        ("POP",),
        # tr B writes k and commits
        ("PUSH", b"trB"),
        ("USE_TRANSACTION",),
        ("PUSH", b"vB"),
        ("PUSH", DATA_PREFIX + b"k"),
        ("SET",),
        ("COMMIT",),
        ("POP",),
        # back to A: write + commit must conflict
        ("PUSH", INS_PREFIX),
        ("USE_TRANSACTION",),
        ("PUSH", b"vA"),
        ("PUSH", DATA_PREFIX + b"k"),
        ("SET",),
        ("COMMIT",),
        ("PUSH", RESULT_PREFIX),
        ("LOG_STACK",),
    ]

    sim = Sim(seed=7)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig())
    db = Database(sim, cluster.proxy_addrs)

    async def go():
        machine = StackMachine(db, INS_PREFIX)
        await machine.run_stream(stream)

        async def read_log(tr):
            return await tr.get_range(RESULT_PREFIX, RESULT_PREFIX + b"\xff")

        return await db.run(read_log)

    log_real = sim.run_until_done(spawn(go()), 600.0)
    data_model, log_model = run_model(stream)
    assert [v for _k, v in log_real] == [v for _k, v in log_model]
    # the last logged item is the conflict error tuple
    assert T.unpack(T.unpack(log_real[-1][1])[0]) == (b"ERROR", b"1020")


def test_selector_ops_in_generated_streams():
    """The generator actually emits the selector ops (the conformance
    seeds above only prove what the streams contain)."""
    ops = set()
    for seed in range(10):
        gen = StreamGenerator(seed, data_prefix=DATA_PREFIX)
        for ins in gen.generate(1000):
            op = ins[0]
            ops.add(op.removesuffix("_SNAPSHOT").removesuffix("_DATABASE"))
    assert {"GET_KEY", "GET_RANGE_SELECTOR", "GET_RANGE_STARTS_WITH"} <= ops


def test_directed_selector_stream():
    """A hand-written stream of GET_KEY / GET_RANGE_SELECTOR edge cases —
    or_equal variants, negative offsets, walks off both keyspace ends
    (prefix-window clamps), inverted selector ranges — must match the
    model oracle item for item."""
    k = lambda i: DATA_PREFIX + b"%03d" % i  # noqa: E731
    stream = [("NEW_TRANSACTION",)]
    for i in (2, 5, 9):
        stream += [("PUSH", b"v%d" % i), ("PUSH", k(i)), ("SET",)]
    stream += [("COMMIT",), ("NEW_TRANSACTION",)]
    # every constructor shape around existing, missing, and edge keys
    for anchor in (k(0), k(2), k(4), k(5), k(9), k(10)):
        for or_equal in (0, 1):
            for offset in (-3, -1, 0, 1, 2, 30):
                stream += [
                    ("PUSH", DATA_PREFIX),
                    ("PUSH", offset),
                    ("PUSH", or_equal),
                    ("PUSH", anchor),
                    ("GET_KEY",),
                ]
    # selector ranges: forward, reverse+limit, inverted (empty)
    for b_off, e_off, limit, reverse in (
        (0, 1, 0, 0), (1, 3, 2, 0), (-2, 2, 0, 1), (2, -2, 0, 0)
    ):
        stream += [
            ("PUSH", DATA_PREFIX),
            ("PUSH", 0),  # STREAMING_MODE
            ("PUSH", reverse),
            ("PUSH", limit),
            ("PUSH", e_off),
            ("PUSH", 1),
            ("PUSH", k(9)),
            ("PUSH", b_off),
            ("PUSH", 0),
            ("PUSH", k(2)),
            ("GET_RANGE_SELECTOR",),
        ]
    # starts-with routes through selector endpoints
    stream += [
        ("PUSH", 0),
        ("PUSH", 0),
        ("PUSH", 0),
        ("PUSH", DATA_PREFIX),
        ("GET_RANGE_STARTS_WITH",),
        ("COMMIT",),
        ("PUSH", RESULT_PREFIX),
        ("LOG_STACK",),
    ]

    sim = Sim(seed=23)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(n_storage=4, replication=2))
    db = Database(sim, cluster.proxy_addrs)

    async def go():
        await store_instructions(db, INS_PREFIX, stream)
        machine = StackMachine(db, INS_PREFIX)
        await machine.run_stream(stream)

        async def read_log(tr):
            return await tr.get_range(RESULT_PREFIX, RESULT_PREFIX + b"\xff")

        return await db.run(read_log)

    log_real = sim.run_until_done(spawn(go()), 600.0)
    _data_model, log_model = run_model(stream)
    assert [v for _k, v in log_real] == [v for _k, v in log_model]
    assert len(log_real) > 70  # every GET_KEY/GET_RANGE pushed something


@pytest.mark.parametrize("seed", [3, 17, 29, 41])
def test_streams_survive_chaos(seed):
    """Under buggify, the machine must complete and the final state must
    be readable and well-formed (per-instruction parity is not required —
    chaos errors are environmental, as in the reference's chaos runs)."""
    stream, (data_real, log_real) = run_real(seed, 250, chaos=True)
    for k, v in data_real:
        assert k.startswith(DATA_PREFIX)
        assert isinstance(v, bytes)
