"""Conflict-kernel fault tolerance (ISSUE 10): deadline-guarded dispatch,
journaled failover to the native/oracle backend, device-fault injection in
sim, and warm compile at backend construction.

The acceptance battery: commit availability recovers after injected device
loss (bounded stall, never a permanent `resolver backend failed`),
journal-replay failover shows verdict parity with a zero-false-commit
oracle (extra conservative aborts allowed), the
HEALTHY→FAILED_OVER→HEALTHY round trip is visible in resolver.metrics →
kernel.health, the status document, and `cli status` — all same-seed
reproducible — and the smoke-shape warm compile makes the first real
dispatch a jit-cache hit with no SlowTask on the real loop.
"""

from foundationdb_tpu.conflict.api import CommitTransaction, Verdict
from foundationdb_tpu.conflict.failover import (
    FAILED_OVER,
    HEALTHY,
    WriteRangeJournal,
)
from foundationdb_tpu.conflict.faults import (
    KERNEL_FAULT_SITES,
    KernelFaultInjector,
    KernelTransientError,
)
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.rng import DeterministicRandom
from foundationdb_tpu.server.interfaces import (
    ResolveBatchRequest,
    TransactionData,
)
from foundationdb_tpu.server.resolver import Resolver


def _req(prev, version, txns):
    return ResolveBatchRequest(
        version=version,
        prev_version=prev,
        transactions=[
            TransactionData(
                read_snapshot=s,
                read_conflict_ranges=list(r),
                write_conflict_ranges=list(w),
                mutations=[],
            )
            for (s, r, w) in txns
        ],
        last_receive_version=0,
        requesting_proxy="px",
    )


class _FalseCommitOracle:
    """Zero-false-commit referee: applies exactly the writes the resolver
    COMMITTED (blind writes always commit), and for each claimed commit
    probes its read set against that history — any overlap with a
    committed write above the snapshot is a false commit. Conservative
    aborts (the resolver refusing what the referee would allow) pass."""

    def __init__(self):
        self.cs = OracleConflictSet()

    def check_batch(self, txns, verdicts, version):
        for t, v in zip(txns, verdicts):
            committed = int(v) == int(Verdict.COMMITTED)
            if committed and t.read_conflict_ranges:
                probe = self.cs.detect_batch(
                    [
                        CommitTransaction(
                            read_snapshot=t.read_snapshot,
                            read_conflict_ranges=list(t.read_conflict_ranges),
                        )
                    ],
                    now=version,
                    new_oldest_version=0,
                )
                assert probe[0] == Verdict.COMMITTED, (
                    f"FALSE COMMIT: txn snap={t.read_snapshot} "
                    f"reads={t.read_conflict_ranges} admitted at v{version} "
                    f"over a newer committed write"
                )
            if committed and t.write_conflict_ranges:
                self.cs.detect_batch(
                    [
                        CommitTransaction(
                            write_conflict_ranges=list(t.write_conflict_ranges)
                        )
                    ],
                    now=version,
                    new_oldest_version=0,
                )


# ---------------------------------------------------------------------------
# Journal + injector units


def test_journal_replay_reconstructs_history():
    j = WriteRangeJournal(capacity=100)
    j.record(10, [(b"a", b"b")])
    j.record(20, [(b"c", b"d")])
    cs = OracleConflictSet()
    j.replay_into(cs)
    # a read of a-b at snapshot 5 conflicts (write at 10); at 15 it's clean
    old = cs.detect_batch(
        [CommitTransaction(read_snapshot=5, read_conflict_ranges=[(b"a", b"b")])],
        now=30, new_oldest_version=0,
    )
    new = cs.detect_batch(
        [CommitTransaction(read_snapshot=15, read_conflict_ranges=[(b"a", b"b")])],
        now=31, new_oldest_version=0,
    )
    assert old == [Verdict.CONFLICT] and new == [Verdict.COMMITTED]


def test_journal_capacity_floor_is_conservative_only():
    """Trimmed history raises the floor: replay makes pre-floor snapshots
    TOO_OLD (conservative abort), never silently-clean (false commit)."""
    j = WriteRangeJournal(capacity=2)
    j.record(10, [(b"a", b"b")])
    j.record(20, [(b"c", b"d")])
    j.record(30, [(b"e", b"f")])  # evicts v10 → floor 11
    assert j.floor == 11 and len(j) == 2
    cs = OracleConflictSet()
    j.replay_into(cs)
    probe = cs.detect_batch(
        [CommitTransaction(read_snapshot=5, read_conflict_ranges=[(b"a", b"b")])],
        now=40, new_oldest_version=0,
    )
    assert probe == [Verdict.TOO_OLD]
    # MVCC-window trim behaves the same way
    j.trim_below(25)
    assert j.floor == 25 and len(j) == 1


def test_injector_same_seed_same_fault_sequence():
    sim = Sim(seed=5)
    sim.activate()

    def roll(seed):
        inj = KernelFaultInjector(
            DeterministicRandom(seed),
            p_dispatch_error=0.3, p_device_loss=0.0,
            p_hang=0.2, p_compile_stall=0.2,
        )
        out = []
        for _ in range(40):
            try:
                inj.on_dispatch()
                out.append(inj.take_stall())
            except KernelTransientError:
                out.append("err")
        return out, dict(inj.counts)

    a = roll(123)
    b = roll(123)
    c = roll(321)
    assert a == b
    assert a != c  # the seed actually drives the sequence
    assert set(t for (_f, t) in KERNEL_FAULT_SITES) >= set(a[1])


# ---------------------------------------------------------------------------
# Resolver-level fault handling


def _resolver(sim, knobs=None, **inj_kw):
    p = sim.new_process("res", "res")
    inj = KernelFaultInjector(
        sim.loop.random.fork(),
        p_dispatch_error=0, p_device_loss=0, p_hang=0, p_compile_stall=0,
        p_encode_error=0, p_encode_hang=0,
        **inj_kw,
    )
    r = Resolver(
        knobs=knobs or Knobs(),
        backend="tpu1",
        first_version=0,
        uid="r0",
        fault_injector=inj,
    )
    r.register_instance(p)
    return r, inj


def test_transient_dispatch_error_retried_in_place():
    """A one-shot transient dispatch error is absorbed by the bounded
    retry (with backoff) — no recovery, no failover, health returns to
    HEALTHY after the clean batch completes."""
    sim = Sim(seed=11)
    sim.activate()
    r, inj = _resolver(sim)

    fire = {"n": 1}
    orig = inj.on_dispatch

    def once():
        if fire["n"]:
            fire["n"] -= 1
            raise KernelTransientError("injected transient dispatch error")
        orig()

    inj.on_dispatch = once

    async def go():
        rep = await r.resolve(_req(0, 10, [(0, [], [(b"a", b"b")])]))
        assert rep.committed == [0]
        h = r.cs.health_snapshot()
        assert h["state"] == HEALTHY
        assert h["retries"] == 1
        assert h["failovers"] == 0 and h["deviceRebuilds"] == 0
        return True

    assert sim.run_until_done(spawn(go()), 60.0)


def test_hang_hits_deadline_and_recovers():
    """An injected never-completing dispatch is bounded by
    CONFLICT_DISPATCH_DEADLINE (virtual time) and recovered — the batch
    still resolves; a finite compile stall rides under the deadline with
    no fault at all."""
    sim = Sim(seed=12)
    sim.activate()
    knobs = Knobs(CONFLICT_DISPATCH_DEADLINE=1.5)
    r, inj = _resolver(sim, knobs=knobs)

    async def go():
        from foundationdb_tpu.runtime.loop import now

        # finite stall: latency only
        inj._pending_stall = 0.3
        t0 = now()
        rep = await r.resolve(_req(0, 10, [(0, [], [(b"a", b"b")])]))
        assert rep.committed == [0]
        assert 0.3 <= now() - t0 < 1.5
        assert r.cs.health_snapshot()["deadlineHits"] == 0

        # hang: the deadline converts it into a recovery
        inj._pending_stall = float("inf")
        t0 = now()
        rep = await r.resolve(
            _req(10, 20, [(5, [(b"a", b"b")], [(b"a", b"b")])])
        )
        assert rep.committed == [1]  # conflict with the v10 write — not lost
        assert now() - t0 >= 1.5
        h = r.cs.health_snapshot()
        assert h["deadlineHits"] == 1
        assert h["faults"] >= 1
        return True

    assert sim.run_until_done(spawn(go()), 120.0)


def _loss_scenario(seed):
    """Device loss mid-stream: kill → failover → heal → re-promotion,
    refereed for false commits. Returns (verdict log, health snapshot)."""
    sim = Sim(seed=seed)
    sim.activate()
    knobs = Knobs(
        CONFLICT_FAILOVER_STRIKES=2, CONFLICT_REPROBE_INTERVAL=0.5
    )
    r, inj = _resolver(sim, knobs=knobs, loss_duration=3.0)
    referee = _FalseCommitOracle()
    log = []

    async def go():
        async def batch(prev, ver, txns):
            rep = await r.resolve(_req(prev, ver, txns))
            referee.check_batch(
                _req(prev, ver, txns).transactions, rep.committed, ver
            )
            log.append((ver, list(rep.committed), r.cs.health))
            return rep

        await batch(0, 10, [(0, [], [(b"a", b"b")])])
        assert r.cs.health == HEALTHY
        inj.lose_device(3.0)
        # contended stream across the loss: reads must keep conflicting
        # against journaled writes, never falsely commit
        await batch(10, 20, [(5, [(b"a", b"b")], [(b"a", b"b")])])
        await batch(20, 30, [(15, [(b"a", b"b")], [(b"a", b"b")])])
        await batch(30, 40, [(25, [(b"c", b"d")], [(b"c", b"d")])])
        assert r.cs.health == FAILED_OVER
        await delay(4.0)  # loss heals; reprobe window passes
        await batch(40, 50, [(45, [(b"a", b"b")], [(b"e", b"f")])])
        assert r.cs.health == HEALTHY
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    return log, r.cs.health_snapshot()


def test_device_loss_failover_promotion_round_trip_zero_false_commits():
    log, health = _loss_scenario(seed=42)
    # availability: every batch resolved (no permanent backend-failed)
    assert [v for v, _c, _h in log] == [10, 20, 30, 40, 50]
    # the round trip is visible in the health machine
    assert health["state"] == HEALTHY
    assert health["failovers"] == 1
    assert health["promotions"] == 1
    assert health["reprobes"] >= 1
    assert health["journalReplays"] >= 2  # failover replay + probe replay
    # verdict semantics across the failover: v20 conflicts (write@10 over
    # snap 5, journaled and replayed onto the fallback); v30 commits (v20's
    # write was ABORTED — an eager failover must not conflate it); v40 and
    # the post-promotion v50 commit cleanly
    assert [c for _v, c, _h in log[1:]] == [[1], [0], [0], [0]]


def test_loss_scenario_is_same_seed_reproducible():
    a = _loss_scenario(seed=43)
    b = _loss_scenario(seed=43)
    assert a == b


# ---------------------------------------------------------------------------
# Double-buffered pipeline faults: the encode executor and the window
# between overlapped dispatches (ISSUE 11)


def test_encode_executor_fault_retried_in_place():
    """A one-shot transient error INSIDE the encode executor (the
    double-buffered pipeline encodes off the dispatch path) is absorbed by
    the bounded retry: the batch re-encodes and resolves, no failover."""
    sim = Sim(seed=31)
    sim.activate()
    r, inj = _resolver(sim)

    fire = {"n": 1}

    def once():
        if fire["n"]:
            fire["n"] -= 1
            raise KernelTransientError("injected encode-executor error")

    inj.on_encode = once

    async def go():
        rep = await r.resolve(_req(0, 10, [(0, [], [(b"a", b"b")])]))
        assert rep.committed == [0]
        h = r.cs.health_snapshot()
        assert h["state"] == HEALTHY
        assert h["retries"] == 1
        assert h["failovers"] == 0 and h["deviceRebuilds"] == 0
        # the overlap evidence rode the metrics seam
        k = r.stats.snapshot()["kernel"]
        assert k["encodeOverlapSeconds"]["count"] >= 1
        assert k["encodeQueueDepth"] == 0
        return True

    assert sim.run_until_done(spawn(go()), 60.0)


def test_encode_hang_hits_deadline_and_recovers():
    """A wedged encode thread (injected hang armed by on_encode) is
    bounded by CONFLICT_DISPATCH_DEADLINE and converted into a journal-
    replay recovery — verdicts stay correct, zero false commits."""
    sim = Sim(seed=32)
    sim.activate()
    knobs = Knobs(CONFLICT_DISPATCH_DEADLINE=1.5)
    r, inj = _resolver(sim, knobs=knobs)
    referee = _FalseCommitOracle()

    async def go():
        from foundationdb_tpu.runtime.loop import now

        req1 = _req(0, 10, [(0, [], [(b"a", b"b")])])
        rep = await r.resolve(req1)
        referee.check_batch(req1.transactions, rep.committed, 10)
        assert rep.committed == [0]

        fire = {"n": 1}

        def once():
            if fire["n"]:
                fire["n"] -= 1
                inj._pending_stall = float("inf")

        inj.on_encode = once
        t0 = now()
        req2 = _req(10, 20, [(5, [(b"a", b"b")], [(b"a", b"b")])])
        rep = await r.resolve(req2)
        referee.check_batch(req2.transactions, rep.committed, 20)
        # conflict with the journaled v10 write — recovered, not lost
        assert rep.committed == [1]
        assert now() - t0 >= 1.5
        h = r.cs.health_snapshot()
        assert h["deadlineHits"] == 1
        assert h["faults"] >= 1
        return True

    assert sim.run_until_done(spawn(go()), 120.0)


def test_device_loss_mid_overlap_zero_false_commits():
    """Device loss in the overlap window: batch N-1's scan is in flight
    and batch N is double-buffered behind it when the device dies on N's
    dispatch. Journal-replay failover must resolve BOTH batches with zero
    false commits and both gates advancing (no wedged version chain)."""
    sim = Sim(seed=33)
    sim.activate()
    knobs = Knobs(CONFLICT_FAILOVER_STRIKES=2)
    r, inj = _resolver(sim, knobs=knobs, loss_duration=30.0)
    referee = _FalseCommitOracle()

    dispatches = {"n": 0}
    orig = inj.on_dispatch

    def lose_on_second(*a):
        dispatches["n"] += 1
        if dispatches["n"] == 2:
            inj.lose_device()
        orig()

    inj.on_dispatch = lose_on_second

    async def go():
        req1 = _req(0, 10, [(0, [], [(b"a", b"b")])])
        req2 = _req(10, 20, [(5, [(b"a", b"b")], [(b"c", b"d")])])
        f1 = spawn(r.resolve(req1))
        f2 = spawn(r.resolve(req2))
        rep1 = await f1
        rep2 = await f2
        referee.check_batch(req1.transactions, rep1.committed, 10)
        referee.check_batch(req2.transactions, rep2.committed, 20)
        assert rep1.committed == [0]
        # read a-b at snap 5 over the v10 committed write: CONFLICT, on
        # whichever backend ended up resolving it
        assert rep2.committed == [1]
        h = r.cs.health_snapshot()
        assert h["faults"] >= 1
        assert r.cs.health == FAILED_OVER
        # the chain kept moving: a third batch resolves on the fallback
        req3 = _req(20, 30, [(15, [(b"c", b"d")], [(b"e", b"f")])])
        rep3 = await r.resolve(req3)
        referee.check_batch(req3.transactions, rep3.committed, 30)
        assert rep3.committed == [0]
        return True

    assert sim.run_until_done(spawn(go()), 300.0)


def test_cluster_failover_round_trip_in_status_and_cli():
    """A full sim cluster on the tpu backend: force a device loss on the
    recruited resolver — commits keep succeeding through failover, the
    HEALTHY→FAILED_OVER→HEALTHY round trip shows up in resolver.metrics →
    kernel.health, the status document's kernel roll-up, and
    `cli status`."""
    from foundationdb_tpu.client import management
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
    from foundationdb_tpu.tools.cli import FdbCli

    sim = Sim(seed=71)
    sim.activate()
    sim.knobs.CONFLICT_FAULT_INJECTION = True
    sim.knobs.CONFLICT_FAILOVER_STRIKES = 2
    sim.knobs.CONFLICT_REPROBE_INTERVAL = 0.5
    cluster = DynamicCluster(
        sim,
        ClusterConfig(
            n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=1,
            conflict_backend="tpu1",
        ),
        n_coordinators=1,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    cli = FdbCli(db, cluster.coordinators)

    def resolvers():
        out = []
        for p in sim.processes.values():
            w = getattr(p, "worker", None)
            if w is None or not p.alive:
                continue
            out += [h.obj for h in w.roles.values() if h.kind == "resolver"]
        return out

    async def go():
        async def put(tr, k, v):
            tr.set(k, v)

        for i in range(5):
            await db.run(lambda tr, i=i: put(tr, b"k%02d" % i, b"v"))
        (res,) = resolvers()
        assert res.cs.health == HEALTHY
        assert res.cs._injector is not None  # knob armed the injector
        res.cs._injector.lose_device(2.0)
        # commits ride the failover (maybe as retried conflicts, never a
        # permanent backend-failed wedge)
        for i in range(5):
            await db.run(lambda tr, i=i: put(tr, b"f%02d" % i, b"v"))
        assert res.cs.health == FAILED_OVER
        mid = await management.get_status(cluster.coordinators, db.client)
        await delay(3.0)  # loss heals; reprobe passes
        for i in range(5):
            await db.run(lambda tr, i=i: put(tr, b"h%02d" % i, b"v"))
        assert res.cs.health == HEALTHY
        doc = await management.get_status(cluster.coordinators, db.client)
        shown = await cli.execute("status")
        details = await cli.execute("status details")
        return res, mid, doc, shown, details

    res, mid, doc, shown, details = sim.run_until_done(spawn(go()), 600.0)

    # resolver.metrics → kernel.health carries the machine's counters
    h = res.stats.snapshot()["kernel"]["health"]
    assert h["state"] == HEALTHY
    assert h["failovers"] >= 1 and h["promotions"] >= 1

    # status document: per-resolver kernel.health + top-level roll-up
    mid_k = mid["kernel"]
    assert mid_k["state"] == FAILED_OVER and mid_k["failovers"] >= 1
    (rsnap,) = doc["resolvers"].values()
    assert rsnap["kernel"]["health"]["state"] == HEALTHY
    assert doc["kernel"]["state"] == HEALTHY
    assert doc["kernel"]["promotions"] >= 1

    # cli status prints the roll-up and per-resolver health
    assert "Conflict kernel: HEALTHY" in shown
    assert "failovers" in shown
    assert "health: HEALTHY on TpuConflictSet" in details


# ---------------------------------------------------------------------------
# Warm compile (satellite): first real dispatch must be a jit-cache hit


def test_warm_compile_makes_first_dispatch_a_jit_hit():
    sim = Sim(seed=13)
    sim.activate()
    p = sim.new_process("res", "res")
    r = Resolver(backend="tpu1", first_version=0, uid="r0")
    r.register_instance(p)

    async def go():
        k0 = r.stats.snapshot()["kernel"]
        assert k0["warmCompiles"] == 1  # compiled at construction
        assert k0["deviceDispatches"] == 0  # …without touching live state
        # warm compiles seed the shape cache without counting dispatch-path
        # misses (hit/miss tallies measure what the LIVE pipeline paid)
        assert k0["jitCacheMisses"] == 0 and k0["jitCacheHits"] == 0
        await r.resolve(_req(0, 10, [(0, [(b"a", b"b")], [(b"a", b"b")])]))
        k1 = r.stats.snapshot()["kernel"]
        # the smoke-shape program was pre-compiled: the first REAL commit
        # batch hits the jit cache instead of paying the first compile
        assert k1["jitCacheHits"] >= 1
        assert k1["jitCacheMisses"] == 0
        return True

    assert sim.run_until_done(spawn(go()), 60.0)


def test_warm_compile_no_slowtask_on_first_resolve_real_loop():
    """On the real personality the warm compile runs on the resolver's
    device thread, so neither construction nor the first resolve blocks
    the run loop past RUN_LOOP_SLOW_TASK_MS (the PR 9 profiler evidence
    this satellite answers)."""
    from foundationdb_tpu.runtime import profiler as profiler_mod
    from foundationdb_tpu.runtime.loop import RealLoop, set_loop
    from foundationdb_tpu.runtime.trace import TraceLog, set_trace_log

    log = TraceLog()
    set_trace_log(log)
    loop = RealLoop(seed=19)
    set_loop(loop)
    knobs = Knobs(RUN_LOOP_SLOW_TASK_MS=50.0)
    profiler_mod.install(loop, knobs=knobs, wall=True, ident="127.0.0.1:9")
    try:
        r = Resolver(knobs=knobs, backend="tpu1", first_version=0, uid="r0")

        async def go():
            rep = await r.resolve(
                _req(0, 10, [(0, [(b"a", b"b")], [(b"a", b"b")])])
            )
            return rep.committed

        fut = spawn(go())
        loop.run(stop_when=fut.is_ready)
        assert fut.get() == [0]
        slow = [
            e for e in log.events
            if e["Type"] == "SlowTask" and "esolve" in str(e.get("Actor", ""))
        ]
        assert slow == [], f"first resolve blocked the loop: {slow}"
    finally:
        r.close()
        set_loop(None)
        loop.close()
        set_trace_log(TraceLog())


# ---------------------------------------------------------------------------
# Jit-cache steady state (satellite): after warm_compile, a mixed run over
# smoke + reshard + grow shapes stays hit-rate ≈ 1.0 with no compile-
# attributed SlowTask on the real loop


def test_jit_cache_steady_state_mixed_shapes_real_loop():
    """Drive enough distinct keys through a tiny-capacity device backend
    that the grid reshards AND grows mid-run. Warm compile seeds the smoke
    shape; every grid-shape change re-warms the recently dispatched
    stacked shapes — so the live dispatch path never pays a compile:
    jitCacheMisses stays 0 (hit rate exactly 1.0 over all dispatches) and
    no SlowTask lands on the resolver band."""
    import random

    from foundationdb_tpu.runtime import profiler as profiler_mod
    from foundationdb_tpu.runtime.loop import RealLoop, set_loop
    from foundationdb_tpu.runtime.trace import TraceLog, set_trace_log

    log = TraceLog()
    set_trace_log(log)
    loop = RealLoop(seed=37)
    set_loop(loop)
    knobs = Knobs(
        RUN_LOOP_SLOW_TASK_MS=50.0,
        CONFLICT_DISPATCH_DEADLINE=60.0,  # CPU compiles must not trip it
    )
    profiler_mod.install(loop, knobs=knobs, wall=True, ident="127.0.0.1:9")
    rnd = random.Random(5)
    try:
        r = Resolver(
            knobs=knobs, backend="tpu1", first_version=0, uid="r0",
            capacity=16,  # tiny: distinct-key traffic must reshard + grow
        )

        async def go():
            prev = 0
            for i in range(40):
                ver = prev + 10
                txns = []
                for _ in range(8):
                    a = b"%06d" % rnd.randrange(100000)
                    w = b"%06d" % rnd.randrange(100000)
                    txns.append(
                        (
                            max(0, ver - 20),
                            [(a, a + b"\xff")],
                            [(w, w + b"\xff")],
                        )
                    )
                await r.resolve(_req(prev, ver, txns))
                prev = ver
            return True

        fut = spawn(go())
        loop.run(stop_when=fut.is_ready)
        assert fut.get() is True
        k = r.stats.snapshot()["kernel"]
        # the run genuinely exercised reshard + grow shapes
        assert k["reshardsDevice"] + k["reshardsHost"] >= 1
        assert k["capacityGrowths"] >= 1, k
        # steady state: every live dispatch hit the jit cache
        assert k["jitCacheMisses"] == 0, k
        assert k["jitCacheHits"] == k["deviceDispatches"] >= 40
        assert k["warmCompiles"] >= 2  # construction + post-grow re-warms
        slow = [
            e for e in log.events
            if e["Type"] == "SlowTask" and "esolve" in str(e.get("Actor", ""))
        ]
        assert slow == [], f"compile leaked onto the run loop: {slow}"
    finally:
        r.close()
        set_loop(None)
        loop.close()
        set_trace_log(TraceLog())


# ---------------------------------------------------------------------------
# Chaos combination (satellite): attrition + clogging + kernel faults


def test_kernel_chaos_with_attrition_and_clogging():
    """The full chaos composition against a tpu-backed sim cluster with
    device-fault injection: process kills + network clogging + kernel
    kill/heal/failover cycles, oracle-checked for zero false commits
    (KernelChaosWorkload's exact ledger + ConsistencyCheck)."""
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
    from foundationdb_tpu.workloads import (
        AttritionWorkload,
        ConsistencyCheckWorkload,
        KernelChaosWorkload,
        RandomCloggingWorkload,
        run_workloads,
    )

    sim = Sim(seed=23, chaos=True)
    sim.activate()
    sim.knobs.CONFLICT_FAULT_INJECTION = True
    cluster = DynamicCluster(
        sim,
        ClusterConfig(
            n_proxies=1, n_resolvers=1, n_tlogs=2, n_storage=2,
            replication=2, conflict_backend="tpu1",
        ),
        n_coordinators=1,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    rng = sim.loop.random
    chaos = KernelChaosWorkload(db, rng.fork(), actors=2, increments=5)
    workloads = [
        chaos,
        RandomCloggingWorkload(db, rng.fork(), duration=3.0),
        AttritionWorkload(
            db, rng.fork(), sim=sim, kills=1, interval=3.0,
            protect=set(cluster.coordinators),
        ),
        ConsistencyCheckWorkload(db, rng.fork(), replication=2),
    ]
    sim.run_until_done(spawn(run_workloads(workloads)), 1200.0)
    # the ledger saw real adversity, not a quiet run
    assert chaos.tally and sum(chaos.tally.values()) == 2 * 5
