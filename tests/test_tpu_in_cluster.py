"""The TPU conflict backend running INSIDE the database (CPU twin under
sim): resolvers built with conflict_backend="tpu1" resolve real commit
batches through the proxy pipeline, pipelined via the encoded/async path,
with verdict behavior identical to the oracle-backed cluster — including
across a recovery (fresh ConflictSet at the recovery version)."""

import pytest

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.errors import NotCommitted
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import spawn, wait_for_all
from foundationdb_tpu.server import Cluster, ClusterConfig
from foundationdb_tpu.server.cluster import DynamicCluster


def make_db(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(conflict_backend="tpu1", **cfg))
    db = Database(sim, cluster.proxy_addrs)
    return sim, cluster, db


def drive(sim, coro, limit=300.0):
    return sim.run_until_done(spawn(coro), limit)


def test_tpu_backend_resolves_commits():
    sim, cluster, db = make_db(seed=31)

    async def go():
        tr = db.transaction()
        tr.set(b"a", b"1")
        await tr.commit()

        # read-write conflict: t1 reads a, t2 writes a, t2 commits first
        t1 = db.transaction()
        await t1.get(b"a")
        t1.set(b"b", b"from-t1")
        t2 = db.transaction()
        t2.set(b"a", b"2")
        await t2.commit()
        with pytest.raises(NotCommitted):
            await t1.commit()

        # blind writes never conflict
        t3 = db.transaction()
        t3.set(b"a", b"3")
        await t3.commit()

        tr = db.transaction()
        assert await tr.get(b"a") == b"3"
        assert await tr.get(b"b") is None
        return True

    assert drive(sim, go())


def test_tpu_backend_concurrent_contention():
    """Many concurrent increment transactions on few keys: exactly the
    committed ones apply (lost-update safety end-to-end through the
    pipelined TPU resolver)."""
    sim, cluster, db = make_db(seed=32, n_proxies=2, n_resolvers=2)

    async def go():
        init = db.transaction()
        for k in (b"x", b"y"):
            init.set(k, b"0")
        await init.commit()

        async def incr(key):
            for _ in range(30):
                tr = db.transaction()
                try:
                    v = int(await tr.get(key))
                    tr.set(key, b"%d" % (v + 1))
                    await tr.commit()
                    return True
                except Exception as e:
                    await tr.on_error(e)
            return False

        oks = await wait_for_all(
            [spawn(incr(b"x")) for _ in range(8)]
            + [spawn(incr(b"y")) for _ in range(8)]
        )
        assert all(oks)
        tr = db.transaction()
        assert await tr.get(b"x") == b"8"
        assert await tr.get(b"y") == b"8"
        return True

    assert drive(sim, go())


def test_tpu_backend_survives_recovery():
    """Kill the master mid-run with TPU-backed resolvers: the new epoch's
    resolvers start a fresh device index at the recovery version; old
    snapshots turn TOO_OLD and retries converge."""
    sim = Sim(seed=33)
    sim.activate()
    cluster = DynamicCluster(
        sim,
        ClusterConfig(n_storage=2, n_resolvers=2, conflict_backend="tpu1"),
        n_coordinators=3,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def go():
        async def fill(tr):
            for i in range(10):
                tr.set(b"r%02d" % i, b"v%d" % i)

        await db.run(fill)

        master_addr = None
        for addr, p in sim.processes.items():
            w = getattr(p, "worker", None)
            if w is not None and p.alive and any(
                h.kind == "master" for h in w.roles.values()
            ):
                master_addr = addr
        assert master_addr
        sim.kill_process(master_addr)

        async def more(tr):
            tr.set(b"post-recovery", b"ok")

        await db.run(more)

        db2 = Database.from_coordinators(sim, cluster.coordinators, client_addr="c2")

        async def check(tr):
            vals = [await tr.get(b"r%02d" % i) for i in range(10)]
            vals.append(await tr.get(b"post-recovery"))
            return vals

        vals = await db2.run(check)
        assert vals == [b"v%d" % i for i in range(10)] + [b"ok"]
        return True

    assert drive(sim, go(), limit=600.0)


def test_resolver_backend_failure_fails_over_not_wedges():
    """A conflict-backend error mid-pipeline no longer poisons the
    resolver (the old permanent `_broken` path): every batch keeps
    resolving through journal-replay recovery, repeated strikes flip the
    health machine to FAILED_OVER onto the native/oracle fallback, and
    neither gate ever wedges (ADVICE r3: gate advance was skipped when
    handle() raised)."""
    from foundationdb_tpu.conflict.failover import FAILED, FAILED_OVER
    from foundationdb_tpu.server.interfaces import (
        ResolveBatchRequest,
        TransactionData,
    )
    from foundationdb_tpu.server.resolver import Resolver

    sim = Sim(seed=77)
    sim.activate()
    p = sim.new_process("res", "res")
    r = Resolver(backend="tpu1", first_version=0, uid="r0")
    r.register_instance(p)

    def req(prev, version):
        return ResolveBatchRequest(
            version=version,
            prev_version=prev,
            transactions=[
                TransactionData(
                    read_snapshot=0,
                    read_conflict_ranges=[(b"a", b"b")],
                    write_conflict_ranges=[(b"a", b"b")],
                    mutations=[],
                )
            ],
            last_receive_version=0,
            requesting_proxy="px",
        )

    async def go():
        ok = await r.resolve(req(0, 10))
        assert ok.committed

        # poison the device dispatch path: every later dispatch raises
        def boom(*a, **kw):
            raise RuntimeError("device gone")

        r.cs.detect_many_encoded_async = boom
        # batches keep resolving — recovery re-resolves each on a
        # journal-rebuilt backend, then strikes force a failover
        for prev, ver in ((10, 20), (20, 30), (30, 40), (40, 50)):
            rep = await r.resolve(req(prev, ver))
            assert rep.committed == [1], (prev, ver)  # conflict: a-b written at v10
        health = r.cs.health_snapshot()
        assert health["state"] == FAILED_OVER
        assert health["failovers"] == 1
        assert health["faults"] > 0
        # structured degraded state is in resolver.metrics → kernel.health
        assert r.stats.snapshot()["kernel"]["health"]["state"] == FAILED_OVER

        # terminal hard failure (kernel AND fallback gone) fails FAST and
        # typed, advancing both gates so the version chain never wedges
        r.cs.health = FAILED
        r.cs.last_error = "fallback gone too"
        for prev, ver in ((50, 60), (60, 70)):
            err = None
            try:
                await r.resolve(req(prev, ver))
            except Exception as e:
                err = e
            assert err is not None and "kernel failed" in str(err), (prev, ver)
        return True

    fut = spawn(go())
    sim.run_until_done(fut, 60.0)
