"""Backup/restore and DR.

The backup invariant (FileBackupAgent): capture-before-snapshot means
snapshot + mutation-log replay reproduces every acknowledged write,
including writes concurrent with the backup. DR: a second cluster in the
same simulation converges to the source's content through the same
mutation-log machinery applied cross-cluster.
"""

from foundationdb_tpu.backup import BackupAgent, BackupContainer, DrAgent
from foundationdb_tpu.backup.agent import restore
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.layers.subspace import Subspace
from foundationdb_tpu.layers.taskbucket import TaskBucket
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster


def make(seed=0, prefix="", client="client", **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = DynamicCluster(sim, ClusterConfig(**cfg), prefix=prefix)
    db = Database.from_coordinators(sim, cluster.coordinators, client_addr=client)
    return sim, cluster, db


def run(sim, coro, limit=600.0):
    return sim.run_until_done(spawn(coro), limit)


async def put(db, key, value):
    async def body(tr):
        tr.set(key, value)

    await db.run(body)


async def get_all(db, begin=b"", end=b"\xff"):
    async def body(tr):
        return await tr.get_range(begin, end)

    return await db.run(body)


def test_taskbucket():
    sim, cluster, db = make(seed=61, n_storage=1, n_tlogs=1)

    async def body():
        tb = TaskBucket(Subspace(("tb",)), lease=2.0)

        async def add(tr):
            await tb.add_task(tr, "work", n=1)
            await tb.add_task(tr, "work", n=2)

        await db.run(add)
        first = await tb.claim_one(db)
        second = await tb.claim_one(db)
        assert first and second
        assert {first[1]["params"]["n"], second[1]["params"]["n"]} == {1, 2}
        assert await tb.claim_one(db) is None
        await tb.finish(db, first[0])
        # unfinished claim re-queues after lease expiry
        await delay(2.5)
        again = await tb.claim_one(db)
        assert again is not None and again[1]["params"]["n"] == second[1]["params"]["n"]
        await tb.finish(db, again[0])
        assert await tb.is_empty(db)

    run(sim, body())


def test_backup_restore_roundtrip_with_concurrent_writes():
    sim, cluster, db = make(
        seed=62, n_proxies=2, n_tlogs=2, n_storage=2, replication=2,
        tlog_replication=2,
    )

    async def body():
        for i in range(40):
            await put(db, b"base%03d" % i, b"v%d" % i)

        container = BackupContainer(sim.disk("backup-store"), "b1")
        agent = BackupAgent(db, container, uid="b1")
        await agent.submit()

        # writes DURING the backup — must land via the mutation log
        for i in range(40, 60):
            await put(db, b"base%03d" % i, b"v%d" % i)

        async def extra(tr):
            tr.clear(b"base000")
            tr.set(b"base001", b"overwritten")

        await db.run(extra)

        await agent.wait_snapshot_complete()
        await agent.discontinue()

        source = await get_all(db)

        # restore into a clean range on the same cluster (clears first)
        n = await restore(db, container)
        assert n > 0
        restored = await get_all(db)
        assert restored == source
        assert (b"base000", b"v0") not in restored
        assert (b"base001", b"overwritten") in restored

    run(sim, body())


def test_dr_replicates_to_second_cluster():
    sim = Sim(seed=63)
    sim.activate()
    a = DynamicCluster(
        sim,
        ClusterConfig(n_proxies=1, n_tlogs=2, n_storage=2, replication=2,
                      tlog_replication=2),
        prefix="a-",
    )
    b = DynamicCluster(
        sim, ClusterConfig(n_proxies=1, n_tlogs=1, n_storage=1), prefix="b-"
    )
    db_a = Database.from_coordinators(sim, a.coordinators, client_addr="ca")
    db_b = Database.from_coordinators(sim, b.coordinators, client_addr="cb")

    async def body():
        for i in range(30):
            await put(db_a, b"k%03d" % i, b"v%d" % i)
        dr = DrAgent(db_a, db_b, uid="dr1")
        await dr.start()
        # concurrent writes replicate continuously
        for i in range(30, 50):
            await put(db_a, b"k%03d" % i, b"v%d" % i)

        async def mutate(tr):
            tr.clear(b"k000")
            tr.set(b"k001", b"changed")

        await db_a.run(mutate)
        await delay(3.0)  # let the apply loop drain
        await dr.stop()

        src = await get_all(db_a, b"k", b"l")
        dst = await get_all(db_b, b"k", b"l")
        assert dst == src
        assert (b"k001", b"changed") in dst

    run(sim, body())
