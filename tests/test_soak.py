"""A slice of the chaos soak in CI: randomized cluster shapes, randomized
knobs, armed BUGGIFY sites, clogging/attrition during Cycle+Sideband, and
a ConsistencyCheck after — each seed reproduces exactly
(python -m foundationdb_tpu.tools.soak runs wider sweeps)."""

import pytest

from foundationdb_tpu.tools.soak import run_one


@pytest.mark.parametrize("seed", [0, 1, 4, 7])
def test_soak_seed(seed):
    out = run_one(seed)
    assert out["seed"] == seed


def test_buggify_fires_under_chaos():
    """The chaos rig actually exercises buggify sites (they were built to
    be hit, not decorative)."""
    fired = 0
    for seed in (2, 3):
        fired += run_one(seed)["buggify_fired"]
    assert fired > 0
