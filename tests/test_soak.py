"""A slice of the chaos soak in CI: randomized cluster shapes, randomized
knobs, armed BUGGIFY sites, clogging/attrition during Cycle+Sideband, and
a ConsistencyCheck after — each seed reproduces exactly
(python -m foundationdb_tpu.tools.soak runs wider sweeps)."""

import pytest

from foundationdb_tpu.tools.soak import run_one


@pytest.mark.parametrize("seed", [0, 1, 4, 7])
def test_soak_seed(seed):
    out = run_one(seed)
    assert out["seed"] == seed


def test_buggify_fires_under_chaos():
    """The chaos rig actually exercises buggify sites (they were built to
    be hit, not decorative)."""
    fired = 0
    for seed in (2, 3):
        fired += run_one(seed)["buggify_fired"]
    assert fired > 0


def test_soak_chaos_composition_kernel_faults_plus_overload():
    """ISSUE 13 chaos composition: kernel fault injection AND the
    admission overload burst armed in one run — rates must adapt through
    kernel degradation/failover while batch/default traffic sheds, with
    zero false commits (the run's oracle-checked workloads gate that) and
    the kernel-fault buggify sites still reachable."""
    out = run_one(0, force_kernel_faults=True, force_overload=True)
    assert out["kernel_faults_armed"]
    assert out["overload_armed"]
    kernel = [s for s in out["buggify_sites"] if s.startswith("kernel-")]
    assert kernel, f"kernel-fault sites did not fire: {out['buggify_sites']}"


def test_soak_reports_fired_sites_and_kernel_faults_fire():
    """Buggify coverage report (ISSUE 10): the soak summary names every
    fired site, and under the pinned seed the kernel-fault-injection
    sites (conflict/faults.py) fire at least once — so the device-fault
    chaos surface can never silently rot out of the matrix."""
    out = run_one(0, force_kernel_faults=True)
    assert out["kernel_faults_armed"]
    sites = out["buggify_sites"]
    assert len(sites) == out["buggify_fired"]
    # code sites render as file:line, named sites keep their tag
    assert any(":" in s for s in sites)
    kernel = [s for s in sites if s.startswith("kernel-")]
    assert kernel, f"no kernel-fault site fired under the pinned seed: {sites}"
