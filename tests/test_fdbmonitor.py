"""fdbmonitor: conf-driven supervision — children launch, a killed child
restarts, the cluster it supervises actually serves traffic, and SIGTERM
stops everything."""

import os
import signal
import socket
import subprocess
import sys
import time

from foundationdb_tpu.tools.tcp_soak import fdbcli, free_ports

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_fdbmonitor_supervises_cluster(tmp_path):
    cport, w1, w2 = free_ports(3)
    coord = f"127.0.0.1:{cport}"
    conf = tmp_path / "cluster.conf"
    conf.write_text(
        f"""
[general]
restart_delay = 1
cluster_coordinators = {coord}
config = n_storage=1,replication=1,n_tlogs=1

[fdbserver.{cport}]
role = coordinator
listen = {coord}
datadir = {tmp_path}/c

[fdbserver.{w1}]
listen = 127.0.0.1:{w1}
class = storage
datadir = {tmp_path}/w1

[fdbserver.{w2}]
listen = 127.0.0.1:{w2}
class = stateless
datadir = {tmp_path}/w2
"""
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    mon = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "foundationdb_tpu.tools.fdbmonitor",
            "--conffile",
            str(conf),
            "--poll-interval",
            "0.5",
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        deadline = time.time() + 120
        while True:
            assert mon.poll() is None, mon.stdout.read()
            rc, out = fdbcli(coord, "set mon ok", timeout=30)
            if rc == 0:
                break
            assert time.time() < deadline, f"cluster never formed: {out}"
            time.sleep(2)

        # kill the storage worker child directly: the monitor must restart
        # it and the cluster must keep serving (datadir resurrection)
        out = subprocess.run(
            ["pkill", "-9", "-f", f"fdbserver.*{w1}"],
            capture_output=True,
        )
        assert out.returncode == 0, "no child matched pkill"
        deadline = time.time() + 120
        while True:
            assert mon.poll() is None
            rc, out = fdbcli(coord, "get mon", timeout=30)
            if rc == 0 and "ok" in out:
                break
            assert time.time() < deadline, f"no recovery: {out}"
            time.sleep(2)

        mon.send_signal(signal.SIGTERM)
        mon.wait(timeout=30)
    finally:
        if mon.poll() is None:
            mon.kill()
        subprocess.run(["pkill", "-9", "-f", f"fdbserver.*{cport}"], capture_output=True)
        subprocess.run(["pkill", "-9", "-f", f"fdbserver.*{w1}"], capture_output=True)
        subprocess.run(["pkill", "-9", "-f", f"fdbserver.*{w2}"], capture_output=True)
