"""Chaos round 2: io_error/disk-full injection, Rollback,
RandomMoveKeys, ChangeConfig under load, and the restarting test tier
(whole-cluster save-and-kill over real processes)."""

import pytest

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.files import DiskFault, SimDisk
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.workloads import (
    ChangeConfigWorkload,
    ConsistencyCheckWorkload,
    CycleWorkload,
    DiskFailureWorkload,
    RandomMoveKeysWorkload,
    RollbackWorkload,
    run_workloads,
)


def make(seed=0, **cfg):
    sim = Sim(seed=seed, chaos=True)
    sim.activate()
    cluster = DynamicCluster(
        sim, ClusterConfig(**cfg), n_coordinators=3
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    return sim, cluster, db


# -- fault primitives ---------------------------------------------------------


def test_sim_disk_io_error_injection():
    sim = Sim(seed=1)
    sim.activate()
    disk = SimDisk(sim, "m1")
    disk.inject_io_errors(1.0)
    f = disk.open("x")

    async def go():
        with pytest.raises(DiskFault):
            await f.write(0, b"data")
        disk.inject_io_errors(0.0)
        await f.write(0, b"data")
        await f.sync()
        assert await f.read(0, 4) == b"data"
        return True

    assert sim.run_until_done(spawn(go()), 10.0)


def test_sim_disk_full():
    sim = Sim(seed=2)
    sim.activate()
    disk = SimDisk(sim, "m2")
    f = disk.open("x")

    async def go():
        await f.write(0, b"a" * 100)
        await f.sync()
        disk.set_capacity(disk.total_bytes() + 10)
        await f.write(100, b"b" * 10)  # exactly fits
        with pytest.raises(DiskFault):
            await f.write(110, b"c" * 50)  # over capacity
        disk.set_capacity(None)
        await f.write(110, b"c" * 50)
        return True

    assert sim.run_until_done(spawn(go()), 10.0)


# -- workloads under load -----------------------------------------------------


def _spec(db, sim, rng, fault_workloads):
    return [
        CycleWorkload(db, rng.fork(), nodes=10, transactions=20),
        *fault_workloads,
        ConsistencyCheckWorkload(db, rng.fork(), replication=2),
    ]


def drive_spec(sim, workloads, limit=1200.0):
    async def go():
        await run_workloads(workloads)
        return True

    assert sim.run_until_done(spawn(go()), limit)


def test_rollback_under_load():
    sim, cluster, db = make(
        seed=11, n_proxies=2, n_tlogs=2, n_storage=2, replication=2,
        tlog_replication=2,
    )
    rng = sim.loop.random
    w = RollbackWorkload(db, rng.fork(), sim=sim, clogs=2, duration=1.5)
    drive_spec(sim, _spec(db, sim, rng, [w]))
    assert w.performed >= 1


def test_random_move_keys_under_load():
    sim, cluster, db = make(
        seed=12, n_storage=4, replication=2, n_tlogs=2, tlog_replication=2
    )
    rng = sim.loop.random
    w = RandomMoveKeysWorkload(db, rng.fork(), sim=sim, moves=3)
    drive_spec(sim, _spec(db, sim, rng, [w]))
    assert w.attempts >= 1


def test_change_config_under_load():
    sim, cluster, db = make(
        seed=13, n_proxies=1, n_resolvers=1, n_storage=2, replication=2,
        n_tlogs=2, tlog_replication=2,
    )
    rng = sim.loop.random
    w = ChangeConfigWorkload(
        db, rng.fork(), coordinators=cluster.coordinators, changes=1,
        choices=[{"n_proxies": 2}],
    )
    drive_spec(sim, _spec(db, sim, rng, [w]))
    assert w.changed >= 1


def test_disk_failure_under_load():
    sim, cluster, db = make(
        seed=14, n_storage=2, replication=2, n_tlogs=2, tlog_replication=2
    )
    rng = sim.loop.random
    w = DiskFailureWorkload(
        db, rng.fork(), sim=sim, episodes=1, duration=1.5, p=0.05
    )
    drive_spec(sim, _spec(db, sim, rng, [w]))
    assert w.faulted


# -- restarting tier (real processes) -----------------------------------------


def test_tcp_cluster_save_kill_restart():
    """SaveAndKill.actor.cpp's shape over real processes: write, SIGKILL
    the whole tree, restart it on the same datadirs/ports, verify
    everything synced before the kill survives, and keep writing."""
    import tempfile

    from foundationdb_tpu.tools.tcp_soak import TcpCluster, fdbcli, wait_for

    with tempfile.TemporaryDirectory(prefix="restart-tier-") as d:
        cluster = TcpCluster(d)
        try:
            wait_for(
                lambda: (
                    fdbcli(cluster.coord, "set boot ok", timeout=30)[0] == 0,
                    "boot",
                ),
                180,
                "cluster never formed",
                cluster,
            )
            for i in range(8):
                rc, out = fdbcli(
                    cluster.coord, f"set rk{i} v{i}", timeout=30
                )
                assert rc == 0, out

            cluster.kill_all()
            cluster.restart_all()

            wait_for(
                lambda: (
                    fdbcli(cluster.coord, "set reborn ok", timeout=30)[0]
                    == 0,
                    "reform",
                ),
                180,
                "cluster never re-formed after full restart",
                cluster,
            )
            rc, out = fdbcli(
                cluster.coord, *[f"get rk{i}" for i in range(8)], timeout=60
            )
            assert rc == 0, out
            for i in range(8):
                assert f"v{i}" in out, f"lost rk{i} after full restart:\n{out}"
        finally:
            cluster.stop()
