"""Locality + replication policies (fdbrpc/Locality.h,
fdbrpc/ReplicationPolicy.h:99-160): policy combinators, zone-aware team
building, and the acid test — kill an ENTIRE zone of a 3-zone
double-replicated cluster and lose nothing."""

import pytest

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.runtime.locality import (
    Locality,
    PolicyAcross,
    PolicyAnd,
    PolicyOne,
    policy_for,
)
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.server.log_system import assign_tags


# -- policy combinators -------------------------------------------------------


def L(m, z=None, dc="dc0"):
    return Locality.of(m, zone=z, dc=dc)


def test_policy_one():
    p = PolicyOne()
    assert p.validate([L("m1")])
    assert p.select([("a", L("m1"))]) == ["a"]
    assert p.select([]) is None


def test_policy_across_zones():
    p = PolicyAcross(2, "zone")
    assert p.validate([L("m1", "z1"), L("m2", "z2")])
    assert not p.validate([L("m1", "z1"), L("m2", "z1")])
    picked = p.select(
        [
            ("a", L("m1", "z1")),
            ("b", L("m2", "z1")),
            ("c", L("m3", "z2")),
        ]
    )
    assert picked is not None and len(picked) == 2
    zones = {"a": "z1", "b": "z1", "c": "z2"}
    assert len({zones[i] for i in picked}) == 2
    # impossible: only one zone
    assert p.select([("a", L("m1", "z1")), ("b", L("m2", "z1"))]) is None


def test_policy_across_nested():
    # 2 DCs, each with 2 distinct zones inside
    p = PolicyAcross(2, "dc", PolicyAcross(2, "zone"))
    cands = [
        ("a", L("m1", "z1", "dc1")),
        ("b", L("m2", "z2", "dc1")),
        ("c", L("m3", "z3", "dc2")),
        ("d", L("m4", "z4", "dc2")),
    ]
    picked = p.select(cands)
    assert picked is not None and len(picked) == 4
    assert p.replicas() == 4
    assert p.validate([l for _i, l in cands])
    assert not p.validate(
        [L("m1", "z1", "dc1"), L("m2", "z2", "dc1"), L("m3", "z3", "dc1")]
    )


def test_policy_and():
    p = PolicyAnd([PolicyAcross(2, "zone"), PolicyAcross(2, "machine")])
    cands = [
        ("a", L("m1", "z1")),
        ("b", L("m2", "z2")),
    ]
    picked = p.select(cands)
    assert picked is not None
    assert p.validate([L("m1", "z1"), L("m2", "z2")])


def test_policy_for():
    assert isinstance(policy_for(1), PolicyOne)
    p = policy_for(3)
    assert isinstance(p, PolicyAcross) and p.n == 3


def test_assign_tags_across_zones():
    addrs = [f"t{i}" for i in range(4)]
    zones = ["z0", "z0", "z1", "z1"]
    logs = assign_tags(addrs, [f"l{i}" for i in range(4)], 8, 2, zones=zones)
    zone_of = dict(zip(addrs, zones))
    # every tag's replicas span two zones
    holders: dict = {}
    for log in logs:
        for t in log.tags:
            holders.setdefault(t, []).append(log.address)
    for t, hs in holders.items():
        assert len(hs) == 2
        assert len({zone_of[h] for h in hs}) == 2, (t, hs)


# -- end-to-end: zone kill ----------------------------------------------------


def run(sim, coro, limit=600.0):
    sim.activate()
    fut = spawn(coro)
    return sim.run_until_done(fut, limit)


def test_zone_kill_loses_nothing():
    """3 zones, 6 storage, 2× replication: every team spans two zones, so
    killing every process in one zone leaves at least one live replica of
    every shard; after recovery all data is readable and writable."""
    sim = Sim(seed=21)
    sim.activate()
    cluster = DynamicCluster(
        sim,
        ClusterConfig(n_storage=6, replication=2, n_tlogs=3, tlog_replication=2),
        n_coordinators=3,
        n_zones=3,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def go():
        keys = [b"zk%03d" % i for i in range(40)]

        async def fill(tr):
            for i, k in enumerate(keys):
                tr.set(k, b"v%d" % i)

        await db.run(fill)

        # all storage teams must span two zones
        await delay(2.0)
        # find the master's shard map via a fresh location scan
        zones_of_team = []
        for k in (b"", b"\x40", b"\x80", b"\xc0"):
            b, e, team = await db._locate(k)
            zs = {sim.processes[a].locality.zone for a in team}
            zones_of_team.append((team, zs))
            assert len(zs) == len(team), (team, zs)

        killed = sim.kill_zone("z0")
        assert killed, "zone z0 had processes"

        # survive: reads + writes continue after recovery
        db2 = Database.from_coordinators(
            sim, cluster.coordinators, client_addr="client2"
        )

        async def check(tr):
            out = []
            for k in keys:
                out.append(await tr.get(k))
            return out

        vals = await db2.run(check)
        assert vals == [b"v%d" % i for i in range(len(keys))]

        async def write_more(tr):
            tr.set(b"after-kill", b"yes")

        await db2.run(write_more)

        async def read_back(tr):
            return await tr.get(b"after-kill")

        assert await db2.run(read_back) == b"yes"
        return True

    assert run(sim, go())
