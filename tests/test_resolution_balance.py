"""Load-driven resolver rebalancing (resolutionBalancing analog).

masterserver.actor.cpp:896 + MasterProxyServer.actor.cpp:370: resolvers
sample per-range load; the master records boundary moves between
resolver ROLES, delivered to every proxy piggybacked on version grants
(ack-based, so a lost grant reply cannot lose the delivery). During the
MVCC transition window each proxy fans reads out to every era's owner —
verdicts stay EXACT: conflicts with writes recorded at the new owner are
caught, and old-snapshot reads of untouched keys still commit.
"""

from foundationdb_tpu.client import Database
from foundationdb_tpu.errors import NotCommitted
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn, wait_for_all
from foundationdb_tpu.server import Cluster, ClusterConfig


def make_db(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(**cfg))
    db = Database(sim, cluster.proxy_addrs)
    return sim, cluster, db


def drive(sim, coro, limit=600.0):
    return sim.run_until_done(spawn(coro), limit)


def force_move(cluster, begin, end, dst_iface):
    ok = cluster.master.set_resolver_changes(
        [(begin, end, dst_iface)], [p.uid for p in cluster.proxies]
    )
    assert ok


def newest_owner_map(proxy):
    return [
        (b, e, owners[-1][1].address, owners[-1][1].uid)
        for b, e, owners in proxy.key_resolvers.ranges()
    ]


def test_hot_prefix_moves_boundary_and_rebalances():
    """All load on a hot prefix deep inside one resolver's range: the
    balancer must move a boundary, and post-move traffic must spread.
    (Scenario shared with dryrun_multichip via rebalance_drill.)"""
    from foundationdb_tpu.workloads.rebalance_drill import hot_prefix_rebalance

    sim, cluster, db = make_db(seed=31, n_resolvers=2, n_proxies=2)
    balancer = cluster.start_resolution_balancer()

    async def go():
        moves, gained = await hot_prefix_rebalance(cluster, db, balancer)
        assert moves >= 1, "no boundary move despite hot prefix"
        # both resolvers saw a real share of post-move traffic (pre-move,
        # resolver 0 saw only empty/system batches)
        assert min(gained) > 0, gained
        return True

    assert drive(sim, go())
    # every proxy converged on the same (newest-owner) partition, and the
    # boundary set actually grew
    maps = [newest_owner_map(pr) for pr in cluster.proxies]
    assert maps[0] == maps[1], "proxies diverged on the resolver partition"
    assert len(maps[0]) > 2, "boundary set did not grow"


def test_moved_range_conflicts_stay_exact():
    """An old-snapshot read of a moved range must CONFLICT when someone
    wrote the key after its snapshot (the write lives at the NEW owner),
    and must still COMMIT when nothing was written (reads fan out to
    every era's owner — no spurious aborts, no missed conflicts)."""
    sim, cluster, db = make_db(seed=32, n_resolvers=2)

    async def go():
        async def put(tr):
            tr.set(b"\xc0fence", b"v0")
            tr.set(b"\xc0quiet", b"q0")

        await db.run(put)

        # two old-snapshot transactions pinned before the move
        tr_conflicted = db.transaction()
        await tr_conflicted.get(b"\xc0fence")
        tr_conflicted.set(b"\xc0fence", b"stale")
        tr_clean = db.transaction()
        await tr_clean.get(b"\xc0quiet")
        tr_clean.set(b"\xc0quiet", b"q1")

        # move [\xc0, \xd0) to resolver 0 (owner of the low half)
        dst = next(iter(cluster.resolver_map.ranges()))[2]
        force_move(cluster, b"\xc0", b"\xd0", dst)

        # a post-move write to the contested key (recorded at the NEW
        # owner; also delivers the change set to the proxies)
        async def clobber(tr):
            tr.set(b"\xc0fence", b"post-move")

        await db.run(clobber)

        try:
            await tr_conflicted.commit()
            raise AssertionError(
                "old-snapshot read missed a post-move write"
            )
        except NotCommitted:
            pass

        # the untouched key commits — the transition causes no spurious
        # aborts
        await tr_clean.commit()
        tr = db.transaction()
        assert await tr.get(b"\xc0quiet") == b"q1"
        assert await tr.get(b"\xc0fence") == b"post-move"
        return True

    assert drive(sim, go())


def test_move_does_not_lose_unrelated_traffic():
    """Writes outside the moved range, in flight around the move, are
    unaffected; data is intact afterwards."""
    sim, cluster, db = make_db(seed=33, n_resolvers=2, n_proxies=2)

    async def go():
        dst = next(iter(cluster.resolver_map.ranges()))[2]

        async def writer(lo):
            for i in range(30):
                async def put(tr, i=i):
                    tr.set(b"k%02d%04d" % (lo, i), b"v%d" % i)

                await db.run(put)
            return True

        w1 = spawn(writer(1))
        w2 = spawn(writer(2))
        await delay(0.02)
        force_move(cluster, b"\x80", b"\xa0", dst)
        await wait_for_all([w1, w2])
        tr = db.transaction()
        rows = await tr.get_range(b"k", b"l", limit=1000)
        assert len(rows) == 60, len(rows)
        return True

    assert drive(sim, go())
