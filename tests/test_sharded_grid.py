"""Sharded grid resolver on a multi-device CPU mesh, differential against
the single-device kernel and the oracle: verdicts must match bit-for-bit
(the sharded design pmax-combines history + intra-batch knowledge before
commit, so there is no multi-resolver relaxation), including across a
host-driven partition reshard."""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from foundationdb_tpu.conflict import grid as G
from foundationdb_tpu.conflict import keys as K
from foundationdb_tpu.conflict import sharded
from foundationdb_tpu.conflict.api import CommitTransaction, Verdict
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet


def _mesh(n_part, n_data):
    devs = jax.devices()
    need = n_part * n_data
    if len(devs) < need:
        pytest.skip(f"need {need} devices, have {len(devs)}")
    return Mesh(
        np.array(devs[:need]).reshape(n_part, n_data),
        axis_names=("part", "data"),
    )


def _make_txns(rnd, n, keyspace, snap, span=6):
    txs = []
    for _ in range(n):
        a = rnd.randrange(keyspace)
        c = rnd.randrange(keyspace)
        txs.append(
            CommitTransaction(
                read_snapshot=snap,
                read_conflict_ranges=[
                    (_key(a, keyspace), _key(a + 1 + rnd.randrange(span), keyspace))
                ],
                write_conflict_ranges=[
                    (_key(c, keyspace), _key(c + 1 + rnd.randrange(span), keyspace))
                ],
            )
        )
    return txs


def _key(i, keyspace):
    # spread keys over the full first-byte range so every partition of the
    # uniform first-lane split owns some traffic
    return bytes([int(255 * i / (keyspace + 64)) % 256]) + (b"%06d" % i)


def _encode_batch(txs, width, T, KR, KW):
    L = width // 4
    sent = K.max_sentinel(width)
    rb = np.tile(sent, (T, KR, 1))
    re = np.tile(sent, (T, KR, 1))
    wb = np.tile(sent, (T, KW, 1))
    we = np.tile(sent, (T, KW, 1))
    t_snap = np.zeros(T, np.int32)
    t_has_reads = np.zeros(T, bool)
    for t, tr in enumerate(txs):
        t_snap[t] = tr.read_snapshot
        t_has_reads[t] = bool(tr.read_conflict_ranges)
        for i, (b, e) in enumerate(tr.read_conflict_ranges):
            rb[t, i] = K.encode_keys([b], width)[0]
            re[t, i] = K.encode_keys([e], width, round_up=True)[0]
        for i, (b, e) in enumerate(tr.write_conflict_ranges):
            wb[t, i] = K.encode_keys([b], width)[0]
            we[t, i] = K.encode_keys([e], width, round_up=True)[0]
    return G.Batch(rb=rb, re=re, wb=wb, we=we, t_snap=t_snap, t_has_reads=t_has_reads)


def test_sharded_matches_single_device_and_oracle():
    n_part, n_data = 4, 2
    mesh = _mesh(n_part, n_data)
    L, width = 2, 8
    B, S = 64, 32
    T, KR, KW = 32, n_data, 1
    rnd = random.Random(11)

    states = sharded.make_sharded_states(n_part, B, S, L)
    spec = jax.tree.map(lambda _: NamedSharding(mesh, P("part")), G.GridState(0, 0, 0, 0, 0))
    states = jax.device_put(states, spec)
    step = sharded.build_sharded_resolver(mesh, lanes=L)

    oracle = OracleConflictSet()
    single = TpuConflictSet(key_width=width, capacity=1 << 9)

    for i in range(14):
        txs = _make_txns(rnd, T, 3000, i)
        want = oracle.detect_batch(list(txs), i + 20, max(i - 6, 0))
        got_single = single.detect_batch(list(txs), i + 20, max(i - 6, 0))
        assert [Verdict(v) for v in got_single] == want, f"single batch {i}"

        batch = _encode_batch(txs, width, T, KR, KW)
        states, verdicts, pressure = step(
            states,
            batch,
            np.int32(i + 20),
            np.int32(max(i - 6, 0)),
            np.int32(max(i - 6, 0)),
        )
        got = [Verdict(int(v)) for v in np.asarray(verdicts)[: len(txs)]]
        assert got == want, f"sharded batch {i}"

        pr = np.asarray(pressure)
        assert (pr[:, 0] <= G.staging_slots(S)).all(), pr
        assert (pr[:, 1] <= S).all(), pr

        if i == 7:
            # mid-run host-driven partition rebalance must not disturb the
            # step function (verdict parity continues below)
            for p in range(n_part):
                states, pres = sharded.reshard_partition(states, p, B, S)
                assert pres <= S
            states = jax.device_put(states, spec)


def test_sharded_reshard_on_overflow():
    """Flood one partition until its staging plane overflows; the host
    grows that partition's grid and replays — parity must hold."""
    n_part, n_data = 2, 1
    mesh = _mesh(n_part, n_data)
    L, width = 2, 8
    B, S = 4, 8
    T, KR, KW = 16, 1, 1
    rnd = random.Random(13)

    states = sharded.make_sharded_states(n_part, B, S, L)
    spec = jax.tree.map(lambda _: NamedSharding(mesh, P("part")), G.GridState(0, 0, 0, 0, 0))
    states = jax.device_put(states, spec)
    step = sharded.build_sharded_resolver(mesh, lanes=L)
    grown = {p: (B, S) for p in range(n_part)}

    oracle = OracleConflictSet()
    # NB: growing one partition changes that shard's static shape; stacked
    # states must share shapes, so overflow here grows ALL partitions.
    # Growth axis matters: staged-overflow (pr[:,0]) means the batch put
    # more NEW distinct keys into one gap than the staging plane holds —
    # no repivoting over live rows can split that gap, so the host grows
    # the SLOT axis; kept-overflow (pr[:,1]) grows the bucket axis.
    for i in range(5):
        # concentrated key traffic: floods few buckets so the staging
        # plane overflows and the host must grow + replay
        txs = _make_txns(rnd, T, 120, i, span=2)
        want = oracle.detect_batch(list(txs), i + 20, max(i - 4, 0))
        batch = _encode_batch(txs, width, T, KR, KW)
        # donation discipline (PR 2's donated-buffer race): the snapshot
        # keeps the ORIGINAL arrays — step() donates a fresh `+ 0` copy,
        # so an abandoned overflow dispatch can never scribble over the
        # buffers the replay reads
        snapshot = states
        for _attempt in range(8):
            new_states, verdicts, pressure = step(
                jax.tree.map(lambda x: x + 0, states),
                batch,
                np.int32(i + 20),
                np.int32(max(i - 4, 0)),
                np.int32(max(i - 4, 0)),
            )
            pr = np.asarray(pressure)
            Bc, Sc = grown[0]
            if (pr[:, 0] <= G.staging_slots(Sc)).all() and (pr[:, 1] <= Sc).all():
                states = new_states
                break
            if (pr[:, 0] > G.staging_slots(Sc)).any():
                Sc *= 2
            else:
                Bc *= 2
            host_snap = jax.tree.map(jax.device_get, snapshot)
            parts = []
            for p in range(n_part):
                shard = jax.tree.map(lambda x: x[p], host_snap)
                new_shard, pres = G.reshard_device(shard, Bc, Sc)
                assert pres <= Sc
                # pull to host: stacking device-resident shards from
                # different mesh devices deadlocks the CPU backend
                parts.append(jax.tree.map(np.asarray, new_shard))
            states = jax.device_put(
                jax.tree.map(lambda *xs: np.stack(xs), *parts), spec
            )
            snapshot = states
            grown = {p: (Bc, Sc) for p in range(n_part)}
        else:
            raise AssertionError("overflow replay did not converge")
        got = [Verdict(int(v)) for v in np.asarray(verdicts)[: len(txs)]]
        assert got == want, f"batch {i}"
    assert grown[0] != (B, S), "test never exercised the overflow path"
