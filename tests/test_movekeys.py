"""Shard relocation: MoveKeys two-phase protocol + fetchKeys + metadata
propagation through resolvers to every proxy's shard map.

The analog of the reference's RandomMoveKeys workload checks: data written
before a move reads back identically after it, through the new team; the
source releases the range; writes during the move are not lost.
"""

import pytest

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.server.movekeys import move_shard


def make(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = DynamicCluster(sim, ClusterConfig(**cfg))
    db = Database.from_coordinators(sim, cluster.coordinators)
    return sim, cluster, db


def run(sim, coro, limit=600.0):
    sim.activate()
    return sim.run_until_done(spawn(coro), limit)


async def put(db, key, value):
    async def body(tr):
        tr.set(key, value)

    await db.run(body)


async def get(db, key):
    async def body(tr):
        return await tr.get(key)

    return await db.run(body)


async def find_storage(sim, db):
    """[(StorageInterface)] from the current coordinated state, via the
    worker hosting the master (test introspection)."""
    out = []
    for addr, p in sim.processes.items():
        w = getattr(p, "worker", None)
        if w is None or not p.alive:
            continue
        for h in w.roles.values():
            if h.kind == "storage":
                from foundationdb_tpu.server.interfaces import StorageInterface

                out.append(StorageInterface(address=addr, uid=h.uid, tag=h.obj.tag))
    return sorted(out, key=lambda s: s.tag)


def test_move_shard_end_to_end():
    # 4 storage servers, 2 teams of 2: shard [0x80,∞) on team {2,3};
    # move it to team {0,1}, then verify reads + release.
    sim, cluster, db = make(
        seed=21,
        n_proxies=2,
        n_resolvers=2,
        n_tlogs=2,
        n_storage=4,
        replication=2,
        tlog_replication=2,
    )

    async def body():
        for i in range(30):
            await put(db, b"\x90k%02d" % i, b"v%d" % i)  # lands in 2nd shard
        storage = await find_storage(sim, db)
        assert len(storage) == 4
        dest = [storage[0], storage[1]]

        # writes concurrent with the move
        stop = [False]

        async def writer():
            i = 30
            while not stop[0]:
                await put(db, b"\x90k%02d" % i, b"v%d" % i)
                i += 1
                await delay(0.05)
            return i

        wfut = spawn(writer())
        await move_shard(db, b"\x80", None, dest)
        stop[0] = True
        total = await wfut

        # location cache refresh → reads must come from the new team
        db.invalidate_cache(b"\x90")
        for i in range(total):
            assert await get(db, b"\x90k%02d" % i) == b"v%d" % i, i

        # the new team serves; the old team dropped the range
        from foundationdb_tpu.server.interfaces import (
            GetKeyServersRequest,
            Tokens,
        )

        # a proxy that didn't commit the final move txn applies its echo
        # at its NEXT commit batch (bounded staleness ≤ the idle-commit
        # interval) — poll until every proxy's map converges
        for _ in range(20):
            reply = await db._proxy_request(
                Tokens.GET_KEY_SERVERS, GetKeyServersRequest(key=b"\x90")
            )
            if set(reply.tags) == {0, 1}:
                break
            await delay(0.1)
        assert set(reply.tags) == {0, 1}, reply
        # source storage no longer owns it
        src_ss = next(
            h.obj
            for p in sim.processes.values()
            if getattr(p, "worker", None)
            for h in p.worker.roles.values()
            if h.kind == "storage" and h.obj.tag == 2
        )
        state = src_ss.owned[b"\x90"]
        assert state is None, state

    run(sim, body())


def test_move_survives_recovery():
    """A moved shard map must be rebuilt from the txs tag at recovery."""
    sim, cluster, db = make(
        seed=22,
        n_proxies=1,
        n_resolvers=1,
        n_tlogs=2,
        n_storage=4,
        replication=2,
        tlog_replication=2,
    )

    async def body():
        for i in range(10):
            await put(db, b"\x90m%02d" % i, b"v%d" % i)
        storage = await find_storage(sim, db)
        dest = [storage[0], storage[1]]
        await move_shard(db, b"\x80", None, dest)

        # kill the master: the new epoch must recover the moved map
        for addr, p in list(sim.processes.items()):
            w = getattr(p, "worker", None)
            if w and p.alive and any(h.kind == "master" for h in w.roles.values()):
                sim.kill_process(addr)
                break
        for i in range(10, 20):
            await put(db, b"\x90m%02d" % i, b"v%d" % i)
        db.invalidate_cache(b"\x90")
        for i in range(20):
            assert await get(db, b"\x90m%02d" % i) == b"v%d" % i, i

        from foundationdb_tpu.server.interfaces import (
            GetKeyServersRequest,
            Tokens,
        )

        reply = await db._proxy_request(
            Tokens.GET_KEY_SERVERS, GetKeyServersRequest(key=b"\x90")
        )
        assert set(reply.tags) == {0, 1}, reply

    run(sim, body())
