"""End-to-end transaction path in simulation: client → proxy → master/
resolver → tlog → storage and back.

The milestone test of SURVEY.md §7 stage 4 (the single-process vertical
slice, here as simulated multi-process roles). Each test builds a seeded
cluster; everything is deterministic from the seed.
"""

import pytest

from foundationdb_tpu.client import Database
from foundationdb_tpu.errors import NotCommitted
from foundationdb_tpu.kv.mutations import MutationType as MT
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import spawn, wait_for_all
from foundationdb_tpu.server import Cluster, ClusterConfig


def make_db(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(**cfg))
    db = Database(sim, cluster.proxy_addrs)
    return sim, cluster, db


def drive(sim, coro, limit=120.0):
    return sim.run_until_done(spawn(coro), limit)


# -- basic read/write ---------------------------------------------------------


def test_set_commit_get():
    sim, cluster, db = make_db()

    async def go():
        tr = db.transaction()
        tr.set(b"hello", b"world")
        v = await tr.commit()
        assert v > 0
        tr2 = db.transaction()
        got = await tr2.get(b"hello")
        assert got == b"world"
        assert await tr2.get(b"missing") is None
        return True

    assert drive(sim, go())


def test_read_your_writes_before_commit():
    sim, cluster, db = make_db(seed=1)

    async def go():
        tr0 = db.transaction()
        tr0.set(b"a", b"committed")
        tr0.set(b"gone", b"x")
        await tr0.commit()

        tr = db.transaction()
        # overlay over storage
        assert await tr.get(b"a") == b"committed"
        tr.set(b"a", b"mine")
        assert await tr.get(b"a") == b"mine"
        tr.clear(b"gone")
        assert await tr.get(b"gone") is None
        # atomic over unknown base resolves through storage
        tr.atomic_op(MT.APPEND_IF_FITS, b"a", b"!")
        assert await tr.get(b"a") == b"mine!"
        tr.atomic_op(MT.ADD, b"ctr", b"\x05")
        assert await tr.get(b"ctr") == b"\x05"
        await tr.commit()

        tr2 = db.transaction()
        assert await tr2.get(b"a") == b"mine!"
        assert await tr2.get(b"gone") is None
        assert await tr2.get(b"ctr") == b"\x05"
        return True

    assert drive(sim, go())


def test_conflict_detection_end_to_end():
    sim, cluster, db = make_db(seed=2)

    async def go():
        setup = db.transaction()
        setup.set(b"k", b"0")
        await setup.commit()

        a = db.transaction()
        b = db.transaction()
        va = await a.get(b"k")
        vb = await b.get(b"k")
        a.set(b"k", b"a")
        b.set(b"k", b"b")
        await a.commit()
        with pytest.raises(NotCommitted):
            await b.commit()
        # non-overlapping writes with non-overlapping reads both commit
        c = db.transaction()
        d = db.transaction()
        await c.get(b"c-key")
        await d.get(b"d-key")
        c.set(b"c-key", b"1")
        d.set(b"d-key", b"1")
        await c.commit()
        await d.commit()
        return True

    assert drive(sim, go())


def test_blind_writes_never_conflict():
    sim, cluster, db = make_db(seed=3)

    async def go():
        trs = [db.transaction() for _ in range(8)]
        for i, tr in enumerate(trs):
            tr.set(b"same-key", b"%d" % i)
        await wait_for_all([spawn(tr.commit()) for tr in trs])
        tr = db.transaction()
        assert await tr.get(b"same-key") is not None
        return True

    assert drive(sim, go())


def test_causal_consistency_across_transactions():
    """A committed write is visible to any later-started transaction
    (GRV ≥ commit version — the getLiveCommittedVersion guarantee)."""
    sim, cluster, db = make_db(seed=4, n_proxies=2)

    async def go():
        for i in range(20):
            tr = db.transaction()
            tr.set(b"seq", b"%03d" % i)
            await tr.commit()
            tr2 = db.transaction()  # may hit the other proxy
            assert await tr2.get(b"seq") == b"%03d" % i
        return True

    assert drive(sim, go())


# -- ranges -------------------------------------------------------------------


def test_range_reads_and_clear_range():
    sim, cluster, db = make_db(seed=5)

    async def go():
        tr = db.transaction()
        for i in range(10):
            tr.set(b"r/%02d" % i, b"v%d" % i)
        await tr.commit()

        tr = db.transaction()
        rows = await tr.get_range(b"r/", b"r0")
        assert [k for k, _ in rows] == [b"r/%02d" % i for i in range(10)]
        rows = await tr.get_range(b"r/", b"r0", limit=3)
        assert len(rows) == 3
        rows = await tr.get_range(b"r/", b"r0", limit=2, reverse=True)
        assert [k for k, _ in rows] == [b"r/09", b"r/08"]

        tr.clear_range(b"r/03", b"r/07")
        tr.set(b"r/05", b"resurrected")
        rows = await tr.get_range(b"r/", b"r0")
        assert [k for k, _ in rows] == [
            b"r/00", b"r/01", b"r/02", b"r/05", b"r/07", b"r/08", b"r/09",
        ]
        assert dict(rows)[b"r/05"] == b"resurrected"
        await tr.commit()

        tr = db.transaction()
        rows = await tr.get_range(b"r/", b"r0")
        assert [k for k, _ in rows] == [
            b"r/00", b"r/01", b"r/02", b"r/05", b"r/07", b"r/08", b"r/09",
        ]
        return True

    assert drive(sim, go())


def test_range_conflict():
    """A range read conflicts with a later write inside the range."""
    sim, cluster, db = make_db(seed=6)

    async def go():
        a = db.transaction()
        await a.get_range(b"q/", b"q0")
        a.set(b"q/result", b"empty")

        b = db.transaction()
        b.set(b"q/item", b"new")
        await b.commit()

        with pytest.raises(NotCommitted):
            await a.commit()
        return True

    assert drive(sim, go())


# -- versionstamps ------------------------------------------------------------


def test_versionstamped_key():
    import struct

    sim, cluster, db = make_db(seed=7)

    async def go():
        tr = db.transaction()
        placeholder = b"log/" + b"\x00" * 10
        tr.set_versionstamped_key(
            placeholder + struct.pack("<I", 4), b"entry-1"
        )
        v = await tr.commit()
        stamp = tr.get_versionstamp()
        assert struct.unpack(">Q", stamp[:8])[0] == v

        tr2 = db.transaction()
        rows = await tr2.get_range(b"log/", b"log0")
        assert len(rows) == 1
        assert rows[0][0] == b"log/" + stamp
        assert rows[0][1] == b"entry-1"
        return True

    assert drive(sim, go())


# -- scaled shapes ------------------------------------------------------------


@pytest.mark.parametrize(
    "shape",
    [
        dict(n_proxies=2, n_resolvers=2, n_tlogs=2, n_storage=2),
        dict(n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=2, replication=2),
        dict(n_proxies=3, n_resolvers=2, n_tlogs=2, n_storage=4, replication=2),
    ],
)
def test_cluster_shapes(shape):
    sim, cluster, db = make_db(seed=8, **shape)

    async def go():
        # writes spanning the whole keyspace (all shards/resolvers)
        tr = db.transaction()
        for first in (0x00, 0x40, 0x80, 0xC0, 0xFF):
            tr.set(bytes([first]) + b"key", b"v%d" % first)
        await tr.commit()
        tr = db.transaction()
        for first in (0x00, 0x40, 0x80, 0xC0, 0xFF):
            assert await tr.get(bytes([first]) + b"key") == b"v%d" % first
        rows = await tr.get_range(b"", b"\xff\xff")
        assert len(rows) == 5
        # cross-shard conflicts still detected
        a = db.transaction()
        await a.get(b"\x00key")
        a.set(b"\xc0key", b"a")
        b = db.transaction()
        b.set(b"\x00key", b"b")
        await b.commit()
        with pytest.raises(NotCommitted):
            await a.commit()
        return True

    assert drive(sim, go())


def test_replicas_converge():
    """With replication=2 both team members end up with identical data
    (the ConsistencyCheck invariant)."""
    sim, cluster, db = make_db(seed=9, n_storage=2, replication=2)

    async def go():
        for i in range(10):
            tr = db.transaction()
            tr.set(b"c/%d" % i, b"v%d" % i)
            await tr.commit()
        return True

    assert drive(sim, go())
    # drain: run sim forward so both replicas pull everything
    sim.run(until=sim.loop.now() + 5.0)
    s0, s1 = cluster.storages
    v = min(s0.version.get(), s1.version.get())
    assert s0.data.range(b"", b"\xff", v) == s1.data.range(b"", b"\xff", v)
    assert len(s0.data.range(b"", b"\xff", v)) == 10


# -- regressions from review --------------------------------------------------


def test_range_limit_with_overlay_clears():
    """A truncated storage reply must not end the range early: clearing the
    first rows and reading with a small limit still yields later keys."""
    sim, cluster, db = make_db(seed=10)

    async def go():
        tr = db.transaction()
        for i in range(10):
            tr.set(b"w/%02d" % i, b"v%d" % i)
        await tr.commit()

        tr = db.transaction()
        tr.clear_range(b"w/00", b"w/04")
        rows = await tr.get_range(b"w/", b"w0", limit=5)
        assert [k for k, _ in rows] == [b"w/04", b"w/05", b"w/06", b"w/07", b"w/08"]
        # pending atomic on a key beyond the first storage window still
        # sees its true base value
        tr.atomic_op(MT.APPEND_IF_FITS, b"w/09", b"+")
        rows = await tr.get_range(b"w/", b"w0", limit=6)
        assert rows[-1] == (b"w/09", b"v9+")
        return True

    assert drive(sim, go())


def test_atomic_adds_apply_exactly_once():
    """Counter increments across many txns sum exactly (would fail if the
    tlog served unsynced entries and storage double-applied them)."""
    sim, cluster, db = make_db(seed=11)

    async def go():
        n = 30
        for _ in range(n):
            tr = db.transaction()
            tr.atomic_op(MT.ADD, b"counter", b"\x01\x00")
            await tr.commit()
        tr = db.transaction()
        assert await tr.get(b"counter") == bytes([n, 0])
        return True

    assert drive(sim, go())


def test_atomic_then_snapshot_read_still_conflicts():
    """Collapsing an atomic chain via a snapshot read must not strip the
    read conflict from a later non-snapshot read of the same key (the
    database-dependent determined value, ReadYourWrites semantics)."""
    import struct

    sim, cluster, db = make_db(seed=11)

    async def go():
        init = db.transaction()
        init.set(b"ctr", struct.pack("<q", 5))
        await init.commit()

        tr = db.transaction()
        await tr.get_read_version()
        tr.atomic_op(MT.ADD, b"ctr", struct.pack("<q", 1))
        v_snap = await tr.get(b"ctr", snapshot=True)  # collapses the chain
        assert struct.unpack("<q", v_snap)[0] == 6
        v = await tr.get(b"ctr")  # non-snapshot: must add read conflict
        assert struct.unpack("<q", v)[0] == 6

        other = db.transaction()
        other.set(b"ctr", struct.pack("<q", 100))
        await other.commit()

        with pytest.raises(NotCommitted):
            await tr.commit()
        return True

    assert drive(sim, go())


def test_reverse_range_across_shards():
    """Reverse range reads walk shards right-to-left (NativeAPI getRange
    reverse handling) — keys span all 4 shards of a 4-storage cluster."""
    sim, cluster, db = make_db(seed=12, n_storage=4)

    async def go():
        tr0 = db.transaction()
        # shard split points are at first bytes 0x40/0x80/0xc0; spread keys
        keys = [bytes([b]) + b"k%02d" % i for i in range(8) for b in (0x10, 0x50, 0x90, 0xd0)]
        for i, k in enumerate(keys):
            tr0.set(k, b"v%d" % i)
        await tr0.commit()
        expect = sorted(keys, reverse=True)

        tr = db.transaction()
        rows = await tr.get_range(b"", b"\xff", limit=len(keys), reverse=True)
        assert [k for k, _ in rows] == expect

        # limited reverse read stops after crossing one shard boundary
        rows = await tr.get_range(b"", b"\xff", limit=10, reverse=True)
        assert [k for k, _ in rows] == expect[:10]

        # reverse read with both endpoints mid-shard
        rows = await tr.get_range(b"\x11", b"\xd0k05", limit=100, reverse=True)
        want = [k for k in expect if b"\x11" <= k < b"\xd0k05"]
        assert [k for k, _ in rows] == want
        return True

    assert drive(sim, go())


def test_reverse_range_fuzz():
    """Randomized forward/reverse/limit/boundary combinations vs a model."""
    import random

    sim, cluster, db = make_db(seed=13, n_storage=4)
    rnd = random.Random(7)

    async def go():
        model = {}
        tr0 = db.transaction()
        for i in range(120):
            k = bytes([rnd.randrange(256)]) + b"%03d" % rnd.randrange(1000)
            v = b"v%d" % i
            model[k] = v
            tr0.set(k, v)
        await tr0.commit()

        tr = db.transaction()
        # overlay some uncommitted writes/clears so RYW merge is exercised
        for i in range(20):
            k = bytes([rnd.randrange(256)]) + b"%03d" % rnd.randrange(1000)
            if rnd.random() < 0.3:
                b2 = k
                e2 = bytes([min(k[0] + 1, 255)])
                tr.clear_range(b2, e2)
                for mk in list(model):
                    if b2 <= mk < e2:
                        del model[mk]
            else:
                model[k] = b"w%d" % i
                tr.set(k, b"w%d" % i)

        srt = sorted(model.items())
        for _ in range(40):
            a = bytes([rnd.randrange(256)])
            b = bytes([rnd.randrange(256)]) + (b"\xff" if rnd.random() < 0.5 else b"")
            if a >= b:
                a, b = b, a or b"\x00"
            if a >= b:
                continue
            limit = rnd.choice([1, 3, 10, 1000])
            reverse = rnd.random() < 0.5
            want = [kv for kv in srt if a <= kv[0] < b]
            if reverse:
                want = list(reversed(want))
            want = want[:limit]
            got = await tr.get_range(a, b, limit=limit, reverse=reverse)
            assert got == want, (a, b, limit, reverse, got[:3], want[:3])
        return True

    assert drive(sim, go())


def test_grv_batching_coalesces_rpcs():
    """Concurrent get_read_version calls share proxy round trips (the
    readVersionBatcher, NativeAPI.actor.cpp:1290) and the proxy coalesces
    its master getLiveCommitted fetches (MasterProxyServer.actor.cpp:925).
    All versions must still be causally valid (>= any prior commit)."""
    sim, cluster, db = make_db(seed=14)

    async def go():
        tr0 = db.transaction()
        tr0.set(b"k", b"v")
        committed = await tr0.commit()

        # count GRV RPCs at the client→proxy boundary
        calls = {"grv": 0}
        orig = db._proxy_request

        async def counting(token, req, **kw):
            from foundationdb_tpu.server.interfaces import Tokens as T

            if token == T.GRV:
                calls["grv"] += 1
            return await orig(token, req, **kw)

        db._proxy_request = counting

        async def one():
            tr = db.transaction()
            return await tr.get_read_version()

        versions = await wait_for_all([spawn(one()) for _ in range(50)])
        assert all(v >= committed for v in versions)
        # 50 concurrent GRVs collapse into a handful of proxy RPCs
        assert calls["grv"] <= 5, calls["grv"]
        return True

    assert drive(sim, go())


def test_partitioned_getcommitversion_does_not_wedge_proxy():
    """A partition that eats the proxy's getCommitVersion request must
    error that batch (commit_unknown_result), not hang it at vfut forever
    — a wedged batch blocks every successor on _resolving_gate while GRVs
    keep succeeding (ADVICE r4 medium). After healing, commits flow again."""
    from foundationdb_tpu.errors import CommitUnknownResult

    sim, cluster, db = make_db(seed=21, n_proxies=1)

    async def go():
        # healthy commit first (warms client caches)
        tr = db.transaction()
        tr.set(b"a", b"1")
        await tr.commit()

        sim.partition("proxy0", "master")
        tr = db.transaction()
        tr.set(b"b", b"2")
        # the commit must RESOLVE (with commit_unknown_result) before the
        # drive limit — the bug was an eternal hang at vfut
        with pytest.raises(CommitUnknownResult):
            await tr.commit()

        sim.heal()
        tr = db.transaction()
        tr.set(b"c", b"3")
        v = await tr.commit()
        assert v > 0
        tr2 = db.transaction()
        assert await tr2.get(b"c") == b"3"
        return True

    assert drive(sim, go(), limit=300.0)


def test_late_version_grant_plugs_chain_hole():
    """A version grant that arrives AFTER the proxy abandoned its batch
    (clogged link: request delivered, reply late) has later versions
    chained onto it by the master — the abandoned batch must still fill
    its slot in the prev->version chain (empty push) or every subsequent
    commit wedges at the resolvers/tlogs forever."""
    from foundationdb_tpu.errors import CommitUnknownResult

    sim, cluster, db = make_db(seed=23, n_proxies=1)

    async def go():
        tr = db.transaction()
        tr.set(b"a", b"1")
        await tr.commit()

        # longer than GETCOMMITVERSION_TIMEOUT: grants for the batches
        # fired early in the clog arrive only after their deadlines
        # expired (but short enough that the proxy's master-gone detector
        # doesn't — correctly — declare the master dead)
        sim.clog_pair("proxy0", "master", 7.5)
        tr = db.transaction()
        tr.set(b"b", b"2")
        try:
            await tr.commit()
        except CommitUnknownResult:
            pass

        # after the clog drains, new commits must flow — they chain onto
        # the late-granted versions, which only works if the holes were
        # plugged
        tr = db.transaction()
        tr.set(b"c", b"3")
        v = await tr.commit()
        assert v > 0
        tr2 = db.transaction()
        assert await tr2.get(b"c") == b"3"
        return True

    assert drive(sim, go(), limit=300.0)
