"""Tier-1 collection audit.

Two guards for the tier-1 harness itself (ROADMAP's verify command runs
with --continue-on-collection-errors, which means a test file that fails
to IMPORT silently drops its whole battery from the run — the suite goes
green while coverage quietly shrinks):

- every tests/test_*.py module must import cleanly, turning any
  collection error into a hard failure inside the budgeted run;
- the selector/bindingtester conformance batteries must stay inside the
  tier-1 budget: no `slow` markers (the tier-1 filter is `-m 'not
  slow'`), so the acceptance-gating tests cannot be quietly opted out.
"""

import importlib.util
import pathlib
import sys

TESTS = pathlib.Path(__file__).resolve().parent

# batteries that gate acceptance criteria: they must run in tier-1
TIER1_PINNED = ["test_selectors.py", "test_bindingtester.py"]


def test_every_test_module_imports():
    failures = []
    for path in sorted(TESTS.glob("test_*.py")):
        name = "tier1_audit__" + path.stem
        if name in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod  # self-referencing imports resolve
        try:
            spec.loader.exec_module(mod)
        except Exception as e:  # noqa: BLE001 — report every broken module
            failures.append(f"{path.name}: {e!r}")
        finally:
            sys.modules.pop(name, None)
    assert not failures, (
        "test modules that fail to import (tier-1 would silently skip "
        "them under --continue-on-collection-errors):\n  "
        + "\n  ".join(failures)
    )


def test_every_server_role_registers_metrics():
    """Metrics-registration lint: every server role class must expose a
    CounterCollection (`self.stats = CounterCollection(...)`) and register
    a `<role>.metrics#<uid>` endpoint, so new roles can't ship dark — the
    status pipeline aggregates exactly these (worker._role_metrics +
    Status's per-role pulls)."""
    import inspect
    import re

    from foundationdb_tpu.server import worker as worker_mod

    # role kind → class, mirroring Worker._make_* dispatch. `master` is a
    # transient recovery-coordinator actor FUNCTION (its long-lived
    # subsystems — DD, Ratekeeper — live behind master.* endpoints), so it
    # is exempt by design, not by omission.
    from foundationdb_tpu.server.log_router import LogRouter
    from foundationdb_tpu.server.proxy import Proxy
    from foundationdb_tpu.server.resolver import Resolver
    from foundationdb_tpu.server.storage import StorageServer
    from foundationdb_tpu.server.tlog import TLog

    role_classes = {
        "tlog": TLog,
        "log_router": LogRouter,
        "resolver": Resolver,
        "proxy": Proxy,
        "storage": StorageServer,
    }
    exempt = {"master"}

    # the registry above must cover every recruitable role kind: a new
    # _make_<role> without a lint entry fails here first
    kinds = set(
        re.findall(r"def _make_(\w+)\(", inspect.getsource(worker_mod.Worker))
    )
    missing = kinds - set(role_classes) - exempt
    assert not missing, f"role kinds without a metrics-lint entry: {missing}"

    for kind, cls in role_classes.items():
        src = inspect.getsource(cls)
        assert re.search(r"self\.stats\s*=\s*CounterCollection\(", src), (
            f"{kind}: role class {cls.__name__} has no CounterCollection — "
            f"its traffic would be invisible to status/trace"
        )
        assert re.search(r"\.metrics#", src), (
            f"{kind}: role class {cls.__name__} registers no *.metrics# "
            f"endpoint — the status aggregator could not pull it"
        )


def test_rpc_endpoints_open_spans_or_are_allowlisted():
    """Span-coverage lint: every RPC endpoint a proxy/storage/resolver
    registers must either open a distributed-trace span (runtime/trace.py
    ``span(``) in its handler, or sit on the explicit allowlist below —
    so a new client-facing endpoint can't ship invisible to the read/
    commit waterfalls the perf PRs cite."""
    import inspect
    import re

    from foundationdb_tpu.server.proxy import Proxy
    from foundationdb_tpu.server.resolver import Resolver
    from foundationdb_tpu.server.storage import StorageServer

    # admin/metrics/liveness endpoints (no client-visible latency to
    # attribute) and long-polls (a span covering a parked watch would
    # report minutes of "latency"): exempt BY NAME, never by default
    ALLOW = {
        "proxy": {"_ping", "_metrics", "_raw_committed"},
        "resolver": {"_ping", "_metrics", "_resolution_metrics", "_split_point"},
        "storage": {
            "_ping",
            "_metrics",
            "_get_version",
            "_owned_ranges",
            "get_shard_state",
            "get_shard_metrics",
            "get_split_key",
            "watch_value",  # long-poll: parks until the value changes
        },
    }

    for kind, cls in (
        ("proxy", Proxy),
        ("resolver", Resolver),
        ("storage", StorageServer),
    ):
        handlers = set()
        for meth in ("register", "register_instance", "register_endpoints"):
            fn = getattr(cls, meth, None)
            if fn is None:
                continue
            handlers |= set(
                re.findall(
                    r"process\.register\([^,]+,\s*self\.(\w+)\)",
                    inspect.getsource(fn),
                )
            )
        assert handlers, f"{kind}: no registered endpoints found by the lint"
        missing = []
        for h in sorted(handlers):
            if h in ALLOW[kind]:
                continue
            if "span(" not in inspect.getsource(getattr(cls, h)):
                missing.append(h)
        assert not missing, (
            f"{kind}: endpoints with neither a span nor an allowlist "
            f"entry: {missing} — open a span (runtime/trace.py) or add an "
            f"explicit exemption here"
        )


def test_acceptance_batteries_not_slow_marked():
    for name in TIER1_PINNED:
        path = TESTS / name
        assert path.exists(), f"{name} missing — acceptance battery gone"
        src = path.read_text()
        assert "mark.slow" not in src and "pytestmark" not in src, (
            f"{name} carries a marker that could drop it from the "
            f"tier-1 'not slow' run"
        )
