"""Tier-1 collection audit.

Two guards for the tier-1 harness itself (ROADMAP's verify command runs
with --continue-on-collection-errors, which means a test file that fails
to IMPORT silently drops its whole battery from the run — the suite goes
green while coverage quietly shrinks):

- every tests/test_*.py module must import cleanly, turning any
  collection error into a hard failure inside the budgeted run;
- the selector/bindingtester conformance batteries must stay inside the
  tier-1 budget: no `slow` markers (the tier-1 filter is `-m 'not
  slow'`), so the acceptance-gating tests cannot be quietly opted out.

The metrics-registration and span-coverage lints that used to live here
as inspect/regex assertions are now flowlint rules (reg-role-metrics,
reg-endpoint-span in foundationdb_tpu/tools/flowlint) with real
cross-module resolution. This file keeps their coverage honest: the old
positive assertions run as fixture tests against the new rules (flag +
near-miss on a synthetic worker/role tree), and the tree-level clean
checks run through the same engine tier-1 gates on in test_flowlint.py.
"""

import importlib.util
import pathlib
import sys

from foundationdb_tpu.tools.flowlint import lint, load_config

TESTS = pathlib.Path(__file__).resolve().parent

# batteries that gate acceptance criteria: they must run in tier-1
TIER1_PINNED = ["test_selectors.py", "test_bindingtester.py"]


def test_every_test_module_imports():
    failures = []
    for path in sorted(TESTS.glob("test_*.py")):
        name = "tier1_audit__" + path.stem
        if name in sys.modules:
            continue
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod  # self-referencing imports resolve
        try:
            spec.loader.exec_module(mod)
        except Exception as e:  # noqa: BLE001 — report every broken module
            failures.append(f"{path.name}: {e!r}")
        finally:
            sys.modules.pop(name, None)
    assert not failures, (
        "test modules that fail to import (tier-1 would silently skip "
        "them under --continue-on-collection-errors):\n  "
        + "\n  ".join(failures)
    )


# ---------------------------------------------------------------------------
# Registration-integrity rules: tree-level clean + fixture coverage.


def _reg_findings(rule_ids):
    res = lint()
    return [f for f in res.failing if f.rule in rule_ids]


def test_every_server_role_registers_metrics():
    """Every recruitable role class owns a CounterCollection and a
    `*.metrics#` endpoint — now enforced by the reg-role-metrics flowlint
    rule, which resolves Worker._make_<kind> factories to the role class
    they instantiate across modules (no inspect regexes). `master` stays
    exempt via flowlint config role_exempt: it is a transient
    recovery-coordinator actor FUNCTION whose long-lived subsystems (DD,
    Ratekeeper) live behind master.* endpoints — exempt by design, with
    the reason recorded in config.json, not by omission."""
    assert not _reg_findings({"reg-role-metrics"}), _reg_findings(
        {"reg-role-metrics"}
    )
    config = load_config()
    assert config["role_exempt"] == ["master"]


def test_rpc_endpoints_open_spans_or_are_exempted_inline():
    """Every RPC endpoint a proxy/storage/resolver registers opens a
    distributed-trace span — now the reg-endpoint-span flowlint rule.
    Exemptions moved from this file's ALLOW dict to inline
    `# flowlint: disable=reg-endpoint-span` comments ON the handler def
    lines (admin/metrics/liveness endpoints and long-polls), so the
    exemption travels with the code it excuses. A new endpoint without a
    span and without an inline exemption fails here."""
    assert not _reg_findings({"reg-endpoint-span"}), _reg_findings(
        {"reg-endpoint-span"}
    )
    # the old ALLOW set survives as inline disables: count them so a bulk
    # deletion (or a rule that silently stopped firing) is visible
    res = lint()
    disabled = [f for f in res.disabled if f.rule == "reg-endpoint-span"]
    assert len(disabled) >= 10, (
        "the span-endpoint exemption set shrank suspiciously — if "
        "endpoints gained real spans, great, update this floor; if the "
        "rule went blind, fix it"
    )


def test_wire_codec_registry_not_stale():
    """Codec staleness gate (ISSUE 18 satellite): every registered wire
    struct must carry a compiled encoder/decoder generated from the SAME
    class object and field list that is currently registered. A schema
    edit that skips re-registration (or a re-registration that skips
    recompilation) would silently fall back to — or worse, disagree with —
    the interpretive codec; `codec_audit()` turns that into a tier-1
    failure. The flag/near-miss fixtures for each staleness mode live in
    test_wire_codec.py; this is the tree-level clean check."""
    from foundationdb_tpu.net import wire

    problems = wire.codec_audit()
    assert not problems, (
        "stale compiled wire codecs (re-run register_struct after schema "
        "edits):\n  " + "\n  ".join(problems)
    )


# ---------------------------------------------------------------------------
# Fixture tests: the old assertions, replayed as flag/near-miss trees
# against the new rules (coverage must not shrink in the migration).

_WORKER = """\
class Worker:
    def _make_widget(self, h):
        from .widget import Widget
        w = Widget()
        return w
"""

_ROLE_OK = """\
from ..runtime.stats import CounterCollection

class Widget:
    def __init__(self):
        self.stats = CounterCollection("widget")

    def register_instance(self, process):
        process.register(f"widget.metrics#{id(self)}", self._metrics)
        process.register("widget.work", self.work)

    async def _metrics(self, _req):  # flowlint: disable=reg-endpoint-span
        return self.stats.snapshot()

    async def work(self, req):
        from ..runtime.trace import span
        with span("Widget.work"):
            return req
"""


def _lint_tree(
    tmp_path, worker_src, role_src, span_roles=("widget",),
    required_counters=None,
):
    pkg = tmp_path / "foundationdb_tpu" / "server"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "worker.py").write_text(worker_src)
    (pkg / "widget.py").write_text(role_src)
    config = {
        "include": ["foundationdb_tpu"],
        "exclude": [],
        "sim_scope": [],
        "host_only": {},
        "baseline": "baseline.json",
        "worker_module": "foundationdb_tpu/server/worker.py",
        "role_exempt": [],
        "span_roles": list(span_roles),
    }
    if required_counters is not None:
        config["role_required_counters"] = required_counters
    return lint(root=tmp_path, config=config)


def test_rule_fixture_role_with_metrics_and_spans_passes(tmp_path):
    res = _lint_tree(tmp_path, _WORKER, _ROLE_OK)
    assert not res.failing, [f.format() for f in res.failing]


def test_rule_fixture_missing_counter_collection_flagged(tmp_path):
    role = _ROLE_OK.replace('        self.stats = CounterCollection("widget")\n', "        pass\n")
    res = _lint_tree(tmp_path, _WORKER, role)
    assert any(
        f.rule == "reg-role-metrics" and f.detail == "Widget-stats"
        for f in res.failing
    ), [f.format() for f in res.failing]


def test_rule_fixture_missing_metrics_endpoint_flagged(tmp_path):
    role = _ROLE_OK.replace("widget.metrics#", "widget.admin#")
    res = _lint_tree(tmp_path, _WORKER, role)
    assert any(
        f.rule == "reg-role-metrics" and f.detail == "Widget-endpoint"
        for f in res.failing
    ), [f.format() for f in res.failing]


def test_rule_fixture_unresolvable_factory_flagged(tmp_path):
    """The old test asserted every _make_<kind> had a lint entry; the rule
    analog: a factory whose role class cannot be resolved is itself a
    finding (add it to role_exempt with a reason, or fix the factory)."""
    worker = _WORKER + (
        "\n"
        "    def _make_mystery(self, h):\n"
        "        return object()\n"
    )
    res = _lint_tree(tmp_path, worker, _ROLE_OK)
    assert any(
        f.rule == "reg-role-metrics" and f.detail == "unresolved-mystery"
        for f in res.failing
    ), [f.format() for f in res.failing]


def test_rule_fixture_required_counter_dropped_flags(tmp_path):
    """role_required_counters (ISSUE 17 satellite): dropping a pinned
    counter flags with the exact `<Class>-counter-<name>` detail; the
    intact role passes the same config (near-miss)."""
    role = _ROLE_OK.replace(
        '        self.stats = CounterCollection("widget")\n',
        '        self.stats = CounterCollection("widget")\n'
        '        self._c_a = self.stats.counter("prefiltered")\n'
        '        self._c_b = self.stats.counter("prefilterChecks")\n',
    )
    required = {"widget": ["prefiltered", "prefilterChecks"]}
    res = _lint_tree(tmp_path, _WORKER, role, required_counters=required)
    assert not res.failing, [f.format() for f in res.failing]
    # drop one pinned counter → that name flags, the other stays quiet
    dropped = role.replace(
        '        self._c_b = self.stats.counter("prefilterChecks")\n', ""
    )
    res = _lint_tree(tmp_path, _WORKER, dropped, required_counters=required)
    assert any(
        f.rule == "reg-role-metrics"
        and f.detail == "Widget-counter-prefilterChecks"
        for f in res.failing
    ), [f.format() for f in res.failing]
    assert not any(
        f.detail == "Widget-counter-prefiltered" for f in res.failing
    )


def test_rule_fixture_spanless_endpoint_flagged_and_disable_exempts(tmp_path):
    spanless = _ROLE_OK.replace(
        "        from ..runtime.trace import span\n"
        '        with span("Widget.work"):\n'
        "            return req\n",
        "        return req\n",
    )
    res = _lint_tree(tmp_path, _WORKER, spanless)
    assert any(
        f.rule == "reg-endpoint-span" and f.detail == "Widget.work"
        for f in res.failing
    ), [f.format() for f in res.failing]
    # the _metrics handler carries an inline disable: exempted, visible
    assert any(
        f.rule == "reg-endpoint-span" and f.detail == "Widget._metrics"
        for f in res.disabled
    )


def test_acceptance_batteries_not_slow_marked():
    for name in TIER1_PINNED:
        path = TESTS / name
        assert path.exists(), f"{name} missing — acceptance battery gone"
        src = path.read_text()
        assert "mark.slow" not in src and "pytestmark" not in src, (
            f"{name} carries a marker that could drop it from the "
            f"tier-1 'not slow' run"
        )
