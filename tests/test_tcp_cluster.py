"""The cluster as real OS processes over TCP (verdictable milestone):
spawn a coordinator + workers as subprocesses via tools/fdbserver, connect
with the TCP fdbcli, commit data, kill the process hosting the master,
and verify the survivors recover and serve everything."""

import os
import signal
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def spawn_server(args):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"  # never let a subprocess touch the TPU
    return subprocess.Popen(
        [sys.executable, "-m", "foundationdb_tpu.tools.fdbserver", *args],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def fdbcli(coordinators, *cmds, timeout=60):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-m",
                "foundationdb_tpu.tools.cli",
                "-C",
                coordinators,
                *[a for c in cmds for a in ("--exec", c)],
                "--timeout",
                str(max(timeout - 10, 5)),
            ],
            env=env,
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        # a hung CLI is a retryable formation failure, not a test error
        return -1, f"fdbcli timed out after {timeout}s: {e.stdout or ''}"
    return out.returncode, out.stdout


def test_tcp_cluster_boot_commit_kill_recover(tmp_path):
    cport, *wports = free_ports(5)
    coord = f"127.0.0.1:{cport}"
    procs = []
    try:
        procs.append(
            spawn_server(
                ["--listen", coord, "--role", "coordinator",
                 "--datadir", str(tmp_path / "coord")]
            )
        )
        config = "n_storage=2,replication=1,n_tlogs=1"
        classes = ["storage", "storage", "transaction", "stateless"]
        for port, pclass in zip(wports, classes):
            procs.append(
                spawn_server(
                    [
                        "--listen", f"127.0.0.1:{port}",
                        "--role", "worker",
                        "--class", pclass,
                        "--coordinators", coord,
                        "--config", config,
                        "--datadir", str(tmp_path / f"w{port}"),
                    ]
                )
            )

        def check_servers_alive(expect_dead=()):
            # fail fast if any server crashed (die_on_actor_error exits 44)
            for p in procs:
                if p in expect_dead:
                    continue
                if p.poll() is not None:
                    out = p.stdout.read() if p.stdout else ""
                    raise AssertionError(
                        f"server died rc={p.returncode}:\n{out}"
                    )

        # write through the TCP fdbcli (retry while the cluster forms)
        deadline = time.time() + 120
        while True:
            check_servers_alive()
            rc, out = fdbcli(coord, "set hello world", timeout=30)
            if rc == 0:
                break
            assert time.time() < deadline, f"cluster never formed: {out}"
            time.sleep(2)

        rc, out = fdbcli(coord, "get hello")
        assert rc == 0 and "world" in out, out

        for i in range(5):
            rc, out = fdbcli(coord, f"set k{i} v{i}")
            assert rc == 0, out

        # find and kill the worker hosting the master: the stateless-class
        # worker is the CC/master preference; kill it and let the cluster
        # re-recruit on the remaining workers
        victim = procs[-1]  # stateless worker
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)

        deadline = time.time() + 120
        while True:
            check_servers_alive(expect_dead=(victim,))
            rc, out = fdbcli(coord, "set after-kill yes", timeout=30)
            if rc == 0:
                break
            assert time.time() < deadline, f"no recovery: {out}"
            time.sleep(2)

        rc, out = fdbcli(
            coord, "get hello", "get k3", "get after-kill", timeout=60
        )
        assert rc == 0, out
        assert "world" in out and "v3" in out and "yes" in out, out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
