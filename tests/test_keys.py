"""Order-preserving key encoding: exactness up to width-1 bytes."""

import random

import numpy as np

from foundationdb_tpu.conflict import keys as K


def _cmp_codes(a, b):
    return K.compare_codes(a, b)


def test_roundtrip_ordering_exhaustive_short():
    ks = [b"", b"\x00", b"\x00\x00", b"a", b"a\x00", b"ab", b"b", b"\xff", b"\xff\xff"]
    codes = K.encode_keys(ks, width=8)
    for i, a in enumerate(ks):
        for j, b in enumerate(ks):
            want = (a > b) - (a < b)
            got = _cmp_codes(codes[i], codes[j])
            assert got == want, (a, b, got, want)


def test_point_range_nonempty_after_encoding():
    # FoundationDB point writes are [k, k + b"\x00"); these must stay non-empty.
    for k in [b"", b"x", b"hello", b"\x00\x00", b"\xfe" * 30]:
        a, b = K.encode_keys([k, k + b"\x00"], width=32)
        assert _cmp_codes(a, b) == -1


def test_random_ordering_matches_bytes():
    rnd = random.Random(7)
    ks = [
        bytes(rnd.randrange(256) for _ in range(rnd.randrange(0, 20)))
        for _ in range(300)
    ]
    codes = K.encode_keys(ks, width=32)
    order_by_bytes = sorted(range(len(ks)), key=lambda i: ks[i])
    order_by_code = sorted(
        range(len(ks)), key=lambda i: tuple(codes[i].tolist() + [ks[i]])
    )
    # codes must sort identically (ties in code only between equal keys,
    # impossible here below width-1 bytes unless keys are equal)
    for a, b in zip(order_by_bytes, order_by_code):
        assert ks[a] == ks[b]


def test_truncation_is_conservative():
    # beyond width-1 bytes two distinct keys may collapse — but only to equal
    a = b"p" * 40 + b"a"
    b = b"p" * 40 + b"b"
    ca, cb = K.encode_keys([a, b], width=32)
    assert _cmp_codes(ca, cb) == 0


def test_truncation_never_reorders():
    # Different-length long keys sharing a truncated prefix must collapse to
    # EQUAL codes, never invert (b"p"*31+b"z" > b"p"*31+b"aa" in byte order,
    # and an unclamped trailing length byte would have reordered them).
    a = b"p" * 31 + b"z"
    b = b"p" * 31 + b"aa"
    ca, cb = K.encode_keys([a, b], width=32)
    assert _cmp_codes(ca, cb) == 0
    # and any long key collapses to exactly its width-1-byte prefix's code
    prefix = b"p" * 31
    cp, cl = K.encode_keys([prefix, prefix + b"qqq"], width=32)
    assert _cmp_codes(cp, cl) == 0


def test_sentinel_is_max():
    s = K.max_sentinel(32)
    codes = K.encode_keys([b"\xff" * 31, b"zzz"], width=32)
    assert _cmp_codes(codes[0], s) == -1
    assert _cmp_codes(codes[1], s) == -1


def test_lane_packing_big_endian():
    c = K.encode_key(b"\x01\x02\x03\x04", width=8)
    assert c.dtype == np.uint32
    assert c[0] == 0x01020304
    assert c[1] == 0x00000004  # length byte in last position
