"""Counters/metrics (runtime/stats.py) + the enriched status document:
role CounterCollections fill, periodic metric trace events fire, and the
CC's status doc aggregates qos/data sections from worker metrics pulls
(flow/Stats.h + Status.actor.cpp analogs)."""

from foundationdb_tpu.client import management
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.runtime.stats import Counter, CounterCollection, LatencySample
from foundationdb_tpu.runtime.trace import TraceLog, set_trace_log, trace_log
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster


def test_counter_interval_and_rate():
    c = Counter("ops")
    c.add(5)
    c += 3
    assert c.value == 8
    assert c.interval_delta == 8
    c.reset_interval()
    assert c.interval_delta == 0
    c.add(2)
    assert c.value == 10 and c.interval_delta == 2


def test_latency_sample_percentiles():
    s = LatencySample("lat", cap=100)
    for i in range(100):
        s.add(i / 1000.0)
    assert abs(s.percentile(0.5) - 0.050) < 0.005
    assert abs(s.percentile(0.95) - 0.095) < 0.005
    snap = s.snapshot()
    assert snap["count"] == 100 and snap["p99"] >= snap["p50"]


def test_latency_sample_sorts_once_per_snapshot():
    s = LatencySample("lat", cap=16)
    for v in (5.0, 1.0, 3.0):
        s.add(v)
    assert s._sorted is None  # dirty until first read
    snap = s.snapshot()
    assert snap["p50"] == 3.0
    cached = s._sorted
    assert cached is not None
    s.percentile(0.5)
    assert s._sorted is cached  # reads share one sorted buffer
    s.add(0.5)
    assert s._sorted is None  # adds invalidate the cache
    assert s.percentile(0.0) == 0.5


def test_latency_sample_reservoir_bounded():
    s = LatencySample("lat", cap=64)
    for i in range(10000):
        s.add(1.0)
    assert len(s._buf) == 64 and s.count == 10000
    assert s.percentile(0.5) == 1.0


def test_collection_snapshot_and_gauge():
    cc = CounterCollection("Test", "t1")
    cc.counter("a").add(7)
    cc.gauge("g", lambda: 42)
    snap = cc.snapshot(elapsed=2.0)
    assert snap["a"] == 7 and snap["a_hz"] == 3.5 and snap["g"] == 42


def test_cluster_metrics_and_status_doc():
    sim = Sim(seed=11)
    sim.activate()
    log = TraceLog()
    set_trace_log(log)
    try:
        cluster = DynamicCluster(
            sim,
            ClusterConfig(n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=2),
            n_coordinators=1,
        )
        db = Database.from_coordinators(sim, cluster.coordinators)

        async def body():
            for i in range(30):

                async def w(tr, i=i):
                    tr.set(b"k%02d" % i, b"v")

                await db.run(w)

            async def r(tr):
                return await tr.get(b"k00")

            assert await db.run(r) == b"v"
            # let metric trace loops fire at least once
            await delay(6.0)
            doc = await management.get_status(cluster.coordinators, db.client)
            return doc

        doc = sim.run_until_done(spawn(body()), 600.0)
        # proxy counters flowed
        qos = doc["qos"]
        assert qos["transactions_committed_total"] >= 30
        # storage data section present and sane
        assert doc["data"]["max_storage_version"] > 0
        assert doc["data"]["min_durable_version"] >= 0
        # ratekeeper rate surfaced
        assert qos.get("released_transactions_per_second", 0) > 0
        # per-worker metrics include role snapshots with latency samples.
        # Aggregate across proxies: a stale proxy role from a fenced
        # first-recovery master may exist with zero traffic.
        commit_in = commit_lat = 0
        p50 = 0.0
        storage_mutations = 0
        for w in doc["cluster"]["workers"].values():
            for snap in (w.get("metrics") or {}).values():
                if snap.get("kind") == "proxy":
                    commit_in += snap["txnCommitIn"]
                    commit_lat += snap["commitLatency"]["count"]
                    p50 = max(p50, snap["commitLatency"]["p50"])
                if snap.get("kind") == "storage":
                    storage_mutations += snap["mutations"]
        assert commit_in >= 30 and commit_lat >= 30 and p50 > 0
        assert storage_mutations > 0
        # periodic metric trace events fired
        assert log.of_type("ProxyMetrics")
    finally:
        set_trace_log(TraceLog())
