"""Perf workloads (ReadWrite/BulkLoad/Throughput) — correctness smoke.

The measured numbers come from tools/perf.py runs; these tests pin the
machinery: workloads complete, counters balance, reports carry sane
values, and the duration-bounded Throughput variant terminates.
"""

from foundationdb_tpu.client import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.runtime.rng import DeterministicRandom
from foundationdb_tpu.server import Cluster, ClusterConfig
from foundationdb_tpu.workloads import run_workloads
from foundationdb_tpu.workloads.readwrite import (
    BulkLoadWorkload,
    ReadWriteWorkload,
    ThroughputWorkload,
)


def make_db(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(**cfg))
    db = Database(sim, cluster.proxy_addrs)
    return sim, cluster, db


def drive(sim, w, limit=600.0):
    async def go():
        await run_workloads([w])
        return True

    assert sim.run_until_done(spawn(go()), limit)


def test_readwrite_90_10_counters_balance():
    sim, _c, db = make_db(seed=5)
    w = ReadWriteWorkload(
        db,
        DeterministicRandom(5),
        actors=5,
        txns_per_actor=8,
        reads_per_txn=9,
        writes_per_txn=1,
        keyspace=500,
    )
    drive(sim, w)
    rep = w.rec.report()
    assert rep["commits"] == 5 * 8
    assert rep["reads"] == rep["commits"] * 9
    assert rep["writes"] == rep["commits"] * 1
    assert rep["ops"] == rep["reads"] + rep["writes"]
    assert rep["ops_per_s"] > 0
    assert rep["read_p50_ms"] > 0
    assert rep["commit_p50_ms"] > 0


def test_bulkload_ingests_all_keys():
    sim, _c, db = make_db(seed=6)
    w = BulkLoadWorkload(
        db, DeterministicRandom(6), actors=3, txns_per_actor=5, keys_per_txn=20
    )
    drive(sim, w)
    rep = w.rec.report()
    assert rep["writes"] == 3 * 5 * 20

    async def count():
        tr = db.transaction()
        rows = await tr.get_range(b"bulk/", b"bulk0", limit=10_000)
        return len(rows)

    assert sim.run_until_done(spawn(count()), 60.0) == 3 * 5 * 20


def test_throughput_duration_bounded():
    sim, _c, db = make_db(seed=7)
    w = ThroughputWorkload(
        db,
        DeterministicRandom(7),
        duration=1.0,
        ramp=0.2,
        actors=4,
        reads_per_txn=2,
        writes_per_txn=2,
        keyspace=200,
    )
    drive(sim, w)
    rep = w.rec.report()
    # steady-state only: the ramp's transactions were reset out
    assert rep["commits"] > 0
    assert rep["ops"] == rep["reads"] + rep["writes"]
