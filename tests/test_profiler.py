"""Run-loop profiler (ISSUE 9): slow-task attribution, per-priority
starvation metrics, and hot-actor flame evidence — on both loop
personalities.

Covers the acceptance battery: deterministic per-actor step counts under
a fixed sim seed, exactly one attributed SlowTask for an injected 100 ms
blocking callback on the real loop, starvation bands visible through
`process.metrics` and the status document on both transports, the
blocking actor topping `cli top`, a non-empty folded-stack artifact from
`cli profile`, the <3% enabled-profiler overhead gate, and the two loop
bugfix regressions (stop_when after IO dispatch; selector closed on
loop.close)."""

import json
import socket
import time

from foundationdb_tpu.client import management
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Endpoint, Sim
from foundationdb_tpu.net.tcp import RealWorld
from foundationdb_tpu.runtime import profiler as profiler_mod
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.loop import RealLoop, set_loop
from foundationdb_tpu.runtime.trace import TraceLog, set_trace_log, trace_log
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.tools import trace_analyze as ta
from foundationdb_tpu.tools.cli import FdbCli


def _fresh_log():
    log = TraceLog()
    set_trace_log(log)
    return log


def free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _spin(seconds):
    """Burn CPU inside ONE callback step — the loop-blocking injection."""
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        pass


# ---------------------------------------------------------------------------
# Sim personality: deterministic attribution


def _sim_run_steps(seed):
    """One small sim-cluster run; returns {actor name: steps}."""
    _fresh_log()
    sim = Sim(seed=seed)
    sim.activate()
    cluster = DynamicCluster(
        sim, ClusterConfig(n_proxies=1, n_resolvers=1, n_storage=2),
        n_coordinators=1,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def go():
        for i in range(10):

            async def w(tr, i=i):
                await tr.get(b"prof%02d" % i)
                tr.set(b"prof%02d" % i, b"v")

            await db.run(w)
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    prof = sim.loop.profiler
    assert prof is not None and prof.snapshot()["personality"] == "sim"
    return {name: a.steps for name, a in prof.actors.items()}


def test_same_seed_sim_runs_have_identical_hot_actor_step_counts():
    """The sim personality's attribution is DETERMINISTIC: two same-seed
    runs execute the exact same callbacks under the exact same owners, so
    the per-actor step counters match exactly (wall-measured busy seconds
    are evidence, not sim state, and are free to differ)."""
    a = _sim_run_steps(seed=29)
    b = _sim_run_steps(seed=29)
    assert a == b
    assert sum(a.values()) > 100  # a real cluster ran, not a stub
    c = _sim_run_steps(seed=30)
    assert c != a  # different seed, different schedule (sanity)


def test_sim_blocking_actor_tops_cli_top_profile_and_status(request):
    """Acceptance, sim personality: a deliberately loop-blocking actor is
    attributed as the hottest actor in `cli top`, `cli profile` produces a
    non-empty folded-stack artifact, per-priority starvation shows in
    `cli status`, and the `process.metrics` endpoint serves the bands.
    (SlowTask trace events are the REAL personality's — the sim loop emits
    no wall-dependent trace events so same-seed runs stay byte-identical;
    test_tcp_* below covers that leg.)"""
    log = _fresh_log()
    sim = Sim(seed=37)
    sim.activate()
    # keep the run JAX-free: the storage index's lazy first compile is a
    # genuine ~200 ms loop-blocking step (the profiler attributes it to
    # StorageServer handlers — ROADMAP item 2's evidence), but THIS test
    # needs the injected hog to be the undisputed top
    sim.knobs.STORAGE_TPU_INDEX = False
    cluster = DynamicCluster(
        sim, ClusterConfig(n_proxies=1, n_resolvers=1, n_storage=2),
        n_coordinators=1,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    cli = FdbCli(db, cluster.coordinators)

    async def loop_hog():
        await delay(0.5)
        _spin(0.12)
        return True

    async def body():
        for i in range(5):

            async def w(tr, i=i):
                tr.set(b"top%02d" % i, b"v")

            await db.run(w)
        start = await cli.execute("profile start 250")
        assert "sampling loop thread" in start
        await db.client.spawn(loop_hog())
        folded = await cli.execute("profile stop")
        await delay(6.0)  # metrics trace loops fire (RunLoopMetrics)
        top = await cli.execute("top")
        status = await cli.execute("status")
        direct = {}
        for addr, p in sim.processes.items():
            if getattr(p, "worker", None) is not None and p.alive:
                direct[addr] = await db.client.request(
                    Endpoint(addr, "process.metrics"), None
                )
        return folded, top, status, direct

    folded, top, status, direct = sim.run_until_done(spawn(body()), 900.0)

    # the blocking actor tops `cli top` (first data row)
    top_lines = top.splitlines()
    assert "hot actors by run-loop busy time" in top_lines[0]
    assert "loop_hog" in top_lines[2], top
    # folded-stack artifact: non-empty, collapsed-stack format, and the
    # blocking actor's frame is in the hottest stack
    assert folded.strip() and not folded.startswith("(no samples")
    first = folded.splitlines()[0]
    assert ";" in first and first.rsplit(" ", 1)[1].isdigit()
    assert "loop_hog" in folded
    # per-priority starvation latency in `cli status`
    assert "Run loop:" in status
    assert "starvation [default]" in status, status
    assert "starvation [max]" in status  # cancel/priority-MAX traffic exists
    # process.metrics endpoint: bands + starvation counts on the wire
    assert direct
    for snap in direct.values():
        assert snap["personality"] == "sim"
        assert snap["bands"]["default"]["starvation"]["count"] > 0
        assert snap["steps"] > 0
        assert any(a["name"].endswith("loop_hog") for a in snap["hot_actors"])
    # periodic RunLoopMetrics trace events rode the normal metrics cadence
    assert any(e["Type"] == "RunLoopMetrics" for e in log.events)


def test_profiler_overhead_under_three_percent_on_smoke_readwrite():
    """Overhead gate: the enabled profiler costs <3% ops/s on the smoke
    readwrite shape (tools/perf's correctness-smoke configuration). Wall
    time of identical same-seed sim runs, best-of-3 interleaved to shed
    scheduler noise."""
    from foundationdb_tpu.runtime.rng import DeterministicRandom
    from foundationdb_tpu.server import Cluster
    from foundationdb_tpu.workloads import run_workloads
    from foundationdb_tpu.workloads.readwrite import ReadWriteWorkload

    def one_run(enabled):
        _fresh_log()
        sim = Sim(seed=3, knobs=Knobs(RUN_LOOP_PROFILER=enabled))
        sim.activate()
        cluster = Cluster(sim, ClusterConfig(n_proxies=1, n_resolvers=1))
        db = Database(sim, cluster.proxy_addrs)
        w = ReadWriteWorkload(
            db,
            DeterministicRandom(3),
            actors=5,
            txns_per_actor=8,
            reads_per_txn=9,
            writes_per_txn=1,
            keyspace=500,
        )

        async def go():
            await run_workloads([w])
            return True

        t0 = time.perf_counter()
        assert sim.run_until_done(spawn(go()), 600.0)
        return time.perf_counter() - t0

    on, off = [], []
    for _ in range(3):
        off.append(one_run(False))
        on.append(one_run(True))
    # best-of-N absorbs GC/scheduler hiccups; a small absolute grace keeps
    # sub-second runs from flaking on timer granularity
    assert min(on) <= min(off) * 1.03 + 0.02, (on, off)


# ---------------------------------------------------------------------------
# Real personality: SlowTask attribution + the loop bugfix regressions


def test_realloop_blocking_callback_emits_exactly_one_attributed_slowtask():
    log = _fresh_log()
    loop = RealLoop(seed=41)
    set_loop(loop)
    knobs = Knobs()  # RUN_LOOP_SLOW_TASK_MS=50 < the injected 100 ms
    prof = profiler_mod.install(loop, knobs=knobs, wall=True, ident="127.0.0.1:9")
    try:

        async def injected_blocker():
            await delay(0.01)
            _spin(0.1)  # ONE callback step holding the loop 100 ms
            return True

        fut = spawn(injected_blocker())
        loop.run(stop_when=fut.is_ready)
        assert fut.get() is True
        slow = [e for e in log.events if e["Type"] == "SlowTask"]
        assert len(slow) == 1, slow
        ev = slow[0]
        assert ev["Actor"].endswith("injected_blocker")
        assert ev["BusyMs"] >= 90.0
        assert ev["Band"] == "default" and ev["Priority"] == 7500
        assert ev["Machine"] == "127.0.0.1:9"
        # starvation: the blocked loop ran its OTHER due work late
        snap = prof.snapshot()
        assert snap["slow_tasks"] == 1
        hot = snap["hot_actors"][0]
        assert hot["name"].endswith("injected_blocker")
        assert hot["max_ms"] >= 90.0
    finally:
        set_loop(None)
        loop.close()


def test_realloop_stop_when_checked_after_io_dispatch():
    """Bugfix regression: a stop condition satisfied inside a selector IO
    callback ends run() promptly — never parked behind another select
    timeout or a further timer drain."""
    loop = RealLoop(seed=43)
    a, b = socket.socketpair()
    a.setblocking(False)
    b.setblocking(False)
    hit = []

    def on_readable():
        b.recv(16)
        hit.append(1)

    try:
        loop.add_reader(b, on_readable)
        # fire the byte once the loop is parked in select
        loop.call_at(loop.now() + 0.02, lambda: a.send(b"x"))
        t0 = time.perf_counter()
        loop.run(until=loop.now() + 5.0, stop_when=lambda: bool(hit))
        dt = time.perf_counter() - t0
        assert hit
        # 20 ms timer + IO dispatch; anything near the 50 ms select
        # timeout (or the 5 s until) means the stop check was skipped
        assert dt < 0.045, dt
    finally:
        loop.remove_reader(b)
        a.close()
        b.close()
        loop.close()


def test_realloop_close_closes_selector_idempotently():
    """Bugfix regression: close() releases the selector's epoll fd (tests
    create many loops; each used to leak one) and is safe to call twice
    (explicit close + __del__ backstop)."""
    loop = RealLoop(seed=44)
    sel = loop._selector
    assert sel.get_map() is not None
    loop.close()
    assert sel.get_map() is None  # selectors.BaseSelector.close() ran
    inner = getattr(sel, "_selector", None)  # the epoll object on Linux
    if inner is not None and hasattr(inner, "closed"):
        assert inner.closed
    loop.close()  # idempotent


# ---------------------------------------------------------------------------
# TCP personality end-to-end (real sockets, full cluster, one OS process)


def test_tcp_cluster_slowtask_top_profile_and_status(tmp_path):
    """Acceptance, TCP personality: coordinator + workers + client as
    RealWorlds over real sockets on one RealLoop. The blocking workload
    yields an attributed SlowTask in the trace, tops `cli top`, `cli
    profile` dumps folded stacks, per-priority starvation shows in `cli
    status`, and `process.metrics` answers over the wire with
    personality="real"."""
    log = _fresh_log()
    knobs = Knobs()
    loop = RealLoop(seed=47)
    cport, w1, w2 = free_ports(3)
    coord = f"127.0.0.1:{cport}"
    worlds = []
    try:
        cw = RealWorld(coord, knobs=knobs, data_dir=str(tmp_path / "c"), loop=loop)
        cw.activate()
        from foundationdb_tpu.server.coordination import CoordinatorServer
        from foundationdb_tpu.server.worker import Worker

        CoordinatorServer(disk=cw.disk("coordination")).register(cw.node)
        worlds.append(cw)
        cfg = dict(n_storage=1, replication=1, n_tlogs=1, n_proxies=1, n_resolvers=1)
        for i, port in enumerate((w1, w2)):
            ww = RealWorld(
                f"127.0.0.1:{port}",
                knobs=knobs,
                data_dir=str(tmp_path / f"w{i}"),
                loop=loop,
            )
            Worker(
                ww.node, [coord], process_class="unset",
                initial_config=cfg, knobs=knobs,
            ).start()
            worlds.append(ww)
        client = RealWorld(
            "127.0.0.1:0", knobs=knobs, data_dir=str(tmp_path / "cl"), loop=loop
        )
        worlds.append(client)
        db = Database.from_coordinators(client, [coord])
        cli = FdbCli(db, [coord])

        async def tcp_loop_hog():
            await delay(0.05)
            _spin(0.1)
            return True

        async def body():
            async def w(tr):
                tr.set(b"tcp-prof", b"v")

            await db.run(w)  # cluster formed end-to-end
            start = await cli.execute("profile start 250")
            assert "sampling loop thread" in start
            await client.node.spawn(tcp_loop_hog())
            folded = await cli.execute("profile stop")
            top = await cli.execute("top")
            status = await cli.execute("status")
            doc = await management.get_status([coord], db.client)
            worker_addrs = list((doc.get("cluster") or {}).get("workers") or {})
            assert worker_addrs
            direct = await db.client.request(
                Endpoint(worker_addrs[0], "process.metrics"), None
            )
            return folded, top, status, doc, direct

        folded, top, status, doc, direct = client.run_until_done(
            spawn(body()), 120.0
        )

        # SlowTask: exactly one, attributed to the blocking actor
        slow = [e for e in log.events if e["Type"] == "SlowTask"]
        assert len(slow) == 1, slow
        assert slow[0]["Actor"].endswith("tcp_loop_hog")
        assert slow[0]["BusyMs"] >= 90.0
        # ... and the trace_analyze table reads the same from the log
        st = ta.slow_tasks(log.events)
        assert st["events"] == 1
        assert st["actors"][0]["actor"].endswith("tcp_loop_hog")
        # blocking actor tops cli top
        assert "tcp_loop_hog" in top.splitlines()[2], top
        # folded stacks captured the spin
        assert folded.strip() and "tcp_loop_hog" in folded
        # per-priority starvation visible in cli status over TCP
        assert "Run loop:" in status and "slow tasks" in status
        assert "starvation [default]" in status, status
        # status document run_loop section + direct endpoint agree
        rl = doc["run_loop"]
        assert rl and all(s["personality"] == "real" for s in rl.values())
        assert direct["personality"] == "real"
        assert direct["bands"]["default"]["starvation"]["count"] > 0
        assert direct["select_seconds"]["count"] > 0  # select latency sampled
    finally:
        for w in worlds:
            w.close()
        set_loop(None)
        loop.close()


# ---------------------------------------------------------------------------
# trace_analyze --slow-tasks (multi-file merge)


def test_trace_analyze_slow_tasks_table_merges_per_server_files(tmp_path):
    def slow(actor, ms, machine, t):
        return {
            "Severity": "Warn", "Type": "SlowTask", "Time": t,
            "Machine": machine, "Actor": actor, "BusyMs": ms,
            "Priority": 7500, "Band": "default",
        }

    f1, f2 = tmp_path / "s1.jsonl", tmp_path / "s2.jsonl"
    f1.write_text(
        "\n".join(
            json.dumps(e)
            for e in [
                slow("Proxy.commit_batch", 120.0, "127.0.0.1:1", 1.0),
                {"Severity": "Info", "Type": "Span", "Time": 1.5},
                slow("Proxy.commit_batch", 80.0, "127.0.0.1:1", 2.0),
            ]
        )
        + "\n"
    )
    f2.write_text(
        json.dumps(slow("Resolver.resolve", 60.0, "127.0.0.1:2", 1.2)) + "\n"
    )
    events = ta.load_events([str(f1), str(f2)])
    st = ta.slow_tasks(events)
    assert st["events"] == 3
    assert st["actors"][0]["actor"] == "Proxy.commit_batch"  # 200 ms total
    assert st["actors"][0]["count"] == 2
    assert st["actors"][0]["max_ms"] == 120.0
    assert st["actors"][1]["machines"] == ["127.0.0.1:2"]
    out = ta.format_slow_tasks(st)
    assert "Proxy.commit_batch" in out and "Resolver.resolve" in out
    assert "no SlowTask" in ta.format_slow_tasks(ta.slow_tasks([]))


# ---------------------------------------------------------------------------
# flowlint: the worker process.metrics registration rule


def _lint_worker(tmp_path, worker_src):
    from foundationdb_tpu.tools.flowlint import lint

    pkg = tmp_path / "foundationdb_tpu" / "server"
    pkg.mkdir(parents=True)
    (pkg / "worker.py").write_text(worker_src)
    config = {
        "include": ["foundationdb_tpu"],
        "exclude": [],
        "sim_scope": [],
        "host_only": {},
        "baseline": "baseline.json",
        "worker_module": "foundationdb_tpu/server/worker.py",
        "role_exempt": [],
        "span_roles": [],
        "process_metrics_endpoint": "process.metrics",
    }
    return lint(root=tmp_path, config=config)


def test_flowlint_worker_without_process_metrics_endpoint_flagged(tmp_path):
    res = _lint_worker(
        tmp_path,
        "class Worker:\n"
        "    def start(self, process):\n"
        '        process.register("worker.metrics", self._rm)\n',
    )
    assert any(
        f.rule == "reg-role-metrics" and f.detail == "worker-process-metrics"
        for f in res.failing
    ), [f.format() for f in res.failing]


def test_flowlint_worker_with_process_metrics_endpoint_clean(tmp_path):
    res = _lint_worker(
        tmp_path,
        "class Worker:\n"
        "    def start(self, process):\n"
        '        process.register("worker.metrics", self._rm)\n'
        '        process.register("process.metrics", self._pm)\n',
    )
    assert not res.failing, [f.format() for f in res.failing]
