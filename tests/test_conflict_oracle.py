"""Oracle ConflictSet semantics: the ground-truth behaviors every backend
must reproduce (reference semantics: fdbserver/SkipList.cpp detectConflicts)."""

from foundationdb_tpu.conflict.api import (
    CommitTransaction,
    ConflictBatch,
    Verdict,
    new_conflict_set,
)


def tx(snapshot, reads=(), writes=()):
    return CommitTransaction(
        read_snapshot=snapshot,
        read_conflict_ranges=list(reads),
        write_conflict_ranges=list(writes),
    )


def detect(cs, txs, now, oldest):
    b = ConflictBatch(cs)
    for t in txs:
        b.add_transaction(t)
    return b.detect_conflicts(now, oldest)


def test_basic_history_conflict():
    cs = new_conflict_set("oracle")
    # batch 1: blind write to [a, b) at version 10
    assert detect(cs, [tx(5, writes=[(b"a", b"b")])], 10, 0) == [Verdict.COMMITTED]
    # read at snapshot 9 overlapping the write → conflict; snapshot 10 → fine
    assert detect(cs, [tx(9, reads=[(b"aa", b"ab")])], 11, 0) == [Verdict.CONFLICT]
    assert detect(cs, [tx(10, reads=[(b"aa", b"ab")])], 12, 0) == [Verdict.COMMITTED]
    # non-overlapping read → fine
    assert detect(cs, [tx(9, reads=[(b"b", b"c")])], 13, 0) == [Verdict.COMMITTED]


def test_point_write_point_read():
    cs = new_conflict_set("oracle")
    detect(cs, [tx(0, writes=[(b"k", b"k\x00")])], 5, 0)
    assert detect(cs, [tx(4, reads=[(b"k", b"k\x00")])], 6, 0) == [Verdict.CONFLICT]
    assert detect(cs, [tx(4, reads=[(b"k\x00", b"k\x01")])], 7, 0) == [Verdict.COMMITTED]


def test_too_old():
    cs = new_conflict_set("oracle")
    detect(cs, [tx(0, writes=[(b"a", b"b")])], 10, 8)  # advances oldest to 8
    assert detect(cs, [tx(5, reads=[(b"x", b"y")])], 11, 8) == [Verdict.TOO_OLD]
    # blind writes (no read ranges) are never too old (SkipList.cpp:989)
    assert detect(cs, [tx(5, writes=[(b"x", b"y")])], 12, 8) == [Verdict.COMMITTED]


def test_intra_batch_order_dependence():
    cs = new_conflict_set("oracle")
    # t0 writes [a,b); t1 reads [a,b) in the same batch → t1 conflicts
    out = detect(
        cs,
        [tx(0, writes=[(b"a", b"b")]), tx(0, reads=[(b"a", b"b")])],
        5,
        0,
    )
    assert out == [Verdict.COMMITTED, Verdict.CONFLICT]

    cs2 = new_conflict_set("oracle")
    # reversed order: reader first → both commit
    out = detect(
        cs2,
        [tx(0, reads=[(b"a", b"b")]), tx(0, writes=[(b"a", b"b")])],
        5,
        0,
    )
    assert out == [Verdict.COMMITTED, Verdict.COMMITTED]


def test_intra_batch_conflicted_writer_does_not_poison():
    cs = new_conflict_set("oracle")
    detect(cs, [tx(0, writes=[(b"a", b"b")])], 10, 0)
    # t0 conflicts on history; its write must NOT be merged nor count
    # against t1's intra-batch check (SkipList.cpp:1150 only sets committed)
    out = detect(
        cs,
        [
            tx(5, reads=[(b"a", b"a\x00")], writes=[(b"q", b"r")]),
            tx(10, reads=[(b"q", b"r")]),
        ],
        11,
        0,
    )
    assert out == [Verdict.CONFLICT, Verdict.COMMITTED]
    # and [q, r) never entered history
    assert detect(cs, [tx(10, reads=[(b"q", b"r")])], 12, 0) == [Verdict.COMMITTED]


def test_gc_forgets_old_versions():
    cs = new_conflict_set("oracle")
    detect(cs, [tx(0, writes=[(b"a", b"b")])], 10, 0)
    # advance oldest beyond 10 → history below is forgotten
    detect(cs, [tx(11, writes=[(b"z", b"zz")])], 20, 15)
    # snapshot 14 < oldest 15 → TOO_OLD (not conflict)
    assert detect(cs, [tx(14, reads=[(b"a", b"b")])], 21, 15) == [Verdict.TOO_OLD]
    # snapshot >= oldest sees no conflict from the forgotten write
    assert detect(cs, [tx(16, reads=[(b"a", b"b")])], 22, 15) == [Verdict.COMMITTED]


def test_adjacent_ranges_do_not_conflict():
    cs = new_conflict_set("oracle")
    detect(cs, [tx(0, writes=[(b"b", b"c")])], 5, 0)
    assert detect(cs, [tx(0, reads=[(b"a", b"b")])], 6, 0) == [Verdict.COMMITTED]
    assert detect(cs, [tx(0, reads=[(b"c", b"d")])], 7, 0) == [Verdict.COMMITTED]


def test_empty_transaction_commits():
    cs = new_conflict_set("oracle")
    assert detect(cs, [tx(0)], 5, 0) == [Verdict.COMMITTED]


def test_overlapping_writes_merge_max_version():
    cs = new_conflict_set("oracle")
    detect(cs, [tx(0, writes=[(b"a", b"m")])], 10, 0)
    detect(cs, [tx(10, writes=[(b"g", b"z")])], 20, 0)
    # overlap region [g, m) now at version 20
    assert detect(cs, [tx(15, reads=[(b"h", b"i")])], 21, 0) == [Verdict.CONFLICT]
    # [a, g) still at version 10
    assert detect(cs, [tx(15, reads=[(b"b", b"c")])], 22, 0) == [Verdict.COMMITTED]
