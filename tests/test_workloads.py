"""Workload specs in simulation — the stage-4 milestone gate (SURVEY.md §7):
Cycle + WriteDuringRead + ConflictRange(oracle) pass in sim, composed with
fault injection, across seeds and cluster shapes (the tests/fast/ spec
style: correctness workloads + clogging in one run)."""

import pytest

from foundationdb_tpu.client import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.server import Cluster, ClusterConfig
from foundationdb_tpu.workloads import (
    ConflictRangeWorkload,
    CycleWorkload,
    RandomCloggingWorkload,
    SidebandWorkload,
    WriteDuringReadWorkload,
    run_workloads,
)


def make_db(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(**cfg))
    db = Database(sim, cluster.proxy_addrs)
    return sim, cluster, db


def run_spec(sim, workloads, limit=600.0):
    sim.run_until_done(spawn(run_workloads(workloads)), limit)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_cycle(seed):
    sim, cluster, db = make_db(seed=seed)
    w = CycleWorkload(db, sim.loop.random.fork(), nodes=15, transactions=40)
    run_spec(sim, [w])


def test_cycle_with_clogging():
    sim, cluster, db = make_db(seed=3, n_proxies=2, n_storage=2, replication=2)
    rng = sim.loop.random
    run_spec(
        sim,
        [
            CycleWorkload(db, rng.fork(), nodes=12, transactions=30),
            RandomCloggingWorkload(db, rng.fork(), duration=3.0),
        ],
    )


@pytest.mark.parametrize("seed", [0, 1])
def test_write_during_read(seed):
    sim, cluster, db = make_db(seed=seed)
    w = WriteDuringReadWorkload(db, sim.loop.random.fork(), rounds=8)
    run_spec(sim, [w])


@pytest.mark.parametrize("seed", [0, 1])
def test_conflict_range_oracle(seed):
    sim, cluster, db = make_db(seed=seed, n_resolvers=2)
    w = ConflictRangeWorkload(db, sim.loop.random.fork(), rounds=25)
    run_spec(sim, [w])


def test_sideband_causality():
    sim, cluster, db = make_db(seed=4, n_proxies=3)
    db2 = Database(sim, cluster.proxy_addrs, client_addr="client2")
    # checker reads through a different client+proxy mix than the mutator
    w = SidebandWorkload(db, sim.loop.random.fork(), messages=20, checker_db=db2)
    run_spec(sim, [w, RandomCloggingWorkload(db, sim.loop.random.fork(), duration=2.0)])


def test_combined_spec_determinism():
    """The same seed replays to the same virtual end-time — the
    reproducibility property the whole test strategy rests on (§4)."""

    def one(seed):
        sim, cluster, db = make_db(seed=seed, n_proxies=2, n_resolvers=2)
        rng = sim.loop.random
        cycle = CycleWorkload(db, rng.fork(), nodes=10, transactions=20)
        run_spec(
            sim,
            [cycle, RandomCloggingWorkload(db, rng.fork(), duration=2.0)],
        )
        return sim.loop.now(), cycle.retries

    assert one(7) == one(7)
    # and different seeds genuinely explore different schedules
    assert one(7) != one(8)
