"""Simulator tests: deterministic multi-process echo, clogging, partitions,
kill/reboot — the SURVEY.md §7 stage-2 milestone (seeded repro of a
multi-process echo service)."""

from foundationdb_tpu.net.sim import BrokenPromise, Endpoint, Sim
from foundationdb_tpu.runtime.futures import delay, spawn


def build_echo_world(seed):
    sim = Sim(seed=seed)
    sim.activate()

    async def echo_boot(p):
        async def echo(payload):
            return ("echo", p.address, payload)

        p.register("echo", echo)

    for i in range(3):
        sim.new_process(f"server{i}", boot=echo_boot)
    client = sim.new_process("client")
    return sim, client


def test_echo_roundtrip():
    sim, client = build_echo_world(1)

    async def go():
        r = await sim.request("client", Endpoint("server0", "echo"), "hi")
        return r

    out = sim.run_until_done(spawn(go()))
    assert out == ("echo", "server0", "hi")
    assert sim.loop.now() > 0  # latency was simulated


def test_determinism_across_runs():
    def one_run(seed):
        sim, client = build_echo_world(seed)
        log = []

        async def go():
            for i in range(10):
                srv = f"server{sim.loop.random.random_int(0, 3)}"
                r = await sim.request("client", Endpoint(srv, "echo"), i)
                log.append((round(sim.loop.now(), 9), r))

        sim.run_until_done(spawn(go()))
        return log

    assert one_run(42) == one_run(42)
    assert one_run(42) != one_run(43)


def test_dead_process_breaks_promise():
    sim, client = build_echo_world(2)
    sim.kill_process("server1")

    async def go():
        try:
            await sim.request("client", Endpoint("server1", "echo"), "x")
            return "replied"
        except BrokenPromise:
            return "broken"

    assert sim.run_until_done(spawn(go())) == "broken"


def test_reboot_restores_service():
    sim, client = build_echo_world(3)
    sim.kill_process("server2", reboot_in=5.0)

    async def go():
        # during downtime: broken
        try:
            await sim.request("client", Endpoint("server2", "echo"), 1)
            first = "replied"
        except BrokenPromise:
            first = "broken"
        await delay(10.0)
        r = await sim.request("client", Endpoint("server2", "echo"), 2)
        return first, r

    first, r = sim.run_until_done(spawn(go()))
    assert first == "broken"
    assert r == ("echo", "server2", 2)
    assert sim.processes["server2"].reboots == 1


def test_clog_delays_delivery():
    sim, client = build_echo_world(4)
    sim.clog_pair("client", "server0", 3.0)

    async def go():
        t0 = sim.loop.now()
        await sim.request("client", Endpoint("server0", "echo"), "x")
        return sim.loop.now() - t0

    dt = sim.run_until_done(spawn(go()))
    assert dt >= 3.0


def test_partition_drops_traffic_until_heal():
    sim, client = build_echo_world(5)
    sim.partition("client", "server0")

    async def go():
        f = sim.request("client", Endpoint("server0", "echo"), "x")
        await delay(5.0)
        stuck = not f.is_ready()
        sim.heal()
        r = await sim.request("client", Endpoint("server0", "echo"), "y")
        return stuck, r

    stuck, r = sim.run_until_done(spawn(go()))
    assert stuck
    assert r == ("echo", "server0", "y")


def test_kill_cancels_in_flight_work():
    sim = Sim(seed=6)
    sim.activate()
    witness = []

    async def slow_boot(p):
        async def slow(payload):
            await delay(100.0)
            witness.append("finished")  # must never happen
            return "done"

        p.register("slow", slow)

    sim.new_process("victim", boot=slow_boot)
    sim.new_process("client")

    async def go():
        f = sim.request("client", Endpoint("victim", "slow"), None)
        await delay(1.0)
        sim.kill_process("victim")
        await delay(200.0)
        return f.is_ready()

    replied = sim.run_until_done(spawn(go()))
    assert witness == []
    # the kill breaks the in-flight reply promise (it resolves, with an error)
    assert replied
