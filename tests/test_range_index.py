"""TPU batched range-index: correctness vs host-side bisect, and the
storage batch_get read path using it."""

import bisect
import random

from foundationdb_tpu.ops.range_index import TpuRangeIndex


def test_batch_lookup_matches_bisect():
    rnd = random.Random(3)
    keys = sorted({bytes(rnd.randrange(256) for _ in range(rnd.randrange(1, 20)))
                   for _ in range(3000)})
    idx = TpuRangeIndex(keys)
    queries = [rnd.choice(keys) if rnd.random() < 0.5
               else bytes(rnd.randrange(256) for _ in range(rnd.randrange(1, 20)))
               for _ in range(500)]
    rows, found = idx.batch_lookup(queries)
    for q, r, f in zip(queries, rows, found):
        i = bisect.bisect_left(keys, q)
        expect_found = i < len(keys) and keys[i] == q
        assert bool(f) == expect_found, q
        if expect_found:
            assert keys[int(r)] == q


def test_batch_range_matches_bisect():
    rnd = random.Random(4)
    keys = sorted({b"%06d" % rnd.randrange(100000) for _ in range(2000)})
    idx = TpuRangeIndex(keys)
    begins, ends = [], []
    for _ in range(200):
        a = b"%06d" % rnd.randrange(100000)
        b = b"%06d" % rnd.randrange(100000)
        if a > b:
            a, b = b, a
        begins.append(a)
        ends.append(b)
    los, his = idx.batch_range(begins, ends)
    for a, b, lo, hi in zip(begins, ends, los, his):
        assert int(lo) == bisect.bisect_left(keys, a)
        assert int(hi) == bisect.bisect_left(keys, b)


def test_storage_batch_get_endpoint():
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.net.sim import Sim
    from foundationdb_tpu.runtime.futures import delay, spawn
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
    from foundationdb_tpu.server.interfaces import Tokens

    knobs = Knobs(
        STORAGE_TPU_INDEX=True,
        MAX_READ_TRANSACTION_LIFE_VERSIONS=1_000_000,  # fast durability
    )
    sim = Sim(seed=71, knobs=knobs)
    sim.activate()
    cluster = DynamicCluster(sim, ClusterConfig(n_storage=1, n_tlogs=1))
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def body():
        async def fill(tr):
            for i in range(200):
                tr.set(b"bk%04d" % i, b"v%d" % i)

        await db.run(fill)
        # wait for a durability advance so the engine + index populate
        await delay(3.0)

        async def grv(tr):
            await tr.get_read_version()
            return tr._read_version

        version = await db.run(grv)
        keys = [b"bk%04d" % i for i in range(0, 200, 7)] + [b"missing"]
        reply = await db._proxy_request(
            Tokens.GET_KEY_SERVERS,
            __import__(
                "foundationdb_tpu.server.interfaces", fromlist=["x"]
            ).GetKeyServersRequest(key=b"bk"),
        )
        from foundationdb_tpu.net.sim import Endpoint

        values = await db.client.request(
            Endpoint(reply.team[0], Tokens.BATCH_GET), (keys, version)
        )
        for k, v in zip(keys[:-1], values[:-1]):
            assert v == b"v%d" % int(k[2:]), (k, v)
        assert values[-1] is None

    sim.run_until_done(spawn(body()), 300.0)


def test_apply_delta_matches_rebuild():
    """Incremental delta-merge must equal a from-scratch rebuild, with
    only the delta re-encoded (adds incl. duplicates, removes incl.
    missing keys)."""
    import random

    from foundationdb_tpu.ops.range_index import TpuRangeIndex

    rnd = random.Random(5)
    keys = sorted({b"%08d" % rnd.randrange(10**8) for _ in range(2000)})
    idx = TpuRangeIndex(keys, width=16)
    live = set(keys)
    for _round in range(5):
        added = {
            b"%08d" % rnd.randrange(10**8) for _ in range(100)
        } - live
        removed = set(rnd.sample(sorted(live), 50))
        live = (live - removed) | added
        idx = idx.apply_delta(sorted(added), sorted(removed))
        ref = TpuRangeIndex(sorted(live), width=16)
        assert idx.n == ref.n, (_round, idx.n, ref.n)
        probe = rnd.sample(sorted(live), 40) + [b"%08d" % rnd.randrange(10**8) for _ in range(10)]
        ri, rf = ref.batch_lookup(probe)
        ii, f = idx.batch_lookup(probe)
        assert list(f) == list(rf), _round
        assert list(ii) == list(ri), _round
        lo1, hi1 = idx.batch_range([b"%08d" % 10**7], [b"%08d" % (5 * 10**7)])
        lo2, hi2 = ref.batch_range([b"%08d" % 10**7], [b"%08d" % (5 * 10**7)])
        assert (list(lo1), list(hi1)) == (list(lo2), list(hi2))


def test_storage_index_stays_synced_through_epochs():
    """With STORAGE_TPU_INDEX on, the delta-merged index stays in sync
    with the engine across several durability epochs (writes + clears),
    and getRange answers through it correctly."""
    from foundationdb_tpu.client import Database
    from foundationdb_tpu.net.sim import Sim
    from foundationdb_tpu.runtime.futures import delay as _delay, spawn
    from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster

    sim = Sim(seed=9)
    sim.activate()
    sim.knobs.STORAGE_DURABILITY_LAG = 0.05  # frequent epochs
    cluster = DynamicCluster(sim, ClusterConfig(), n_coordinators=1)
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def go():
        rows = {}
        for burst in range(4):
            async def put(tr, burst=burst):
                for i in range(30):
                    k = b"ix%02d%02d" % (burst, i)
                    tr.set(k, b"v%d" % burst)
                    rows[k] = b"v%d" % burst
                if burst:
                    tr.clear_range(
                        b"ix%02d00" % (burst - 1), b"ix%02d10" % (burst - 1)
                    )

            await db.run(put)
            if burst:
                for i in range(10):
                    rows.pop(b"ix%02d%02d" % (burst - 1, i), None)
            await _delay(6.0)  # cross the MVCC window: engine absorbs
        tr = db.transaction()
        got = dict(await tr.get_range(b"ix", b"iy", limit=1000))
        assert got == rows, (len(got), len(rows))
        checked = 0
        for _addr, p in sim.processes.items():
            w = getattr(p, "worker", None)
            if w is None or not p.alive:
                continue
            for h in w.roles.values():
                if h.kind != "storage":
                    continue
                ss = h.obj
                assert ss._range_index is not None
                assert ss._range_index.n == len(ss.engine._keys)
                checked += 1
        assert checked, "no storage role found"
        return True

    assert sim.run_until_done(spawn(go()), 600.0)


def test_long_key_code_collisions_range_correct():
    """Keys longer than the code width collapse to one truncated code;
    getRange through the index must still return exactly [begin, end) —
    colliding keys below begin filtered, collision runs past the hi
    bound extended (review finding)."""
    from foundationdb_tpu.client import Database
    from foundationdb_tpu.net.sim import Sim
    from foundationdb_tpu.runtime.futures import delay as _delay, spawn
    from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster

    sim = Sim(seed=10)
    sim.activate()
    sim.knobs.STORAGE_DURABILITY_LAG = 0.05
    cluster = DynamicCluster(sim, ClusterConfig(), n_coordinators=1)
    db = Database.from_coordinators(sim, cluster.coordinators)
    p = b"p" * 40  # well past the 32-byte code width

    async def go():
        suffixes = [b"a", b"b", b"c", b"d", b"e"]

        async def put(tr):
            for sfx in suffixes:
                tr.set(p + sfx, b"v" + sfx)

        await db.run(put)
        await _delay(6.0)  # absorb into the durable engine + index

        tr = db.transaction()
        # sub-range between colliding keys
        rows = await tr.get_range(p + b"b", p + b"d", limit=100)
        assert rows == [(p + b"b", b"vb"), (p + b"c", b"vc")], rows
        # begin at a colliding key: nothing below may leak in
        rows = await tr.get_range(p + b"c", p + b"z", limit=100)
        assert rows == [
            (p + b"c", b"vc"), (p + b"d", b"vd"), (p + b"e", b"ve")
        ], rows
        # clear one colliding key; the delta must remove exactly one row
        async def clr(tr2):
            tr2.clear(p + b"c")

        await db.run(clr)
        await _delay(6.0)
        tr = db.transaction()
        rows = await tr.get_range(p, p + b"z", limit=100)
        assert [k for k, _v in rows] == [
            p + b"a", p + b"b", p + b"d", p + b"e"
        ], rows
        return True

    assert sim.run_until_done(spawn(go()), 600.0)
