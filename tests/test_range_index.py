"""TPU batched range-index: correctness vs host-side bisect, and the
storage batch_get read path using it."""

import bisect
import random

from foundationdb_tpu.ops.range_index import TpuRangeIndex


def test_batch_lookup_matches_bisect():
    rnd = random.Random(3)
    keys = sorted({bytes(rnd.randrange(256) for _ in range(rnd.randrange(1, 20)))
                   for _ in range(3000)})
    idx = TpuRangeIndex(keys)
    queries = [rnd.choice(keys) if rnd.random() < 0.5
               else bytes(rnd.randrange(256) for _ in range(rnd.randrange(1, 20)))
               for _ in range(500)]
    rows, found = idx.batch_lookup(queries)
    for q, r, f in zip(queries, rows, found):
        i = bisect.bisect_left(keys, q)
        expect_found = i < len(keys) and keys[i] == q
        assert bool(f) == expect_found, q
        if expect_found:
            assert keys[int(r)] == q


def test_batch_range_matches_bisect():
    rnd = random.Random(4)
    keys = sorted({b"%06d" % rnd.randrange(100000) for _ in range(2000)})
    idx = TpuRangeIndex(keys)
    begins, ends = [], []
    for _ in range(200):
        a = b"%06d" % rnd.randrange(100000)
        b = b"%06d" % rnd.randrange(100000)
        if a > b:
            a, b = b, a
        begins.append(a)
        ends.append(b)
    los, his = idx.batch_range(begins, ends)
    for a, b, lo, hi in zip(begins, ends, los, his):
        assert int(lo) == bisect.bisect_left(keys, a)
        assert int(hi) == bisect.bisect_left(keys, b)


def test_storage_batch_get_endpoint():
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.net.sim import Sim
    from foundationdb_tpu.runtime.futures import delay, spawn
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
    from foundationdb_tpu.server.interfaces import Tokens

    knobs = Knobs(
        STORAGE_TPU_INDEX=True,
        MAX_READ_TRANSACTION_LIFE_VERSIONS=1_000_000,  # fast durability
    )
    sim = Sim(seed=71, knobs=knobs)
    sim.activate()
    cluster = DynamicCluster(sim, ClusterConfig(n_storage=1, n_tlogs=1))
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def body():
        async def fill(tr):
            for i in range(200):
                tr.set(b"bk%04d" % i, b"v%d" % i)

        await db.run(fill)
        # wait for a durability advance so the engine + index populate
        await delay(3.0)

        async def grv(tr):
            await tr.get_read_version()
            return tr._read_version

        version = await db.run(grv)
        keys = [b"bk%04d" % i for i in range(0, 200, 7)] + [b"missing"]
        reply = await db._proxy_request(
            Tokens.GET_KEY_SERVERS,
            __import__(
                "foundationdb_tpu.server.interfaces", fromlist=["x"]
            ).GetKeyServersRequest(key=b"bk"),
        )
        from foundationdb_tpu.net.sim import Endpoint

        values = await db.client.request(
            Endpoint(reply.team[0], Tokens.BATCH_GET), (keys, version)
        )
        for k, v in zip(keys[:-1], values[:-1]):
            assert v == b"v%d" % int(k[2:]), (k, v)
        assert values[-1] is None

    sim.run_until_done(spawn(body()), 300.0)
