"""Dynamic cluster formation + recovery from role death.

The analog of the reference's Attrition-style simulation specs: a cluster
built only from coordinators and workers must elect a cluster controller,
recruit a master, seed storage, and serve transactions; killing the
processes hosting the master / a proxy / a tlog must lead to a recovery
(SURVEY.md §3.3) after which data written before the kill is intact and new
writes succeed.
"""

import pytest

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster


def make(seed=0, n_coordinators=1, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = DynamicCluster(
        sim, ClusterConfig(**cfg), n_coordinators=n_coordinators
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    return sim, cluster, db


def run(sim, coro, limit=600.0):
    sim.activate()
    fut = spawn(coro)
    return sim.run_until_done(fut, limit)


def worker_hosting(sim, kind):
    """Addresses of worker processes currently hosting a role of `kind`."""
    out = []
    for addr, p in sim.processes.items():
        w = getattr(p, "worker", None)
        if w is not None and p.alive:
            if any(h.kind == kind for h in w.roles.values()):
                out.append(addr)
    return out


async def put(db, key, value):
    async def body(tr):
        tr.set(key, value)

    await db.run(body)


async def get(db, key):
    async def body(tr):
        return await tr.get(key)

    return await db.run(body)


def test_dynamic_cluster_forms_and_serves():
    sim, cluster, db = make(
        n_proxies=2, n_resolvers=2, n_tlogs=2, n_storage=2, replication=2,
        tlog_replication=2,
    )

    async def body():
        await put(db, b"hello", b"world")
        assert await get(db, b"hello") == b"world"
        # a second client sees it too (causal via GRV)
        db2 = Database.from_coordinators(
            sim, cluster.coordinators, client_addr="client2"
        )
        assert await get(db2, b"hello") == b"world"

    run(sim, body())


@pytest.mark.parametrize("victim_kind", ["master", "proxy", "tlog"])
def test_kill_role_recovers(victim_kind):
    sim, cluster, db = make(
        seed=7,
        n_proxies=2,
        n_resolvers=1,
        n_tlogs=2,
        n_storage=2,
        replication=2,
        tlog_replication=2,
    )

    async def body():
        for i in range(10):
            await put(db, b"pre%02d" % i, b"v%d" % i)

        victims = worker_hosting(sim, victim_kind)
        assert victims, f"no worker hosting {victim_kind}"
        sim.kill_process(victims[0])  # no reboot: stays dead

        # new writes must eventually succeed (retry loop rides recovery)
        for i in range(10):
            await put(db, b"post%02d" % i, b"v%d" % i)

        # and nothing acknowledged before the kill is lost
        for i in range(10):
            assert await get(db, b"pre%02d" % i) == b"v%d" % i, i
        for i in range(10):
            assert await get(db, b"post%02d" % i) == b"v%d" % i, i

    run(sim, body())


def test_repeated_master_kills():
    """Several recoveries in sequence; epochs chain correctly."""
    sim, cluster, db = make(
        seed=3,
        n_proxies=1,
        n_resolvers=1,
        n_tlogs=2,
        n_storage=2,
        replication=2,
        tlog_replication=2,
        n_coordinators=3,
    )

    async def body():
        for round_no in range(3):
            await put(db, b"k%d" % round_no, b"v%d" % round_no)
            victims = worker_hosting(sim, "master")
            if victims:
                sim.kill_process(victims[0])
            await delay(1.0)
        for round_no in range(3):
            assert await get(db, b"k%d" % round_no) == b"v%d" % round_no

    run(sim, body())


def test_cc_kill_reelects():
    """Killing the cluster controller's process triggers re-election and a
    fresh recovery; the database stays usable."""
    sim, cluster, db = make(
        seed=11,
        n_proxies=1,
        n_resolvers=1,
        n_tlogs=1,
        n_storage=1,
        n_coordinators=3,
    )

    async def body():
        await put(db, b"a", b"1")
        # the CC is whichever worker currently holds leadership
        cc_addrs = [
            addr
            for addr, p in sim.processes.items()
            if getattr(p, "worker", None) is not None
            and p.alive
            and p.worker._cc is not None
        ]
        assert cc_addrs
        sim.kill_process(cc_addrs[0])
        await put(db, b"b", b"2")
        assert await get(db, b"a") == b"1"
        assert await get(db, b"b") == b"2"

    run(sim, body())
