"""Transport v2 (ISSUE 14): frame-batched zero-copy wire path, loopback
for colocated worlds, batch dispatch, and the truncation fault site.

Layers covered:
 - wire buffers: O(n) total copying under bursts (the quadratic
   bytes-concat regression), super-frame encode/parse round-trips, CRC
   and truncation rejection, mixed legacy+super streams, byte-dribble
   reassembly;
 - real sockets: gen-7 vs gen-6 differential (same results, fewer
   frames), partial-flush truncation fault → typed retryable failure +
   reconnect (no wedged connection);
 - loopback: auto-selection for colocated worlds, codec parity (typed
   errors, unserializable payloads, no aliasing), close semantics;
 - sim parity: the transport-truncate chaos site fails exactly the
   faulted request with TransportTruncated (retryable), and the
   bindingtester oracle stays green with the batching knob both ways;
 - flowlint: the worker transport.metrics registration rule.
"""

import socket

import pytest

from foundationdb_tpu.net import wire
from foundationdb_tpu.net.sim import BrokenPromise, Endpoint, TransportTruncated
from foundationdb_tpu.net.tcp import RealWorld
from foundationdb_tpu.runtime.futures import settled, spawn, wait_for_all
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.loop import RealLoop, set_loop


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def make_world(loop, **knob_overrides):
    return RealWorld(
        f"127.0.0.1:{free_port()}", knobs=Knobs(**knob_overrides), loop=loop
    )


# ---------------------------------------------------------------------------
# wire buffers: linear copying under bursts


def test_send_buffer_linear_copying_on_1000_message_burst():
    """Regression for the legacy path's quadratic ``del outbuf[:n]`` churn:
    a 1,000-message burst drained in small chunks must move O(total)
    bytes, not O(n^2). bytes_moved counts every compaction relocation."""
    sb = wire.SendBuffer(watermark=1 << 12)
    total = 0
    for i in range(1000):
        frame = wire.encode_frame(b"m" * 100 + str(i).encode())
        sb.append(frame)
        total += len(frame)
    drained = 0
    while len(sb):
        n = min(137, len(sb))  # worst-case fragmented sends
        drained += n
        sb.consume(n)
    assert drained == total
    # linear bound: compaction may move each byte at most a constant
    # number of times (watermark amortization), never O(n) times
    assert sb.bytes_moved <= 2 * total, (sb.bytes_moved, total)


def test_recv_buffer_linear_copying_and_compaction():
    rb = wire.RecvBuffer(size=4096, watermark=1 << 12)
    payloads = [b"x" * 80 + str(i).encode() for i in range(1000)]
    stream = b"".join(wire.encode_frame(p) for p in payloads)
    got = []
    pos = 0
    while pos < len(stream):
        chunk = stream[pos : pos + 333]
        pos += len(chunk)
        rb.feed(chunk)
        views, consumed, _n = wire.parse_frames(rb)
        got.extend(bytes(v) for v in views)
        del views
        rb.consume(consumed)
    assert got == payloads
    assert rb.bytes_moved <= 2 * len(stream), (rb.bytes_moved, len(stream))


# ---------------------------------------------------------------------------
# super-frames


def test_super_frame_roundtrip_mixed_with_legacy():
    msgs1 = [b"alpha", b"b" * 500, b""]
    msgs2 = [b"gamma"]
    stream = (
        b"".join(wire.encode_super_frame(msgs1))
        + wire.encode_frame(b"legacy-single")
        + b"".join(wire.encode_super_frame(msgs2 * 3))
    )
    rb = wire.RecvBuffer()
    # dribble byte-by-byte: reassembly must never mis-frame
    got = []
    for i in range(len(stream)):
        rb.feed(stream[i : i + 1])
        views, consumed, _n = wire.parse_frames(rb)
        got.extend(bytes(v) for v in views)
        del views
        rb.consume(consumed)
    assert got == msgs1 + [b"legacy-single"] + msgs2 * 3


def test_super_frame_checksum_and_truncation_rejected():
    frame = b"".join(wire.encode_super_frame([b"one", b"two"]))
    bad = bytearray(frame)
    bad[-1] ^= 0xFF
    rb = wire.RecvBuffer()
    rb.feed(bytes(bad))
    with pytest.raises(wire.WireError):
        wire.parse_frames(rb)
    # an internally inconsistent entry table (count lies) must also fail
    lying = bytearray(frame)
    import struct as _struct
    import zlib as _zlib

    entries = frame[12:]
    _struct.pack_into("<I", lying, 8, 5)  # claim 5 entries
    _struct.pack_into("<I", lying, 4, _zlib.crc32(entries))
    rb2 = wire.RecvBuffer()
    rb2.feed(bytes(lying))
    with pytest.raises(wire.WireError):
        wire.parse_frames(rb2)


def test_decode_value_from_memoryview_zero_copy_slices():
    v = (1, "abc", b"\x00\xff" * 50, [True, None, 3.5], {"k": -7})
    enc = wire.encode_value(v)
    assert wire.decode_value(memoryview(enc)) == v
    # truncated memoryview surfaces WireError, not Index/struct errors
    with pytest.raises(wire.WireError):
        wire.decode_value(memoryview(enc)[: len(enc) - 3])


# ---------------------------------------------------------------------------
# real sockets: differential + metrics


def _rpc_battery(loop, a, b):
    from foundationdb_tpu.errors import NotCommitted
    from foundationdb_tpu.net.tcp import RemoteError

    async def echo(x):
        return ("echo", x)

    async def conflicted(_x):
        raise NotCommitted("conflict")

    b.node.register("echo", echo)
    b.node.register("conflict", conflicted)

    async def body():
        out = []
        # burst: many same-tick requests — the batching leg must coalesce
        futs = [
            a.node.request(Endpoint(b.node.address, "echo"), (i, "p" * i))
            for i in range(40)
        ]
        out.append(await wait_for_all(futs))
        try:
            await a.node.request(Endpoint(b.node.address, "nope"), None)
            out.append("no-bp")
        except BrokenPromise:
            out.append("bp")
        try:
            await a.node.request(Endpoint(b.node.address, "conflict"), None)
            out.append("no-nc")
        except NotCommitted:
            out.append("nc")
        except RemoteError:
            out.append("re")
        return out

    return a.run_until_done(spawn(body()), 30.0)


def test_batched_vs_legacy_socket_differential():
    """Gen-7 super-frames vs gen-6 per-message frames over real sockets:
    byte-identical results, strictly fewer frames than messages on the
    batching leg."""
    results = {}
    frames = {}
    for batching in (True, False):
        loop = RealLoop(seed=11)
        a = make_world(
            loop, TRANSPORT_FRAME_BATCHING=batching, TRANSPORT_LOOPBACK=False
        )
        b = make_world(
            loop, TRANSPORT_FRAME_BATCHING=batching, TRANSPORT_LOOPBACK=False
        )
        try:
            a.activate()
            results[batching] = _rpc_battery(loop, a, b)
            snap = a.transport_metrics.snapshot()
            frames[batching] = (snap["framesSent"], snap["messagesSent"])
            assert snap["tcpMessages"] > 0 and snap["loopbackMessages"] == 0
        finally:
            a.close()
            b.close()
            set_loop(None)
            loop.close()
    assert results[True] == results[False]
    f_on, m_on = frames[True]
    f_off, m_off = frames[False]
    assert m_on == m_off
    assert f_off == m_off  # legacy: one frame per message
    assert f_on < m_on  # batching: the 40-burst coalesced


def test_flush_truncation_fault_degrades_per_request_and_reconnects():
    """A torn super-frame (partial flush + connection death) fails every
    in-flight request with the retryable BrokenPromise family — nothing
    hangs, the connection is NOT wedged, and the next request succeeds
    over a fresh connection."""
    loop = RealLoop(seed=13)
    a = make_world(loop, TRANSPORT_LOOPBACK=False)
    b = make_world(loop, TRANSPORT_LOOPBACK=False)

    async def echo(x):
        return x

    b.node.register("echo", echo)
    fired = []

    def tear_once(conn):
        if not fired:
            fired.append(conn)
            return True
        return False

    async def body():
        # establish the connection first (the preamble must not be torn)
        assert await a.node.request(Endpoint(b.node.address, "echo"), 0) == 0
        a._flush_fault = tear_once
        futs = [
            a.node.request(Endpoint(b.node.address, "echo"), i)
            for i in range(10)
        ]
        await wait_for_all([settled(f) for f in futs])
        outcomes = []
        for f in futs:
            try:
                outcomes.append(("ok", f.get()))
            except BrokenPromise:
                outcomes.append(("broken", None))
        # the torn flush killed the batch: every future resolved, none ok
        assert fired and all(k == "broken" for k, _v in outcomes), outcomes
        a._flush_fault = None
        # NOT wedged: a fresh request reconnects and succeeds
        r = await a.node.request(Endpoint(b.node.address, "echo"), "again")
        assert r == "again"
        return True

    try:
        a.activate()
        assert a.run_until_done(spawn(body()), 30.0)
        assert a.transport_metrics.snapshot()["truncationFaults"] == 1
    finally:
        a.close()
        b.close()
        set_loop(None)
        loop.close()


# ---------------------------------------------------------------------------
# loopback


def test_loopback_auto_selected_for_colocated_worlds():
    loop = RealLoop(seed=17)
    a = make_world(loop)
    b = make_world(loop)
    try:
        a.activate()
        out = _rpc_battery(loop, a, b)
        assert out[1] == "bp" and out[2] == "nc"
        snap = a.transport_metrics.snapshot()
        assert snap["loopbackMessages"] > 0
        assert snap["tcpMessages"] == 0  # never touched a socket
        assert snap["framesSent"] < snap["messagesSent"]  # batched drains
    finally:
        a.close()
        b.close()
        set_loop(None)
        loop.close()


def test_loopback_codec_parity_no_aliasing_and_unserializable_errors():
    """Loopback peers exchange CODEC COPIES: mutating a request after
    send must not leak to the handler, and unserializable payloads fail
    the sender exactly like the socket path would."""
    loop = RealLoop(seed=19)
    a = make_world(loop)
    b = make_world(loop)
    seen = []

    async def keep(x):
        seen.append(x)
        return len(seen)

    b.node.register("keep", keep)

    async def body():
        payload = {"k": [1, 2, 3]}
        f = a.node.request(Endpoint(b.node.address, "keep"), payload)
        payload["k"].append(99)  # mutate after send, before delivery
        await f
        assert seen[0] == {"k": [1, 2, 3]}, seen
        try:
            await a.node.request(Endpoint(b.node.address, "keep"), object())
            return "accepted-unserializable"
        except wire.WireError:
            return "rejected"

    try:
        a.activate()
        assert a.run_until_done(spawn(body()), 30.0) == "rejected"
    finally:
        a.close()
        b.close()
        set_loop(None)
        loop.close()


def test_loopback_close_semantics_match_dead_peer():
    loop = RealLoop(seed=23)
    a = make_world(loop)

    async def body():
        b = make_world(loop)

        async def pong(_x):
            return "pong"

        b.node.register("ping", pong)
        assert (
            await a.node.request(Endpoint(b.node.address, "ping"), None)
        ) == "pong"
        assert a.transport_metrics.snapshot()["loopbackMessages"] > 0
        # peer closes: in-flight + subsequent requests break (typed,
        # retryable), exactly like a dead TCP peer
        addr = b.node.address
        b.close()
        try:
            await a.node.request(Endpoint(addr, "ping"), None)
            return "no-break"
        except BrokenPromise:
            return "broke"

    try:
        a.activate()
        assert a.run_until_done(spawn(body()), 30.0) == "broke"
    finally:
        a.close()
        set_loop(None)
        loop.close()


def test_tls_worlds_never_loop_back(tmp_path):
    """A TLS world must keep its peer-authentication story: loopback is
    disabled even for colocated TLS worlds (they talk TLS over sockets).
    Super-frame batching still rides the TLS stream (the joined-buffer
    flush path — SSLSocket has no sendmsg)."""
    from test_tls import gen_ca_and_cert

    crt, key, ca = gen_ca_and_cert(str(tmp_path))
    tls = dict(certfile=crt, keyfile=key, cafile=ca)
    loop = RealLoop(seed=29)
    a = RealWorld(f"127.0.0.1:{free_port()}", knobs=Knobs(), loop=loop, tls=tls)
    b = RealWorld(f"127.0.0.1:{free_port()}", knobs=Knobs(), loop=loop, tls=tls)

    async def echo(x):
        return x

    b.node.register("echo", echo)

    async def body():
        futs = [
            a.node.request(Endpoint(b.node.address, "echo"), i)
            for i in range(20)
        ]
        return await wait_for_all(futs)

    try:
        a.activate()
        assert a.run_until_done(spawn(body()), 60.0) == list(range(20))
        snap = a.transport_metrics.snapshot()
        assert snap["loopbackMessages"] == 0
        assert snap["tcpMessages"] > 0
        assert snap["framesSent"] < snap["messagesSent"]  # super-framed TLS
    finally:
        a.close()
        b.close()
        set_loop(None)
        loop.close()


# ---------------------------------------------------------------------------
# sim parity: the transport-truncate chaos site


def test_sim_transport_fault_fails_only_faulted_request_typed():
    from foundationdb_tpu.net.sim import Sim
    from foundationdb_tpu.runtime.rng import DeterministicRandom

    sim = Sim(seed=31)
    sim.activate()
    p = sim.new_process("1.1.1.1:1")
    q = sim.new_process("2.2.2.2:2")

    async def echo(x):
        return x

    q.register("echo", echo)

    class _AlwaysOnce:
        """First roll fires, the rest don't."""

        def __init__(self):
            self.rolls = 0

        def coinflip(self, _p):
            self.rolls += 1
            return self.rolls == 1

    sim.arm_transport_faults(_AlwaysOnce(), p=1.0)

    async def body():
        try:
            await p.request(Endpoint(q.address, "echo"), "first")
            return "no-fault"
        except TransportTruncated as e:
            assert isinstance(e, BrokenPromise)  # retryable family
        # per-request degradation: the NEXT request sails through
        return await p.request(Endpoint(q.address, "echo"), "second")

    assert sim.run_until_done(spawn(body()), 60.0) == "second"
    assert sim.transport_metrics.snapshot()["truncationFaults"] == 1
    set_loop(None)


def test_commit_pipeline_survives_truncation_burst():
    """Regression for the version-chain wedge the chaos site exposed:
    resolve/tlog-commit RPCs eaten mid-pipeline used to tear a permanent
    hole in the prev→version chain (thousands of TLog.commit handlers
    parked at the VersionGate forever). With proxy-side retransmission
    (log_system.retransmitting_request) a fault burst costs retries,
    never the epoch: commits issued during AND after the burst all
    succeed without recovery."""
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.net.sim import Sim
    from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster

    sim = Sim(seed=53)
    sim.activate()
    cluster = DynamicCluster(
        sim,
        ClusterConfig(n_proxies=1, n_resolvers=2, n_tlogs=2, n_storage=2),
        n_coordinators=1,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)

    class _Rng:
        def __init__(self, seed):
            import random

            self._r = random.Random(seed)

        def coinflip(self, p):
            return self._r.random() < p

    async def go():
        async def w(tr, i):
            tr.set(b"tw%03d" % i, b"v%d" % i)

        # settle the cluster, then arm a hot fault window over live commits
        await db.run(lambda tr: w(tr, 999))
        t0 = sim.loop.now()
        sim.arm_transport_faults(_Rng(1), p=0.08, windows=[(t0, t0 + 3.0)])
        for i in range(40):
            await db.run(lambda tr, i=i: w(tr, i))
        # burst over: the pipeline must still be healthy
        async def check(tr):
            rows = await tr.get_range(b"tw", b"tx")
            return len(rows)

        return await db.run(check)

    assert sim.run_until_done(spawn(go()), 600.0) == 41
    assert sim.transport_metrics.snapshot()["truncationFaults"] > 0
    set_loop(None)


@pytest.mark.parametrize("batching", [True, False])
def test_bindingtester_oracle_with_transport_knob(batching):
    """Semantics gate: the bindingtester oracle must stay green with the
    transport knob both ways (the knob reshapes framing/batching, never
    results)."""
    from test_bindingtester import run_model, run_real

    stream, (data_real, log_real) = run_real(
        seed=47, n_ops=300,
        knobs=Knobs(TRANSPORT_FRAME_BATCHING=batching),
    )
    data_model, log_model = run_model(stream)
    assert list(data_real) == list(data_model)
    assert list(log_real) == list(log_model)


# ---------------------------------------------------------------------------
# flowlint: worker must register transport.metrics


def _lint_worker(tmp_path, worker_src):
    from foundationdb_tpu.tools.flowlint import lint

    pkg = tmp_path / "foundationdb_tpu" / "server"
    pkg.mkdir(parents=True)
    (pkg / "worker.py").write_text(worker_src)
    config = {
        "include": ["foundationdb_tpu"],
        "exclude": [],
        "sim_scope": [],
        "host_only": {},
        "baseline": "baseline.json",
        "worker_module": "foundationdb_tpu/server/worker.py",
        "role_exempt": [],
        "span_roles": [],
        "transport_metrics_endpoint": "transport.metrics",
    }
    return lint(root=tmp_path, config=config)


def test_flowlint_worker_without_transport_metrics_flagged(tmp_path):
    res = _lint_worker(
        tmp_path,
        "class Worker:\n"
        "    def start(self, process):\n"
        '        process.register("worker.metrics", self._rm)\n',
    )
    assert any(
        f.rule == "reg-role-metrics" and f.detail == "worker-transport-metrics"
        for f in res.failing
    ), [f.format() for f in res.failing]


def test_flowlint_worker_with_transport_metrics_clean(tmp_path):
    res = _lint_worker(
        tmp_path,
        "class Worker:\n"
        "    def start(self, process):\n"
        '        process.register("transport.metrics", self._tm)\n',
    )
    assert not res.failing, [f.format() for f in res.failing]
