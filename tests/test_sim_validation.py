"""sim_validation durability oracle + QuietDatabase (verdict r3 missing #5):
acked-commit coverage asserted across real recoveries, violations actually
detected, and quiet_database settling before consistency checks."""

import pytest

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.workloads.quiet import quiet_database


def make(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = DynamicCluster(sim, ClusterConfig(**cfg), n_coordinators=3)
    db = Database.from_coordinators(sim, cluster.coordinators)
    return sim, cluster, db


def test_oracle_tracks_acks_and_recovery_checks_them():
    sim, cluster, db = make(
        seed=13, n_storage=2, n_tlogs=2, tlog_replication=2
    )

    async def body():
        for i in range(10):

            async def w(tr, i=i):
                tr.set(b"d%02d" % i, b"v")

            await db.run(w)
        acked = sim.validation.max_acked
        assert acked > 0
        # kill the master: recovery must pass the oracle's check
        for addr, p in list(sim.processes.items()):
            w = getattr(p, "worker", None)
            if w and p.alive and any(
                h.kind == "master" for h in w.roles.values()
            ):
                sim.kill_process(addr)
                break

        async def more(tr):
            tr.set(b"post", b"1")

        await db.run(more)
        assert sim.validation.max_acked > acked
        assert not sim.validation.violations
        return True

    assert sim.run_until_done(spawn(body()), 600.0)


def test_oracle_detects_lost_acks():
    from foundationdb_tpu.runtime.validation import DurabilityOracle

    o = DurabilityOracle()
    o.note_acked(500)
    o.note_acked(300)  # never regresses
    assert o.max_acked == 500
    o.check_recovery(500, 2)  # equal is fine
    with pytest.raises(AssertionError):
        o.check_recovery(499, 3)
    assert o.violations


def test_quiet_database_settles():
    sim, cluster, db = make(
        seed=14, n_storage=4, n_tlogs=2, replication=2, tlog_replication=2
    )

    async def body():
        for i in range(20):

            async def w(tr, i=i):
                tr.set(b"\x90q%02d" % i, b"v%d" % i)

            await db.run(w)
        # a live relocation: quiet must outlast it
        from foundationdb_tpu.server.movekeys import move_shard
        from tests.test_movekeys import find_storage

        storage = await find_storage(sim, db)
        mover = spawn(move_shard(db, b"\x80", None, [storage[0], storage[1]]))
        await quiet_database(db)
        assert mover.is_ready()  # quiet outlasted the move
        # map is stable and every member serves the whole shard now
        from foundationdb_tpu.workloads.quiet import _walk_shards

        shards = await _walk_shards(db)
        assert shards == await _walk_shards(db)
        return True

    assert sim.run_until_done(spawn(body()), 600.0)
