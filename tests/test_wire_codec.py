"""Schema-compiled wire codec (ISSUE 18): byte identity, staleness gate,
slab-settled futures.

Layers covered:
 - golden-bytes fixture (tests/golden_wire.json): canonical encodings
   every registered struct must reproduce BYTE-FOR-BYTE on both codec
   paths — the cross-version regression tripwire (a codegen change that
   alters even one length byte fails here before it bricks a mixed-
   version cluster). Regen with:  python tests/test_wire_codec.py --regen
 - fuzzed differential: random field trees through compiled vs
   interpretive encode must be identical bytes; decode must reproduce
   the fields exactly (compared field-wise, never via repr — enum-typed
   fields legitimately hold plain ints under fuzz and some __repr__s
   assume the enum);
 - codec_audit(): the staleness gate is clean on the real registry and
   actually fires on each failure mode (missing codec, stale class
   binding, field drift, missing encoder);
 - settle_batch(): one loop step settles many futures, error and value
   mixed, priority order preserved, nested cascades collected, and the
   off-path (FUTURE_SLAB_SETTLE=false) stays per-waiter.
"""

import dataclasses
import json
import os
import random
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from foundationdb_tpu.net import wire

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden_wire.json")


def registered_dataclasses():
    """Every register_struct dataclass (register_custom pack/unpack pairs
    carry hand-written codecs and are exempt, as in codec_audit)."""
    return {
        name: entry
        for name, entry in sorted(wire._struct_by_name.items())
        if isinstance(entry, type)
    }


# deterministic per-field synthesis for the golden fixture. No sets and
# no float NaN: set iteration order depends on PYTHONHASHSEED for
# bytes/str members, and NaN != NaN breaks round-trip comparison.
_SYNTH_POOL = [
    0,
    -1,
    4095,          # top of the small-int cache
    4096,          # first uncached int
    -129,          # below the cache
    1 << 70,       # multi-byte little-endian body
    b"",
    b"key/000042",
    b"\x00\xff" * 3,
    "",
    "name-7",
    "uni-☃",
    None,
    True,
    False,
    1.5,
    -2.25,
    (1, b"a", "b"),
    [b"k", None, 3],
    {b"k": 1, "s": b"v"},
]


def synth_value(i):
    return _SYNTH_POOL[i % len(_SYNTH_POOL)]


def canonical_instance(cls):
    flds = dataclasses.fields(cls)
    return cls(*[synth_value(i) for i in range(len(flds))])


def hot_messages():
    """Realistic commit/read-path messages (enums, nested structs,
    mutation lists) — the shapes a loaded cluster actually moves."""
    from foundationdb_tpu.tools.perf import _hot_message_set

    return _hot_message_set()


def fields_equal(a, b):
    """Field-wise equality without repr: fuzzed instances may hold plain
    ints in enum-typed fields, and some __repr__s assume the enum."""
    if a.__class__ is not b.__class__:
        return False
    for fl in dataclasses.fields(a):
        if getattr(a, fl.name) != getattr(b, fl.name):
            return False
    return True


@pytest.fixture
def both_codecs():
    """Restore the compiled codec after any test that toggles it."""
    yield
    wire.set_compiled_codec(True)


# ---------------------------------------------------------------------------
# golden-bytes fixture


def build_golden():
    entries = {}
    wire.set_compiled_codec(True)
    try:
        for name, cls in registered_dataclasses().items():
            inst = canonical_instance(cls)
            entries[name] = {
                "fields": [fl.name for fl in dataclasses.fields(cls)],
                "hex": wire.encode_value(inst).hex(),
            }
        hot = [wire.encode_value(m).hex() for m in hot_messages()]
    finally:
        wire.set_compiled_codec(True)
    return {"format": "gen-9", "structs": entries, "hot": hot}


def test_golden_fixture_exists_and_covers_registry():
    with open(GOLDEN) as f:
        golden = json.load(f)
    missing = set(registered_dataclasses()) - set(golden["structs"])
    assert not missing, (
        f"structs with no golden encoding (regen: python "
        f"tests/test_wire_codec.py --regen): {sorted(missing)}"
    )


@pytest.mark.parametrize("compiled", [True, False], ids=["compiled", "interp"])
def test_golden_bytes_reproduced(compiled, both_codecs):
    """Both codec paths must reproduce the checked-in bytes exactly. A
    diff here is a WIRE FORMAT CHANGE: it needs a protocol version bump
    and a deliberate fixture regen, not a silent update."""
    with open(GOLDEN) as f:
        golden = json.load(f)
    wire.set_compiled_codec(compiled)
    regd = registered_dataclasses()
    for name, entry in golden["structs"].items():
        cls = regd.get(name)
        if cls is None:
            continue  # struct removed; coverage test owns the inverse
        if [fl.name for fl in dataclasses.fields(cls)] != entry["fields"]:
            pytest.fail(
                f"{name}: field list drifted from golden fixture — wire "
                f"format change, bump PROTOCOL_VERSION and regen"
            )
        inst = canonical_instance(cls)
        got = wire.encode_value(inst)
        assert got.hex() == entry["hex"], f"{name}: bytes drifted"
        back = wire.decode_value(got)
        assert fields_equal(back, inst), f"{name}: decode round-trip"
    for want, msg in zip(golden["hot"], hot_messages()):
        assert wire.encode_value(msg).hex() == want


# ---------------------------------------------------------------------------
# fuzzed differential: compiled vs interpretive


def fuzz_value(rnd, depth=0):
    roll = rnd.random()
    if depth >= 2 or roll < 0.55:
        return rnd.choice(
            [
                rnd.randrange(-(1 << 40), 1 << 40),
                rnd.randrange(-128, 4096),
                rnd.randbytes(rnd.randrange(0, 40)),
                "".join(chr(rnd.randrange(32, 0x2FF)) for _ in range(rnd.randrange(8))),
                None,
                bool(rnd.getrandbits(1)),
                rnd.random() * 1e6,
            ]
        )
    if roll < 0.7:
        return tuple(fuzz_value(rnd, depth + 1) for _ in range(rnd.randrange(3)))
    if roll < 0.85:
        return [fuzz_value(rnd, depth + 1) for _ in range(rnd.randrange(3))]
    return {
        rnd.randbytes(4): fuzz_value(rnd, depth + 1)
        for _ in range(rnd.randrange(3))
    }


def test_fuzzed_differential_all_structs(both_codecs):
    """Random field trees through every registered struct: compiled and
    interpretive encodings must be the same bytes, and decode must give
    back the same fields (bytes and memoryview readers both)."""
    rnd = random.Random(1807)
    mismatches = []
    for name, cls in registered_dataclasses().items():
        for trial in range(8):
            flds = dataclasses.fields(cls)
            inst = cls(*[fuzz_value(rnd) for _ in flds])
            wire.set_compiled_codec(True)
            comp = wire.encode_value(inst)
            dec_c = wire.decode_value(comp)
            wire.set_compiled_codec(False)
            interp = wire.encode_value(inst)
            dec_i = wire.decode_value(interp)
            if comp != interp:
                mismatches.append(f"{name}[{trial}]: bytes differ")
            elif not fields_equal(dec_c, inst) or not fields_equal(dec_i, inst):
                mismatches.append(f"{name}[{trial}]: decode mismatch")
    assert not mismatches, mismatches[:10]


def test_differential_hot_messages_and_memoryview(both_codecs):
    for msg in hot_messages():
        wire.set_compiled_codec(True)
        comp = wire.encode_value(msg)
        wire.set_compiled_codec(False)
        assert wire.encode_value(msg) == comp
        wire.set_compiled_codec(True)
        # the zero-copy super-frame path hands decode a memoryview
        assert fields_equal(wire.decode_value(memoryview(comp)), msg)
        assert fields_equal(wire.decode_value(comp), msg)


def test_knob_toggle_via_realworld_settings():
    assert wire.compiled_codec_enabled()
    wire.set_compiled_codec(False)
    assert not wire.compiled_codec_enabled()
    wire.set_compiled_codec(True)
    assert wire.compiled_codec_enabled()


# ---------------------------------------------------------------------------
# codec_audit staleness gate


def test_codec_audit_clean_on_real_registry():
    assert wire.codec_audit() == []


def test_codec_audit_fires_on_missing_codec():
    name = "GetValueRequest"
    saved = wire._COMPILED_META.pop(name)
    try:
        assert any("no compiled codec" in p for p in wire.codec_audit())
    finally:
        wire._COMPILED_META[name] = saved


def test_codec_audit_fires_on_stale_class_binding():
    """A registry poke that bypasses register_struct (rebinding the name
    to a new class) leaves the codec compiled against the OLD class."""
    name = "GetValueRequest"
    saved = wire._struct_by_name[name]

    @dataclasses.dataclass
    class GetValueRequest:
        key: bytes = b""
        version: int = -1

    wire._struct_by_name[name] = GetValueRequest
    try:
        assert any("stale class" in p for p in wire.codec_audit())
    finally:
        wire._struct_by_name[name] = saved
    assert wire.codec_audit() == []


def test_codec_audit_fires_on_field_drift():
    name = "GetValueRequest"
    cls, fields = wire._COMPILED_META[name]
    wire._COMPILED_META[name] = (cls, fields[:-1])
    try:
        assert any("drifted" in p for p in wire.codec_audit())
    finally:
        wire._COMPILED_META[name] = (cls, fields)


def test_codec_audit_fires_on_missing_decoder():
    name = "GetValueRequest"
    saved = wire._COMPILED_DEC.pop(name)
    try:
        assert any("missing" in p for p in wire.codec_audit())
    finally:
        wire._COMPILED_DEC[name] = saved


def test_reregister_heals_field_drift():
    """register_struct IS the schema-compilation step: re-registering a
    drifted class regenerates the codec and the audit goes clean."""
    name = "GetValueRequest"
    cls, fields = wire._COMPILED_META[name]
    wire._COMPILED_META[name] = (cls, ("bogus",))
    assert wire.codec_audit() != []
    wire.register_struct(cls)
    assert wire.codec_audit() == []


# ---------------------------------------------------------------------------
# slab-settled futures


def run_sim(fn):
    from foundationdb_tpu.net.sim import Sim
    from foundationdb_tpu.runtime.futures import spawn

    sim = Sim(seed=7)
    sim.activate()
    fut = spawn(fn())
    sim.run_until_done(fut, 60.0)
    return fut.get()


def test_settle_batch_settles_many_waiters_in_one_step():
    from foundationdb_tpu.runtime import futures as ft

    async def body():
        waiters = [ft.Future() for i in range(6)]
        order = []

        async def wait_on(i, f):
            order.append((i, await f))

        tasks = [ft.spawn(wait_on(i, f)) for i, f in enumerate(waiters)]
        await ft.delay(0.01)  # everyone parked on its future
        ft.settle_batch([(f, i * 10, None) for i, f in enumerate(waiters)])
        await ft.wait_for_all(tasks)
        return order

    assert run_sim(lambda: body()) == [(i, i * 10) for i in range(6)]


def test_settle_batch_mixed_values_and_errors():
    from foundationdb_tpu.runtime import futures as ft

    async def body():
        ok, bad = ft.Future(), ft.Future()
        results = {}

        async def wait_ok():
            results["ok"] = await ok

        async def wait_bad():
            try:
                await bad
            except RuntimeError as e:
                results["bad"] = str(e)

        t1, t2 = ft.spawn(wait_ok()), ft.spawn(wait_bad())
        await ft.delay(0.01)
        ft.settle_batch([(ok, 42, None), (bad, None, RuntimeError("boom"))])
        await ft.wait_for_all([t1, t2])
        return results

    assert run_sim(lambda: body()) == {"ok": 42, "bad": "boom"}


def test_settle_batch_nested_cascade_collected():
    """A waiter that settles ANOTHER future from inside its continuation
    must not deadlock or drop the nested wakeup."""
    from foundationdb_tpu.runtime import futures as ft

    async def body():
        first, second = ft.Future(), ft.Future()
        got = []

        async def one():
            got.append(await first)
            second._set("cascade")

        async def two():
            got.append(await second)

        t1, t2 = ft.spawn(one()), ft.spawn(two())
        await ft.delay(0.01)
        ft.settle_batch([(first, "root", None)])
        await ft.wait_for_all([t1, t2])
        return got

    assert run_sim(lambda: body()) == ["root", "cascade"]


def test_settle_batch_respects_disable_knob():
    from foundationdb_tpu.runtime import futures as ft

    async def body():
        ft.set_slab_settle(False)
        try:
            assert not ft.slab_settle_enabled()
            waiters = [ft.Future() for i in range(3)]
            got = []

            async def wait_on(f):
                got.append(await f)

            tasks = [ft.spawn(wait_on(f)) for f in waiters]
            await ft.delay(0.01)
            ft.settle_batch([(f, i, None) for i, f in enumerate(waiters)])
            await ft.wait_for_all(tasks)
            return got
        finally:
            ft.set_slab_settle(True)

    assert run_sim(lambda: body()) == [0, 1, 2]


def test_settle_batch_skips_already_ready_futures():
    from foundationdb_tpu.runtime import futures as ft

    async def body():
        f = ft.Future()
        f._set("already")
        g = ft.Future()
        # re-settling a ready future is a no-op (as with _set), not a crash
        ft.settle_batch([(f, "clobbered", None), (g, "set", None)])
        ft.settle_batch([])  # empty batch: no collector install, no step
        return (await f, await g)

    assert run_sim(lambda: body()) == ("already", "set")


if __name__ == "__main__":
    if "--regen" in sys.argv:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        golden = build_golden()
        with open(GOLDEN, "w") as f:
            json.dump(golden, f, indent=1)
            f.write("\n")
        print(
            f"wrote {GOLDEN}: {len(golden['structs'])} structs, "
            f"{len(golden['hot'])} hot messages"
        )
    else:
        sys.exit(pytest.main([__file__, "-q"]))
