"""Pipelined group-commit fsync (ISSUE 18): the tlog may overlap the
next version's push with an in-flight write+fsync round, but the
durability contract is unchanged — a commit is ACKED only after the
round covering it returns from fsync.

Layers covered:
 - overlap: N chained commits complete in ~1 fsync's worth of sim time
   with the pipeline on vs ~N fsyncs with it off (the knob A/B), and
   pipelineDepth records the overlap;
 - no early ack: with the physical sync parked, the version gate has
   released (pushes accumulated) but no commit future is ready, the
   durable version has not moved, and peeks clamp below the unfsynced
   entries;
 - retransmit in the pushed-but-unfsynced gap: a duplicate of a version
   past the gate but above the durable floor must not be acked as
   "already durable";
 - crash during pipelined fsync (the SITE_FSYNC_PIPELINE_STALL chaos
   site held open): kill semantics drop unsynced writes, and a fresh
   tlog recovered from the same disk must still serve EVERY version that
   was acked before the crash.
"""

import pytest

from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.buggify import Buggify, set_buggify
from foundationdb_tpu.runtime.futures import Future, delay, spawn, wait_for_all
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.loop import now
from foundationdb_tpu.runtime.rng import DeterministicRandom
from foundationdb_tpu.server.interfaces import (
    TLogCommitRequest,
    TLogPeekRequest,
)
from foundationdb_tpu.server.tlog import SITE_FSYNC_PIPELINE_STALL, TLog


def run(coro, seed=7, limit=60.0):
    sim = Sim(seed=seed)
    sim.activate()
    return sim.run_until_done(spawn(coro), limit)


def commit_req(v, tag=0):
    from foundationdb_tpu.kv.mutations import Mutation, MutationType

    return TLogCommitRequest(
        epoch=0,
        prev_version=v - 1,
        version=v,
        messages={
            tag: [Mutation(MutationType.SET_VALUE, b"k%04d" % v, b"v%04d" % v)]
        },
        known_committed=0,
    )


def chained_commits(tl, n):
    """Spawn n version-chained commits concurrently (the proxy shape:
    many in flight, the tlog's gate sequences them)."""
    return [spawn(tl.commit(commit_req(v))) for v in range(1, n + 1)]


# ---------------------------------------------------------------------------
# overlap: the knob A/B on the modeled-fsync path


@pytest.mark.parametrize("pipeline", [True, False], ids=["on", "off"])
def test_pipeline_overlaps_modeled_fsync(pipeline):
    fsync_s = 0.01
    n = 8
    result = {}

    async def body():
        knobs = Knobs()
        knobs.TLOG_FSYNC_TIME = fsync_s
        knobs.TLOG_FSYNC_PIPELINE = pipeline
        tl = TLog(log_id="tp", knobs=knobs)
        t0 = now()
        futs = chained_commits(tl, n)
        await wait_for_all(futs)
        result["elapsed"] = now() - t0
        result["peak"] = tl._pipeline_peak
        assert tl.version.get() == n

    run(body())
    if pipeline:
        # every commit's modeled fsync overlaps: ~1 fsync total, and the
        # pending-slab depth saw the overlap
        assert result["elapsed"] < 2 * fsync_s, result
        assert result["peak"] > 1, result
    else:
        # serialized: the version chain holds each commit until the
        # previous fsync returned
        assert result["elapsed"] >= n * fsync_s * 0.99, result
        assert result["peak"] == 0, result


# ---------------------------------------------------------------------------
# no early ack: park the physical sync, watch the gate run ahead


def test_ack_waits_for_covering_fsync_on_disk():
    sim = Sim(seed=11)
    sim.activate()

    async def body():
        tl = TLog(log_id="td", disk=sim.disk("m0"))
        await tl.commit(commit_req(1))  # opens the queue file
        assert tl.version.get() == 1

        f = tl.dq._file
        real_sync = f.sync
        hold = Future()

        async def parked_sync():
            await hold
            await real_sync()

        f.sync = parked_sync
        try:
            c2 = spawn(tl.commit(commit_req(2)))
            c3 = spawn(tl.commit(commit_req(3)))
            await delay(0.05)
            # pipelined: both versions pushed, version chain released...
            assert tl._gate.version == 3
            # ...but NOTHING acked and the durable horizon unmoved
            assert not c2.is_ready() and not c3.is_ready()
            assert tl.version.get() == 1
            # peeks clamp at the durable version: unfsynced entries are
            # never served to storage (begin=2 would long-poll on the
            # durable horizon, which is exactly the point)
            reply = await tl.peek(TLogPeekRequest(tag=0, begin=1))
            assert [v for v, _m in reply.messages] == [1]
            assert reply.end_version == 1
        finally:
            f.sync = real_sync
            hold._set(None)
        await wait_for_all([c2, c3])
        assert tl.version.get() == 3
        reply = await tl.peek(TLogPeekRequest(tag=0, begin=2))
        assert [v for v, _m in reply.messages] == [2, 3]

    sim.run_until_done(spawn(body()), 60.0)


def test_retransmit_in_unfsynced_gap_not_acked():
    """A proxy retransmit for a version the gate has passed but the
    durable horizon has not must NOT be answered as a duplicate-of-
    durable — that would ack data that can still be lost."""
    from foundationdb_tpu.runtime.loop import Cancelled

    sim = Sim(seed=13)
    sim.activate()

    async def body():
        tl = TLog(log_id="tr", disk=sim.disk("m0"))
        await tl.commit(commit_req(1))
        # simulate the gap a cancelled push leaves: gate past v2, durable
        # floor still at v1, no pending future for v2
        tl._gate.advance_to(2)
        with pytest.raises(Cancelled):
            await tl.commit(commit_req(2))
        # a version at or below the durable floor IS a safe duplicate
        assert await tl.commit(commit_req(1)) is None

    sim.run_until_done(spawn(body()), 60.0)


# ---------------------------------------------------------------------------
# crash during pipelined fsync → recovery serves every acked version


def test_crash_during_pipelined_fsync_preserves_acked():
    """The SITE_FSYNC_PIPELINE_STALL chaos window held open (buggify
    pinned to always-fire widens the pushed-but-unfsynced gap), then a
    kill drops unsynced writes. The recovered tlog must serve every
    version acked before the crash; versions never acked may go either
    way."""
    sim = Sim(seed=17)
    # run_until_done re-activates the sim (reinstalling sim.buggify), so
    # force the chaos site by replacing the sim's own instance
    sim.buggify = Buggify(DeterministicRandom(17), p_enabled=1.0, p_fire=1.0)
    sim.activate()
    try:
        disk = sim.disk("m0")
        acked = []

        async def crash_run():
            tl = TLog(log_id="tc", disk=disk)
            futs = chained_commits(tl, 12)
            # wait until a prefix is acked, then "crash" with the rest
            # mid-pipeline (the stall site keeps rounds in flight)
            while tl.version.get() < 4:
                await delay(0.001)
            for v, f in enumerate(futs, start=1):
                if f.is_ready() and not f.is_error():
                    acked.append(v)
            for f in futs:
                f.cancel()
            return True

        sim.run_until_done(spawn(crash_run()), 60.0)
        disk.on_kill()  # unsynced writes lost (AsyncFileNonDurable)
        assert acked, "crash landed before any ack — test shape broken"

        async def recover_run():
            tl2 = TLog(log_id="tc", disk=disk)
            await tl2.recover()
            # every acked version is present and peekable
            assert tl2.version.get() >= max(acked)
            reply = await tl2.peek(TLogPeekRequest(tag=0, begin=1))
            got = {v for v, _m in reply.messages}
            missing = [v for v in acked if v not in got]
            assert not missing, f"acked versions lost by crash: {missing}"
            return True

        sim.run_until_done(spawn(recover_run()), 60.0)
    finally:
        set_buggify(Buggify(None))


def test_stall_site_fires_under_forced_buggify():
    """The named chaos site is actually reachable on the dq commit path
    (soak's fired-site report keys on it)."""
    sim = Sim(seed=19)
    b = Buggify(DeterministicRandom(19), p_enabled=1.0, p_fire=1.0)
    sim.buggify = b
    sim.activate()
    try:

        async def body():
            tl = TLog(log_id="tf", disk=sim.disk("m0"))
            await wait_for_all(chained_commits(tl, 3))
            return True

        sim.run_until_done(spawn(body()), 60.0)
        assert SITE_FSYNC_PIPELINE_STALL in b.fired
    finally:
        set_buggify(Buggify(None))
