"""ManagementAPI + status + fdbcli analog: exclude/include, configure with
forced recovery, status document, CLI command vocabulary."""

from foundationdb_tpu.client import management
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.tools.cli import FdbCli


def make(seed=0, n_coordinators=1, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = DynamicCluster(
        sim, ClusterConfig(**cfg), n_coordinators=n_coordinators
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    return sim, cluster, db


def run(sim, coro, limit=600.0):
    return sim.run_until_done(spawn(coro), limit)


async def put(db, key, value):
    async def body(tr):
        tr.set(key, value)

    await db.run(body)


async def get(db, key):
    async def body(tr):
        return await tr.get(key)

    return await db.run(body)


def test_exclude_drains_server():
    sim, cluster, db = make(
        seed=51, n_proxies=1, n_resolvers=1, n_tlogs=2, n_storage=4,
        replication=2, tlog_replication=2,
    )

    async def body():
        for i in range(20):
            await put(db, b"x%02d" % i, b"v%d" % i)
        # find the worker address hosting storage tag 0
        victim = next(
            addr
            for addr, p in sim.processes.items()
            if getattr(p, "worker", None) and p.alive
            for h in p.worker.roles.values()
            if h.kind == "storage" and h.obj.tag == 0
        )
        await management.exclude_servers(db, [victim])
        await management.wait_for_excluded(db, [victim])
        assert victim in await management.get_excluded(db)
        # all data still there
        for i in range(20):
            assert await get(db, b"x%02d" % i) == b"v%d" % i, i
        await management.include_servers(db)
        assert await management.get_excluded(db) == []

    run(sim, body())


def test_configure_changes_shape():
    sim, cluster, db = make(
        seed=52, n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=1,
    )

    async def body():
        await put(db, b"a", b"1")
        await management.configure(
            db, cluster.coordinators, db.client, n_proxies=2, n_resolvers=2
        )
        # new generation must eventually serve with 2 proxies
        deadline = sim.loop.now() + 60.0
        while True:
            await delay(1.0)
            doc = await management.get_status(cluster.coordinators, db.client)
            proxies = doc.get("client", {}).get("proxies", [])
            if len(proxies) == 2:
                break
            assert sim.loop.now() < deadline, doc
        assert await get(db, b"a") == b"1"
        await put(db, b"b", b"2")
        assert await get(db, b"b") == b"2"

    run(sim, body())


def test_status_document():
    sim, cluster, db = make(
        seed=53, n_proxies=2, n_resolvers=1, n_tlogs=2, n_storage=2,
        replication=2, tlog_replication=2,
    )

    async def body():
        await put(db, b"s", b"1")
        doc = await management.get_status(cluster.coordinators, db.client)
        c = doc["cluster"]
        assert c["recovered"] is True
        assert c["recovery_count"] >= 1
        assert len(c["workers"]) >= 4
        assert c["logs"]["epoch"] >= 1
        assert len(doc["client"]["proxies"]) == 2

    run(sim, body())


def test_cli_vocabulary():
    sim, cluster, db = make(
        seed=54, n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=1,
    )
    cli = FdbCli(db, cluster.coordinators)

    async def body():
        assert await cli.execute("set hello world") == "Committed"
        assert "`world'" in await cli.execute("get hello")
        assert "not found" in await cli.execute("get missing")
        await cli.execute("set hello2 there")
        out = await cli.execute("getrange hello hellp 10")
        assert "hello" in out and "hello2" in out
        assert await cli.execute("clear hello") == "Committed"
        assert "not found" in await cli.execute("get hello")
        status = await cli.execute("status")
        assert "Cluster controller" in status and "Recovered: True" in status
        assert "unknown command" in await cli.execute("bogus")

    run(sim, body())
