"""Read-pipeline battery (ISSUE 12): same-tick coalescing semantics.

Asserts the four satellite guarantees: (a) same-tick gets collapse into
exactly one Storage.multiGet hop per storage team, (b) RYW-overlay and
key-selector results are byte-identical between the batched and
unbatched paths, (c) the bindingtester oracle stays green with the
coalescing knob forced both ways, (d) per-entry faults (too_old / drop /
partial reply) fail only the affected entry's future or degrade it to
the per-key path without losing correctness — plus the tier-1-safe CPU
smoke that the batched endpoint actually runs over the range index.
"""

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.kv.mutations import MutationType
from foundationdb_tpu.kv.selector import KeySelector
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, settled, spawn, wait_for_all
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.runtime.trace import TraceLog, set_trace_log
from foundationdb_tpu.server import Cluster, ClusterConfig
from foundationdb_tpu.server.interfaces import (
    GetKeyServersRequest,
    MultiGetRequest,
    READ_ERR_DROPPED,
    READ_ERR_TOO_OLD,
)

import pytest


def _cluster(seed=3, n_storage=1, replication=1, knobs=None):
    sim = Sim(seed=seed, knobs=knobs)
    sim.activate()
    cluster = Cluster(
        sim, ClusterConfig(n_storage=n_storage, replication=replication)
    )
    db = Database(sim, cluster.proxy_addrs)
    return sim, cluster, db


def _span_events(log):
    return [e for e in log.events if e.get("Type") == "Span"]


# -- (a) one batched hop per team ---------------------------------------------


def test_same_tick_gets_one_multiget_span_per_team():
    log = TraceLog()
    set_trace_log(log)
    # two single-replica teams: keys below b"\x80" on ss0, above on ss1
    sim, cluster, db = _cluster(seed=11, n_storage=2)
    keys = [b"a%02d" % i for i in range(6)] + [b"\x90k%02d" % i for i in range(6)]

    async def go():
        async def fill(tr):
            for k in keys:
                tr.set(k, b"v" + k)

        await db.run(fill)
        # warm the location cache with an UNSAMPLED transaction so the
        # measured round's gets all join the same tick (a cache miss
        # would defer that key's read behind a keyServers hop)
        warm = db.transaction()
        for k in keys:
            assert await warm.get(k) == b"v" + k
        tr = db.transaction()
        tr.set_debug_id("txn-read-pipeline")  # forces sampling
        await tr.get_read_version()
        futs = [spawn(tr.get(k)) for k in keys]
        vals = await wait_for_all(futs)
        assert vals == [b"v" + k for k in keys]
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    spans = _span_events(log)
    multigets = [s for s in spans if s["Name"] == "Storage.multiGet"]
    assert len(multigets) == 2, [
        (s["Name"], s.get("Machine")) for s in multigets
    ]
    assert {s.get("Machine") for s in multigets} == {"ss0", "ss1"}
    # the 12 sampled per-key hops collapsed: 6 keys per team in each batch
    assert sorted(s.get("keys") for s in multigets) == [6, 6]
    assert not [s for s in spans if s["Name"] == "Storage.getValue"]
    set_trace_log(TraceLog())


def test_same_tick_ranges_one_multigetrange_span():
    log = TraceLog()
    set_trace_log(log)
    sim, cluster, db = _cluster(seed=13)

    async def go():
        async def fill(tr):
            for i in range(40):
                tr.set(b"r%03d" % i, b"v%d" % i)

        await db.run(fill)
        warm = db.transaction()
        await warm.get(b"r000")
        tr = db.transaction()
        tr.set_debug_id("txn-range-pipeline")
        await tr.get_read_version()
        futs = [
            spawn(tr.get_range(b"r000", b"r005")),
            spawn(tr.get_range(b"r010", b"r020", limit=4)),
            spawn(tr.get_range(b"r020", b"r030", limit=3, reverse=True)),
        ]
        a, b, c = await wait_for_all(futs)
        assert [k for k, _ in a] == [b"r%03d" % i for i in range(5)]
        assert [k for k, _ in b] == [b"r%03d" % i for i in range(10, 14)]
        assert [k for k, _ in c] == [b"r%03d" % i for i in (29, 28, 27)]
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    spans = _span_events(log)
    mgr = [s for s in spans if s["Name"] == "Storage.multiGetRange"]
    assert len(mgr) == 1 and mgr[0].get("ranges") == 3, mgr
    assert not [s for s in spans if s["Name"] == "Storage.getRange"]
    set_trace_log(TraceLog())


# -- (b) byte-identical to the unbatched path ---------------------------------


def _battery(coalescing: bool):
    """A scripted RYW + selector + range mix; returns every read result."""
    knobs = Knobs(CLIENT_READ_COALESCING=coalescing)
    sim, cluster, db = _cluster(seed=7, n_storage=2, knobs=knobs)
    out = []

    async def go():
        async def fill(tr):
            for i in range(30):
                tr.set(b"d%03d" % i, b"base%d" % i)
            for i in range(6):
                tr.set(b"\x90m%02d" % i, b"hi%d" % i)

        await db.run(fill)

        tr = db.transaction()
        # RYW overlay: overwrite, atomic chain over a database value,
        # clear a band, then read it all back through the batched path
        tr.set(b"d005", b"mine")
        tr.atomic_op(MutationType.ADD, b"d007", (3).to_bytes(8, "little"))
        tr.clear_range(b"d010", b"d013")
        futs = [spawn(tr.get(b"d%03d" % i)) for i in range(16)]
        out.append(await wait_for_all(futs))
        # selector resolutions (merged-overlay and storage walks)
        sels = [
            KeySelector.first_greater_or_equal(b"d006"),
            KeySelector.last_less_than(b"d010"),
            KeySelector.first_greater_than(b"d029"),
            KeySelector.first_greater_or_equal(b"d000" + b"\x00"),
        ]
        out.append(
            await wait_for_all([spawn(tr.get_key(s)) for s in sels])
        )
        # ranges: forward, limited, reverse, cross-team, selector-ended
        rfuts = [
            spawn(tr.get_range(b"d000", b"d020", limit=7)),
            spawn(tr.get_range(b"d004", b"d016")),
            spawn(tr.get_range(b"d000", b"d030", limit=5, reverse=True)),
            spawn(tr.get_range(b"a", b"\xff")),
            spawn(
                tr.get_range(
                    KeySelector.first_greater_than(b"d002"), b"d009"
                )
            ),
        ]
        out.append(await wait_for_all(rfuts))
        await tr.commit()

        # a second transaction sees the committed state
        tr2 = db.transaction()
        out.append(await wait_for_all(
            [spawn(tr2.get(b"d%03d" % i)) for i in (5, 7, 11)]
        ))
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    return out


def test_coalesced_results_byte_identical_to_unbatched():
    assert _battery(True) == _battery(False)


# -- (c) bindingtester oracle with the knob both ways -------------------------


@pytest.mark.parametrize("coalescing", [True, False])
def test_bindingtester_oracle_with_coalescing_knob(coalescing):
    from test_bindingtester import run_model, run_real

    stream, (data_real, log_real) = run_real(
        seed=31, n_ops=400,
        knobs=Knobs(CLIENT_READ_COALESCING=coalescing),
    )
    data_model, log_model = run_model(stream)
    assert list(data_real) == list(data_model)
    assert list(log_real) == list(log_model)


# -- (d) per-entry faults ------------------------------------------------------


def test_too_old_subset_fails_only_that_future():
    sim, cluster, db = _cluster(seed=17)
    ss = cluster.storages[0]
    poison = b"f/poison"

    def inj(req, reply):
        if isinstance(req, MultiGetRequest):
            for i, k in enumerate(req.keys):
                if k == poison:
                    reply.errors = list(reply.errors) + [(i, READ_ERR_TOO_OLD)]
        return reply

    ss._read_fault_injector = inj

    async def go():
        async def fill(tr):
            for k in (b"f/a", poison, b"f/z"):
                tr.set(k, b"v" + k)

        await db.run(fill)
        warm = db.transaction()
        await warm.get(b"f/a")
        tr = db.transaction()
        await tr.get_read_version()
        futs = [spawn(tr.get(k)) for k in (b"f/a", poison, b"f/z")]
        for f in futs:
            await settled(f)
        from foundationdb_tpu.errors import TransactionTooOld

        assert futs[0].get() == b"vf/a"
        assert futs[2].get() == b"vf/z"
        assert futs[1].is_error()
        try:
            futs[1].get()
        except TransactionTooOld:
            pass
        return True

    assert sim.run_until_done(spawn(go()), 300.0)


def test_dropped_and_partial_replies_degrade_to_per_key_reads():
    sim, cluster, db = _cluster(seed=19)
    ss = cluster.storages[0]

    def inj(req, reply):
        if isinstance(req, MultiGetRequest) and len(req.keys) >= 2:
            # partial reply: the tail entry vanishes entirely, another is
            # marked dropped — the client must re-read both per-key
            reply.values = list(reply.values[:-1])
            reply.errors = list(reply.errors) + [(0, READ_ERR_DROPPED)]
        return reply

    ss._read_fault_injector = inj
    keys = [b"p/%02d" % i for i in range(8)]

    async def go():
        async def fill(tr):
            for k in keys:
                tr.set(k, b"v" + k)

        await db.run(fill)
        warm = db.transaction()
        await warm.get(keys[0])
        tr = db.transaction()
        await tr.get_read_version()
        vals = await wait_for_all([spawn(tr.get(k)) for k in keys])
        assert vals == [b"v" + k for k in keys]
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    assert ss.stats.counters["multiGetBatches"].value >= 1


def test_pipeline_depth_and_chunking_drain_queued_batches():
    knobs = Knobs(
        CLIENT_MULTIGET_MAX_KEYS=2, CLIENT_READ_PIPELINE_DEPTH=1
    )
    sim, cluster, db = _cluster(seed=23, knobs=knobs)
    ss = cluster.storages[0]
    keys = [b"q/%02d" % i for i in range(9)]

    async def go():
        async def fill(tr):
            for k in keys:
                tr.set(k, b"v" + k)

        await db.run(fill)
        warm = db.transaction()
        await warm.get(keys[0])
        tr = db.transaction()
        await tr.get_read_version()
        vals = await wait_for_all([spawn(tr.get(k)) for k in keys])
        assert vals == [b"v" + k for k in keys]
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    # 9 same-tick keys at max 2 per batch = 5 chunks, drained through the
    # depth-1 pipeline one at a time
    assert ss.stats.counters["multiGetBatches"].value >= 5


# -- tier-1-safe CPU smoke: the index answers the batch -----------------------


def test_batched_path_exercised_over_range_index_cpu():
    from foundationdb_tpu.net.sim import Endpoint
    from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
    from foundationdb_tpu.server.interfaces import Tokens

    knobs = Knobs(
        STORAGE_TPU_INDEX=True,
        MAX_READ_TRANSACTION_LIFE_VERSIONS=1_000_000,  # fast durability
    )
    sim = Sim(seed=71, knobs=knobs)
    sim.activate()
    cluster = DynamicCluster(sim, ClusterConfig(n_storage=1, n_tlogs=1))
    db = Database.from_coordinators(sim, cluster.coordinators)
    keys = [b"ix/%03d" % i for i in range(32)]

    async def go():
        async def fill(tr):
            for k in keys:
                tr.set(k, b"v" + k)

        await db.run(fill)
        # let the durability loop drop the rows to the engine and build
        # the range-index snapshot, so the batch MUST miss the window
        await delay(8.0)
        warm = db.transaction()
        await warm.get(keys[0])
        tr = db.transaction()
        await tr.get_read_version()
        vals = await wait_for_all([spawn(tr.get(k)) for k in keys])
        assert vals == [b"v" + k for k in keys]
        # legacy batchGet rides the same shared core (parity)
        version = await tr.get_read_version()
        reply = await db._proxy_request(
            Tokens.GET_KEY_SERVERS, GetKeyServersRequest(key=keys[0])
        )
        bg = await db.client.request(
            Endpoint(reply.team[0], Tokens.BATCH_GET), (keys, version)
        )
        assert bg == vals
        return True

    assert sim.run_until_done(spawn(go()), 600.0)
    # the batch's engine misses went through TpuRangeIndex.batch_lookup
    snaps = []
    for addr, proc in sim.processes.items():
        for token, handler in proc.endpoints.items():
            if token.startswith("storage.metrics#"):
                snaps.append((addr, handler))

    async def pull():
        out = []
        for _addr, h in snaps:
            out.append(await h(None))
        return out

    metrics = sim.run_until_done(spawn(pull()), 60.0)
    total_keys = sum(m.get("multiGetKeys", 0) for m in metrics)
    total_index = sum(m.get("multiGetIndexKeys", 0) for m in metrics)
    assert total_keys >= len(keys)
    assert total_index >= len(keys) - 1, (total_keys, total_index)
