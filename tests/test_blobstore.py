"""Blob-store backup tier: HTTP framing, S3-style store, container, and
backup/restore through it (fdbrpc/BlobStore.actor.cpp +
fdbclient/BackupContainer.actor.cpp's blobstore:// scheme analog)."""

import pytest

from foundationdb_tpu.backup.blobstore import (
    BlobStoreClient,
    BlobStoreContainer,
    BlobStoreServer,
    open_container,
    parse_blobstore_url,
)
from foundationdb_tpu.client import Database
from foundationdb_tpu.net import http
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.runtime.rng import DeterministicRandom
from foundationdb_tpu.server import Cluster, ClusterConfig
from foundationdb_tpu.workloads import run_workloads
from foundationdb_tpu.workloads.backup_workload import BackupWorkload


# -- framing ------------------------------------------------------------------


def test_http_framing_roundtrip():
    raw = http.encode_request("PUT", "/b/x/k", b"hello", {"X-Extra": "1"})
    method, path, headers, body = http.parse_request(raw)
    assert (method, path, body) == ("PUT", "/b/x/k", b"hello")
    assert headers["x-extra"] == "1"

    resp = http.encode_response(200, b"world")
    status, headers, body = http.parse_response(resp)
    assert (status, body) == (200, b"world")

    # incomplete frames parse as None, not garbage
    assert http.parse_request(raw[:10]) is None
    assert http.parse_response(resp[:-2]) is None


def test_url_parse():
    assert parse_blobstore_url("blobstore://bh:80/bucket/a/b") == (
        "bh", 80, "bucket", "a/b"
    )
    with pytest.raises(ValueError):
        parse_blobstore_url("blobstore://bh:80/bucketonly")


# -- simulated transport ------------------------------------------------------


def test_blob_crud_over_sim():
    sim = Sim(seed=1)
    sim.activate()
    server = BlobStoreServer()
    server.mount_sim(sim.new_process("blobhost"))
    client_proc = sim.new_process("blobclient")
    cl = BlobStoreClient(
        http.SimHttpTransport(client_proc, "blobhost"), "bkt"
    )

    async def go():
        await cl.put("a/1", b"one")
        await cl.put("a/2", b"two")
        await cl.put("b/1", b"three")
        assert await cl.get("a/1") == b"one"
        assert await cl.get("missing") is None
        assert await cl.list("a/") == ["a/1", "a/2"]
        assert await cl.list() == ["a/1", "a/2", "b/1"]
        await cl.delete("a/1")
        assert await cl.get("a/1") is None
        assert await cl.list("a/") == ["a/2"]
        return True

    assert sim.run_until_done(spawn(go()), 60.0)


def test_backup_restore_through_blobstore_sim():
    """The backup workload parameterized over the blobstore:// scheme —
    snapshot + mutation log travel as real HTTP bytes through the sim."""
    sim = Sim(seed=2)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig())
    db = Database(sim, cluster.proxy_addrs)
    BlobStoreServer().mount_sim(sim.new_process("blobhost"))

    w = BackupWorkload(
        db,
        DeterministicRandom(2),
        sim=sim,
        writes=25,
        container_url="blobstore://blobhost:80/backups/soak",
    )

    async def go():
        await run_workloads([w])
        return True

    assert sim.run_until_done(spawn(go()), 600.0)
    assert w.ok


def test_backup_restore_through_blobstore_under_chaos():
    """Same, with buggify armed and a clogged blob link mid-backup."""
    sim = Sim(seed=3, chaos=True)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig())
    db = Database(sim, cluster.proxy_addrs)
    BlobStoreServer().mount_sim(sim.new_process("blobhost"))

    w = BackupWorkload(
        db,
        DeterministicRandom(3),
        sim=sim,
        writes=25,
        container_url="blobstore://blobhost:80/backups/chaos",
    )

    async def go():
        from foundationdb_tpu.runtime.futures import delay

        t = spawn(run_workloads([w]))
        await delay(0.3)
        sim.clog_pair("client", "blobhost", 1.0)
        await t
        return True

    assert sim.run_until_done(spawn(go()), 600.0)
    assert w.ok


def test_container_log_seq_continues():
    """Two container handles on the same blob backup must not overwrite
    each other's log chunks (the directory container's invariant holds
    here too)."""
    sim = Sim(seed=4)
    sim.activate()
    server = BlobStoreServer()
    server.mount_sim(sim.new_process("blobhost"))
    proc = sim.new_process("c")

    async def go():
        c1 = BlobStoreContainer(
            BlobStoreClient(http.SimHttpTransport(proc, "blobhost"), "bkt"),
            "name",
        )
        await c1.reset()
        await c1.append_log_chunk([(b"k1", b"m1")])
        c2 = BlobStoreContainer(
            BlobStoreClient(http.SimHttpTransport(proc, "blobhost"), "bkt"),
            "name",
        )
        await c2.append_log_chunk([(b"k2", b"m2")])
        log = await c1.read_log()
        assert log == [(b"k1", b"m1"), (b"k2", b"m2")]
        return True

    assert sim.run_until_done(spawn(go()), 60.0)


# -- real sockets -------------------------------------------------------------


def test_blob_crud_over_real_http():
    """RealHttpTransport against the threaded stub server: actual TCP."""
    from foundationdb_tpu.runtime.loop import RealLoop, set_loop
    from foundationdb_tpu.tools.blobserver import RealBlobServer

    srv = RealBlobServer(port=0).start()
    loop = RealLoop(seed=9)
    set_loop(loop)
    try:
        cl = BlobStoreClient(
            http.RealHttpTransport(loop, "127.0.0.1", srv.port), "bkt"
        )

        async def go():
            await cl.put("x/1", b"alpha")
            await cl.put("x/2", b"beta" * 10_000)  # multi-read response
            assert await cl.get("x/1") == b"alpha"
            assert await cl.get("x/2") == b"beta" * 10_000
            assert await cl.list("x/") == ["x/1", "x/2"]
            await cl.delete("x/1")
            assert await cl.get("x/1") is None
            return True

        fut = spawn(go())
        loop.run(until=loop.now() + 30.0, stop_when=fut.is_ready)
        assert fut.is_ready() and fut.get()
    finally:
        srv.stop()
        loop.close()


def test_open_container_dispatch():
    sim = Sim(seed=5)
    sim.activate()
    BlobStoreServer().mount_sim(sim.new_process("blobhost"))
    proc = sim.new_process("c")
    c = open_container(
        "blobstore://blobhost:80/bkt/nm", sim=sim, process=proc
    )
    assert isinstance(c, BlobStoreContainer)
    from foundationdb_tpu.backup.container import BackupContainer

    c2 = open_container("file://store/nm", sim=sim)
    assert isinstance(c2, BackupContainer)


def test_tcp_cluster_backup_to_real_blobstore():
    """End-to-end over real processes: a TCP cluster backs up to a live
    blob server via the CLI's blobstore:// URL dispatch, and restores."""
    import tempfile

    from foundationdb_tpu.tools.blobserver import RealBlobServer
    from foundationdb_tpu.tools.tcp_soak import TcpCluster, fdbcli, wait_for

    srv = RealBlobServer(port=0).start()
    with tempfile.TemporaryDirectory(prefix="blob-tcp-") as d:
        cluster = TcpCluster(d)
        try:
            wait_for(
                lambda: (
                    fdbcli(cluster.coord, "set seed ok", timeout=30)[0] == 0,
                    "boot",
                ),
                180,
                "cluster never formed",
                cluster,
            )
            rc, out = fdbcli(
                cluster.coord, "set bk1 v1", "set bk2 v2", timeout=30
            )
            assert rc == 0, out
            url = f"blobstore://127.0.0.1:{srv.port}/bkt/t1"
            rc, out = fdbcli(cluster.coord, f"backup start {url}", timeout=60)
            assert rc == 0, out
            # the backup snapshot is in the blob server now
            assert any(
                k.startswith("t1/snap/") for (_b, k) in srv.core.objects
            ), sorted(srv.core.objects)
            # clobber, then restore from the blob target
            rc, out = fdbcli(cluster.coord, "set bk1 clobbered", timeout=30)
            assert rc == 0, out
            rc, out = fdbcli(cluster.coord, f"restore {url}", timeout=60)
            assert rc == 0, out
            rc, out = fdbcli(cluster.coord, "get bk1", "get bk2", timeout=30)
            assert rc == 0 and "v1" in out and "v2" in out, out
        finally:
            cluster.stop()
            srv.stop()
