"""The API-fuzz battery (verdict r3 missing #1): ApiCorrectness,
Serializability, and RywFuzz against the ModelStore oracle — plain, under
chaos (clogging + attrition), and in a DynamicCluster across recoveries."""

import pytest

from foundationdb_tpu.client import Database
from foundationdb_tpu.client.database import Database as Db
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.server import Cluster, ClusterConfig
from foundationdb_tpu.server.cluster import DynamicCluster
from foundationdb_tpu.workloads import (
    ApiCorrectnessWorkload,
    RandomCloggingWorkload,
    RywFuzzWorkload,
    SerializabilityWorkload,
    run_workloads,
)


def make_db(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(**cfg))
    db = Database(sim, cluster.proxy_addrs)
    return sim, cluster, db


def run_spec(sim, workloads, limit=900.0):
    sim.run_until_done(spawn(run_workloads(workloads)), limit)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_api_correctness(seed):
    sim, cluster, db = make_db(seed=seed)
    rng = sim.loop.random
    run_spec(
        sim,
        [
            ApiCorrectnessWorkload(db, rng.fork(), transactions=30, client_id=0),
            ApiCorrectnessWorkload(db, rng.fork(), transactions=30, client_id=1),
        ],
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_serializability(seed):
    sim, cluster, db = make_db(seed=seed, n_proxies=2, n_resolvers=2)
    rng = sim.loop.random
    run_spec(
        sim,
        [
            SerializabilityWorkload(
                db, rng.fork(), transactions=25, client_id=i, client_count=4
            )
            for i in range(4)
        ],
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_ryw_fuzz(seed):
    sim, cluster, db = make_db(seed=seed)
    rng = sim.loop.random
    run_spec(
        sim,
        [RywFuzzWorkload(db, rng.fork(), transactions=20, client_id=0)],
    )


def test_fuzz_battery_under_clogging():
    sim, cluster, db = make_db(
        seed=5, n_proxies=2, n_resolvers=2, n_storage=2, replication=2
    )
    rng = sim.loop.random
    run_spec(
        sim,
        [
            ApiCorrectnessWorkload(db, rng.fork(), transactions=20, client_id=0),
            SerializabilityWorkload(
                db, rng.fork(), transactions=15, client_id=0, client_count=2
            ),
            SerializabilityWorkload(
                db, rng.fork(), transactions=15, client_id=1, client_count=2
            ),
            RywFuzzWorkload(db, rng.fork(), transactions=12, client_id=1),
            RandomCloggingWorkload(db, rng.fork(), duration=4.0),
        ],
    )


@pytest.mark.parametrize("seed", [11, 12])
def test_fuzz_battery_across_recovery(seed):
    """DynamicCluster + master kill mid-fuzz: the battery must still verify
    (retry loops ride the recovery; unknown results disambiguate)."""
    sim = Sim(seed=seed)
    sim.activate()
    cluster = DynamicCluster(
        sim,
        ClusterConfig(n_storage=2, n_tlogs=2, tlog_replication=2),
        n_coordinators=3,
    )
    db = Db.from_coordinators(sim, cluster.coordinators)
    rng = sim.loop.random

    async def killer():
        from foundationdb_tpu.runtime.futures import delay

        await delay(2.0)
        for addr, p in list(sim.processes.items()):
            w = getattr(p, "worker", None)
            if w and p.alive and any(
                h.kind == "master" for h in w.roles.values()
            ):
                sim.kill_process(addr)
                return

    spawn(killer())
    run_spec(
        sim,
        [
            ApiCorrectnessWorkload(db, rng.fork(), transactions=25, client_id=0),
            SerializabilityWorkload(
                db, rng.fork(), transactions=20, client_id=0, client_count=2
            ),
            SerializabilityWorkload(
                db, rng.fork(), transactions=20, client_id=1, client_count=2
            ),
        ],
        limit=900.0,
    )
