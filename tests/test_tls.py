"""Mutual-TLS on the real-TCP transport (fdbrpc/TLSConnection analog).

Covered deterministically at the transport level: request/reply and
long-poll traffic between TLS worlds, simultaneous bidirectional
connects, plaintext rejection, and wrong-CA rejection. A full TLS
cluster boots and recovers (covered by boot assertions below); driving
it through many fdbcli invocations is timing-sensitive on this 1-core
box and is exercised by tools, not asserted here."""

import json
import os
import socket
import ssl

from foundationdb_tpu.tools.tcp_soak import free_ports
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def gen_ca_and_cert(dirpath, name="cluster"):
    """Self-signed CA + a cert it signs (openssl CLI)."""
    ca_key = f"{dirpath}/{name}-ca.key"
    ca_crt = f"{dirpath}/{name}-ca.crt"
    key = f"{dirpath}/{name}.key"
    csr = f"{dirpath}/{name}.csr"
    crt = f"{dirpath}/{name}.crt"
    run = lambda *a: subprocess.run(a, check=True, capture_output=True)
    run("openssl", "genrsa", "-out", ca_key, "2048")
    run(
        "openssl", "req", "-x509", "-new", "-key", ca_key, "-days", "1",
        "-subj", f"/CN={name}-ca", "-out", ca_crt,
    )
    run("openssl", "genrsa", "-out", key, "2048")
    run("openssl", "req", "-new", "-key", key, "-subj", f"/CN={name}", "-out", csr)
    run(
        "openssl", "x509", "-req", "-in", csr, "-CA", ca_crt, "-CAkey", ca_key,
        "-CAcreateserial", "-days", "1", "-out", crt,
    )
    return crt, key, ca_crt


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


_SERVER = r"""
import sys
sys.path.insert(0, {repo!r})
from foundationdb_tpu.net.tcp import RealWorld
from foundationdb_tpu.runtime.futures import delay

world = RealWorld({listen!r}, tls=dict(certfile={crt!r}, keyfile={key!r}, cafile={ca!r}))
world.activate()

async def slow(req):
    await delay(1.0)
    return ("pong", req)

async def fast(req):
    return ("fast", req)

world.node.register("slow", slow)
world.node.register("fast", fast)
print("up", flush=True)
world.run()
"""

_CLIENT = r"""
import sys
sys.path.insert(0, {repo!r})
from foundationdb_tpu.net.tcp import RealWorld
from foundationdb_tpu.net.sim import Endpoint
from foundationdb_tpu.runtime.futures import spawn, timeout as ftimeout

world = RealWorld("127.0.0.1:0", tls=dict(certfile={crt!r}, keyfile={key!r}, cafile={ca!r}))
world.activate()

async def body():
    ok = 0
    for i in range(5):
        r = await ftimeout(world.node.request(Endpoint({target!r}, "fast"), i), 10.0)
        ok += r is not None
    r = await ftimeout(world.node.request(Endpoint({target!r}, "slow"), 99), 10.0)
    ok += r is not None
    print("OK", ok, flush=True)
    return True

fut = spawn(body())
world.run(until=60.0, stop_when=fut.is_ready)
"""


def test_tls_transport_request_reply(tmp_path):
    crt, key, ca = gen_ca_and_cert(str(tmp_path))
    port, = free_ports(1)
    target = f"127.0.0.1:{port}"
    srv = subprocess.Popen(
        [sys.executable, "-c", _SERVER.format(repo=REPO, listen=target, crt=crt, key=key, ca=ca)],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        deadline = time.time() + 30
        while "up" not in (srv.stdout.readline() or ""):
            assert time.time() < deadline
        out = subprocess.run(
            [sys.executable, "-c", _CLIENT.format(repo=REPO, target=target, crt=crt, key=key, ca=ca)],
            env=_env(), capture_output=True, text=True, timeout=90,
        )
        assert "OK 6" in out.stdout, (out.stdout, out.stderr[-500:])

        # plaintext peer: must get nothing intelligible / be dropped
        s = socket.create_connection(("127.0.0.1", port), timeout=5)
        s.settimeout(3)
        s.sendall(b"not a tls hello")
        try:
            data = s.recv(100)
            assert data == b"" or b"127.0.0.1" not in data, data
        except (socket.timeout, ConnectionError):
            pass
        finally:
            s.close()

        # wrong CA: mutual auth rejects the handshake
        wrong_crt, wrong_key, wrong_ca = gen_ca_and_cert(
            str(tmp_path), name="intruder"
        )
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(wrong_crt, wrong_key)
        ctx.load_verify_locations(wrong_ca)
        ctx.check_hostname = False
        s = socket.create_connection(("127.0.0.1", port), timeout=5)
        s.settimeout(5)
        try:
            with ctx.wrap_socket(s) as w:
                w.recv(100)
            raise AssertionError("wrong-CA handshake unexpectedly succeeded")
        except ssl.SSLError:
            pass
        except (socket.timeout, ConnectionError):
            pass
        finally:
            s.close()
    finally:
        srv.kill()
        try:
            srv.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass


def fdbcli(coordinators, *cmds, tls3=None, timeout=45):
    extra = []
    if tls3:
        crt, key, ca = tls3
        extra = ["--tls-cert", crt, "--tls-key", key, "--tls-ca", ca]
    try:
        out = subprocess.run(
            [
                sys.executable, "-m", "foundationdb_tpu.tools.cli",
                "-C", coordinators,
                *[a for c in cmds for a in ("--exec", c)],
                "--timeout", str(max(timeout - 10, 5)),
                *extra,
            ],
            env=_env(), cwd=REPO, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        return -1, f"timed out: {e.stdout or ''}"
    return out.returncode, out.stdout


def test_tls_cluster_serves_and_rejects(tmp_path):
    """End to end over mutual TLS: the cluster serves an authed fdbcli;
    plaintext and wrong-CA clients get nothing."""
    tls3 = gen_ca_and_cert(str(tmp_path))
    wrong3 = gen_ca_and_cert(str(tmp_path), name="intruder")
    crt, key, ca = tls3
    cport, w1, w2 = free_ports(3)
    coord = f"127.0.0.1:{cport}"

    def boot(args):
        return subprocess.Popen(
            [
                sys.executable, "-m", "foundationdb_tpu.tools.fdbserver",
                *args, "--tls-cert", crt, "--tls-key", key, "--tls-ca", ca,
            ],
            env=_env(), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    procs = [
        boot(["--listen", coord, "--role", "coordinator",
              "--datadir", str(tmp_path / "c")])
    ]
    for port, pclass in ((w1, "storage"), (w2, "stateless")):
        procs.append(
            boot([
                "--listen", f"127.0.0.1:{port}",
                "--role", "worker",
                "--class", pclass,
                "--coordinators", coord,
                "--config", "n_storage=1,replication=1,n_tlogs=1",
                "--datadir", str(tmp_path / f"w{port}"),
            ])
        )
    try:
        deadline = time.time() + 180
        while True:
            for p in procs:
                assert p.poll() is None, p.stdout.read()
            rc, out = fdbcli(coord, "set sec ure", tls3=tls3, timeout=30)
            if rc == 0:
                break
            assert time.time() < deadline, f"TLS cluster never formed: {out}"
            time.sleep(2)
        rc, out = fdbcli(coord, "get sec", tls3=tls3)
        assert rc == 0 and "ure" in out, out
        rc, out = fdbcli(coord, "get sec", tls3=None, timeout=20)
        assert rc != 0 or "ure" not in out, out
        rc, out = fdbcli(coord, "get sec", tls3=wrong3, timeout=20)
        assert rc != 0 or "ure" not in out, out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass


def test_tls_cluster_forms(tmp_path):
    """A mutual-TLS cluster of real processes elects, recruits every role,
    and fully recovers (asserted from trace events)."""
    crt, key, ca = gen_ca_and_cert(str(tmp_path))
    cport, w1, w2 = free_ports(3)
    coord = f"127.0.0.1:{cport}"

    def boot(args, tf):
        return subprocess.Popen(
            [
                sys.executable, "-m", "foundationdb_tpu.tools.fdbserver",
                *args, "--tracefile", tf,
                "--tls-cert", crt, "--tls-key", key, "--tls-ca", ca,
            ],
            env=_env(), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    traces = [str(tmp_path / f"t{i}.trace") for i in range(3)]
    procs = [
        boot(
            ["--listen", coord, "--role", "coordinator",
             "--datadir", str(tmp_path / "c")],
            traces[0],
        )
    ]
    for i, (port, pclass) in enumerate(((w1, "storage"), (w2, "stateless")), 1):
        procs.append(
            boot(
                [
                    "--listen", f"127.0.0.1:{port}",
                    "--role", "worker",
                    "--class", pclass,
                    "--coordinators", coord,
                    "--config", "n_storage=1,replication=1,n_tlogs=1",
                    "--datadir", str(tmp_path / f"w{port}"),
                ],
                traces[i],
            )
        )
    try:
        deadline = time.time() + 180
        while True:
            for p in procs:
                assert p.poll() is None
            types = set()
            for tf in traces:
                try:
                    for line in open(tf):
                        types.add(json.loads(line)["Type"])
                except FileNotFoundError:
                    pass
            if "MasterFullyRecovered" in types:
                break
            assert time.time() < deadline, f"no recovery over TLS: {sorted(types)}"
            time.sleep(2)
        assert "ElectionWon" in types and "RoleRecruited" in types
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
