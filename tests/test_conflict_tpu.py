"""Differential tests: TPU conflict kernel vs the Python oracle.

The reference validates its skip list against SlowConflictSet
(SkipList.cpp:59-88) and with the oracle-checked ConflictRange workload
(fdbserver/workloads/ConflictRange.actor.cpp); this is the same strategy —
randomized batches must produce byte-identical verdict sequences.
"""

import random

import pytest

from foundationdb_tpu.conflict.api import CommitTransaction, Verdict, new_conflict_set


def _random_range(rnd, keyspace):
    a = rnd.randrange(keyspace)
    b = a + 1 + rnd.randrange(10)
    enc = lambda x: b"k%08d" % x
    return (enc(a), enc(b))


def _random_batch(rnd, keyspace, n_txns, snap_lo, snap_hi):
    txs = []
    for _ in range(n_txns):
        tr = CommitTransaction(read_snapshot=rnd.randrange(snap_lo, snap_hi + 1))
        for _ in range(rnd.randrange(0, 3)):
            tr.read_conflict_ranges.append(_random_range(rnd, keyspace))
        for _ in range(rnd.randrange(0, 3)):
            tr.write_conflict_ranges.append(_random_range(rnd, keyspace))
        txs.append(tr)
    return txs


def _run_differential(seed, batches, keyspace, n_txns, capacity=1 << 8):
    rnd = random.Random(seed)
    tpu = new_conflict_set("tpu", capacity=capacity)
    oracle = new_conflict_set("oracle")
    version = 100
    for b in range(batches):
        oldest = max(0, version - 40)  # sliding MVCC window
        snap_lo = max(0, version - 60)  # sometimes below the horizon → TOO_OLD
        txs = _random_batch(rnd, keyspace, n_txns, snap_lo, version)
        vt = tpu.detect_batch(txs, version + 10, oldest)
        vo = oracle.detect_batch(txs, version + 10, oldest)
        assert vt == vo, f"batch {b} diverged: tpu={vt} oracle={vo}"
        version += 10
    # abort-rate sanity: contention must actually produce every verdict kind
    return None


@pytest.mark.parametrize("seed", range(4))
def test_differential_high_contention(seed):
    # tiny keyspace → heavy overlap, exercises history + intra-batch + GC
    _run_differential(seed, batches=25, keyspace=30, n_txns=12)


@pytest.mark.parametrize("seed", range(2))
def test_differential_low_contention(seed):
    _run_differential(seed + 100, batches=10, keyspace=100000, n_txns=16)


def test_differential_growth_from_tiny_capacity():
    # capacity 16 forces repeated index growth mid-run
    _run_differential(7, batches=20, keyspace=500, n_txns=10, capacity=16)


def test_point_and_edge_semantics_match_oracle():
    tpu = new_conflict_set("tpu", capacity=1 << 6)
    oracle = new_conflict_set("oracle")

    def both(txs, now, oldest):
        a = tpu.detect_batch(txs, now, oldest)
        b = oracle.detect_batch(txs, now, oldest)
        assert a == b, (a, b)
        return a

    t0 = CommitTransaction(0, [], [(b"k", b"k\x00")])
    assert both([t0], 10, 0) == [Verdict.COMMITTED]
    # exact point read of the written key vs adjacent point
    r_hit = CommitTransaction(5, [(b"k", b"k\x00")], [])
    r_miss = CommitTransaction(5, [(b"k\x00", b"k\x00\x00")], [])
    assert both([r_hit, r_miss], 11, 0) == [Verdict.CONFLICT, Verdict.COMMITTED]
    # empty ranges are no-ops
    weird = CommitTransaction(5, [(b"z", b"a")], [(b"q", b"q")])
    assert both([weird], 12, 0) == [Verdict.COMMITTED]
    # intra-batch chain: w(a), r(a)+w(b), r(b) → C, X, C
    c0 = CommitTransaction(12, [], [(b"a", b"b")])
    c1 = CommitTransaction(12, [(b"a", b"b")], [(b"b", b"c")])
    c2 = CommitTransaction(12, [(b"b", b"c")], [])
    assert both([c0, c1, c2], 13, 0) == [
        Verdict.COMMITTED,
        Verdict.CONFLICT,
        Verdict.COMMITTED,
    ]


def test_clear_resets_history():
    tpu = new_conflict_set("tpu", capacity=1 << 6)
    tpu.detect_batch([CommitTransaction(0, [], [(b"a", b"b")])], 10, 0)
    tpu.clear(20)
    out = tpu.detect_batch([CommitTransaction(25, [(b"a", b"b")], [])], 30, 20)
    assert out == [Verdict.COMMITTED]


def test_detect_many_matches_sequential():
    # the scanned multi-batch path must agree with batch-at-a-time resolution
    rnd = random.Random(11)
    seq = new_conflict_set("tpu", capacity=1 << 8)
    piped = new_conflict_set("tpu", capacity=1 << 8)
    version = 100
    work = []
    expected = []
    for b in range(8):
        oldest = max(0, version - 40)
        txs = _random_batch(rnd, 40, 10, max(0, version - 60), version)
        work.append((txs, version + 10, oldest))
        expected.append(seq.detect_batch(txs, version + 10, oldest))
        version += 10
    got = piped.detect_many(work)
    assert got == expected


def test_native_backend_matches_oracle():
    pytest.importorskip("ctypes")
    rnd = random.Random(5)
    nat = new_conflict_set("native")
    orc = new_conflict_set("oracle")
    version = 100
    for b in range(20):
        oldest = max(0, version - 40)
        txs = _random_batch(rnd, 30, 12, max(0, version - 60), version)
        vn = nat.detect_batch(txs, version + 10, oldest)
        vo = orc.detect_batch(txs, version + 10, oldest)
        assert vn == vo, f"batch {b}: {vn} vs {vo}"
        version += 10


def test_long_key_point_write_not_dropped():
    # Keys beyond width-1 bytes: the encoded range must widen, never collapse
    # to empty — a dropped write would be a missed conflict (serializability
    # violation). Conservative false conflicts are acceptable here.
    k = b"p" * 40  # longer than the 31-byte exact window
    tpu = new_conflict_set("tpu", capacity=1 << 6)
    tpu.detect_batch([CommitTransaction(0, [], [(k, k + b"\x00")])], 10, 0)
    out = tpu.detect_batch([CommitTransaction(5, [(k, k + b"\x00")], [])], 11, 0)
    assert out == [Verdict.CONFLICT]


def test_native_clear_preserves_horizon():
    nat = new_conflict_set("native")
    nat.clear(20)
    out = nat.detect_batch([CommitTransaction(5, [(b"a", b"b")], [])], 30, 20)
    assert out == [Verdict.TOO_OLD]


def test_pre_encoded_too_old_tracks_horizon():
    # TOO_OLD must be decided at resolve time (device-side), not encode time.
    tpu = new_conflict_set("tpu", capacity=1 << 8)
    stale = tpu.encode([CommitTransaction(5, [(b"a", b"b")], [])])
    filler = tpu.encode([CommitTransaction(55, [], [(b"x", b"y")])])
    outs = tpu.detect_many_encoded([(filler, 60, 50), (stale, 100, 50)])
    assert outs[1] == [Verdict.TOO_OLD]


def test_verdict_mix_under_contention():
    # ensure the differential workloads actually exercise all verdicts
    rnd = random.Random(3)
    tpu = new_conflict_set("tpu", capacity=1 << 8)
    seen = set()
    version = 100
    for b in range(30):
        oldest = max(0, version - 40)
        txs = _random_batch(rnd, 30, 12, max(0, version - 60), version)
        for v in tpu.detect_batch(txs, version + 10, oldest):
            seen.add(v)
        version += 10
    assert seen == {Verdict.COMMITTED, Verdict.CONFLICT, Verdict.TOO_OLD}


def test_hot_key_batch_exceeding_slot_capacity():
    """A batch where every transaction writes the SAME key must not
    overflow the grid (staged rows aggregate per distinct boundary —
    repivoting could never split equal codes across buckets)."""
    tpu = new_conflict_set("tpu", capacity=1 << 8)  # S=32 slots
    oracle = new_conflict_set("oracle")
    point = [(b"counter", b"counter\x00")]
    txs = [
        CommitTransaction(read_snapshot=0, write_conflict_ranges=list(point))
        for _ in range(40)
    ]
    assert tpu.detect_batch(txs, 10, 0) == oracle.detect_batch(txs, 10, 0)
    rw = [
        CommitTransaction(
            read_snapshot=5,
            read_conflict_ranges=list(point),
            write_conflict_ranges=list(point),
        )
        for _ in range(40)
    ]
    assert tpu.detect_batch(rw, 20, 0) == oracle.detect_batch(rw, 20, 0)


def test_clear_to_end_of_keyspace_boundary():
    # A clear_range ending at/past the maximal encodable key stages a row
    # whose code equals the all-0xFF staging sentinel; the merge sort must
    # still keep it separate from padding rows (grid.merge_writes sorts by
    # (bucket, code) so padding — bucket B — can never interleave).
    # Differentially check against the oracle across a few follow-up reads.
    tpu = new_conflict_set("tpu", capacity=1 << 6)
    oracle = new_conflict_set("oracle")
    end = b"\xff" * 40  # encodes to the sentinel code at any key width
    batches = [
        [CommitTransaction(0, [], [(b"m", end)])],
        [CommitTransaction(5, [(b"z", end)], [])],  # read inside cleared tail
        [CommitTransaction(12, [(b"a", b"b")], [(b"q", b"r")])],
        [CommitTransaction(12, [(b"n", end)], [])],
    ]
    v = 10
    for txs in batches:
        got = tpu.detect_batch(txs, v, 0)
        want = oracle.detect_batch(txs, v, 0)
        assert got == want, (got, want, txs)
        v += 1


def test_many_hot_writes_to_sentinel_key():
    # many txns in ONE batch all clearing to end-of-keyspace: the staged
    # sentinel-coded rows aggregate into a single boundary without
    # clobbering the touched-bucket bookkeeping (nondeterministic winner
    # was possible when padding shared the run)
    tpu = new_conflict_set("tpu", capacity=1 << 6)
    oracle = new_conflict_set("oracle")
    end = b"\xff" * 40
    hot = [
        CommitTransaction(0, [], [(b"h%02d" % i, end)]) for i in range(20)
    ]
    probe = [CommitTransaction(3, [(b"h05", b"h06")], [])]
    for txs, v in ((hot, 10), (probe, 11)):
        got = tpu.detect_batch(txs, v, 0)
        want = oracle.detect_batch(txs, v, 0)
        assert got == want, (got, want)
