"""Transaction debug chains + status machine/process sections
(g_traceBatch attach ids, MasterProxyServer.actor.cpp:345; Status's
processStatus sections)."""

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.tools.commit_chain import chain, format_chain, sampled_ids


def test_commit_debug_chain_covers_every_stage():
    sim = Sim(seed=51)
    sim.activate()
    cluster = DynamicCluster(
        sim, ClusterConfig(n_proxies=2, n_resolvers=2), n_coordinators=1
    )
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def go():
        tr = db.transaction()
        tr.set_debug_id("probe-1")
        await tr.get(b"warm")  # pins a read version (GRV in the chain)
        tr.set(b"dbg", b"v")
        await tr.commit()
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    evs = chain("probe-1")
    stages = [e["Event"] for e in evs]
    for must in (
        "ClientCommitStart",
        "ProxyReceived",
        "GotCommitVersion",
        "Resolving",
        "Resolved",
        "Logged",
        "Replied",
        "ClientCommitDone",
    ):
        assert must in stages, (must, stages)
    # time-ordered with a sane total
    times = [e["Time"] for e in evs]
    assert times == sorted(times)
    total_ms = (times[-1] - times[0]) * 1000
    assert 0 < total_ms < 1000
    text = format_chain("probe-1")
    assert "ms total" in text and "Logged" in text
    assert "probe-1" in sampled_ids()


def test_commit_sampling_knob():
    sim = Sim(seed=52)
    sim.activate()
    sim.knobs.CLIENT_COMMIT_SAMPLE = 1.0  # tag every commit
    cluster = DynamicCluster(sim, ClusterConfig(), n_coordinators=1)
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def go():
        for i in range(3):

            async def put(tr, i=i):
                tr.set(b"s%d" % i, b"v")

            await db.run(put)
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    ids = [i for i in sampled_ids() if i.startswith("txn-")]
    assert len(ids) >= 3
    for did in ids[:3]:
        stages = [e["Event"] for e in chain(did)]
        assert "Replied" in stages, (did, stages)


def test_status_machine_process_sections():
    from foundationdb_tpu.client.management import get_status

    sim = Sim(seed=53)
    sim.activate()
    cluster = DynamicCluster(sim, ClusterConfig(), n_coordinators=1)
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def go():
        async def put(tr):
            tr.set(b"x", b"1")

        await db.run(put)
        await delay(5.0)  # let SystemMonitor produce samples
        doc = await get_status(cluster.coordinators, db.client)
        assert doc.get("processes"), doc.keys()
        for _addr, sm in doc["processes"].items():
            assert "RunLoopLag" in sm and "Actors" in sm
        assert doc.get("machines")
        m = next(iter(doc["machines"].values()))
        assert m["processes"] >= 1
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
