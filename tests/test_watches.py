"""Watches and change feeds (ISSUE 16).

The notification subsystem end to end: WatchManager staging/committed-
frontier gating at the unit level; the client cancel/Cancelled discipline
(reset cancels outstanding watches promptly, storage death surfaces
BrokenPromise to the re-registration loop instead of wedging, failover
re-registration never double-fires a future); change-feed streaming,
resume and the retention-floor TOO_OLD; the status/cli surface; and the
pub/sub layer built on both.
"""

import pytest

from foundationdb_tpu.client import Database
from foundationdb_tpu.errors import (
    TooManyWatches,
    TransactionCancelled,
    TransactionTooOld,
    WrongShardServer,
)
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn, timeout
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.server import Cluster, ClusterConfig


def make_db(seed=0, knobs=None, **cfg):
    sim = Sim(seed=seed, knobs=knobs)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(**cfg))
    db = Database(sim, cluster.proxy_addrs)
    return sim, cluster, db


def drive(sim, coro, limit=120.0):
    return sim.run_until_done(spawn(coro), limit)


# -- WatchManager unit: staging, committed gating, limits, rollback -----------


def _manager(knobs=None):
    from foundationdb_tpu.runtime.stats import CounterCollection
    from foundationdb_tpu.server.watches import WatchManager

    c = CounterCollection("t", "t")
    return WatchManager(
        knobs or Knobs(),
        registered=c.counter("r"),
        fired=c.counter("f"),
        cancelled=c.counter("c"),
        streamed=c.counter("s"),
        fanout_batches=c.counter("b"),
    )


def test_watch_fires_only_past_committed_frontier():
    """An applied-but-uncommitted epoch must not fire: triggers wait for
    the known-committed frontier (the zero-phantom invariant)."""
    wm = _manager()
    e = wm.register(b"k", None)
    wm.on_epoch(100, {b"k": b"v1"}, (), 0.0)
    assert not e.future.is_ready()
    wm.advance_committed(99, 0.0)
    assert not e.future.is_ready()  # frontier still below the epoch
    wm.advance_committed(100, 0.0)
    assert e.future.is_ready()
    assert e.future.get() == (b"v1", 100)
    assert wm.parked_count() == 0


def test_rollback_drops_staged_epoch_without_firing():
    """A recovery rollback truncates staged (uncommitted) epochs: the
    watch they would have triggered never fires with rolled-back data."""
    wm = _manager()
    e = wm.register(b"k", None)
    wm.on_epoch(100, {b"k": b"ghost"}, (), 0.0)
    wm.rollback_after(50)
    wm.advance_committed(200, 0.0)
    assert not e.future.is_ready()  # the ghost write never committed
    # the NEXT committed change fires normally
    wm.on_epoch(300, {b"k": b"real"}, (), 0.0)
    wm.advance_committed(300, 0.0)
    assert e.future.get() == (b"real", 300)


def test_watches_fire_in_version_order_one_fanout_batch():
    """Several staged epochs covered by one frontier advance fire in
    version order and count one fan-out batch."""
    wm = _manager()
    entries = [wm.register(b"k%d" % i, None) for i in range(3)]
    for i, v in enumerate((10, 20, 30)):
        wm.on_epoch(v, {b"k%d" % i: b"x"}, (), 0.0)
    wm.advance_committed(30, 0.0)
    versions = [e.future.get()[1] for e in entries]
    assert versions == [10, 20, 30]
    assert wm._c_fanout.value == 1


def test_clear_range_fires_none_and_same_value_does_not_fire():
    wm = _manager()
    ea = wm.register(b"a", b"old")
    eb = wm.register(b"b", b"same")
    wm.on_epoch(10, {b"b": b"same"}, ((b"a", b"a\x00"),), 0.0)
    wm.advance_committed(10, 0.0)
    assert ea.future.get() == (None, 10)  # cleared → fires with None
    assert not eb.future.is_ready()  # unchanged value → no fire


def test_watch_limit_raises_typed_retryable():
    knobs = Knobs(STORAGE_WATCH_LIMIT=2)
    wm = _manager(knobs)
    wm.register(b"a", None)
    wm.register(b"b", None)
    with pytest.raises(TooManyWatches) as ei:
        wm.register(b"c", None)
    assert ei.value.retryable
    assert wm.bytes_held() > 0


def test_watch_bytes_gauge_tracks_registration_lifecycle():
    wm = _manager()
    e1 = wm.register(b"key1", b"value-bytes")
    held = wm.bytes_held()
    assert held >= len(b"key1") + len(b"value-bytes")
    wm.deregister(e1)
    assert wm.bytes_held() == 0 and wm.parked_count() == 0
    assert wm._c_cancelled.value == 1  # unfired deregister is a cancel


def test_fail_range_on_shard_drop():
    """A shard drop fails its parked watches with WrongShardServer (so
    holders re-locate) — it must NOT fire them as a data clear."""
    wm = _manager()
    e = wm.register(b"m", b"v")
    out = wm.register(b"z", b"v")  # outside the dropped range
    wm.fail_range(b"a", b"n", WrongShardServer)
    with pytest.raises(WrongShardServer):
        e.future.get()
    assert not out.future.is_ready()
    assert wm.parked_count() == 1


def test_feed_collect_pages_whole_versions_and_resumes():
    wm = _manager()
    wm.on_epoch(10, {b"a": b"1", b"b": b"2"}, (), 0.0)
    wm.on_epoch(20, {b"a": b"3"}, ((b"b", b"c"),), 0.0)
    wm.advance_committed(20, 0.0)
    batches, nv, more = wm.feed_collect(b"", b"\xff", 0, 100, "s1", 0.0)
    assert [b[0] for b in batches] == [10, 20]
    assert batches[0][2] == [(b"a", b"1"), (b"b", b"2")]
    assert batches[1][1] == [(b"b", b"c")] and batches[1][2] == [(b"a", b"3")]
    assert nv == 20 and not more
    # resume from mid-stream: only the later version
    batches, _, _ = wm.feed_collect(b"", b"\xff", 10, 100, "s1", 0.0)
    assert [b[0] for b in batches] == [20]
    # tiny page limit: whole versions still never split
    batches, nv, more = wm.feed_collect(b"", b"\xff", 0, 1, "s1", 0.0)
    assert [b[0] for b in batches] == [10] and more and nv == 10


def test_feed_too_old_below_retention_floor():
    knobs = Knobs(STORAGE_FEED_RETENTION_VERSIONS=100)
    wm = _manager(knobs)
    wm.on_epoch(10, {b"a": b"1"}, (), 0.0)
    wm.advance_committed(10, 0.0)
    wm.advance_committed(1000, 0.0)  # floor = 1000 - 100 = 900
    with pytest.raises(TransactionTooOld):
        wm.feed_collect(b"", b"\xff", 10, 100, "", 0.0)


def test_feed_lease_holds_floor_but_is_capped():
    """An active subscriber's cursor pins the retention floor; an
    abandoned one cannot hold it past 2x retention."""
    knobs = Knobs(STORAGE_FEED_RETENTION_VERSIONS=100)
    wm = _manager(knobs)
    wm.on_epoch(10, {b"a": b"1"}, (), 0.0)
    wm.advance_committed(10, 0.0)
    # subscriber parked at version 10 with a live lease
    wm.feed_collect(b"", b"\xff", 0, 100, "slow", now := 0.0)
    wm.advance_committed(150, now)  # plain retention would floor at 50
    assert wm._floor <= 10  # lease held it
    wm.advance_committed(500, now)  # 2x-retention cap: 500-200=300 > 10
    assert wm._floor == 300  # abandoned subscriber cannot wedge memory


# -- client cancel / Cancelled discipline -------------------------------------


def test_reset_cancels_precommit_watch_future():
    """watch() before commit, then reset: the future errors promptly with
    the non-retryable TransactionCancelled (fdb's watch lifetime)."""
    sim, cluster, db = make_db()

    async def body():
        tr = db.transaction()
        fut = tr.watch(b"never")
        tr.reset()
        with pytest.raises(TransactionCancelled) as ei:
            fut.get()
        assert not ei.value.retryable
        return True

    assert drive(sim, body())


def test_reset_cancels_parked_postcommit_watch():
    """A committed watch parked server-side dies with the transaction
    that owns it: reset() cancels the actor and the future errors with
    TransactionCancelled PROMPTLY (no waiting out the park). The server
    slot is abandoned, not leaked: like the reference, it drains when
    the key next changes (fire into the void), and the cancelled future
    is never overwritten by that late fire."""
    sim, cluster, db = make_db()

    async def body():
        tr = db.transaction()
        tr.set(b"k", b"v0")
        fut = tr.watch(b"k")
        await tr.commit()
        await delay(1.0)  # actor registers and parks server-side
        ss = cluster.storages[0]
        assert ss.watches.parked_count() == 1
        tr.reset()
        await delay(0.001)  # one tick: cancel delivery, not a park wait
        with pytest.raises(TransactionCancelled):
            fut.get()  # errored at reset time, not after a park

        async def change(t):
            t.set(b"k", b"v1")

        await db.run(change)
        await delay(1.0)
        assert ss.watches.parked_count() == 0  # abandoned slot drained
        with pytest.raises(TransactionCancelled):
            fut.get()  # the late fire never resurrects the future
        return True

    assert drive(sim, body())


def test_watch_only_txn_anchors_baseline_no_lost_wakeup():
    """The seed-5 chaos-soak find: a watch-only transaction has no read
    version, and reading the baseline at a FRESH version silently adopts
    a change that lands between commit and registration — a permanent
    lost wakeup. The commit must anchor a GRV for its watches: a change
    racing the (clogged) registration still fires."""
    sim, cluster, db = make_db()

    async def body():
        tr = db.transaction()
        fut = tr.watch(b"race")  # no reads, no writes: watch-only
        await tr.commit()  # anchors the baseline GRV
        # delay the watch actor's baseline read + registration past the
        # racing change: clog the client<->storage link only (the change
        # commits through proxy/tlog, which stay clear)
        ss_addr = cluster.storages[0].process.address
        sim.clog_pair("client", ss_addr, 2.0)

        async def change(t):
            t.set(b"race", b"landed")

        await db.run(change)
        assert not fut.is_ready()  # registration still clogged out
        got = await timeout(fut, 60.0, default=b"LOST")
        assert got == b"landed"
        return True

    assert drive(sim, body())


def test_db_run_watch_survives_and_fires_after_success():
    """db.run does NOT cancel watches on success: the returned future
    outlives the retry loop and fires on the next change."""
    sim, cluster, db = make_db()

    async def body():
        async def register(tr):
            tr.set(b"wk", b"v0")
            return tr.watch(b"wk")

        fut = await db.run(register)
        await delay(0.5)
        assert not fut.is_ready()

        async def change(tr):
            tr.set(b"wk", b"v1")

        await db.run(change)
        assert await timeout(fut, 60.0, default=b"LOST") == b"v1"
        return True

    assert drive(sim, body())


def test_storage_death_brokenpromise_reregisters_no_duplicate_fire():
    """Kill the storage holding a parked watch: the parked RPC breaks
    (BrokenPromise), the client loop re-registers on the surviving
    replica at the original baseline, and the eventual change fires the
    future EXACTLY once with the committed value."""
    sim, cluster, db = make_db(replication=2, n_storage=2)

    async def body():
        async def register(tr):
            tr.set(b"fk", b"v0")
            return tr.watch(b"fk")

        fut = await db.run(register)
        await delay(1.0)
        parked = [s for s in cluster.storages if s.watches.parked_count()]
        assert parked, "watch never parked"
        sim.kill_process(parked[0].process.address)
        await delay(1.0)
        assert not fut.is_ready()  # death alone must not fire/err it

        async def change(tr):
            tr.set(b"fk", b"v1")

        await db.run(change)
        assert await timeout(fut, 60.0, default=b"LOST") == b"v1"
        # duplicate-fire suppression is structural (Future sets once);
        # give any straggler re-registration time to misbehave
        await delay(2.0)
        assert fut.get() == b"v1"
        return True

    assert drive(sim, body(), 300.0)


# -- change feed end to end ----------------------------------------------------


def test_change_feed_streams_and_resumes():
    sim, cluster, db = make_db()

    async def body():
        async def w1(tr):
            tr.set(b"f/a", b"1")
            tr.set(b"f/b", b"2")

        async def w2(tr):
            tr.clear(b"f/a")
            tr.set(b"f/c", b"3")

        await db.run(w1)
        await db.run(w2)
        feed = db.change_feed(b"f/", b"f0", from_version=0)
        events = []
        versions = []
        while len(events) < 4:
            for b in await feed.next_batches():
                versions.append(b.version)
                events.extend(("clear", c) for c in b.clears)
                events.extend(("set", s) for s in b.sets)
        assert versions == sorted(versions)
        assert ("set", (b"f/a", b"1")) in events
        assert ("set", (b"f/c", b"3")) in events
        assert any(k == "clear" and c[0] <= b"f/a" < c[1] for k, c in events)
        # replaying the feed reproduces the range
        state = {}
        feed2 = db.change_feed(b"f/", b"f0", from_version=0)
        got = 0
        while got < 4:
            for b in await feed2.next_batches():
                for cb, ce in b.clears:
                    for k in [k for k in state if cb <= k < ce]:
                        del state[k]
                for k, v in b.sets:
                    state[k] = v
                    got += 1
                got += len(b.clears)
        async def read(tr):
            return await tr.get_range(b"f/", b"f0")

        assert sorted(state.items()) == sorted(await db.run(read))
        # resume from the first feed's cursor: nothing new yet
        feed3 = db.change_feed(b"f/", b"f0", from_version=feed.version)
        nxt = spawn(feed3.next_batches())
        await delay(0.5)
        assert not nxt.is_ready()  # parked, not replaying history

        async def w3(tr):
            tr.set(b"f/d", b"4")

        await db.run(w3)
        batches = await timeout(nxt, 60.0, default=None)
        assert batches and batches[-1].sets == [(b"f/d", b"4")]
        return True

    assert drive(sim, body(), 300.0)


def test_change_feed_too_old_surfaces_to_client():
    knobs = Knobs(STORAGE_FEED_RETENTION_VERSIONS=1000)
    sim, cluster, db = make_db(knobs=knobs)

    async def body():
        async def w(tr):
            tr.set(b"t/a", b"1")

        await db.run(w)
        # let the committed frontier run far past retention
        await delay(3.0)
        feed = db.change_feed(b"t/", b"t0", from_version=1)
        with pytest.raises(TransactionTooOld):
            await feed.next_batches()
        return True

    assert drive(sim, body())


# -- surface: status doc, cli line, flowlint pin ------------------------------


def test_status_and_cli_surface_watches():
    """Counters flow storage.metrics → status workload.watches → the
    `cli status` "Watches:" line."""
    from foundationdb_tpu.client import management
    from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
    from foundationdb_tpu.tools.cli import FdbCli

    sim = Sim(seed=3)
    sim.activate()
    cluster = DynamicCluster(
        sim, ClusterConfig(n_storage=1, n_tlogs=1, n_proxies=1)
    )
    db = Database.from_coordinators(sim, cluster.coordinators)
    cli = FdbCli(db, cluster.coordinators)

    async def go():
        async def register(tr):
            return [tr.watch(b"st/%d" % i) for i in range(5)]

        futs = await db.run(register)

        async def release(tr):
            for i in range(5):
                tr.set(b"st/%d" % i, b"go")

        await db.run(release)
        for f in futs:
            await timeout(f, 60.0)
        await delay(6.0)  # metrics poll interval
        doc = await management.get_status(cluster.coordinators, db.client)
        text = await cli.execute("status")
        return doc, text

    doc, text = sim.run_until_done(spawn(go()), 600.0)
    wa = doc["workload"]["watches"]
    assert wa["registered"]["counter"] >= 5
    assert wa["fired"]["counter"] >= 5
    assert wa["fanout_batches"]["counter"] >= 1
    assert "Watches:" in text, text
    assert "fan-out batches" in text


def test_flowlint_pins_watch_counters():
    """Dropping a watch counter the config pins must flag
    reg-role-metrics — the watches status/cli surface cannot silently go
    dark (ISSUE 16 satellite)."""
    from foundationdb_tpu.tools.flowlint import lint, load_config

    config = load_config()
    pinned = set(config["role_required_counters"]["storage"])
    assert {
        "watchesRegistered",
        "watchesFired",
        "watchesCancelled",
        "watchFanoutBatches",
        "feedEntriesStreamed",
        "watchesParked",
        "watchBytes",
    } <= pinned
    config["role_required_counters"] = {"storage": ["watchesMissingCtr"]}
    result = lint(config=config)
    hits = [
        f
        for f in result.failing
        if f.rule == "reg-role-metrics" and "watchesMissingCtr" in f.detail
    ]
    assert hits, "missing required watch counter did not flag"


# -- pub/sub layer -------------------------------------------------------------


def test_pubsub_topic_watch_wake_and_feed_tail():
    from foundationdb_tpu.layers import Subspace, Topic

    sim, cluster, db = make_db()
    topic = Topic(Subspace(("ps",)), "news")

    async def body():
        # a parked watch-subscriber wakes on publish
        waiter = spawn(topic.wait_for_messages(db, after_seq=-1))
        tail = topic.tail(db, from_version=0)
        await delay(0.5)
        assert not waiter.is_ready()

        async def pub(tr):
            await topic.publish(tr, b"hello")
            await topic.publish(tr, b"world")

        await db.run(pub)
        msgs = await timeout(waiter, 60.0, default=None)
        assert msgs == [(0, b"hello"), (1, b"world")]
        # the feed tailer sees the same messages in publish order
        tailed = []
        while len(tailed) < 2:
            tailed.extend(await tail.next_messages())
        assert tailed == [(0, b"hello"), (1, b"world")]
        # a second wait resumes past the consumed cursor and wakes on
        # the NEXT publish only
        waiter2 = spawn(topic.wait_for_messages(db, after_seq=1))
        await delay(0.5)
        assert not waiter2.is_ready()

        async def pub2(tr):
            await topic.publish(tr, b"again")

        await db.run(pub2)
        assert await timeout(waiter2, 60.0, default=None) == [(2, b"again")]
        return True

    assert drive(sim, body(), 300.0)


# -- error taxonomy ------------------------------------------------------------


def test_watch_error_types():
    assert TooManyWatches().retryable
    assert not TransactionCancelled().retryable
