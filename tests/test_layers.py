"""Tuple/Subspace/Directory layers + watches.

Tuple encoding mirrors the bindings' spec (ordering preserved, round-trip
exact); the directory layer allocates prefixes transactionally; watches
fire on value change through the storage watchValue long-poll.
"""

import pytest

from foundationdb_tpu.client import Database
from foundationdb_tpu.layers import DirectoryLayer, Subspace
from foundationdb_tpu.layers import tuple as T
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn, timeout
from foundationdb_tpu.server import Cluster, ClusterConfig


def make_db(seed=0, **cfg):
    sim = Sim(seed=seed)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(**cfg))
    db = Database(sim, cluster.proxy_addrs)
    return sim, cluster, db


def drive(sim, coro, limit=120.0):
    return sim.run_until_done(spawn(coro), limit)


# -- tuple --------------------------------------------------------------------


def test_tuple_roundtrip():
    cases = [
        (),
        (None,),
        (b"bytes", "string", 0, 1, -1, 255, -255, 65536, -65536),
        (1.5, -1.5, 0.0, float(10**10)),
        (True, False),
        (b"a\x00b", "emb\x00str"),
        (("nested", (1, None, b"x")), 2),
        (2**63 - 1, -(2**63) + 1),
    ]
    for t in cases:
        assert T.unpack(T.pack(t)) == t, t


def test_tuple_ordering_matches_value_order():
    import random

    rnd = random.Random(5)
    vals = []
    for _ in range(200):
        kind = rnd.randrange(3)
        if kind == 0:
            vals.append((rnd.randrange(-10**9, 10**9),))
        elif kind == 1:
            vals.append((rnd.randrange(-10**9, 10**9), rnd.random()))
        else:
            vals.append(
                (
                    rnd.randrange(-100, 100),
                    bytes(rnd.randrange(256) for _ in range(rnd.randrange(8))),
                )
            )
    ints = sorted(v for v in vals if len(v) == 1)
    packed = sorted(T.pack(v) for v in vals if len(v) == 1)
    assert [T.unpack(p) for p in packed] == ints


def test_subspace():
    app = Subspace(("app",))
    users = app["users"]
    k = users.pack((42, "alice"))
    assert users.contains(k) and app.contains(k)
    assert users.unpack(k) == (42, "alice")
    b, e = users.range()
    assert b < k < e


# -- directory ----------------------------------------------------------------


def test_directory_layer():
    sim, cluster, db = make_db()

    async def body():
        d = DirectoryLayer()

        async def create(tr):
            users = await d.create_or_open(tr, ("app", "users"))
            tr.set(users.pack((1,)), b"alice")
            return users.raw_prefix

        prefix = await db.run(create)

        async def reopen(tr):
            users = await d.open(tr, ("app", "users"))
            assert users.raw_prefix == prefix
            return await tr.get(users.pack((1,)))

        assert await db.run(reopen) == b"alice"

        async def listing(tr):
            return await d.list(tr, ("app",))

        assert await db.run(listing) == ["users"]

        async def second(tr):
            other = await d.create_or_open(tr, ("app", "events"))
            assert other.raw_prefix != prefix
            return sorted(await d.list(tr, ("app",)))

        assert await db.run(second) == ["events", "users"]

        async def remove(tr):
            await d.remove(tr, ("app", "users"))

        await db.run(remove)

        async def gone(tr):
            return await d.exists(tr, ("app", "users"))

        assert await db.run(gone) is False

    drive(sim, body())


# -- watches ------------------------------------------------------------------


def test_watch_fires_on_change():
    sim, cluster, db = make_db()

    async def body():
        async def setup(tr):
            tr.set(b"watched", b"v0")

        await db.run(setup)

        fired = db.watch(b"watched")
        await delay(0.5)
        assert not fired.is_ready()

        async def change(tr):
            tr.set(b"watched", b"v1")

        await db.run(change)
        new_value = await timeout(fired, 10.0, default="TIMEOUT")
        assert new_value == b"v1"

    drive(sim, body())


def test_transaction_watch_after_commit():
    sim, cluster, db = make_db()

    async def body():
        tr = db.transaction()
        tr.set(b"k", b"a")
        w = tr.watch(b"k")
        await tr.commit()
        await delay(0.5)
        assert not w.is_ready()

        async def change(tr2):
            tr2.set(b"k", b"b")

        await db.run(change)
        assert await timeout(w, 10.0, default="TIMEOUT") == b"b"

    drive(sim, body())


def test_set_then_watch_baseline_is_written_value():
    """A transaction that READS (pinning its read version), then SETS the
    watched key, then watches it: the baseline must be the value the
    transaction WROTE, not the pre-write value at its read version —
    otherwise every set-then-watch registration fires immediately and
    spuriously (watch loops become busy polls). ADVICE r4 finding."""
    sim, cluster, db = make_db()

    async def body():
        async def setup(tr):
            tr.set(b"other", b"x")

        await db.run(setup)

        tr = db.transaction()
        await tr.get(b"other")  # pins _read_version before the write
        tr.set(b"k", b"mine")
        w = tr.watch(b"k")
        await tr.commit()
        await delay(0.5)
        assert not w.is_ready(), "watch fired on the watcher's own write"

        async def change(tr2):
            tr2.set(b"k", b"theirs")

        await db.run(change)
        assert await timeout(w, 10.0, default="TIMEOUT") == b"theirs"

    drive(sim, body())


def test_clear_then_watch_does_not_fire_on_own_clear():
    sim, cluster, db = make_db()

    async def body():
        async def setup(tr):
            tr.set(b"c", b"x")

        await db.run(setup)

        tr = db.transaction()
        await tr.get(b"c")
        tr.clear(b"c")
        w = tr.watch(b"c")
        await tr.commit()
        await delay(0.5)
        assert not w.is_ready(), "watch fired on the watcher's own clear"

        async def change(tr2):
            tr2.set(b"c", b"back")

        await db.run(change)
        assert await timeout(w, 10.0, default="TIMEOUT") == b"back"

    drive(sim, body())


def test_watch_on_clear_fires_with_none():
    sim, cluster, db = make_db()

    async def body():
        async def setup(tr):
            tr.set(b"todel", b"x")

        await db.run(setup)
        w = db.watch(b"todel")

        async def clear(tr):
            tr.clear(b"todel")

        await delay(0.2)
        await db.run(clear)
        assert await timeout(w, 10.0, default="TIMEOUT") is None

    drive(sim, body())
