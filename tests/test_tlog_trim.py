"""TLog trim semantics: the txs tag must not pin other tags' data.

Regression tests for the trim-horizon rule (server/tlog.py _trim): TXS_TAG
is popped only by a recovering master, so it is excluded from the horizon
min; entries below the horizon that still carry unpopped txs data are
retained txs-only (the reference's separate txnStateStore retention).
"""

from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.server.interfaces import (
    TLogCommitRequest,
    TLogPeekRequest,
    TLogPopRequest,
)
from foundationdb_tpu.server.systemdata import TXS_TAG
from foundationdb_tpu.server.tlog import TLog


def run(coro):
    sim = Sim(seed=7)
    sim.activate()
    return sim.run_until_done(spawn(coro), 60.0)


def test_txs_tag_does_not_pin_trim():
    async def body():
        tl = TLog(log_id="t0")
        prev = 0
        for v in range(1, 11):
            msgs = {0: [f"m{v}".encode()]}
            if v == 3:
                msgs[TXS_TAG] = [b"meta3"]
            await tl.commit(
                TLogCommitRequest(
                    epoch=0, prev_version=prev, version=v, messages=msgs,
                    known_committed=0,
                )
            )
            prev = v
        # storage acks tag 0 through v=8: with the fix, everything but the
        # txs residue at v=3 trims even though TXS_TAG was never popped
        await tl.pop(TLogPopRequest(tag=0, upto=8))
        assert tl._versions == [3, 9, 10], tl._versions
        v3 = dict(tl._log)[3]
        assert set(v3) == {TXS_TAG}, "non-txs payload must be stripped"

        # a recovering master can still read the full txs stream
        reply = await tl.peek(TLogPeekRequest(tag=TXS_TAG, begin=1))
        assert [v for v, _m in reply.messages] == [3]

        # the master pops txs after its cstate snapshot → residue goes too
        await tl.pop(TLogPopRequest(tag=TXS_TAG, upto=8))
        assert tl._versions == [9, 10], tl._versions

    run(body())


def test_trim_all_popped_only_txs_left():
    async def body():
        tl = TLog(log_id="t1")
        await tl.commit(
            TLogCommitRequest(
                epoch=0, prev_version=0, version=1,
                messages={TXS_TAG: [b"meta"]}, known_committed=0,
            )
        )
        await tl.commit(
            TLogCommitRequest(
                epoch=0, prev_version=1, version=2,
                messages={1: [b"x"]}, known_committed=0,
            )
        )
        await tl.pop(TLogPopRequest(tag=1, upto=2))
        # only the txs entry remains; it still serves peeks
        assert tl._versions == [1]
        reply = await tl.peek(TLogPeekRequest(tag=TXS_TAG, begin=1))
        assert [v for v, _m in reply.messages] == [1]

    run(body())


def test_spill_bounds_memory_and_serves_peeks():
    """TLOG_SPILL_THRESHOLD: a tag that never pops (dead storage server)
    must not grow tlog memory without bound — old payloads spill to the
    DiskQueue (spill-by-reference, TLogServer.actor.cpp:518) and peeks
    read them back transparently."""
    from foundationdb_tpu.kv.mutations import Mutation, MutationType
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.server.tlog import Spilled

    sim = Sim(seed=9)
    sim.activate()

    async def body():
        knobs = Knobs()
        knobs.TLOG_SPILL_THRESHOLD = 2048
        tl = TLog(log_id="ts", disk=sim.disk("m0"), knobs=knobs)
        prev = 0
        payload = [Mutation(MutationType.SET_VALUE, b"k" * 32, b"v" * 32)]
        for v in range(1, 101):
            await tl.commit(
                TLogCommitRequest(
                    epoch=0, prev_version=prev, version=v,
                    messages={0: list(payload), 1: list(payload)},
                    known_committed=0,
                )
            )
            prev = v
        assert tl._mem_bytes <= 2048, tl._mem_bytes
        assert any(isinstance(m, Spilled) for _v, m in tl._log)

        # a late peek from version 1 reads spilled payloads back intact
        reply = await tl.peek(TLogPeekRequest(tag=1, begin=1))
        assert [v for v, _m in reply.messages] == list(range(1, 101))
        assert all(m == payload for _v, m in reply.messages)

        # popping tag 0 must not disturb tag 1's spilled data
        await tl.pop(TLogPopRequest(tag=0, upto=50))
        reply = await tl.peek(TLogPeekRequest(tag=1, begin=1))
        assert [v for v, _m in reply.messages] == list(range(1, 101))

        # after every tag pops, memory and log drain
        await tl.pop(TLogPopRequest(tag=1, upto=100))
        await tl.pop(TLogPopRequest(tag=0, upto=100))
        assert tl._versions == []
        assert tl._mem_bytes == 0, tl._mem_bytes
        return True

    assert sim.run_until_done(spawn(body()), 60.0)
