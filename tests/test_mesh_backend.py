"""MeshConflictSet: the sharded kernel behind the ConflictSet seam.

Differential: mesh verdicts must be bit-identical to the single-device
TpuConflictSet across random batches, including after overflow-driven
rebalances. In-cluster: resolvers built with conflict_backend="tpu"
auto-upgrade to the mesh (8 virtual CPU devices in CI) and behave
identically through the proxy pipeline."""

import random

import jax
import pytest

from foundationdb_tpu.conflict.api import CommitTransaction, new_conflict_set
from foundationdb_tpu.conflict.mesh_backend import MeshConflictSet
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet


def make_batches(n_batches, n_txns, keyspace=2000, seed=0):
    rnd = random.Random(seed)
    batches = []
    for i in range(n_batches):
        txs = []
        for _ in range(n_txns):
            a = rnd.randrange(keyspace)
            b = a + 1 + rnd.randrange(8)
            c = rnd.randrange(keyspace)
            d = c + 1 + rnd.randrange(8)
            txs.append(
                CommitTransaction(
                    read_snapshot=i,
                    read_conflict_ranges=[(b"%06d" % a, b"%06d" % b)],
                    write_conflict_ranges=[(b"%06d" % c, b"%06d" % d)],
                )
            )
        batches.append(txs)
    return batches


def test_factory_auto_upgrades_to_mesh():
    assert len(jax.devices()) > 1  # conftest forces 8 virtual CPU devices
    cs = new_conflict_set("tpu")
    assert isinstance(cs, MeshConflictSet)
    assert isinstance(new_conflict_set("tpu1"), TpuConflictSet)
    assert isinstance(new_conflict_set("mesh"), MeshConflictSet)


def test_mesh_matches_single_device():
    batches = make_batches(8, 48, seed=3)
    single = TpuConflictSet(key_width=12, capacity=1 << 12)
    mesh = MeshConflictSet(key_width=12, capacity=1 << 12, n_parts=4)
    window = 20
    for i, txs in enumerate(batches):
        vs = single.detect_batch(txs, now=i + window, new_oldest_version=i)
        vm = mesh.detect_batch(txs, now=i + window, new_oldest_version=i)
        assert [int(v) for v in vs] == [int(v) for v in vm], f"batch {i}"


def test_mesh_matches_single_device_wide_ranges():
    """Cross-partition ranges (clears spanning shards) + point writes:
    clipping must reconstruct global verdicts exactly."""
    rnd = random.Random(9)
    single = TpuConflictSet(key_width=12, capacity=1 << 12)
    mesh = MeshConflictSet(key_width=12, capacity=1 << 12, n_parts=4)
    window = 20
    for i in range(6):
        txs = []
        for _ in range(24):
            if rnd.random() < 0.3:
                # wide range spanning many partitions
                a = bytes([rnd.randrange(0, 200)])
                b = bytes([rnd.randrange(ord(a[:1]) + 1, 255)])
            else:
                k = rnd.randrange(3000)
                a, b = b"%06d" % k, b"%06d" % (k + 1)
            read = rnd.random() < 0.7
            write = rnd.random() < 0.7 or not read
            txs.append(
                CommitTransaction(
                    read_snapshot=max(0, i - rnd.randrange(3)),
                    read_conflict_ranges=[(a, b)] if read else [],
                    write_conflict_ranges=[(a, b)] if write else [],
                )
            )
        vs = single.detect_batch(txs, now=i + window, new_oldest_version=i)
        vm = mesh.detect_batch(txs, now=i + window, new_oldest_version=i)
        assert [int(v) for v in vs] == [int(v) for v in vm], f"round {i}"


def test_mesh_pipelined_async_and_clear():
    batches = make_batches(6, 32, seed=5)
    mesh = MeshConflictSet(key_width=12, capacity=1 << 12, n_parts=2)
    single = TpuConflictSet(key_width=12, capacity=1 << 12)
    # pipelined: dispatch all three groups before collecting any
    handles = []
    for g in range(0, 6, 2):
        work = [
            (mesh.encode(batches[i]), i + 20, i) for i in range(g, g + 2)
        ]
        handles.append(mesh.detect_many_encoded_async(work))
    mesh_verdicts = []
    for h in handles:
        mesh_verdicts.extend(h())
    for i, txs in enumerate(batches):
        vs = single.detect_batch(txs, now=i + 20, new_oldest_version=i)
        assert [int(v) for v in vs] == [int(v) for v in mesh_verdicts[i]]
    # clear resets history at a version: old snapshots turn TOO_OLD
    mesh.clear(100)
    t = CommitTransaction(
        read_snapshot=50,
        read_conflict_ranges=[(b"a", b"b")],
        write_conflict_ranges=[],
    )
    v = mesh.detect_batch([t], now=101, new_oldest_version=100)
    assert int(v[0]) == 2  # TOO_OLD


def test_mesh_in_cluster():
    """conflict_backend='tpu' in a cluster auto-upgrades resolvers to the
    mesh; commits/conflicts behave identically through the full pipeline."""
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.errors import NotCommitted
    from foundationdb_tpu.net.sim import Sim
    from foundationdb_tpu.runtime.futures import spawn
    from foundationdb_tpu.server import Cluster, ClusterConfig

    sim = Sim(seed=41)
    sim.activate()
    cluster = Cluster(sim, ClusterConfig(conflict_backend="tpu"))
    from foundationdb_tpu.conflict.mesh_backend import MeshConflictSet as M

    assert any(
        isinstance(r.cs.primary, M) for r in cluster.resolvers
    ), "cluster resolver did not auto-upgrade to the mesh backend"
    db = Database(sim, cluster.proxy_addrs)

    async def go():
        tr = db.transaction()
        tr.set(b"a", b"1")
        await tr.commit()
        t1 = db.transaction()
        await t1.get(b"a")
        t1.set(b"b", b"from-t1")
        t2 = db.transaction()
        t2.set(b"a", b"2")
        await t2.commit()
        with pytest.raises(NotCommitted):
            await t1.commit()
        t3 = db.transaction()
        assert await t3.get(b"a") == b"2"
        assert await t3.get(b"b") is None
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
