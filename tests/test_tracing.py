"""Distributed span tracing (ISSUE 6): cross-RPC context propagation over
both transports, deterministic sampling, read-path waterfalls with
≥90%-of-p50 stage coverage, and per-endpoint latency bands surfaced
through role metrics and the status document."""

import json
import socket

import pytest

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Endpoint, Sim
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.runtime.trace import (
    TraceLog,
    set_trace_log,
    span,
    trace_log,
)
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.tools import trace_analyze as ta


def _fresh_log():
    log = TraceLog()
    set_trace_log(log)
    return log


def _span_events(log):
    return [e for e in log.events if e.get("Type") == "Span"]


def _run_traced_sim(seed: int):
    """One sim cluster run with every transaction sampled; returns the
    TraceLog it filled."""
    log = _fresh_log()
    sim = Sim(seed=seed)
    sim.activate()
    sim.knobs.TRACE_SAMPLE_RATE = 1.0
    cluster = DynamicCluster(
        sim, ClusterConfig(n_proxies=1, n_resolvers=1, n_storage=2),
        n_coordinators=1,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def go():
        async def w(tr):
            tr.set(b"trace-k", b"v")

        await db.run(w)

        async def r(tr):
            return await tr.get(b"trace-k")

        assert await db.run(r) == b"v"
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    return log


def test_sim_propagation_parent_child_across_three_hops():
    """A sampled commit's spans must link client → proxy → resolver and
    client → proxy → tlog (≥3 processes deep), and a sampled read must
    link client → storage — all via RPC-envelope inheritance only."""
    log = _run_traced_sim(seed=11)
    spans = _span_events(log)
    assert spans, "no spans emitted at TRACE_SAMPLE_RATE=1.0"
    by_id = {s["SpanId"]: s for s in spans}

    def hop_chain(leaf):
        """Machines along the parent chain, leaf → root."""
        chain, seen = [], set()
        s = leaf
        while s is not None and s["SpanId"] not in seen:
            seen.add(s["SpanId"])
            chain.append(s.get("Machine", ""))
            s = by_id.get(s.get("Parent") or "")
        return chain

    resolver_leaves = [s for s in spans if s["Name"] == "Resolver.resolve"]
    tlog_leaves = [s for s in spans if s["Name"] == "TLog.push"]
    # reads ride the batched pipeline by default (ISSUE 12): the storage
    # leaf of a sampled get is the multiGet hop
    storage_leaves = [
        s for s in spans if s["Name"] in ("Storage.multiGet", "Storage.getValue")
    ]
    assert resolver_leaves and tlog_leaves and storage_leaves
    for leaves in (resolver_leaves, tlog_leaves):
        assert any(
            len(set(hop_chain(s))) >= 3 for s in leaves
        ), f"no ≥3-process parent chain for {leaves[0]['Name']}"
    # the read path: storage span parented (transitively) to a client span
    assert any(
        "client" in hop_chain(s) and len(set(hop_chain(s))) >= 2
        for s in storage_leaves
    )
    # every non-root parent reference resolves within the trace
    for s in spans:
        parent = s.get("Parent") or ""
        if parent:
            assert parent in by_id, (s["Name"], parent)
            assert by_id[parent]["Trace"] == s["Trace"]


def test_same_seed_runs_emit_identical_sampled_spans():
    """Determinism (the sim's core guarantee, extended to tracing): two
    same-seed runs must produce byte-identical sampled span sets —
    trace ids, span ids, parentage, names, and timings."""

    def canonical(log):
        return json.dumps(
            sorted(
                (
                    e["Trace"], e["SpanId"], e.get("Parent"), e["Name"],
                    e["Machine"], e["Begin"], e["Dur"],
                )
                for e in _span_events(log)
            )
        )

    a = canonical(_run_traced_sim(seed=23))
    b = canonical(_run_traced_sim(seed=23))
    assert a == b
    c = canonical(_run_traced_sim(seed=24))
    assert c != a  # different seed, different sampled ids (sanity)


def test_tcp_propagation_across_three_hops():
    """Span context crosses REAL sockets: a request chain A → B → C must
    hand each hop the upstream context (the wire envelope, net/tcp.py),
    with parent/child linkage intact."""
    from foundationdb_tpu.net.tcp import RealWorld
    from foundationdb_tpu.runtime.loop import RealLoop
    from foundationdb_tpu.runtime.trace import active_span

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    from foundationdb_tpu.runtime.knobs import Knobs

    _fresh_log()
    loop = RealLoop(seed=5)
    # pin sockets: colocated worlds would auto-select the loopback path
    # (its span propagation is covered by the test below)
    worlds = [
        RealWorld(
            f"127.0.0.1:{free_port()}",
            knobs=Knobs(TRANSPORT_LOOPBACK=False),
            loop=loop,
        )
        for _ in range(3)
    ]
    a, b, c = worlds
    try:

        async def handler_c(_req):
            ctx = active_span()
            return (ctx.trace_id, ctx.span_id) if ctx else None

        async def handler_b(_req):
            inherited = active_span()
            with span("hop.b", b.node.address) as sp:
                downstream = await b.node.request(
                    Endpoint(c.node.address, "hopC"), None
                )
            return {
                "inherited": (inherited.trace_id, inherited.span_id)
                if inherited
                else None,
                "b_span": (sp.context.trace_id, sp.context.span_id)
                if sp.sampled
                else None,
                "c_saw": downstream,
            }

        b.node.register("hopB", handler_b)
        c.node.register("hopC", handler_c)

        async def client():
            with span(
                "hop.a", a.node.address,
                parent=__import__(
                    "foundationdb_tpu.runtime.trace", fromlist=["root_context"]
                ).root_context("tcp-trace-1"),
            ) as root:
                out = await a.node.request(Endpoint(b.node.address, "hopB"), None)
                return root.context.span_id, out

        a.activate()
        root_id, out = a.run_until_done(spawn(client()), 30.0)
        # B inherited A's span as its ambient parent
        assert out["inherited"] == ("tcp-trace-1", root_id)
        # C inherited B's span (opened INSIDE b's handler) — 3rd hop
        assert out["c_saw"] == out["b_span"]
        assert out["b_span"][0] == "tcp-trace-1"
        # unsampled request: no context crosses
        async def plain():
            return await a.node.request(Endpoint(b.node.address, "hopB"), None)

        out2 = a.run_until_done(spawn(plain()), 30.0)
        assert out2["inherited"] is None
    finally:
        for w in worlds:
            w.close()
        loop.close()


@pytest.mark.parametrize("loopback", [True, False])
def test_span_envelope_over_superframes_and_loopback(loopback):
    """ISSUE 14: the span-context envelope survives the gen-7 transport —
    a same-tick BURST of sampled requests rides one super-frame (socket
    leg) or one loopback batch drain, and every handler still inherits
    its own caller's context (per-message envelopes inside the batch)."""
    from foundationdb_tpu.net.tcp import RealWorld
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.runtime.loop import RealLoop, set_loop
    from foundationdb_tpu.runtime.trace import active_span, root_context

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    _fresh_log()
    loop = RealLoop(seed=6)
    knobs = Knobs(TRANSPORT_LOOPBACK=loopback)
    a = RealWorld(f"127.0.0.1:{free_port()}", knobs=knobs, loop=loop)
    b = RealWorld(f"127.0.0.1:{free_port()}", knobs=knobs, loop=loop)
    try:

        async def who(_req):
            ctx = active_span()
            return (ctx.trace_id, ctx.span_id) if ctx else None

        b.node.register("who", who)

        async def one(i):
            with span("burst.client", a.node.address,
                      parent=root_context(f"sf-trace-{i}")) as sp:
                seen = await a.node.request(
                    Endpoint(b.node.address, "who"), i
                )
                return (sp.context.trace_id, sp.context.span_id), seen

        async def client():
            from foundationdb_tpu.runtime.futures import wait_for_all

            return await wait_for_all([spawn(one(i)) for i in range(12)])

        a.activate()
        out = a.run_until_done(spawn(client()), 30.0)
        for mine, seen in out:
            assert seen == mine  # each handler saw ITS caller's context
        snap = a.transport_metrics.snapshot()
        if loopback:
            assert snap["loopbackMessages"] > 0 and snap["tcpMessages"] == 0
        else:
            assert snap["tcpMessages"] > 0 and snap["loopbackMessages"] == 0
        # the burst actually coalesced (super-frame / batched drain)
        assert snap["framesSent"] < snap["messagesSent"], snap
    finally:
        a.close()
        b.close()
        set_loop(None)
        loop.close()


def test_latency_bands_in_status_and_resolver_metrics():
    """Per-endpoint latency-band histograms reach the status document's
    workload section (cluster-wide sums) and the role's own *.metrics
    endpoint (per-role exact counts)."""
    from foundationdb_tpu.client import management
    from foundationdb_tpu.runtime.futures import delay

    _fresh_log()
    sim = Sim(seed=31)
    sim.activate()
    cluster = DynamicCluster(
        sim, ClusterConfig(n_proxies=1, n_resolvers=1, n_storage=2),
        n_coordinators=1,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def go():
        for i in range(12):

            async def w(tr, i=i):
                await tr.get(b"band%02d" % i)
                tr.set(b"band%02d" % i, b"v")

            await db.run(w)
        await delay(6.0)  # metrics trace loops + probes fire
        doc = await management.get_status(cluster.coordinators, db.client)
        direct = {}
        for addr, p in sim.processes.items():
            wk = getattr(p, "worker", None)
            if wk is None or not p.alive:
                continue
            for uid, h in wk.roles.items():
                if h.kind == "resolver":
                    direct[uid] = await db.client.request(
                        Endpoint(addr, f"resolver.metrics#{uid}"), None
                    )
        return doc, direct

    doc, direct = sim.run_until_done(spawn(go()), 900.0)
    bands = doc["workload"]["latency_bands"]
    for leg in ("grv", "read", "commit", "resolve"):
        assert bands[leg]["count"] > 0, (leg, bands)
        assert sum(bands[leg]["bands"].values()) == bands[leg]["count"]
    assert direct
    for snap in direct.values():
        rb = snap["resolveLatencyBands"]
        assert rb["count"] > 0
        assert sum(rb["bands"].values()) == rb["count"]


def test_read_waterfall_covers_p50(request):
    """Acceptance: a 90/10-style sim run's read spans must attribute
    ≥90% of measured p50 read latency to named stages."""
    log = _fresh_log()
    sim = Sim(seed=41)
    sim.activate()
    sim.knobs.TRACE_SAMPLE_RATE = 1.0
    cluster = DynamicCluster(
        sim, ClusterConfig(n_proxies=1, n_resolvers=1, n_storage=2),
        n_coordinators=1,
    )
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def go():
        # seed rows, then a 90/10 read-heavy mix
        async def seed_rows(tr):
            for i in range(20):
                tr.set(b"rw%03d" % i, b"v%d" % i)

        await db.run(seed_rows)
        for n in range(10):

            async def mix(tr, n=n):
                for i in range(9):
                    await tr.get(b"rw%03d" % ((n * 9 + i) % 20))
                tr.set(b"rw%03d" % (n % 20), b"w%d" % n)

            await db.run(mix)
        return True

    assert sim.run_until_done(spawn(go()), 600.0)
    cp = ta.critical_path(log.events, root_prefix="Client.get")
    assert "Client.get" in cp, cp.keys()
    agg = cp["Client.get"]
    assert agg["traces"] >= 50
    assert agg["p50_ms"] > 0
    # named stages account for ≥90% of the measured read latency
    assert agg["coverage"] >= 0.9, agg
    # the stage names an operator needs are all attributed; with read
    # coalescing on (the default) the per-key Client.rpc/Storage.* stages
    # collapse into the batched multiGet hop
    stage_names = {s["stage"] for s in agg["stages"]}
    assert {"Client.rpc", "Client.multiGet", "Storage.multiGet"} <= stage_names, (
        stage_names
    )
    assert "Storage.getValue" not in stage_names, stage_names
    # and a waterfall renders for some sampled read
    traces = ta.spans_by_trace(log.events)
    read_traces = [
        tid
        for tid, spans in traces.items()
        if any(s["Name"] == "Client.get" for s in spans)
    ]
    assert read_traces
    text = ta.format_waterfall(log.events, read_traces[0])
    assert "Client.get" in text and "ms" in text


def test_trace_analyze_merges_multiple_files_in_time_order(tmp_path):
    """TCP clusters write one trace file per fdbserver; the analyzer must
    interleave them by time (satellite fix — only one file + its rolled
    siblings used to be read)."""
    f1 = tmp_path / "proc1.jsonl"
    f2 = tmp_path / "proc2.jsonl"
    f1.write_text(
        "\n".join(
            json.dumps({"Type": "X", "Time": t, "Machine": "p1"})
            for t in (0.1, 0.3, 0.5)
        )
        + "\n"
    )
    f2.write_text(
        "\n".join(
            json.dumps({"Type": "X", "Time": t, "Machine": "p2"})
            for t in (0.2, 0.4)
        )
        + "\n"
    )
    merged = ta.load_events([str(f1), str(f2)])
    assert [e["Time"] for e in merged] == [0.1, 0.2, 0.3, 0.4, 0.5]
    assert [e["Machine"] for e in merged] == ["p1", "p2", "p1", "p2", "p1"]
    # single-path (string) form still works, rolled siblings included
    single = ta.load_events(str(f1))
    assert [e["Time"] for e in single] == [0.1, 0.3, 0.5]


def test_commit_chain_back_compat_and_read_stages():
    """STAGE_ORDER keeps the historical commit stages (exact strings, in
    order) and gains read-path stages; full_chain() carries read events
    while chain() stays commit-only."""
    from foundationdb_tpu.tools.commit_chain import (
        COMMIT_STAGES,
        STAGE_ORDER,
        chain,
        full_chain,
    )

    assert STAGE_ORDER[: len(COMMIT_STAGES)] == [
        "ClientCommitStart",
        "ProxyReceived",
        "GotCommitVersion",
        "Resolving",
        "Resolved",
        "Logged",
        "Replied",
        "ClientCommitDone",
    ]
    assert "ClientReadStart" in STAGE_ORDER and "StorageRead" in STAGE_ORDER
    # prefilter stages (ISSUE 17) append after the watch stages so the
    # historical prefix stays byte-stable
    assert STAGE_ORDER[-2:] == ["Proxy.prefilter", "Prefiltered"]

    log = _fresh_log()
    sim = Sim(seed=47)
    sim.activate()
    cluster = DynamicCluster(sim, ClusterConfig(), n_coordinators=1)
    db = Database.from_coordinators(sim, cluster.coordinators)

    async def go():
        tr = db.transaction()
        tr.set_debug_id("chain-1")
        await tr.get(b"warm")
        tr.set(b"k", b"v")
        await tr.commit()
        return True

    assert sim.run_until_done(spawn(go()), 300.0)
    commit_events = {e["Event"] for e in chain("chain-1", log.events)}
    assert "ClientReadStart" not in commit_events  # stable legacy output
    assert "ClientCommitDone" in commit_events
    full = {e["Event"] for e in full_chain("chain-1", log.events)}
    assert "ClientReadStart" in full and "ClientCommitDone" in full
