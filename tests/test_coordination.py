"""Coordination layer: generation-register fencing, leader election,
coordinated state through a coordinator majority."""

import pytest

from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import AsyncVar
from foundationdb_tpu.server.coordination import (
    ClusterStateChanged,
    CoordinatedState,
    CoordinatorServer,
    LeaderInfo,
    monitor_leader,
    try_become_leader,
)


def make_coords(sim, n=3):
    sim.activate()
    addrs = []
    for i in range(n):
        c = CoordinatorServer()
        c.register(sim.new_process(f"coord{i}"))
        addrs.append(f"coord{i}")
    return addrs


def test_coordinated_state_read_write_roundtrip():
    sim = Sim(seed=1)
    coords = make_coords(sim)
    p = sim.new_process("master0")

    async def go():
        cs = CoordinatedState(p, coords)
        prev = await cs.read()
        assert prev is None  # brand-new cluster
        await cs.write({"epoch": 1})
        cs2 = CoordinatedState(p, coords)
        got = await cs2.read()
        assert got == {"epoch": 1}
        return True

    assert sim.run_until_done(p.spawn(go()), limit=60)


def test_coordinated_state_fencing():
    """A second reader with a higher generation fences the first writer —
    the exclusivity that makes master recovery safe."""
    sim = Sim(seed=2)
    coords = make_coords(sim)
    p1 = sim.new_process("masterA")
    p2 = sim.new_process("masterB")

    async def go():
        cs1 = CoordinatedState(p1, coords)
        await cs1.read()
        await cs1.write({"owner": "A"})
        # B adopts a higher generation
        cs2 = CoordinatedState(p2, coords)
        got = await cs2.read()
        assert got == {"owner": "A"}
        # A's next write must now fail
        with pytest.raises(ClusterStateChanged):
            await cs1.write({"owner": "A2"})
        # B's write goes through
        await cs2.write({"owner": "B"})
        cs3 = CoordinatedState(p1, coords)
        assert (await cs3.read()) == {"owner": "B"}
        return True

    assert sim.run_until_done(p1.spawn(go()), limit=60)


def test_coordinated_state_survives_coordinator_minority_failure():
    sim = Sim(seed=3)
    coords = make_coords(sim, n=5)
    sim.kill_process("coord0")
    sim.kill_process("coord3")
    p = sim.new_process("master0")

    async def go():
        cs = CoordinatedState(p, coords)
        await cs.read()
        await cs.write("still-works")
        cs2 = CoordinatedState(p, coords)
        return await cs2.read()

    assert sim.run_until_done(p.spawn(go()), limit=60) == "still-works"


def test_leader_election_single_winner_and_failover():
    sim = Sim(seed=4)
    coords = make_coords(sim)
    pa = sim.new_process("workerA")
    pb = sim.new_process("workerB")

    infoa = LeaderInfo(address="workerA", priority=2, change_id=101)
    infob = LeaderInfo(address="workerB", priority=1, change_id=102)

    events = []  # (t, name, "won"|"lost")

    async def campaign(p, info, name):
        while True:
            lead = await try_become_leader(p, coords, info)
            events.append((sim.loop.now(), name, "won"))
            await lead.lost
            events.append((sim.loop.now(), name, "lost"))

    pa.spawn(campaign(pa, infoa, "A"))
    pb.spawn(campaign(pb, infob, "B"))

    # A (higher priority) must end up holding leadership. B may have won a
    # transient nomination before A's candidacy arrived (the reference has
    # the same startup race — generation fencing makes stale leaders
    # harmless), but must lose it to A.
    sim.run(until=10)
    a_events = [(n, e) for _, n, e in events if n == "A"]
    b_events = [(n, e) for _, n, e in events if n == "B"]
    assert a_events == [("A", "won")]  # A holds at t=10 and never lost
    assert not b_events or b_events[-1] == ("B", "lost")

    # kill A: its candidacy lease expires; B takes over
    t_kill = sim.loop.now()
    sim.kill_process("workerA")
    sim.run(
        until=t_kill + 30,
        stop_when=lambda: events and events[-1][1:] == ("B", "won"),
    )
    assert events[-1][1:] == ("B", "won")
    assert events[-1][0] > t_kill


def test_monitor_leader_converges():
    sim = Sim(seed=5)
    coords = make_coords(sim)
    pw = sim.new_process("workerA")
    pc = sim.new_process("client0")
    info = LeaderInfo(address="workerA", priority=1, change_id=7)

    seen = AsyncVar(None)
    pw.spawn(_campaign_forever(pw, coords, info))
    pc.spawn(monitor_leader(pc, coords, seen))
    sim.run(until=15)
    assert seen.get() is not None and seen.get().address == "workerA"


async def _campaign_forever(p, coords, info):
    lead = await try_become_leader(p, coords, info)
    await lead.lost
