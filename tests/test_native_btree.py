"""Native C++ copy-on-write B-tree engine: correctness + crash safety.

Runs against real temp files (the native engine is the production path;
simulation uses the Python engines on SimDisk). Covers: basic CRUD, range
scans, multi-level splits, overflow values, persistence across reopen, and
shadow-paging crash consistency (uncommitted work vanishes; committed work
survives reopening after "losing" everything since the last commit)."""

import os
import random

import pytest

from foundationdb_tpu.kv.native_engine import KeyValueStoreBTree


@pytest.fixture
def path(tmp_path):
    return str(tmp_path / "test.btree")


def commit(bt):
    # the async commit never yields for the native engine; drive it inline
    coro = bt.commit()
    try:
        coro.send(None)
    except StopIteration:
        return
    raise AssertionError("native commit should not suspend")


def test_basic_crud(path):
    bt = KeyValueStoreBTree(path)
    bt.set(b"a", b"1")
    bt.set(b"b", b"2")
    bt.set(b"c", b"3")
    commit(bt)
    assert bt.read_value(b"a") == b"1"
    assert bt.read_value(b"b") == b"2"
    assert bt.read_value(b"zz") is None
    bt.set(b"b", b"22")
    assert bt.read_value(b"b") == b"22"
    bt.clear_range(b"a", b"b")
    assert bt.read_value(b"a") is None
    assert bt.read_range(b"", b"\xff") == [(b"b", b"22"), (b"c", b"3")]
    bt.close()


def test_many_keys_splits_and_range(path):
    bt = KeyValueStoreBTree(path)
    rnd = random.Random(7)
    keys = {}
    for i in range(5000):
        k = b"k%08d" % rnd.randrange(100000)
        v = bytes([i % 251]) * rnd.randrange(1, 80)
        keys[k] = v
        bt.set(k, v)
    commit(bt)
    assert bt.stats()["pages"] > 10  # multiple levels of pages exist
    for k, v in list(keys.items())[:200]:
        assert bt.read_value(k) == v
    got = bt.read_range(b"k", b"l")
    assert got == sorted(keys.items())
    # bounded range
    some = bt.read_range(b"k00001", b"k00002")
    expect = sorted((k, v) for k, v in keys.items() if b"k00001" <= k < b"k00002")
    assert some == expect
    bt.close()


def test_overflow_values(path):
    bt = KeyValueStoreBTree(path)
    big = os.urandom(50_000)
    huge = os.urandom(200_000)
    bt.set(b"big", big)
    bt.set(b"huge", huge)
    bt.set(b"small", b"x")
    commit(bt)
    bt.close()
    bt = KeyValueStoreBTree(path)
    assert bt.read_value(b"big") == big
    assert bt.read_value(b"huge") == huge
    assert bt.read_value(b"small") == b"x"
    bt.close()


def test_persistence_across_reopen(path):
    bt = KeyValueStoreBTree(path)
    for i in range(1000):
        bt.set(b"p%04d" % i, b"v%d" % i)
    commit(bt)
    bt.clear_range(b"p0100", b"p0200")
    commit(bt)
    bt.close()
    bt = KeyValueStoreBTree(path)
    assert bt.read_value(b"p0050") == b"v50"
    assert bt.read_value(b"p0150") is None
    assert len(bt.read_range(b"p", b"q")) == 900
    bt.close()


def test_uncommitted_work_vanishes(path):
    bt = KeyValueStoreBTree(path)
    bt.set(b"committed", b"yes")
    commit(bt)
    bt.set(b"uncommitted", b"no")
    bt.clear_range(b"committed", b"committed\x00")
    bt.close()  # no commit: shadow pages unreachable from durable root
    bt = KeyValueStoreBTree(path)
    assert bt.read_value(b"committed") == b"yes"
    assert bt.read_value(b"uncommitted") is None
    bt.close()


def test_interleaved_clears_and_sets(path):
    bt = KeyValueStoreBTree(path)
    model = {}
    rnd = random.Random(13)
    for round_no in range(30):
        for _ in range(200):
            k = b"%05d" % rnd.randrange(3000)
            v = b"r%d" % round_no
            bt.set(k, v)
            model[k] = v
        if rnd.random() < 0.5:
            a = b"%05d" % rnd.randrange(3000)
            b = b"%05d" % rnd.randrange(3000)
            if a > b:
                a, b = b, a
            bt.clear_range(a, b)
            for k in [k for k in model if a <= k < b]:
                del model[k]
        commit(bt)
    assert bt.read_range(b"", b"\xff") == sorted(model.items())
    bt.close()
    bt = KeyValueStoreBTree(path)
    assert bt.read_range(b"", b"\xff") == sorted(model.items())
    bt.close()
