"""Real TCP transport tests (net/tcp.py + net/wire.py).

The sim-only transport was round 1's biggest gap (VERDICT missing #1: no
socket code in the repo). These tests drive the real thing on localhost:
framing + handshake, request/reply, BrokenPromise semantics for dead
endpoints/peers, reconnects, and wire round-trips of the rich metadata
payloads that cross process boundaries during recruitment.
"""

import socket
import threading

import pytest

from foundationdb_tpu.net import wire
from foundationdb_tpu.net.sim import BrokenPromise, Endpoint
from foundationdb_tpu.net.tcp import RealWorld
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.runtime.loop import RealLoop


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def run_worlds(main_world, coro, limit=20.0):
    main_world.activate()
    return main_world.run_until_done(spawn(coro), limit)


def make_world(loop):
    # these tests exercise REAL sockets (framing, reconnects, handshake);
    # colocated worlds would otherwise auto-select the in-process loopback
    # (net/loopback.py — covered by tests/test_transport.py)
    from foundationdb_tpu.runtime.knobs import Knobs

    return RealWorld(
        f"127.0.0.1:{free_port()}",
        knobs=Knobs(TRANSPORT_LOOPBACK=False),
        loop=loop,
    )


def test_wire_roundtrip_rich_values():
    from foundationdb_tpu.kv.keyrange_map import KeyRangeMap
    from foundationdb_tpu.kv.mutations import Mutation, MutationType
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.server.interfaces import (
        CommitRequest,
        ProxyInterface,
        TransactionData,
    )
    from foundationdb_tpu.server.log_system import (
        LogSystem,
        LogSystemConfig,
        OldTLogSet,
        TLogInterface,
        TLogSet,
    )

    m = KeyRangeMap(default=None)
    m.insert(b"a", b"m", ("team", 1))
    m.insert(b"m", None, ("team", 2))
    tl = TLogSet(
        epoch=3,
        logs=(TLogInterface(address="h:1", log_id="l0", tags=(0, 1)),),
        replication=1,
    )
    vals = [
        None,
        True,
        -(1 << 80),
        3.5,
        b"\x00\xff",
        "héllo",
        (1, [2, {b"k": "v"}], frozenset({1, 2})),
        Mutation(MutationType.SET_VALUE, b"k", b"v"),
        CommitRequest(
            transaction=TransactionData(
                read_snapshot=7,
                mutations=[Mutation(MutationType.SET_VALUE, b"a", b"1")],
                read_conflict_ranges=[(b"a", b"b")],
                write_conflict_ranges=[(b"a", b"b")],
            )
        ),
        ProxyInterface("1.2.3.4:100", "uid-1"),
        LogSystemConfig(epoch=3, current=tl, old=(OldTLogSet(set=tl, end_version=9),)),
        LogSystem(tl),
        Knobs(MAX_BATCH_TXNS=7),
    ]
    for v in vals:
        enc = wire.encode_value(v)
        out = wire.decode_value(enc)
        if isinstance(v, KeyRangeMap):
            assert list(out.ranges()) == list(v.ranges())
        elif isinstance(v, LogSystem):
            assert out.tlog_set == v.tlog_set
        elif isinstance(v, Knobs):
            assert out.as_dict() == v.as_dict()
        else:
            assert out == v or repr(out) == repr(v), (v, out)
    enc = wire.encode_value(m)
    assert list(wire.decode_value(enc).ranges()) == list(m.ranges())


def test_frame_checksum_rejected():
    f = bytearray(wire.encode_frame(b"hello"))
    f[-1] ^= 0xFF
    with pytest.raises(wire.WireError):
        wire.decode_frames(f)


def test_request_reply_and_errors():
    loop = RealLoop(seed=1)
    a = make_world(loop)
    b = make_world(loop)

    async def echo(x):
        return ("echo", x)

    async def boom(_x):
        raise ValueError("kapow")

    b.node.register("echo", echo)
    b.node.register("boom", boom)

    async def body():
        r = await a.node.request(Endpoint(b.node.address, "echo"), {"n": 1})
        assert r == ("echo", {"n": 1})
        # unknown token → BrokenPromise
        try:
            await a.node.request(Endpoint(b.node.address, "nope"), None)
            assert False
        except BrokenPromise:
            pass
        # remote exception → RemoteError
        from foundationdb_tpu.net.tcp import RemoteError

        try:
            await a.node.request(Endpoint(b.node.address, "boom"), None)
            assert False
        except RemoteError as e:
            assert "kapow" in str(e)
        # local loopback
        a.node.register("self", echo)
        r = await a.node.request(Endpoint(a.node.address, "self"), 5)
        assert r == ("echo", 5)
        return "done"

    assert run_worlds(a, body()) == "done"
    a.close()
    b.close()


def test_dead_peer_and_reconnect():
    loop = RealLoop(seed=2)
    a = make_world(loop)

    async def body():
        dead = f"127.0.0.1:{free_port()}"
        try:
            await a.node.request(Endpoint(dead, "x"), None)
            assert False
        except BrokenPromise:
            pass
        # peer comes up afterwards: a new request connects fresh
        b = make_world(loop)

        async def pong(_x):
            return "pong"

        b.node.register("ping", pong)
        r = await a.node.request(Endpoint(b.node.address, "ping"), None)
        assert r == "pong"
        # peer dies: in-flight + subsequent requests break, then recover
        b.close()
        try:
            await a.node.request(Endpoint(b.node.address, "ping"), None)
            assert False
        except BrokenPromise:
            pass
        return "ok"

    assert run_worlds(a, body()) == "ok"
    a.close()


def test_fdb_error_propagates_by_class():
    from foundationdb_tpu.errors import NotCommitted

    loop = RealLoop(seed=3)
    a = make_world(loop)
    b = make_world(loop)

    async def conflicted(_x):
        raise NotCommitted("conflict")

    b.node.register("c", conflicted)

    async def body():
        try:
            await a.node.request(Endpoint(b.node.address, "c"), None)
            assert False
        except NotCommitted:
            return "typed"

    assert run_worlds(a, body()) == "typed"
    a.close()
    b.close()
