"""Client load balancing (verdict r3 missing #6): latency EWMA + penalty
ordering, hedged second requests riding past a stalled replica."""

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.client.loadbalance import QueueModel
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import spawn
from foundationdb_tpu.runtime.rng import DeterministicRandom
from foundationdb_tpu.server import Cluster, ClusterConfig


def test_queue_model_orders_by_cost():
    Sim(seed=0).activate()  # model reads the loop clock
    m = QueueModel()
    rng = DeterministicRandom(1)
    m.get("slow").latency = 0.05
    m.get("fast").latency = 0.001
    assert m.order(["slow", "fast"], rng)[0] == "fast"
    # outstanding load dominates latency
    m.get("fast").outstanding = 5
    assert m.order(["slow", "fast"], rng)[0] == "slow"
    # failed replicas sort last regardless
    m.get("slow").end(0.0, ok=False)
    m.get("slow").failed_until = 1e9
    assert m.order(["slow", "fast"], rng)[-1] == "slow"


def test_hedged_read_beats_clogged_replica():
    """Clog the primary replica's link mid-run: reads keep completing via
    the hedge to the healthy replica instead of stalling."""
    sim = Sim(seed=9)
    sim.activate()
    cluster = Cluster(
        sim, ClusterConfig(n_storage=2, replication=2, n_tlogs=1)
    )
    db = Database(sim, cluster.proxy_addrs)

    async def body():
        async def w(tr):
            for i in range(20):
                tr.set(b"h%02d" % i, b"v%d" % i)

        await db.run(w)

        # clog every link from the client toward one storage replica
        sim.clog_pair("client", "ss0", 30.0)
        sim.clog_pair("ss0", "client", 30.0)

        from foundationdb_tpu.runtime.loop import now

        t0 = now()
        for i in range(20):

            async def r(tr, i=i):
                return await tr.get(b"h%02d" % i)

            assert await db.run(r) == b"v%d" % i
        took = now() - t0
        # without hedging, any read landing on ss0 first would stall for
        # the full clog window (30s); hedges bound it to ~2x latency
        assert took < 10.0, took
        return True

    assert sim.run_until_done(spawn(body()), 300.0)
