#!/usr/bin/env python
"""North-star benchmark: resolver conflict-check throughput, TPU vs native.

Workload mirrors the reference's skip-list microbench (fdbserver/SkipList.cpp
skipListTest, -r skiplisttest: batches of transactions with 1 read + 1 write
range each, narrow ranges over a uniform keyspace, a sliding ~50-batch MVCC
window), at the BASELINE.json north-star configuration (1M-key
high-contention keyspace).

Both backends resolve the *same* pre-encoded batches; verdict sequences must
match exactly (identical abort rate — the north-star's fairness clause).
Timed region covers resolution only, matching the reference's "Detect only"
metric; the TPU side pipelines groups of batches through one lax.scan
dispatch per group (resolve_many), the production shape of the resolver.

Prints ONE JSON line:
  {"metric": ..., "value": tpu txn/s, "unit": "txn/s",
   "vs_baseline": tpu/native ratio}
"""

import json
import os
import random
import sys
import time

BATCHES = int(os.environ.get("BENCH_BATCHES", "200"))
TXNS = int(os.environ.get("BENCH_TXNS", "2500"))
KEYSPACE = int(os.environ.get("BENCH_KEYSPACE", "1000000"))
WINDOW = 50
GROUP = int(os.environ.get("BENCH_GROUP", "40"))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_batches(n_batches, n_txns, seed=0):
    from foundationdb_tpu.conflict.api import CommitTransaction

    rnd = random.Random(seed)
    batches = []
    for i in range(n_batches):
        txs = []
        for _ in range(n_txns):
            a = rnd.randrange(KEYSPACE)
            b = a + 1 + rnd.randrange(10)
            c = rnd.randrange(KEYSPACE)
            d = c + 1 + rnd.randrange(10)
            txs.append(
                CommitTransaction(
                    read_snapshot=i,
                    read_conflict_ranges=[(b"%08d" % a, b"%08d" % b)],
                    write_conflict_ranges=[(b"%08d" % c, b"%08d" % d)],
                )
            )
        batches.append(txs)
    return batches


def bench_range_index():
    """BENCH_COMPONENT=range_index: the storage read path's batched lookup
    primitive vs the host-side bisect loop (SURVEY.md secondary target)."""
    import bisect

    import numpy as np

    from foundationdb_tpu.ops.range_index import TpuRangeIndex

    n_keys = int(os.environ.get("BENCH_INDEX_KEYS", "1000000"))
    batch = int(os.environ.get("BENCH_INDEX_BATCH", "4096"))
    rounds = int(os.environ.get("BENCH_INDEX_ROUNDS", "50"))
    rnd = random.Random(0)
    keys = sorted({b"%012d" % rnd.randrange(10**12) for _ in range(n_keys)})
    log(f"building index over {len(keys)} keys")
    idx = TpuRangeIndex(keys)
    queries = [
        [rnd.choice(keys) if rnd.random() < 0.7 else b"%012d" % rnd.randrange(10**12)
         for _ in range(batch)]
        for _ in range(rounds)
    ]
    # warm the kernel
    idx.batch_lookup(queries[0])
    t0 = time.time()
    hits = 0
    for q in queries:
        _rows, found = idx.batch_lookup(q)
        hits += int(found.sum())
    tpu_dt = time.time() - t0
    tpu_qps = rounds * batch / tpu_dt
    log(f"tpu index: {tpu_dt:.2f}s, {tpu_qps/1e6:.3f} M lookups/s, {hits} hits")
    t0 = time.time()
    host_hits = 0
    for q in queries:
        for k in q:
            i = bisect.bisect_left(keys, k)
            if i < len(keys) and keys[i] == k:
                host_hits += 1
    host_dt = time.time() - t0
    host_qps = rounds * batch / host_dt
    log(f"host bisect: {host_dt:.2f}s, {host_qps/1e6:.3f} M lookups/s")
    assert hits == host_hits, (hits, host_hits)

    # incremental maintenance: the per-epoch delta-merge the storage path
    # uses vs a full rebuild (the round-4 weak spot: O(N) per epoch)
    delta_add = [b"%012d" % rnd.randrange(10**12) for _ in range(1000)]
    delta_del = rnd.sample(keys, 1000)
    t0 = time.time()
    idx2 = idx.apply_delta(delta_add, delta_del)
    delta_dt = time.time() - t0
    t0 = time.time()
    keys2 = sorted(set(keys) - set(delta_del) | set(delta_add))
    TpuRangeIndex(keys2)
    rebuild_dt = time.time() - t0
    log(
        f"epoch update (1K adds + 1K dels over {len(keys)} keys): "
        f"delta {delta_dt*1000:.1f} ms vs full rebuild "
        f"{rebuild_dt*1000:.1f} ms ({rebuild_dt/max(delta_dt,1e-9):.0f}x)"
    )
    assert idx2.n == len(keys2)
    print(
        json.dumps(
            {
                "metric": "storage_batched_lookup_throughput",
                "value": round(tpu_qps, 1),
                "unit": "lookups/s",
                "vs_baseline": round(tpu_qps / host_qps, 3),
                "native_lookups_s": round(host_qps, 1),  # the denominator
            }
        )
    )


def bench_read_pipeline():
    """BENCH_COMPONENT=read_pipeline: the 90/10 read-heavy TCP row with
    the read pipeline ON vs OFF (ISSUE 12 acceptance; round-5 baseline
    was 4,902 ops/s on this row). Each leg is a real multi-process TCP
    cluster driven by tools/perf; the ON leg embeds the cluster's
    workload/latency_probe status sections, and a traced sim leg embeds
    the span breakdown showing the per-key Client.rpc/Storage.* stages
    collapsed into the batched hop. Writes BENCH_r07.json next to the
    printed JSON line."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    actors = int(os.environ.get("BENCH_RP_ACTORS", "40"))
    txns = int(os.environ.get("BENCH_RP_TXNS", "120"))
    procs = int(os.environ.get("BENCH_RP_PROCS", "2"))

    def run_perf(extra, timeout=1800, workload="90_10"):
        cmd = [
            sys.executable, "-m", "foundationdb_tpu.tools.perf",
            "--workload", workload,
            "--actors", str(actors), "--txns", str(txns),
            "--client-procs", str(procs), "--parallel-reads",
        ] + extra
        log("running: " + " ".join(cmd[3:]))
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo,
        )
        for ln in (r.stderr or "").strip().splitlines()[-4:]:
            log("perf| " + ln)
        lines = [l for l in (r.stdout or "").splitlines() if l.startswith("{")]
        return json.loads(lines[-1]) if lines else None

    on = run_perf(["--mode", "tcp", "--status-json"])
    off = run_perf(["--mode", "tcp", "--no-read-coalescing"])
    read_on = run_perf(["--mode", "tcp"], workload="read")
    read_off = run_perf(
        ["--mode", "tcp", "--no-read-coalescing"], workload="read"
    )
    traced = run_perf(
        ["--mode", "sim", "--trace-sample", "0.2", "--txns", "40"]
    )
    round5_ops = 4902.0  # BENCH_NOTES.md round-5 90/10 TCP row
    round5_read_row = 9860.0  # round-5 100%-read TCP row
    reads_on = (on or {}).get("reads_per_s", 0.0)
    reads_off = (off or {}).get("reads_per_s", 0.0)
    round5_reads = round5_ops * 0.9
    artifact = {
        "metric": "read_pipeline_90_10_tcp",
        "value": reads_on,
        "unit": "reads/s",
        "vs_baseline": round(reads_on / 305_000.0, 4),  # reference read row
        "vs_round5": round(reads_on / round5_reads, 2),
        "vs_pipeline_off": round(reads_on / max(reads_off, 1e-9), 2),
        "shape": f"90_10 x {actors} actors x {txns} txns x {procs} procs",
        "round5_ops_per_s": round5_ops,
        "round5_read_row_reads_per_s": round5_read_row,
        "pipeline_on": on,
        "pipeline_off": off,
        "read_row_on": read_on,
        "read_row_off": read_off,
        "sim_traced": traced,
    }
    with open(os.path.join(repo, "BENCH_r07.json"), "w") as f:
        json.dump(artifact, f, indent=1, default=str)
    log(
        f"read pipeline 90/10 tcp: ON {reads_on:.0f} reads/s, "
        f"OFF {reads_off:.0f} reads/s, round5 {round5_reads:.0f} reads/s "
        f"({reads_on / max(round5_reads, 1e-9):.1f}x round5)"
    )
    print(json.dumps({
        k: artifact[k]
        for k in (
            "metric", "value", "unit", "vs_baseline", "vs_round5",
            "vs_pipeline_off", "shape",
        )
    }))


def bench_transport():
    """BENCH_COMPONENT=transport: the transport v2 A/B (ISSUE 14). Three
    evidence layers, all same-shape gen-7 vs gen-6:
      - raw wire path: pipelined echo RPCs between two colocated worlds
        (gen-6 sockets vs gen-7 super-frames vs gen-7 loopback) — the
        transport isolated from the cluster;
      - cluster rows: 90/10 and read workloads on a colocated in-process
        TCP cluster (tools/perf --mode tcp-inproc) with the new transport
        vs --transport-legacy, run_loop + transport snapshots embedded;
      - a traced leg embedding the span breakdown (Client.rpc self-time).
    Writes BENCH_r09.json next to the printed JSON line."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    actors = int(os.environ.get("BENCH_TR_ACTORS", "60"))
    txns = int(os.environ.get("BENCH_TR_TXNS", "80"))

    def run_perf(extra, workload="90_10", timeout=1800):
        cmd = [
            sys.executable, "-m", "foundationdb_tpu.tools.perf",
            "--mode", "tcp-inproc", "--workload", workload,
            "--actors", str(actors), "--txns", str(txns),
            "--parallel-reads",
        ] + extra
        log("running: " + " ".join(cmd[3:]))
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo,
        )
        for ln in (r.stderr or "").strip().splitlines()[-4:]:
            log("perf| " + ln)
        lines = [l for l in (r.stdout or "").splitlines() if l.startswith("{")]
        return json.loads(lines[-1]) if lines else None

    def echo_bench(batching, loopback, n=6000, depth=64):
        """Raw pipelined RPC echo between two colocated worlds."""
        import time as _time

        from foundationdb_tpu.net.sim import Endpoint
        from foundationdb_tpu.net.tcp import RealWorld
        from foundationdb_tpu.runtime.futures import spawn, wait_for_all
        from foundationdb_tpu.runtime.knobs import Knobs
        from foundationdb_tpu.runtime.loop import RealLoop, set_loop
        import socket as _socket

        def free_port():
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        knobs = Knobs(
            TRANSPORT_FRAME_BATCHING=batching, TRANSPORT_LOOPBACK=loopback
        )
        loop = RealLoop(seed=1)
        a = RealWorld(f"127.0.0.1:{free_port()}", knobs=knobs, loop=loop)
        b = RealWorld(f"127.0.0.1:{free_port()}", knobs=knobs, loop=loop)

        async def echo(x):
            return x

        b.node.register("echo", echo)
        ep = Endpoint(b.node.address, "echo")

        async def worker(i):
            for _ in range(n // depth):
                await a.node.request(ep, (b"key%d" % i, 12345, "value"))

        async def go():
            t0 = _time.perf_counter()
            await wait_for_all([spawn(worker(i)) for i in range(depth)])
            return _time.perf_counter() - t0

        a.activate()
        dt = a.run_until_done(spawn(go()), 300.0)
        snap = a.transport_metrics.snapshot()
        a.close()
        b.close()
        set_loop(None)
        loop.close()
        return {
            "rpc_per_s": round(n / dt, 1),
            "msgs_per_frame": snap["messagesPerFrame"],
            "loopback": snap["loopbackMessages"] > 0,
        }

    echo_gen6 = echo_bench(batching=False, loopback=False)
    echo_gen7_sock = echo_bench(batching=True, loopback=False)
    echo_gen7_loop = echo_bench(batching=True, loopback=True)
    log(
        f"echo rpc/s: gen6 {echo_gen6['rpc_per_s']:.0f}, gen7-sockets "
        f"{echo_gen7_sock['rpc_per_s']:.0f}, gen7-loopback "
        f"{echo_gen7_loop['rpc_per_s']:.0f}"
    )

    on90 = run_perf(["--trace-sample", "0.2"])
    off90 = run_perf(["--transport-legacy"])
    read_on = run_perf([], workload="read")
    read_off = run_perf(["--transport-legacy"], workload="read")

    ops_on = (on90 or {}).get("ops_per_s", 0.0)
    ops_off = (off90 or {}).get("ops_per_s", 0.0)
    artifact = {
        "metric": "transport_90_10_inproc_tcp",
        "value": ops_on,
        "unit": "ops/s",
        "vs_baseline": round(ops_on / 107_000.0, 4),  # reference 90/10 row
        "vs_gen6": round(ops_on / max(ops_off, 1e-9), 2),
        "echo_rpc_vs_gen6": round(
            echo_gen7_loop["rpc_per_s"] / max(echo_gen6["rpc_per_s"], 1e-9), 2
        ),
        "shape": f"tcp-inproc 90_10 x {actors} actors x {txns} txns",
        "echo": {
            "gen6_sockets": echo_gen6,
            "gen7_sockets": echo_gen7_sock,
            "gen7_loopback": echo_gen7_loop,
        },
        "inproc_90_10_on": on90,
        "inproc_90_10_legacy": off90,
        "inproc_read_on": read_on,
        "inproc_read_legacy": read_off,
    }
    with open(os.path.join(repo, "BENCH_r09.json"), "w") as f:
        json.dump(artifact, f, indent=1, default=str)
    log(
        f"transport 90/10 inproc: ON {ops_on:.0f} ops/s vs gen-6 "
        f"{ops_off:.0f} ops/s ({artifact['vs_gen6']:.2f}x); raw echo "
        f"{artifact['echo_rpc_vs_gen6']:.2f}x gen-6"
    )
    print(json.dumps({
        k: artifact[k]
        for k in (
            "metric", "value", "unit", "vs_baseline", "vs_gen6",
            "echo_rpc_vs_gen6", "shape",
        )
    }))


def bench_storage_engine():
    """BENCH_COMPONENT=storage_engine: the epoch-batched engine A/B
    (ISSUE 15 / ROADMAP item 5). Three evidence layers:
      - micro ingest: the same mutation stream applied through the epoch
        path (apply_epoch, one sorted merge per batch) vs the legacy
        per-mutation path (insort per new key), window map and durable
        engine both — wall time + the keys_moved counters;
      - cluster rows: the 50/50 and read TCP rows (multi-process, the
        round-5/7/9 regime) with STORAGE_EPOCH_BATCHING on vs off —
        same-day same-shape A/B, ON leg embeds the cluster's status
        sections (storage_engine counters, latency_probe);
      - the sustained mixed soak (clients + bulkload + backup
        concurrently, tools/soak.py --mixed): read-probe p95 by thirds
        must stay flat while ingest runs hot.
    native_txn_s rides along from the native conflict-set baseline (the
    ROADMAP's denominator discipline). Writes BENCH_r10.json."""
    import subprocess
    import time as _time

    repo = os.path.dirname(os.path.abspath(__file__))
    actors = int(os.environ.get("BENCH_SE_ACTORS", "40"))
    txns = int(os.environ.get("BENCH_SE_TXNS", "120"))
    procs = int(os.environ.get("BENCH_SE_PROCS", "2"))

    # ---- micro ingest A/B (host-only, no cluster) ----
    def micro_ingest():
        from foundationdb_tpu.kv.versioned_map import (
            EpochVersionedMap,
            VersionedMap,
        )

        rnd = random.Random(5)
        n_epochs = int(os.environ.get("BENCH_SE_EPOCHS", "120"))
        per_epoch = int(os.environ.get("BENCH_SE_MUTS", "400"))
        stream = []
        v = 0
        for _ in range(n_epochs):
            v += 10
            entries = {
                b"%010d" % rnd.randrange(10**9): b"v" * 16
                for _ in range(per_epoch)
            }
            clears = (
                [(b"%010d" % (c := rnd.randrange(10**9)), b"%010d" % (c + 500))]
                if rnd.random() < 0.05
                else []
            )
            stream.append((v, entries, clears))

        em = EpochVersionedMap()
        t0 = _time.perf_counter()
        for v, entries, clears in stream:
            em.apply_epoch(v, entries, clears)
        epoch_dt = _time.perf_counter() - t0

        lm = VersionedMap()
        t0 = _time.perf_counter()
        for v, entries, clears in stream:
            for b, e in clears:
                lm.clear_range(b, e, v)
            for k, val in entries.items():
                lm.set(k, val, v)
        legacy_dt = _time.perf_counter() - t0
        total = n_epochs * per_epoch
        log(
            f"micro ingest ({n_epochs}x{per_epoch} muts): epoch "
            f"{epoch_dt:.2f}s ({total/epoch_dt/1e3:.0f} Kmut/s, "
            f"{em.keys_moved/1e6:.1f}M keys moved) vs legacy "
            f"{legacy_dt:.2f}s ({total/legacy_dt/1e3:.0f} Kmut/s) = "
            f"{legacy_dt/epoch_dt:.2f}x"
        )
        return {
            "epochs": n_epochs,
            "mutations_per_epoch": per_epoch,
            "epoch_apply_s": round(epoch_dt, 3),
            "legacy_apply_s": round(legacy_dt, 3),
            "epoch_muts_per_s": round(total / epoch_dt, 1),
            "legacy_muts_per_s": round(total / legacy_dt, 1),
            "speedup": round(legacy_dt / epoch_dt, 2),
            "epoch_keys_moved": em.keys_moved,
        }

    micro = micro_ingest()

    # ---- native conflict-set baseline (the denominator on record) ----
    from foundationdb_tpu.conflict.native import NativeConflictSet

    nb, nt = 40, 640  # CPU smoke shape (ROADMAP: quote shape with ratio)
    nat = NativeConflictSet()
    global BATCHES, TXNS
    old_shape = (BATCHES, TXNS)
    BATCHES, TXNS = nb, nt
    nat_batches = make_batches(nb, nt)
    BATCHES, TXNS = old_shape
    nat_enc = [nat.encode_batch(txs) for txs in nat_batches]
    t0 = _time.perf_counter()
    for i, enc in enumerate(nat_enc):
        nat.resolve_encoded(enc, i + WINDOW, i)
    nat_tps = nb * nt / (_time.perf_counter() - t0)
    log(f"native baseline ({nb}x{nt}): {nat_tps/1e6:.3f} Mtxn/s")

    # ---- cluster rows: 50/50 + read TCP, knob on vs off ----
    def run_perf(extra, workload="50_50", timeout=1800, mode="tcp"):
        cmd = [
            sys.executable, "-m", "foundationdb_tpu.tools.perf",
            "--mode", mode, "--workload", workload,
            "--actors", str(actors), "--txns", str(txns),
            "--client-procs", str(procs), "--parallel-reads",
        ] + extra
        log("running: " + " ".join(cmd[3:]))
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo,
        )
        for ln in (r.stderr or "").strip().splitlines()[-4:]:
            log("perf| " + ln)
        lines = [l for l in (r.stdout or "").splitlines() if l.startswith("{")]
        return json.loads(lines[-1]) if lines else None

    on = run_perf(["--status-json"])
    off = run_perf(["--storage-legacy-engine"])
    read_on = run_perf([], workload="read")
    read_off = run_perf(["--storage-legacy-engine"], workload="read")

    # ---- controlled same-process A/B (tcp-inproc): the multi-process
    # rows on this one-core box swing +-9% run to run (7 processes fight
    # the scheduler), so the colocated leg is where the engine delta is
    # actually measurable — run_loop hot-actor attribution rides along
    inproc_on = run_perf([], mode="tcp-inproc")
    inproc_off = run_perf(["--storage-legacy-engine"], mode="tcp-inproc")
    # the ingest-heavy row is where the apply path IS the bottleneck:
    # bulkload (50 contiguous keys/txn, 8 writers — past that the row is
    # commit-queue-bound, not apply-bound) exercises epoch apply + the
    # engine's one-merge-per-epoch drain end to end
    bulk_args = ["--actors", "8", "--txns", "120"]
    bulk_on = run_perf(bulk_args, mode="tcp-inproc", workload="bulkload")
    bulk_off = run_perf(
        bulk_args + ["--storage-legacy-engine"],
        mode="tcp-inproc",
        workload="bulkload",
    )

    def keys_s(rep):
        rep = rep or {}
        return rep.get("keys_per_s") or rep.get("writes_per_s") or 0.0

    # ---- sustained mixed soak (flatness evidence) ----
    mixed = None
    try:
        r = subprocess.run(
            [
                sys.executable, "-m", "foundationdb_tpu.tools.soak",
                "--mixed", os.environ.get("BENCH_SE_MIXED_S", "20"), "3",
            ],
            capture_output=True, text=True, timeout=1800,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo,
        )
        lines = [l for l in (r.stdout or "").splitlines() if l.startswith("{")]
        mixed = json.loads(lines[-1]) if lines else None
    except Exception as e:
        log(f"mixed soak leg failed: {e!r}")

    ops_on = (on or {}).get("ops_per_s", 0.0)
    ops_off = (off or {}).get("ops_per_s", 0.0)
    round5_5050 = 5186.0  # BENCH_NOTES round-5 50/50 TCP row
    artifact = {
        "metric": "storage_engine_50_50_tcp",
        "value": ops_on,
        "unit": "ops/s",
        "vs_baseline": round(ops_on / 107_000.0, 4),  # reference row
        "vs_epoch_off": round(ops_on / max(ops_off, 1e-9), 2),
        "vs_round5_row": round(ops_on / round5_5050, 2),
        "native_txn_s": round(nat_tps, 1),
        "native_shape": f"{nb}x{nt}",
        "shape": f"50_50 x {actors} actors x {txns} txns x {procs} procs",
        "round5_50_50_ops_per_s": round5_5050,
        "inproc_50_50_vs_off": round(
            ((inproc_on or {}).get("ops_per_s") or 0.0)
            / max((inproc_off or {}).get("ops_per_s") or 0.0, 1e-9),
            2,
        ),
        "micro_ingest": micro,
        "epoch_on": on,
        "epoch_off": off,
        "read_row_on": read_on,
        "read_row_off": read_off,
        "inproc_50_50_on": inproc_on,
        "inproc_50_50_off": inproc_off,
        "bulkload_vs_off": round(keys_s(bulk_on) / max(keys_s(bulk_off), 1e-9), 2),
        "bulkload_on": bulk_on,
        "bulkload_off": bulk_off,
        "mixed_soak": mixed,
    }
    with open(os.path.join(repo, "BENCH_r10.json"), "w") as f:
        json.dump(artifact, f, indent=1, default=str)
    log(
        f"storage engine 50/50 tcp: ON {ops_on:.0f} ops/s vs OFF "
        f"{ops_off:.0f} ops/s ({artifact['vs_epoch_off']:.2f}x multi-proc); "
        f"in-proc {artifact['inproc_50_50_vs_off']:.2f}x; bulkload "
        f"{artifact['bulkload_vs_off']:.2f}x; read row "
        f"ON {(read_on or {}).get('reads_per_s', 0):.0f} vs OFF "
        f"{(read_off or {}).get('reads_per_s', 0):.0f}; micro ingest "
        f"{micro['speedup']:.2f}x"
    )
    print(json.dumps({
        k: artifact[k]
        for k in (
            "metric", "value", "unit", "vs_baseline", "vs_epoch_off",
            "inproc_50_50_vs_off", "bulkload_vs_off", "vs_round5_row",
            "native_txn_s", "native_shape", "shape",
        )
    }))


def bench_prefilter():
    """BENCH_COMPONENT=prefilter: the proxy conflict pre-filter contention
    sweep (ISSUE 17). Same-seed sim-cluster A/B (PROXY_CONFLICT_PREFILTER
    on vs off) at three contention levels — a hot-keyspace readwrite mix
    whose abort rate climbs as the keyspace shrinks. Per leg: wall time,
    committed/conflicted/prefiltered counters, workload.abort_rate, the
    resolver-side transaction count (the work the filter exists to
    shed), and the resolve/commit latency-band counts. The uplift claim
    is resolver-side: at the high-contention shape the ON leg must show
    workload.prefiltered > 0 and fewer transactions reaching resolvers
    for the same offered load, with resolver band counts dropping at
    equal commit bands. Writes BENCH_r11.json."""
    import time as _time

    from foundationdb_tpu.client import management
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.net.sim import Endpoint, Sim
    from foundationdb_tpu.runtime.futures import spawn
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
    from foundationdb_tpu.workloads import run_workloads
    from foundationdb_tpu.workloads.readwrite import ReadWriteWorkload

    actors = int(os.environ.get("BENCH_PF_ACTORS", "12"))
    txns = int(os.environ.get("BENCH_PF_TXNS", "40"))
    seed = int(os.environ.get("BENCH_PF_SEED", "17"))
    # keyspace sizes: 8 keys = pathological contention, 64 = hot,
    # 4096 = the low-contention control (filter should do ~nothing)
    keyspaces = [
        int(k) for k in os.environ.get("BENCH_PF_KEYSPACES", "8,64,4096").split(",")
    ]

    def leg(keyspace, prefilter_on):
        knobs = Knobs(PROXY_CONFLICT_PREFILTER=prefilter_on)
        sim = Sim(seed=seed, knobs=knobs)
        sim.activate()
        cluster = DynamicCluster(
            sim,
            ClusterConfig(n_proxies=2, n_resolvers=2, n_tlogs=1, n_storage=2),
        )
        db = Database.from_coordinators(sim, cluster.coordinators)
        wl = ReadWriteWorkload(
            db, sim.loop.random.fork(), actors=actors, txns_per_actor=txns,
            reads_per_txn=4, writes_per_txn=2, keyspace=keyspace,
            prefix=b"pf/",
        )

        async def body():
            await run_workloads([wl])
            doc = await management.get_status(cluster.coordinators, db.client)
            # resolver-side work: sum the resolvers' transactions counter
            # straight off every worker's role-metrics endpoint
            r_txns = 0
            for addr in list(sim.processes):
                try:
                    snaps = await db.client.request(
                        Endpoint(addr, "worker.metrics"), None
                    )
                except Exception:
                    continue
                for snap in (snaps or {}).values():
                    if isinstance(snap, dict) and snap.get("kind") == "resolver":
                        r_txns += snap.get("transactions", 0)
            return doc, r_txns

        t0 = _time.perf_counter()
        doc, resolver_txns = sim.run_until_done(spawn(body()), 1800.0)
        wall = _time.perf_counter() - t0
        assert not sim.prefilter_oracle.violations, sim.prefilter_oracle.violations
        wld = doc.get("workload") or {}
        txd = wld.get("transactions") or {}
        bands = wld.get("latency_bands") or {}
        out = {
            "keyspace": keyspace,
            "prefilter": prefilter_on,
            "wall_s": round(wall, 3),
            "committed": (txd.get("committed") or {}).get("counter", 0),
            "conflicted": (txd.get("conflicted") or {}).get("counter", 0),
            "prefiltered": (wld.get("prefiltered") or {}).get("counter", 0),
            "abort_rate": wld.get("abort_rate", 0.0),
            "resolver_txns": resolver_txns,
            "resolve_band_count": (bands.get("resolve") or {}).get("count", 0),
            "commit_band_count": (bands.get("commit") or {}).get("count", 0),
            "oracle_rejections_checked": sim.prefilter_oracle.rejections_checked,
        }
        return out

    sweep = []
    for ks in keyspaces:
        on = leg(ks, True)
        off = leg(ks, False)
        saved = off["resolver_txns"] - on["resolver_txns"]
        row = {
            "keyspace": ks,
            "on": on,
            "off": off,
            "resolver_txns_saved": saved,
            "resolver_txns_saved_frac": round(
                saved / max(off["resolver_txns"], 1), 4
            ),
            "wall_ratio_off_over_on": round(
                off["wall_s"] / max(on["wall_s"], 1e-9), 2
            ),
        }
        sweep.append(row)
        log(
            f"keyspace {ks}: ON prefiltered={on['prefiltered']} "
            f"abort={on['abort_rate']:.2f} resolver_txns={on['resolver_txns']} "
            f"vs OFF abort={off['abort_rate']:.2f} "
            f"resolver_txns={off['resolver_txns']} "
            f"(saved {row['resolver_txns_saved_frac']:.0%})"
        )

    hot = sweep[0]
    repo = os.path.dirname(os.path.abspath(__file__))
    artifact = {
        "metric": "prefilter_resolver_txns_saved_frac",
        "value": hot["resolver_txns_saved_frac"],
        "unit": "fraction of resolver-side txns shed at hottest keyspace",
        "vs_baseline": hot["wall_ratio_off_over_on"],
        "prefiltered_hot": hot["on"]["prefiltered"],
        "shape": (
            f"{actors} actors x {txns} txns, keyspaces "
            + ",".join(str(k) for k in keyspaces)
        ),
        "sweep": sweep,
    }
    with open(os.path.join(repo, "BENCH_r11.json"), "w") as f:
        json.dump(artifact, f, indent=1, default=str)
    print(json.dumps({
        k: artifact[k]
        for k in (
            "metric", "value", "unit", "vs_baseline", "prefiltered_hot",
            "shape",
        )
    }))


def bench_commit_path():
    """BENCH_COMPONENT=commit_path: the ISSUE-18 commit-path A/B. Three
    mechanisms behind one legacy flag (--commit-path-legacy pins the
    interpretive codec + per-waiter settling + serialized tlog fsync):
      - codec micro (perf --codec-micro): the compiled codec's isolated
        encode/decode speedup + the byte-identity verdict;
      - cluster rows: 50/50 TCP (multi-process, the round-5/7/9 regime)
        and the write row, ON vs legacy, same-day interleaved; ON leg
        embeds status evidence (workload.tlog fsync rounds/group joins);
      - the colocated tcp-inproc 50/50 + write rows, where the delta is
        measurable on this one-core box (multi-proc swings +-9%);
        run_loop profiler snapshots ride in every leg.
    native_txn_s rides along from the native conflict-set baseline (the
    ROADMAP's denominator discipline). Writes BENCH_r12.json."""
    import subprocess
    import time as _time

    repo = os.path.dirname(os.path.abspath(__file__))
    actors = int(os.environ.get("BENCH_CP_ACTORS", "40"))
    txns = int(os.environ.get("BENCH_CP_TXNS", "120"))
    procs = int(os.environ.get("BENCH_CP_PROCS", "2"))

    # ---- native conflict-set baseline (the denominator on record) ----
    from foundationdb_tpu.conflict.native import NativeConflictSet

    nb, nt = 40, 640  # CPU smoke shape (ROADMAP: quote shape with ratio)
    nat = NativeConflictSet()
    global BATCHES, TXNS
    old_shape = (BATCHES, TXNS)
    BATCHES, TXNS = nb, nt
    nat_batches = make_batches(nb, nt)
    BATCHES, TXNS = old_shape
    nat_enc = [nat.encode_batch(txs) for txs in nat_batches]
    t0 = _time.perf_counter()
    for i, enc in enumerate(nat_enc):
        nat.resolve_encoded(enc, i + WINDOW, i)
    nat_tps = nb * nt / (_time.perf_counter() - t0)
    log(f"native baseline ({nb}x{nt}): {nat_tps/1e6:.3f} Mtxn/s")

    def run_perf(extra, workload="50_50", timeout=1800, mode="tcp"):
        cmd = [
            sys.executable, "-m", "foundationdb_tpu.tools.perf",
            "--mode", mode, "--workload", workload,
            "--actors", str(actors), "--txns", str(txns),
            "--client-procs", str(procs), "--parallel-reads",
        ] + extra
        log("running: " + " ".join(cmd[3:]))
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo,
        )
        for ln in (r.stderr or "").strip().splitlines()[-4:]:
            log("perf| " + ln)
        lines = [l for l in (r.stdout or "").splitlines() if l.startswith("{")]
        return json.loads(lines[-1]) if lines else None

    # ---- codec micro (isolated wire-layer contribution) ----
    micro = run_perf(["--codec-micro"], mode="sim")  # mode ignored by flag
    if micro:
        log(
            f"codec micro: encode x{micro.get('encode_speedup')} decode "
            f"x{micro.get('decode_speedup')} compiled, byte_identical="
            f"{micro.get('byte_identical')}"
        )

    # ---- cluster rows: interleaved ON/legacy pairs, same day ----
    on = run_perf(["--status-json"])
    off = run_perf(["--commit-path-legacy"])
    inproc_on = run_perf([], mode="tcp-inproc")
    inproc_off = run_perf(["--commit-path-legacy"], mode="tcp-inproc")
    # the write row is where the commit path IS the workload (0r+10w:
    # every op is a mutation through codec + slab settle + tlog fsync)
    write_on = run_perf([], mode="tcp-inproc", workload="write")
    write_off = run_perf(
        ["--commit-path-legacy"], mode="tcp-inproc", workload="write"
    )

    def ratio(a, b, metric="ops_per_s"):
        return round(
            ((a or {}).get(metric) or 0.0)
            / max((b or {}).get(metric) or 0.0, 1e-9),
            2,
        )

    ops_on = (on or {}).get("ops_per_s", 0.0)
    ops_off = (off or {}).get("ops_per_s", 0.0)
    round5_5050 = 5186.0  # BENCH_NOTES round-5 50/50 TCP row
    tlog_ev = (((on or {}).get("status") or {}).get("workload") or {}).get(
        "tlog"
    )
    artifact = {
        "metric": "commit_path_50_50_tcp",
        "value": ops_on,
        "unit": "ops/s",
        "vs_baseline": round(ops_on / 107_000.0, 4),  # reference row
        "vs_legacy": round(ops_on / max(ops_off, 1e-9), 2),
        "vs_round5_row": round(ops_on / round5_5050, 2),
        "native_txn_s": round(nat_tps, 1),
        "native_shape": f"{nb}x{nt}",
        "shape": f"50_50 x {actors} actors x {txns} txns x {procs} procs",
        "round5_50_50_ops_per_s": round5_5050,
        "inproc_50_50_vs_legacy": ratio(inproc_on, inproc_off),
        "write_vs_legacy": ratio(write_on, write_off, "writes_per_s"),
        "codec_micro": micro,
        "tlog_status_on": tlog_ev,
        "on": on,
        "legacy": off,
        "inproc_50_50_on": inproc_on,
        "inproc_50_50_legacy": inproc_off,
        "write_on": write_on,
        "write_legacy": write_off,
    }
    with open(os.path.join(repo, "BENCH_r12.json"), "w") as f:
        json.dump(artifact, f, indent=1, default=str)
    log(
        f"commit path 50/50 tcp: ON {ops_on:.0f} ops/s vs legacy "
        f"{ops_off:.0f} ops/s ({artifact['vs_legacy']:.2f}x multi-proc); "
        f"in-proc {artifact['inproc_50_50_vs_legacy']:.2f}x; write row "
        f"{artifact['write_vs_legacy']:.2f}x; tlog evidence {tlog_ev}"
    )
    print(json.dumps({
        k: artifact[k]
        for k in (
            "metric", "value", "unit", "vs_baseline", "vs_legacy",
            "inproc_50_50_vs_legacy", "write_vs_legacy", "vs_round5_row",
            "native_txn_s", "native_shape", "shape",
        )
    }))


def bench_admission():
    """BENCH_COMPONENT=admission: the overload A/B (ISSUE 13). Two legs of
    tools/perf --overload-factor (same seed, same offered load): admission
    ON (per-class buckets + deadline shedding) vs OFF (the pre-ISSUE-13
    unbounded deadline-free park). Evidence embedded per leg: goodput vs
    calibrated peak, admitted-traffic commit p95, and the cluster's
    qos/workload/latency_probe status sections. Writes BENCH_r08.json."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    factor = os.environ.get("BENCH_OVERLOAD_FACTOR", "5")
    actors = os.environ.get("BENCH_OVERLOAD_ACTORS", "20")
    duration = os.environ.get("BENCH_OVERLOAD_DURATION", "3.0")

    def run_perf(extra):
        cmd = [
            sys.executable, "-m", "foundationdb_tpu.tools.perf",
            "--overload-factor", factor, "--actors", actors,
            "--duration", duration,
        ] + extra
        log("running: " + " ".join(cmd[3:]))
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=3600,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo,
        )
        for ln in (r.stderr or "").strip().splitlines()[-4:]:
            log("perf| " + ln)
        lines = [l for l in (r.stdout or "").splitlines() if l.startswith("{")]
        return json.loads(lines[-1]) if lines else None

    on = run_perf([])
    off = run_perf(["--no-admission"])
    goodput_on = (on or {}).get("goodput_ratio", 0.0)
    goodput_off = (off or {}).get("goodput_ratio", 0.0)
    p95_on = (on or {}).get("admitted_commit_p95_ms", 0.0)
    p95_off = (off or {}).get("admitted_commit_p95_ms", 0.0)
    artifact = {
        "metric": "admission_overload_goodput_ratio",
        "value": goodput_on,
        "unit": "goodput/peak at ~%sx offered load" % factor,
        "vs_baseline": round(goodput_on / max(goodput_off, 1e-9), 2),
        "admitted_commit_p95_ms_on": p95_on,
        "admitted_commit_p95_ms_off": p95_off,
        "shape": f"overload x{factor}, {actors} base actors, {duration}s legs",
        "admission_on": on,
        "admission_off": off,
    }
    with open(os.path.join(repo, "BENCH_r08.json"), "w") as f:
        json.dump(artifact, f, indent=1, default=str)
    log(
        f"admission overload A/B: goodput ON {goodput_on:.2f} of peak "
        f"(p95 {p95_on:.1f} ms) vs OFF {goodput_off:.2f} (p95 "
        f"{p95_off:.1f} ms)"
    )
    print(json.dumps({
        k: artifact[k]
        for k in (
            "metric", "value", "unit", "vs_baseline",
            "admitted_commit_p95_ms_on", "admitted_commit_p95_ms_off",
            "shape",
        )
    }))


def bench_e2e():
    """BENCH_COMPONENT=e2e: whole-system commit throughput + latency — N
    clients through client→proxy→resolver→tlog→storage in simulation
    (BASELINE.md's concurrent-writes shape: many clients, 10 keys/txn).

    Reports wall-clock txn/s (host work of the full pipeline) and p50/p95
    commit latency in SIM time (the model-time cost of batching and the
    5-phase pipeline — the analog of the reference's 1.5-2.5 ms commit
    budget, performance.rst:36). BENCH_E2E_BACKEND picks the resolver's
    conflict backend (default tpu; oracle/native for comparison)."""
    from foundationdb_tpu.client.database import Database
    from foundationdb_tpu.net.sim import Sim
    from foundationdb_tpu.runtime.futures import spawn, wait_for_all
    from foundationdb_tpu.runtime.loop import now as sim_now
    from foundationdb_tpu.server import Cluster, ClusterConfig

    backend = os.environ.get("BENCH_E2E_BACKEND", "tpu")
    n_clients = int(os.environ.get("BENCH_E2E_CLIENTS", "50"))
    n_txns = int(os.environ.get("BENCH_E2E_TXNS", "40"))
    keyspace = int(os.environ.get("BENCH_E2E_KEYSPACE", "100000"))
    net = os.environ.get("BENCH_E2E_NET", "datacenter")

    sim = Sim(seed=0)
    sim.activate()
    if net == "datacenter":
        # the reference's commit-latency budget (performance.rst:36,
        # 1.5-2.5 ms) is measured on REAL clusters with ~0.1-0.25 ms
        # network hops; Sim2's default latency model averages 0.5 ms/hop
        # (flow/Knobs.cpp:106). For the perf-budget comparison, model the
        # benchmark network; BENCH_E2E_NET=sim2 keeps the fat sim profile.
        sim.knobs.SIM_FAST_LATENCY = 0.00025
        sim.knobs.SIM_MAX_LATENCY = 0.001
    cluster = Cluster(
        sim, ClusterConfig(n_proxies=2, n_resolvers=2, conflict_backend=backend)
    )
    db = Database(sim, cluster.proxy_addrs)
    rnd = random.Random(7)
    latencies = []

    committed = [0]

    async def client(cid):
        for t in range(n_txns):
            for attempt in range(20):
                tr = db.transaction()
                try:
                    for _ in range(10):
                        k = b"%06d" % rnd.randrange(keyspace)
                        tr.set(k, b"c%d-%d" % (cid, t))
                    t0 = sim_now()
                    await tr.commit()
                    latencies.append(sim_now() - t0)
                    committed[0] += 1
                    break
                except Exception as e:
                    await tr.on_error(e)
        return True

    async def go():
        return await wait_for_all([spawn(client(c)) for c in range(n_clients)])

    t0 = time.time()
    oks = sim.run_until_done(spawn(go()), 3600.0)
    wall = time.time() - t0
    for pr in cluster.proxies:
        snap = pr.stats.snapshot()
        log(
            f"  proxy {pr.uid}: p1Version {snap['phase1Version']} "
            f"p2Resolve {snap['phase2Resolve']} p4Push {snap['phase4LogPush']}"
        )
    assert all(oks)
    total = committed[0]
    assert total == len(latencies)
    latencies.sort()
    p50 = latencies[len(latencies) // 2] * 1000
    p95 = latencies[int(len(latencies) * 0.95)] * 1000
    tps = total / wall
    log(
        f"e2e[{backend},{net}]: {total} txns in {wall:.2f}s wall = {tps:.0f} "
        f"txn/s; commit latency p50 {p50:.2f}ms p95 {p95:.2f}ms (sim time)"
    )
    print(
        json.dumps(
            {
                "metric": "e2e_commit_throughput",
                "value": round(tps, 1),
                "unit": "txn/s",
                "vs_baseline": round(tps / 46000.0, 4),
                "native_txn_s": 46000.0,  # the reference-cluster denominator
                "p50_commit_ms_simtime": round(p50, 2),
                "p95_commit_ms_simtime": round(p95, 2),
                "backend": backend,
                "net_profile": net,
            }
        )
    )


def bench_resolver_pipeline():
    """BENCH_COMPONENT=resolver_pipeline: before/after evidence for the
    double-buffered conflict pipeline (ISSUE 11). Runs a Resolver on the
    REAL loop personality with the run-loop profiler installed, resolving
    the same chained commit batches through the device backend twice:

      before — CONFLICT_ENCODE_THREADS=0: host encode serialized inside
               the dispatch job on the device thread (the pre-PR shape);
      after  — the default dedicated encode executor: batch N encodes
               while batch N-1's device scan is in flight.

    Prints ONE JSON line embedding both run_loop snapshots (busy
    fraction, per-priority starvation, slow tasks) and kernel snapshots
    (encodeOverlapSeconds = encode time hidden off the critical path)
    next to txn/s. NOTE on a 1-core host the overlap is bounded by the
    core count (degraded-evidence capture, BENCH_NOTES.md); on-chip the
    scan occupies the device while the host encodes, so the hidden
    fraction is the real win."""
    import jax
    import jax._src.xla_bridge as xb

    xb._backend_factories.pop("axon", None)
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from foundationdb_tpu.runtime import profiler as profiler_mod
    from foundationdb_tpu.runtime.futures import spawn
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.runtime.loop import RealLoop, set_loop
    from foundationdb_tpu.server.interfaces import (
        ResolveBatchRequest,
        TransactionData,
    )
    from foundationdb_tpu.server.resolver import Resolver

    batches_n = int(os.environ.get("BENCH_PIPE_BATCHES", "30"))
    txns_n = int(os.environ.get("BENCH_PIPE_TXNS", "256"))
    cap = 1 << 14
    batches = make_batches(batches_n, txns_n, seed=3)
    reqs = []
    prev = 0
    for i, txs in enumerate(batches):
        ver = prev + 10
        reqs.append(
            ResolveBatchRequest(
                version=ver,
                prev_version=prev,
                transactions=[
                    TransactionData(
                        read_snapshot=max(0, ver - 500),
                        read_conflict_ranges=list(t.read_conflict_ranges),
                        write_conflict_ranges=list(t.write_conflict_ranges),
                        mutations=[],
                    )
                    for t in txs
                ],
                last_receive_version=0,
                requesting_proxy="px",
            )
        )
        prev = ver

    def run_mode(encode_threads):
        loop = RealLoop(seed=11)
        set_loop(loop)
        knobs = Knobs(
            CONFLICT_ENCODE_THREADS=encode_threads,
            CONFLICT_DISPATCH_DEADLINE=300.0,  # CPU compiles ride under it
        )
        prof = profiler_mod.install(
            loop, knobs=knobs, wall=True, ident="bench"
        )
        r = Resolver(
            knobs=knobs, backend="tpu1", first_version=0, uid="r0",
            capacity=cap, key_width=12,
        )
        try:

            async def go():
                futs = [spawn(r.resolve(rq)) for rq in reqs]
                for f in futs:
                    await f
                return True

            t0 = time.time()
            fut = spawn(go())
            loop.run(stop_when=fut.is_ready)
            assert fut.get() is True
            wall = time.time() - t0
            kernel = r.stats.snapshot()["kernel"]
            run_loop = prof.snapshot()
            tps = batches_n * txns_n / wall
            log(
                f"encode_threads={encode_threads}: {wall:.2f}s "
                f"= {tps/1e3:.1f} Ktxn/s, overlap "
                f"{kernel['encodeOverlapSeconds']}"
            )
            return {
                "encode_threads": encode_threads,
                "txn_s": round(tps, 1),
                "wall_s": round(wall, 3),
                "run_loop": run_loop,
                "kernel": kernel,
            }
        finally:
            r.close()
            set_loop(None)
            loop.close()

    log("warmup pass (pays the in-process XLA compiles for both modes)")
    run_mode(0)  # discarded: both timed runs ride the warm compile cache
    before = run_mode(0)
    after = run_mode(int(os.environ.get("CONFLICT_ENCODE_THREADS", "1")))
    print(
        json.dumps(
            {
                "metric": "resolver_pipeline_ab",
                "unit": "txn/s",
                "value": after["txn_s"],
                "vs_before": round(
                    after["txn_s"] / max(before["txn_s"], 1e-9), 3
                ),
                "shape": f"{batches_n}x{txns_n}",
                "before": before,
                "after": after,
            },
            default=str,
        )
    )


_EVIDENCE_BEGIN = "<!-- degraded-evidence:begin -->"
_EVIDENCE_END = "<!-- degraded-evidence:end -->"


def bench_degraded_evidence():
    """BENCH_COMPONENT=degraded_evidence (also auto-run by the default
    bench when the TPU tunnel is unreachable): run the grid kernel on the
    CPU JAX backend at the bench smoke shape and persist per-phase
    op/byte counts (XLA cost analysis) plus a bandwidth-model device-time
    prediction into BENCH_NOTES.md — so the numbers a wedged-tunnel round
    would otherwise assert from memory are derived, on the record, and
    reviewable against the next healthy-tunnel capture."""
    import jax
    import jax._src.xla_bridge as xb

    xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from foundationdb_tpu.conflict import grid as G
    from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet

    batches_n = int(os.environ.get("BENCH_EVIDENCE_BATCHES", "40"))
    txns_n = int(os.environ.get("BENCH_EVIDENCE_TXNS", "640"))
    kw = int(os.environ.get("BENCH_KEY_WIDTH", "12"))
    cap = 1 << 17
    while cap < 4 * txns_n * WINDOW:
        cap <<= 1
    log(f"degraded evidence: CPU grid kernel at {batches_n}x{txns_n}, cap {cap}")
    global BATCHES, TXNS
    BATCHES, TXNS = batches_n, txns_n
    batches = make_batches(batches_n, txns_n)
    tpu = TpuConflictSet(key_width=kw, capacity=cap)
    enc = [tpu.encode(txs) for txs in batches]
    state = tpu._state
    batch = enc[0][0]  # encode() returns (Batch, n_real, epoch)
    B, S, lp1 = state.grid.shape

    def costed(name, fn, *args):
        try:
            c = jax.jit(fn).lower(*args).compile().cost_analysis()
            if isinstance(c, (list, tuple)):
                c = c[0] if c else {}
            return {
                "phase": name,
                "gflops": round(float(c.get("flops", 0.0)) / 1e9, 3),
                "mbytes": round(
                    float(c.get("bytes accessed", 0.0)) / 1e6, 2
                ),
            }
        except Exception as e:  # cost analysis is best-effort per backend
            log(f"cost analysis for {name} failed: {e!r}")
            return {"phase": name, "gflops": None, "mbytes": None}

    now = jnp.int32(WINDOW)
    oldest = jnp.int32(0)
    H = G.history_conflicts(state, batch)
    commit = G.intra_batch_commits(batch, H)
    phases = [
        costed("history_conflicts", G.history_conflicts, state, batch),
        costed("intra_batch_commits", G.intra_batch_commits, batch, H),
        costed(
            "merge_writes", G.merge_writes, state, batch, commit, now, oldest
        ),
        costed(
            "resolve_batch (end-to-end)",
            lambda st, b: G._resolve_one(st, b, now, oldest, oldest),
            state,
            batch,
        ),
    ]

    # a short measured CPU run anchors the counts to an actual execution
    work = [(enc[i], i + WINDOW, i) for i in range(min(GROUP, batches_n))]
    tpu.detect_many_encoded(work)  # compile
    tpu2 = TpuConflictSet(key_width=kw, capacity=cap)
    work2 = [(tpu2.encode(txs), i + WINDOW, i) for i, txs in enumerate(
        batches[: min(GROUP, batches_n)]
    )]
    t0 = time.time()
    tpu2.detect_many_encoded(work2)
    cpu_batch_ms = (time.time() - t0) * 1000 / len(work2)

    # device-time prediction from the bandwidth model: the grid phases are
    # HBM-bound dense passes (grid.py module doc), so bytes/bandwidth is
    # the floor a healthy-tunnel capture should approach
    HBM_GBS = float(os.environ.get("BENCH_HBM_GBS", "819"))  # v5e spec
    total_mb = sum(p["mbytes"] or 0.0 for p in phases[:3])
    pred_ms = total_mb / (HBM_GBS * 1e3) * 1e3  # MB / (GB/s)

    lines = [
        _EVIDENCE_BEGIN,
        "## Degraded-evidence capture (CPU backend; tunnel unreachable)",
        "",
        f"Captured {time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} on "
        f"the CPU JAX backend (jax {jax.__version__}); shape "
        f"{batches_n}x{txns_n} txns (the documented smoke shape), grid "
        f"B={B} S={S} lanes={lp1 - 1}, key_width={kw}, capacity={cap}, "
        f"GROUP={GROUP}, WINDOW={WINDOW}.",
        "",
        "Per-phase XLA cost analysis (one batch through the jitted phase):",
        "",
        "| phase | GFLOPs | MB accessed |",
        "|---|---|---|",
    ]
    for p in phases:
        lines.append(
            f"| {p['phase']} | {p['gflops']} | {p['mbytes']} |"
        )
    lines += [
        "",
        f"Measured CPU execution: {cpu_batch_ms:.1f} ms/batch "
        f"(group of {len(work2)} via resolve_many).",
        f"Bandwidth-model device prediction: {total_mb:.1f} MB/batch over "
        f"{HBM_GBS:.0f} GB/s HBM ≈ **{pred_ms:.2f} ms/batch** in-scan "
        f"(compare scratch/profile_donate.py's ~4.6 ms at the full "
        f"200x2500 shape; phases are HBM-bound, so scale with MB/batch).",
        _EVIDENCE_END,
    ]
    section = "\n".join(lines) + "\n"
    notes_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BENCH_NOTES.md")
    try:
        with open(notes_path) as f:
            text = f.read()
    except OSError:
        text = ""
    if _EVIDENCE_BEGIN in text and _EVIDENCE_END in text:
        pre = text.split(_EVIDENCE_BEGIN)[0]
        post = text.split(_EVIDENCE_END, 1)[1].lstrip("\n")
        text = pre + section + post
    else:
        text = text.rstrip("\n") + "\n\n" + section
    with open(notes_path, "w") as f:
        f.write(text)
    log(f"degraded evidence appended to {notes_path}")
    print(
        json.dumps(
            {
                "metric": "degraded_evidence",
                "value": round(pred_ms, 3),
                "unit": "predicted_ms_per_batch",
                "cpu_ms_per_batch": round(cpu_batch_ms, 1),
                "phases": phases,
                "kernel": tpu2.metrics.snapshot(),
            },
            default=str,
        )
    )


def probe_device(max_tries=3):
    """Probe JAX backend init in a SUBPROCESS with a hard timeout: a hung
    TPU tunnel must not hang the bench (round-3 failure mode — the capture
    died inside backend init with zero output). Returns the platform name
    or None after retries with backoff."""
    import subprocess

    # When pinned to CPU, drop the axon TPU plugin's backend factory first:
    # xla_bridge initializes every REGISTERED platform regardless of
    # JAX_PLATFORMS, and a wedged tunnel then hangs even a CPU probe
    # (same workaround as tests/conftest.py).
    child = (
        "import os\n"
        "import jax\n"
        "if os.environ.get('JAX_PLATFORMS', '').startswith('cpu'):\n"
        "    import jax._src.xla_bridge as xb\n"
        "    xb._backend_factories.pop('axon', None)\n"
        "    jax.config.update('jax_platforms', 'cpu')\n"
        "print(jax.devices()[0].platform)\n"
    )
    for attempt in range(max_tries):
        try:
            r = subprocess.run(
                [sys.executable, "-c", child],
                capture_output=True,
                text=True,
                timeout=120,
                env=dict(os.environ),
            )
            if r.returncode == 0 and r.stdout.strip():
                return r.stdout.strip()
            log(f"device probe attempt {attempt+1}: rc={r.returncode} "
                f"{(r.stderr or '').strip()[-200:]}")
        except subprocess.TimeoutExpired:
            log(f"device probe attempt {attempt+1}: timed out (tunnel hang)")
        time.sleep(5 * (attempt + 1))
    return None


def main():
    global BATCHES, TXNS
    if os.environ.get("BENCH_COMPONENT") == "range_index":
        bench_range_index()
        return
    if os.environ.get("BENCH_COMPONENT") == "e2e":
        bench_e2e()
        return
    if os.environ.get("BENCH_COMPONENT") == "degraded_evidence":
        bench_degraded_evidence()
        return
    if os.environ.get("BENCH_COMPONENT") == "resolver_pipeline":
        bench_resolver_pipeline()
        return
    if os.environ.get("BENCH_COMPONENT") == "read_pipeline":
        bench_read_pipeline()
        return
    if os.environ.get("BENCH_COMPONENT") == "transport":
        bench_transport()
        return
    if os.environ.get("BENCH_COMPONENT") == "admission":
        bench_admission()
        return
    if os.environ.get("BENCH_COMPONENT") == "storage_engine":
        bench_storage_engine()
        return
    if os.environ.get("BENCH_COMPONENT") == "prefilter":
        bench_prefilter()
        return
    if os.environ.get("BENCH_COMPONENT") == "commit_path":
        bench_commit_path()
        return
    from foundationdb_tpu.conflict.native import NativeConflictSet

    # the device phase is gated on a probe; size the workload to what we
    # actually run on (the full 200x2500 shape compiles+runs for minutes
    # on a 1-core CPU host — fine on the chip, useless as a CI smoke)
    platform = probe_device()
    on_chip = platform in ("tpu", "axon")
    if (
        not on_chip
        and "BENCH_BATCHES" not in os.environ
        and "BENCH_TXNS" not in os.environ
    ):
        BATCHES, TXNS = 40, 640
        log(f"platform={platform}: shrinking to {BATCHES}x{TXNS} smoke shape")
    if platform == "cpu":
        # mirror the probe's gate in this process before any jax use
        import jax
        import jax._src.xla_bridge as xb

        xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")

    log(f"generating {BATCHES} batches x {TXNS} txns over {KEYSPACE} keys")
    batches = make_batches(BATCHES, TXNS)

    # ---- native CPU baseline (the versioned skip list) ----
    nat = NativeConflictSet()
    nat_enc = [nat.encode_batch(txs) for txs in batches]
    t0 = time.time()
    nat_verdicts = []
    for i, enc in enumerate(nat_enc):
        nat_verdicts.append(nat.resolve_encoded(enc, i + WINDOW, i))
    nat_dt = time.time() - t0
    nat_tps = BATCHES * TXNS / nat_dt
    aborts = sum(int((v != 0).sum()) for v in nat_verdicts)
    log(
        f"native skiplist: {nat_dt:.2f}s, {nat_tps/1e6:.3f} Mtxn/s, "
        f"abort rate {aborts/(BATCHES*TXNS):.4f}, "
        f"boundaries {nat.boundary_count}"
    )

    # 200x2500 is the DEFAULT cross-round comparison shape (ROADMAP
    # standing guidance: the 40x640 smoke baseline drifts ±18% run to
    # run, so a vs_baseline quoted from it doesn't compare across
    # rounds). When the device phase ran a different (shrunk) shape,
    # still put the full-shape native denominator on record — ~25s on
    # this host — so the round's numbers can be compared honestly.
    nat_tps_full = None
    if f"{BATCHES}x{TXNS}" == "200x2500":
        nat_tps_full = nat_tps
    elif os.environ.get("BENCH_SKIP_FULL_NATIVE") != "1":
        log("computing 200x2500 native reference baseline (comparison shape)")
        full = make_batches(200, 2500)
        natf = NativeConflictSet()
        enc_f = [natf.encode_batch(txs) for txs in full]
        t0 = time.time()
        for i, enc in enumerate(enc_f):
            natf.resolve_encoded(enc, i + WINDOW, i)
        nat_tps_full = 200 * 2500 / (time.time() - t0)
        log(f"native 200x2500 reference: {nat_tps_full/1e6:.3f} Mtxn/s")
        del full, enc_f, natf

    # STAGED OUTPUT: the native baseline is on record BEFORE any device
    # work — a device failure below can no longer erase the whole run
    # (the driver keeps the last JSON line; this one stands until the
    # device phase replaces it)
    print(
        json.dumps(
            {
                "metric": "resolver_conflict_check_throughput",
                "value": 0.0,
                "unit": "txn/s",
                "vs_baseline": 0.0,
                "stage": "native_baseline_only",
                "native_txn_s": round(nat_tps, 1),
                "native_txn_s_200x2500": (
                    round(nat_tps_full, 1) if nat_tps_full else None
                ),
                "shape": f"{BATCHES}x{TXNS}",
                "device": platform,
            }
        ),
        flush=True,
    )
    if platform is None:
        log("no usable JAX backend after retries; native baseline stands")
        # tunnel unreachable: leave derived per-phase evidence on record
        # (CPU grid kernel + XLA cost analysis -> BENCH_NOTES.md) so the
        # round's device-time expectations are reviewable, not asserted
        try:
            bench_degraded_evidence()
        except Exception as e:
            log(f"degraded-evidence capture failed: {e!r}")
        return

    try:
        _device_phase(batches, nat_tps, nat_verdicts, nat_tps_full)
    except Exception as e:  # staged line above remains the result
        log(f"device phase failed: {e!r}")


def _device_phase(batches, nat_tps, nat_verdicts, nat_tps_full=None):
    from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet

    # ---- TPU kernel (bucket-grid, conflict/grid.py) ----
    # key_width=12 keeps bench keys (8 B) exact with 3 uint32 lanes (the
    # code's last byte is a length byte, so width w is exact only for
    # keys <= w-1 bytes) — an operator tuning knob, like the reference's
    # key-size assumptions in its own skiplist microbench
    # (SkipList.cpp:1412).
    kw = int(os.environ.get("BENCH_KEY_WIDTH", "12"))
    cap = 1 << 17
    while cap < 4 * TXNS * WINDOW:
        cap <<= 1
    tpu = TpuConflictSet(key_width=kw, capacity=cap)
    tpu_enc = [tpu.encode(txs) for txs in batches]

    # warmup/compile on a copy of the first group; also pre-compile the
    # on-device rebalance so a mid-run reshard costs ms, not a compile
    warm = TpuConflictSet(key_width=kw, capacity=cap)
    warm_enc = [warm.encode(txs) for txs in batches[:GROUP]]
    t0 = time.time()
    warm.detect_many_encoded(
        [(e, i + WINDOW, i) for i, e in enumerate(warm_enc)]
    )
    warm._reshard(warm._state)
    # index construction for the real run: seed pivots from the encoded
    # key sample BEFORE the timed region (the reference's skiplisttest
    # also builds its index from presorted data outside "Detect only",
    # SkipList.cpp:1429-1464)
    tpu._reshard(tpu._state)
    log(f"compile+warmup: {time.time()-t0:.1f}s")

    # bounded-depth pipelining: keep a few groups in flight (the tunnel
    # round trip overlaps device compute of later groups) while collecting
    # as we go, so the backend can slip a cheap rebalance between groups
    # instead of paying an overflow replay of the whole pipeline
    DEPTH = 3
    t0 = time.time()
    handles = []
    tpu_verdicts = []
    for g in range(0, BATCHES, GROUP):
        if len(handles) >= DEPTH:
            tpu_verdicts.extend(handles.pop(0)())
        work = [
            (tpu_enc[i], i + WINDOW, i) for i in range(g, min(g + GROUP, BATCHES))
        ]
        handles.append(tpu.detect_many_encoded_async(work))
    for h in handles:
        tpu_verdicts.extend(h())
    tpu_dt = time.time() - t0
    tpu_tps = BATCHES * TXNS / tpu_dt
    t_aborts = sum(sum(1 for v in vs if v != 0) for vs in tpu_verdicts)
    log(
        f"tpu kernel: {tpu_dt:.2f}s, {tpu_tps/1e6:.3f} Mtxn/s, "
        f"abort rate {t_aborts/(BATCHES*TXNS):.4f}"
    )

    # ---- verdict parity (identical abort decisions) ----
    mismatch = 0
    for i in range(BATCHES):
        nv = nat_verdicts[i]
        tv = tpu_verdicts[i]
        for t in range(TXNS):
            if int(nv[t]) != int(tv[t]):
                mismatch += 1
    if mismatch:
        log(f"WARNING: {mismatch} verdict mismatches vs native baseline")
    else:
        log("verdict parity: all batches identical to native baseline")

    print(
        json.dumps(
            {
                "metric": "resolver_conflict_check_throughput",
                "value": round(tpu_tps, 1),
                "unit": "txn/s",
                "vs_baseline": round(tpu_tps / nat_tps, 3),
                # the ratio's denominator on its face (ROADMAP standing
                # guidance: the native smoke-shape baseline swings ±18%,
                # so a vs_baseline without its native_txn_s is ambiguous)
                # and the workload shape, pinned to 200x2500 on-chip for
                # cross-round comparisons; off-chip runs carry the
                # 200x2500 native reference alongside the same-shape one
                "native_txn_s": round(nat_tps, 1),
                "native_txn_s_200x2500": (
                    round(nat_tps_full, 1) if nat_tps_full else None
                ),
                "shape": f"{BATCHES}x{TXNS}",
                # kernel counter snapshot: occupancy / overflow replays /
                # transfer bytes ride every capture, so a number whose run
                # hit reshard churn carries that provenance on its face
                "kernel": tpu.metrics.snapshot(),
            },
            default=str,
        )
    )


if __name__ == "__main__":
    main()
