"""Where do the bench's 21ms/batch go? Instrument host-side phases."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time
import numpy as np
import jax

from foundationdb_tpu.conflict import grid as G
from foundationdb_tpu.conflict import tpu_backend as TB
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
import bench as B

BATCHES = 200
TXNS = 2500
WINDOW = 50
GROUP = 20

batches = B.make_batches(BATCHES, TXNS)
cap = 1 << 19
tpu = TpuConflictSet(key_width=12, capacity=cap)
t0 = time.time()
encs = [tpu.encode(txs) for txs in batches]
print(f"encode: {(time.time()-t0)/BATCHES*1000:.2f} ms/batch")

# count reshards
orig_reshard = tpu._reshard
reshard_calls = []
def counting_reshard(*a, **k):
    t0 = time.time()
    orig_reshard(*a, **k)
    reshard_calls.append((time.time() - t0, k.get('grow', a[1] if len(a)>1 else False), tpu._B))
tpu._reshard = counting_reshard

# instrument _stack and _dispatch
orig_stack = tpu._stack
stack_time = [0.0]
def timed_stack(bs):
    t0 = time.time()
    r = orig_stack(bs)
    stack_time[0] += time.time() - t0
    return r
tpu._stack = timed_stack

orig_dispatch = tpu._dispatch
disp_time = [0.0]
def timed_dispatch(g):
    t0 = time.time()
    orig_dispatch(g)
    disp_time[0] += time.time() - t0
tpu._dispatch = timed_dispatch

# warmup
warm = [(encs[i], i + WINDOW, i) for i in range(GROUP)]
t0 = time.time()
tpu.detect_many_encoded(warm)
print(f"warmup+compile: {time.time()-t0:.1f}s; reshards so far {len(reshard_calls)}")
stack_time[0] = 0.0
disp_time[0] = 0.0
n_resh0 = len(reshard_calls)

t0 = time.time()
handles = []
outs = []
coll_times = []
t_disp = 0.0
t_coll0 = time.time()
for g in range(GROUP, BATCHES, GROUP):
    if len(handles) >= 3:
        tc = time.time()
        outs.extend(handles.pop(0)())
        coll_times.append(time.time() - tc)
    td = time.time()
    work = [(encs[i], i + WINDOW, i) for i in range(g, min(g + GROUP, BATCHES))]
    handles.append(tpu.detect_many_encoded_async(work))
    t_disp += time.time() - td
for h in handles:
    tc = time.time()
    outs.extend(h())
    coll_times.append(time.time() - tc)
t_coll = time.time() - t_coll0
total = time.time() - t0
nb = BATCHES - GROUP
print(f"timed region: {total:.2f}s for {nb} batches = {total/nb*1000:.2f} ms/batch")
print(f"  dispatch loop: {t_disp:.2f}s (stack {stack_time[0]:.2f}s, device-call {disp_time[0]:.2f}s)")
print(f"  collect loop:  {t_coll:.2f}s  per-group: {[f'{c*1000:.0f}ms' for c in coll_times]}")
print(f"  reshards in timed region: {len(reshard_calls)-n_resh0}, times {[f'{r:.2f}s' for r in reshard_calls[n_resh0:]]}")
print(f"  count sum: {int(np.asarray(tpu._state.count).sum())}, B={tpu._B}")
