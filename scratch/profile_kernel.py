"""Phase-level timing of the current TPU conflict kernel at bench shapes.

Run from anywhere: python scratch/profile_kernel.py
(do NOT set PYTHONPATH — it breaks the axon TPU plugin discovery)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from foundationdb_tpu.conflict import tpu_index as TI
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
from bench import make_batches

print("devices:", jax.devices(), flush=True)

TXNS = 2500
WINDOW = 50
P = 1 << 17
L = 8
NLIVE = 131072  # steady-state boundary count from round-1 bench

# Synthetic steady-state index: NLIVE sorted random boundaries, random vers.
rng = np.random.default_rng(0)
raw = rng.integers(0, 2**32, size=(NLIVE, L), dtype=np.uint32)
raw[NLIVE - 1] = 0xFFFFFFFF
order = np.lexsort(tuple(raw[:, i] for i in reversed(range(L))))
bounds = np.full((P, L), 0xFFFFFFFF, dtype=np.uint32)
bounds[:NLIVE] = raw[order]
bounds[0] = 0
vers = np.zeros(P, np.int32)
vers[:NLIVE] = rng.integers(1, 50, size=NLIVE)
state = TI.IndexState(
    bounds=jnp.asarray(bounds),
    vers=jnp.asarray(vers),
    tree=TI.build_tree(jnp.asarray(vers)),
    n=jnp.int32(NLIVE),
)
jax.block_until_ready(state)

cs = TpuConflictSet(capacity=P)
txs = make_batches(1, TXNS)[0]
b0, num_txns = cs._encode(txs)
batch = jax.device_put(b0)
jax.block_until_ready(batch)
print("shapes: P", state.bounds.shape, "R", batch.rb.shape, "W", batch.wb.shape,
      "T", num_txns, flush=True)


def timeit(name, fn, *args, n=10):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    compile_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:32s} {dt*1e3:9.3f} ms   (compile {compile_dt:.1f}s)", flush=True)
    return out


hist = jax.jit(functools.partial(TI.history_conflicts, num_txns=num_txns))
H = timeit("history_conflicts", hist, state, batch)

intra = jax.jit(functools.partial(TI.intra_batch_commits, num_txns=num_txns))
commit = timeit("intra_batch_commits", intra, batch, H)

merge = jax.jit(TI.merge_writes)
now = jnp.int32(60)
old = jnp.int32(10)
timeit("merge_writes", merge, state, batch, commit, now, old)

bt = jax.jit(TI.build_tree)
timeit("build_tree(P)", bt, state.vers)

W_ = batch.wb.shape[0]
R_ = batch.rb.shape[0]


@jax.jit
def intra_parts(batch, H):
    T = num_txns
    W = batch.wb.shape[0]
    w_active = TI.lex_lt(batch.wb, batch.we)
    r_active = TI.lex_lt(batch.rb, batch.re)
    pts = TI._lex_sort_rows(jnp.concatenate([batch.wb, batch.we], axis=0))
    wb_g = TI._searchsorted(pts, batch.wb, "right")
    we_g = TI._searchsorted(pts, batch.we, "left")
    ra_g = TI._searchsorted(pts, batch.rb, "right")
    rb_g = TI._searchsorted(pts, batch.re, "left")
    return w_active, r_active, wb_g, we_g, ra_g, rb_g


parts = timeit("intra: sort+4 searchsorted(2W)", intra_parts, batch, H)
w_active, r_active, wb_g, we_g, ra_g, rb_g = parts


@jax.jit
def intra_cover(batch, w_active, wb_g, we_g):
    T = num_txns
    W = batch.wb.shape[0]
    diff = jnp.zeros((2 * W + 2, T), dtype=jnp.int32)
    one = jnp.where(w_active, 1, 0).astype(jnp.int32)
    diff = diff.at[wb_g, batch.w_owner].add(one, mode="drop")
    diff = diff.at[we_g + 1, batch.w_owner].add(-one, mode="drop")
    covered = jnp.cumsum(diff, axis=0)[:-1] > 0
    S = jnp.concatenate([jnp.zeros((1, T), jnp.int32),
                         jnp.cumsum(covered.astype(jnp.int32), axis=0)])
    return S


S = timeit("intra: scatter+cumsum [2W,T]", intra_cover, batch, w_active, wb_g, we_g)


@jax.jit
def intra_fix(batch, S, r_active, ra_g, rb_g, H):
    T = num_txns
    overlap = (S[rb_g + 1] - S[ra_g]) > 0
    overlap = overlap & r_active[:, None]
    Pji = jnp.zeros((T, T), dtype=bool)
    Pji = Pji.at[batch.r_owner].max(overlap, mode="drop")
    earlier = jnp.arange(T)[None, :] < jnp.arange(T)[:, None]
    Pji = Pji & earlier

    def body(val):
        commit, _ = val
        blocked = (Pji & commit[None, :]).any(axis=1)
        new = ~H & ~blocked
        return new, jnp.any(new != commit)

    commit, _ = jax.lax.while_loop(lambda v: v[1], body, (~H, jnp.array(True)))
    return commit


timeit("intra: overlap+Pji+fixpoint", intra_fix, batch, S, r_active, ra_g, rb_g, H)


@jax.jit
def hist_search(state, batch):
    lo = TI._searchsorted(state.bounds, batch.rb, "right") - 1
    hi = TI._searchsorted(state.bounds, batch.re, "left") - 1
    return lo, hi


lo, hi = timeit("hist: 2x searchsorted(P)", hist_search, state, batch)


@jax.jit
def hist_rmax(state, lo, hi):
    return TI.range_max(state.tree, jnp.maximum(lo, 0), hi)


timeit("hist: range_max", hist_rmax, state, lo, hi)


@jax.jit
def merge_scatter(state, C):
    P, L = state.bounds.shape
    W = C.shape[0] // 2
    M = P + 2 * W
    A = state.bounds
    a_j = TI._searchsorted(A, C, "right")
    posC = jnp.arange(2 * W, dtype=jnp.int32) + a_j
    hist = jnp.zeros((P + 1,), jnp.int32).at[a_j].add(1)
    posA = jnp.arange(P, dtype=jnp.int32) + jnp.cumsum(hist)[:P]
    D0 = jnp.full((M, L), TI.SENTINEL, dtype=jnp.uint32)
    D0 = D0.at[posA].set(A)
    D0 = D0.at[posC].set(C)
    return D0


C = TI._lex_sort_rows(jnp.concatenate([batch.wb, batch.we], axis=0))
D0 = timeit("merge: row-scatter into M", merge_scatter, state, C)


@jax.jit
def merge_runs(D0):
    M = D0.shape[0]
    prev_differs = jnp.concatenate([jnp.ones((1,), bool), (D0[1:] != D0[:-1]).any(axis=1)])
    run_id = jnp.cumsum(prev_differs.astype(jnp.int32)) - 1
    starts = jnp.full((M + 1,), M, jnp.int32)
    starts = starts.at[run_id].min(jnp.arange(M, dtype=jnp.int32))
    next_start = starts[run_id + 1]
    return next_start


timeit("merge: run-id pass (M)", merge_runs, D0)
