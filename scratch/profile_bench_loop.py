"""Replicate bench.py's timed TPU loop with per-stage timing."""
import random
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import numpy as np

from foundationdb_tpu.conflict.api import CommitTransaction
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet

BATCHES, TXNS, KEYSPACE, WINDOW, GROUP, DEPTH = 200, 2500, 1000000, 50, 20, 3


def make_batches(n, seed=0):
    rnd = random.Random(seed)
    out = []
    for i in range(n):
        txs = []
        for _ in range(TXNS):
            a = rnd.randrange(KEYSPACE)
            b = a + 1 + rnd.randrange(10)
            c = rnd.randrange(KEYSPACE)
            d = c + 1 + rnd.randrange(10)
            txs.append(CommitTransaction(
                read_snapshot=i,
                read_conflict_ranges=[(b"%08d" % a, b"%08d" % b)],
                write_conflict_ranges=[(b"%08d" % c, b"%08d" % d)],
            ))
        out.append(txs)
    return out


batches = make_batches(BATCHES)
cap = 1 << 17
while cap < 4 * TXNS * WINDOW:
    cap <<= 1
tpu = TpuConflictSet(key_width=12, capacity=cap)
tpu_enc = [tpu.encode(txs) for txs in batches]

warm = TpuConflictSet(key_width=12, capacity=cap)
warm_enc = [warm.encode(txs) for txs in batches[:GROUP]]
t0 = time.time()
warm.detect_many_encoded([(e, i + WINDOW, i) for i, e in enumerate(warm_enc)])
warm._reshard(warm._state)
print(f"compile+warmup: {time.time()-t0:.1f}s", flush=True)

# instrument _dispatch and _collect
orig_dispatch = tpu._dispatch
orig_collect = tpu._collect
t_dispatch = [0.0]
t_collect = [0.0]
n_redispatch = [0]

def timed_dispatch(group):
    t = time.perf_counter()
    orig_dispatch(group)
    t_dispatch[0] += time.perf_counter() - t
    n_redispatch[0] += 1

def timed_collect(group):
    t = time.perf_counter()
    r = orig_collect(group)
    t_collect[0] += time.perf_counter() - t
    return r

tpu._dispatch = timed_dispatch
tpu._collect = timed_collect

t0 = time.time()
handles = []
n_done = 0
for g in range(0, BATCHES, GROUP):
    if len(handles) >= DEPTH:
        vs = handles.pop(0)()
        n_done += len(vs)
    work = [(tpu_enc[i], i + WINDOW, i) for i in range(g, min(g + GROUP, BATCHES))]
    handles.append(tpu.detect_many_encoded_async(work))
for h in handles:
    n_done += len(h())
dt = time.time() - t0
print(f"total: {dt:.2f}s = {dt/BATCHES*1000:.2f} ms/batch, {BATCHES*TXNS/dt/1e6:.3f} Mtxn/s")
print(f"dispatch calls {n_redispatch[0]} time {t_dispatch[0]:.2f}s")
print(f"collect time (incl. device wait) {t_collect[0]:.2f}s")
