"""Verify drive: bulk load → tracker splits → clear → merge, with the
durability oracle live and a kill in the middle; fuzz workloads riding."""
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.runtime.knobs import Knobs
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster
from foundationdb_tpu.server.interfaces import GetKeyServersRequest, Tokens
from foundationdb_tpu.workloads import ApiCorrectnessWorkload, run_workloads
from foundationdb_tpu.workloads.quiet import quiet_database

knobs = Knobs(
    DD_SHARD_MAX_BYTES=4096, DD_SHARD_MIN_BYTES=2048, DD_TRACKER_INTERVAL=0.5
)
sim = Sim(seed=99, knobs=knobs)
sim.activate()
cluster = DynamicCluster(
    sim,
    ClusterConfig(n_storage=2, replication=2, n_tlogs=2, tlog_replication=2),
    n_coordinators=3,
)
db = Database.from_coordinators(sim, cluster.coordinators)


async def walk():
    out, key = [], b""
    while True:
        r = await db._proxy_request(
            Tokens.GET_KEY_SERVERS, GetKeyServersRequest(key=key)
        )
        out.append((r.begin, r.end))
        if r.end is None:
            return out
        key = r.end


async def body():
    for batch in range(20):

        async def w(tr, batch=batch):
            for i in range(10):
                tr.set(b"bulk/%03d/%02d" % (batch, i), b"x" * 200)

        await db.run(w)
    for _ in range(40):
        await delay(1.0)
        if len(await walk()) >= 4:
            break
    n_split = len(await walk())
    assert n_split >= 4, n_split
    print("split into", n_split, "shards", flush=True)

    # kill the master mid-life; oracle checks recovery end version
    for addr, p in list(sim.processes.items()):
        w = getattr(p, "worker", None)
        if w and p.alive and any(h.kind == "master" for h in w.roles.values()):
            sim.kill_process(addr)
            break

    # fuzz battery still verifies across the recovery
    await run_workloads(
        [ApiCorrectnessWorkload(db, sim.loop.random.fork(), transactions=10)]
    )
    print("fuzz after recovery OK", flush=True)

    async def clr(tr):
        tr.clear_range(b"bulk/", b"bulk0")

    await db.run(clr)
    for _ in range(90):
        await delay(1.0)
        if len(await walk()) <= n_split - 2:
            break
    n_merged = len(await walk())
    assert n_merged <= n_split - 2, (n_split, n_merged)
    print("merged back to", n_merged, "shards", flush=True)

    await quiet_database(db)
    assert not sim.validation.violations
    print(
        "oracle: max acked", sim.validation.max_acked, "no violations",
        flush=True,
    )
    return True


print(sim.run_until_done(spawn(body()), 900.0))
