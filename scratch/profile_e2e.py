"""End-to-end timing of resolve_batch / resolve_many at bench shapes.

Run: python scratch/profile_e2e.py   (no PYTHONPATH — breaks axon discovery)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time

import jax
import jax.numpy as jnp
import numpy as np

from foundationdb_tpu.conflict import tpu_index as TI
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
from bench import make_batches

print("devices:", jax.devices(), flush=True)

TXNS = 2500
P = 1 << 17
L = 8
NLIVE = 131072
G = 20

rng = np.random.default_rng(0)
raw = rng.integers(0, 2**32, size=(NLIVE, L), dtype=np.uint32)
raw[NLIVE - 1] = 0xFFFFFFFF
order = np.lexsort(tuple(raw[:, i] for i in reversed(range(L))))
bounds = np.full((P, L), 0xFFFFFFFF, dtype=np.uint32)
bounds[:NLIVE] = raw[order]
bounds[0] = 0
vers = np.zeros(P, np.int32)
vers[:NLIVE] = rng.integers(1, 50, size=NLIVE)


def fresh_state():
    return TI.IndexState(
        bounds=jnp.asarray(bounds),
        vers=jnp.asarray(vers),
        tree=TI.build_tree(jnp.asarray(vers)),
        n=jnp.int32(NLIVE),
    )


cs = TpuConflictSet(capacity=P)
batches = make_batches(G, TXNS)
encs = [cs._encode(txs)[0] for txs in batches]
num_txns = cs._encode(batches[0])[1]

# raw dispatch overhead
@jax.jit
def null_fn(x):
    return x + 1


x = jnp.zeros((8,), jnp.int32)
jax.block_until_ready(null_fn(x))
t0 = time.perf_counter()
for _ in range(20):
    x = null_fn(x)
jax.block_until_ready(x)
print(f"null dispatch:       {(time.perf_counter()-t0)/20*1e3:8.2f} ms", flush=True)

# host->device transfer of one encoded batch
t0 = time.perf_counter()
for i in range(10):
    b = jax.device_put(encs[i % G])
    jax.block_until_ready(b)
print(f"batch h2d transfer:  {(time.perf_counter()-t0)/10*1e3:8.2f} ms", flush=True)

# single resolve_batch, state threading (donated)
state = fresh_state()
jax.block_until_ready(state)
now = jnp.int32(60)
t0 = time.perf_counter()
state, verdicts, needed = TI.resolve_batch(
    state, jax.device_put(encs[0]), now, jnp.int32(1), jnp.int32(5), num_txns
)
jax.block_until_ready(verdicts)
print(f"resolve_batch compile: {time.perf_counter()-t0:.1f}s", flush=True)
t0 = time.perf_counter()
N = 10
for i in range(N):
    state, verdicts, needed = TI.resolve_batch(
        state, jax.device_put(encs[(i + 1) % G]), now + i,
        jnp.int32(1 + i), jnp.int32(5 + i), num_txns
    )
jax.block_until_ready(verdicts)
print(f"resolve_batch:       {(time.perf_counter()-t0)/N*1e3:8.2f} ms/batch", flush=True)

# resolve_many over G batches
cs2 = TpuConflictSet(capacity=P)
cs2._state = fresh_state()
cs2._n_bound = NLIVE
work_enc = [cs2.encode(txs) for txs in batches]
t0 = time.perf_counter()
out = cs2.detect_many_encoded([(e, 60 + i, 10 + i) for i, e in enumerate(work_enc)])
print(f"resolve_many compile+run: {time.perf_counter()-t0:.1f}s", flush=True)
cs3 = TpuConflictSet(capacity=P)
cs3._state = fresh_state()
cs3._n_bound = NLIVE
t0 = time.perf_counter()
out = cs3.detect_many_encoded([(e, 60 + i, 10 + i) for i, e in enumerate(work_enc)])
dt = time.perf_counter() - t0
print(f"resolve_many:        {dt/G*1e3:8.2f} ms/batch ({dt:.2f}s for {G})", flush=True)
