"""Per-call accounting of the production loop's host overhead."""
import random
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import numpy as np

from foundationdb_tpu.conflict import grid as G
from foundationdb_tpu.conflict.api import CommitTransaction
from foundationdb_tpu.conflict import tpu_backend as TB

BATCHES, TXNS, KEYSPACE, WINDOW, GROUP, DEPTH = 200, 2500, 1000000, 50, 40, 3

T = {}


def acc(name, dt):
    T[name] = T.get(name, 0.0) + dt


def make_batches(n, seed=0):
    rnd = random.Random(seed)
    out = []
    for i in range(n):
        txs = []
        for _ in range(TXNS):
            a = rnd.randrange(KEYSPACE)
            b = a + 1 + rnd.randrange(10)
            c = rnd.randrange(KEYSPACE)
            d = c + 1 + rnd.randrange(10)
            txs.append(CommitTransaction(read_snapshot=i,
                read_conflict_ranges=[(b"%08d" % a, b"%08d" % b)],
                write_conflict_ranges=[(b"%08d" % c, b"%08d" % d)]))
        out.append(txs)
    return out


batches = make_batches(BATCHES)
cap = 1 << 17
while cap < 4 * TXNS * WINDOW:
    cap <<= 1
tpu = TB.TpuConflictSet(key_width=12, capacity=cap)
enc = [tpu.encode(txs) for txs in batches]
warm = TB.TpuConflictSet(key_width=12, capacity=cap)
warm_enc = [warm.encode(txs) for txs in batches[:GROUP]]
warm.detect_many_encoded([(e, i + WINDOW, i) for i, e in enumerate(warm_enc)])
warm._reshard(warm._state)
print("warm done", flush=True)

orig_stack = tpu._stack
def stack_timed(b):
    t = time.perf_counter(); r = orig_stack(b); acc("stack+device_put", time.perf_counter() - t); return r
tpu._stack = stack_timed

orig_resolve_many = G.resolve_many
def rm_timed(*a, **k):
    t = time.perf_counter(); r = orig_resolve_many(*a, **k); acc("resolve_many call", time.perf_counter() - t); return r
G.resolve_many = rm_timed

orig_tm = jax.tree_util.tree_map
def _snap_copy(state):
    t = time.perf_counter()
    r = orig_tm(lambda x: x + 0, state)
    acc("snapshot copy", time.perf_counter() - t)
    return r

orig_dispatch = TB.TpuConflictSet._dispatch
def dispatch_timed(self, group):
    t = time.perf_counter()
    metas = group["metas"]
    nows = np.asarray([m[0] - self._base for m in metas], np.int32)
    olds_pre = np.asarray([max(m[1] - self._base, 0) for m in metas], np.int32)
    olds_post = np.asarray([max(m[2] - self._base, 0) for m in metas], np.int32)
    group["snapshot"] = _snap_copy(self._state)
    state, verdicts, pressure = G.resolve_many(self._state, group["stacked"], nows, olds_pre, olds_post)
    self._state = state
    group["verdicts"] = verdicts
    group["pressure"] = pressure
    t2 = time.perf_counter()
    for a in (verdicts, pressure):
        ca = getattr(a, "copy_to_host_async", None)
        if ca is not None:
            ca()
    acc("copy_to_host_async", time.perf_counter() - t2)
    acc("dispatch total", time.perf_counter() - t)
tpu._dispatch = dispatch_timed.__get__(tpu)

orig_get = jax.device_get
def get_timed(x):
    t = time.perf_counter(); r = orig_get(x); acc("device_get", time.perf_counter() - t); return r
jax.device_get = get_timed

t0 = time.time()
handles = []
n = 0
for g in range(0, BATCHES, GROUP):
    if len(handles) >= DEPTH:
        n += len(handles.pop(0)())
    work = [(enc[i], i + WINDOW, i) for i in range(g, min(g + GROUP, BATCHES))]
    t = time.perf_counter()
    handles.append(tpu.detect_many_encoded_async(work))
    acc("async dispatch wrapper", time.perf_counter() - t)
for h in handles:
    t = time.perf_counter()
    n += len(h())
    acc("collect wrapper", time.perf_counter() - t)
dt = time.time() - t0
print(f"total {dt:.2f}s = {dt/BATCHES*1000:.2f} ms/batch, {BATCHES*TXNS/dt/1e6:.3f} Mtxn/s")
for k, v in sorted(T.items(), key=lambda kv: -kv[1]):
    print(f"  {k:24s} {v:.3f}s")
