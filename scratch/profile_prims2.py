"""Primitive costs for the bucket-grid conflict-index design.

Run: python scratch/profile_prims2.py  (no PYTHONPATH)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

print("devices:", jax.devices(), flush=True)
rng = np.random.default_rng(0)


def timeit(name, fn, *args, n=10):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    c = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:46s} {dt*1e3:9.3f} ms  (compile {c:.1f}s)", flush=True)
    return out


L = 3
B = 2048  # buckets
S = 96    # slots per bucket
Q = 8192  # query endpoints per batch
W = 8192  # write endpoints per batch
T = 2560  # txns

buckets = jnp.asarray(rng.integers(0, 2**31, (B, S, L + 1), dtype=np.int32))
qb = jnp.asarray(rng.integers(0, B, (Q,), dtype=np.int32))
q3 = jnp.asarray(rng.integers(0, 2**31, (Q, L), dtype=np.int32))
pivots = jnp.asarray(np.sort(rng.integers(0, 2**31, (B,), dtype=np.int32)))
pivots3 = jnp.asarray(rng.integers(0, 2**31, (B, L), dtype=np.int32))


# 1. block gather: per query, one bucket's [S, L+1] block
@jax.jit
def block_gather(buckets, qb):
    return buckets[qb]  # [Q, S, L+1]


g = timeit(f"block gather [{Q},{S},{L+1}] buckets", block_gather, buckets, qb)


# 2. two-level dense rank: 64 super + 64 within (here flat B for simplicity)
@jax.jit
def dense_rank_flat(q3, pivots3):
    # lex q >= pivot, counted — [Q, B] compares, L lanes
    ge = jnp.zeros((Q, B), bool)
    eq = jnp.ones((Q, B), bool)
    for i in range(L):
        qi = q3[:, None, i]
        pi = pivots3[None, :, i]
        ge = ge | (eq & (qi > pi))
        eq = eq & (qi == pi)
    return (ge | eq).sum(axis=1, dtype=jnp.int32)


timeit(f"dense lex rank [{Q}x{B}] flat", dense_rank_flat, q3, pivots3)


@jax.jit
def dense_rank_2level(q3, pivots3):
    sup = pivots3[:: B // 64]  # [64, L]
    def rank_vs(qv, pv):
        ge = jnp.zeros(qv.shape[:1] + pv.shape[:1], bool)
        eq = jnp.ones_like(ge)
        for i in range(L):
            qi = qv[:, None, i]
            pi = pv[None, :, i]
            ge = ge | (eq & (qi > pi))
            eq = eq & (qi == pi)
        return (ge | eq).sum(axis=1, dtype=jnp.int32)

    hi = jnp.maximum(rank_vs(q3, sup) - 1, 0)  # [Q] super bucket
    sub = pivots3.reshape(64, B // 64, L)[hi]  # [Q, 32, L] block gather
    ge = jnp.zeros((Q, B // 64), bool)
    eq = jnp.ones_like(ge)
    for i in range(L):
        qi = q3[:, None, i]
        pi = sub[:, :, i]
        ge = ge | (eq & (qi > pi))
        eq = eq & (qi == pi)
    lo = (ge | eq).sum(axis=1, dtype=jnp.int32)
    return hi * (B // 64) + jnp.maximum(lo - 1, 0)


timeit("dense lex rank 2-level (64+32)", dense_rank_2level, q3, pivots3)


# 3. masked range-max over gathered windows [Q, S]
@jax.jit
def window_max(g, q3):
    bounds = g[..., :L]  # [Q, S, L]
    vers = g[..., L]
    a = q3[:, None, :]
    gt = jnp.zeros((Q, S), bool)
    eq = jnp.ones((Q, S), bool)
    for i in range(L):
        bi = bounds[:, :, i]
        ai = a[:, :, i]
        gt = gt | (eq & (bi > ai))
        eq = eq & (bi == ai)
    mask = gt
    return jnp.max(jnp.where(mask, vers, 0), axis=1)


timeit(f"masked window max [{Q}x{S}]", window_max, g, q3)


# 4. bucket-interval dense max: [Q, B] mask of buckets strictly between
bmax = jnp.asarray(rng.integers(0, 50, (B,), dtype=np.int32))
lo_b = jnp.asarray(rng.integers(0, B - 1, (Q,), dtype=np.int32))
hi_b = jnp.asarray(np.minimum(rng.integers(0, B, (Q,)), B - 1).astype(np.int32))


@jax.jit
def bucket_between_max(bmax, lo_b, hi_b):
    ar = jnp.arange(B, dtype=jnp.int32)[None, :]
    mask = (ar > lo_b[:, None]) & (ar < hi_b[:, None])
    return jnp.max(jnp.where(mask, bmax[None, :], 0), axis=1)


timeit(f"bucket between-max [{Q}x{B}]", bucket_between_max, bmax, lo_b, hi_b)


# 5. per-bucket vmapped bitonic sort: [B, S+D, L+1] rows, sort by 3 lanes
D = 32
staged = jnp.asarray(
    rng.integers(0, 2**31, (B, S + D, L + 1), dtype=np.int32)
)


@jax.jit
def bucket_sort(staged):
    cols = tuple(staged[..., i] for i in range(L + 1))
    out = jax.lax.sort(cols, dimension=1, num_keys=L)
    return jnp.stack(out, axis=-1)


timeit(f"per-bucket sort [{B},{S+D},{L+1}] dim=1", bucket_sort, staged)


# 6. scatter 8K rows into [B, S+D] staging at computed (bucket, slot)
wrows = jnp.asarray(rng.integers(0, 2**31, (W, L + 1), dtype=np.int32))
wbkt = jnp.asarray(rng.integers(0, B, (W,), dtype=np.int32))
wslot = jnp.asarray(rng.integers(0, D, (W,), dtype=np.int32))


@jax.jit
def scatter_stage(wrows, wbkt, wslot):
    st = jnp.zeros((B, D, L + 1), jnp.int32)
    return st.at[wbkt, wslot].set(wrows, mode="drop")


timeit(f"2D row scatter {W} into [{B},{D}]", scatter_stage, wrows, wbkt, wslot)


# 6b. flat 1D row scatter equivalent
@jax.jit
def scatter_flat(wrows, wbkt, wslot):
    st = jnp.zeros((B * D, L + 1), jnp.int32)
    return st.at[wbkt * D + wslot].set(wrows, mode="drop")


timeit(f"flat row scatter {W} into [{B*D}]", scatter_flat, wrows, wbkt, wslot)


# 7. global bitonic of batch endpoints [8192, 5 cols]
cols = [jnp.asarray(rng.integers(0, 2**31, (W,), dtype=np.int32)) for _ in range(5)]


@jax.jit
def sort_batch(*cols):
    return jax.lax.sort(cols, num_keys=4)


timeit("sort 8192 x 5cols (4 keys)", sort_batch, *cols)


# 8. dense padded overlap [T,1] vs [T,1] -> Pji + MXU fixpoint
ra = jnp.asarray(rng.integers(0, 2**31, (T, L), dtype=np.int32))
rb = ra + 10
wa = jnp.asarray(rng.integers(0, 2**31, (T, L), dtype=np.int32))
wb = wa + 10
H = jnp.asarray(rng.random(T) < 0.3)


@jax.jit
def intra_dense(ra, rb, wa, wb, H):
    def lex_lt(x, y):  # [T,1,L] vs [1,T,L] -> [T,T]
        lt = jnp.zeros((T, T), bool)
        eq = jnp.ones((T, T), bool)
        for i in range(L):
            xi = x[:, None, i]
            yi = y[None, :, i]
            lt = lt | (eq & (xi < yi))
            eq = eq & (xi == yi)
        return lt

    Pji = lex_lt(ra, wb) & lex_lt(wa, rb)  # read j overlaps write i
    earlier = jnp.arange(T)[None, :] < jnp.arange(T)[:, None]
    Pf = (Pji & earlier).astype(jnp.bfloat16)

    def body(val):
        commit, _ = val
        blocked = (Pf @ commit.astype(jnp.bfloat16)) > 0
        new = ~H & ~blocked
        return new, jnp.any(new != commit)

    commit, _ = jax.lax.while_loop(lambda v: v[1], body, (~H, jnp.array(True)))
    return commit


timeit(f"intra dense overlap+MXU fixpoint [T={T}]", intra_dense, ra, rb, wa, wb, H)


# 9. segment positions: per-bucket slot of sorted writes (run-position)
sb = jnp.sort(wbkt)


@jax.jit
def run_pos(sb):
    idx = jnp.arange(W, dtype=jnp.int32)
    is_start = jnp.concatenate([jnp.ones(1, bool), sb[1:] != sb[:-1]])
    start_idx = jnp.where(is_start, idx, 0)
    return idx - jax.lax.cummax(start_idx)


timeit("run positions (cummax) [8192]", run_pos, sb)

# 10. full-image dense passes over the grid [B, S] (version GC etc.)
vers_grid = jnp.asarray(rng.integers(0, 50, (B, S + D), dtype=np.int32))


@jax.jit
def grid_pass(v):
    v = jnp.where(v < 10, 0, v)
    return jax.lax.cummax(v, axis=1)


timeit(f"grid cummax pass [{B},{S+D}]", grid_pass, vers_grid)
