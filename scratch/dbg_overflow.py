import faulthandler
import sys
import time

sys.path.insert(0, "/root/repo")
faulthandler.dump_traceback_later(150, exit=True)

import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax._src.xla_bridge as xb
xb._backend_factories.pop("axon", None)

import random

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from foundationdb_tpu.conflict import grid as G
from foundationdb_tpu.conflict import sharded
from foundationdb_tpu.conflict.api import Verdict
from foundationdb_tpu.conflict.oracle import OracleConflictSet

sys.path.insert(0, "/root/repo/tests")
import test_sharded_grid as tg

t0 = time.time()


def log(msg):
    print(f"[{time.time()-t0:7.1f}] {msg}", flush=True)


n_part, n_data = 2, 1
mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), axis_names=("part", "data"))
L, width = 2, 8
B, S = 4, 8
T, KR, KW = 16, 1, 1
rnd = random.Random(13)

states = sharded.make_sharded_states(n_part, B, S, L)
spec = jax.tree.map(lambda _: NamedSharding(mesh, P("part")), G.GridState(0, 0, 0, 0))
states = jax.device_put(states, spec)
step = sharded.build_sharded_resolver(mesh, lanes=L)
grown = (B, S)
log("setup done")

oracle = OracleConflictSet()
for i in range(5):
    txs = tg._make_txns(rnd, T, 120, i, span=2)
    want = oracle.detect_batch(list(txs), i + 20, max(i - 4, 0))
    batch = tg._encode_batch(txs, width, T, KR, KW)
    snapshot = jax.tree.map(lambda x: x + 0, states)
    tries = 0
    while True:
        tries += 1
        Bc, Sc = grown
        log(f"batch {i} try {tries} Bc={Bc}")
        new_states, verdicts, pressure = step(
            states, batch, np.int32(i + 20), np.int32(max(i - 4, 0)), np.int32(max(i - 4, 0))
        )
        pr = np.asarray(pressure)
        log(f"  pressure {pr.tolist()}")
        if (pr[:, 0] <= G.staging_slots(Sc)).all() and (pr[:, 1] <= Sc).all():
            states = new_states
            break
        Bc *= 2
        log("  device_get snapshot")
        host_snap = jax.tree.map(jax.device_get, snapshot)
        parts = []
        for p in range(n_part):
            shard = jax.tree.map(lambda x: x[p], host_snap)
            log(f"  reshard part {p} -> B={Bc}")
            new_shard, pres = G.reshard_device(shard, Bc, Sc)
            log(f"  reshard part {p} done pres={int(jax.device_get(pres))}")
            parts.append(jax.tree.map(np.asarray, new_shard))
        log("  stacking")
        states = jax.device_put(jax.tree.map(lambda *xs: np.stack(xs), *parts), spec)
        log("  device_put done")
        snapshot = jax.tree.map(lambda x: x + 0, states)
        grown = (Bc, Sc)
    got = [Verdict(int(v)) for v in np.asarray(verdicts)[: len(txs)]]
    assert got == want, f"batch {i}"
    log(f"batch {i} OK")
log(f"done, grown={grown}")
