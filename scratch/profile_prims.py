"""Primitive-cost measurements on the v5e to drive the kernel redesign.

Run: python scratch/profile_prims.py  (no PYTHONPATH)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time

import jax
import jax.numpy as jnp
import numpy as np

print("devices:", jax.devices(), flush=True)
rng = np.random.default_rng(0)


def timeit(name, fn, *args, n=10):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    c = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:44s} {dt*1e3:9.3f} ms  (compile {c:.1f}s)", flush=True)
    return out


L = 3
P = 131072
Q = 8192

base = jnp.asarray(np.sort(rng.integers(0, 2**31, (P,), dtype=np.int32)))
base3 = jnp.asarray(rng.integers(0, 2**31, (P, L), dtype=np.int32))
vers = jnp.asarray(rng.integers(1, 50, (P,), dtype=np.int32))
q1 = jnp.asarray(rng.integers(0, 2**31, (Q,), dtype=np.int32))
q3 = jnp.asarray(rng.integers(0, 2**31, (Q, L), dtype=np.int32))
idxQ = jnp.asarray(rng.integers(0, P, (Q,), dtype=np.int32))
idxP = jnp.asarray(rng.integers(0, P, (P,), dtype=np.int32))

# 1. sorts
def mk_sort(n, cols):
    data = [jnp.asarray(rng.integers(0, 2**31, (n,), dtype=np.int32)) for _ in range(cols)]

    @jax.jit
    def f(*d):
        return jax.lax.sort(d, num_keys=min(3, cols))

    return f, data


for n in (8192, 16384, 131072, 262144, 524288):
    f, data = mk_sort(n, 5)
    timeit(f"sort n={n} cols=5 keys=3", f, *data)

# 2. row gathers
@jax.jit
def row_gather_q(a, idx):
    return a[idx]


timeit("row gather 8192 rows from [131072,3]", row_gather_q, base3, idxQ)
timeit("row gather 131072 rows from [131072,3]", row_gather_q, base3, idxP)

# 3. contiguous block gather (dynamic_slice in vmap / gather w/ slice sizes)
@jax.jit
def block_gather(a, starts):
    # [Q, 32] contiguous slices from 1-D array
    return jax.vmap(lambda s: jax.lax.dynamic_slice(a, (s,), (32,)))(starts)


timeit("block gather 8192 x 32 contiguous (1D)", block_gather, base, idxQ)

# 4. 1-D gathers
@jax.jit
def g1(a, idx):
    return a[idx]


timeit("1D gather 8192 from [131072]", g1, vers, idxQ)
timeit("1D gather 131072 from [131072]", g1, vers, idxP)

# 5. 1-D scatter-add
@jax.jit
def sc_add(idx):
    return jnp.zeros((P,), jnp.int32).at[idx].add(1)


timeit("1D scatter-add 8192 into [131072]", sc_add, idxQ)


@jax.jit
def sc_add_sorted(idx):
    return jnp.zeros((P,), jnp.int32).at[idx].add(1, unique_indices=False, indices_are_sorted=True)


timeit("1D scatter-add 8192 sorted-idx", sc_add_sorted, jnp.sort(idxQ))

# 6. row scatter
@jax.jit
def row_scatter(q, idx):
    return jnp.zeros((P + Q, L), jnp.int32).at[idx].set(q)


timeit("row scatter 8192x3 into [139264,3]", row_scatter, q3, idxQ)

# 7. cumsums
@jax.jit
def cs(a):
    return jnp.cumsum(a)


timeit("cumsum [131072]", cs, vers)
big = jnp.asarray(rng.integers(0, 100, (524288,), dtype=np.int32))
timeit("cumsum [524288]", cs, big)

# 8. dense compare RxW 3-lane lex + reduce
w3 = jnp.asarray(rng.integers(0, 2**31, (4096, L), dtype=np.int32))
r3 = jnp.asarray(rng.integers(0, 2**31, (4096, L), dtype=np.int32))


@jax.jit
def dense_lex(r, w):
    # lex r < w over trailing lane, dense [4096, 4096]
    lt = jnp.zeros((4096, 4096), bool)
    eq = jnp.ones((4096, 4096), bool)
    for i in range(L):
        ri = r[:, None, i]
        wi = w[None, :, i]
        lt = lt | (eq & (ri < wi))
        eq = eq & (ri == wi)
    return lt.any(axis=1)


timeit("dense lex cmp [4096x4096x3] + reduce", dense_lex, r3, w3)

# 9/10. MXU fixpoint
Pji = jnp.asarray(rng.random((4096, 4096)) < 0.001, dtype=jnp.bfloat16)
H = jnp.asarray(rng.random((4096,)) < 0.3)


@jax.jit
def fixpoint(Pji, H):
    def body(val):
        commit, _ = val
        blocked = (Pji @ commit.astype(jnp.bfloat16)) > 0
        new = ~H & ~blocked
        return new, jnp.any(new != commit)

    commit, _ = jax.lax.while_loop(lambda v: v[1], body, (~H, jnp.array(True)))
    return commit


timeit("MXU bf16 matvec fixpoint [4096^2]", fixpoint, Pji, H)

# 11. binary search: 18 rounds, 8192 queries, 3-lane rows
def lex_lt(a, b):
    lt = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]), dtype=bool)
    eq = jnp.ones_like(lt)
    for i in range(a.shape[-1]):
        ai, bi = a[..., i], b[..., i]
        lt = lt | (eq & (ai < bi))
        eq = eq & (ai == bi)
    return lt


@jax.jit
def bsearch(sorted3, q):
    lo = jnp.zeros(q.shape[:-1], jnp.int32)
    hi = jnp.full(q.shape[:-1], P, jnp.int32)
    for _ in range(18):
        mid = (lo + hi) >> 1
        row = sorted3[mid]
        go = lex_lt(row, q)
        lo = jnp.where(go, mid + 1, lo)
        hi = jnp.where(go, hi, mid)
    return lo


timeit("binary search 8192 q into [131072,3] x18", bsearch, base3, q3)

# 12. one-hot matmul positioning: rank of q among 4096 pivots via MXU-able compare
piv = jnp.asarray(np.sort(rng.integers(0, 2**31, (4096,), dtype=np.int32)))


@jax.jit
def rank_dense(q, piv):
    return (q[:, None] >= piv[None, :]).sum(axis=1)


timeit("dense rank 8192 q vs 4096 pivots (1 lane)", rank_dense, q1, piv)

# 13. sparse-table 2-gather range max
st = jnp.asarray(rng.integers(1, 50, (18, P), dtype=np.int32))
lo_i = jnp.asarray(rng.integers(0, P - 1, (Q,), dtype=np.int32))
ln = jnp.asarray(rng.integers(1, 1000, (Q,), dtype=np.int32))


@jax.jit
def st_rmax(st, lo, ln):
    k = 31 - jax.lax.clz(ln)  # floor log2
    hi = lo + ln - (1 << k)
    a = st[k, lo]
    b = st[k, hi]
    return jnp.maximum(a, b)


timeit("sparse-table rmax 8192 q (2x 2D gather)", st_rmax, st, lo_i, ln)

# 14. sort payload columns count effect
f, data = mk_sort(139264, 3)
timeit("sort n=139264 cols=3 keys=3", f, *data)
f, data = mk_sort(139264, 6)
timeit("sort n=139264 cols=6 keys=3", f, *data)

# 15. segment-max via sorted-order cummax variant: associative_scan max over [524288]
@jax.jit
def cmax(a):
    return jax.lax.cummax(a)


timeit("cummax [524288]", cmax, big)
