"""Round-3 phase profile of the grid kernel at bench shape.

Each phase runs inside a lax.scan whose iterations are serially
data-dependent (state threads through, or the carry perturbs an input the
phase actually reads), so XLA cannot hoist the body. Per-iteration cost =
slope between scan lengths 8 and 72, which cancels the axon tunnel's
~65ms blocked-dispatch floor.

Run without PYTHONPATH overrides (axon plugin needs /root/.axon_site).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time
import numpy as np
import jax
import jax.numpy as jnp

from foundationdb_tpu.conflict import grid as G
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
import bench as B

BATCHES = 24
TXNS = 2500
WINDOW = 50

print("devices:", jax.devices())
batches = B.make_batches(BATCHES, TXNS)
cap = 1 << 19
tpu = TpuConflictSet(key_width=12, capacity=cap)
encs = [tpu.encode(txs) for txs in batches]
tpu.detect_many_encoded([(encs[i], i + WINDOW, i) for i in range(8)])
state = tpu._state
print("grid shape:", state.grid.shape, "count sum:", int(np.asarray(state.count).sum()))

b, n, _ = encs[10]
batch = G.Batch(*[jnp.asarray(x) for x in b])
T, KR, L = batch.rb.shape
print("batch:", batch.rb.shape, batch.wb.shape)

now = jnp.int32(10 + WINDOW - tpu._base)
old = jnp.int32(max(10 - tpu._base, 0))

B_, S, Lp1 = state.grid.shape
Lk = Lp1 - 1
KW = batch.wb.shape[1]
Wtot = T * KW


def slope(name, make_run):
    """make_run(n) -> zero-arg callable; time n=8 vs n=72, report slope."""
    runs = {n: make_run(n) for n in (8, 72)}
    t0 = time.time()
    jax.block_until_ready(runs[8]())
    ct = time.time() - t0
    jax.block_until_ready(runs[72]())

    def rep(n):
        best = 1e9
        for _ in range(3):
            t0 = time.time()
            jax.block_until_ready(runs[n]())
            best = min(best, time.time() - t0)
        return best

    t8, t72 = rep(8), rep(72)
    dt = (t72 - t8) / 64 * 1000
    print(f"{name:44s} {dt:8.3f} ms/iter   (compile {ct:.1f}s, floor {t8*1000:.1f}ms)")
    return dt


def scan_state(name, step_fn):
    """step_fn(state) -> new GridState-like pytree; thread it."""

    def make_run(n):
        @jax.jit
        def run(st):
            def step(st, _):
                return step_fn(st), None

            out, _ = jax.lax.scan(step, st, None, length=n)
            return out

        return lambda: run(state)

    return slope(name, make_run)


def scan_carry(name, fn):
    """fn(c) -> int32 scalar, must genuinely consume c."""

    def make_run(n):
        @jax.jit
        def run(c0):
            def step(c, _):
                return fn(c), None

            out, _ = jax.lax.scan(step, c0, None, length=n)
            return out

        return lambda: run(jnp.int32(0))

    return slope(name, make_run)


def fold(out):
    s = jnp.int32(0)
    for leaf in jax.tree_util.tree_leaves(out):
        s = s ^ leaf.reshape(-1)[0].astype(jnp.int32)
    return s


# ---- top-level phases ----

def full_step(st):
    st2, verdicts, pressure = G._resolve_one(st, batch, now, old, old)
    return st2

scan_state("FULL _resolve_one (state thread)", full_step)


def hist_intra(c):
    b2 = batch._replace(t_snap=batch.t_snap + (c & 1))
    H = G.history_conflicts(state, b2)
    commit = G.intra_batch_commits(b2, H)
    return fold(commit)

scan_carry("history + intra (carry chain)", hist_intra)


def hist_only(c):
    b2 = batch._replace(t_snap=batch.t_snap + (c & 1))
    return fold(G.history_conflicts(state, b2))

scan_carry("history_conflicts", hist_only)


H_dev = jax.jit(G.history_conflicts)(state, batch)
commit_dev = jax.jit(G.intra_batch_commits)(batch, H_dev)


def merge_step(st):
    st2, pressure = G.merge_writes(st, batch, commit_dev, now, old)
    return st2

scan_state("merge_writes (state thread)", merge_step)


# ---- merge components, state-threaded where possible ----

def merge_flatsort_only(st):
    w_ok = G.lex_lt(batch.wb, batch.we) & commit_dev[:, None]
    c = batch.wb.reshape(Wtot, Lk)
    d = batch.we.reshape(Wtot, Lk)
    ok = w_ok.reshape(Wtot)
    bc = G._rank_le(c, st.pivots)
    bd = G._rank_le(d, st.pivots)
    codes = jnp.concatenate([c, d], axis=0)
    evs = jnp.concatenate([jnp.where(ok, 1, 0), jnp.where(ok, -1, 0)]).astype(jnp.int32)
    bkt = jnp.where(jnp.concatenate([ok, ok]), jnp.concatenate([bc, bd]), B_).astype(jnp.int32)
    cols = (bkt,) + tuple(codes[:, i] for i in range(Lk)) + (evs,)
    s = jax.lax.sort(cols, num_keys=Lk + 1)
    return st._replace(count=st.count + (s[0].reshape(-1)[0] & 0x1))

scan_state("merge comp: rank+flatsort", merge_flatsort_only)


def merge_carry_only(st):
    w_ok = G.lex_lt(batch.wb, batch.we) & commit_dev[:, None]
    ok = w_ok.reshape(Wtot)
    bc = G._rank_le(batch.wb.reshape(Wtot, Lk), st.pivots)
    bd = G._rank_le(batch.we.reshape(Wtot, Lk), st.pivots)
    evs = jnp.concatenate([jnp.where(ok, 1, 0), jnp.where(ok, -1, 0)]).astype(jnp.int32)
    bkt = jnp.where(jnp.concatenate([ok, ok]), jnp.concatenate([bc, bd]), B_).astype(jnp.int32)
    ar = jnp.arange(B_, dtype=jnp.int32)[None, :]
    evsum = jnp.sum(jnp.where(bkt[:, None] == ar, evs[:, None], 0), axis=0)
    carry = jnp.cumsum(evsum)
    return st._replace(count=st.count ^ (carry & 0x1))

scan_state("merge comp: carry [2W,B]+cumsum(B)", merge_carry_only)


def merge_bigsort_only(st):
    old_bnd = st.grid[..., :Lk]
    m_code = jnp.concatenate([old_bnd, old_bnd], axis=1)
    m_ver = jnp.concatenate([st.grid[..., Lk].astype(jnp.int32)] * 2, axis=1)
    cols = tuple(m_code[..., i] for i in range(Lk)) + (m_ver,)
    s = jax.lax.sort(cols, dimension=1, num_keys=Lk + 1)
    return st._replace(bmax=st.bmax ^ (s[Lk][:, 0] & 1))

scan_state("merge comp: per-bucket sort [B,2S]", merge_bigsort_only)


def merge_fill_only(st):
    v = jnp.concatenate([st.grid[..., Lk].astype(jnp.int32)] * 2, axis=1)
    h = v > 0
    f = G._log_shift_fill(v, h)
    return st._replace(bmax=st.bmax ^ (f[:, -1] & 1))

scan_state("merge comp: log_shift_fill [B,2S]", merge_fill_only)


def merge_compact_only(st):
    m_code = jnp.concatenate([st.grid[..., :Lk]] * 2, axis=1)
    nv = jnp.concatenate([st.grid[..., Lk].astype(jnp.int32)] * 2, axis=1)
    keep = nv > 0
    cols = (jnp.where(keep, 0, 1).astype(jnp.int32),) + tuple(
        m_code[..., i] for i in range(Lk)
    ) + (nv,)
    s = jax.lax.sort(cols, dimension=1, num_keys=1, is_stable=True)
    return st._replace(bmax=st.bmax ^ (s[1][:, 0].astype(jnp.int32) & 1))

scan_state("merge comp: compact sort [B,2S] 1key", merge_compact_only)


# ---- candidate blocks: touched-bucket merge at various [U, SS] ----

for U, SS in [(4096, 24), (4096, 40), (4096, 88), (8192, 24), (16384, 128)]:
    key_cols = [
        jax.random.randint(jax.random.PRNGKey(i), (U, SS), 0, 1 << 30, dtype=jnp.int32)
        for i in range(Lk + 1)
    ]

    def make_run(n, key_cols=key_cols):
        @jax.jit
        def run(cols):
            def step(cols, _):
                s = jax.lax.sort(tuple(cols), dimension=1, num_keys=Lk + 1)
                return list(s), None

            out, _ = jax.lax.scan(step, cols, None, length=n)
            return out[0]

        return lambda: run(key_cols)

    slope(f"cand: sort [U={U},{SS}] {Lk+1}key", make_run)


def gather_step(st):
    idx = (st.count[:4096] + jnp.arange(4096, dtype=jnp.int32) * 3) % B_
    g = st.grid[idx]
    return st._replace(count=st.count ^ (g[:, 0, 0].astype(jnp.int32)[0] & 1))

scan_state("cand: gather 4096xS bucket rows", gather_step)


def scatter_step(st):
    idx = (st.count[:4096] + jnp.arange(4096, dtype=jnp.int32) * 7) % B_
    rows = st.grid[:4096]
    g = st.grid.at[idx].set(rows)
    return st._replace(grid=g)

scan_state("cand: gather+scatter 4096xS rows", scatter_step)
