"""Why is _reshard still ~2s, and what pressure triggers it?"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time
import numpy as np
import jax

from foundationdb_tpu.conflict import grid as G
from foundationdb_tpu.conflict import keys as K
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
import bench as B

BATCHES = 100
TXNS = 2500
WINDOW = 50
GROUP = 20

batches = B.make_batches(BATCHES, TXNS)
cap = 1 << 19
tpu = TpuConflictSet(key_width=12, capacity=cap)
encs = [tpu.encode(txs) for txs in batches]

# run groups, printing pressure each collect
orig_collect = tpu._collect
def loud_collect(group):
    r = orig_collect(group)
    return r
import foundationdb_tpu.conflict.tpu_backend as TB

for g in range(0, BATCHES, GROUP):
    work = [(encs[i], i + WINDOW, i) for i in range(g, min(g + GROUP, BATCHES))]
    h = tpu.detect_many_encoded_async(work)
    h()
    pr = "collected"
    print(f"group {g//GROUP}: B={tpu._B} count_sum={int(np.asarray(tpu._state.count).sum())} "
          f"count_max={int(np.asarray(tpu._state.count).max())}")

# now time the pieces of a reshard at this state
state = tpu._state
t0 = time.time(); codes, vers = G.live_rows(state); print(f"live_rows: {time.time()-t0:.3f}s N={len(codes)}")
t0 = time.time(); enc = K.encode_keys(tpu._sample, tpu._width); print(f"encode sample({len(tpu._sample)}): {time.time()-t0:.3f}s")
t0 = time.time()
allc = np.concatenate([codes, enc])
keys = G.codes_to_bytes(np.ascontiguousarray(allc))
_, uniq_idx = np.unique(keys, return_index=True)
cands = allc[uniq_idx]
cands = cands[cands.any(axis=1)]
print(f"unique: {time.time()-t0:.3f}s cands={len(cands)}")
from foundationdb_tpu.conflict.tpu_backend import _pick_pivots
t0 = time.time(); piv = _pick_pivots(cands, tpu._B, tpu._lanes); print(f"pick_pivots: {time.time()-t0:.3f}s P={len(piv)}")
t0 = time.time(); st = G.reshard_host(state, piv, tpu._B, tpu._S); print(f"reshard_host: {time.time()-t0:.3f}s")
t0 = time.time(); jax.block_until_ready(st.grid); print(f"device upload: {time.time()-t0:.3f}s grid {st.grid.shape}")
