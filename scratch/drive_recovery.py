"""Verify drive: dynamic cluster, kill master, recover, read back, status."""
from foundationdb_tpu.client import management
from foundationdb_tpu.client.database import Database
from foundationdb_tpu.net.sim import Sim
from foundationdb_tpu.runtime.futures import delay, spawn
from foundationdb_tpu.server.cluster import ClusterConfig, DynamicCluster

sim = Sim(seed=11)
sim.activate()
cluster = DynamicCluster(
    sim,
    ClusterConfig(
        n_proxies=1, n_resolvers=1, n_tlogs=2, n_storage=2, tlog_replication=2
    ),
    n_coordinators=3,
)
db = Database.from_coordinators(sim, cluster.coordinators)


async def body():
    for i in range(20):

        async def w(tr, i=i):
            tr.set(b"k%02d" % i, b"v%d" % i)

        await db.run(w)
    victim = next(
        addr
        for addr, p in sim.processes.items()
        if getattr(p, "worker", None) and p.alive
        for h in p.worker.roles.values()
        if h.kind == "master"
    )
    print("killing master host", victim, flush=True)
    sim.kill_process(victim)
    for i in range(20, 40):

        async def w(tr, i=i):
            tr.set(b"k%02d" % i, b"v%d" % i)

        await db.run(w)
    db2 = Database.from_coordinators(sim, cluster.coordinators)

    async def r(tr):
        return await tr.get_range(b"k", b"l")

    rows = await db2.run(r)
    assert len(rows) == 40, len(rows)
    assert all(v == b"v%d" % i for i, (_k, v) in enumerate(rows))
    await delay(6.0)
    doc = await management.get_status(cluster.coordinators, db.client)
    # counters are process-local (reference behavior): the pre-kill proxy's
    # 20 commits died with its host; only the new epoch's proxy counts
    assert doc["qos"]["transactions_committed_total"] >= 20, doc["qos"]
    assert doc["data"]["max_storage_version"] > 0
    assert doc["cluster"]["recovery_count"] >= 2
    print(
        "recovery+status OK; recoveries:",
        doc["cluster"]["recovery_count"],
        "committed:",
        doc["qos"]["transactions_committed_total"],
        flush=True,
    )
    return True


print(sim.run_until_done(spawn(body()), 600.0), flush=True)
