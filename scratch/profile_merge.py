"""Decompose resolve_many cost by running scan variants with phases stubbed.

Timing-only (verdict correctness irrelevant for stubs); each variant is the
same lax.scan over 20 batches with donated state, differing in which pieces
of the step run. Differences between variants attribute in-scan time."""

import random
import sys
import time

sys.path.insert(0, "/root/repo")

import functools

import jax
import jax.numpy as jnp
import numpy as np

from foundationdb_tpu.conflict import grid as G
from foundationdb_tpu.conflict.api import CommitTransaction
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet

TXNS = 2500
KEYSPACE = 1000000
WINDOW = 50
GROUP = 20


def log(m):
    print(m, flush=True)


def make_batches(n_batches, n_txns, seed=0):
    rnd = random.Random(seed)
    out = []
    for i in range(n_batches):
        txs = []
        for _ in range(n_txns):
            a = rnd.randrange(KEYSPACE)
            b = a + 1 + rnd.randrange(10)
            c = rnd.randrange(KEYSPACE)
            d = c + 1 + rnd.randrange(10)
            txs.append(
                CommitTransaction(
                    read_snapshot=i,
                    read_conflict_ranges=[(b"%08d" % a, b"%08d" % b)],
                    write_conflict_ranges=[(b"%08d" % c, b"%08d" % d)],
                )
            )
        out.append(txs)
    return out


def merge_variant(state, batch, commit, now, oldest, *, parts):
    """Reimplementation of merge_writes with sections gated by `parts`."""
    B, S, Lp1 = state.grid.shape
    L = Lp1 - 1
    T, KW, _ = batch.wb.shape
    Wtot = T * KW
    S2 = G.staging_slots(S)
    U = min(2 * Wtot, B)

    w_ok = G.lex_lt(batch.wb, batch.we) & commit[:, None]
    c = batch.wb.reshape(Wtot, L)
    d = batch.we.reshape(Wtot, L)
    ok = w_ok.reshape(Wtot)
    okok = jnp.concatenate([ok, ok])

    if "rank" in parts:
        bc = G._rank_le(c, state.pivots)
        bd = G._rank_le(d, state.pivots)
    else:
        bc = jnp.zeros((Wtot,), jnp.int32)
        bd = jnp.zeros((Wtot,), jnp.int32)

    codes = jnp.concatenate([c, d], axis=0)
    codes = jnp.where(okok[:, None], codes, G.SENTINEL)
    evs = jnp.concatenate([jnp.where(ok, 1, 0), jnp.where(ok, -1, 0)]).astype(jnp.int32)
    bkt = jnp.where(okok, jnp.concatenate([bc, bd]), B).astype(jnp.int32)

    if "sort1" in parts:
        cols = (bkt,) + tuple(codes[:, i] for i in range(L)) + (evs,)
        sorted_cols = jax.lax.sort(cols, num_keys=L + 1)
        sb = sorted_cols[0]
        scode = jnp.stack(sorted_cols[1 : L + 1], axis=1)
        sev = sorted_cols[L + 1]
    else:
        sb, scode, sev = bkt, codes, evs

    valid = sb < B
    code_new = jnp.concatenate(
        [jnp.ones(1, bool), (scode[1:] != scode[:-1]).any(axis=1) | (sb[1:] != sb[:-1])]
    )
    code_last = jnp.concatenate([code_new[1:], jnp.ones(1, bool)])
    bkt_new = jnp.concatenate([jnp.ones(1, bool), sb[1:] != sb[:-1]])
    bkt_last = jnp.concatenate([bkt_new[1:], jnp.ones(1, bool)])

    pe = jnp.cumsum(sev)
    pe_prev = jnp.concatenate([jnp.zeros(1, jnp.int32), pe[:-1]])
    pe_before_run = G._log_shift_fill(
        jnp.where(code_new, pe_prev, 0)[None, :], code_new[None, :]
    )[0]
    agg_ev = pe - pe_before_run
    pe_before_bkt = G._log_shift_fill(
        jnp.where(bkt_new, pe_prev, 0)[None, :], bkt_new[None, :]
    )[0]
    bkt_ev = pe - pe_before_bkt

    ucum = jnp.cumsum((bkt_new & valid).astype(jnp.int32)) - 1
    ccum = jnp.cumsum((code_new & valid).astype(jnp.int32))
    ccum_at_bkt = G._log_shift_fill(
        jnp.where(bkt_new, ccum - 1, 0)[None, :], bkt_new[None, :]
    )[0]
    slot = ccum - 1 - ccum_at_bkt
    max_staged = jnp.max(jnp.where(code_last & valid, slot + 1, 0))

    flat = jnp.where(code_last & valid & (slot < S2), ucum * S2 + slot, U * S2)
    st_code = jnp.full((U * S2 + 1, L), G.SENTINEL, dtype=jnp.uint32)
    st_code = st_code.at[flat].set(scode, mode="drop")[: U * S2].reshape(U, S2, L)
    st_ev = jnp.zeros((U * S2 + 1,), jnp.int32).at[flat].set(agg_ev, mode="drop")[
        : U * S2
    ].reshape(U, S2)

    tid = jnp.full((U + 1,), B, jnp.int32).at[
        jnp.where(bkt_new & valid, ucum, U)
    ].set(sb, mode="drop")[:U]

    evsum_B = jnp.zeros((B + 1,), jnp.int32).at[
        jnp.where(bkt_last & valid, sb, B)
    ].add(jnp.where(bkt_last & valid, bkt_ev, 0), mode="drop")[:B]
    carry = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(evsum_B)[:-1]])

    tid_c = jnp.minimum(tid, B - 1)
    u_live = tid < B
    if "gather" in parts:
        old = state.grid[tid_c]
        old_used = (jnp.arange(S)[None, :] < state.count[tid_c][:, None]) & u_live[:, None]
        old_code = jnp.where(old_used[..., None], old[..., :L], G.SENTINEL)
        old_ver = jnp.where(old_used, old[..., L].astype(jnp.int32), 0)
    else:
        old_code = jnp.full((U, S, L), G.SENTINEL, jnp.uint32)
        old_ver = jnp.zeros((U, S), jnp.int32)

    M = S + S2
    m_code = jnp.concatenate([old_code, st_code], axis=1)
    m_ver = jnp.concatenate([old_ver, jnp.zeros((U, S2), jnp.int32)], axis=1)
    m_ev = jnp.concatenate([jnp.zeros((U, S), jnp.int32), st_ev], axis=1)
    m_old = jnp.concatenate(
        [ (old_ver > -1).astype(jnp.int32) if "gather" not in parts else (old_code != G.SENTINEL).any(-1).astype(jnp.int32), jnp.zeros((U, S2), jnp.int32)], axis=1
    )

    if "sort2" in parts:
        cols = tuple(m_code[..., i] for i in range(L)) + (m_ver, m_ev, m_old)
        sorted_cols = jax.lax.sort(cols, dimension=1, num_keys=L)
        g_code = jnp.stack(sorted_cols[:L], axis=-1)
        g_ver = sorted_cols[L]
        g_ev = sorted_cols[L + 1]
        g_old = sorted_cols[L + 2].astype(bool)
    else:
        g_code, g_ver, g_ev, g_old = m_code, m_ver, m_ev, m_old.astype(bool)

    base = G._log_shift_fill(jnp.where(g_old, g_ver, 0), g_old)
    carry_in = jnp.where(u_live, carry[tid_c], 0)
    cov = carry_in[:, None] + jnp.cumsum(g_ev, axis=1)
    covered = cov > 0
    nv = jnp.where(covered, jnp.maximum(base, now), base)
    nv = jnp.where(nv < oldest, 0, nv)

    is_sent = (g_code == G.SENTINEL).all(axis=-1)
    nxt_differs = jnp.concatenate(
        [(g_code[:, 1:] != g_code[:, :-1]).any(axis=-1), jnp.ones((U, 1), bool)], axis=1
    )
    keep = (~is_sent) & nxt_differs
    shifted_nv = jnp.pad(nv, ((0, 0), (1, 0)), constant_values=-1)[:, :M]
    first_of_run = jnp.concatenate(
        [jnp.ones((U, 1), bool), (g_code[:, 1:] != g_code[:, :-1]).any(axis=-1)], axis=1
    )
    pval = G._log_shift_fill(jnp.where(first_of_run, shifted_nv, 0), first_of_run)
    keep = keep & (nv != pval)

    kept_cnt = keep.sum(axis=1, dtype=jnp.int32)
    max_kept = jnp.max(jnp.where(u_live, kept_cnt, 0))

    if "sort3" in parts:
        cols = (jnp.where(keep, 0, 1).astype(jnp.int32),) + tuple(
            g_code[..., i] for i in range(L)
        ) + (nv,)
        sorted_cols = jax.lax.sort(cols, dimension=1, num_keys=1, is_stable=True)
        out_code = jnp.stack(sorted_cols[1 : L + 1], axis=-1)[:, :S, :]
        out_ver = sorted_cols[L + 1][:, :S]
    else:
        out_code = g_code[:, :S, :]
        out_ver = nv[:, :S]

    new_count_u = jnp.minimum(kept_cnt, S)
    used = jnp.arange(S)[None, :] < new_count_u[:, None]
    out_code = jnp.where(used[..., None], out_code, G.SENTINEL)
    out_ver = jnp.where(used, out_ver, 0)
    out_rows = jnp.concatenate([out_code, out_ver.astype(jnp.uint32)[..., None]], axis=-1)
    out_bmax = jnp.max(out_ver, axis=1)

    if "scatter" in parts:
        new_grid = state.grid.at[tid].set(out_rows, mode="drop")
        new_count = state.count.at[tid].set(new_count_u, mode="drop")
        new_bmax = state.bmax.at[tid].set(out_bmax, mode="drop")
    else:
        new_grid, new_count, new_bmax = state.grid, state.count, state.bmax

    if "collapse" in parts:
        is_touched = jnp.zeros((B + 1,), bool).at[tid].set(True, mode="drop")[:B]
        covered_b = (carry > 0) & ~is_touched
        collapsed = jnp.full((B, S, Lp1), G.SENTINEL, dtype=jnp.uint32)
        collapsed = collapsed.at[:, :, L].set(0)
        collapsed = collapsed.at[:, 0, :L].set(state.pivots)
        collapsed = collapsed.at[:, 0, L].set(now.astype(jnp.uint32))
        cmask = covered_b[:, None, None]
        new_grid = jnp.where(cmask, collapsed, new_grid)
        new_count = jnp.where(covered_b, 1, new_count)
        new_bmax = jnp.where(covered_b, now, new_bmax)

    pressure = jnp.stack([max_staged, max_kept])
    return G.GridState(state.pivots, new_grid, new_count, new_bmax), pressure


ALL = {"rank", "sort1", "gather", "sort2", "sort3", "scatter", "collapse"}


def make_runner(parts, do_history, do_intra):
    @functools.partial(jax.jit, donate_argnames=("state",))
    def run(state, batches, nows, olds_pre, olds_post):
        def step(st, inp):
            batch, now, old_pre, old_post = inp
            if do_history:
                H = G.history_conflicts(st, batch) | (
                    batch.t_has_reads & (batch.t_snap < old_pre)
                )
            else:
                H = batch.t_snap < old_pre
            if do_intra:
                commit = G.intra_batch_commits(batch, H)
            else:
                commit = ~H
            st2, pressure = merge_variant(
                st, batch, commit, now, old_post, parts=parts
            )
            return st2, pressure

        state, pressures = jax.lax.scan(
            step, state, (batches, nows, olds_pre, olds_post)
        )
        return state, pressures

    return run


def main():
    log(f"devices: {jax.devices()}")
    batches = make_batches(40 + GROUP, TXNS)
    cap = 1 << 17
    while cap < 4 * TXNS * WINDOW:
        cap <<= 1
    tpu = TpuConflictSet(key_width=12, capacity=cap)
    enc = [tpu.encode(txs) for txs in batches]
    work = [(enc[i], i + WINDOW, i) for i in range(40)]
    for g in range(0, 40, GROUP):
        tpu.detect_many_encoded(work[g : g + GROUP])
    base_state = tpu._state
    log(f"B={tpu._B} S={tpu._S} live={int(np.asarray(base_state.count).sum())}")

    stacked = tpu._stack([e[0] for e in enc[40 : 40 + GROUP]])
    stacked = jax.tree_util.tree_map(jnp.asarray, stacked)
    nows = jnp.asarray([41 + WINDOW - tpu._base] * GROUP, jnp.int32)
    olds = jnp.asarray([41 - tpu._base] * GROUP, jnp.int32)

    variants = [
        ("FULL", ALL, True, True),
        ("no history", ALL, False, True),
        ("no intra", ALL, True, False),
        ("merge only", ALL, False, False),
        ("merge -collapse", ALL - {"collapse"}, False, False),
        ("merge -scatter-collapse", ALL - {"scatter", "collapse"}, False, False),
        ("merge -sort2", ALL - {"sort2"}, False, False),
        ("merge -sort3", ALL - {"sort3"}, False, False),
        ("merge -sort2-sort3", ALL - {"sort2", "sort3"}, False, False),
        ("merge -gather", ALL - {"gather", "sort2", "sort3", "scatter", "collapse"}, False, False),
        ("merge -rank", ALL - {"rank"}, False, False),
        ("merge skeleton(sort1 only)", {"sort1"}, False, False),
    ]
    for name, parts, hist, intra in variants:
        run = make_runner(frozenset(parts), hist, intra)

        def go():
            st = jax.tree_util.tree_map(lambda x: x + 0, base_state)
            out = run(st, stacked, nows, olds, olds)
            jax.block_until_ready(out)
            return out

        go()  # compile
        t0 = time.perf_counter()
        n = 3
        for _ in range(n):
            go()
        dt = (time.perf_counter() - t0) / n
        # subtract the state copy cost? measure it once
        log(f"{name:28s} {dt/GROUP*1000:8.3f} ms/batch")


if __name__ == "__main__":
    main()
