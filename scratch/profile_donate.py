"""Does donation explain 20ms vs 6ms per batch in resolve_many?"""
import random
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from foundationdb_tpu.conflict import grid as G
from foundationdb_tpu.conflict.api import CommitTransaction
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet

TXNS, KEYSPACE, WINDOW, GROUP = 2500, 1000000, 50, 20


def make_batches(n, seed=0):
    rnd = random.Random(seed)
    out = []
    for i in range(n):
        txs = []
        for _ in range(TXNS):
            a = rnd.randrange(KEYSPACE)
            b = a + 1 + rnd.randrange(10)
            c = rnd.randrange(KEYSPACE)
            d = c + 1 + rnd.randrange(10)
            txs.append(CommitTransaction(
                read_snapshot=i,
                read_conflict_ranges=[(b"%08d" % a, b"%08d" % b)],
                write_conflict_ranges=[(b"%08d" % c, b"%08d" % d)],
            ))
        out.append(txs)
    return out


batches = make_batches(40 + GROUP)
cap = 1 << 17
while cap < 4 * TXNS * WINDOW:
    cap <<= 1
tpu = TpuConflictSet(key_width=12, capacity=cap)
enc = [tpu.encode(txs) for txs in batches]
for g in range(0, 40, GROUP):
    tpu.detect_many_encoded([(enc[i], i + WINDOW, i) for i in range(g, g + GROUP)])
base_state = tpu._state

stacked = jax.tree_util.tree_map(jnp.asarray, tpu._stack([e[0] for e in enc[40:40 + GROUP]]))
nows = jnp.asarray([41 + WINDOW - tpu._base] * GROUP, jnp.int32)
olds = jnp.asarray([41 - tpu._base] * GROUP, jnp.int32)

# donated version (the production path)
def run_donated():
    st = jax.tree_util.tree_map(lambda x: x + 0, base_state)
    out = G.resolve_many(st, stacked, nows, olds, olds)  # resolve_many donates
    jax.block_until_ready(out)
    return out

# non-donated
nod = jax.jit(G.resolve_many.__wrapped__)
def run_nodonate():
    out = nod(base_state, stacked, nows, olds, olds)
    jax.block_until_ready(out)
    return out

for name, fn in [("donated", run_donated), ("no-donate", run_nodonate)]:
    fn()
    t0 = time.perf_counter()
    for _ in range(3):
        fn()
    dt = (time.perf_counter() - t0) / 3
    print(f"{name:12s} {dt/GROUP*1000:8.3f} ms/batch  ({GROUP*TXNS/dt/1e6:.3f} Mtxn/s)", flush=True)

# host-side verdict conversion cost (what _collect does per group)
from foundationdb_tpu.conflict.api import Verdict
_st, verdicts, _pr = run_donated()
out = np.asarray(jax.device_get(verdicts))
t0 = time.perf_counter()
res = [[Verdict(int(v)) for v in out[g, :TXNS]] for g in range(GROUP)]
dt = time.perf_counter() - t0
print(f"Verdict(int(v)) conversion: {dt/GROUP*1000:.3f} ms/batch")
t0 = time.perf_counter()
res2 = [out[g, :TXNS].tolist() for g in range(GROUP)]
dt2 = time.perf_counter() - t0
print(f"tolist() only:             {dt2/GROUP*1000:.3f} ms/batch")
