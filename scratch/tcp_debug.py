import os
import subprocess
import sys
import time

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/tests")
import test_tcp_cluster as T

ports = T.free_ports(5)
cport, *wports = ports
coord = f"127.0.0.1:{cport}"
procs = [
    T.spawn_server(["--listen", coord, "--role", "coordinator",
                    "--datadir", f"/tmp/tcpdbg/coord", "--tracefile", "/tmp/tcpdbg/coord.trace"])
]
config = "n_storage=2,replication=1,n_tlogs=1"
classes = ["storage", "storage", "transaction", "stateless"]
for port, pclass in zip(wports, classes):
    procs.append(
        T.spawn_server([
            "--listen", f"127.0.0.1:{port}", "--role", "worker",
            "--class", pclass, "--coordinators", coord,
            "--config", config, "--datadir", f"/tmp/tcpdbg/w{port}", "--tracefile", f"/tmp/tcpdbg/w{port}.trace",
        ])
    )
time.sleep(10)
for p in procs:
    if p.poll() is not None:
        print("EXITED:", p.args)
rc, out = T.fdbcli(coord, "set hello world", timeout=30)
print("cli rc", rc, "out", out)
for p in procs:
    p.kill()
outs = []
for p in procs:
    try:
        o, _ = p.communicate(timeout=5)
    except Exception:
        o = "<none>"
    outs.append(o)
for p, o in zip(procs, outs):
    print("=== ", " ".join(p.args[-6:]))
    print(o[-1500:])
