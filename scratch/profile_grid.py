"""Phase-level profiling of the bucket-grid kernel on the real chip.

Times each kernel phase separately (jitted in isolation, donated where the
real path donates) at the bench shape, plus the composed resolve_many, plus
host-side stack/encode overhead — to find where the 23 ms/batch goes.
"""

import os
import random
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from foundationdb_tpu.conflict import grid as G
from foundationdb_tpu.conflict.api import CommitTransaction
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet

BATCHES = 60
TXNS = 2500
KEYSPACE = 1000000
WINDOW = 50
GROUP = 20


def log(m):
    print(m, flush=True)


def make_batches(n_batches, n_txns, seed=0):
    rnd = random.Random(seed)
    batches = []
    for i in range(n_batches):
        txs = []
        for _ in range(n_txns):
            a = rnd.randrange(KEYSPACE)
            b = a + 1 + rnd.randrange(10)
            c = rnd.randrange(KEYSPACE)
            d = c + 1 + rnd.randrange(10)
            txs.append(
                CommitTransaction(
                    read_snapshot=i,
                    read_conflict_ranges=[(b"%08d" % a, b"%08d" % b)],
                    write_conflict_ranges=[(b"%08d" % c, b"%08d" % d)],
                )
            )
        batches.append(txs)
    return batches


def timeit(name, fn, n=20):
    fn()  # warm
    jax.effects_barrier()
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = fn()
    jax.tree_util.tree_map(
        lambda x: x.block_until_ready() if hasattr(x, "block_until_ready") else x,
        out,
    )
    dt = (time.perf_counter() - t0) / n
    log(f"{name:34s} {dt*1000:8.3f} ms")
    return dt


def main():
    log(f"devices: {jax.devices()}")
    batches = make_batches(BATCHES, TXNS)

    cap = 1 << 17
    while cap < 4 * TXNS * WINDOW:
        cap <<= 1
    tpu = TpuConflictSet(key_width=12, capacity=cap)
    log(f"B={tpu._B} S={tpu._S} lanes={tpu._lanes}")

    t0 = time.perf_counter()
    enc = [tpu.encode(txs) for txs in batches]
    log(f"encode: {(time.perf_counter()-t0)/BATCHES*1000:.2f} ms/batch")

    # run a realistic prefix so the grid is populated like mid-bench
    work = [(enc[i], i + WINDOW, i) for i in range(40)]
    for g in range(0, 40, GROUP):
        tpu.detect_many_encoded(work[g : g + GROUP])
    state = tpu._state
    log(
        f"after 40 batches: live rows {int(np.asarray(state.count).sum())}, "
        f"count max {int(np.asarray(state.count).max())}"
    )

    # host stack overhead
    raw = [e[0] for e in enc[40 : 40 + GROUP]]
    t0 = time.perf_counter()
    for _ in range(5):
        stacked = tpu._stack(raw)
    log(f"host _stack({GROUP}): {(time.perf_counter()-t0)/5*1000:.2f} ms")

    stacked_dev = jax.tree_util.tree_map(jnp.asarray, stacked)
    batch1 = jax.tree_util.tree_map(lambda x: x[0], stacked_dev)

    nows = np.asarray([41 + WINDOW - tpu._base] * GROUP, np.int32)
    olds = np.asarray([41 - tpu._base] * GROUP, np.int32)
    now1 = jnp.asarray(nows[0])
    old1 = jnp.asarray(olds[0])

    # individual phases (no donation: state reused)
    jit_hist = jax.jit(G.history_conflicts)
    H = jit_hist(state, batch1)

    jit_intra = jax.jit(G.intra_batch_commits)
    commit = jit_intra(batch1, H)

    jit_merge = jax.jit(G.merge_writes)
    timeit("history_conflicts", lambda: jit_hist(state, batch1))
    timeit("intra_batch_commits", lambda: jit_intra(batch1, H))
    timeit("merge_writes", lambda: jit_merge(state, batch1, commit, now1, old1))

    # sub-phases of intra: the Pji compare alone vs the fixpoint
    def pji_only(batch, H):
        T, KR, L = batch.rb.shape
        Pji = jnp.zeros((T, T), dtype=bool)
        for ar in range(KR):
            rb = batch.rb[:, ar, None, None, :]
            re = batch.re[:, ar, None, None, :]
            wb = batch.wb[None, :, :, :]
            we = batch.we[None, :, :, :]
            o = G.lex_lt(rb, we) & G.lex_lt(wb, re)
            Pji = Pji | o.any(axis=2)
        return Pji

    jit_pji = jax.jit(pji_only)
    timeit("  intra: Pji compare only", lambda: jit_pji(batch1, H))

    # composed single batch
    jit_one = jax.jit(G.resolve_batch, donate_argnames=())

    def one():
        return jit_one(state, batch1, now1, old1, old1)

    timeit("resolve_batch (1 batch, no donate)", one, n=10)

    # composed group of 20 via resolve_many (no donation for repeat)
    jit_many = jax.jit(G.resolve_many, donate_argnames=())

    def many():
        return jit_many(state, stacked_dev, jnp.asarray(nows), jnp.asarray(olds), jnp.asarray(olds))

    dt = timeit(f"resolve_many (group of {GROUP})", many, n=3)
    log(f"  => per-batch {dt/GROUP*1000:.3f} ms, per-txn throughput {GROUP*TXNS/dt/1e6:.3f} Mtxn/s")


if __name__ == "__main__":
    main()
