"""Kernel fault tolerance around the ``newConflictSet()`` seam.

The resolver's MVCC conflict check lives on a device (the paper's bet) —
and a device is allowed to die. ``GuardedConflictSet`` wraps the backend
the resolver talks to with:

- a **bounded journal** of committed write conflict ranges inside the MVCC
  window (``WriteRangeJournal``) — the resolver already computes them, the
  journal just keeps them replayable;
- a **health state machine** HEALTHY → DEGRADED → FAILED_OVER →
  (re-probe) → HEALTHY, with FAILED as the terminal "even the fallback is
  gone" state (what used to be the resolver's permanent ``_broken``
  poison);
- **journal-replay recovery**: a faulted batch is re-resolved on a freshly
  built backend whose history is reconstructed from the journal. Replay is
  write-only blind transactions, so the rebuilt history is exactly the
  committed write set — verdict semantics are preserved with **zero false
  commits**; reads older than the journal floor turn TOO_OLD, i.e. at
  worst extra conservative aborts while replaying;
- **failover** to the ``native`` C++ skip list (or the ``oracle`` as a
  backstop) after repeated strikes, and **re-promotion** to the device
  backend once a periodic probe dispatch passes.

Deadline + bounded in-place retry live in the resolver
(server/resolver.py:_dispatch_collect), which owns the dispatch/collect
awaits; this module owns what happens when those fail.
"""

from __future__ import annotations

import time
from collections import deque

from ..runtime.knobs import Knobs
from ..runtime.loop import Cancelled, now as loop_now
from ..runtime.trace import SevError, SevInfo, SevWarn, trace
from .api import CommitTransaction, new_conflict_set
from .faults import KernelTimeoutError, StaleEncodingError

HEALTHY = "HEALTHY"
DEGRADED = "DEGRADED"
FAILED_OVER = "FAILED_OVER"
FAILED = "FAILED"

_STATE_ORDER = {HEALTHY: 0, DEGRADED: 1, FAILED_OVER: 2, FAILED: 3}


def health_rank(state: str) -> int:
    """Severity order for status roll-ups (worst state wins)."""
    return _STATE_ORDER.get(state, 0)


class KernelFailedError(RuntimeError):
    """Conflict kernel AND its fallback are broken — commits cannot be
    checked on this resolver. The structured (kernel.health=FAILED +
    SevError trace) replacement for the old opaque ``resolver backend
    failed`` RuntimeError."""


class WriteRangeJournal:
    """Bounded, version-ordered journal of committed write conflict ranges
    inside the MVCC window. ``floor`` is the first version whose committed
    history is fully journaled: replay onto a backend cleared at ``floor``
    reconstructs verdict-equivalent history for every snapshot >= floor,
    while older snapshots become TOO_OLD (a conservative abort, never a
    false commit)."""

    def __init__(self, capacity: int, floor: int = 0):
        self.capacity = max(int(capacity), 1)
        self.entries: deque = deque()  # (version, [(begin, end), ...]) ascending
        self.floor = floor
        self.dropped = 0

    def __len__(self) -> int:
        return len(self.entries)

    def record(self, version: int, ranges: list) -> None:
        if ranges:
            self.entries.append((version, list(ranges)))
        while len(self.entries) > self.capacity:
            v, _ = self.entries.popleft()
            self.floor = max(self.floor, v + 1)
            self.dropped += 1

    def trim_below(self, version: int) -> None:
        """MVCC GC: snapshots below ``version`` are TOO_OLD on any backend,
        so their history can never flip a verdict."""
        while self.entries and self.entries[0][0] < version:
            self.entries.popleft()
        self.floor = max(self.floor, version)

    def reset(self, floor: int) -> None:
        self.entries.clear()
        self.floor = floor

    def head_version(self) -> int:
        return self.entries[-1][0] if self.entries else self.floor

    def replay_into(self, cs) -> None:
        """Reconstruct history on a fresh backend: blind write-only txns
        always commit, so the backend ends with exactly the journaled
        committed writes at their original versions."""
        cs.clear(self.floor)
        work = [
            ([CommitTransaction(write_conflict_ranges=list(ranges))], v, 0)
            for v, ranges in self.entries
        ]
        if not work:
            return
        if hasattr(cs, "detect_many"):
            cs.detect_many(work)  # one device dispatch for the whole replay
        else:
            for txns, v, old in work:
                cs.detect_batch(txns, now=v, new_oldest_version=old)


class _GuardMetrics:
    """``resolver.metrics`` → ``kernel`` section: the inner device
    KernelMetrics snapshot (occupancy, replays, transfer bytes, …) merged
    with the guard's ``health`` subsection, so status/cli/bench consumers
    keep one well-known place to look."""

    def __init__(self, guard: "GuardedConflictSet"):
        self._guard = guard

    def snapshot(self) -> dict:
        inner = getattr(self._guard.primary, "metrics", None)
        out = inner.snapshot() if inner is not None else {}
        out["health"] = self._guard.health_snapshot()
        # encode-executor queue depth (the resolver owns the executor and
        # wires the callable; guard-level so it survives backend swaps)
        out["encodeQueueDepth"] = int(self._guard.encode_queue_fn())
        return out


class GuardedConflictSet:
    """The conflict set the resolver actually holds. Delegates to the
    current backend (device while healthy, native/oracle after failover)
    and owns journal + health + recovery. The async-dispatch protocol is
    emulated over sync fallbacks so the resolver's pipelined path keeps
    working across a failover."""

    def __init__(
        self,
        backend: str,
        knobs: Knobs = None,
        uid: str = "",
        fault_injector=None,
        **backend_kw,
    ):
        self.knobs = knobs or Knobs()
        self.kind = backend
        self.uid = uid
        self._kw = dict(backend_kw)
        self._injector = fault_injector
        self.journal = WriteRangeJournal(self.knobs.CONFLICT_JOURNAL_CAPACITY)
        self.health = HEALTHY
        self.last_error = ""
        self._strikes = 0
        self._gen = 0  # bumped on every backend swap (stale-encoding fence)
        self._last_probe = None
        # health counters (surfaced via health_snapshot → kernel.health)
        self.c_faults = 0
        self.c_retries = 0
        self.c_deadline_hits = 0
        self.c_rebuilds = 0
        self.c_failovers = 0
        self.c_reprobes = 0
        self.c_probe_failures = 0
        self.c_promotions = 0
        self.c_journal_replays = 0
        # wired by the resolver to its encode executor's queue depth
        self.encode_queue_fn = lambda: 0
        self.metrics = _GuardMetrics(self)
        self._cs = None  # set below; _note_fault may run before it exists
        try:
            self._cs = self._build_primary()
        except Cancelled:
            raise
        except BaseException as e:
            # device dead at boot (lost tunnel): start failed over rather
            # than refuse the role — the journal is empty, so the fallback
            # is exactly equivalent
            self._note_fault(e)
            self._failover()
        self.pipelined = hasattr(self._cs, "detect_many_encoded_async") or (
            self.health == FAILED_OVER and backend in ("tpu", "tpu1", "mesh")
        )

    # -- backend construction / swap ------------------------------------------

    @property
    def primary(self):
        """The current backend, unwrapped of the fault injector (for
        isinstance checks and metrics access)."""
        return getattr(self._cs, "inner", self._cs)

    @property
    def backend_name(self) -> str:
        return type(self.primary).__name__ if self._cs is not None else "none"

    @property
    def failed(self) -> bool:
        return self.health == FAILED

    def _build_primary(self):
        return new_conflict_set(
            self.kind, fault_injector=self._injector, **self._kw
        )

    def _swap(self, cs, health: str) -> None:
        self._cs = cs
        self._gen += 1
        self.health = health
        if health == HEALTHY:
            self.last_error = ""

    def _note_fault(self, err) -> None:
        self.c_faults += 1
        self._strikes += 1
        if isinstance(err, KernelTimeoutError) and "recovery" in str(err):
            # sync-path hang (no resolver deadline wait counted it)
            self.c_deadline_hits += 1
        self.last_error = repr(err)
        if self.health == HEALTHY:
            self.health = DEGRADED
        trace(
            SevWarn,
            "KernelFault",
            "",
            Resolver=self.uid,
            Backend=self.backend_name,
            Strikes=self._strikes,
            Health=self.health,
            Err=repr(err),
        )

    def note_retry(self) -> None:
        self.c_retries += 1
        if self.health == HEALTHY:
            self.health = DEGRADED

    def note_deadline(self) -> None:
        self.c_deadline_hits += 1

    def note_ok(self) -> None:
        """A batch completed through the normal device path: strikes reset
        and a DEGRADED kernel is healthy again."""
        self._strikes = 0
        if self.health == DEGRADED:
            self.health = HEALTHY
            self.last_error = ""

    def _hard_fail(self, err) -> None:
        self.health = FAILED
        self.last_error = repr(err)
        trace(
            SevError,
            "KernelFailed",
            "",
            Resolver=self.uid,
            Err=repr(err),
        )

    # -- journal ---------------------------------------------------------------

    def record_committed(self, version: int, ranges: list, oldest: int) -> None:
        """Called once per resolved batch, in version order (the resolver's
        gates guarantee it): journal this batch's committed write ranges
        and GC the journal to the MVCC window."""
        self.journal.record(version, ranges)
        if oldest > 0:
            self.journal.trim_below(oldest)

    def _replayed(self, cs):
        self.journal.replay_into(cs)
        self.c_journal_replays += 1
        return cs

    def _check_stall(self, cs) -> None:
        """Sync paths can't await an injected stall: a finite stall is just
        latency (ignore), an infinite one is the hang fault."""
        take = getattr(cs, "take_stall", None)
        stall = take() if take is not None else None
        if stall == float("inf"):
            raise KernelTimeoutError("injected hang during recovery dispatch")

    # -- recovery / failover / re-promotion -------------------------------------

    def recover_resolve(self, transactions, version, new_oldest, err=None):
        """The device path failed for this batch (deadline, device loss,
        exhausted retries, arbitrary backend exception): re-resolve it on a
        backend rebuilt from the journal. Strikes escalate to failover; if
        even the fallback fails, health=FAILED and KernelFailedError raises
        (typed, SevError-traced — never an opaque RuntimeError)."""
        if err is not None:
            self._note_fault(err)
        if self.health not in (FAILED_OVER, FAILED):
            attempts = self.knobs.CONFLICT_REBUILD_ATTEMPTS
            for _attempt in range(attempts):
                if self._strikes >= self.knobs.CONFLICT_FAILOVER_STRIKES:
                    break
                try:
                    cs = self._replayed(self._build_primary())
                    verdicts = cs.detect_batch(
                        transactions, now=version, new_oldest_version=new_oldest
                    )
                    self._check_stall(cs)
                except Cancelled:
                    raise
                except BaseException as e:
                    self._note_fault(e)
                    continue
                self.c_rebuilds += 1
                self._swap(cs, DEGRADED)  # healthy again after a clean batch
                trace(
                    SevInfo,
                    "KernelRebuilt",
                    "",
                    Resolver=self.uid,
                    Version=version,
                    JournalDepth=len(self.journal),
                )
                return verdicts
        if self.health != FAILED_OVER:
            self._failover()
        try:
            return self._cs.detect_batch(
                transactions, now=version, new_oldest_version=new_oldest
            )
        except Cancelled:
            raise
        except BaseException as e:
            self._hard_fail(e)
            raise KernelFailedError(
                f"conflict kernel and fallback both failed: {e!r}"
            ) from e

    def _failover(self) -> None:
        """Construct the fallback (native skip list, oracle as backstop),
        replay the journal so verdict semantics carry over, and flip the
        state machine to FAILED_OVER."""
        for kind in ("native", "oracle"):
            try:
                cs = self._replayed(new_conflict_set(kind))
            except Cancelled:
                raise
            except BaseException:
                continue  # no native toolchain → oracle backstop
            self._swap(cs, FAILED_OVER)
            self.c_failovers += 1
            self._last_probe = loop_now()
            trace(
                SevWarn,
                "KernelFailover",
                "",
                Resolver=self.uid,
                Fallback=type(cs).__name__,
                JournalDepth=len(self.journal),
                JournalFloor=self.journal.floor,
            )
            return
        err = RuntimeError("no fallback conflict backend could be built")
        self._hard_fail(err)
        raise KernelFailedError(str(err))

    def _maybe_promote(self) -> None:
        """While failed over: periodically rebuild the device backend from
        the journal and smoke-probe it; on success the device takes back
        over (HEALTHY)."""
        if self.health != FAILED_OVER:
            return
        t = loop_now()
        if (
            self._last_probe is not None
            and t - self._last_probe < self.knobs.CONFLICT_REPROBE_INTERVAL
        ):
            return
        self._last_probe = t
        self.c_reprobes += 1
        try:
            cs = self._replayed(self._build_primary())
            cs.detect_batch(
                [], now=self.journal.head_version(), new_oldest_version=0
            )
            self._check_stall(cs)
        except Cancelled:
            raise
        except BaseException as e:
            self.c_probe_failures += 1
            self.last_error = repr(e)
            return
        self._swap(cs, HEALTHY)
        self._strikes = 0
        self.c_promotions += 1
        trace(
            SevInfo,
            "KernelPromoted",
            "",
            Resolver=self.uid,
            Backend=self.backend_name,
            JournalDepth=len(self.journal),
        )

    # -- ConflictSet protocol (delegation + async emulation) ---------------------

    @property
    def oldest_version(self) -> int:
        return self._cs.oldest_version

    def warm_compile(self) -> None:
        fn = getattr(self._cs, "warm_compile", None)
        if fn is None:
            return
        try:
            fn()
        except Cancelled:
            raise
        except Exception as e:
            # warm compile is an optimization, never a boot failure
            trace(SevWarn, "KernelWarmCompileFailed", "", Resolver=self.uid, Err=repr(e))

    def take_stall(self):
        take = getattr(self._cs, "take_stall", None)
        return take() if take is not None else None

    def clear(self, version: int) -> None:
        self.journal.reset(version)
        try:
            self._cs.clear(version)
        except Cancelled:
            raise
        except BaseException as e:
            self._note_fault(e)
            # the journal is empty at `version`: the fallback (or a later
            # promoted device) starts from exactly the cleared state
            self._failover()

    def prepare(self, now_version: int) -> None:
        fn = getattr(self._cs, "prepare", None)
        if fn is not None:
            fn(now_version)

    def encode(self, transactions):
        """Generation-stamped, TIMED encoding: returns ((gen, payload),
        encode_seconds). The resolver runs this on its encode executor —
        the double-buffered pipeline's off-loop thread — and uses the
        duration to compute how much encode time was hidden behind the
        previous batch's device scan (encodeOverlapSeconds). A backend
        swap between encode and dispatch surfaces as a transient
        StaleEncodingError (the resolver re-encodes)."""
        t0 = time.perf_counter()  # flowlint: disable=det-wall-clock — phase evidence
        fn = getattr(self._cs, "encode", None)
        payload = fn(transactions) if fn is not None else list(transactions)
        return (self._gen, payload), time.perf_counter() - t0  # flowlint: disable=det-wall-clock — phase evidence

    def note_encode_overlap(self, encode_s: float, stalled_s: float) -> None:
        """Per-batch encode-overlap evidence: of ``encode_s`` seconds of
        host encode, ``stalled_s`` actually delayed the dispatch — the
        rest was hidden behind the in-flight device scan."""
        m = getattr(self.primary, "metrics", None)
        if m is not None and hasattr(m, "encode_overlap_s"):
            m.encode_overlap_s.add(max(0.0, encode_s - stalled_s))

    def detect_many_encoded_async(self, work):
        self._maybe_promote()
        cs = self._cs
        for (gen, _payload), _v, _old in work:
            if gen != self._gen:
                raise StaleEncodingError(
                    "stale encoding: backend swapped after encode()"
                )
        if hasattr(cs, "detect_many_encoded_async"):
            return cs.detect_many_encoded_async(
                [(payload, v, old) for (_g, payload), v, old in work]
            )
        # sync emulation over the fallback: resolve now, hand back a thunk
        # (the resolver's pipelined path keeps one shape across failover)
        outs = [
            cs.detect_batch(payload, now=v, new_oldest_version=old)
            for (_g, payload), v, old in work
        ]
        return lambda: outs

    def detect_batch(self, transactions, now, new_oldest_version):
        """The resolver's non-pipelined path (and recovery re-resolves):
        guarded so a backend error degrades instead of poisoning."""
        if self.failed:
            raise KernelFailedError(f"conflict kernel failed: {self.last_error}")
        self._maybe_promote()
        try:
            verdicts = self._cs.detect_batch(
                transactions, now=now, new_oldest_version=new_oldest_version
            )
            self._check_stall(self._cs)
            return verdicts
        except Cancelled:
            raise
        except KernelFailedError:
            raise
        except BaseException as e:
            return self.recover_resolve(
                transactions, now, new_oldest_version, err=e
            )

    # -- observability -----------------------------------------------------------

    def health_snapshot(self) -> dict:
        return {
            "state": self.health,
            "backend": self.backend_name,
            "strikes": self._strikes,
            "faults": self.c_faults,
            "retries": self.c_retries,
            "deadlineHits": self.c_deadline_hits,
            "deviceRebuilds": self.c_rebuilds,
            "failovers": self.c_failovers,
            "reprobes": self.c_reprobes,
            "probeFailures": self.c_probe_failures,
            "promotions": self.c_promotions,
            "journalDepth": len(self.journal),
            "journalFloor": self.journal.floor,
            "journalReplays": self.c_journal_replays,
            "lastError": self.last_error,
        }
