"""Host glue for the TPU conflict-detection kernel.

``TpuConflictSet`` implements the ConflictSet interface (conflict/api.py) on
top of the functional device index in tpu_index.py:

- encodes byte-string conflict ranges to fixed-width lane codes
  (conflict/keys.py), padding batches to power-of-two buckets so jit
  specializations stay bounded;
- tracks the int64→int32 version rebasing origin (device versions are
  offsets; the host rebases when the offset approaches int32 range);
- pre-grows index capacity before a batch could overflow it (merged boundary
  count is at most n + 2·writes, so growth never needs a device round-trip
  retry);
- converts device verdicts back to the API's Verdict enum.

The same class runs unmodified on CPU (JAX_PLATFORMS=cpu) — that is the
deterministic simulation twin the test suite uses, mirroring how the
reference runs its resolver under deterministic simulation (SURVEY.md §4).
"""

from __future__ import annotations

import numpy as np

from . import keys as K
from . import tpu_index as TI
from .api import CommitTransaction, ConflictSet, Verdict

_INT32_REBASE_THRESHOLD = 1 << 30


def _bucket(n: int, floor: int = 32) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


class TpuConflictSet(ConflictSet):
    def __init__(self, key_width: int = K.DEFAULT_KEY_WIDTH, capacity: int = 1 << 14):
        super().__init__()
        self._width = key_width
        self._lanes = K.lanes_for_width(key_width)
        self._capacity = capacity
        self._state = TI.make_state(capacity, self._lanes)
        # Conservative host-side bound on the device boundary count (reading
        # state.n would force a device sync per batch). n only grows by at
        # most 2·writes per batch and GC only shrinks it.
        self._n_bound = 1
        # Device versions are stored as (version - base); base starts at -1 so
        # every live version maps to >= 1 (0 means "never written").
        self._base = -1
        self._base_epoch = 0

    # -- ConflictSet interface ------------------------------------------------

    def clear(self, version: int) -> None:
        self._state = TI.make_state(self._capacity, self._lanes)
        self._n_bound = 1
        self._base = version - 1
        self._base_epoch += 1
        self.oldest_version = version

    def detect_batch(
        self, transactions: list[CommitTransaction], now: int, new_oldest_version: int
    ) -> list[Verdict]:
        return self.detect_batch_async(transactions, now, new_oldest_version)()

    def detect_batch_async(
        self, transactions: list[CommitTransaction], now: int, new_oldest_version: int
    ):
        """Dispatch one batch without waiting for the device; returns a
        zero-arg callable yielding the verdict list.

        Under the axon tunnel a host↔device round trip costs ~70ms, so the
        resolver pipelines: dispatch batch k+1 while k's verdicts are still
        in flight (the reference's phase-gated batch pipelining,
        MasterProxyServer.actor.cpp:353)."""
        self._maybe_rebase(now)  # before encoding: snapshots are base-relative
        batch, num_txns = self._encode(transactions)
        self._ensure_capacity(2 * int(batch.wb.shape[0]))

        # TOO_OLD gates on the pre-batch horizon; GC applies the post-batch
        # horizon — matching the reference's ordering (addTransaction checks
        # cs->oldestVersion, SkipList.cpp:989; removeBefore at :1195).
        horizon = max(self.oldest_version, new_oldest_version)
        state, verdicts, _needed = TI.resolve_batch(
            self._state,
            batch,
            np.int32(now - self._base),
            np.int32(max(self.oldest_version - self._base, 0)),
            np.int32(max(horizon - self._base, 0)),
            num_txns,
        )
        self._state = state
        self._n_bound = min(
            self._n_bound + 2 * int(batch.wb.shape[0]), self._capacity
        )
        self.oldest_version = horizon
        n = len(transactions)

        def result(verdicts=verdicts, n=n):
            out = np.asarray(verdicts[:n])
            return [Verdict(int(v)) for v in out]

        return result

    def detect_many(
        self, work: list[tuple[list[CommitTransaction], int, int]]
    ) -> list[list[Verdict]]:
        """Resolve many (transactions, now, new_oldest) batches in one device
        dispatch via lax.scan (TI.resolve_many). All batches are padded to
        shared bucket shapes."""
        if not work:
            return []
        self._maybe_rebase(max(now for _, now, _2 in work))
        return self.detect_many_encoded(
            [(self.encode(txs), now, old) for txs, now, old in work]
        )

    def encode(self, transactions: list[CommitTransaction]):
        """Pre-encode a batch for detect_many_encoded. Encodings are
        horizon-independent but base-relative: a version rebase invalidates
        them (guarded via the epoch stamp)."""
        b, T = self._encode(transactions)
        return b, T, len(transactions), self._base_epoch

    def detect_many_encoded(self, work) -> list[list[Verdict]]:
        """work: list of (encoded, now, new_oldest), encoded from encode()."""
        if not work:
            return []
        encoded = []
        counts = []
        for (b, T, n_real, epoch), now, new_oldest in work:
            if epoch != self._base_epoch:
                raise RuntimeError(
                    "stale encoding: version base was rebased after encode()"
                )
            old_pre = self.oldest_version
            horizon = max(self.oldest_version, new_oldest)
            encoded.append((b, T, now, old_pre, horizon))
            counts.append(n_real)
            self.oldest_version = horizon
        return self._detect_encoded(encoded, counts)

    def _detect_encoded(self, encoded, counts) -> list[list[Verdict]]:
        self._ensure_capacity(sum(2 * int(b.wb.shape[0]) for b, *_ in encoded))

        # Re-pad every batch to the group-max bucket shapes and stack.
        Tm = max(T for _, T, *_ in encoded)
        Rm = max(int(b.rb.shape[0]) for b, *_ in encoded)
        Wm = max(int(b.wb.shape[0]) for b, *_ in encoded)
        stacked = TI.Batch(
            rb=np.stack([self._pad2(b.rb, Rm) for b, *_ in encoded]),
            re=np.stack([self._pad2(b.re, Rm) for b, *_ in encoded]),
            r_snap=np.stack([self._pad1(b.r_snap, Rm) for b, *_ in encoded]),
            r_owner=np.stack([self._pad1(b.r_owner, Rm) for b, *_ in encoded]),
            wb=np.stack([self._pad2(b.wb, Wm) for b, *_ in encoded]),
            we=np.stack([self._pad2(b.we, Wm) for b, *_ in encoded]),
            w_owner=np.stack([self._pad1(b.w_owner, Wm) for b, *_ in encoded]),
            t_snap=np.stack([self._pad1(b.t_snap, Tm) for b, *_ in encoded]),
            t_has_reads=np.stack(
                [self._pad1(b.t_has_reads, Tm) for b, *_ in encoded]
            ),
        )
        nows = np.asarray(
            [now - self._base for _, _, now, *_ in encoded], np.int32
        )
        olds_pre = np.asarray(
            [max(p - self._base, 0) for *_, p, _h in encoded], np.int32
        )
        olds_post = np.asarray(
            [max(h - self._base, 0) for *_, h in encoded], np.int32
        )
        state, verdicts, _needed = TI.resolve_many(
            self._state, stacked, nows, olds_pre, olds_post, Tm
        )
        self._state = state
        for b, *_ in encoded:
            self._n_bound = min(
                self._n_bound + 2 * int(b.wb.shape[0]), self._capacity
            )
        out = np.asarray(verdicts)
        return [
            [Verdict(int(v)) for v in out[g, : counts[g]]]
            for g in range(len(encoded))
        ]

    @staticmethod
    def _pad2(a: np.ndarray, size: int) -> np.ndarray:
        if a.shape[0] == size:
            return a
        out = np.full((size, a.shape[1]), 0xFFFFFFFF, dtype=a.dtype)
        out[: a.shape[0]] = a
        return out

    @staticmethod
    def _pad1(a: np.ndarray, size: int) -> np.ndarray:
        if a.shape[0] == size:
            return a
        out = np.zeros((size,), dtype=a.dtype)
        out[: a.shape[0]] = a
        return out

    # -- internals ------------------------------------------------------------

    def _encode(self, transactions):
        """Encode a batch to device arrays. Horizon-independent: TOO_OLD is
        determined on device from per-transaction snapshots, so pre-encoded
        batches stay valid as the horizon advances. Only a version rebase
        invalidates an encoding (checked via _base_epoch)."""
        reads: list[tuple[bytes, bytes, int, int]] = []
        writes: list[tuple[bytes, bytes, int]] = []
        t_snap_l = []
        t_has_reads_l = []
        for t, tr in enumerate(transactions):
            snap = max(tr.read_snapshot - self._base, 0)
            t_snap_l.append(snap)
            t_has_reads_l.append(bool(tr.read_conflict_ranges))
            for (b, e) in tr.read_conflict_ranges:
                reads.append((b, e, snap, t))
            for (b, e) in tr.write_conflict_ranges:
                writes.append((b, e, t))

        T = _bucket(max(len(transactions), 1))
        R = _bucket(max(len(reads), 1))
        W = _bucket(max(len(writes), 1))
        sent = K.max_sentinel(self._width)

        def pad_codes(ks: list[bytes], size: int, round_up: bool) -> np.ndarray:
            out = np.tile(sent, (size, 1))
            if ks:
                out[: len(ks)] = K.encode_keys(ks, self._width, round_up=round_up)
            return out

        # Range begins round down, ends round up: a truncated range can only
        # widen (conflict/keys.py), never collapse to empty.
        rb = pad_codes([r[0] for r in reads], R, False)
        re = pad_codes([r[1] for r in reads], R, True)
        # padded slots: rb == re == sentinel → inactive (rb >= re)
        r_snap = np.zeros(R, np.int32)
        r_snap[: len(reads)] = [r[2] for r in reads]
        r_owner = np.zeros(R, np.int32)
        r_owner[: len(reads)] = [r[3] for r in reads]

        wb = pad_codes([w[0] for w in writes], W, False)
        we = pad_codes([w[1] for w in writes], W, True)
        w_owner = np.zeros(W, np.int32)
        w_owner[: len(writes)] = [w[2] for w in writes]

        t_snap = np.zeros(T, np.int32)
        t_snap[: len(t_snap_l)] = t_snap_l
        t_has_reads = np.zeros(T, bool)
        t_has_reads[: len(t_has_reads_l)] = t_has_reads_l

        batch = TI.Batch(
            rb=rb, re=re, r_snap=r_snap, r_owner=r_owner,
            wb=wb, we=we, w_owner=w_owner,
            t_snap=t_snap, t_has_reads=t_has_reads,
        )
        return batch, T

    def _maybe_rebase(self, now: int) -> None:
        if now - self._base < _INT32_REBASE_THRESHOLD:
            return
        new_base = self.oldest_version - 1
        delta = new_base - self._base
        if delta > 0:
            self._state = TI.rebase(self._state, np.int32(delta))
            self._base = new_base
            self._base_epoch += 1

    def _ensure_capacity(self, extra: int) -> None:
        # needed <= n + extra; grow until that fits (keeps resolve_*'s state
        # donation safe — no retry path). Only when the conservative bound is
        # tight do we pay one device sync to learn the true n.
        if self._n_bound + extra <= self._capacity:
            return
        self._n_bound = max(int(self._state.n), 1)
        while self._n_bound + extra > self._capacity:
            self._capacity *= 2
            self._state = TI.grow_state(self._state, self._capacity)
