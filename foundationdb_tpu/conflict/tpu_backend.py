"""Host glue for the bucket-grid TPU conflict kernel (conflict/grid.py).

``TpuConflictSet`` implements the ConflictSet interface (conflict/api.py):

- encodes byte-string conflict ranges to fixed-width lane codes
  (conflict/keys.py), padded *per transaction* (KR read / KW write range
  slots) so the kernel's intra-batch check is a dense [T, T] compare;
- tracks the int64→int32 version rebasing origin;
- owns the **reshard loop**: the device returns per-group bucket pressure;
  on overflow the host rebuilds the grid under fresh pivots (quantiles of
  the live boundary set / its key sample) from a pre-group snapshot and
  replays the group — verdicts are deterministic, so callers never see it.
  Proactive reshards run between groups when pressure nears the slot
  capacity, growing the bucket count when the index genuinely fills.

The same class runs unmodified on CPU (JAX_PLATFORMS=cpu) — the
deterministic simulation twin the test suite uses (SURVEY.md §4: TPU
kernels must have a sim-mode CPU twin).
"""

# flowlint: disable-file=det-wall-clock — KernelMetrics phase timings
# measure HOST wall time of device work (encode/dispatch/collect/reshard)
# on purpose; they are evidence counters, never inputs to sim scheduling
# (same-seed replay is unaffected: no control flow reads them).

from __future__ import annotations

import time

import jax
import numpy as np

from . import grid as G
from . import keys as K
from ..runtime.stats import CounterCollection
from .api import CommitTransaction, ConflictSet, Verdict
from .faults import StaleEncodingError

_INT32_REBASE_THRESHOLD = 1 << 30
_SAMPLE_CAP = 131072
_VERDICT_TABLE = [Verdict(i) for i in range(3)]

# occupancy-driven reshard defaults (resolver threads the CONFLICT_RESHARD_*
# knobs in): rebalance when collected pressure crosses this fraction of the
# slot ceiling; grow the bucket count when the live-row fill fraction does
DEFAULT_RESHARD_PRESSURE = 0.75
DEFAULT_GROW_FILL = 0.5
_RECENT_SHAPES = 4  # stacked shapes re-warmed after a grid-shape change


class KernelMetrics:
    """The conflict kernel's CounterCollection (shared by the single-device
    and mesh backends) — per-phase wall-time latency samples, overflow-
    replay / reshard / growth counters, host↔device transfer bytes, and a
    jit-cache hit/miss tally (a new stacked shape = a new XLA program).
    Wall time is real time (``time.perf_counter``), NOT sim time: these
    phases measure actual device/tunnel work, which virtual time cannot
    see. Surfaced through ``resolver.metrics`` / the status document's
    resolver sections, and embedded into bench captures."""

    def __init__(self, ident: str = ""):
        self.collection = CounterCollection("ConflictKernel", ident)
        c = self.collection.counter
        self.groups = c("groups")
        self.batches = c("batches")
        self.txns = c("txns")
        self.dispatches = c("deviceDispatches")
        self.overflow_replays = c("overflowReplays")
        self.replayed_groups = c("replayedGroups")
        self.reshards_device = c("reshardsDevice")
        self.reshards_host = c("reshardsHost")
        self.reshards_proactive = c("reshardsProactive")
        self.capacity_growths = c("capacityGrowths")
        self.rebases = c("rebases")
        self.h2d_bytes = c("hostToDeviceBytes")
        self.d2h_bytes = c("deviceToHostBytes")
        self.jit_hits = c("jitCacheHits")
        self.jit_misses = c("jitCacheMisses")
        self.warm_compiles = c("warmCompiles")
        self.encode_s = self.collection.latency("encodeSeconds")
        self.encode_overlap_s = self.collection.latency("encodeOverlapSeconds")
        self.dispatch_s = self.collection.latency("dispatchSeconds")
        self.collect_s = self.collection.latency("collectSeconds")
        self.reshard_s = self.collection.latency("reshardSeconds")
        self.warm_s = self.collection.latency("warmCompileSeconds")
        self._shapes: set = set()

    def note_shape(self, key, warm: bool = False) -> None:
        """Host-side jit-cache model: a (G, T, KR, KW, B) stacked shape
        seen before hits the compile cache; a fresh one forces a compile.
        ``warm=True`` (warm_compile / post-reshard re-warm) seeds the cache
        without counting a dispatch-path hit or miss — those tallies
        measure what the LIVE pipeline paid, which is how the steady-state
        acceptance (`hit rate ≈ 1.0`) reads them; warm work is accounted
        in ``warmCompiles``/``warmCompileSeconds`` instead."""
        if key in self._shapes:
            if not warm:
                self.jit_hits.add()
        else:
            self._shapes.add(key)
            if not warm:
                self.jit_misses.add()

    def gauge(self, name: str, fn) -> None:
        self.collection.gauge(name, fn)

    def snapshot(self) -> dict:
        return self.collection.snapshot()


def tree_nbytes(tree) -> int:
    """Total bytes of every array leaf (host↔device transfer accounting)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def _bucket(n: int, floor: int = 1) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


class KeyReservoir:
    """Bounded reservoir of raw endpoint keys feeding sample-seeded pivot
    selection — shared by the single-device and mesh backends."""

    __slots__ = ("keys", "_skip")

    def __init__(self):
        self.keys: list[bytes] = []
        self._skip = 0

    def add(self, key: bytes) -> None:
        self._skip += 1
        if len(self.keys) < _SAMPLE_CAP:
            self.keys.append(key)
        elif self._skip % 17 == 0:
            self.keys[self._skip % _SAMPLE_CAP] = key

    def __bool__(self) -> bool:
        return bool(self.keys)


def encode_transactions(
    transactions, width: int, base: int, sample_cb=None
) -> G.Batch:
    """Encode a commit batch into the kernel's padded Batch (host numpy).
    Shared by the single-device and mesh backends."""
    n = max(len(transactions), 1)
    # pad T to a coarse grid: powers of two up to 512, then multiples
    # of 512 — a 2500-txn batch costs 2560 rows of work, not 4096
    # (every kernel phase scales with T; the compile cache still only
    # sees a handful of shapes)
    T = _bucket(n, 8) if n <= 512 else ((n + 511) // 512) * 512
    KR = _bucket(
        max((len(t.read_conflict_ranges) for t in transactions), default=0)
        or 1
    )
    KW = _bucket(
        max((len(t.write_conflict_ranges) for t in transactions), default=0)
        or 1
    )
    sent = K.max_sentinel(width)
    rb = np.tile(sent, (T, KR, 1))
    re = np.tile(sent, (T, KR, 1))
    wb = np.tile(sent, (T, KW, 1))
    we = np.tile(sent, (T, KW, 1))
    t_snap = np.zeros(T, np.int32)
    t_has_reads = np.zeros(T, bool)

    r_begins, r_ends, w_begins, w_ends = [], [], [], []
    r_pos, w_pos = [], []
    for t, tr in enumerate(transactions):
        t_snap[t] = max(tr.read_snapshot - base, 0)
        t_has_reads[t] = bool(tr.read_conflict_ranges)
        for i, (b, e) in enumerate(tr.read_conflict_ranges):
            r_begins.append(b)
            r_ends.append(e)
            r_pos.append((t, i))
        for i, (b, e) in enumerate(tr.write_conflict_ranges):
            w_begins.append(b)
            w_ends.append(e)
            w_pos.append((t, i))
            if sample_cb is not None:
                sample_cb(b)
                sample_cb(e)

    if r_begins:
        cb = K.encode_keys(r_begins, width, round_up=False)
        ce = K.encode_keys(r_ends, width, round_up=True)
        for (t, i), eb, ee in zip(r_pos, cb, ce):
            rb[t, i] = eb
            re[t, i] = ee
    if w_begins:
        cb = K.encode_keys(w_begins, width, round_up=False)
        ce = K.encode_keys(w_ends, width, round_up=True)
        for (t, i), eb, ee in zip(w_pos, cb, ce):
            wb[t, i] = eb
            we[t, i] = ee

    return G.Batch(
        rb=rb, re=re, wb=wb, we=we, t_snap=t_snap, t_has_reads=t_has_reads
    )


def stack_batches(batches: list[G.Batch], lanes: int) -> G.Batch:
    """Stack host-encoded batches into one [G, ...] group (host numpy),
    padding every leaf to the group's max (T, KR, KW) with sentinel rows —
    the payload a single stacked device dispatch consumes. Shared by the
    single-device and mesh backends."""
    T = max(b.rb.shape[0] for b in batches)
    KR = max(b.rb.shape[1] for b in batches)
    KW = max(b.wb.shape[1] for b in batches)
    sent_row = np.full(lanes, 0xFFFFFFFF, dtype=np.uint32)

    def pad3(a, k):
        t, kk, _L = a.shape
        if t == T and kk == k:
            return a
        out = np.tile(sent_row, (T, k, 1))
        out[:t, :kk] = a
        return out

    def pad1(a, dtype):
        if a.shape[0] == T:
            return a
        out = np.zeros(T, dtype)
        out[: a.shape[0]] = a
        return out

    return G.Batch(
        rb=np.stack([pad3(b.rb, KR) for b in batches]),
        re=np.stack([pad3(b.re, KR) for b in batches]),
        wb=np.stack([pad3(b.wb, KW) for b in batches]),
        we=np.stack([pad3(b.we, KW) for b in batches]),
        t_snap=np.stack([pad1(b.t_snap, np.int32) for b in batches]),
        t_has_reads=np.stack([pad1(b.t_has_reads, bool) for b in batches]),
    )


def sentinel_batch(T: int, KR: int, KW: int, lanes: int) -> G.Batch:
    """An all-sentinel (fully inactive) batch at an exact padded shape —
    the zero-cost payload warm compiles and re-warms dispatch against."""
    sent_row = np.full(lanes, 0xFFFFFFFF, dtype=np.uint32)
    return G.Batch(
        rb=np.tile(sent_row, (T, KR, 1)),
        re=np.tile(sent_row, (T, KR, 1)),
        wb=np.tile(sent_row, (T, KW, 1)),
        we=np.tile(sent_row, (T, KW, 1)),
        t_snap=np.zeros(T, np.int32),
        t_has_reads=np.zeros(T, bool),
    )


def _pick_pivots(
    cands: np.ndarray, n_buckets: int, lanes: int, lo: np.ndarray = None
) -> np.ndarray:
    """≤ n_buckets-1 quantile pivots from sorted unique candidate codes
    (uint32[N, lanes], all strictly above ``lo``); bucket 0 always starts
    at ``lo`` (the empty key for a full-range grid, the partition's lower
    bound for a mesh shard)."""
    if lo is None:
        lo = np.zeros((1, lanes), dtype=np.uint32)
    lo = np.asarray(lo, dtype=np.uint32).reshape(1, lanes)
    n_piv = min(n_buckets - 1, len(cands))
    if n_piv <= 0:
        return lo
    step = len(cands) / (n_piv + 1)
    idx = np.minimum(
        (np.arange(1, n_piv + 1) * step).astype(np.int64), len(cands) - 1
    )
    idx = np.unique(idx)
    return np.concatenate([lo, cands[idx]], axis=0)


class TpuConflictSet(ConflictSet):
    def __init__(
        self,
        key_width: int = K.DEFAULT_KEY_WIDTH,
        capacity: int = 1 << 14,
        reshard_pressure: float = DEFAULT_RESHARD_PRESSURE,
        grow_fill: float = DEFAULT_GROW_FILL,
    ):
        super().__init__()
        self._width = key_width
        self._lanes = K.lanes_for_width(key_width)
        self._reshard_pressure = reshard_pressure
        self._grow_fill = grow_fill
        # grid shape: B buckets × S slots with ~2× slack over `capacity`
        # boundaries. Shallow buckets (S=32) over twice as many pivots:
        # every per-bucket pass (merge sort window, history window
        # gathers) scales with S, while the two-level rank cost grows
        # only ~√2 with B — measured ~25% off the per-batch budget vs
        # the round-3 S=64 shape at equal capacity.
        self._B = _bucket(max(8, capacity // 16))
        self._S = 32
        self._state = G.make_state(self._B, self._S, self._lanes)
        self._base = -1  # device versions are (version - base); 0 = never
        self._base_epoch = 0
        # reservoir of raw endpoint keys for pivot selection
        self._sample = KeyReservoir()
        self._resharded_once = False
        self._rebalance_wanted = False
        # stacked shapes the live pipeline dispatched lately — re-warmed
        # whenever the grid shape (B) changes so post-reshard/post-grow
        # dispatches stay jit-cache hits
        self._recent_shapes: list[tuple] = []
        # dispatched-but-uncollected groups, in dispatch order
        self._inflight: list[dict] = []
        # kernel observability (ISSUE 5): counters/samples every perf PR
        # cites instead of tunnel-dependent bench reruns
        self.metrics = KernelMetrics()
        self._last_pressure = (0, 0)  # (max staged, max kept) at last collect
        self.metrics.gauge("occupancy", lambda: G.occupancy_stats(self._state))
        self.metrics.gauge("stagingSlots", lambda: G.staging_slots(self._S))
        self.metrics.gauge("lastMaxStagedRows", lambda: self._last_pressure[0])
        self.metrics.gauge("lastMaxKeptRows", lambda: self._last_pressure[1])
        self.metrics.gauge("inflightGroups", lambda: len(self._inflight))

    # -- ConflictSet interface ------------------------------------------------

    def warm_compile(self) -> None:
        """Pre-compile the smoke-shape kernel (1 group, T=8, KR=KW=1) on a
        SCRATCH grid so the first real commit batch doesn't pay the
        first-compile inside the dispatch path (the ~200 ms loop-blocking
        step PR 9's run-loop profiler attributes to the resolver band).
        Logical state and the version base are untouched; the compiled XLA
        program signature matches the first small dispatch, so that
        dispatch is a jit-cache hit. Re-invoked internally (_warm_recent)
        whenever a reshard/grow changes the grid shape, so every stacked
        shape the pipeline can dispatch post-reshard is pre-compiled too."""
        b = encode_transactions([], self._width, 0)
        self._warm_shape((1, b.rb.shape[0], b.rb.shape[1], b.wb.shape[1]))

    def _warm_shape(self, shape: tuple) -> None:
        """Compile-and-discard one stacked (G, T, KR, KW) shape against a
        scratch grid at the CURRENT grid shape."""
        t0 = time.perf_counter()
        Gn, T, KR, KW = shape
        scratch = G.make_state(self._B, self._S, self._lanes)
        b = sentinel_batch(T, KR, KW, self._lanes)
        stacked = jax.tree_util.tree_map(
            lambda a: jax.device_put(
                np.broadcast_to(np.asarray(a)[None], (Gn,) + a.shape)
            ),
            b,
        )
        zeros = np.zeros(Gn, np.int32)
        out = G.resolve_many(scratch, stacked, zeros, zeros, zeros)
        jax.block_until_ready(out)
        self.metrics.note_shape((Gn, T, KR, KW, self._B), warm=True)
        self.metrics.warm_compiles.add()
        self.metrics.warm_s.add(time.perf_counter() - t0)

    def _note_recent_shape(self, shape: tuple) -> None:
        if shape in self._recent_shapes:
            return
        self._recent_shapes.append(shape)
        del self._recent_shapes[:-_RECENT_SHAPES]

    def _warm_recent(self) -> None:
        """The grid shape just changed (grow / host reshard): every stacked
        program the pipeline compiled is stale. Pre-compile the recently
        dispatched shapes against the new grid so the next dispatches are
        jit-cache hits instead of in-band first compiles."""
        for shape in self._recent_shapes:
            self._warm_shape(shape)

    def _flush(self) -> None:
        while self._inflight:
            self._collect(self._inflight[0])

    def clear(self, version: int) -> None:
        self._flush()
        self._state = G.make_state(self._B, self._S, self._lanes)
        self._base = version - 1
        self._base_epoch += 1
        self._resharded_once = False
        self.oldest_version = version

    def detect_batch(
        self, transactions: list[CommitTransaction], now: int, new_oldest_version: int
    ) -> list[Verdict]:
        return self.detect_many([(transactions, now, new_oldest_version)])[0]

    def detect_many(
        self, work: list[tuple[list[CommitTransaction], int, int]]
    ) -> list[list[Verdict]]:
        """Resolve many (transactions, now, new_oldest) batches in one
        device dispatch (grid.resolve_many lax.scan)."""
        if not work:
            return []
        self._maybe_rebase(max(now for _, now, _2 in work))
        return self.detect_many_encoded(
            [(self.encode(txs), now, old) for txs, now, old in work]
        )

    def prepare(self, now: int) -> None:
        """Call before encode() when driving the encoded/async path
        directly: rebases the int32 version origin when ``now`` drifts far
        from the base (flushes in-flight work first)."""
        self._maybe_rebase(now)

    def encode(self, transactions: list[CommitTransaction]):
        """Pre-encode a batch for detect_many_encoded. Encodings are
        base-relative: a version rebase invalidates them (epoch stamp).
        Safe to call from the resolver's encode executor while dispatches
        run on the device thread: epoch and base are read FIRST, so a
        concurrent rebase can only make this encoding visibly stale
        (StaleEncodingError at dispatch → re-encode), never silently
        mis-based."""
        t0 = time.perf_counter()
        epoch, base = self._base_epoch, self._base
        b = encode_transactions(
            transactions, self._width, base, sample_cb=self._sample.add
        )
        self.metrics.encode_s.add(time.perf_counter() - t0)
        return b, len(transactions), epoch

    def detect_many_encoded(self, work) -> list[list[Verdict]]:
        """work: list of ((Batch, n_real, epoch), now, new_oldest)."""
        return self.detect_many_encoded_async(work)()

    def detect_many_encoded_async(self, work):
        """Dispatch a group without waiting; returns a zero-arg callable
        yielding the verdict lists. The caller may dispatch further groups
        before collecting — the inter-group state dependency lives on
        device, so dispatches pipeline and the host↔device round trip is
        paid once per *collection*, not per group (the commit pipeline's
        phase overlap, MasterProxyServer.actor.cpp:353, applied to the
        tunnel)."""
        if not work:
            return lambda: []
        for (_b, _n, epoch), _now, _old in work:
            # validate every encoding BEFORE mutating the horizon, so a
            # stale group raises with no partial side effects (the
            # resolver re-encodes and calls again)
            if epoch != self._base_epoch:
                raise StaleEncodingError(
                    "stale encoding: version base was rebased after encode()"
                )
        counts = []
        metas = []  # (now, oldest_pre, oldest_post) absolute versions
        batches = []
        for (b, n_real, _epoch), now, new_oldest in work:
            horizon = max(self.oldest_version, new_oldest)
            metas.append((now, self.oldest_version, horizon))
            self.oldest_version = horizon
            counts.append(n_real)
            batches.append(b)

        self.metrics.groups.add()
        self.metrics.batches.add(len(batches))
        self.metrics.txns.add(sum(counts))

        if not self._resharded_once:
            self._reshard(self._state)
        elif self._rebalance_wanted:
            # occupancy-driven proactive maintenance (the collected
            # pressure/headroom crossed the reshard threshold): drain the
            # pipeline and rebalance/grow BETWEEN batches — a deliberate
            # one-group bubble instead of an overflow replay of every
            # in-flight group later, and never a stall of a live dispatch
            self._flush()
            self.metrics.reshards_proactive.add()
            self._reshard(self._state, grow=self._wants_growth())
            self._rebalance_wanted = False

        stacked = self._stack(batches)
        group = {
            "stacked": stacked,
            "metas": metas,
            "counts": counts,
            "done": None,
        }
        self._dispatch(group)
        self._inflight.append(group)

        def result(group=group):
            return self._collect(group)

        return result

    def _dispatch(self, group) -> None:
        t0 = time.perf_counter()
        metas = group["metas"]
        st = group["stacked"]
        self.metrics.dispatches.add()
        shape = (len(metas), st.rb.shape[-3], st.rb.shape[-2], st.wb.shape[-2])
        self._note_recent_shape(shape)
        # the compiled program is keyed by the batch shape AND the grid
        # shape: a grow recompiles, which is why it re-warms (_warm_recent)
        self.metrics.note_shape(shape + (self._B,))
        nows = np.asarray([m[0] - self._base for m in metas], np.int32)
        olds_pre = np.asarray(
            [max(m[1] - self._base, 0) for m in metas], np.int32
        )
        olds_post = np.asarray(
            [max(m[2] - self._base, 0) for m in metas], np.int32
        )
        # resolve_many DONATES its state argument, so never hand it a
        # buffer that anything else still reads: the pre-group snapshot
        # keeps the ORIGINAL arrays (never donated → always intact for a
        # replay) and the kernel consumes a fresh `+ 0` copy whose only
        # reference is this dispatch. The earlier form (snapshot = copy,
        # donate the original) raced: with warm compiles the copy executes
        # genuinely async, and XLA:CPU would recycle the donated buffer
        # under the still-pending read — garbage pivots on replay.
        group["snapshot"] = self._state
        work = jax.tree_util.tree_map(lambda x: x + 0, self._state)
        state, verdicts, pressure = G.resolve_many(
            work, group["stacked"], nows, olds_pre, olds_post
        )
        self._state = state
        group["verdicts"] = verdicts
        group["pressure"] = pressure
        # start the device→host copies NOW (they complete behind later
        # dispatches): _collect's device_get then costs no extra tunnel
        # round trip — with a remote device (axon tunnel) a synchronous
        # fetch at collect time was a large fraction of the whole budget
        for a in (verdicts, pressure):
            copy_async = getattr(a, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        self.metrics.dispatch_s.add(time.perf_counter() - t0)

    def _collect(self, group) -> list[list[Verdict]]:
        if group["done"] is not None:
            return group["done"]
        # collect in dispatch order (earlier groups first: a replay there
        # invalidates everything after)
        while self._inflight and self._inflight[0] is not group:
            self._collect(self._inflight[0])
        assert self._inflight and self._inflight[0] is group
        t0 = time.perf_counter()
        S2 = G.staging_slots(self._S)
        for attempt in range(6):
            # one host↔device round trip for both pressure and verdicts
            pr, out = jax.device_get((group["pressure"], group["verdicts"]))
            self.metrics.d2h_bytes.add(int(pr.nbytes) + int(out.nbytes))
            if int(pr[0]) <= S2 and int(pr[1]) <= self._S:
                break
            self.metrics.overflow_replays.add()
            self.metrics.replayed_groups.add(len(self._inflight))
            # the in-flight chain is being ABANDONED for a replay: wait for
            # its async computations to finish first. An abandoned
            # resolve_many still writes into its donated buffers, and the
            # allocator can hand that freed memory to the replay's
            # snapshot/reshard arrays while the write is in flight —
            # observed as garbage pivot codes whenever compiles are cache-
            # warm enough for execution to run genuinely async.
            jax.block_until_ready(self._state)
            # overflow: some bucket needed more staging/grid slots than it
            # has — rebuild the grid under fresh pivots from the pre-group
            # snapshot, then replay this group and everything after it.
            # Attempt 0: cheap on-device rebalance (handles live-set skew).
            # Attempt 1+: host reshard whose pivots include the recent key
            # SAMPLE — a device rebalance can only split between live
            # boundaries, which never converges when the overflowing batch
            # floods a single gap with brand-new keys (append workloads).
            self._reshard(
                group["snapshot"], grow=attempt >= 2, with_sample=attempt >= 1
            )
            for g in self._inflight:
                self._dispatch(g)
        else:
            raise RuntimeError("conflict grid reshard did not converge")
        self._last_pressure = (int(pr[0]), int(pr[1]))
        self.metrics.collect_s.add(time.perf_counter() - t0)
        if int(pr[1]) > int(self._S * self._reshard_pressure) or int(
            pr[0]
        ) > int(S2 * self._reshard_pressure):
            # the occupancy/headroom signal crossed the reshard threshold
            # (CONFLICT_RESHARD_PRESSURE): rebalance before more work
            # lands. With nothing else in flight do it now; otherwise flag
            # it for the next dispatch (which drains the pipeline first).
            # Growth is decided from the live-row fill fraction
            # (CONFLICT_GROW_FILL) — and reshard_device still grows on its
            # own exactly when a balanced quantile split can't fit.
            if len(self._inflight) == 1:
                self.metrics.reshards_proactive.add()
                self._reshard(self._state, grow=self._wants_growth())
                self._rebalance_wanted = False
            else:
                self._rebalance_wanted = True
        # table-indexed conversion over a plain python list: ~100× cheaper
        # than Verdict(int(v)) per element (an IntEnum __call__ per txn was
        # ~25% of the whole resolve budget at bench scale)
        table = _VERDICT_TABLE
        group["done"] = [
            [table[v] for v in out[g, : group["counts"][g]].tolist()]
            for g in range(len(group["counts"]))
        ]
        # collected groups can never be re-dispatched: drop everything
        # pinning device/host memory (snapshots scale with pipeline depth)
        group.pop("snapshot", None)
        group.pop("verdicts", None)
        group.pop("stacked", None)
        group.pop("metas", None)
        self._inflight.pop(0)
        return group["done"]

    # -- internals ------------------------------------------------------------

    def _wants_growth(self) -> bool:
        """Live-row fill fraction against the grow threshold — consulted
        only on proactive reshard decisions, when the pipeline is drained
        (reading ``count`` then costs no pipeline sync)."""
        occ = G.occupancy_stats(self._state)
        return occ["fillFraction"] >= self._grow_fill

    def _stack(self, batches: list[G.Batch]) -> G.Batch:
        stacked = stack_batches(batches, self._lanes)
        # upload asynchronously NOW: with pipelined dispatches the transfer
        # overlaps earlier groups' device compute instead of stalling the
        # dispatch inside the jit call (a ~46 ms/group synchronous upload
        # over the tunnel otherwise)
        self.metrics.h2d_bytes.add(tree_nbytes(stacked))
        return jax.tree_util.tree_map(jax.device_put, stacked)

    def _reshard(
        self,
        from_state: G.GridState,
        grow: bool = False,
        with_sample: bool = False,
    ) -> None:
        """Rebuild the grid under fresh pivots. Normally this runs
        entirely ON DEVICE (grid.reshard_device — no grid download/upload
        over the tunnel), balancing on the LIVE boundary set. That can't
        split a gap that a new batch floods with keys the grid has never
        seen (an append workload writing past the last boundary), so
        overflow-replay escalation and the initial reshard use the host
        path, whose pivots also come from the recent key sample."""
        t0 = time.perf_counter()
        B0 = self._B
        if self._resharded_once and not with_sample:
            if grow:
                self._B *= 2
                self.metrics.capacity_growths.add()
            while True:
                state, pressure = G.reshard_device(from_state, self._B, self._S)
                if int(jax.device_get(pressure)) <= self._S:
                    self._state = state
                    self.metrics.reshards_device.add()
                    self.metrics.reshard_s.add(time.perf_counter() - t0)
                    if self._B != B0:
                        self._warm_recent()
                    return
                # quantile split can't fit: more buckets and retry
                self._B *= 2
                self.metrics.capacity_growths.add()
        self._reshard_host_sampled(from_state, grow=grow)
        self.metrics.reshards_host.add()
        self.metrics.reshard_s.add(time.perf_counter() - t0)
        if self._B != B0:
            self._warm_recent()

    def _reshard_host_sampled(
        self, from_state: G.GridState, grow: bool = False
    ) -> None:
        """Host reshard: pivots from live boundaries ∪ the key sample
        (covers keys arriving in not-yet-merged batches)."""
        if grow:
            self._B *= 2
            self.metrics.capacity_growths.add()
        state = from_state
        L = self._lanes
        codes, _vers = G.live_rows(state)
        if self._sample:
            codes = np.concatenate(
                [codes, K.encode_keys(self._sample.keys, self._width)]
            )
        keys = G.codes_to_bytes(np.ascontiguousarray(codes))
        _, uniq_idx = np.unique(keys, return_index=True)
        cands = codes[uniq_idx]  # sorted unique (void sort = lane order)
        cands = cands[cands.any(axis=1)]  # pivot 0 (zero code) is implicit

        n_live = int(np.asarray(state.count).sum())
        if n_live * 2 > self._B * self._S:
            self._B *= 2

        while True:
            pivot_codes = _pick_pivots(cands, self._B, L)
            try:
                self._state = G.reshard_host(state, pivot_codes, self._B, self._S)
                break
            except OverflowError:
                # quantile split still left some bucket over capacity:
                # grow and retry with more pivots available
                self._B *= 2
                self.metrics.capacity_growths.add()
        self._resharded_once = True

    def _maybe_rebase(self, now: int) -> None:
        if now - self._base < _INT32_REBASE_THRESHOLD:
            return
        self._flush()  # in-flight groups were encoded against the old base
        new_base = self.oldest_version - 1
        delta = new_base - self._base
        if delta > 0:
            self._state = G.rebase(self._state, np.int32(delta))
            self._base = new_base
            self._base_epoch += 1
            self.metrics.rebases.add()
