"""Order-preserving fixed-width key encoding for device-resident indexes.

The reference's conflict index (fdbserver/SkipList.cpp) keys its skip list on
variable-length byte strings and compares them with ``KeyInfo`` ordering rules
(SkipList.cpp:147-177, including the ``\\x00``-append point-range edge cases).
A TPU kernel needs fixed shapes, so keys are encoded into a fixed-width code
that preserves lexicographic order for all keys up to ``width - 1`` bytes:

    code = key[:width-1] zero-padded to width-1 bytes, then min(len(key), width-1)

Why this is order-preserving (keys of length <= width-1):
- Two distinct keys of equal length differ somewhere in the first width-1
  bytes, and zero padding does not disturb byte-wise comparison past that.
- If ``a`` is a proper prefix of ``b``, their padded prefixes compare equal
  and the trailing length byte breaks the tie the right way (shorter < longer).
  In particular ``k`` < ``k + b"\\x00"`` survives encoding, which is what makes
  FoundationDB point-write ranges ``[k, k+\\x00)`` non-empty after encoding.

Keys longer than width-1 bytes are truncated: range begins round down and
range ends round up (``round_up=True``), so truncation can only *widen*
ranges and *merge* distinct keys — a conservative approximation that may add
false conflicts but never misses one. (Default width is 32 → exact for keys
up to 31 bytes; the reference's own benchmark keys — benchmarking.rst:22 —
are 16 bytes.)

Device layout: each code is ``width // 4`` big-endian uint32 lanes, so
lexicographic byte order == lexicographic lane order, and an N-key index is a
``uint32[N, width//4]`` tensor.
"""

from __future__ import annotations

import numpy as np

DEFAULT_KEY_WIDTH = 32  # bytes per code, including the trailing length byte


def lanes_for_width(width: int) -> int:
    if width % 4 != 0 or width < 8:
        raise ValueError(f"key width must be a multiple of 4 and >= 8, got {width}")
    return width // 4


def encode_key(key: bytes, width: int = DEFAULT_KEY_WIDTH) -> np.ndarray:
    """Encode one key into uint32 big-endian lanes (shape [width//4])."""
    return encode_keys([key], width)[0]


def encode_keys(
    keys: list[bytes], width: int = DEFAULT_KEY_WIDTH, round_up: bool = False
) -> np.ndarray:
    """Encode a batch of keys → uint32[len(keys), width//4], order-preserving.

    ``round_up=False`` rounds truncated keys DOWN (codes the width-1-byte
    prefix); ``round_up=True`` rounds them UP (strictly above every key
    sharing the truncated prefix, still below any larger prefix). Range
    endpoints must use round-down for begins and round-up for ends so a
    truncated range can only GROW (conservative: may add false conflicts,
    never drops a write — e.g. a point range on a 40-byte key must not
    collapse to empty)."""
    lanes_for_width(width)  # validate
    n = len(keys)
    buf = np.zeros((n, width), dtype=np.uint8)
    for i, k in enumerate(keys):
        m = min(len(k), width - 1)
        if m:
            buf[i, :m] = np.frombuffer(k, dtype=np.uint8, count=m)
        # Clamp the length byte at width-1: every truncated key collapses to
        # the same code as its width-1-byte prefix, so truncation can only
        # MERGE keys (conservative), never reorder them. (An unclamped length
        # would order b"p"*31+b"z" before the byte-wise-smaller b"p"*31+b"aa".)
        if round_up and len(k) > width - 1:
            buf[i, width - 1] = 0xFF  # > any clamped length byte
        else:
            buf[i, width - 1] = min(len(k), width - 1)
    return pack_lanes(buf)


def pack_lanes(codes_u8: np.ndarray) -> np.ndarray:
    """uint8[N, width] → big-endian uint32[N, width//4] (order-preserving)."""
    n, width = codes_u8.shape
    lanes = codes_u8.reshape(n, width // 4, 4).astype(np.uint32)
    return (lanes[..., 0] << 24) | (lanes[..., 1] << 16) | (lanes[..., 2] << 8) | lanes[..., 3]


def max_sentinel(width: int = DEFAULT_KEY_WIDTH) -> np.ndarray:
    """A code strictly greater than every encodable key: all-0xFF lanes.

    (Only keys starting with width-1 bytes of 0xFF could encode to it, and
    real keyspace stays below the ``\\xff\\xff`` system-key prefix.)
    Used to pad unused index capacity so searchsorted lands before it.
    """
    return np.full((lanes_for_width(width),), 0xFFFFFFFF, dtype=np.uint32)


def compare_codes(a: np.ndarray, b: np.ndarray) -> int:
    """Lexicographic comparison of two lane codes: -1 / 0 / +1 (host-side)."""
    for x, y in zip(a.tolist(), b.tolist()):
        if x != y:
            return -1 if x < y else 1
    return 0
