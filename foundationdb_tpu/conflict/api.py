"""ConflictSet API — the narrow factory seam the commit path builds on.

Mirrors the reference's ``fdbserver/ConflictSet.h:27-60`` (``newConflictSet()``
/ ``ConflictBatch``): the resolver (server/resolver.py) talks only to this
interface, so backends are interchangeable:

- ``oracle`` — pure-Python reference implementation (the analog of the
  reference's ``SlowConflictSet``, SkipList.cpp:59-88). Ground truth for
  differential tests; O(N) per query.
- ``native`` — C++ versioned skip list via ctypes (conflict/native.py), the
  CPU baseline the TPU backend is benchmarked against.
- ``tpu`` — the JAX/XLA vectorized interval-overlap kernel over an
  HBM-resident versioned write-range index (conflict/tpu_backend.py).

Transaction semantics (reference ``ConflictBatch::addTransaction``
SkipList.cpp:979 and ``detectConflicts`` SkipList.cpp:1163):

1. A transaction whose ``read_snapshot`` is older than the set's
   ``oldest_version`` *and* that has read conflict ranges is TOO_OLD.
2. A read range [begin, end) conflicts if some write range committed at
   version > read_snapshot overlaps it (history check).
3. Transactions are then scanned in batch order: a transaction also conflicts
   if any of its read ranges overlaps a write range of an *earlier,
   committed* transaction of the same batch (intra-batch check,
   SkipList.cpp:1133).
4. Write ranges of committed transactions are merged into the history at
   version ``now``; history below ``new_oldest_version`` is garbage-collected.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class Verdict(enum.IntEnum):
    COMMITTED = 0
    CONFLICT = 1
    TOO_OLD = 2


@dataclass
class CommitTransaction:
    """Wire-format analog of fdbclient/CommitTransaction.h:27-60 (conflict part)."""

    read_snapshot: int = 0
    read_conflict_ranges: list[tuple[bytes, bytes]] = field(default_factory=list)
    write_conflict_ranges: list[tuple[bytes, bytes]] = field(default_factory=list)


class ConflictSet:
    """Abstract versioned write-range history. One per resolver key-partition."""

    def __init__(self) -> None:
        self.oldest_version = 0

    def detect_batch(
        self, transactions: list[CommitTransaction], now: int, new_oldest_version: int
    ) -> list[Verdict]:
        raise NotImplementedError

    def clear(self, version: int) -> None:
        """Reset history (reference clearConflictSet, SkipList.cpp:1097)."""
        raise NotImplementedError


class ConflictBatch:
    """Collects one commit batch, then resolves it — API parity with the
    reference's ConflictBatch (ConflictSet.h:40-60)."""

    def __init__(self, cs: ConflictSet) -> None:
        self._cs = cs
        self._transactions: list[CommitTransaction] = []

    def add_transaction(self, tr: CommitTransaction) -> int:
        self._transactions.append(tr)
        return len(self._transactions) - 1

    def detect_conflicts(self, now: int, new_oldest_version: int) -> list[Verdict]:
        return self._cs.detect_batch(self._transactions, now, new_oldest_version)


def new_conflict_set(
    backend: str = "oracle", fault_injector=None, **kwargs
) -> ConflictSet:
    """The ``newConflictSet()`` factory seam (ConflictSet.h:28).

    ``tpu`` auto-upgrades to the mesh backend when more than one device is
    visible — the cluster resolver then shards its conflict index across
    the whole mesh (key-range partitioning, conflict/sharded.py) with no
    configuration. ``mesh`` / ``tpu1`` force the choice either way.

    ``fault_injector`` (sim-only, conflict/faults.py) wraps the built
    device backend in a ``FaultInjectingConflictSet`` so chaos runs can
    inject dispatch errors, hangs, device loss, and compile stalls at this
    seam; it is ignored for the sync CPU backends (oracle/native), which
    are the failover *targets*.
    """
    cs = _build_conflict_set(backend, **kwargs)
    if fault_injector is not None and hasattr(cs, "detect_many_encoded_async"):
        from .faults import FaultInjectingConflictSet

        cs = FaultInjectingConflictSet(cs, fault_injector)
    return cs


def _build_conflict_set(backend: str, **kwargs) -> ConflictSet:
    if backend == "oracle":
        from .oracle import OracleConflictSet

        return OracleConflictSet(**kwargs)
    if backend == "native":
        from .native import NativeConflictSet

        return NativeConflictSet(**kwargs)
    if backend == "tpu":
        # consult only ALREADY-initialized jax backends: jax.devices()
        # would otherwise INITIALIZE one here — and on a box whose remote
        # TPU tunnel is wedged, backend init can hang a whole simulation
        # that never needed a device (round-3 failure mode). Processes
        # that want the mesh initialize jax before building the cluster
        # (tests/conftest, dryrun, real servers at boot).
        multi = False
        try:
            import jax._src.xla_bridge as xb

            if xb._backends:
                import jax

                multi = len(jax.devices()) > 1
        except Exception:
            multi = False
        if multi:
            from .mesh_backend import MeshConflictSet

            return MeshConflictSet(**kwargs)
        from .tpu_backend import TpuConflictSet

        return TpuConflictSet(**kwargs)
    if backend == "tpu1":
        from .tpu_backend import TpuConflictSet

        return TpuConflictSet(**kwargs)
    if backend == "mesh":
        from .mesh_backend import MeshConflictSet

        return MeshConflictSet(**kwargs)
    raise ValueError(f"unknown conflict-set backend {backend!r}")
