"""ctypes binding for the native C++ versioned-skip-list conflict set.

The CPU baseline for the north-star benchmark (BASELINE.json): the TPU
kernel must beat this by >=10x on the high-contention workload. Built from
foundationdb_tpu/native/skiplist_conflict.cpp (``make -C
foundationdb_tpu/native``; auto-built on first use if g++ is available).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from .api import CommitTransaction, ConflictSet, Verdict

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libskiplist_conflict.so"))
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR), "-s"], check=True
        )
    lib = ctypes.CDLL(_LIB_PATH)
    lib.csn_create.restype = ctypes.c_void_p
    lib.csn_destroy.argtypes = [ctypes.c_void_p]
    lib.csn_count.argtypes = [ctypes.c_void_p]
    lib.csn_count.restype = ctypes.c_int64
    lib.csn_set_oldest.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.csn_resolve.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,  # keys
        np.ctypeslib.ndpointer(np.uint64),  # offsets
        np.ctypeslib.ndpointer(np.int32),  # reads
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int32),  # writes
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int64),  # snapshots
        ctypes.c_int32,
        ctypes.c_int64,  # now
        ctypes.c_int64,  # new_oldest
        np.ctypeslib.ndpointer(np.uint8),  # verdicts out
    ]
    _lib = lib
    return lib


class NativeConflictSet(ConflictSet):
    def __init__(self) -> None:
        super().__init__()
        self._lib = _load()
        self._cs = self._lib.csn_create()

    def __del__(self):
        if getattr(self, "_cs", None):
            self._lib.csn_destroy(self._cs)
            self._cs = None

    def clear(self, version: int) -> None:
        self._lib.csn_destroy(self._cs)
        self._cs = self._lib.csn_create()
        self._lib.csn_set_oldest(self._cs, version)
        self.oldest_version = version

    @property
    def boundary_count(self) -> int:
        return self._lib.csn_count(self._cs)

    def encode_batch(self, transactions: list[CommitTransaction]):
        """Pack a batch into the flat C ABI arrays (reusable across calls)."""
        keys: list[bytes] = []
        reads: list[int] = []
        writes: list[int] = []
        snaps = np.zeros(max(len(transactions), 1), np.int64)

        def add_key(k: bytes) -> int:
            keys.append(k)
            return len(keys) - 1

        for t, tr in enumerate(transactions):
            snaps[t] = tr.read_snapshot
            for (b, e) in tr.read_conflict_ranges:
                reads.extend((add_key(b), add_key(e), t))
            for (b, e) in tr.write_conflict_ranges:
                writes.extend((add_key(b), add_key(e), t))

        blob = b"".join(keys)
        offsets = np.zeros(len(keys) + 1, np.uint64)
        np.cumsum([len(k) for k in keys], out=offsets[1:])
        r = np.asarray(reads or [0], np.int32)
        w = np.asarray(writes or [0], np.int32)
        return (
            blob,
            offsets,
            r,
            len(reads) // 3,
            w,
            len(writes) // 3,
            snaps,
            len(transactions),
        )

    def resolve_encoded(self, enc, now: int, new_oldest_version: int) -> np.ndarray:
        blob, offsets, r, nr, w, nw, snaps, nt = enc
        verdicts = np.zeros(max(nt, 1), np.uint8)
        self._lib.csn_resolve(
            self._cs, blob, offsets, r, nr, w, nw, snaps, nt,
            now, new_oldest_version, verdicts,
        )
        if new_oldest_version > self.oldest_version:
            self.oldest_version = new_oldest_version
        return verdicts[:nt]

    def detect_batch(
        self, transactions: list[CommitTransaction], now: int, new_oldest_version: int
    ) -> list[Verdict]:
        enc = self.encode_batch(transactions)
        out = self.resolve_encoded(enc, now, new_oldest_version)
        return [Verdict(int(v)) for v in out]
