"""Pure-Python oracle ConflictSet — ground truth for differential testing.

The analog of the reference's ``SlowConflictSet`` (SkipList.cpp:59-88), which
keeps a KeyRangeMap of versions and answers "max committed-write version over
a key range". Here the history is a step function over raw byte-string
keyspace, stored as a sorted list of (boundary_key, max_version_of_gap_right).

Deliberately simple (bisect + linear sweeps) — correctness reference only.
"""

from __future__ import annotations

import bisect

from .api import CommitTransaction, ConflictSet, Verdict


class _StepFunction:
    """Map from key (bytes) to int version: piecewise constant, half-open gaps.

    boundaries[i] is the start of gap i; gap i spans [boundaries[i],
    boundaries[i+1]) (the last gap is unbounded). values[i] is the max write
    version recorded over that gap; 0 means "never written".
    """

    def __init__(self) -> None:
        self.boundaries: list[bytes] = [b""]
        self.values: list[int] = [0]

    def _locate(self, key: bytes) -> int:
        """Index of the gap containing key."""
        return bisect.bisect_right(self.boundaries, key) - 1

    def _ensure_boundary(self, key: bytes) -> int:
        i = self._locate(key)
        if self.boundaries[i] != key:
            self.boundaries.insert(i + 1, key)
            self.values.insert(i + 1, self.values[i])
            return i + 1
        return i

    def max_over(self, begin: bytes, end: bytes) -> int:
        if begin >= end:
            return 0
        lo = self._locate(begin)
        hi = bisect.bisect_left(self.boundaries, end, lo=lo + 1) - 1
        return max(self.values[lo : hi + 1])

    def raise_to(self, begin: bytes, end: bytes, version: int) -> None:
        if begin >= end:
            return
        lo = self._ensure_boundary(begin)
        hi = bisect.bisect_left(self.boundaries, end, lo=lo + 1)
        if hi == len(self.boundaries) or self.boundaries[hi] != end:
            # hi is the first boundary > end's gap start; split end's gap
            self.boundaries.insert(hi, end)
            self.values.insert(hi, self.values[hi - 1])
        for i in range(lo, hi):
            if self.values[i] < version:
                self.values[i] = version

    def forget_below(self, version: int) -> None:
        """GC: gaps whose version is below ``version`` can never conflict with
        a non-too-old read, so flatten them to 0 and coalesce."""
        for i, v in enumerate(self.values):
            if v < version:
                self.values[i] = 0
        bs, vs = [self.boundaries[0]], [self.values[0]]
        for b, v in zip(self.boundaries[1:], self.values[1:]):
            if v != vs[-1]:
                bs.append(b)
                vs.append(v)
        self.boundaries, self.values = bs, vs


def _overlaps(a_begin: bytes, a_end: bytes, b_begin: bytes, b_end: bytes) -> bool:
    return a_begin < b_end and b_begin < a_end


class OracleConflictSet(ConflictSet):
    def __init__(self) -> None:
        super().__init__()
        self._history = _StepFunction()

    def clear(self, version: int) -> None:
        self._history = _StepFunction()
        self.oldest_version = version

    def detect_batch(
        self, transactions: list[CommitTransaction], now: int, new_oldest_version: int
    ) -> list[Verdict]:
        verdicts: list[Verdict] = []
        # Phases 1-2: too-old + history check (SkipList.cpp:989,1210).
        for tr in transactions:
            if tr.read_snapshot < self.oldest_version and tr.read_conflict_ranges:
                verdicts.append(Verdict.TOO_OLD)
                continue
            conflict = any(
                self._history.max_over(b, e) > tr.read_snapshot
                for (b, e) in tr.read_conflict_ranges
            )
            verdicts.append(Verdict.CONFLICT if conflict else Verdict.COMMITTED)

        # Phase 3: intra-batch, in order, against earlier *committed* writes
        # (SkipList.cpp:1133 checkIntraBatchConflicts).
        committed_writes: list[tuple[bytes, bytes]] = []
        for t, tr in enumerate(transactions):
            if verdicts[t] == Verdict.COMMITTED:
                hit = any(
                    _overlaps(rb, re, wb, we)
                    for (rb, re) in tr.read_conflict_ranges
                    for (wb, we) in committed_writes
                )
                if hit:
                    verdicts[t] = Verdict.CONFLICT
            if verdicts[t] == Verdict.COMMITTED:
                committed_writes.extend(tr.write_conflict_ranges)

        # Phases 4-5: merge committed writes at ``now``; advance GC horizon
        # (SkipList.cpp:1260 mergeWriteConflictRanges, :1195 removeBefore).
        for t, tr in enumerate(transactions):
            if verdicts[t] == Verdict.COMMITTED:
                for (wb, we) in tr.write_conflict_ranges:
                    self._history.raise_to(wb, we, now)
        if new_oldest_version > self.oldest_version:
            self.oldest_version = new_oldest_version
            self._history.forget_below(new_oldest_version)
        return verdicts
