"""Bucket-grid conflict index — the TPU-native MVCC conflict kernel, v2.

Replaces the round-1 sorted-array kernel (tpu_index.py), whose per-batch
cost was dominated by exactly the operations a TPU is worst at: 18-step
binary-search gathers, large row scatters, segment-tree walks, and a full
capacity-sized index rewrite per batch. Measured on the v5e, gathers and
scatters cost ~25-100 ns *per element* while dense vector ops stream at
HBM speed — so this design expresses every phase as dense tile work:

    pivots: uint32[B, L]     — lower bound key of bucket b (pivots[0] = 0);
                               buckets partition keyspace into key ranges
    grid:   uint32[B, S, L+1]— per bucket: S slots of (boundary key lanes,
                               gap version), sorted within the bucket;
                               slot 0 is always the bucket's pivot
    count:  int32[B]         — used slots per bucket
    bmax:   int32[B]         — max gap version in bucket (query shortcut)

The MVCC write history is the step function V(key) = version of the gap
containing key; gaps never span buckets (every pivot is a boundary).

Per batch, everything is a handful of dense ops:

- **history check**: each read endpoint finds its bucket by a dense rank
  against the pivots (one [Q, B] lex-compare pass — no binary search),
  block-gathers that bucket's S-slot window (contiguous DMA, not row
  gathers), and takes masked maxes over the window plus a dense [Q, B]
  between-buckets max of ``bmax``. The skip list's probe loop
  (fdbserver/SkipList.cpp:1210 checkReadConflictRanges) becomes ~6 vector
  passes for the whole batch.
- **intra-batch check** (the reference's MiniConflictSet,
  SkipList.cpp:1028): ranges are padded per transaction, so the
  read-vs-write overlap matrix is a direct dense [T, T] lex compare —
  no gap partition, no scatters — and the in-order greedy commit
  recursion runs as an MXU matvec fixpoint.
- **merge + GC** (mergeWriteConflictRanges / removeBefore,
  SkipList.cpp:1260,665): committed write endpoints are staged into their
  buckets (one flat sort of the batch's ~2W endpoints + one small
  scatter), then every bucket merges old slots with staged rows by a
  *per-bucket* bitonic sort over its 2S rows (vectorized across all B
  buckets), forward-fills gap versions with log-shift passes, applies
  coverage prefix sums, GCs below the horizon, coalesces equal steps, and
  compacts with one stable flag sort. Work per batch is O(B·S) dense —
  independent of total history size only through the grid shape, and ~50×
  less traffic than the round-1 full-index rewrite.

Versions on device are int32 offsets from a host-tracked base (see
tpu_backend.py). Skew/overflow is handled by the host: each dispatch
returns per-bucket pressure; the host *reshards* (new pivots from its key
sample) and replays a group from a state snapshot on overflow — verdicts
are deterministic, so a replay is invisible to callers.

Sharding story (multi-device resolver): the bucket axis is the natural
shard axis — each device owns a contiguous pivot range, which is exactly
key-range partitioning of conflict resolution across resolvers
(fdbserver/MasterProxyServer.actor.cpp:233 ResolutionRequestBuilder).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

SENTINEL = jnp.uint32(0xFFFFFFFF)

COMMITTED, CONFLICT, TOO_OLD = 0, 1, 2


class GridState(NamedTuple):
    pivots: jax.Array  # uint32[B, L]; unused buckets = all-0xFF
    grid: jax.Array  # uint32[B, S, L+1]; [..., :L] bounds, [..., L] version
    count: jax.Array  # int32[B]
    bmax: jax.Array  # int32[B]


class Batch(NamedTuple):
    """One commit batch, padded per transaction to static shapes.

    Ranges are bucketed per txn (KR read / KW write slots each); inactive
    slots have begin == end == SENTINEL and self-deactivate in compares.
    """

    rb: jax.Array  # uint32[T, KR, L]
    re: jax.Array  # uint32[T, KR, L]
    wb: jax.Array  # uint32[T, KW, L]
    we: jax.Array  # uint32[T, KW, L]
    t_snap: jax.Array  # int32[T]
    t_has_reads: jax.Array  # bool[T]


# ---------------------------------------------------------------------------
# lex helpers (trailing lane axis, broadcasting)


def lex_lt(a: jax.Array, b: jax.Array) -> jax.Array:
    lanes = a.shape[-1]
    lt = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]), dtype=bool)
    eq = jnp.ones_like(lt)
    for i in range(lanes):
        ai, bi = a[..., i], b[..., i]
        lt = lt | (eq & (ai < bi))
        eq = eq & (ai == bi)
    return lt


def lex_le(a: jax.Array, b: jax.Array) -> jax.Array:
    return ~lex_lt(b, a)


def _rank_le(points: jax.Array, pivots: jax.Array) -> jax.Array:
    """#(pivots <= point) - 1 per point: dense [N, B] lex compare.
    points [..., L], pivots [B, L] → int32[...]."""
    le = lex_le(pivots[None, :, :], points[..., None, :])  # pivot <= point
    return le.sum(axis=-1, dtype=jnp.int32) - 1


def _rank_lt(points: jax.Array, pivots: jax.Array) -> jax.Array:
    """#(pivots < point) - 1 per point (bucket of point⁻)."""
    lt = lex_lt(pivots[None, :, :], points[..., None, :])
    return lt.sum(axis=-1, dtype=jnp.int32) - 1


# ---------------------------------------------------------------------------
# Phase 1: history check


def history_conflicts(state: GridState, batch: Batch) -> jax.Array:
    """bool[T]: some read range overlaps a gap with version > txn snapshot."""
    T, KR, L = batch.rb.shape
    B, S, _ = state.grid.shape
    a = batch.rb.reshape(T * KR, L)
    e = batch.re.reshape(T * KR, L)
    active = lex_lt(a, e)
    snap = jnp.repeat(batch.t_snap, KR)

    ba = _rank_le(a, state.pivots)  # bucket containing a
    be = _rank_lt(e, state.pivots)  # bucket containing e⁻

    win_a = state.grid[jnp.maximum(ba, 0)]  # [Q, S, L+1] block gather
    used_a = jnp.arange(S)[None, :] < state.count[jnp.maximum(ba, 0)][:, None]
    bnd_a = win_a[..., :L]
    ver_a = win_a[..., L].astype(jnp.int32)

    # value at a: version of the last slot <= a (slot 0 = pivot <= a always)
    le_a = lex_le(bnd_a, a[:, None, :]) & used_a
    rank_a = le_a.sum(axis=1, dtype=jnp.int32) - 1
    onehot = jnp.arange(S)[None, :] == rank_a[:, None]
    v_at_a = jnp.max(jnp.where(onehot, ver_a, 0), axis=1)

    # gaps starting strictly inside (a, e) within a's bucket
    inside_a = (
        used_a
        & lex_lt(a[:, None, :], bnd_a)
        & lex_lt(bnd_a, e[:, None, :])
    )
    v_in_a = jnp.max(jnp.where(inside_a, ver_a, 0), axis=1)

    # e's bucket (when different): gaps starting before e
    diff = be > ba
    win_e = state.grid[jnp.maximum(be, 0)]
    used_e = jnp.arange(S)[None, :] < state.count[jnp.maximum(be, 0)][:, None]
    bnd_e = win_e[..., :L]
    ver_e = win_e[..., L].astype(jnp.int32)
    in_e = used_e & lex_lt(bnd_e, e[:, None, :])
    v_in_e = jnp.where(diff, jnp.max(jnp.where(in_e, ver_e, 0), axis=1), 0)

    # buckets strictly between
    ar = jnp.arange(B, dtype=jnp.int32)[None, :]
    between = (ar > ba[:, None]) & (ar < be[:, None])
    v_btw = jnp.max(jnp.where(between, state.bmax[None, :], 0), axis=1)

    vmax = jnp.maximum(jnp.maximum(v_at_a, v_in_a), jnp.maximum(v_in_e, v_btw))
    hit = active & (vmax > snap)
    return hit.reshape(T, KR).any(axis=1)


# ---------------------------------------------------------------------------
# Phase 2: intra-batch greedy commit (dense Pji + MXU fixpoint)


def intra_batch_commits(batch: Batch, H: jax.Array) -> jax.Array:
    T, KR, L = batch.rb.shape
    KW = batch.wb.shape[1]
    # one [T, T, KW] compare per read slot: program size grows with KR
    # only, intermediates stay bounded by T²·KW (a full KR×KW broadcast
    # would square both)
    Pji = jnp.zeros((T, T), dtype=bool)
    for ar in range(KR):
        rb = batch.rb[:, ar, None, None, :]  # [T, 1, 1, L] reads of j
        re = batch.re[:, ar, None, None, :]
        wb = batch.wb[None, :, :, :]  # [1, T, KW, L] writes of i
        we = batch.we[None, :, :, :]
        # read j overlaps write i: rb_j < we_i and wb_i < re_j
        o = lex_lt(rb, we) & lex_lt(wb, re)  # [T, T, KW]
        Pji = Pji | o.any(axis=2)
    earlier = jnp.arange(T)[None, :] < jnp.arange(T)[:, None]
    Pf = (Pji & earlier).astype(jnp.bfloat16)

    def body(val):
        commit, _ = val
        blocked = (
            jnp.matmul(Pf, commit.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
            > 0
        )
        new = ~H & ~blocked
        return new, jnp.any(new != commit)

    commit, _ = jax.lax.while_loop(
        lambda v: v[1], body, (~H, jnp.array(True))
    )
    return commit


# ---------------------------------------------------------------------------
# Phase 3: merge committed writes + GC + coalesce (per-bucket dense)


def _log_shift_fill(val: jax.Array, have: jax.Array) -> jax.Array:
    """Forward-fill along axis 1: val where have, else last earlier value.
    Hillis-Steele log passes (no gathers)."""
    n = val.shape[1]
    shift = 1
    while shift < n:
        pv = jnp.pad(val, ((0, 0), (shift, 0)))[:, :n]
        ph = jnp.pad(have, ((0, 0), (shift, 0)))[:, :n]
        val = jnp.where(have, val, pv)
        have = have | ph
        shift <<= 1
    return val


def merge_writes(
    state: GridState,
    batch: Batch,
    commit: jax.Array,
    now: jax.Array,
    oldest: jax.Array,
) -> tuple[GridState, jax.Array]:
    """Raise V(k) to max(V(k), now) over committed write ranges; GC below
    ``oldest``; coalesce equal steps. Returns (new_state, pressure) where
    ``pressure`` = int32[2]: [max staged rows in any bucket (overflow if
    > S), max kept rows in any bucket (overflow if > S)]."""
    B, S, Lp1 = state.grid.shape
    L = Lp1 - 1
    T, KW, _ = batch.wb.shape
    Wtot = T * KW

    w_ok = lex_lt(batch.wb, batch.we) & commit[:, None]
    c = batch.wb.reshape(Wtot, L)
    d = batch.we.reshape(Wtot, L)
    ok = w_ok.reshape(Wtot)

    bc = _rank_le(c, state.pivots)
    bd = _rank_le(d, state.pivots)

    # staged rows: (code, ev) — begins carry +1, ends -1
    codes = jnp.concatenate([c, d], axis=0)  # [2W, L]
    evs = jnp.concatenate(
        [jnp.where(ok, 1, 0), jnp.where(ok, -1, 0)]
    ).astype(jnp.int32)
    bkt = jnp.where(
        jnp.concatenate([ok, ok]),
        jnp.concatenate([bc, bd]),
        B,  # invalid → out of range, dropped by scatter
    ).astype(jnp.int32)

    # per-bucket event carry: events in earlier buckets (a write spanning
    # buckets keeps later buckets covered until its end event)
    ar = jnp.arange(B, dtype=jnp.int32)[None, :]
    evsum = jnp.sum(
        jnp.where(bkt[:, None] == ar, evs[:, None], 0), axis=0
    )  # [B]
    carry = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(evsum)[:-1]]
    )

    # sort staged rows by (bucket, code), then AGGREGATE equal (bucket,
    # code) runs: one staged row per distinct boundary, carrying the run's
    # event sum. Without this, a hot-key batch (many txns writing the same
    # key) would stage more same-code rows than any repivoting could ever
    # split across buckets.
    N2 = 2 * Wtot
    cols = (bkt,) + tuple(codes[:, i] for i in range(L)) + (evs,)
    sorted_cols = jax.lax.sort(cols, num_keys=L + 1)
    sb = sorted_cols[0]
    scode = jnp.stack(sorted_cols[1 : L + 1], axis=1)
    sev = sorted_cols[L + 1]
    idx = jnp.arange(N2, dtype=jnp.int32)

    code_new = jnp.concatenate(
        [
            jnp.ones(1, bool),
            (sb[1:] != sb[:-1]) | (scode[1:] != scode[:-1]).any(axis=1),
        ]
    )
    code_last = jnp.concatenate([code_new[1:], jnp.ones(1, bool)])
    pe = jnp.cumsum(sev)
    # event prefix just before each run, forward-filled across the run
    pe_prev = jnp.concatenate([jnp.zeros(1, jnp.int32), pe[:-1]])
    pe_before = _log_shift_fill(
        jnp.where(code_new, pe_prev, 0)[None, :], code_new[None, :]
    )[0]
    agg_ev = pe - pe_before  # valid at run-last rows

    bkt_new = jnp.concatenate([jnp.ones(1, bool), sb[1:] != sb[:-1]])
    rl_cum = jnp.cumsum((code_last & (sb < B)).astype(jnp.int32))
    rl_cum_prev = jnp.concatenate([jnp.zeros(1, jnp.int32), rl_cum[:-1]])
    rl_base = _log_shift_fill(
        jnp.where(bkt_new, rl_cum_prev, 0)[None, :], bkt_new[None, :]
    )[0]
    slot = rl_cum - 1 - rl_base  # distinct-code slot within bucket

    staged_cnt = jnp.zeros((B,), jnp.int32).at[sb].add(
        jnp.where(code_last & (sb < B), 1, 0), mode="drop"
    )
    max_staged = jnp.max(staged_cnt)

    # scatter run-last rows into [B, S] staging planes (flat 1-D index)
    flat = jnp.where(
        code_last & (sb < B) & (slot < S), sb * S + slot, B * S
    )
    st_code = jnp.full((B * S + 1, L), SENTINEL, dtype=jnp.uint32)
    st_code = st_code.at[flat].set(scode, mode="drop")[: B * S].reshape(
        B, S, L
    )
    st_ev = jnp.zeros((B * S + 1,), jnp.int32).at[flat].set(
        agg_ev, mode="drop"
    )[: B * S].reshape(B, S)

    # merged per-bucket rows: old slots (tie 0) then staged (tie 1)
    M = 2 * S
    old_bnd = state.grid[..., :L]
    old_used = jnp.arange(S)[None, :] < state.count[:, None]
    old_bnd = jnp.where(old_used[..., None], old_bnd, SENTINEL)
    old_ver = jnp.where(old_used, state.grid[..., L].astype(jnp.int32), 0)

    m_code = jnp.concatenate([old_bnd, st_code], axis=1)  # [B, M, L]
    m_tie = jnp.concatenate(
        [jnp.zeros((B, S), jnp.int32), jnp.ones((B, S), jnp.int32)], axis=1
    )
    m_ver = jnp.concatenate([old_ver, jnp.zeros((B, S), jnp.int32)], axis=1)
    m_ev = jnp.concatenate([jnp.zeros((B, S), jnp.int32), st_ev], axis=1)
    m_old = jnp.concatenate(
        [old_used.astype(jnp.int32), jnp.zeros((B, S), jnp.int32)], axis=1
    )

    cols = tuple(m_code[..., i] for i in range(L)) + (
        m_tie,
        m_ver,
        m_ev,
        m_old,
    )
    sorted_cols = jax.lax.sort(cols, dimension=1, num_keys=L + 1)
    g_code = jnp.stack(sorted_cols[:L], axis=-1)  # [B, M, L]
    g_ver = sorted_cols[L + 1]
    g_ev = sorted_cols[L + 2]
    g_old = sorted_cols[L + 3].astype(bool)

    # forward-fill gap base values from old rows
    base = _log_shift_fill(jnp.where(g_old, g_ver, 0), g_old)

    # coverage prefix: gap starting at row m is covered iff carry + Σ ev > 0
    cov = carry[:, None] + jnp.cumsum(g_ev, axis=1)
    covered = cov > 0

    nv = jnp.where(covered, jnp.maximum(base, now), base)
    nv = jnp.where(nv < oldest, 0, nv)

    is_sent = (g_code == SENTINEL).all(axis=-1)
    # dedupe: keep last row of each equal-code run (it has the full prefix)
    nxt_differs = jnp.concatenate(
        [
            (g_code[:, 1:] != g_code[:, :-1]).any(axis=-1),
            jnp.ones((B, 1), bool),
        ],
        axis=1,
    )
    keep = (~is_sent) & nxt_differs
    # coalesce: drop a run whose value equals the previous run's value
    # (transitive through dropped runs, since equality is transitive).
    # Previous run's value = nv at the row just before this run's first
    # row; broadcast it across the run with a forward fill. The first run
    # of each bucket (the pivot boundary) sees the pad value -1, never
    # equal to a version, so it is always kept — preserving the
    # slot-0-is-the-pivot invariant.
    shifted_nv = jnp.pad(nv, ((0, 0), (1, 0)), constant_values=-1)[:, :M]
    first_of_run = jnp.concatenate(
        [
            jnp.ones((B, 1), bool),
            (g_code[:, 1:] != g_code[:, :-1]).any(axis=-1),
        ],
        axis=1,
    )
    pval = _log_shift_fill(
        jnp.where(first_of_run, shifted_nv, 0), first_of_run
    )
    keep = keep & (nv != pval)

    kept_cnt = keep.sum(axis=1, dtype=jnp.int32)
    max_kept = jnp.max(kept_cnt)

    # compact: stable sort by !keep, take first S rows
    cols = (jnp.where(keep, 0, 1).astype(jnp.int32),) + tuple(
        g_code[..., i] for i in range(L)
    ) + (nv,)
    sorted_cols = jax.lax.sort(cols, dimension=1, num_keys=1, is_stable=True)
    out_code = jnp.stack(sorted_cols[1 : L + 1], axis=-1)[:, :S, :]
    out_ver = sorted_cols[L + 1][:, :S]

    new_count = jnp.minimum(kept_cnt, S)
    used = jnp.arange(S)[None, :] < new_count[:, None]
    out_code = jnp.where(used[..., None], out_code, SENTINEL)
    out_ver = jnp.where(used, out_ver, 0)
    new_grid = jnp.concatenate(
        [out_code, out_ver.astype(jnp.uint32)[..., None]], axis=-1
    )
    new_bmax = jnp.max(out_ver, axis=1)

    pressure = jnp.stack([max_staged, max_kept])
    return (
        GridState(state.pivots, new_grid, new_count, new_bmax),
        pressure,
    )


# ---------------------------------------------------------------------------
# Full resolver step


def _resolve_one(state, batch, now, oldest_pre, oldest_post):
    too_old = batch.t_has_reads & (batch.t_snap < oldest_pre)
    H = history_conflicts(state, batch) | too_old
    commit = intra_batch_commits(batch, H)
    new_state, pressure = merge_writes(state, batch, commit, now, oldest_post)
    verdicts = jnp.where(
        too_old,
        jnp.int8(TOO_OLD),
        jnp.where(commit, jnp.int8(COMMITTED), jnp.int8(CONFLICT)),
    )
    return new_state, verdicts, pressure


@functools.partial(jax.jit, donate_argnames=("state",))
def resolve_batch(
    state: GridState,
    batch: Batch,
    now: jax.Array,
    oldest_pre: jax.Array,
    oldest_post: jax.Array,
):
    """One batch end-to-end. Returns (state, verdicts int8[T], pressure)."""
    return _resolve_one(state, batch, now, oldest_pre, oldest_post)


@functools.partial(jax.jit, donate_argnames=("state",))
def resolve_many(
    state: GridState,
    batches: Batch,  # leading group axis G on every leaf
    nows: jax.Array,
    oldests_pre: jax.Array,
    oldests_post: jax.Array,
):
    """G batches in one dispatch via lax.scan (state threads on device) —
    the device-side analog of the reference's pipelined commit batches
    (MasterProxyServer.actor.cpp:353). Returns (state, verdicts int8[G,T],
    pressure int32[2] = max over the group)."""

    def step(st, inp):
        batch, now, old_pre, old_post = inp
        st2, verdicts, pressure = _resolve_one(st, batch, now, old_pre, old_post)
        return st2, (verdicts, pressure)

    state, (verdicts, pressures) = jax.lax.scan(
        step, state, (batches, nows, oldests_pre, oldests_post)
    )
    return state, verdicts, jnp.max(pressures, axis=0)


@jax.jit
def rebase(state: GridState, delta: jax.Array) -> GridState:
    """Shift the version origin by ``delta`` (host advances its base)."""
    ver = state.grid[..., -1].astype(jnp.int32)
    used = jnp.arange(state.grid.shape[1])[None, :] < state.count[:, None]
    ver = jnp.where(used, jnp.maximum(ver - delta, 0), 0)
    grid = jnp.concatenate(
        [state.grid[..., :-1], ver.astype(jnp.uint32)[..., None]], axis=-1
    )
    return GridState(state.pivots, grid, state.count, jnp.max(ver, axis=1))


# ---------------------------------------------------------------------------
# Host-side construction / resharding (rare, numpy)


def make_state(n_buckets: int, n_slots: int, lanes: int) -> GridState:
    """Fresh index: one live bucket [0, ∞) with version 0 everywhere."""
    pivots = np.full((n_buckets, lanes), 0xFFFFFFFF, dtype=np.uint32)
    pivots[0] = 0
    grid = np.full((n_buckets, n_slots, lanes + 1), 0xFFFFFFFF, dtype=np.uint32)
    grid[..., lanes] = 0
    grid[0, 0, :lanes] = 0
    count = np.zeros((n_buckets,), np.int32)
    count[0] = 1
    return GridState(
        pivots=jnp.asarray(pivots),
        grid=jnp.asarray(grid),
        count=jnp.asarray(count),
        bmax=jnp.zeros((n_buckets,), jnp.int32),
    )


def reshard_host(
    state: GridState, new_pivot_codes: np.ndarray, n_buckets: int, n_slots: int
) -> GridState:
    """Rebuild the grid under new pivots (numpy; rare — init, growth, or
    skew). Preserves the step function exactly: every live boundary is
    re-bucketed and each new pivot becomes a boundary inheriting the value
    of the gap containing it."""
    pivots_old = np.asarray(state.pivots)
    grid = np.asarray(state.grid)
    count = np.asarray(state.count)
    B_old, S_old, Lp1 = grid.shape
    L = Lp1 - 1

    rows = []
    for b in range(B_old):
        for s in range(int(count[b])):
            rows.append((tuple(int(x) for x in grid[b, s, :L]), int(grid[b, s, L])))
    rows.sort()

    piv = [tuple(int(x) for x in p) for p in new_pivot_codes]
    assert piv[0] == tuple([0] * L), "pivot 0 must be the empty key"
    assert len(piv) <= n_buckets

    import bisect as _b

    keys = [r[0] for r in rows]
    new_grid = np.full((n_buckets, n_slots, Lp1), 0xFFFFFFFF, dtype=np.uint32)
    new_count = np.zeros((n_buckets,), np.int32)
    new_bmax = np.zeros((n_buckets,), np.int32)
    bounds_per = [[] for _ in range(len(piv))]
    for k, v in rows:
        nb = _b.bisect_right(piv, k) - 1
        bounds_per[nb].append((k, v))
    for nb, plist in enumerate(bounds_per):
        # pivot row first, inheriting the gap value at the pivot
        if not plist or plist[0][0] != piv[nb]:
            i = _b.bisect_right(keys, piv[nb]) - 1
            inherit = rows[i][1] if i >= 0 else 0
            plist.insert(0, (piv[nb], inherit))
        # coalesce: drop a boundary whose step value equals the previous
        # kept one (the pivot row at index 0 is always kept); duplicate
        # keys keep the later value
        out = []
        for k, v in plist:
            if out and out[-1][0] == k:
                out[-1] = (k, v)
                continue
            if out and out[-1][1] == v:
                continue
            out.append((k, v))
        if len(out) > n_slots:
            raise OverflowError(
                f"bucket {nb} needs {len(out)} slots > {n_slots}"
            )
        for s, (k, v) in enumerate(out):
            new_grid[nb, s, :L] = k
            new_grid[nb, s, L] = v
        new_count[nb] = len(out)
        new_bmax[nb] = max((v for _k, v in out), default=0)
    new_pivots = np.full((n_buckets, L), 0xFFFFFFFF, dtype=np.uint32)
    for nb, p in enumerate(piv):
        new_pivots[nb] = p
    return GridState(
        pivots=jnp.asarray(new_pivots),
        grid=jnp.asarray(new_grid),
        count=jnp.asarray(new_count),
        bmax=jnp.asarray(new_bmax),
    )
