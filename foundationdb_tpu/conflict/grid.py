"""Bucket-grid conflict index — the TPU-native MVCC conflict kernel, v2.

Replaces the round-1 sorted-array kernel (tpu_index.py), whose per-batch
cost was dominated by exactly the operations a TPU is worst at: 18-step
binary-search gathers, large row scatters, segment-tree walks, and a full
capacity-sized index rewrite per batch. Measured on the v5e, gathers and
scatters cost ~25-100 ns *per element* while dense vector ops stream at
HBM speed — so this design expresses every phase as dense tile work:

    pivots: uint32[B, L]     — lower bound key of bucket b (pivots[0] = 0);
                               buckets partition keyspace into key ranges
    grid:   uint32[B, S, L+1]— per bucket: S slots of (boundary key lanes,
                               gap version), sorted within the bucket;
                               slot 0 is always the bucket's pivot
    count:  int32[B]         — used slots per bucket
    bmax:   int32[B]         — max gap version in bucket (query shortcut)

The MVCC write history is the step function V(key) = version of the gap
containing key; gaps never span buckets (every pivot is a boundary).

Per batch, everything is a handful of dense ops:

- **history check**: each read endpoint finds its bucket by a dense rank
  against the pivots (one [Q, B] lex-compare pass — no binary search),
  block-gathers that bucket's S-slot window (contiguous DMA, not row
  gathers), and takes masked maxes over the window plus a dense [Q, B]
  between-buckets max of ``bmax``. The skip list's probe loop
  (fdbserver/SkipList.cpp:1210 checkReadConflictRanges) becomes ~6 vector
  passes for the whole batch.
- **intra-batch check** (the reference's MiniConflictSet,
  SkipList.cpp:1028): ranges are padded per transaction, so the
  read-vs-write overlap matrix is a direct dense [T, T] lex compare —
  no gap partition, no scatters — and the in-order greedy commit
  recursion runs as an MXU matvec fixpoint.
- **merge + GC** (mergeWriteConflictRanges / removeBefore,
  SkipList.cpp:1260,665): committed write endpoints are staged into their
  buckets (one flat sort of the batch's ~2W endpoints + one small
  scatter), then every bucket merges old slots with staged rows by a
  *per-bucket* bitonic sort over its 2S rows (vectorized across all B
  buckets), forward-fills gap versions with log-shift passes, applies
  coverage prefix sums, GCs below the horizon, coalesces equal steps, and
  compacts with one stable flag sort. Work per batch is O(B·S) dense —
  independent of total history size only through the grid shape, and ~50×
  less traffic than the round-1 full-index rewrite.

Versions on device are int32 offsets from a host-tracked base (see
tpu_backend.py). Skew/overflow is handled by the host: each dispatch
returns per-bucket pressure; the host *reshards* (new pivots from its key
sample) and replays a group from a state snapshot on overflow — verdicts
are deterministic, so a replay is invisible to callers.

Sharding story (multi-device resolver): the bucket axis is the natural
shard axis — each device owns a contiguous pivot range, which is exactly
key-range partitioning of conflict resolution across resolvers
(fdbserver/MasterProxyServer.actor.cpp:233 ResolutionRequestBuilder).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

SENTINEL = jnp.uint32(0xFFFFFFFF)

COMMITTED, CONFLICT, TOO_OLD = 0, 1, 2

# offset keeping packed staged-event payloads nonnegative (|ev| ≤ 2·W·KW
# per batch, far below 2^20); see merge_writes' packed sort operand
_EV_OFF = 1 << 20


class GridState(NamedTuple):
    pivots: jax.Array  # uint32[B, L]; unused buckets = all-0xFF
    grid: jax.Array  # uint32[B, S, L+1]; [..., :L] bounds, [..., L] version
    count: jax.Array  # int32[B]
    bmax: jax.Array  # int32[B]; EFFECTIVE max (includes floor)
    floor: jax.Array  # int32[B]; every gap's effective version is
    #                   max(stored, floor) — how a committed write that
    #                   *spans* a bucket raises the whole bucket in O(1)
    #                   instead of rewriting its rows (the round-3 design
    #                   rewrote the full [B, S, L+1] grid per batch for
    #                   this; ~3.7 ms/batch of pure HBM traffic at bench
    #                   shape). Folded into row versions whenever a bucket
    #                   is next touched by a merge / reshard / rebase.


class Batch(NamedTuple):
    """One commit batch, padded per transaction to static shapes.

    Ranges are bucketed per txn (KR read / KW write slots each); inactive
    slots have begin == end == SENTINEL and self-deactivate in compares.
    """

    rb: jax.Array  # uint32[T, KR, L]
    re: jax.Array  # uint32[T, KR, L]
    wb: jax.Array  # uint32[T, KW, L]
    we: jax.Array  # uint32[T, KW, L]
    t_snap: jax.Array  # int32[T]
    t_has_reads: jax.Array  # bool[T]


# ---------------------------------------------------------------------------
# lex helpers (trailing lane axis, broadcasting)


def lex_lt(a: jax.Array, b: jax.Array) -> jax.Array:
    lanes = a.shape[-1]
    lt = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]), dtype=bool)
    eq = jnp.ones_like(lt)
    for i in range(lanes):
        ai, bi = a[..., i], b[..., i]
        lt = lt | (eq & (ai < bi))
        eq = eq & (ai == bi)
    return lt


def lex_le(a: jax.Array, b: jax.Array) -> jax.Array:
    return ~lex_lt(b, a)


def searchsorted_lex(sorted_arr: jax.Array, q: jax.Array, side: str) -> jax.Array:
    """Vectorized binary search over a lex-sorted [P, L] array (used by
    the storage read path's batched range index, ops/range_index.py).

    side='right': first index with sorted_arr[i] >  q  (#elements <= q)
    side='left' : first index with sorted_arr[i] >= q  (#elements <  q)
    """
    P = sorted_arr.shape[0]
    steps = max(1, int(np.ceil(np.log2(P))) + 1)
    lo = jnp.zeros(q.shape[:-1], dtype=jnp.int32)
    hi = jnp.full(q.shape[:-1], P, dtype=jnp.int32)
    for _ in range(steps):
        mid = (lo + hi) >> 1
        row = sorted_arr[mid]  # gather [..., L]
        go_right = lex_le(row, q) if side == "right" else lex_lt(row, q)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
    return lo


def _split_factors(n: int) -> tuple[int, int]:
    """n = B1 * B2 with both powers of two, B1 >= B2 (n must be a power
    of two)."""
    lg = n.bit_length() - 1
    b1 = 1 << ((lg + 1) // 2)
    return b1, n // b1


def _rank_le(points: jax.Array, pivots: jax.Array) -> jax.Array:
    """#(pivots <= point) - 1 per point. points [N, L], pivots [B, L] →
    int32[N]. Two-level: rank against B1 superpivots (every B2-th pivot),
    then within the B2-pivot block — O(N·(B1+B2)) instead of O(N·B).
    Exact because pivots are sorted: every pivot in a block below the
    landing block is <= the landing block's superpivot <= point."""
    B = pivots.shape[0]
    B1, B2 = _split_factors(B)
    if B2 == 1:
        le = lex_le(pivots[None, :, :], points[:, None, :])
        return le.sum(axis=-1, dtype=jnp.int32) - 1
    pb = pivots.reshape(B1, B2, pivots.shape[-1])
    sup = pb[:, 0, :]
    s1 = lex_le(sup[None], points[:, None, :]).sum(axis=-1, dtype=jnp.int32) - 1
    blk = pb[jnp.maximum(s1, 0)]  # [N, B2, L] block gather
    s2 = lex_le(blk, points[:, None, :]).sum(axis=-1, dtype=jnp.int32) - 1
    return s1 * B2 + s2


def _rank_lt(points: jax.Array, pivots: jax.Array) -> jax.Array:
    """#(pivots < point) - 1 per point (bucket of point⁻), two-level."""
    B = pivots.shape[0]
    B1, B2 = _split_factors(B)
    if B2 == 1:
        lt = lex_lt(pivots[None, :, :], points[:, None, :])
        return lt.sum(axis=-1, dtype=jnp.int32) - 1
    pb = pivots.reshape(B1, B2, pivots.shape[-1])
    sup = pb[:, 0, :]
    s1 = lex_lt(sup[None], points[:, None, :]).sum(axis=-1, dtype=jnp.int32) - 1
    blk = pb[jnp.maximum(s1, 0)]
    s2 = lex_lt(blk, points[:, None, :]).sum(axis=-1, dtype=jnp.int32) - 1
    return s1 * B2 + s2


def _rank_le_lt(pa: jax.Array, pe: jax.Array, pivots: jax.Array):
    """(rank_le(pa), rank_lt(pe)) with ONE fused second-level block gather
    instead of two — the gather is descriptor-bound, so halving the
    dispatches matters while the extra compare lanes are nearly free."""
    B = pivots.shape[0]
    B1, B2 = _split_factors(B)
    if B2 == 1:
        return _rank_le(pa, pivots), _rank_lt(pe, pivots)
    Q = pa.shape[0]
    pb = pivots.reshape(B1, B2, pivots.shape[-1])
    sup = pb[:, 0, :]
    s1a = lex_le(sup[None], pa[:, None, :]).sum(axis=-1, dtype=jnp.int32) - 1
    s1e = lex_lt(sup[None], pe[:, None, :]).sum(axis=-1, dtype=jnp.int32) - 1
    blk = pb[jnp.maximum(jnp.concatenate([s1a, s1e]), 0)]  # [2Q, B2, L]
    s2a = lex_le(blk[:Q], pa[:, None, :]).sum(axis=-1, dtype=jnp.int32) - 1
    s2e = lex_lt(blk[Q:], pe[:, None, :]).sum(axis=-1, dtype=jnp.int32) - 1
    return s1a * B2 + s2a, s1e * B2 + s2e


# ---------------------------------------------------------------------------
# Phase 1: history check


def history_conflicts(state: GridState, batch: Batch) -> jax.Array:
    """bool[T]: some read range overlaps a gap with version > txn snapshot."""
    T, KR, L = batch.rb.shape
    B, S, _ = state.grid.shape
    a = batch.rb.reshape(T * KR, L)
    e = batch.re.reshape(T * KR, L)
    active = lex_lt(a, e)
    snap = jnp.repeat(batch.t_snap, KR)

    # bucket containing a / bucket containing e⁻, one fused rank pass
    ba, be = _rank_le_lt(a, e, state.pivots)

    # ONE fused block gather serves both endpoints' bucket windows (and
    # their counts): half the gather dispatches of the
    # separate win_a/win_e form — gathers here are descriptor-bound, not
    # byte-bound, so fewer launches is the lever (BENCH_NOTES r4 attack
    # list: "fuse the history-check bucket gathers")
    Q = a.shape[0]
    idx = jnp.concatenate([jnp.maximum(ba, 0), jnp.maximum(be, 0)])
    win = state.grid[idx]  # [2Q, S, L+1] block gather
    cnt = state.count[idx]
    used = jnp.arange(S)[None, :] < cnt[:, None]
    win_a, win_e = win[:Q], win[Q:]
    used_a, used_e = used[:Q], used[Q:]
    bnd_a = win_a[..., :L]
    ver_a = win_a[..., L].astype(jnp.int32)

    # value at a: version of the last slot <= a (slot 0 = pivot <= a always)
    le_a = lex_le(bnd_a, a[:, None, :]) & used_a
    rank_a = le_a.sum(axis=1, dtype=jnp.int32) - 1
    onehot = jnp.arange(S)[None, :] == rank_a[:, None]
    v_at_a = jnp.max(jnp.where(onehot, ver_a, 0), axis=1)

    # gaps starting strictly inside (a, e) within a's bucket
    inside_a = (
        used_a
        & lex_lt(a[:, None, :], bnd_a)
        & lex_lt(bnd_a, e[:, None, :])
    )
    v_in_a = jnp.max(jnp.where(inside_a, ver_a, 0), axis=1)

    # e's bucket (when different): gaps starting before e
    diff = be > ba
    bnd_e = win_e[..., :L]
    ver_e = win_e[..., L].astype(jnp.int32)
    in_e = used_e & lex_lt(bnd_e, e[:, None, :])
    v_in_e = jnp.where(diff, jnp.max(jnp.where(in_e, ver_e, 0), axis=1), 0)

    # buckets strictly between ba and be: two-level max over bmax —
    # whole superblocks strictly between the endpoints' superblocks via a
    # dense [Q, B1] pass, partial edge superblocks via [Q, B2] block
    # gathers (instead of one O(Q·B) dense pass)
    B1, B2 = _split_factors(B)
    bmax_blk = state.bmax.reshape(B1, B2)
    bmax_sup = bmax_blk.max(axis=1)  # [B1]
    s1a, s2a = ba // B2, ba % B2
    s1e, s2e = be // B2, be % B2
    ar1 = jnp.arange(B1, dtype=jnp.int32)[None, :]
    full_sup = (ar1 > s1a[:, None]) & (ar1 < s1e[:, None])
    v_sup = jnp.max(jnp.where(full_sup, bmax_sup[None, :], 0), axis=1)
    ar2 = jnp.arange(B2, dtype=jnp.int32)[None, :]
    blk = bmax_blk[jnp.concatenate([jnp.maximum(s1a, 0), jnp.maximum(s1e, 0)])]
    blk_a, blk_e = blk[:Q], blk[Q:]  # fused [2Q, B2] block gather
    hi2 = jnp.where(s1e == s1a, s2e, B2)
    in_a = (ar2 > s2a[:, None]) & (ar2 < hi2[:, None])
    v_edge_a = jnp.max(jnp.where(in_a, blk_a, 0), axis=1)
    in_e = (s1e > s1a)[:, None] & (ar2 < s2e[:, None])
    v_edge_e = jnp.max(jnp.where(in_e, blk_e, 0), axis=1)
    v_btw = jnp.maximum(v_sup, jnp.maximum(v_edge_a, v_edge_e))

    # bucket floors: the gap containing a (always overlapped) carries at
    # least floor[ba]; when e⁻ lands in a later bucket its pivot gap
    # starts before e, so floor[be] applies too
    fl = state.floor[idx]
    fl_a = fl[:Q]
    fl_e = jnp.where(diff, fl[Q:], 0)

    vmax = jnp.maximum(jnp.maximum(v_at_a, v_in_a), jnp.maximum(v_in_e, v_btw))
    vmax = jnp.maximum(vmax, jnp.maximum(fl_a, fl_e))
    hit = active & (vmax > snap)
    return hit.reshape(T, KR).any(axis=1)


# ---------------------------------------------------------------------------
# Phase 2: intra-batch greedy commit (dense Pji + MXU fixpoint)


def _endpoint_ranks(batch: Batch):
    """Dense int32 ranks over ALL of the batch's endpoint codes: equal
    codes share a rank and order is preserved, so every later lex compare
    on [L] lanes collapses to ONE int32 compare. One flat sort of the
    batch's endpoints replaces 3·L compare passes over the [T, T] overlap
    matrix — the sort is O((KR+KW)·T·log) while the matrix is O(T²), so
    this wins for every production shape. Returns (rb_r, re_r, wb_r, we_r)
    with the original [T, K] shapes."""
    T, KR, L = batch.rb.shape
    KW = batch.wb.shape[1]
    pts = jnp.concatenate(
        [
            batch.rb.reshape(T * KR, L),
            batch.re.reshape(T * KR, L),
            batch.wb.reshape(T * KW, L),
            batch.we.reshape(T * KW, L),
        ]
    )
    P = pts.shape[0]
    iota = jnp.arange(P, dtype=jnp.int32)
    cols = tuple(pts[:, i] for i in range(L)) + (iota,)
    sorted_cols = jax.lax.sort(cols, num_keys=L)
    scode = jnp.stack(sorted_cols[:L], axis=1)
    sidx = sorted_cols[L]
    new = jnp.concatenate(
        [jnp.ones(1, bool), (scode[1:] != scode[:-1]).any(axis=1)]
    )
    dense = jnp.cumsum(new.astype(jnp.int32)) - 1
    ranks = jnp.zeros((P,), jnp.int32).at[sidx].set(dense)
    a = T * KR
    b = 2 * T * KR
    c = b + T * KW
    return (
        ranks[:a].reshape(T, KR),
        ranks[a:b].reshape(T, KR),
        ranks[b:c].reshape(T, KW),
        ranks[c:].reshape(T, KW),
    )


def intra_batch_commits(batch: Batch, H: jax.Array, combine_pji=None) -> jax.Array:
    T, KR, L = batch.rb.shape
    KW = batch.wb.shape[1]
    rb_r, re_r, wb_r, we_r = _endpoint_ranks(batch)
    # one [T, T, KW] compare per read slot: program size grows with KR
    # only, intermediates stay bounded by T²·KW (a full KR×KW broadcast
    # would square both). Inactive slots (begin == end) self-deactivate:
    # equal codes share a rank, so rank(b) < rank(e) fails.
    Pji = jnp.zeros((T, T), dtype=bool)
    for ar in range(KR):
        rb = rb_r[:, ar, None, None]  # [T, 1, 1] reads of j
        re = re_r[:, ar, None, None]
        wb = wb_r[None, :, :]  # [1, T, KW] writes of i
        we = we_r[None, :, :]
        # read j overlaps write i: rb_j < we_i and wb_i < re_j
        o = (rb < we) & (wb < re)  # [T, T, KW]
        Pji = Pji | o.any(axis=2)
    if combine_pji is not None:
        # sharded resolver: each partition sees only its clipped ranges;
        # any genuine overlap survives clipping in at least one partition,
        # so a pmax across the mesh reconstructs the global matrix
        Pji = combine_pji(Pji)
    earlier = jnp.arange(T)[None, :] < jnp.arange(T)[:, None]
    Pf = (Pji & earlier).astype(jnp.bfloat16)

    def body(val):
        commit, _ = val
        blocked = (
            jnp.matmul(Pf, commit.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
            > 0
        )
        new = ~H & ~blocked
        return new, jnp.any(new != commit)

    commit, _ = jax.lax.while_loop(
        lambda v: v[1], body, (~H, jnp.array(True))
    )
    return commit


# ---------------------------------------------------------------------------
# Phase 3: merge committed writes + GC + coalesce (per-bucket dense)


def _log_shift_fill(val: jax.Array, have: jax.Array) -> jax.Array:
    """Forward-fill along axis 1: val where have, else last earlier value.
    Hillis-Steele log passes (no gathers)."""
    n = val.shape[1]
    shift = 1
    while shift < n:
        pv = jnp.pad(val, ((0, 0), (shift, 0)))[:, :n]
        ph = jnp.pad(have, ((0, 0), (shift, 0)))[:, :n]
        val = jnp.where(have, val, pv)
        have = have | ph
        shift <<= 1
    return val


def staging_slots(n_slots: int) -> int:
    """Staging-plane width per touched bucket (distinct new boundaries a
    single batch may land in one bucket before the host must repivot)."""
    return max(4, n_slots // 2)


def merge_writes(
    state: GridState,
    batch: Batch,
    commit: jax.Array,
    now: jax.Array,
    oldest: jax.Array,
) -> tuple[GridState, jax.Array]:
    """Raise V(k) to max(V(k), now) over committed write ranges; GC below
    ``oldest``; coalesce equal steps. Returns (new_state, pressure) where
    ``pressure`` = int32[2]: [max staged rows in any bucket (overflow if
    > staging_slots(S)), max kept rows in any bucket (overflow if > S)].

    Cost is proportional to what the batch touches, not the grid: the full
    sort/fill/compact merge runs only over the <= 2W buckets holding a
    staged endpoint ([U, S + S2] where U = 2W); buckets merely *spanned* by
    a committed write (covered, no endpoint inside) collapse to a single
    gap at version ``now`` in one dense masked pass — the analog of the
    reference separating probe from insert (SkipList.cpp:524 CheckMax vs
    :511 addConflictRanges), keyed on the observation that a fully covered
    bucket's whole step function becomes max(base, now) = now."""
    B, S, Lp1 = state.grid.shape
    L = Lp1 - 1
    T, KW, _ = batch.wb.shape
    Wtot = T * KW
    N2 = 2 * Wtot
    S2 = staging_slots(S)
    U = min(N2, B)  # distinct touched buckets is bounded by both

    w_ok = lex_lt(batch.wb, batch.we) & commit[:, None]
    c = batch.wb.reshape(Wtot, L)
    d = batch.we.reshape(Wtot, L)
    ok = w_ok.reshape(Wtot)
    okok = jnp.concatenate([ok, ok])

    # one fused rank pass for both write endpoints (same comparator)
    bcd = _rank_le(jnp.concatenate([c, d]), state.pivots)
    bc, bd = bcd[:Wtot], bcd[Wtot:]

    # staged rows: (code, ev) — begins carry +1, ends -1; invalid rows get
    # sentinel codes so they sort last
    codes = jnp.concatenate([c, d], axis=0)  # [2W, L]
    codes = jnp.where(okok[:, None], codes, SENTINEL)
    evs = jnp.concatenate(
        [jnp.where(ok, 1, 0), jnp.where(ok, -1, 0)]
    ).astype(jnp.int32)
    bkt = jnp.where(
        okok, jnp.concatenate([bc, bd]), B
    ).astype(jnp.int32)

    # sort staged rows by (bucket, code) — bucket is a monotone function
    # of code for valid rows, so this is code order with invalid rows
    # (bkt = B) pushed strictly last — then AGGREGATE equal-code runs:
    # one staged row per distinct boundary, carrying the run's event sum.
    # Without this, a hot-key batch (many txns writing the same key)
    # would stage more same-code rows than any repivoting could split.
    # Bucket must lead the sort keys: a VALID endpoint whose code is the
    # all-0xFF sentinel (a clear_range to end-of-keyspace) would otherwise
    # interleave with padding rows and break the run detection below.
    cols = (bkt,) + tuple(codes[:, i] for i in range(L)) + (evs,)
    sorted_cols = jax.lax.sort(cols, num_keys=L + 1)
    sb = sorted_cols[0]
    scode = jnp.stack(sorted_cols[1 : L + 1], axis=1)
    sev = sorted_cols[L + 1]

    valid = sb < B
    code_new = jnp.concatenate(
        [
            jnp.ones(1, bool),
            (scode[1:] != scode[:-1]).any(axis=1) | (sb[1:] != sb[:-1]),
        ]
    )
    code_last = jnp.concatenate([code_new[1:], jnp.ones(1, bool)])
    bkt_new = jnp.concatenate([jnp.ones(1, bool), sb[1:] != sb[:-1]])
    bkt_last = jnp.concatenate([bkt_new[1:], jnp.ones(1, bool)])

    pe = jnp.cumsum(sev)
    pe_prev = jnp.concatenate([jnp.zeros(1, jnp.int32), pe[:-1]])
    # event prefix just before each run, forward-filled across the run
    pe_before_run = _log_shift_fill(
        jnp.where(code_new, pe_prev, 0)[None, :], code_new[None, :]
    )[0]
    agg_ev = pe - pe_before_run  # valid at run-last rows
    pe_before_bkt = _log_shift_fill(
        jnp.where(bkt_new, pe_prev, 0)[None, :], bkt_new[None, :]
    )[0]
    bkt_ev = pe - pe_before_bkt  # at bucket-last rows: the bucket's Σ ev

    # touched-bucket ordinal u (constant within a bucket's run of rows)
    # and distinct-code slot within the bucket
    ucum = jnp.cumsum((bkt_new & valid).astype(jnp.int32)) - 1
    ccum = jnp.cumsum((code_new & valid).astype(jnp.int32))
    ccum_at_bkt = _log_shift_fill(
        jnp.where(bkt_new, ccum - 1, 0)[None, :], bkt_new[None, :]
    )[0]
    slot = ccum - 1 - ccum_at_bkt

    max_staged = jnp.max(jnp.where(code_last & valid, slot + 1, 0))

    # staging planes [U, S2]: scatter run-last rows (flat 1-D index)
    flat = jnp.where(
        code_last & valid & (slot < S2), ucum * S2 + slot, U * S2
    )
    st_code = jnp.full((U * S2 + 1, L), SENTINEL, dtype=jnp.uint32)
    st_code = st_code.at[flat].set(scode, mode="drop")[: U * S2].reshape(
        U, S2, L
    )
    st_ev = jnp.zeros((U * S2 + 1,), jnp.int32).at[flat].set(
        agg_ev, mode="drop"
    )[: U * S2].reshape(U, S2)

    # touched bucket ids [U] (B = unused slot)
    tid = jnp.full((U + 1,), B, jnp.int32).at[
        jnp.where(bkt_new & valid, ucum, U)
    ].set(sb, mode="drop")[:U]

    # per-bucket event sums → carry[b] = Σ ev in buckets < b (a write
    # spanning buckets keeps later buckets covered until its end event)
    evsum_B = jnp.zeros((B + 1,), jnp.int32).at[
        jnp.where(bkt_last & valid, sb, B)
    ].add(jnp.where(bkt_last & valid, bkt_ev, 0), mode="drop")[:B]
    carry = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(evsum_B)[:-1]]
    )

    # gather the touched buckets' subgrids and merge [U, S + S2]
    tid_c = jnp.minimum(tid, B - 1)
    u_live = tid < B
    old = state.grid[tid_c]  # [U, S, L+1] block gather
    old_used = (
        jnp.arange(S)[None, :] < state.count[tid_c][:, None]
    ) & u_live[:, None]
    old_code = jnp.where(old_used[..., None], old[..., :L], SENTINEL)
    # fold the bucket floor into the rows now that we're rewriting them
    old_ver = jnp.where(
        old_used,
        jnp.maximum(old[..., L].astype(jnp.int32), state.floor[tid_c][:, None]),
        0,
    )

    M = S + S2
    m_code = jnp.concatenate([old_code, st_code], axis=1)  # [U, M, L]
    # pack (ver, ev, old) into ONE int32 payload: a row is EITHER an old
    # grid row (ver, old=1, ev=0) or a staged event row (ev, old=0,
    # ver=0), so bit 0 tags the kind and the rest carries the value.
    # Versions stay < 2^30 (the host rebases at _INT32_REBASE_THRESHOLD,
    # tpu_backend.py), so ver << 1 cannot overflow; ev ∈ [-2W, 2W] ≪
    # _EV_OFF. One payload operand instead of three = a third of the
    # bitonic sort's non-key traffic (BENCH_NOTES r4 attack list).
    packed_old = jnp.where(
        old_used, (old_ver << 1) | 1, jnp.int32(_EV_OFF << 1)
    )
    packed_st = (st_ev + _EV_OFF) << 1
    m_pk = jnp.concatenate([packed_old, packed_st], axis=1)

    # sort by code only: within an equal-code run the fills/prefix sums
    # below are order-independent (the run-last row sees the full prefix,
    # and at most one old row exists per code)
    cols = tuple(m_code[..., i] for i in range(L)) + (m_pk,)
    sorted_cols = jax.lax.sort(cols, dimension=1, num_keys=L)
    g_code = jnp.stack(sorted_cols[:L], axis=-1)  # [U, M, L]
    g_pk = sorted_cols[L]
    g_old = (g_pk & 1) == 1
    g_ver = jnp.where(g_old, g_pk >> 1, 0)
    g_ev = jnp.where(g_old, 0, (g_pk >> 1) - _EV_OFF)

    # forward-fill gap base values from old rows
    base = _log_shift_fill(jnp.where(g_old, g_ver, 0), g_old)

    # coverage prefix: gap starting at row m is covered iff carry + Σ ev > 0
    carry_in = jnp.where(u_live, carry[tid_c], 0)
    cov = carry_in[:, None] + jnp.cumsum(g_ev, axis=1)
    covered = cov > 0

    nv = jnp.where(covered, jnp.maximum(base, now), base)
    nv = jnp.where(nv < oldest, 0, nv)

    is_sent = (g_code == SENTINEL).all(axis=-1)
    # dedupe: keep last row of each equal-code run (it has the full prefix)
    nxt_differs = jnp.concatenate(
        [
            (g_code[:, 1:] != g_code[:, :-1]).any(axis=-1),
            jnp.ones((U, 1), bool),
        ],
        axis=1,
    )
    keep = (~is_sent) & nxt_differs
    # coalesce: drop a run whose value equals the previous run's value
    # (transitive through dropped runs, since equality is transitive).
    # Previous run's value = nv at the row just before this run's first
    # row; broadcast it across the run with a forward fill. The first run
    # of each bucket (the pivot boundary) sees the pad value -1, never
    # equal to a version, so it is always kept — preserving the
    # slot-0-is-the-pivot invariant.
    shifted_nv = jnp.pad(nv, ((0, 0), (1, 0)), constant_values=-1)[:, :M]
    first_of_run = jnp.concatenate(
        [
            jnp.ones((U, 1), bool),
            (g_code[:, 1:] != g_code[:, :-1]).any(axis=-1),
        ],
        axis=1,
    )
    pval = _log_shift_fill(
        jnp.where(first_of_run, shifted_nv, 0), first_of_run
    )
    keep = keep & (nv != pval)

    kept_cnt = keep.sum(axis=1, dtype=jnp.int32)
    max_kept = jnp.max(jnp.where(u_live, kept_cnt, 0))

    # compact: stable sort by !keep, take first S rows
    cols = (jnp.where(keep, 0, 1).astype(jnp.int32),) + tuple(
        g_code[..., i] for i in range(L)
    ) + (nv,)
    sorted_cols = jax.lax.sort(cols, dimension=1, num_keys=1, is_stable=True)
    out_code = jnp.stack(sorted_cols[1 : L + 1], axis=-1)[:, :S, :]
    out_ver = sorted_cols[L + 1][:, :S]

    new_count_u = jnp.minimum(kept_cnt, S)
    used = jnp.arange(S)[None, :] < new_count_u[:, None]
    out_code = jnp.where(used[..., None], out_code, SENTINEL)
    out_ver = jnp.where(used, out_ver, 0)
    out_rows = jnp.concatenate(
        [out_code, out_ver.astype(jnp.uint32)[..., None]], axis=-1
    )
    out_bmax = jnp.max(out_ver, axis=1)

    # scatter merged subgrids back (unused u slots have tid == B → dropped);
    # their floor is folded into the rewritten rows, so it resets to 0
    new_grid = state.grid.at[tid].set(out_rows, mode="drop")
    new_count = state.count.at[tid].set(new_count_u, mode="drop")
    new_bmax = state.bmax.at[tid].set(out_bmax, mode="drop")
    new_floor = state.floor.at[tid].set(0, mode="drop")

    # untouched-but-covered buckets (a committed write spans them without
    # an endpoint inside): every gap's effective version becomes
    # max(base, now) = now — expressed as a floor raise, two O(B) masked
    # passes instead of rewriting the whole [B, S, L+1] grid
    is_touched = jnp.zeros((B + 1,), bool).at[tid].set(True, mode="drop")[:B]
    covered_b = (carry > 0) & ~is_touched
    new_floor = jnp.where(covered_b, jnp.maximum(new_floor, now), new_floor)
    new_bmax = jnp.where(covered_b, jnp.maximum(new_bmax, now), new_bmax)

    pressure = jnp.stack([max_staged, max_kept])
    return (
        GridState(state.pivots, new_grid, new_count, new_bmax, new_floor),
        pressure,
    )


# ---------------------------------------------------------------------------
# Full resolver step


def _resolve_one(state, batch, now, oldest_pre, oldest_post):
    too_old = batch.t_has_reads & (batch.t_snap < oldest_pre)
    H = history_conflicts(state, batch) | too_old
    commit = intra_batch_commits(batch, H)
    new_state, pressure = merge_writes(state, batch, commit, now, oldest_post)
    verdicts = jnp.where(
        too_old,
        jnp.int8(TOO_OLD),
        jnp.where(commit, jnp.int8(COMMITTED), jnp.int8(CONFLICT)),
    )
    return new_state, verdicts, pressure


@functools.partial(jax.jit, donate_argnames=("state",))
def resolve_batch(
    state: GridState,
    batch: Batch,
    now: jax.Array,
    oldest_pre: jax.Array,
    oldest_post: jax.Array,
):
    """One batch end-to-end. Returns (state, verdicts int8[T], pressure)."""
    return _resolve_one(state, batch, now, oldest_pre, oldest_post)


@functools.partial(jax.jit, donate_argnames=("state",))
def resolve_many(
    state: GridState,
    batches: Batch,  # leading group axis G on every leaf
    nows: jax.Array,
    oldests_pre: jax.Array,
    oldests_post: jax.Array,
):
    """G batches in one dispatch via lax.scan (state threads on device) —
    the device-side analog of the reference's pipelined commit batches
    (MasterProxyServer.actor.cpp:353). Returns (state, verdicts int8[G,T],
    pressure int32[2] = max over the group)."""

    def step(st, inp):
        batch, now, old_pre, old_post = inp
        st2, verdicts, pressure = _resolve_one(st, batch, now, old_pre, old_post)
        return st2, (verdicts, pressure)

    state, (verdicts, pressures) = jax.lax.scan(
        step, state, (batches, nows, oldests_pre, oldests_post)
    )
    return state, verdicts, jnp.max(pressures, axis=0)


@jax.jit
def rebase(state: GridState, delta: jax.Array) -> GridState:
    """Shift the version origin by ``delta`` (host advances its base)."""
    ver = state.grid[..., -1].astype(jnp.int32)
    used = jnp.arange(state.grid.shape[1])[None, :] < state.count[:, None]
    ver = jnp.where(used, jnp.maximum(ver - delta, 0), 0)
    grid = jnp.concatenate(
        [state.grid[..., :-1], ver.astype(jnp.uint32)[..., None]], axis=-1
    )
    floor = jnp.maximum(state.floor - delta, 0)
    bmax = jnp.maximum(jnp.max(ver, axis=1), floor)
    return GridState(state.pivots, grid, state.count, bmax, floor)


@functools.partial(jax.jit, static_argnums=(1, 2))
def reshard_device(
    state: GridState, n_buckets: int, n_slots: int
) -> tuple[GridState, jax.Array]:
    """Rebalance the grid ON DEVICE: new pivots = row-count quantiles of
    the live boundary set, every live row permuted into its new bucket.
    No host round trip — the grid (tens of MB) never crosses the tunnel,
    which is what made host resharding cost ~2s.

    Because pivots are chosen FROM the live boundaries, each new bucket's
    first assigned row is exactly its pivot row, so the slot-0-is-the-pivot
    invariant holds with no insertion step, and inheritance is implicit.

    Returns (new_state, pressure): pressure = max rows any new bucket
    needs; if > n_slots the caller must retry with more buckets (rows were
    dropped — the state is unusable)."""
    B, S, Lp1 = state.grid.shape
    L = Lp1 - 1
    N = B * S
    used = (jnp.arange(S)[None, :] < state.count[:, None]).reshape(N)
    code = jnp.where(
        used[:, None], state.grid[..., :L].reshape(N, L), SENTINEL
    )
    # fold each bucket's floor into its rows (the output grid starts with
    # floor 0 everywhere)
    ver_f = jnp.maximum(
        state.grid[..., L].astype(jnp.int32), state.floor[:, None]
    ).reshape(N)
    ver = jnp.where(used, ver_f.astype(state.grid.dtype), 0)

    # compact live rows to the front, preserving global key order (rows
    # are sorted within buckets and buckets are ordered): prefix-sum
    # destination + scatter — stable by construction and far cheaper to
    # compile and run than a 1M-row multi-operand sort
    n_live = used.sum(dtype=jnp.int32)
    dest = jnp.cumsum(used.astype(jnp.int32)) - 1
    dest = jnp.where(used, dest, N)
    lcode = jnp.full((N + 1, L), SENTINEL, dtype=jnp.uint32).at[dest].set(
        code, mode="drop"
    )[:N]
    lver = jnp.zeros((N + 1,), ver.dtype).at[dest].set(ver, mode="drop")[:N]
    lused = jnp.arange(N, dtype=jnp.int32) < n_live

    # block partitioning: row j's new bucket = j // q with q =
    # ceil(n_live / n_buckets) — exactly balanced by construction, and
    # every quantity stays well inside int32 (the previous strided
    # quantile-index form computed (i-1)*(n_live-1), which OVERFLOWS
    # int32 once Bp·n_live passes 2^31: pivots past the overflow point
    # were garbage and one bucket swallowed the whole tail). Pivots are
    # block-start rows, so the slot-0-is-the-pivot invariant holds with
    # no insertion step, and live codes being distinct keeps pivots
    # distinct. Pivot 0 = the smallest live boundary (the state's lower
    # bound: zero code for a full-range grid, the partition's lower
    # bound for a sharded resolver's shard).
    q = jnp.maximum((n_live + n_buckets - 1) // n_buckets, 1)
    pos = jnp.arange(N, dtype=jnp.int32)
    nb = jnp.where(lused, pos // q, n_buckets).astype(jnp.int32)
    slot = pos - (pos // q) * q
    pressure = jnp.max(jnp.where(lused, slot + 1, 0))

    # bucket b's pivot = live row b·q (SENTINEL past the last used bucket;
    # b·q ≤ N for b ≤ n_buckets since q ≥ N/n_buckets never holds — clamp)
    pidx = jnp.minimum(
        jnp.arange(n_buckets, dtype=jnp.int32) * q, jnp.int32(N)
    )
    new_pivots = jnp.full((n_buckets, L), SENTINEL, dtype=jnp.uint32)
    new_pivots = jnp.where(
        (pidx < n_live)[:, None],
        jnp.concatenate([lcode, jnp.full((1, L), SENTINEL, jnp.uint32)])[pidx],
        new_pivots,
    )

    flat = jnp.where(
        lused & (slot < n_slots), nb * n_slots + slot, n_buckets * n_slots
    )
    rows = jnp.concatenate([lcode, lver[:, None]], axis=1)
    g = jnp.full((n_buckets * n_slots + 1, Lp1), SENTINEL, dtype=jnp.uint32)
    g = g.at[flat].set(rows, mode="drop")[: n_buckets * n_slots]
    new_grid = g.reshape(n_buckets, n_slots, Lp1)
    is_row = (new_grid[..., :L] != SENTINEL).any(axis=-1)
    new_count = is_row.sum(axis=1, dtype=jnp.int32)
    out_ver = jnp.where(is_row, new_grid[..., L].astype(jnp.int32), 0)
    new_grid = jnp.concatenate(
        [new_grid[..., :L], out_ver.astype(jnp.uint32)[..., None]], axis=-1
    )
    new_bmax = jnp.max(out_ver, axis=1)
    return (
        GridState(
            new_pivots,
            new_grid,
            new_count,
            new_bmax,
            jnp.zeros((n_buckets,), jnp.int32),  # floors folded into rows
        ),
        pressure,
    )


# ---------------------------------------------------------------------------
# Host-side construction / resharding (rare, numpy)


def make_state(n_buckets: int, n_slots: int, lanes: int) -> GridState:
    """Fresh index: one live bucket [0, ∞) with version 0 everywhere."""
    pivots = np.full((n_buckets, lanes), 0xFFFFFFFF, dtype=np.uint32)
    pivots[0] = 0
    grid = np.full((n_buckets, n_slots, lanes + 1), 0xFFFFFFFF, dtype=np.uint32)
    grid[..., lanes] = 0
    grid[0, 0, :lanes] = 0
    count = np.zeros((n_buckets,), np.int32)
    count[0] = 1
    return GridState(
        pivots=jnp.asarray(pivots),
        grid=jnp.asarray(grid),
        count=jnp.asarray(count),
        bmax=jnp.zeros((n_buckets,), jnp.int32),
        floor=jnp.zeros((n_buckets,), jnp.int32),
    )


def occupancy_stats(state: GridState) -> dict:
    """Bucket-occupancy / headroom gauges for the kernel's
    CounterCollection (status document + bench provenance). Host numpy
    over the small per-bucket arrays only — the [B, S, L+1] grid itself
    never crosses the tunnel."""
    count = np.asarray(state.count)
    B, S, _ = state.grid.shape
    live = int(count.sum())
    worst = int(count.max(initial=0))
    return {
        "liveRows": live,
        "usedBuckets": int((count > 0).sum()),
        "bucketCount": int(B),
        "slotCapacity": int(S),
        "maxBucketRows": worst,
        "slotHeadroom": int(S - worst),
        "fillFraction": round(live / float(B * S), 6),
    }


def codes_to_bytes(codes: np.ndarray) -> np.ndarray:
    """uint32[N, L] lane codes → void-dtype byte keys whose memcmp order
    equals lane order (big-endian), for vectorized searchsorted."""
    n, L = codes.shape
    be = np.ascontiguousarray(codes.astype(">u4"))
    return be.view(np.dtype((np.void, 4 * L))).reshape(n)


def live_rows(state: GridState) -> tuple[np.ndarray, np.ndarray]:
    """(codes uint32[N, L], versions int64[N]) of all live boundaries, in
    global key order (buckets are ordered and sorted internally). Bucket
    floors are folded into the returned versions."""
    grid = np.asarray(state.grid)
    count = np.asarray(state.count)
    floor = np.asarray(state.floor)
    B_old, S_old, Lp1 = grid.shape
    used = np.arange(S_old)[None, :] < count[:, None]
    codes = grid[..., : Lp1 - 1][used]
    vers = grid[..., Lp1 - 1].astype(np.int64)
    vers = np.maximum(vers, floor[:, None].astype(np.int64))[used]
    return codes, vers


def reshard_host(
    state: GridState, new_pivot_codes: np.ndarray, n_buckets: int, n_slots: int
) -> GridState:
    """Rebuild the grid under new pivots (vectorized numpy; rare — init,
    growth, or skew). Preserves the step function exactly: every live
    boundary is re-bucketed and each new pivot becomes a boundary
    inheriting the value of the gap containing it."""
    grid = np.asarray(state.grid)
    Lp1 = grid.shape[-1]
    L = Lp1 - 1

    codes, vers = live_rows(state)
    keys = codes_to_bytes(codes)

    piv = np.asarray(new_pivot_codes, dtype=np.uint32).reshape(-1, L)
    # pivot 0 must EQUAL the smallest live boundary (the state's lower
    # bound by the slot-0 invariant: the zero code for a full-range grid,
    # the partition's lower bound for a mesh shard) — a pivot below it
    # would make searchsorted-1 yield -1 and inherit a garbage version
    # from the last row
    assert tuple(piv[0].tolist()) == tuple(codes[0].tolist()), (
        "pivot 0 must equal the smallest live boundary"
    )
    P = piv.shape[0]
    assert P <= n_buckets
    piv_keys = codes_to_bytes(piv)

    # pivot rows inherit the value of the gap containing them (live row 0
    # is always the old bucket-0 pivot at code 0, so idx >= 0)
    idx = np.searchsorted(keys, piv_keys, side="right") - 1
    inherit = vers[idx]

    # combined row set: pivots first so an equal-coded live row (sorted
    # after) wins the dedupe-keep-last rule
    all_codes = np.concatenate([piv, codes])
    all_vers = np.concatenate([inherit, vers])
    all_bkt = np.concatenate(
        [
            np.arange(P, dtype=np.int64),
            np.searchsorted(piv_keys, keys, side="right") - 1,
        ]
    )
    all_keys = codes_to_bytes(all_codes)
    is_piv = np.concatenate(
        [np.ones(P, dtype=np.int8), np.zeros(len(vers), dtype=np.int8)]
    )
    order = np.lexsort((1 - is_piv, all_keys))  # by key, pivots first
    k_s = all_keys[order]
    v_s = all_vers[order]
    b_s = all_bkt[order]
    c_s = all_codes[order]
    p_s = is_piv[order].astype(bool)

    # dedupe equal keys keeping the LAST (live-row value wins over pivot
    # inheritance); a deduped-away pivot row keeps its pivot-ness
    n = len(k_s)
    last = np.ones(n, dtype=bool)
    last[:-1] = k_s[:-1] != k_s[1:]
    first = np.ones(n, dtype=bool)
    first[1:] = k_s[1:] != k_s[:-1]
    # propagate pivot flag to the kept (last) row of each run: runs have
    # length 1 or 2 (pivot + live row), so OR with the previous row
    piv_kept = p_s.copy()
    piv_kept[1:] |= p_s[:-1] & ~first[1:]

    k_d = k_s[last]
    v_d = v_s[last]
    b_d = b_s[last]
    c_d = c_s[last]
    p_d = piv_kept[last]

    # coalesce: drop rows whose value equals the previous kept row's value
    # — except pivot rows, which always stay (slot 0 invariant). Equality
    # is transitive, so compare against the previous ROW after noting that
    # dropped rows always share the kept predecessor's value.
    m = len(k_d)
    prev_val = np.empty(m, dtype=np.int64)
    prev_val[0] = -1
    prev_val[1:] = v_d[:-1]
    keep = p_d | (v_d != prev_val)
    # a non-pivot row after a DROPPED row: compare against the last kept
    # value — iterate via np: since dropped rows have value == their
    # predecessor's, chains of equal values collapse; keep = value changed
    # from previous row, or pivot. (A row equal to a dropped predecessor
    # is equal to the kept ancestor too — transitive — so this is exact.)

    k_k = k_d[keep]
    v_k = v_d[keep]
    b_k = b_d[keep]
    c_k = c_d[keep]

    # slot index within bucket
    nkeep = len(k_k)
    bucket_first = np.ones(nkeep, dtype=bool)
    bucket_first[1:] = b_k[1:] != b_k[:-1]
    pos = np.arange(nkeep, dtype=np.int64)
    run_start = np.maximum.accumulate(np.where(bucket_first, pos, 0))
    slot = pos - run_start

    counts = np.zeros(n_buckets, dtype=np.int64)
    np.add.at(counts, b_k, 1)
    if counts.max(initial=0) > n_slots:
        worst = int(counts.argmax())
        raise OverflowError(
            f"bucket {worst} needs {int(counts[worst])} slots > {n_slots}"
        )

    new_grid = np.full((n_buckets, n_slots, Lp1), 0xFFFFFFFF, dtype=np.uint32)
    new_grid[..., L] = 0
    new_grid[b_k, slot, :L] = c_k
    new_grid[b_k, slot, L] = v_k.astype(np.uint32)
    new_count = counts.astype(np.int32)
    new_bmax = np.zeros(n_buckets, dtype=np.int64)
    np.maximum.at(new_bmax, b_k, v_k)

    new_pivots = np.full((n_buckets, L), 0xFFFFFFFF, dtype=np.uint32)
    new_pivots[:P] = piv
    return GridState(
        pivots=jnp.asarray(new_pivots),
        grid=jnp.asarray(new_grid),
        count=jnp.asarray(new_count),
        bmax=jnp.asarray(new_bmax.astype(np.int32)),
        floor=jnp.zeros((n_buckets,), jnp.int32),  # folded by live_rows
    )
