"""Seeded device-fault injection at the conflict-kernel seam (sim-only).

The deterministic-simulation answer to "what happens when the TPU behind
``newConflictSet()`` breaks" (our bench history says it will: the tunnel
has been wedged since round 4, BENCH_NOTES.md). A ``KernelFaultInjector``
rolls four named fault kinds from a forked seeded RNG — optionally armed
through this run's BUGGIFY sites, so chaos soaks exercise them organically
— and ``FaultInjectingConflictSet`` applies them in front of a real device
backend:

- **dispatch error**: a transient exception out of the dispatch path (the
  resolver's bounded in-place retry should absorb it);
- **device loss**: every dispatch/clear raises until the loss heals at a
  seeded virtual-time horizon (drives journal-replay failover, then
  re-promotion once probes pass);
- **hang**: the dispatch "completes" but its results never arrive — an
  infinite stall the resolver's per-batch deadline must convert into a
  recovery instead of a wedged commit pipeline;
- **compile stall**: a finite stall (a first-shape compile, a slow tunnel
  round trip) that should ride under the deadline without failover.

Stalls are modeled as *virtual-time* waits the resolver performs under its
deadline (``take_stall()``), so same-seed runs replay byte-identically and
the deadline machinery is genuinely exercised in simulation — exactly the
sim-mode-twin discipline of SURVEY.md §4.

Every fired fault is recorded under a NAMED buggify site
(``("conflict/faults.py", "kernel-…")``), so the soak's fired-site
coverage report (tools/soak.py) shows which kernel faults a run hit.
"""

from __future__ import annotations

from ..runtime.buggify import buggify, mark_fired
from ..runtime.loop import now


class KernelFaultError(Exception):
    """Base of conflict-kernel faults. ``transient`` marks errors a bounded
    in-place dispatch retry may absorb; everything else escalates to the
    resolver's journal-replay recovery (conflict/failover.py)."""

    transient = False


class KernelTransientError(KernelFaultError):
    """Retryable dispatch failure (spurious device/tunnel error)."""

    transient = True


class StaleEncodingError(KernelTransientError):
    """The encoded payload no longer matches the backend — the version
    base was rebased or the guard swapped backends between ``encode()``
    and dispatch (the double-buffered pipeline encodes batch N while
    batch N-1 is still on device, so this window is real). Transient by
    construction: the resolver re-encodes and retries in place."""


class KernelDeviceLostError(KernelFaultError):
    """The device is gone; rebuild or failover — in-place retry is futile."""


class KernelTimeoutError(KernelFaultError):
    """The per-batch dispatch deadline (CONFLICT_DISPATCH_DEADLINE) passed
    with the device still silent — raised by the resolver, not injected."""


# named buggify sites — stable keys for the soak's fired-site coverage
SITE_DISPATCH_ERROR = ("conflict/faults.py", "kernel-dispatch-error")
SITE_DEVICE_LOSS = ("conflict/faults.py", "kernel-device-loss")
SITE_HANG = ("conflict/faults.py", "kernel-dispatch-hang")
SITE_COMPILE_STALL = ("conflict/faults.py", "kernel-compile-stall")
SITE_ENCODE_ERROR = ("conflict/faults.py", "kernel-encode-error")
SITE_ENCODE_HANG = ("conflict/faults.py", "kernel-encode-hang")

KERNEL_FAULT_SITES = (
    SITE_DISPATCH_ERROR,
    SITE_DEVICE_LOSS,
    SITE_HANG,
    SITE_COMPILE_STALL,
    SITE_ENCODE_ERROR,
    SITE_ENCODE_HANG,
)


class KernelFaultInjector:
    """Shared fault state + seeded RNG. Lives OUTSIDE the backend instance
    it wraps, so an injected device loss survives the failover machinery's
    fresh backend constructions (a rebuilt index on a dead device must
    still fail until the loss heals)."""

    def __init__(
        self,
        rng,
        p_dispatch_error: float = 0.05,
        p_device_loss: float = 0.02,
        p_hang: float = 0.02,
        p_compile_stall: float = 0.05,
        p_encode_error: float = 0.03,
        p_encode_hang: float = 0.01,
        loss_duration: float = 1.0,
        stall_seconds: float = 0.25,
    ):
        self.rng = rng
        self.p_dispatch_error = p_dispatch_error
        self.p_device_loss = p_device_loss
        self.p_hang = p_hang
        self.p_compile_stall = p_compile_stall
        self.p_encode_error = p_encode_error
        self.p_encode_hang = p_encode_hang
        self.loss_duration = loss_duration
        self.stall_seconds = stall_seconds
        self._lost_until = 0.0
        self._pending_stall: float = None
        self.counts: dict[str, int] = {}  # site tag → times fired

    def _roll(self, p: float, site: tuple) -> bool:
        # two arming paths, both seeded: this injector's own RNG fork
        # (focused tests pin probabilities) OR the run's BUGGIFY machinery
        # (chaos soaks arm sites organically). Either way the named site
        # lands in the run's fired-site coverage.
        hit = buggify(site)
        if not hit and p > 0 and self.rng.coinflip(p):
            hit = True
            mark_fired(site)
        if hit:
            self.counts[site[1]] = self.counts.get(site[1], 0) + 1
        return hit

    @property
    def device_lost(self) -> bool:
        return now() < self._lost_until

    def lose_device(self, duration: float = None) -> None:
        """Force a loss episode (workloads/tests drive kill/heal cycles)."""
        self._lost_until = now() + (
            self.loss_duration if duration is None else duration
        )

    def on_dispatch(self) -> None:
        """Called in front of every device dispatch/clear; raises the
        injected fault or arms a stall for ``take_stall()``."""
        if self.device_lost:
            raise KernelDeviceLostError(
                "injected device loss (heals at %.3f)" % self._lost_until
            )
        if self._roll(self.p_device_loss, SITE_DEVICE_LOSS):
            self._lost_until = now() + self.loss_duration
            raise KernelDeviceLostError(
                "injected device loss (heals at %.3f)" % self._lost_until
            )
        if self._roll(self.p_dispatch_error, SITE_DISPATCH_ERROR):
            raise KernelTransientError("injected transient dispatch error")
        if self._roll(self.p_hang, SITE_HANG):
            self._pending_stall = float("inf")
        elif self._roll(self.p_compile_stall, SITE_COMPILE_STALL):
            self._pending_stall = self.stall_seconds

    def on_encode(self) -> None:
        """Called in front of every host encode on the encode executor —
        the double-buffered pipeline's off-loop thread. A raised error
        fails the encode future (the resolver's bounded retry re-encodes);
        an armed hang models an encode thread wedged on a poisoned batch,
        which the resolver's dispatch deadline must bound."""
        if self._roll(self.p_encode_error, SITE_ENCODE_ERROR):
            raise KernelTransientError("injected encode-executor error")
        if self._roll(self.p_encode_hang, SITE_ENCODE_HANG):
            self._pending_stall = float("inf")

    def take_stall(self):
        """Seconds the in-flight dispatch should stall (inf = never
        completes), or None. Consumed once per armed fault."""
        s, self._pending_stall = self._pending_stall, None
        return s


class FaultInjectingConflictSet:
    """Sim-only wrapper over a device ConflictSet: same interface, with the
    injector consulted in front of every dispatch. Selected through
    ``new_conflict_set(..., fault_injector=...)`` (conflict/api.py)."""

    def __init__(self, inner, injector: KernelFaultInjector):
        assert hasattr(inner, "detect_many_encoded_async"), (
            "fault injection targets the device (async-dispatch) backends"
        )
        self.inner = inner
        self.injector = injector

    # -- passthrough state ----------------------------------------------------

    @property
    def metrics(self):
        return self.inner.metrics

    @property
    def oldest_version(self) -> int:
        return self.inner.oldest_version

    def warm_compile(self) -> None:
        fn = getattr(self.inner, "warm_compile", None)
        if fn is not None:
            fn()  # scratch-state compile: not a dispatch, never injected

    def prepare(self, now_version: int) -> None:
        self.inner.prepare(now_version)

    def encode(self, transactions):
        self.injector.on_encode()
        return self.inner.encode(transactions)

    def take_stall(self):
        return self.injector.take_stall()

    # -- injected dispatch paths ----------------------------------------------

    def clear(self, version: int) -> None:
        self.injector.on_dispatch()
        self.inner.clear(version)

    def detect_batch(self, transactions, now, new_oldest_version):
        self.injector.on_dispatch()
        return self.inner.detect_batch(transactions, now, new_oldest_version)

    def detect_many(self, work):
        self.injector.on_dispatch()
        return self.inner.detect_many(work)

    def detect_many_encoded(self, work):
        self.injector.on_dispatch()
        return self.inner.detect_many_encoded(work)

    def detect_many_encoded_async(self, work):
        self.injector.on_dispatch()
        return self.inner.detect_many_encoded_async(work)
