"""TPU-resident versioned write-range index — the conflict-detection kernel.

This is the TPU-native replacement for the reference's versioned skip list
(fdbserver/SkipList.cpp): where the skip list keeps per-node "version
pyramids" (SkipList.cpp:281-377) probed one read-range at a time
(checkReadConflictRanges, SkipList.cpp:1210), this kernel keeps the whole
MVCC write history as a *step function over keyspace*:

    bounds: uint32[P, L]  — sorted, de-duplicated boundary key codes
                            (L lanes per key, conflict/keys.py); unused
                            capacity padded with an all-0xFF sentinel
    vers:   int32[P]      — max committed-write version of the half-open gap
                            [bounds[i], bounds[i+1]); 0 = never written /
                            forgotten (older than the GC horizon)
    tree:   int32[2P]     — segment tree over ``vers`` for O(log P) range-max

Everything is functional and jit-compiled with static shapes:

- history check  = vectorized lexicographic binary search of every read
  range's endpoints (2·log2(P) gathers for the whole batch) + segment-tree
  range-max, compared against each transaction's read snapshot;
- intra-batch check (the reference's MiniConflictSet, SkipList.cpp:1028) =
  write-coverage bitmaps over the batch's own boundary partition built with
  scatter-add + prefix sums, then a fixpoint of the in-order greedy
  commit recursion (converges in dependency-depth iterations);
- merge (mergeWriteConflictRanges, SkipList.cpp:1260) = parallel sorted
  merge of committed write boundaries into ``bounds`` + recomputed gap
  versions, with equal-value gap coalescing doubling as incremental GC
  (removeBefore, SkipList.cpp:665).

Versions on device are int32 offsets from a host-tracked base (versions are
int64 host-side; the MVCC window is ~5s ≈ 5M versions, so offsets fit
comfortably; the host rebases long before overflow).

All shapes (P capacity, L lanes, R/W/T batch buckets) are static per jit
specialization; the host buckets batches to powers of two to bound
recompiles.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

SENTINEL = jnp.uint32(0xFFFFFFFF)

# Verdict codes (match conflict.api.Verdict)
COMMITTED, CONFLICT, TOO_OLD = 0, 1, 2


class IndexState(NamedTuple):
    bounds: jax.Array  # uint32[P, L], sorted, sentinel-padded
    vers: jax.Array  # int32[P], 0 beyond n
    tree: jax.Array  # int32[2P], segment tree over vers (root at 1)
    n: jax.Array  # int32 scalar: live boundary count


class Batch(NamedTuple):
    """One commit batch, encoded and padded to static shapes by the host."""

    rb: jax.Array  # uint32[R, L] read-range begins
    re: jax.Array  # uint32[R, L] read-range ends (rb>=re ⇒ inactive slot)
    r_snap: jax.Array  # int32[R] rebased read snapshots
    r_owner: jax.Array  # int32[R] owning transaction index
    wb: jax.Array  # uint32[W, L] write-range begins
    we: jax.Array  # uint32[W, L] write-range ends (wb>=we ⇒ inactive slot)
    w_owner: jax.Array  # int32[W]
    t_snap: jax.Array  # int32[T] rebased per-transaction read snapshot
    t_has_reads: jax.Array  # bool[T] transaction has read conflict ranges


# ---------------------------------------------------------------------------
# Lexicographic multi-lane comparisons


def lex_lt(a: jax.Array, b: jax.Array) -> jax.Array:
    """a < b lexicographically over the trailing lane axis (broadcasts)."""
    lanes = a.shape[-1]
    lt = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]), dtype=bool)
    eq = jnp.ones_like(lt)
    for i in range(lanes):
        ai, bi = a[..., i], b[..., i]
        lt = lt | (eq & (ai < bi))
        eq = eq & (ai == bi)
    return lt


def lex_le(a: jax.Array, b: jax.Array) -> jax.Array:
    return ~lex_lt(b, a)


def _searchsorted(sorted_arr: jax.Array, q: jax.Array, side: str) -> jax.Array:
    """Vectorized binary search over a lex-sorted [P, L] array.

    side='right': first index with sorted_arr[i] >  q  (#elements <= q)
    side='left' : first index with sorted_arr[i] >= q  (#elements <  q)
    """
    P = sorted_arr.shape[0]
    steps = max(1, int(np.ceil(np.log2(P))) + 1)
    lo = jnp.zeros(q.shape[:-1], dtype=jnp.int32)
    hi = jnp.full(q.shape[:-1], P, dtype=jnp.int32)
    for _ in range(steps):
        mid = (lo + hi) >> 1
        row = sorted_arr[mid]  # gather [..., L]
        go_right = lex_le(row, q) if side == "right" else lex_lt(row, q)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
    return lo


# ---------------------------------------------------------------------------
# Segment tree (range max over gap versions)


def build_tree(vers: jax.Array) -> jax.Array:
    """vers int32[P] (P a power of two) → tree int32[2P], root at index 1."""
    levels = [vers]
    cur = vers
    while cur.shape[0] > 1:
        cur = cur.reshape(-1, 2).max(axis=1)
        levels.append(cur)
    return jnp.concatenate([jnp.zeros((1,), jnp.int32)] + levels[::-1])


def range_max(tree: jax.Array, lo: jax.Array, hi: jax.Array) -> jax.Array:
    """max(vers[lo..hi]) per query; 0 when hi < lo. Standard iterative
    bottom-up segment-tree walk, vectorized over queries."""
    P = tree.shape[0] // 2
    l = lo + P
    r = hi + P + 1  # half-open [l, r)
    m = jnp.zeros_like(lo)
    for _ in range(int(np.log2(P)) + 1):
        active = l < r
        take_l = active & ((l & 1) == 1)
        m = jnp.where(take_l, jnp.maximum(m, tree[jnp.minimum(l, 2 * P - 1)]), m)
        l = l + (l & 1)
        take_r = (l < r) & ((r & 1) == 1)
        m = jnp.where(take_r, jnp.maximum(m, tree[jnp.maximum(r - 1, 0)]), m)
        r = r - (r & 1)
        l >>= 1
        r >>= 1
    return m


# ---------------------------------------------------------------------------
# Phase 1: history conflicts (the skip list's checkReadConflictRanges)


def history_conflicts(state: IndexState, batch: Batch, num_txns: int) -> jax.Array:
    """bool[T]: transaction has a read range overlapping a write committed
    after its snapshot."""
    active = lex_lt(batch.rb, batch.re)
    lo = _searchsorted(state.bounds, batch.rb, "right") - 1
    hi = _searchsorted(state.bounds, batch.re, "left") - 1
    mx = range_max(state.tree, jnp.maximum(lo, 0), hi)
    hit = active & (mx > batch.r_snap)
    H = jnp.zeros((num_txns,), dtype=bool)
    return H.at[batch.r_owner].max(hit, mode="drop")


# ---------------------------------------------------------------------------
# Phase 2: intra-batch conflicts (the reference's MiniConflictSet,
# SkipList.cpp:1028, vectorized as coverage bitmaps + prefix sums)


def intra_batch_commits(
    batch: Batch, H: jax.Array, num_txns: int, combine_pji=None
) -> jax.Array:
    """bool[T] commit mask implementing the in-order greedy recursion
    (checkIntraBatchConflicts, SkipList.cpp:1133):

        commit[j] = !H[j] and no read range of j overlaps a write range of a
                    committed i < j

    ``combine_pji``: optional hook to combine the T×T read/write-overlap
    matrix across mesh shards (the sharded resolver pmax-reduces it over its
    data axis) before the fixpoint runs.
    """
    T = num_txns
    W = batch.wb.shape[0]
    w_active = lex_lt(batch.wb, batch.we)
    r_active = lex_lt(batch.rb, batch.re)

    # Partition keyspace by the batch's own write endpoints.
    pts = _lex_sort_rows(jnp.concatenate([batch.wb, batch.we], axis=0))  # [2W, L]

    # Gap id of key x = #points <= x, in [0, 2W]. A write [wb, we) covers gap
    # ids [right(wb), left(we)]; a read [ra, rb) intersects [right(ra), left(rb)].
    wb_g = _searchsorted(pts, batch.wb, "right")
    we_g = _searchsorted(pts, batch.we, "left")
    # Coverage per (gap, owner): scatter +1/-1 and prefix-sum over gaps.
    diff = jnp.zeros((2 * W + 2, T), dtype=jnp.int32)
    one = jnp.where(w_active, 1, 0).astype(jnp.int32)
    diff = diff.at[wb_g, batch.w_owner].add(one, mode="drop")
    diff = diff.at[we_g + 1, batch.w_owner].add(-one, mode="drop")
    covered = jnp.cumsum(diff, axis=0)[:-1] > 0  # bool[2W+1, T]
    # S[p, i] = number of covered gaps with id < p, exclusive prefix.
    S = jnp.concatenate(
        [jnp.zeros((1, T), jnp.int32), jnp.cumsum(covered.astype(jnp.int32), axis=0)]
    )

    ra_g = _searchsorted(pts, batch.rb, "right")
    rb_g = _searchsorted(pts, batch.re, "left")
    overlap = (S[rb_g + 1] - S[ra_g]) > 0  # bool[R, T]: read r vs writer i
    overlap = overlap & r_active[:, None]
    # Fold reads to their owning transaction: P[j, i] = some read of j
    # overlaps writes of i.
    Pji = jnp.zeros((T, T), dtype=bool)
    Pji = Pji.at[batch.r_owner].max(overlap, mode="drop")
    if combine_pji is not None:
        Pji = combine_pji(Pji)
    # Only earlier transactions can invalidate later ones.
    earlier = jnp.arange(T)[None, :] < jnp.arange(T)[:, None]  # [j, i]: i < j
    Pji = Pji & earlier

    # Greedy in-order recursion as a fixpoint (converges in dependency depth).
    def body(val):
        commit, _ = val
        blocked = (Pji & commit[None, :]).any(axis=1)
        new = ~H & ~blocked
        return new, jnp.any(new != commit)

    def cond(val):
        return val[1]

    commit0 = ~H
    commit, _ = jax.lax.while_loop(cond, body, (commit0, jnp.array(True)))
    return commit


def _lex_sort_rows(rows: jax.Array) -> jax.Array:
    """Sort [N, L] rows lexicographically (lane 0 most significant)."""
    cols = tuple(rows[:, i] for i in range(rows.shape[1]))
    out = jax.lax.sort(cols, num_keys=len(cols))
    return jnp.stack(out, axis=1)


# ---------------------------------------------------------------------------
# Phase 3: merge committed writes + GC + tree rebuild


def merge_writes(
    state: IndexState,
    batch: Batch,
    commit: jax.Array,
    now: jax.Array,
    oldest: jax.Array,
) -> tuple[IndexState, jax.Array]:
    """Insert committed write ranges at version ``now``; flatten versions
    below ``oldest`` to 0 and coalesce equal-value gaps (incremental GC).

    Gather-light design: after the stable positional merge of old bounds (A)
    with the batch's committed write endpoints (C), every per-gap quantity is
    derived from prefix sums over the merged array —

      rank(run)  = #A elements <= run key                  → old step value
      cover(run) = #write-begins <= run key - #write-ends  → covered by batch

    — so the only gathers against capacity-sized arrays are int32 (no
    multi-lane row gathers).

    Returns (new_state, needed): ``needed`` is the boundary count the merged
    index wanted; the host pre-grows capacity so needed <= P always holds.
    """
    P, L = state.bounds.shape
    W = batch.wb.shape[0]
    M = P + 2 * W

    w_ok = lex_lt(batch.wb, batch.we) & commit[batch.w_owner]
    sentinel_row = jnp.full((L,), SENTINEL, dtype=jnp.uint32)
    cb = jnp.where(w_ok[:, None], batch.wb, sentinel_row)
    ce = jnp.where(w_ok[:, None], batch.we, sentinel_row)
    # Sort the batch endpoints carrying a +1/-1 coverage flag.
    cpts = jnp.concatenate([cb, ce], axis=0)
    cflag = jnp.concatenate(
        [jnp.where(w_ok, 1, 0), jnp.where(w_ok, -1, 0)]
    ).astype(jnp.int32)
    cols = tuple(cpts[:, i] for i in range(L)) + (cflag,)
    sorted_cols = jax.lax.sort(cols, num_keys=L)
    C = jnp.stack(sorted_cols[:L], axis=1)  # [2W, L]
    cflag_s = sorted_cols[L]

    # Stable positional merge: A elements precede equal C elements. Only the
    # small side is binary-searched (2W queries into A); A-side positions come
    # from a histogram of C's insertion points — #C before A[i] = #{j: a_j <= i}
    # — avoiding P row-gather binary-search queries.
    A = state.bounds
    a_j = _searchsorted(A, C, "right")  # [2W] in [0, P]
    posC = jnp.arange(2 * W, dtype=jnp.int32) + a_j
    hist = jnp.zeros((P + 1,), jnp.int32).at[a_j].add(1)
    posA = jnp.arange(P, dtype=jnp.int32) + jnp.cumsum(hist)[:P]
    D0 = jnp.full((M, L), SENTINEL, dtype=jnp.uint32)
    D0 = D0.at[posA].set(A)
    D0 = D0.at[posC].set(C)
    from_a = jnp.zeros((M,), jnp.int32).at[posA].set(1)
    flag = jnp.zeros((M,), jnp.int32).at[posC].set(cflag_s)

    # Exclusive prefixes: EA[p] = #A elements before p; E[p] = #begins-#ends.
    EA = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(from_a)])
    E = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(flag)])

    # Runs of equal keys — each run is one gap of the merged step function.
    prev_differs = jnp.concatenate(
        [jnp.ones((1,), bool), (D0[1:] != D0[:-1]).any(axis=1)]
    )
    run_id = jnp.cumsum(prev_differs.astype(jnp.int32)) - 1  # [M]
    starts = jnp.full((M + 1,), M, jnp.int32)
    starts = starts.at[run_id].min(jnp.arange(M, dtype=jnp.int32))
    next_start = starts[run_id + 1]  # [M]: start of the following run

    # Gap value (constant within a run): old step value at the run key,
    # raised to ``now`` where the batch's committed writes cover it, then
    # GC-flattened below ``oldest``.
    rank = jnp.maximum(EA[next_start] - 1, 0)
    old_val = state.vers[rank]
    covered = E[next_start] > 0
    val = jnp.where(covered, jnp.maximum(old_val, now), old_val)
    val = jnp.where(val < oldest, 0, val)

    is_sent = (D0 == SENTINEL).all(axis=1)
    val = jnp.where(is_sent, 0, val)
    prev_val = jnp.concatenate([jnp.full((1,), -1, jnp.int32), val[:-1]])
    keep = (~is_sent) & prev_differs & ((val != prev_val) | (jnp.arange(M) == 0))
    needed = keep.sum().astype(jnp.int32)

    # Compact kept boundaries into a fresh capacity-P index.
    dst = jnp.cumsum(keep.astype(jnp.int32)) - 1
    dst = jnp.where(keep & (dst < P), dst, M)  # overflow / dropped → OOB
    new_bounds = jnp.full((P, L), SENTINEL, dtype=jnp.uint32)
    new_bounds = new_bounds.at[dst].set(D0, mode="drop")
    new_vers = jnp.zeros((P,), dtype=jnp.int32)
    new_vers = new_vers.at[dst].set(val, mode="drop")

    new_state = IndexState(
        bounds=new_bounds,
        vers=new_vers,
        tree=build_tree(new_vers),
        n=jnp.minimum(needed, P),
    )
    return new_state, needed


# ---------------------------------------------------------------------------
# Full resolver step


def _resolve_one(
    state: IndexState,
    batch: Batch,
    now: jax.Array,
    oldest_pre: jax.Array,
    oldest_post: jax.Array,
    num_txns: int,
) -> tuple[IndexState, jax.Array, jax.Array]:
    """oldest_pre: the horizon in force when the batch arrived (gates
    TOO_OLD, like cs->oldestVersion in addTransaction, SkipList.cpp:989);
    oldest_post: the horizon to GC to after the batch (removeBefore)."""
    too_old = batch.t_has_reads & (batch.t_snap < oldest_pre)
    H = history_conflicts(state, batch, num_txns) | too_old
    commit = intra_batch_commits(batch, H, num_txns)
    new_state, needed = merge_writes(state, batch, commit, now, oldest_post)
    verdicts = jnp.where(
        too_old,
        jnp.int8(TOO_OLD),
        jnp.where(commit, jnp.int8(COMMITTED), jnp.int8(CONFLICT)),
    )
    return new_state, verdicts, needed


# The host pre-grows capacity whenever n + 2W might exceed P (needed is always
# <= n + 2W), so donating ``state`` is safe: the retry-from-old-state path can
# never be hit.
@functools.partial(jax.jit, static_argnames=("num_txns",), donate_argnames=("state",))
def resolve_batch(
    state: IndexState,
    batch: Batch,
    now: jax.Array,
    oldest_pre: jax.Array,
    oldest_post: jax.Array,
    num_txns: int,
) -> tuple[IndexState, jax.Array, jax.Array]:
    """One commit batch end-to-end on device.

    Returns (new_state, verdicts int8[T], needed int32)."""
    return _resolve_one(state, batch, now, oldest_pre, oldest_post, num_txns)


@functools.partial(jax.jit, static_argnames=("num_txns",), donate_argnames=("state",))
def resolve_many(
    state: IndexState,
    batches: Batch,  # every leaf has a leading group axis G
    nows: jax.Array,  # int32[G]
    oldests_pre: jax.Array,  # int32[G]
    oldests_post: jax.Array,  # int32[G]
    num_txns: int,
) -> tuple[IndexState, jax.Array, jax.Array]:
    """Resolve G consecutive commit batches in ONE device dispatch.

    The index state threads through a lax.scan, so inter-batch dependencies
    stay on device — this is the device-side analog of the reference's
    pipelined commit batches (MasterProxyServer.actor.cpp:353 gating), and
    the main defense against host↔device round-trip latency.

    Returns (new_state, verdicts int8[G, T], needed int32[G]).
    """

    def step(st, inp):
        batch, now, old_pre, old_post = inp
        st2, verdicts, needed = _resolve_one(
            st, batch, now, old_pre, old_post, num_txns
        )
        return st2, (verdicts, needed)

    state, (verdicts, needed) = jax.lax.scan(
        step, state, (batches, nows, oldests_pre, oldests_post)
    )
    return state, verdicts, needed


@jax.jit
def rebase(state: IndexState, delta: jax.Array) -> IndexState:
    """Shift the version origin by ``delta`` (host advances its base by the
    same amount). Versions that would go non-positive are already below the
    GC horizon and flatten to 0."""
    vers = jnp.maximum(state.vers - delta, 0)
    return IndexState(state.bounds, vers, build_tree(vers), state.n)


def make_state(capacity: int, lanes: int) -> IndexState:
    """Fresh index: one boundary (the empty key's code, all zeros) with
    version 0 covering all of keyspace."""
    if capacity & (capacity - 1):
        raise ValueError(f"capacity must be a power of two, got {capacity}")
    bounds = np.full((capacity, lanes), 0xFFFFFFFF, dtype=np.uint32)
    bounds[0] = 0
    vers = np.zeros((capacity,), dtype=np.int32)
    return IndexState(
        bounds=jnp.asarray(bounds),
        vers=jnp.asarray(vers),
        tree=build_tree(jnp.asarray(vers)),
        n=jnp.int32(1),
    )


def grow_state(state: IndexState, new_capacity: int) -> IndexState:
    """Double (or more) the boundary capacity, preserving contents."""
    P, L = state.bounds.shape
    if new_capacity <= P:
        raise ValueError("new capacity must exceed current")
    bounds = jnp.full((new_capacity, L), SENTINEL, dtype=jnp.uint32)
    bounds = bounds.at[:P].set(state.bounds)
    vers = jnp.zeros((new_capacity,), jnp.int32).at[:P].set(state.vers)
    return IndexState(bounds, vers, build_tree(vers), state.n)
