"""Proxy-side conflict pre-filter: a decaying summary of recently
committed write ranges (ISSUE 17).

Each proxy keeps a cheap, strictly-conservative picture of what the
resolvers have recently committed, fed from committed-write-range
feedback piggybacked on every ``ResolveBatchReply``. Before a
transaction joins a commit batch, the proxy probes this summary with the
transaction's read conflict ranges: if a *stored* committed range
provably overlaps a read at a version newer than the read snapshot, the
resolver is guaranteed to convict the transaction (its history only ever
contains MORE than this summary), so the proxy fails it locally with the
normal retryable ``not_committed`` — skipping the version grant, the
resolver codec round, and the tlog push the doomed transaction would
otherwise pay for.

Structure: a coarse interval bloom over key prefixes. Ranges whose
``[begin, end)`` stays within one ``PREFILTER_PREFIX_LEN``-byte prefix
live as exact ``(begin, end, version)`` entries in that prefix's bucket;
ranges spanning prefixes go on a small *wide* side list. Each bucket
additionally tracks the max committed version it has ever seen
(``ceiling``) as a cheap first-pass screen. A check probes only the
buckets of the read range's two endpoint prefixes plus the wide list —
reads spanning many buckets may miss entries in the middle, which is
fine: misses are free (the resolver still convicts), false rejections
are not.

Conservativeness invariant (the in-sim oracle differential in
runtime/validation.py re-proves this on every rejection): every
path that LOSES information — bucket-entry eviction, whole-bucket
eviction, wide-list overflow, version-floor decay, feedback truncation,
``reset()`` — only produces false NEGATIVES. A rejection requires an
exact stored entry ``(b, e, v)`` with ``b < read_end and read_begin < e``
(the same half-open overlap the authoritative conflict set uses) and
``v > read_snapshot``.
"""

from __future__ import annotations

from collections import OrderedDict, deque

from ..runtime.stats import CounterCollection


def _strinc(prefix: bytes):
    """First key after the range of keys with this prefix, or None if
    there is none (prefix is all 0xff — the range is open-ended)."""
    p = prefix.rstrip(b"\xff")
    if not p:
        return None
    return p[:-1] + bytes([p[-1] + 1])


class _Bucket:
    __slots__ = ("entries", "ceiling", "touched")

    def __init__(self, cap: int):
        # (begin, end, version), oldest first; overflow pops oldest
        self.entries: deque = deque(maxlen=cap)
        # max committed version ever recorded here (cheap pre-screen)
        self.ceiling = 0
        # last feed version, for stalest-bucket eviction
        self.touched = 0


class ConflictPrefilter:
    """Per-proxy decaying summary of recently committed write ranges."""

    def __init__(self, knobs, ident: str = ""):
        self.knobs = knobs
        self.prefix_len = int(knobs.PREFILTER_PREFIX_LEN)
        self.bucket_cap = int(knobs.PREFILTER_BUCKET_ENTRIES)
        self.max_buckets = int(knobs.PREFILTER_MAX_BUCKETS)
        self.wide_cap = int(knobs.PREFILTER_WIDE_RANGES)
        # insertion-ordered so stalest-bucket eviction is O(1)-ish;
        # move_to_end on touch keeps it LRU by feed version
        self.buckets: "OrderedDict[bytes, _Bucket]" = OrderedDict()
        self.wide: deque = deque(maxlen=self.wide_cap)
        # everything committed at or below this version has been
        # forgotten; checks below it can't be rejected by us (the
        # resolver may still TOO_OLD them — not our job)
        self.floor = 0
        self.max_version = 0
        self._ranges_fed = 0
        self._ranges_decayed = 0
        self._buckets_evicted = 0
        self.stats = CounterCollection("Prefilter", ident)
        self.stats.gauge("buckets", lambda: len(self.buckets))
        self.stats.gauge(
            "rangeEntries",
            lambda: sum(len(b.entries) for b in self.buckets.values()),
        )
        self.stats.gauge("wideRanges", lambda: len(self.wide))
        self.stats.gauge("versionFloor", lambda: self.floor)
        self.stats.gauge("maxVersion", lambda: self.max_version)
        self.stats.gauge("rangesFed", lambda: self._ranges_fed)
        self.stats.gauge("rangesDecayed", lambda: self._ranges_decayed)
        self.stats.gauge("bucketsEvicted", lambda: self._buckets_evicted)

    # ------------------------------------------------------------- feed

    def feed(self, committed_ranges, version_floor: int = 0) -> int:
        """Absorb resolver feedback: ``committed_ranges`` is a list of
        ``(version, [(begin, end), ...])`` pairs; ``version_floor`` is
        the resolver's authoritative forget horizon (jumps on failover /
        journal capacity pressure). Returns the number of ranges fed."""
        fed = 0
        for version, ranges in committed_ranges:
            version = int(version)
            if version <= self.floor:
                continue
            if version > self.max_version:
                self.max_version = version
            for begin, end in ranges:
                self._insert(bytes(begin), bytes(end), version)
                fed += 1
        self._ranges_fed += fed
        if version_floor > self.floor:
            self.note_floor(version_floor)
        return fed

    def _insert(self, begin: bytes, end: bytes, version: int) -> None:
        prefix = begin[: self.prefix_len]
        nxt = _strinc(prefix)
        if nxt is not None and end <= nxt:
            bucket = self.buckets.get(prefix)
            if bucket is None:
                bucket = self.buckets[prefix] = _Bucket(self.bucket_cap)
                while len(self.buckets) > self.max_buckets:
                    # stalest feed version first (LRU order)
                    _, evicted = self.buckets.popitem(last=False)
                    self._buckets_evicted += 1
                    self._ranges_decayed += len(evicted.entries)
            else:
                self.buckets.move_to_end(prefix)
            if len(bucket.entries) == bucket.entries.maxlen:
                self._ranges_decayed += 1  # deque pops the oldest
            bucket.entries.append((begin, end, version))
            if version > bucket.ceiling:
                bucket.ceiling = version
            bucket.touched = version
        else:
            # spans buckets: exact entry on the bounded wide list
            if len(self.wide) == self.wide.maxlen:
                self._ranges_decayed += 1
            self.wide.append((begin, end, version))

    def note_floor(self, version_floor: int) -> None:
        """Advance the forget horizon and drop entries at/below it.
        Dropping only forgets conflicts — conservative."""
        if version_floor <= self.floor:
            return
        self.floor = version_floor
        dead = []
        for prefix, bucket in self.buckets.items():
            if bucket.ceiling <= version_floor:
                dead.append(prefix)
                self._ranges_decayed += len(bucket.entries)
                continue
            kept = [e for e in bucket.entries if e[2] > version_floor]
            self._ranges_decayed += len(bucket.entries) - len(kept)
            bucket.entries.clear()
            bucket.entries.extend(kept)
        for prefix in dead:
            del self.buckets[prefix]
        kept_wide = [e for e in self.wide if e[2] > version_floor]
        self._ranges_decayed += len(self.wide) - len(kept_wide)
        self.wide.clear()
        self.wide.extend(kept_wide)

    def reset(self, floor: int = 0) -> None:
        """Forget everything (e.g. resolver generation change)."""
        self._ranges_decayed += len(self.wide) + sum(
            len(b.entries) for b in self.buckets.values()
        )
        self.buckets.clear()
        self.wide.clear()
        self.floor = max(self.floor, floor)

    # ------------------------------------------------------------ check

    def check(self, read_snapshot: int, read_ranges) -> bool:
        """True iff some *stored* committed range overlaps a read range
        at a version newer than ``read_snapshot`` — i.e. the resolver is
        guaranteed to convict this transaction. Never guesses: absent or
        forgotten entries mean False."""
        if read_snapshot >= self.max_version or not read_ranges:
            return False  # nothing committed past the snapshot
        for rb, re_ in read_ranges:
            rb = bytes(rb)
            re_ = bytes(re_)
            probes = [rb[: self.prefix_len]]
            # end key is exclusive; probing its prefix still only ADDS
            # candidate entries, and the exact overlap test below
            # filters non-overlaps, so over-probing stays conservative
            ep = re_[: self.prefix_len]
            if ep != probes[0]:
                probes.append(ep)
            for prefix in probes:
                bucket = self.buckets.get(prefix)
                if bucket is None or bucket.ceiling <= read_snapshot:
                    continue
                for eb, ee, v in bucket.entries:
                    if v > read_snapshot and eb < re_ and rb < ee:
                        return True
            for eb, ee, v in self.wide:
                if v > read_snapshot and eb < re_ and rb < ee:
                    return True
        return False
