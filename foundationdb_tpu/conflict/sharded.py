"""Multi-resolver conflict detection sharded over a TPU device mesh.

The reference scales conflict resolution by key-range partitioning across
resolver processes (keyResolvers map + ResolutionRequestBuilder,
MasterProxyServer.actor.cpp:233-311; dynamic rebalancing
masterserver.actor.cpp:896), with the proxy combining per-resolver verdicts
by min — conflict dominates (MasterProxyServer.actor.cpp:482-489).

The TPU-native equivalent maps that axis onto the device mesh:

- mesh axis ``part``: each device (group) owns one key-range partition of the
  versioned write-range index (an independent IndexState shard). Every
  transaction's conflict ranges are *clipped* to the partition, resolved
  locally, and verdicts are max-combined across ``part`` (COMMITTED=0 <
  CONFLICT=1 < TOO_OLD=2, so max == "conflict dominates").
- mesh axis ``data``: read ranges within a partition are data-parallel for
  the history check and the intra-batch overlap matrix; partial results
  combine with a psum/pmax over ``data``.

Faithful to the reference's semantics including its documented relaxation:
resolvers are independent, so a transaction aborted by partition A still has
its writes merged by partition B (the reference has exactly this behavior —
each resolver only knows its own key ranges).

Collectives ride the ICI mesh; no host round-trips inside a batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import tpu_index as TI


def make_sharded_states(n_parts: int, capacity: int, lanes: int) -> TI.IndexState:
    """Stack of per-partition index states with leading axis [n_parts]."""
    states = [TI.make_state(capacity, lanes) for _ in range(n_parts)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def _partition_bounds(lanes: int, n_parts: int, idx):
    """Key-code range [plo, phi) owned by partition ``idx``: uniform split of
    the first uint32 lane (dynamic resplitting by sampled load — the analog
    of ResolutionSplitRequest — can replace this policy later)."""
    step = jnp.uint32((1 << 32) // n_parts)
    lo0 = step * idx.astype(jnp.uint32)
    plo = jnp.zeros((lanes,), jnp.uint32).at[0].set(lo0)
    is_last = idx == n_parts - 1
    hi0 = jnp.where(is_last, jnp.uint32(0xFFFFFFFF), lo0 + step)
    phi = jnp.where(
        is_last,
        jnp.full((lanes,), 0xFFFFFFFF, jnp.uint32),
        jnp.zeros((lanes,), jnp.uint32).at[0].set(hi0),
    )
    return plo, phi


def _lex_clip(b, e, plo, phi):
    """Intersect ranges [b, e) with the partition [plo, phi)."""
    b2 = jnp.where(TI.lex_lt(b, plo[None, :])[:, None], plo[None, :], b)
    e2 = jnp.where(TI.lex_lt(phi[None, :], e)[:, None], phi[None, :], e)
    return b2, e2


def build_sharded_resolver(mesh: Mesh, num_txns: int, lanes: int):
    """Returns a jitted fn(states, batch, now, oldest_pre, oldest_post) ->
    (states, verdicts, needed) running one commit batch across the mesh.

    ``states`` leading axis is sharded over ``part``; the batch's read arrays
    are sharded over ``data`` (axis 0); everything else is replicated.
    ``needed`` is int32[n_parts]: each partition's post-merge boundary count —
    the host watches it to grow capacity / trigger dynamic re-splitting (the
    analog of ResolutionSplitRequest, Resolver.actor.cpp:279).
    """
    n_parts = mesh.shape["part"]

    def local_step(state_stk, batch: TI.Batch, now, oldest_pre, oldest_post):
        # state_stk: this partition's IndexState with leading axis 1
        state = jax.tree.map(lambda x: x[0], state_stk)
        pidx = jax.lax.axis_index("part")
        plo, phi = _partition_bounds(lanes, n_parts, pidx)

        rb, re = _lex_clip(batch.rb, batch.re, plo, phi)
        wb, we = _lex_clip(batch.wb, batch.we, plo, phi)
        local_batch = TI.Batch(
            rb=rb, re=re, r_snap=batch.r_snap, r_owner=batch.r_owner,
            wb=wb, we=we, w_owner=batch.w_owner,
            t_snap=batch.t_snap, t_has_reads=batch.t_has_reads,
        )

        too_old = batch.t_has_reads & (batch.t_snap < oldest_pre)

        # History check: reads are sharded over 'data'; combine per-txn hits.
        H_local = TI.history_conflicts(state, local_batch, num_txns)
        H = jax.lax.pmax(H_local.astype(jnp.int32), "data").astype(bool)
        H = H | too_old

        # Intra-batch: shared kernel, with the T×T overlap matrix pmax-combined
        # across the data shards before the greedy fixpoint.
        commit = TI.intra_batch_commits(
            local_batch,
            H,
            num_txns,
            combine_pji=lambda p: jax.lax.pmax(p.astype(jnp.int32), "data").astype(
                bool
            ),
        )

        # Merge commits into this partition's shard (writes are replicated
        # along 'data', so every data-row computes the same new state).
        new_state, needed = TI.merge_writes(
            state, local_batch, commit, now, oldest_post
        )

        verdict = jnp.where(
            too_old,
            jnp.int8(TI.TOO_OLD),
            jnp.where(commit, jnp.int8(TI.COMMITTED), jnp.int8(TI.CONFLICT)),
        )
        verdict = jax.lax.pmax(verdict, "part")
        verdict = jax.lax.pmax(verdict, "data")
        return (
            jax.tree.map(lambda x: x[None], new_state),
            verdict,
            needed[None],
        )

    state_spec = jax.tree.map(lambda _: P("part"), TI.IndexState(0, 0, 0, 0))
    batch_spec = TI.Batch(
        rb=P("data"), re=P("data"), r_snap=P("data"), r_owner=P("data"),
        wb=P(), we=P(), w_owner=P(), t_snap=P(), t_has_reads=P(),
    )
    shard_fn = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec, batch_spec, P(), P(), P()),
        out_specs=(state_spec, P(), P("part")),
        check_vma=False,
    )
    return jax.jit(shard_fn)
