"""Multi-resolver conflict detection sharded over a TPU device mesh — the
bucket-grid kernel (conflict/grid.py) partitioned by key range.

The reference scales conflict resolution by key-range partitioning across
resolver processes (keyResolvers map + ResolutionRequestBuilder,
MasterProxyServer.actor.cpp:233-311; dynamic rebalancing
masterserver.actor.cpp:896), with the proxy combining per-resolver verdicts
by "conflict dominates" (MasterProxyServer.actor.cpp:482-489).

The TPU-native mapping:

- mesh axis ``part``: each device owns one key-range partition as an
  independent ``GridState`` (its pivot 0 is the partition's lower bound).
  Every transaction's conflict ranges are *clipped* to the partition and
  resolved against the local grid.
- mesh axis ``data``: the per-transaction read-range slots (the KR axis)
  are data-parallel; per-slot history hits and overlap matrices combine
  with a pmax.

One deliberate improvement over the reference: independent resolvers
cannot see each other's aborts, so a transaction aborted by partition A
still has its writes merged by partition B (a documented relaxation that
admits phantom conflicts). Here a single ``pmax`` over ICI makes the
history verdict and the intra-batch overlap matrix global BEFORE the
greedy commit fixpoint and the merge, so every partition merges exactly
the globally-committed writes — sharded verdicts equal single-device
verdicts bit-for-bit. Collectives ride the mesh; no host round-trips
inside a batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from . import grid as G


def _shard_map(fn, mesh, in_specs, out_specs):
    """Version-portable shard_map: prefer the stable ``jax.shard_map``
    (newer jax, ``check_vma`` keyword); fall back to
    ``jax.experimental.shard_map`` (``check_rep``) on older releases — the
    jax on the bench box predates the promotion, and an AttributeError
    here used to kill the whole mesh backend at construction."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as sm

    return sm(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def make_sharded_states(
    n_parts: int, n_buckets: int, n_slots: int, lanes: int
) -> G.GridState:
    """Stack of per-partition GridStates with leading axis [n_parts].

    Each partition's buckets pre-split its key range uniformly (first
    uint32 lane), so the first batches spread their staged rows instead of
    flooding one bucket — the static analog of the single-device backend's
    sample-seeded initial reshard. Pivot rows carry version 0 (the empty
    history) and persist by the slot-0 invariant."""
    step = (1 << 32) // n_parts
    sub = max(step // n_buckets, 1)
    states = []
    for p in range(n_parts):
        lo0 = p * step
        n_sub = min(n_buckets, step // sub)
        pivots = np.full((n_buckets, lanes), 0xFFFFFFFF, dtype=np.uint32)
        grid = np.full(
            (n_buckets, n_slots, lanes + 1), 0xFFFFFFFF, dtype=np.uint32
        )
        grid[..., lanes] = 0
        count = np.zeros((n_buckets,), np.int32)
        for b in range(n_sub):
            pivots[b] = 0
            pivots[b, 0] = lo0 + b * sub
            grid[b, 0, :lanes] = pivots[b]
            count[b] = 1
        states.append(
            G.GridState(
                pivots=jnp.asarray(pivots),
                grid=jnp.asarray(grid),
                count=jnp.asarray(count),
                bmax=jnp.zeros((n_buckets,), jnp.int32),
                floor=jnp.zeros((n_buckets,), jnp.int32),
            )
        )
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def _partition_bounds(lanes: int, n_parts: int, idx):
    step = jnp.uint32((1 << 32) // n_parts)
    lo0 = step * idx.astype(jnp.uint32)
    plo = jnp.zeros((lanes,), jnp.uint32).at[0].set(lo0)
    is_last = idx == n_parts - 1
    hi0 = jnp.where(is_last, jnp.uint32(0xFFFFFFFF), lo0 + step)
    phi = jnp.where(
        is_last,
        jnp.full((lanes,), 0xFFFFFFFF, jnp.uint32),
        jnp.zeros((lanes,), jnp.uint32).at[0].set(hi0),
    )
    return plo, phi


def _clip(b, e, plo, phi):
    """Intersect ranges [b, e) with the partition [plo, phi); an empty
    intersection leaves b >= e, which self-deactivates in the kernel's
    lex_lt(begin, end) activity checks. Shapes [..., L]."""
    lo = jnp.broadcast_to(plo, b.shape)
    hi = jnp.broadcast_to(phi, e.shape)
    b2 = jnp.where(G.lex_lt(b, lo)[..., None], lo, b)
    e2 = jnp.where(G.lex_lt(hi, e)[..., None], hi, e)
    return b2, e2


def _local_resolve(state, batch: G.Batch, now, oldest_pre, oldest_post, plo, phi):
    """One partition's view of one batch: clip ranges to the partition,
    resolve against the local grid shard, and make verdicts global with
    mesh collectives. The shared body of the single-batch and
    scan-stacked (double-buffered) step functions."""

    def pmax_all(x, axes=("part", "data")):
        return jax.lax.pmax(x.astype(jnp.int32), axes)

    rb, re = _clip(batch.rb, batch.re, plo, phi)
    wb, we = _clip(batch.wb, batch.we, plo, phi)
    local = G.Batch(
        rb=rb,
        re=re,
        wb=wb,
        we=we,
        t_snap=batch.t_snap,
        t_has_reads=batch.t_has_reads,
    )

    too_old = batch.t_has_reads & (batch.t_snap < oldest_pre)
    # global history verdict: each partition checks its clipped reads
    # against its shard of the MVCC history, then one pmax over the
    # whole mesh ("conflict dominates", made global)
    H_local = G.history_conflicts(state, local)
    H = pmax_all(H_local).astype(bool) | too_old

    commit = G.intra_batch_commits(
        local,
        H,
        combine_pji=lambda p: pmax_all(p).astype(bool),
    )

    # merge is per-partition (writes replicated along data, clipped to
    # the partition; every data row computes the same new state)
    new_state, pressure = G.merge_writes(
        state, local, commit, now, oldest_post
    )

    verdicts = jnp.where(
        too_old,
        jnp.int8(G.TOO_OLD),
        jnp.where(commit, jnp.int8(G.COMMITTED), jnp.int8(G.CONFLICT)),
    )
    return new_state, verdicts, pressure


def _mesh_specs():
    state_spec = jax.tree.map(
        lambda _: P("part"), G.GridState(0, 0, 0, 0, 0)
    )
    batch_spec = G.Batch(
        rb=P(None, "data"),
        re=P(None, "data"),
        wb=P(),
        we=P(),
        t_snap=P(),
        t_has_reads=P(),
    )
    return state_spec, batch_spec


def build_sharded_resolver(mesh: Mesh, lanes: int):
    """Returns a jitted fn(states, batch, now, oldest_pre, oldest_post) ->
    (states, verdicts, pressure) resolving one commit batch across the
    mesh. ``states`` leading axis shards over ``part``; the batch's read
    arrays shard their KR axis over ``data``; writes are replicated.
    ``pressure`` is int32[n_parts, 2] — per-partition staging/kept
    maxima, the host's overflow + rebalance signal (the analog of
    ResolutionSplitRequest, Resolver.actor.cpp:279)."""
    n_parts = mesh.shape["part"]

    def local_step(state_stk, batch: G.Batch, now, oldest_pre, oldest_post):
        state = jax.tree.map(lambda x: x[0], state_stk)
        pidx = jax.lax.axis_index("part")
        plo, phi = _partition_bounds(lanes, n_parts, pidx)
        new_state, verdicts, pressure = _local_resolve(
            state, batch, now, oldest_pre, oldest_post, plo, phi
        )
        return (
            jax.tree.map(lambda x: x[None], new_state),
            verdicts,
            pressure[None],
        )

    state_spec, batch_spec = _mesh_specs()
    shard_fn = _shard_map(
        local_step,
        mesh,
        in_specs=(state_spec, batch_spec, P(), P(), P()),
        out_specs=(state_spec, P(), P("part")),
    )
    return jax.jit(shard_fn, donate_argnums=(0,))


def build_sharded_resolver_many(mesh: Mesh, lanes: int):
    """The group-stacked face of build_sharded_resolver: ONE compiled
    ``pjit``/shard_map program resolving a whole stacked group of batches
    (leading axis G on every batch leaf) via an on-device lax.scan, with
    the stacked grid states DONATED — the inter-batch state dependency
    never leaves HBM, and the host pays one dispatch per group instead of
    one per batch (the SNIPPETS.md pjit train-step shape: compiled,
    automatically partitioned, donated carry).

    fn(states, batches, nows, oldests_pre, oldests_post) ->
    (states, verdicts int8[G, T], pressures int32[G, n_parts, 2]).
    Per-batch pressures (not a group max) so the host's occupancy-driven
    reshard decisions see exactly which batch pushed the grid where."""
    n_parts = mesh.shape["part"]

    def local_many(state_stk, batches: G.Batch, nows, oldests_pre, oldests_post):
        state = jax.tree.map(lambda x: x[0], state_stk)
        pidx = jax.lax.axis_index("part")
        plo, phi = _partition_bounds(lanes, n_parts, pidx)

        def step(st, inp):
            batch, now, old_pre, old_post = inp
            st2, verdicts, pressure = _local_resolve(
                st, batch, now, old_pre, old_post, plo, phi
            )
            return st2, (verdicts, pressure)

        state, (verdicts, pressures) = jax.lax.scan(
            step, state, (batches, nows, oldests_pre, oldests_post)
        )
        return (
            jax.tree.map(lambda x: x[None], state),
            verdicts,
            pressures[:, None],
        )

    state_spec, batch_spec1 = _mesh_specs()
    batch_spec = jax.tree.map(
        lambda s: P(*((None,) + tuple(s))), batch_spec1
    )
    shard_fn = _shard_map(
        local_many,
        mesh,
        in_specs=(state_spec, batch_spec, P(), P(), P()),
        out_specs=(state_spec, P(), P(None, "part")),
    )
    return jax.jit(shard_fn, donate_argnums=(0,))


def stacked_occupancy_stats(states: G.GridState) -> dict:
    """Per-partition occupancy gauges over a stacked (mesh) state — the
    multi-device face of grid.occupancy_stats. Aggregates host-side from
    the small count arrays; the grids stay on their devices."""
    counts = np.asarray(states.count)  # [n_parts, B]
    n_parts, B = counts.shape
    S = states.grid.shape[2]
    per_part = counts.sum(axis=1)
    worst = int(counts.max(initial=0))
    return {
        "partitions": int(n_parts),
        "liveRows": int(per_part.sum()),
        "liveRowsPerPartition": [int(x) for x in per_part],
        "usedBuckets": int((counts > 0).sum()),
        "bucketCount": int(n_parts * B),
        "slotCapacity": int(S),
        "maxBucketRows": worst,
        "slotHeadroom": int(S - worst),
        "fillFraction": round(float(per_part.sum()) / float(n_parts * B * S), 6),
    }


def reshard_partition(
    states: G.GridState, p: int, n_buckets: int, n_slots: int
) -> tuple[G.GridState, int]:
    """Rebalance one partition's grid in the stacked state (host-driven,
    between batches — the dynamic-resplit analog). Returns (new stacked
    states, pressure) — pressure > n_slots means the partition needs a
    larger grid (caller grows and retries)."""
    shard = jax.tree.map(lambda x: x[p], states)
    new_shard, pressure = G.reshard_device(shard, n_buckets, n_slots)
    out = jax.tree.map(lambda full, s: full.at[p].set(s), states, new_shard)
    return out, int(jax.device_get(pressure))
