"""Multi-device ConflictSet: the sharded bucket-grid kernel behind the
standard ConflictSet seam, so a cluster resolver transparently scales its
MVCC conflict index across a TPU device mesh.

The reference scales conflict resolution by recruiting more resolver
PROCESSES, each owning a key-range partition (ResolutionRequestBuilder,
MasterProxyServer.actor.cpp:233; rebalanced by masterserver.actor.cpp:896).
On TPU the same partitioning maps onto a device mesh INSIDE one resolver
role: each device owns a contiguous key-range shard of the grid
(conflict/sharded.py), collectives make history verdicts and the
intra-batch overlap matrix global before the commit fixpoint, and verdicts
are bit-identical to a single-device resolver (tests/test_mesh_backend.py
asserts this differentially).

Dispatch is ONE compiled ``pjit``/shard_map program per group
(sharded.build_sharded_resolver_many): the group's batches stack on the
host, upload once, and an on-device lax.scan threads the DONATED stacked
grid states through every batch — no host round-trip between batches.
Donation discipline follows PR 2's donated-buffer race: the pre-group
snapshot keeps the ORIGINAL (never-donated) arrays for overflow replay;
the kernel consumes a fresh ``+ 0`` copy.

Reshard/grow decisions are occupancy-driven and run BETWEEN groups:
collected per-partition pressure against the CONFLICT_RESHARD_PRESSURE
threshold flags partitions for a proactive rebalance (the in-cluster
analog of the reference's ResolutionSplitRequest,
fdbserver/Resolver.actor.cpp:279), and the stacked fill fraction against
CONFLICT_GROW_FILL grows every partition's grid — so maintenance costs a
deliberate pipeline bubble, never an overflow replay of live dispatches.
Overflow replay from the snapshot remains the backstop; callers never
observe it. A grid-shape change re-warms recently dispatched stacked
shapes so post-reshard/post-grow dispatches stay jit-cache hits.

`new_conflict_set("tpu")` auto-upgrades to this backend when more than
one JAX device is visible; `__graft_entry__.dryrun_multichip` drives the
same class, so the driver's multi-chip validation exercises exactly the
cluster's code path.
"""

# flowlint: disable-file=det-wall-clock — KernelMetrics phase timings
# measure HOST wall time of device work (encode/dispatch/collect/reshard)
# on purpose; they are evidence counters, never inputs to sim scheduling
# (same-seed replay is unaffected: no control flow reads them).

from __future__ import annotations

import functools
import time

import numpy as np

from . import grid as G
from . import keys as K
from . import sharded
from .api import CommitTransaction, ConflictSet, Verdict
from .faults import StaleEncodingError
from .tpu_backend import (
    _INT32_REBASE_THRESHOLD,
    _RECENT_SHAPES,
    _VERDICT_TABLE,
    DEFAULT_GROW_FILL,
    DEFAULT_RESHARD_PRESSURE,
    KernelMetrics,
    KeyReservoir,
    _bucket,
    _pick_pivots,
    encode_transactions,
    sentinel_batch,
    stack_batches,
    tree_nbytes,
)

def _lex_gt(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Row-wise lexicographic a > b over uint32 lanes (host numpy).
    codes_to_bytes void keys sort correctly (np.unique/searchsorted) but
    numpy defines NO elementwise comparison ufunc for void dtypes, so
    filtering needs this explicit lane loop."""
    a = np.asarray(a)
    b = np.broadcast_to(np.asarray(b), a.shape)
    gt = np.zeros(len(a), bool)
    eq = np.ones(len(a), bool)
    for i in range(a.shape[1]):
        gt |= eq & (a[:, i] > b[:, i])
        eq &= a[:, i] == b[:, i]
    return gt


class MeshConflictSet(ConflictSet):
    def __init__(
        self,
        key_width: int = K.DEFAULT_KEY_WIDTH,
        capacity: int = 1 << 14,
        mesh=None,
        n_parts: int = None,
        reshard_pressure: float = DEFAULT_RESHARD_PRESSURE,
        grow_fill: float = DEFAULT_GROW_FILL,
    ):
        super().__init__()
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        self._jax = jax
        self._width = key_width
        self._lanes = K.lanes_for_width(key_width)
        self._reshard_pressure = reshard_pressure
        self._grow_fill = grow_fill
        if mesh is None:
            devs = jax.devices()
            if n_parts is None:
                n_parts = len(devs)
            mesh = Mesh(
                np.array(devs[:n_parts]).reshape(n_parts, 1),
                axis_names=("part", "data"),
            )
        self.mesh = mesh
        self._n_parts = mesh.shape["part"]
        # per-partition grid: capacity splits across partitions
        self._B = _bucket(max(8, capacity // 16 // self._n_parts))
        self._S = 32
        self._sharding = jax.tree.map(
            lambda _: NamedSharding(mesh, P("part")),
            G.GridState(0, 0, 0, 0, 0),
        )
        # stacked-batch sharding: leading group axis replicated, read
        # slots data-parallel (matches build_sharded_resolver_many specs)
        self._batch_sharding = G.Batch(
            rb=NamedSharding(mesh, P(None, None, "data")),
            re=NamedSharding(mesh, P(None, None, "data")),
            wb=NamedSharding(mesh, P()),
            we=NamedSharding(mesh, P()),
            t_snap=NamedSharding(mesh, P()),
            t_has_reads=NamedSharding(mesh, P()),
        )
        self._states = self._fresh_states()
        self._step_many = sharded.build_sharded_resolver_many(
            mesh, lanes=self._lanes
        )
        self._base = -1
        self._base_epoch = 0
        self._inflight: list[dict] = []
        # occupancy-driven maintenance flags, set at collect from the
        # per-partition pressure, executed between groups
        self._rebalance_parts: set[int] = set()
        # stacked shapes re-warmed whenever the grid shape (B) changes
        self._recent_shapes: list[tuple] = []
        # reservoir of raw endpoint keys for sample-seeded pivot selection
        # (a device rebalance can only split between LIVE boundaries; a
        # batch flooding one gap with brand-new keys needs pivots from
        # the sample — same escalation as the single-device backend)
        self._sample = KeyReservoir()
        # kernel observability — same collection shape as TpuConflictSet,
        # with per-partition occupancy
        self.metrics = KernelMetrics()
        self.metrics.gauge(
            "occupancy", lambda: sharded.stacked_occupancy_stats(self._states)
        )
        self.metrics.gauge("stagingSlots", lambda: G.staging_slots(self._S))
        self.metrics.gauge("inflightGroups", lambda: len(self._inflight))

    def _fresh_states(self):
        return self._jax.device_put(
            sharded.make_sharded_states(
                self._n_parts, self._B, self._S, self._lanes
            ),
            self._sharding,
        )

    # -- ConflictSet interface ------------------------------------------------

    def warm_compile(self) -> None:
        """Pre-compile the group-stacked resolver step for the smoke shape
        (G=1, T=8, KR=KW=1) on scratch states — same first-commit-batch
        de-stall as TpuConflictSet.warm_compile, against the mesh's pjit'd
        scan program. Re-invoked internally (_warm_recent) after any
        grid-shape change so post-reshard/post-grow stacked shapes are
        pre-compiled too."""
        b = encode_transactions([], self._width, 0)
        self._warm_shape((1, b.rb.shape[0], b.rb.shape[1], b.wb.shape[1]))

    def _warm_shape(self, shape: tuple) -> None:
        t0 = time.perf_counter()
        Gn, T, KR, KW = shape
        scratch = self._fresh_states()
        b = sentinel_batch(T, KR, KW, self._lanes)
        stacked = self._put_batches(
            G.Batch(*(np.broadcast_to(a[None], (Gn,) + a.shape) for a in b))
        )
        zeros = np.zeros(Gn, np.int32)
        out = self._step_many(scratch, stacked, zeros, zeros, zeros)
        self._jax.block_until_ready(out)
        self.metrics.note_shape((Gn, T, KR, KW, self._B), warm=True)
        self.metrics.warm_compiles.add()
        self.metrics.warm_s.add(time.perf_counter() - t0)

    def _note_recent_shape(self, shape: tuple) -> None:
        if shape in self._recent_shapes:
            return
        self._recent_shapes.append(shape)
        del self._recent_shapes[:-_RECENT_SHAPES]

    def _warm_recent(self) -> None:
        for shape in self._recent_shapes:
            self._warm_shape(shape)

    def clear(self, version: int) -> None:
        self._flush()
        self._states = self._fresh_states()
        self._base = version - 1
        self._base_epoch += 1
        self.oldest_version = version

    def detect_batch(self, transactions, now, new_oldest_version):
        return self.detect_many([(transactions, now, new_oldest_version)])[0]

    def detect_many(self, work):
        if not work:
            return []
        self._maybe_rebase(max(now for _, now, _2 in work))
        return self.detect_many_encoded(
            [(self.encode(txs), now, old) for txs, now, old in work]
        )

    def prepare(self, now: int) -> None:
        self._maybe_rebase(now)

    def encode(self, transactions):
        """Host encode — safe off-thread (see TpuConflictSet.encode: epoch
        and base read first, so a concurrent rebase surfaces as a
        StaleEncodingError at dispatch, never a mis-based encoding)."""
        t0 = time.perf_counter()
        epoch, base = self._base_epoch, self._base
        b = encode_transactions(
            transactions, self._width, base, sample_cb=self._sample.add
        )
        self.metrics.encode_s.add(time.perf_counter() - t0)
        return b, len(transactions), epoch

    def detect_many_encoded(self, work):
        return self.detect_many_encoded_async(work)()

    def detect_many_encoded_async(self, work):
        """Same pipelining contract as TpuConflictSet: dispatch without
        waiting, collect later; the inter-batch state dependency lives on
        the mesh (one donated scan program per group)."""
        if not work:
            return lambda: []
        for (_b, _n, epoch), _now, _old in work:
            if epoch != self._base_epoch:
                raise StaleEncodingError(
                    "stale encoding: version base was rebased after encode()"
                )
        counts = []
        metas = []  # (now, oldest_pre, oldest_post) absolute versions
        batches = []
        for (b, n_real, _epoch), now, new_oldest in work:
            horizon = max(self.oldest_version, new_oldest)
            metas.append((now, self.oldest_version, horizon))
            self.oldest_version = horizon
            counts.append(n_real)
            batches.append(b)
        self.metrics.groups.add()
        self.metrics.batches.add(len(batches))
        self.metrics.txns.add(sum(counts))

        if self._rebalance_parts:
            # occupancy-driven proactive maintenance between groups: drain
            # the pipeline, then grow (stacked fill fraction over the
            # CONFLICT_GROW_FILL threshold) or rebalance the flagged
            # partitions — a deliberate bubble, never a live-dispatch stall
            self._flush()
            self.metrics.reshards_proactive.add()
            occ = sharded.stacked_occupancy_stats(self._states)
            if occ["fillFraction"] >= self._grow_fill:
                self._grow()
            else:
                for p in sorted(self._rebalance_parts):
                    self._states, pr = sharded.reshard_partition(
                        self._states, p, self._B, self._S
                    )
                    self.metrics.reshards_device.add()
                    if pr > self._S:
                        self._host_reshard_partition(p)
                self._states = self._jax.device_put(
                    self._states, self._sharding
                )
            self._rebalance_parts.clear()

        group = {
            "batches": batches,
            "metas": metas,
            "counts": counts,
            "done": None,
        }
        self._dispatch(group)
        self._inflight.append(group)

        def result(group=group):
            return self._collect(group)

        return result

    # -- internals ------------------------------------------------------------

    def _put_batches(self, stacked: G.Batch):
        return self._jax.tree_util.tree_map(
            self._jax.device_put, stacked, self._batch_sharding
        )

    def _dispatch(self, group) -> None:
        t0 = time.perf_counter()
        self.metrics.dispatches.add()
        metas = group["metas"]
        stacked = stack_batches(group["batches"], self._lanes)
        shape = (
            len(metas),
            stacked.rb.shape[1],
            stacked.rb.shape[2],
            stacked.wb.shape[2],
        )
        self._note_recent_shape(shape)
        self.metrics.note_shape(shape + (self._B,))
        self.metrics.h2d_bytes.add(tree_nbytes(stacked))
        stacked = self._put_batches(stacked)
        nows = np.asarray([m[0] - self._base for m in metas], np.int32)
        olds_pre = np.asarray(
            [max(m[1] - self._base, 0) for m in metas], np.int32
        )
        olds_post = np.asarray(
            [max(m[2] - self._base, 0) for m in metas], np.int32
        )
        # the step DONATES its states argument: the pre-group snapshot
        # keeps the ORIGINAL arrays (never donated → always intact for a
        # replay) and the kernel consumes a fresh `+ 0` copy whose only
        # reference is this dispatch — the exact discipline of PR 2's
        # donated-buffer race fix in the single-device backend (the
        # previous mesh code had it backwards: it donated the original and
        # kept the copy, racing the async snapshot read)
        group["snapshot"] = self._states
        work = self._jax.tree_util.tree_map(lambda x: x + 0, self._states)
        states, verdicts, pressures = self._step_many(
            work, stacked, nows, olds_pre, olds_post
        )
        self._states = states
        group["verdicts"] = verdicts  # int8[G, T]
        group["pressures"] = pressures  # int32[G, n_parts, 2]
        # start device→host copies now — _collect's device_get then pays
        # no extra tunnel round trip
        for a in (verdicts, pressures):
            copy_async = getattr(a, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        self.metrics.dispatch_s.add(time.perf_counter() - t0)

    def _collect(self, group):
        if group["done"] is not None:
            return group["done"]
        while self._inflight and self._inflight[0] is not group:
            self._collect(self._inflight[0])
        assert self._inflight and self._inflight[0] is group
        t0 = time.perf_counter()
        S2 = G.staging_slots(self._S)
        for attempt in range(6):
            # one host↔device round trip for both pressures and verdicts
            prs, out = self._jax.device_get(
                (group["pressures"], group["verdicts"])
            )
            self.metrics.d2h_bytes.add(int(prs.nbytes) + int(out.nbytes))
            worst = prs.max(axis=0)  # [n_parts, 2] over the group
            over = (worst[:, 0] > S2) | (worst[:, 1] > self._S)
            if not over.any():
                break
            self.metrics.overflow_replays.add()
            self.metrics.replayed_groups.add(len(self._inflight))
            # abandoned-chain barrier (see TpuConflictSet._collect): the
            # replay must not reuse memory a still-executing donated
            # computation writes into
            self._jax.block_until_ready(self._states)
            # overflow: rebalance the offending partitions from the
            # pre-group snapshot, then replay this group and everything
            # after it (verdicts are deterministic — invisible to callers).
            # Attempt 0: on-device rebalance (live-set skew). Attempt 1+:
            # host reshard with the key SAMPLE — a device rebalance can
            # only split between live boundaries, which never converges
            # when a batch floods one gap with brand-new keys. Attempt 3+
            # also grows every partition's grid.
            self._states = group["snapshot"]
            if attempt >= 3:
                self._grow()
            for p in np.nonzero(over)[0]:
                if attempt == 0:
                    self._states, pr = sharded.reshard_partition(
                        self._states, int(p), self._B, self._S
                    )
                    self.metrics.reshards_device.add()
                    if pr <= self._S:
                        continue
                self._host_reshard_partition(int(p))
            self._states = self._jax.device_put(self._states, self._sharding)
            for g in self._inflight:
                self._dispatch(g)
        else:
            raise RuntimeError("mesh conflict grid reshard did not converge")

        # proactive-rebalance signal for the NEXT group boundary: any
        # partition whose staged/kept maxima crossed the pressure threshold
        self._rebalance_parts.update(
            int(p)
            for p in np.nonzero(
                (worst[:, 0] > int(S2 * self._reshard_pressure))
                | (worst[:, 1] > int(self._S * self._reshard_pressure))
            )[0]
        )

        table = _VERDICT_TABLE
        done = [
            [table[v] for v in out[g, : group["counts"][g]].tolist()]
            for g in range(len(group["counts"]))
        ]
        self.metrics.collect_s.add(time.perf_counter() - t0)
        group["done"] = done
        # collected groups can never be re-dispatched: drop everything
        # pinning device/host memory (snapshots scale with pipeline depth)
        group.pop("snapshot", None)
        group.pop("verdicts", None)
        group.pop("pressures", None)
        group.pop("batches", None)
        group.pop("metas", None)
        self._inflight.pop(0)
        return done

    def _host_reshard_partition(self, p: int) -> None:
        """Rebuild partition p's grid under pivots drawn from its live
        boundaries ∪ the key sample clipped to its range (the mesh analog
        of TpuConflictSet._reshard_host_sampled). Grows every partition
        when a balanced split cannot fit."""
        t0 = time.perf_counter()
        self.metrics.reshards_host.add()
        tm = self._jax.tree_util.tree_map
        while True:
            shard = tm(lambda x: x[p], self._states)
            codes, _vers = G.live_rows(shard)
            lo = np.asarray(shard.pivots)[0]  # partition lower bound
            cands = codes
            if self._sample:
                samp = K.encode_keys(self._sample.keys, self._width)
                cands = np.concatenate([cands, samp])
            keys = G.codes_to_bytes(np.ascontiguousarray(cands))
            _, uniq = np.unique(keys, return_index=True)
            cands = cands[uniq]
            # keep only candidates strictly above the partition's lower
            # bound and (when not the last partition) below its upper
            # bound — live rows of OTHER partitions never appear here,
            # but sampled keys can
            keep = _lex_gt(cands, lo)
            if p + 1 < self._n_parts:
                hi = np.asarray(self._states.pivots)[p + 1][0]
                keep &= _lex_gt(np.broadcast_to(hi, cands.shape), cands)
            cands = cands[keep]
            pivots = _pick_pivots(cands, self._B, self._lanes, lo=lo)
            try:
                new_shard = G.reshard_host(shard, pivots, self._B, self._S)
            except OverflowError:
                self._grow()
                continue
            self._states = tm(
                lambda full, s: full.at[p].set(s), self._states, new_shard
            )
            self.metrics.reshard_s.add(time.perf_counter() - t0)
            return

    def _grow(self) -> None:
        """Double every partition's bucket count (vmapped on-device
        reshard folds floors and rebalances each shard), then re-warm the
        recently dispatched stacked shapes at the new grid shape."""
        self._B *= 2
        self.metrics.capacity_growths.add()
        grown, _pr = self._jax.vmap(
            functools.partial(
                G.reshard_device.__wrapped__,
                n_buckets=self._B,
                n_slots=self._S,
            )
        )(self._states)
        self._states = self._jax.device_put(grown, self._sharding)
        self._warm_recent()

    def _flush(self) -> None:
        while self._inflight:
            self._collect(self._inflight[0])

    def _maybe_rebase(self, now: int) -> None:
        if now - self._base < _INT32_REBASE_THRESHOLD:
            return
        self._flush()
        new_base = self.oldest_version - 1
        delta = new_base - self._base
        if delta > 0:
            self._states = self._jax.device_put(
                self._jax.vmap(G.rebase.__wrapped__, in_axes=(0, None))(
                    self._states, np.int32(delta)
                ),
                self._sharding,
            )
            self._base = new_base
            self._base_epoch += 1
            self.metrics.rebases.add()
