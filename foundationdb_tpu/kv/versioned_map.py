"""Multi-version ordered map — the storage server's MVCC window.

The analog of the reference's VersionedMap persistent treap
(fdbclient/VersionedMap.h:31-68): holds the last few seconds of versions in
memory so reads at any version in [oldest_version, latest_version] see a
consistent snapshot. The reference uses a path-copying treap; here the same
semantics come from per-key version-history lists over one sorted key index —
simpler, and the batched-lookup form feeds the planned XLA range-query
primitive (SURVEY.md §7 stage 7) where the treap's pointer-chasing could not.

Two personalities share the read interface:

- ``VersionedMap`` — the legacy per-mutation store: every write keeps the
  key index sorted with an O(n) ``bisect.insort`` and every ``clear_range``
  materializes per-key tombstones.
- ``EpochVersionedMap`` — the epoch-batched store (ISSUE 15, Jiffy's
  batch-update + O(1)-snapshot shape from PAPERS.md): ``apply_epoch``
  applies a whole mutation batch at one version, merging the sorted key
  index ONCE per batch, recording ``clear_range`` as native range
  tombstones (no per-key materialization, and wide clears stop touching
  engine rows entirely), and ``snapshot(version)`` returns an O(1)
  ``PinnedSnapshot`` handle whose pin clamps the owner's compaction
  horizon — a reader at a pinned version never races ``forget_before``.

Both keep a touch log — (version, key) per appended history entry — so
compaction visits only keys touched below the new horizon instead of
scanning the whole ``_hist`` dict per durability advance.

Mutations must be applied in nondecreasing version order (the storage server's
update loop guarantees this, mirroring storageserver.actor.cpp:2321).
"""

from __future__ import annotations

import bisect
from typing import Iterator, Optional


def _find_le(h: list[tuple[int, Optional[bytes]]], version: int) -> int:
    """Index of the last entry with entry.version <= version, else -1."""
    lo, hi = 0, len(h)
    while lo < hi:
        mid = (lo + hi) // 2
        if h[mid][0] <= version:
            lo = mid + 1
        else:
            hi = mid
    return lo - 1


def merge_sorted_keys(keys: list, new_sorted: list) -> tuple[list, int]:
    """(merged, elements_moved): merge sorted distinct new keys into a
    sorted key list — the per-epoch replacement for per-key ``insort``
    (which moves O(n) elements per NEW key). A small batch insorts (the
    C memmove beats any merge below ~16 keys); a larger one extends and
    sorts — CPython's timsort detects the two runs and gallops the merge
    at C speed, O(n+m) with a tiny constant. ``elements_moved`` feeds
    the ``keys_moved`` regression counters (PR 14's RecvBuffer
    ``bytes_moved`` discipline): callers assert bulk ingest stays
    O(N log N), not N·O(n)."""
    if not keys:
        return list(new_sorted), len(new_sorted)
    if new_sorted[0] > keys[-1]:
        # append-only fast path (fresh suffix): nothing below moves
        keys.extend(new_sorted)
        return keys, len(new_sorted)
    if len(new_sorted) < 16:
        moved = 0
        for k in new_sorted:
            i = bisect.bisect_left(keys, k)
            moved += len(keys) - i
            keys.insert(i, k)
        return keys, moved
    keys.extend(new_sorted)
    keys.sort()
    return keys, len(keys)


class VersionedMap:
    def __init__(self) -> None:
        self._keys: list[bytes] = []  # sorted; includes tombstoned keys until GC
        self._hist: dict[bytes, list[tuple[int, Optional[bytes]]]] = {}
        # (version, key) per appended entry, version-nondecreasing: the
        # compaction work list — forget_before visits only keys touched
        # below its horizon (the old code scanned every key in _hist per
        # durability advance, O(total-keys) even for a 2-key epoch)
        self._touch_log: list[tuple[int, bytes]] = []
        self.forget_visits = 0  # keys visited by forget_before (test evidence)
        self.oldest_version = 0
        self.latest_version = 0

    # -- writes (version-ordered) ---------------------------------------------

    def _append(self, key: bytes, version: int, value: Optional[bytes]) -> None:
        h = self._hist.get(key)
        if h is None:
            self._hist[key] = [(version, value)]
            bisect.insort(self._keys, key)
            self._touch_log.append((version, key))
        elif h[-1][0] == version:
            h[-1] = (version, value)  # same-version overwrite: already logged
        else:
            h.append((version, value))
            self._touch_log.append((version, key))

    def set(self, key: bytes, value: bytes, version: int) -> None:
        assert version >= self.latest_version, "mutations must be version-ordered"
        self.latest_version = version
        self._append(key, version, value)

    def clear_range(self, begin: bytes, end: bytes, version: int) -> None:
        assert version >= self.latest_version
        self.latest_version = version
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        for key in self._keys[lo:hi]:
            self._append(key, version, None)

    def latest(self, key: bytes) -> Optional[bytes]:
        """Value at latest_version (used when applying atomic ops)."""
        h = self._hist.get(key)
        return h[-1][1] if h else None

    def latest_with_presence(self, key: bytes):
        """(known, value) at latest_version — known=False means the window
        has no entry and the caller falls through to the durable engine."""
        h = self._hist.get(key)
        if h:
            return True, h[-1][1]
        return False, None

    # -- reads ----------------------------------------------------------------

    def _at(self, key: bytes, version: int) -> Optional[bytes]:
        h = self._hist.get(key)
        if not h:
            return None
        i = _find_le(h, version)
        return h[i][1] if i >= 0 else None

    def get(self, key: bytes, version: int) -> Optional[bytes]:
        assert version >= self.oldest_version, "read below MVCC window"
        return self._at(key, version)

    def get_with_presence(self, key: bytes, version: int):
        """(known, value): known=False means the window has no entry — the
        caller falls through to the durable engine (the storage server's
        memory-over-disk merge, storageserver readRange:916)."""
        assert version >= self.oldest_version, "read below MVCC window"
        h = self._hist.get(key)
        if not h:
            return False, None
        i = _find_le(h, version)
        if i < 0:
            return False, None  # all entries newer than `version`
        return True, h[i][1]

    def entries_with_tombstones(
        self, begin: bytes, end: bytes, version: int
    ) -> list[tuple[bytes, Optional[bytes]]]:
        """All window-known (key, value|None-tombstone) in [begin, end) at
        `version` — for merging over the engine's rows."""
        assert version >= self.oldest_version
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        out = []
        for k in self._keys[lo:hi]:
            h = self._hist.get(k)
            i = _find_le(h, version)
            if i >= 0:
                out.append((k, h[i][1]))
        return out

    def window_view(self, begin: bytes, end: bytes, version: int):
        """(overlay, clears) for the window-over-engine merge: overlay maps
        window-known keys in [begin, end) to value|None-tombstone; clears
        are the native range tombstones that must additionally mask engine
        rows. The legacy map materializes per-key tombstones, so its
        clears list is always empty."""
        return dict(self.entries_with_tombstones(begin, end, version)), ()

    def range(
        self,
        begin: bytes,
        end: bytes,
        version: int,
        limit: int = 1 << 30,
        reverse: bool = False,
    ) -> list[tuple[bytes, bytes]]:
        assert version >= self.oldest_version
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        keys = self._keys[lo:hi]
        if reverse:
            keys = reversed(keys)
        out: list[tuple[bytes, bytes]] = []
        for k in keys:
            v = self._at(k, version)
            if v is not None:
                out.append((k, v))
                if len(out) >= limit:
                    break
        return out

    def __iter__(self) -> Iterator[bytes]:
        return iter(self._keys)

    # -- rollback (storageserver.actor.cpp:2172) ------------------------------

    def _rollback_entries(self, version: int) -> None:
        """Discard history entries above `version`, visiting only keys the
        touch log names there (rollback is rare; the filter is O(log))."""
        stale = {k for v, k in self._touch_log if v > version}
        self._touch_log = [e for e in self._touch_log if e[0] <= version]
        dead: list[bytes] = []
        for key in stale:
            h = self._hist.get(key)
            if h is None:
                continue
            i = _find_le(h, version)
            del h[i + 1 :]
            if not h:
                dead.append(key)
        self._drop_keys(dead)

    def _drop_keys(self, dead: list) -> None:
        if not dead:
            return
        if len(dead) == 1:
            key = dead[0]
            del self._hist[key]
            i = bisect.bisect_left(self._keys, key)
            del self._keys[i]
            return
        dead_set = set(dead)
        for key in dead_set:
            del self._hist[key]
        self._keys = [k for k in self._keys if k not in dead_set]

    def rollback_after(self, version: int) -> None:
        """Discard all history above `version` — the storage server's
        rollback when a recovery's epoch-end cuts off versions it had
        applied from a tlog whose tail didn't survive (rollback:2172)."""
        if version >= self.latest_version:
            return
        self._rollback_entries(version)
        self.latest_version = version

    # -- compaction -----------------------------------------------------------

    def _pop_touched(self, version: int) -> set:
        """Keys touched at versions <= `version`: the only keys a
        compaction to that horizon can affect. Pops the log prefix."""
        n = 0
        log = self._touch_log
        while n < len(log) and log[n][0] <= version:
            n += 1
        touched = {k for _v, k in log[:n]}
        del log[:n]
        return touched

    def forget_before(self, version: int, drop_known: bool = False) -> None:
        """Advance oldest_version, dropping superseded history (the analog of
        the storage server making versions durable and trimming the treap,
        storageserver.actor.cpp:2536). Visits only keys the touch log
        names below the horizon — a 2-key epoch costs 2 visits, not a
        scan of every key in the window.

        drop_known=True additionally drops entries ≤ version entirely —
        correct only when a durable engine holds the state at `version`
        and reads fall through to it (get_with_presence)."""
        if version < self.oldest_version or (
            version == self.oldest_version and not drop_known
        ):
            return
        version = min(version, self.latest_version)
        dead: list[bytes] = []
        for key in self._pop_touched(version):
            h = self._hist.get(key)
            if h is None:
                continue  # rolled back or already dropped
            self.forget_visits += 1
            # keep the newest entry at-or-below `version` plus everything after
            i = _find_le(h, version)
            if drop_known:
                if i >= 0:
                    del h[: i + 1]
                if not h:
                    dead.append(key)
                continue
            if i > 0:
                del h[:i]
            if len(h) == 1 and h[0][1] is None and h[0][0] <= version:
                dead.append(key)
        self._drop_keys(dead)
        self.oldest_version = version


class PinnedSnapshot:
    """O(1) immutable read handle at a pinned version (ROADMAP item 5 —
    Jiffy's snapshot operation). Registering the pin clamps the owner's
    compaction horizon: while the pin is held, ``forget_before`` cannot
    pass ``version``, so every read through the handle sees exactly the
    state at pin time without copying anything. The handle goes TOO_OLD
    (``invalidated``) when a rollback cuts off its version, or when the
    owner is forced past it (the storage server's pin-lag cap bounds how
    long an abandoned pin may grow the MVCC window)."""

    __slots__ = ("version", "pinned_at", "invalidated", "_vm", "_id")

    def __init__(self, vm: "EpochVersionedMap", version: int, pinned_at: float):
        self.version = version
        self.pinned_at = pinned_at
        self.invalidated = False
        self._vm = vm
        self._id = None

    def release(self) -> None:
        self._vm._pins.pop(self._id, None)

    @property
    def valid(self) -> bool:
        return not self.invalidated and self.version >= self._vm.oldest_version

    def _check(self) -> None:
        if not self.valid:
            from ..errors import TransactionTooOld

            raise TransactionTooOld()

    def get(self, key: bytes) -> Optional[bytes]:
        self._check()
        return self._vm.get(key, self.version)

    def get_with_presence(self, key: bytes):
        self._check()
        return self._vm.get_with_presence(key, self.version)

    def range(self, begin, end, limit: int = 1 << 30, reverse: bool = False):
        self._check()
        return self._vm.range(begin, end, self.version, limit=limit, reverse=reverse)

    def window_view(self, begin, end):
        self._check()
        return self._vm.window_view(begin, end, self.version)


class EpochVersionedMap(VersionedMap):
    """Epoch-batched MVCC window (ISSUE 15): whole mutation batches apply
    as one epoch, clears are native range tombstones, and snapshots pin.

    Write path: ``apply_epoch(version, entries, clears)`` — entries is the
    batch's FINAL per-key state (a set overwritten by a later clear in the
    same batch was already dropped by the builder; values may be None for
    point tombstones from atomic clears), clears the batch's range
    tombstones in arrival order. The sorted key index merges once per
    epoch (``merge_sorted_keys``) instead of an O(n) insort per new key.

    Read path: a key's value at ``version`` is its newest history entry
    ≤ version, unless a range tombstone with a version in (entry_version,
    version] covers the key — then it reads as absent-with-presence (the
    tombstone masks both window history and engine rows below it).

    Compaction: ``forget_before`` pops whole superseded epochs off the
    touch log and the clear list — O(touched), never O(total-keys) — and
    is clamped by active pins (``min_pinned``); ``rollback_after``
    truncates clears above the boundary and invalidates pins that hold
    cut-off versions (they fail TOO_OLD instead of serving them)."""

    def __init__(self) -> None:
        super().__init__()
        # native range tombstones, version-ascending; parallel version
        # list for bisect. A clear never touches per-key history.
        self._clears: list[tuple[int, bytes, bytes]] = []
        self._clear_versions: list[int] = []
        self._pins: dict[int, PinnedSnapshot] = {}
        self._pin_seq = 0
        self.keys_moved = 0  # sorted-index elements moved (regression counter)
        self.epochs_applied = 0

    # -- epoch writes ----------------------------------------------------------

    def apply_epoch(
        self,
        version: int,
        entries: dict,
        clears=(),
    ) -> None:
        assert version >= self.latest_version, "epochs must be version-ordered"
        self.latest_version = version
        new_keys: list = []
        hist = self._hist
        log = self._touch_log
        for k, v in entries.items():
            h = hist.get(k)
            if h is None:
                hist[k] = [(version, v)]
                new_keys.append(k)
                log.append((version, k))
            elif h[-1][0] == version:
                h[-1] = (version, v)
            else:
                h.append((version, v))
                log.append((version, k))
        for b, e in clears:
            self._clears.append((version, b, e))
            self._clear_versions.append(version)
        if new_keys:
            new_keys.sort()
            self._keys, moved = merge_sorted_keys(self._keys, new_keys)
            self.keys_moved += moved
        self.epochs_applied += 1

    # single-mutation writes ride one-op epochs (fetchKeys splices and
    # tests); the storage server's pull loop always batches
    def set(self, key: bytes, value: bytes, version: int) -> None:
        self.apply_epoch(version, {key: value})

    def clear_range(self, begin: bytes, end: bytes, version: int) -> None:
        self.apply_epoch(version, {}, ((begin, end),))

    # -- reads -----------------------------------------------------------------

    def _clears_over(self, key: bytes, after: int, upto: int) -> bool:
        """Any range tombstone with version in (after, upto] covering key?"""
        lo = bisect.bisect_right(self._clear_versions, after)
        hi = bisect.bisect_right(self._clear_versions, upto)
        for _cv, b, e in self._clears[lo:hi]:
            if b <= key < e:
                return True
        return False

    def latest_with_presence(self, key: bytes):
        h = self._hist.get(key)
        ev = h[-1][0] if h else -1
        if self._clears_over(key, ev, self.latest_version):
            return True, None
        if h:
            return True, h[-1][1]
        return False, None

    def latest(self, key: bytes) -> Optional[bytes]:
        return self.latest_with_presence(key)[1]

    def _at_presence(self, key: bytes, version: int):
        h = self._hist.get(key)
        i = _find_le(h, version) if h else -1
        ev = h[i][0] if i >= 0 else -1
        if self._clears_over(key, ev, version):
            return True, None
        if i >= 0:
            return True, h[i][1]
        return False, None

    def _at(self, key: bytes, version: int) -> Optional[bytes]:
        return self._at_presence(key, version)[1]

    def get_with_presence(self, key: bytes, version: int):
        assert version >= self.oldest_version, "read below MVCC window"
        return self._at_presence(key, version)

    def _range_clears(self, begin: bytes, end: bytes, version: int) -> list:
        hi = bisect.bisect_right(self._clear_versions, version)
        return [
            c for c in self._clears[:hi] if c[1] < end and c[2] > begin
        ]

    def window_view(self, begin: bytes, end: bytes, version: int):
        """(overlay, clears): overlay maps window-touched keys in
        [begin, end) to value|None at `version`; clears are the range
        tombstones ≤ version overlapping the range, which the caller must
        additionally apply over engine rows (every retained clear is
        newer than any engine content — superseded clears are drained to
        the engine before forget_before pops them)."""
        assert version >= self.oldest_version
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        clears = self._range_clears(begin, end, version)
        out: dict = {}
        for k in self._keys[lo:hi]:
            h = self._hist.get(k)
            i = _find_le(h, version)
            ev = h[i][0] if i >= 0 else -1
            if any(cv > ev and b <= k < e for cv, b, e in clears):
                out[k] = None
            elif i >= 0:
                out[k] = h[i][1]
        return out, [(b, e) for _cv, b, e in clears]

    def entries_with_tombstones(
        self, begin: bytes, end: bytes, version: int
    ) -> list[tuple[bytes, Optional[bytes]]]:
        """Window-TOUCHED keys only: a native range tombstone is NOT
        expanded over engine rows here — engine-merging callers must use
        window_view and apply its clears to the engine side."""
        overlay, _clears = self.window_view(begin, end, version)
        return sorted(overlay.items())

    def range(
        self,
        begin: bytes,
        end: bytes,
        version: int,
        limit: int = 1 << 30,
        reverse: bool = False,
    ) -> list[tuple[bytes, bytes]]:
        assert version >= self.oldest_version
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        keys = self._keys[lo:hi]
        if reverse:
            keys = reversed(keys)
        clears = self._range_clears(begin, end, version)
        out: list[tuple[bytes, bytes]] = []
        for k in keys:
            h = self._hist.get(k)
            i = _find_le(h, version)
            if i < 0:
                continue
            ev = h[i][0]
            if h[i][1] is None or any(
                cv > ev and b <= k < e for cv, b, e in clears
            ):
                continue
            out.append((k, h[i][1]))
            if len(out) >= limit:
                break
        return out

    # -- snapshots (O(1) pins) -------------------------------------------------

    def snapshot(self, version: int, pinned_at: float = 0.0) -> PinnedSnapshot:
        """An immutable read handle at `version`, O(1): nothing is copied —
        the pin registration clamps forget_before instead."""
        assert version >= self.oldest_version, "snapshot below MVCC window"
        snap = PinnedSnapshot(self, version, pinned_at)
        self._pin_seq += 1
        snap._id = self._pin_seq
        self._pins[snap._id] = snap
        return snap

    def min_pinned(self) -> Optional[int]:
        versions = [p.version for p in self._pins.values() if not p.invalidated]
        return min(versions) if versions else None

    def oldest_pin(self) -> Optional[PinnedSnapshot]:
        live = [p for p in self._pins.values() if not p.invalidated]
        return min(live, key=lambda p: (p.version, p.pinned_at)) if live else None

    def pinned_count(self) -> int:
        return sum(1 for p in self._pins.values() if not p.invalidated)

    # -- rollback / compaction -------------------------------------------------

    def rollback_after(self, version: int) -> None:
        if version >= self.latest_version:
            return
        # pins above the boundary hold versions the recovery cut off:
        # they must fail TOO_OLD, never serve them
        for pin in self._pins.values():
            if pin.version > version:
                pin.invalidated = True
        cut = bisect.bisect_right(self._clear_versions, version)
        del self._clears[cut:]
        del self._clear_versions[cut:]
        self._rollback_entries(version)
        self.latest_version = version

    def forget_before(self, version: int, drop_known: bool = False) -> None:
        if version < self.oldest_version or (
            version == self.oldest_version and not drop_known
        ):
            return
        version = min(version, self.latest_version)
        floor = self.min_pinned()
        if floor is not None and floor < version:
            # a pin holds the horizon; the storage server's pin-lag cap
            # invalidates overstaying pins BEFORE asking for the advance
            version = floor
            if version < self.oldest_version or (
                version == self.oldest_version and not drop_known
            ):
                return
        visit = self._pop_touched(version)
        # superseded range tombstones: whole clears pop off the list.
        # Without an engine the final pre-horizon state must survive in
        # the per-key chains, so a popped clear first materializes point
        # tombstones over the keys it still masks (bounded by covered
        # keys); with an engine (drop_known) the drained engine already
        # reflects the clear and it simply drops.
        cut = bisect.bisect_right(self._clear_versions, version)
        if cut:
            if not drop_known:
                for cv, b, e in self._clears[:cut]:
                    lo = bisect.bisect_left(self._keys, b)
                    hi = bisect.bisect_left(self._keys, e)
                    for k in self._keys[lo:hi]:
                        h = self._hist.get(k)
                        i = _find_le(h, cv)
                        # an entry AT the clear's version is the epoch's
                        # final word (set-after-clear): the clear lost
                        if i < 0 or h[i][1] is None or h[i][0] == cv:
                            continue
                        h.insert(i + 1, (cv, None))
                        visit.add(k)
            del self._clears[:cut]
            del self._clear_versions[:cut]
        dead: list[bytes] = []
        for key in visit:
            h = self._hist.get(key)
            if h is None:
                continue
            self.forget_visits += 1
            i = _find_le(h, version)
            if drop_known:
                if i >= 0:
                    del h[: i + 1]
                if not h:
                    dead.append(key)
                continue
            if i > 0:
                del h[:i]
            if len(h) == 1 and h[0][1] is None and h[0][0] <= version:
                dead.append(key)
        self._drop_keys(dead)
        self.oldest_version = version
        # a pin the caller force-advanced past (pin-lag cap) is dead
        for pin in self._pins.values():
            if pin.version < version:
                pin.invalidated = True
