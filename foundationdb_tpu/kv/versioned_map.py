"""Multi-version ordered map — the storage server's MVCC window.

The analog of the reference's VersionedMap persistent treap
(fdbclient/VersionedMap.h:31-68): holds the last few seconds of versions in
memory so reads at any version in [oldest_version, latest_version] see a
consistent snapshot. The reference uses a path-copying treap; here the same
semantics come from per-key version-history lists over one sorted key index —
simpler, and the batched-lookup form feeds the planned XLA range-query
primitive (SURVEY.md §7 stage 7) where the treap's pointer-chasing could not.

Mutations must be applied in nondecreasing version order (the storage server's
update loop guarantees this, mirroring storageserver.actor.cpp:2321).
"""

from __future__ import annotations

import bisect
from typing import Iterator, Optional


def _find_le(h: list[tuple[int, Optional[bytes]]], version: int) -> int:
    """Index of the last entry with entry.version <= version, else -1."""
    lo, hi = 0, len(h)
    while lo < hi:
        mid = (lo + hi) // 2
        if h[mid][0] <= version:
            lo = mid + 1
        else:
            hi = mid
    return lo - 1


class VersionedMap:
    def __init__(self) -> None:
        self._keys: list[bytes] = []  # sorted; includes tombstoned keys until GC
        self._hist: dict[bytes, list[tuple[int, Optional[bytes]]]] = {}
        self.oldest_version = 0
        self.latest_version = 0

    # -- writes (version-ordered) ---------------------------------------------

    def _append(self, key: bytes, version: int, value: Optional[bytes]) -> None:
        h = self._hist.get(key)
        if h is None:
            self._hist[key] = [(version, value)]
            bisect.insort(self._keys, key)
        elif h[-1][0] == version:
            h[-1] = (version, value)
        else:
            h.append((version, value))

    def set(self, key: bytes, value: bytes, version: int) -> None:
        assert version >= self.latest_version, "mutations must be version-ordered"
        self.latest_version = version
        self._append(key, version, value)

    def clear_range(self, begin: bytes, end: bytes, version: int) -> None:
        assert version >= self.latest_version
        self.latest_version = version
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        for key in self._keys[lo:hi]:
            self._append(key, version, None)

    def latest(self, key: bytes) -> Optional[bytes]:
        """Value at latest_version (used when applying atomic ops)."""
        h = self._hist.get(key)
        return h[-1][1] if h else None

    # -- reads ----------------------------------------------------------------

    def _at(self, key: bytes, version: int) -> Optional[bytes]:
        h = self._hist.get(key)
        if not h:
            return None
        i = _find_le(h, version)
        return h[i][1] if i >= 0 else None

    def get(self, key: bytes, version: int) -> Optional[bytes]:
        assert version >= self.oldest_version, "read below MVCC window"
        return self._at(key, version)

    def get_with_presence(self, key: bytes, version: int):
        """(known, value): known=False means the window has no entry — the
        caller falls through to the durable engine (the storage server's
        memory-over-disk merge, storageserver readRange:916)."""
        assert version >= self.oldest_version, "read below MVCC window"
        h = self._hist.get(key)
        if not h:
            return False, None
        i = _find_le(h, version)
        if i < 0:
            return False, None  # all entries newer than `version`
        return True, h[i][1]

    def entries_with_tombstones(
        self, begin: bytes, end: bytes, version: int
    ) -> list[tuple[bytes, Optional[bytes]]]:
        """All window-known (key, value|None-tombstone) in [begin, end) at
        `version` — for merging over the engine's rows."""
        assert version >= self.oldest_version
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        out = []
        for k in self._keys[lo:hi]:
            h = self._hist.get(k)
            i = _find_le(h, version)
            if i >= 0:
                out.append((k, h[i][1]))
        return out

    def range(
        self,
        begin: bytes,
        end: bytes,
        version: int,
        limit: int = 1 << 30,
        reverse: bool = False,
    ) -> list[tuple[bytes, bytes]]:
        assert version >= self.oldest_version
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        keys = self._keys[lo:hi]
        if reverse:
            keys = reversed(keys)
        out: list[tuple[bytes, bytes]] = []
        for k in keys:
            v = self._at(k, version)
            if v is not None:
                out.append((k, v))
                if len(out) >= limit:
                    break
        return out

    def __iter__(self) -> Iterator[bytes]:
        return iter(self._keys)

    # -- rollback (storageserver.actor.cpp:2172) ------------------------------

    def rollback_after(self, version: int) -> None:
        """Discard all history above `version` — the storage server's
        rollback when a recovery's epoch-end cuts off versions it had
        applied from a tlog whose tail didn't survive (rollback:2172)."""
        if version >= self.latest_version:
            return
        dead: list[bytes] = []
        for key, h in self._hist.items():
            i = _find_le(h, version)
            del h[i + 1 :]
            if not h:
                dead.append(key)
        for key in dead:
            del self._hist[key]
            i = bisect.bisect_left(self._keys, key)
            del self._keys[i]
        self.latest_version = version

    # -- compaction -----------------------------------------------------------

    def forget_before(self, version: int, drop_known: bool = False) -> None:
        """Advance oldest_version, dropping superseded history (the analog of
        the storage server making versions durable and trimming the treap,
        storageserver.actor.cpp:2536).

        drop_known=True additionally drops entries ≤ version entirely —
        correct only when a durable engine holds the state at `version`
        and reads fall through to it (get_with_presence)."""
        if version < self.oldest_version or (
            version == self.oldest_version and not drop_known
        ):
            return
        version = min(version, self.latest_version)
        dead: list[bytes] = []
        for key, h in self._hist.items():
            # keep the newest entry at-or-below `version` plus everything after
            i = _find_le(h, version)
            if drop_known:
                if i >= 0:
                    del h[: i + 1]
                if not h:
                    dead.append(key)
                continue
            if i > 0:
                del h[:i]
            if len(h) == 1 and h[0][1] is None and h[0][0] <= version:
                dead.append(key)
        for key in dead:
            del self._hist[key]
            i = bisect.bisect_left(self._keys, key)
            del self._keys[i]
        self.oldest_version = version
