"""Atomic-op apply functions.

The analog of fdbclient/Atomic.h. Semantics (matching the reference's
current-generation ops, i.e. the V2 variants where the reference kept buggy
V1 compatibility shims):

- arithmetic/bitwise ops produce a result of the *operand's* length, with the
  existing value zero-extended or truncated to match;
- on a missing key, every op (including AND, per the reference's doAndV2)
  stores the operand;
- COMPARE_AND_CLEAR returns None (clear) iff the existing value equals the
  operand.

Every function takes (existing: bytes | None, param: bytes) and returns the
new value, or None meaning "key cleared".
"""

from __future__ import annotations

from typing import Callable, Optional

from .mutations import MutationType

APPEND_LIMIT = 131072  # value-size limit, matches reference VALUE_SIZE_LIMIT


def _fit(existing: Optional[bytes], n: int) -> bytes:
    e = existing or b""
    return e[:n].ljust(n, b"\x00")


def do_add(existing: Optional[bytes], param: bytes) -> bytes:
    if not param:
        return b""
    n = len(param)
    a = int.from_bytes(_fit(existing, n), "little")
    b = int.from_bytes(param, "little")
    return ((a + b) % (1 << (8 * n))).to_bytes(n, "little")


def do_and(existing: Optional[bytes], param: bytes) -> bytes:
    if existing is None:
        return param  # doAndV2: absent key stores the operand
    e = _fit(existing, len(param))
    return bytes(x & y for x, y in zip(e, param))


def do_or(existing: Optional[bytes], param: bytes) -> bytes:
    e = _fit(existing, len(param))
    return bytes(x | y for x, y in zip(e, param))


def do_xor(existing: Optional[bytes], param: bytes) -> bytes:
    e = _fit(existing, len(param))
    return bytes(x ^ y for x, y in zip(e, param))


def do_append_if_fits(existing: Optional[bytes], param: bytes) -> bytes:
    e = existing or b""
    return e + param if len(e) + len(param) <= APPEND_LIMIT else e


def do_max(existing: Optional[bytes], param: bytes) -> bytes:
    if existing is None:
        return param
    e = _fit(existing, len(param))
    a = int.from_bytes(e, "little")
    b = int.from_bytes(param, "little")
    return e if a > b else param


def do_min(existing: Optional[bytes], param: bytes) -> bytes:
    if existing is None:
        return param
    e = _fit(existing, len(param))
    a = int.from_bytes(e, "little")
    b = int.from_bytes(param, "little")
    return e if a < b else param


def do_byte_max(existing: Optional[bytes], param: bytes) -> bytes:
    if existing is None:
        return param
    return existing if existing > param else param


def do_byte_min(existing: Optional[bytes], param: bytes) -> bytes:
    if existing is None:
        return param
    return existing if existing < param else param


def do_compare_and_clear(
    existing: Optional[bytes], param: bytes
) -> Optional[bytes]:
    return None if existing == param else existing


APPLY: dict[MutationType, Callable[[Optional[bytes], bytes], Optional[bytes]]] = {
    MutationType.ADD: do_add,
    MutationType.AND: do_and,
    MutationType.OR: do_or,
    MutationType.XOR: do_xor,
    MutationType.APPEND_IF_FITS: do_append_if_fits,
    MutationType.MAX: do_max,
    MutationType.MIN: do_min,
    MutationType.BYTE_MIN: do_byte_min,
    MutationType.BYTE_MAX: do_byte_max,
    MutationType.COMPARE_AND_CLEAR: do_compare_and_clear,
}


def apply_atomic(
    op: MutationType, existing: Optional[bytes], param: bytes
) -> Optional[bytes]:
    return APPLY[op](existing, param)
