"""ctypes binding for the native C++ copy-on-write B-tree engine.

The disk-resident IKeyValueStore (the role of the reference's modified
sqlite btree, fdbserver/KeyValueStoreSQLite.actor.cpp) — same interface as
kv.engine.KeyValueStoreMemory, for real deployments and benchmarks (the
simulator uses the Python engines on SimDisk for determinism, mirroring
how the reference runs sqlite on simulated files)."""

from __future__ import annotations

import ctypes
import os
import subprocess

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libbtree_kvstore.so"))
_lib = None

_MAX_VALUE = 1 << 20


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR), "-s"], check=True
        )
    lib = ctypes.CDLL(_LIB_PATH)
    lib.bt_open.restype = ctypes.c_void_p
    lib.bt_open.argtypes = [ctypes.c_char_p]
    lib.bt_close.argtypes = [ctypes.c_void_p]
    lib.bt_set.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.bt_clear_range.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.bt_commit.argtypes = [ctypes.c_void_p]
    lib.bt_get.restype = ctypes.c_int64
    lib.bt_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.bt_range_open.restype = ctypes.c_void_p
    lib.bt_range_open.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.bt_cursor_next.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
    ]
    lib.bt_cursor_current.argtypes = lib.bt_cursor_next.argtypes
    lib.bt_cursor_close.argtypes = [ctypes.c_void_p]
    lib.bt_stats.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    _lib = lib
    return lib


class KeyValueStoreBTree:
    """IKeyValueStore over the native B-tree (kv.engine-compatible)."""

    def __init__(self, path: str):
        self._lib = _load()
        self.path = path
        self._h = self._lib.bt_open(path.encode())
        if not self._h:
            raise OSError(f"bt_open failed: {path}")
        self._vbuf = ctypes.create_string_buffer(_MAX_VALUE)
        self._kbuf = ctypes.create_string_buffer(1 << 14)

    async def recover(self) -> None:
        pass  # bt_open already recovered the latest committed epoch

    def set(self, key: bytes, value: bytes) -> None:
        rc = self._lib.bt_set(self._h, key, len(key), value, len(value))
        if rc != 0:
            raise ValueError(f"bt_set failed (rc={rc}; key too large?)")

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self._lib.bt_clear_range(self._h, begin, len(begin), end, len(end))

    async def commit(self) -> None:
        rc = self._lib.bt_commit(self._h)
        if rc != 0:
            raise OSError(f"bt_commit failed: {rc}")

    def read_value(self, key: bytes):
        n = self._lib.bt_get(self._h, key, len(key), self._vbuf, len(self._vbuf))
        if n < 0:
            return None
        if n > len(self._vbuf):
            # value larger than the buffer: grow and re-read (bt_get never
            # truncates silently — it reports the true length)
            self._vbuf = ctypes.create_string_buffer(int(n))
            n = self._lib.bt_get(self._h, key, len(key), self._vbuf, len(self._vbuf))
        return self._vbuf.raw[:n]

    def read_range(self, begin: bytes, end: bytes, limit: int = 1 << 30):
        cur = self._lib.bt_range_open(self._h, begin, len(begin), end, len(end))
        out = []
        klen = ctypes.c_int64()
        vlen = ctypes.c_int64()
        try:
            while len(out) < limit:
                rc = self._lib.bt_cursor_next(
                    cur,
                    self._kbuf, len(self._kbuf), ctypes.byref(klen),
                    self._vbuf, len(self._vbuf), ctypes.byref(vlen),
                )
                if rc == 0:
                    break
                if rc == -1:
                    # row held in the cursor; grow and re-copy
                    if klen.value > len(self._kbuf):
                        self._kbuf = ctypes.create_string_buffer(int(klen.value))
                    if vlen.value > len(self._vbuf):
                        self._vbuf = ctypes.create_string_buffer(int(vlen.value))
                    rc = self._lib.bt_cursor_current(
                        cur,
                        self._kbuf, len(self._kbuf), ctypes.byref(klen),
                        self._vbuf, len(self._vbuf), ctypes.byref(vlen),
                    )
                    assert rc == 1
                out.append(
                    (self._kbuf.raw[: klen.value], self._vbuf.raw[: vlen.value])
                )
        finally:
            self._lib.bt_cursor_close(cur)
        return out

    def stats(self):
        e = ctypes.c_uint64()
        p = ctypes.c_uint64()
        lb = ctypes.c_uint64()
        self._lib.bt_stats(self._h, ctypes.byref(e), ctypes.byref(p), ctypes.byref(lb))
        return {"epoch": e.value, "pages": p.value, "live_bytes": lb.value}

    def close(self) -> None:
        if self._h:
            self._lib.bt_close(self._h)
            self._h = None

    def __len__(self) -> int:
        return len(self.read_range(b"", b"\xff\xff\xff\xff\xff\xff"))
