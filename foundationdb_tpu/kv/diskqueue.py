"""DiskQueue: the durable push/pop queue under the TLog and the memory
storage engine.

The analog of fdbserver/DiskQueue.actor.cpp: an append-only entry log with
per-entry CRC framing, an atomically-updated meta record holding the popped
frontier, and crash recovery that replays valid entries and discards any
torn tail (the reference's checksummed two-file ring; here one data file
per generation with copy-compaction when the popped prefix dominates,
which preserves the same guarantees on the IAsyncFile model).

Durability contract (what the TLog's commit ack means):
- ``push()`` buffers; ``commit()`` writes + fsyncs — after commit returns,
  every pushed entry survives a kill.
- ``pop(upto)`` logically discards entries with offset < upto; persisted
  with the next commit; compaction reclaims space by copying the live
  suffix into a fresh file and atomically switching the meta record.
- ``recover()`` returns [(offset, payload)] of all live entries, stopping
  at the first bad CRC (a torn write from a kill — everything before it
  was acknowledged, everything after never was).
"""

from __future__ import annotations

import struct
import zlib

from ..runtime.futures import Future

_META_MAGIC = b"FDBQMETA"
_ENTRY_HDR = struct.Struct("<II")  # length, crc32


class DiskQueue:
    def __init__(self, disk, name: str):
        self.disk = disk
        self.name = name
        self._meta = disk.open(f"{name}.meta")
        self._file = None
        self._file_id = 0
        self._popped = 0  # offset: entries below are discarded
        self._end = 0  # append position (committed + buffered)
        self._buffer: list[bytes] = []
        self._buffer_base = 0
        self._pop_dirty = False
        self._push_gen = 0  # bumped per push; compaction aborts if raced
        self._flip_pending = None  # Future while a compaction meta-flip runs
        # group commit (ISSUE 15): concurrent commit() callers coalesce —
        # one physical write+fsync round covers every caller whose pushes
        # and pops it observed; followers whose work the round made
        # durable return without their own fsync
        self._commit_active = None  # Future while a commit round runs
        self._durable_end = 0  # highest append offset a round made durable
        self._durable_pop = 0  # highest popped frontier made durable
        self.commits = 0  # physical write+fsync rounds
        self.group_joins = 0  # commit() calls satisfied by another round
        self.fsync_seconds = 0.0  # cumulative time inside write+fsync rounds

    # -- recovery --------------------------------------------------------------

    async def recover(self) -> list[tuple[int, bytes]]:
        """Open (or create) the queue; return live [(offset, payload)]."""
        meta = await self._meta.read(0, 64)
        if len(meta) >= 28 and meta[:8] == _META_MAGIC:
            (crc,) = struct.unpack_from("<I", meta, 24)
            if crc == zlib.crc32(meta[:24]):
                self._file_id, self._popped = struct.unpack_from("<QQ", meta, 8)
        self._file = self.disk.open(f"{self.name}.{self._file_id}.data")
        raw = await self._file.read(0, self._file.size())
        out: list[tuple[int, bytes]] = []
        pos = 0
        while pos + _ENTRY_HDR.size <= len(raw):
            length, crc = _ENTRY_HDR.unpack_from(raw, pos)
            payload = raw[pos + _ENTRY_HDR.size : pos + _ENTRY_HDR.size + length]
            if len(payload) < length or zlib.crc32(payload) != crc:
                break  # torn tail from a kill: never acknowledged
            if pos >= self._popped:
                out.append((pos, payload))
            pos += _ENTRY_HDR.size + length
        self._buffer_base = pos
        # entries pushed before recovery (lazy first-commit open) keep
        # their relative offsets above the recovered end
        shift = pos - 0
        if self._buffer and shift:
            raise AssertionError("pushes preceded recovery of a non-empty queue")
        self._end = pos + sum(len(b) for b in self._buffer)
        await self._file.truncate(pos)  # drop the torn tail for clean appends
        return out

    # -- operation -------------------------------------------------------------

    def push(self, payload: bytes) -> int:
        """Queue an entry; returns its offset (valid after next commit)."""
        self._push_gen += 1
        offset = self._end
        self._buffer.append(
            _ENTRY_HDR.pack(len(payload), zlib.crc32(payload)) + payload
        )
        self._end += _ENTRY_HDR.size + len(payload)
        return offset

    async def commit(self) -> None:
        """Make all pushed entries (and any pop) durable.

        Group-committed: while a round's write+fsync is in flight, later
        callers park on it; a caller whose pushes/pops the finished round
        covered returns WITHOUT another fsync (N concurrent committers →
        a bounded number of fsync rounds, not N). The durability contract
        is unchanged: after commit() returns, everything pushed before
        the call survives a kill."""
        from ..runtime.buggify import buggify
        from ..runtime.futures import Future, delay

        if buggify():
            await delay(0.002)  # slow fsync (stalls the commit quorum)
        target_end = self._end
        target_pop = self._popped
        while self._commit_active is not None:
            await self._commit_active
            if (
                self._durable_end >= target_end
                and self._durable_pop >= target_pop
            ):
                self.group_joins += 1
                return
        self._commit_active = Future()
        try:
            while self._flip_pending is not None:
                # a compaction has swapped files but not yet flipped the meta
                # record: committing (and acking!) into the new file before
                # the flip is durable would lose the entry if we crash with
                # the meta still naming the old file
                await self._flip_pending
            if self._file is None:
                # lazy open for a freshly created queue (first commit wins;
                # the tlog's version gate serializes callers)
                await self.recover()
            from ..runtime.loop import now

            t0 = now()
            end_now = self._end
            pop_now = self._popped
            if self._buffer:
                blob = b"".join(self._buffer)
                base = self._buffer_base
                self._buffer = []
                self._buffer_base = self._end
                await self._file.write(base, blob)
            await self._file.sync()
            if self._pop_dirty:
                await self._write_meta()
                self._pop_dirty = False
            self._durable_end = max(self._durable_end, end_now)
            self._durable_pop = max(self._durable_pop, pop_now)
            self.commits += 1
            self.fsync_seconds += now() - t0
        finally:
            done, self._commit_active = self._commit_active, None
            done._set(None)

    async def read_entry(self, offset: int, end: int) -> bytes:
        """Read back one pushed entry by its [offset, end) coordinates —
        the tlog's spill-by-reference path (spilled payloads live only
        here). CRC-checked; the entry must have been committed."""
        raw = await self._file.read(offset, end - offset)
        length, crc = _ENTRY_HDR.unpack_from(raw, 0)
        payload = raw[_ENTRY_HDR.size : _ENTRY_HDR.size + length]
        if len(payload) != length or zlib.crc32(payload) != crc:
            raise IOError(f"diskqueue {self.name}: bad entry at {offset}")
        return payload

    def pop(self, upto_offset: int) -> None:
        if upto_offset > self._popped:
            self._popped = upto_offset
            self._pop_dirty = True

    async def compact(self) -> int:
        """Reclaim the popped prefix: copy live data to a fresh file, then
        atomically switch the meta record (write-new-then-flip ordering).
        Returns the offset shift applied (0 if nothing happened) so
        callers can rebase any offsets they cached."""
        if (
            self._popped == 0
            or self._buffer
            or self._flip_pending is not None
            or self._commit_active is not None
        ):
            # an in-flight commit round holds a reference into the current
            # file; swapping under its write/sync awaits could land an
            # acked entry only in the about-to-be-removed file
            return 0
        gen = self._push_gen
        live = await self._file.read(0, self._file.size())
        live = live[self._popped :]
        new_id = self._file_id + 1
        new_file = self.disk.open(f"{self.name}.{new_id}.data")
        await new_file.truncate(0)
        if live:
            await new_file.write(0, live)
        await new_file.sync()
        if self._push_gen != gen:
            # a push raced our copy; its offset assumes the old layout —
            # abandon this compaction attempt (the file is retried later)
            self.disk.remove(f"{self.name}.{new_id}.data")
            return 0
        # swap synchronously (no awaits until the meta flip below): pushes
        # from here on use new-file coordinates and commit() blocks on the
        # flip, so nothing acked can land only in an unreachable file
        old_id, shift = self._file_id, self._popped
        self._file_id, self._popped = new_id, 0
        self._end -= shift
        self._buffer_base -= shift
        self._durable_end = max(0, self._durable_end - shift)
        self._durable_pop = 0
        self._file = new_file
        self._flip_pending = Future()
        try:
            await self._write_meta()  # the flip: synced meta names new file
        finally:
            flip, self._flip_pending = self._flip_pending, None
            flip._set(None)
        self.disk.remove(f"{self.name}.{old_id}.data")
        return shift

    async def _write_meta(self) -> None:
        body = _META_MAGIC + struct.pack("<QQ", self._file_id, self._popped)
        blob = body + struct.pack("<I", zlib.crc32(body))
        await self._meta.write(0, blob)
        await self._meta.sync()

    @property
    def popped_offset(self) -> int:
        return self._popped

    @property
    def bytes_used(self) -> int:
        return self._end - self._popped
