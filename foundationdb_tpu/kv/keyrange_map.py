"""Key-range → value map.

The analog of the reference's KeyRangeMap (fdbclient/KeyRangeMap.h:36 over
fdbrpc/RangeMap.h): a total map over the key space [b"", ∞) represented as
sorted boundary keys, each owning the half-open range up to the next boundary.
Used for the shard map (key → storage team), the proxy's keyResolvers map,
and — stage 7 — batched as the XLA interval-query primitive on the read path.
"""

from __future__ import annotations

import bisect
from typing import Any, Iterator, Optional, Tuple


class KeyRangeMap:
    def __init__(self, default: Any = None) -> None:
        self._bounds: list[bytes] = [b""]
        self._vals: list[Any] = [default]

    def _idx(self, key: bytes) -> int:
        return bisect.bisect_right(self._bounds, key) - 1

    def __getitem__(self, key: bytes) -> Any:
        return self._vals[self._idx(key)]

    def range_for(self, key: bytes) -> Tuple[bytes, Optional[bytes], Any]:
        """(begin, end, value) of the range containing key; end=None is ∞."""
        i = self._idx(key)
        end = self._bounds[i + 1] if i + 1 < len(self._bounds) else None
        return self._bounds[i], end, self._vals[i]

    def range_before(self, key: bytes) -> Tuple[bytes, Optional[bytes], Any]:
        """(begin, end, value) of the range containing the keys immediately
        BELOW ``key`` (i.e. the predecessor's range). For key == b"" there is
        no predecessor; returns the first range."""
        i = self._idx(key)
        if self._bounds[i] == key and i > 0:
            i -= 1
        end = self._bounds[i + 1] if i + 1 < len(self._bounds) else None
        return self._bounds[i], end, self._vals[i]

    def insert(self, begin: bytes, end: Optional[bytes], value: Any) -> None:
        """Set value on [begin, end); end=None means to infinity."""
        if end is not None and begin >= end:
            return
        # value that resumes at `end`
        if end is not None:
            resume = self._vals[self._idx(end)]
        lo = bisect.bisect_left(self._bounds, begin)
        hi = bisect.bisect_left(self._bounds, end) if end is not None else len(self._bounds)
        new_bounds = [begin]
        new_vals = [value]
        if end is not None and (hi >= len(self._bounds) or self._bounds[hi] != end):
            new_bounds.append(end)
            new_vals.append(resume)
        self._bounds[lo:hi] = new_bounds
        self._vals[lo:hi] = new_vals

    def ranges(self) -> Iterator[Tuple[bytes, Optional[bytes], Any]]:
        """Yield (begin, end, value); final range has end=None (infinity)."""
        for i, b in enumerate(self._bounds):
            e = self._bounds[i + 1] if i + 1 < len(self._bounds) else None
            yield b, e, self._vals[i]

    def intersecting(
        self, begin: bytes, end: Optional[bytes]
    ) -> list[Tuple[bytes, Optional[bytes], Any]]:
        """Ranges overlapping [begin, end), clipped to it. O(log n + k):
        this sits on the proxy's per-conflict-range routing hot path."""
        out = []
        i = self._idx(begin)
        n = len(self._bounds)
        while i < n:
            b = self._bounds[i]
            if end is not None and b >= end:
                break
            e = self._bounds[i + 1] if i + 1 < n else None
            cb = max(b, begin)
            ce = e if end is None else (end if e is None else min(e, end))
            out.append((cb, ce, self._vals[i]))
            i += 1
        return out

    def _split_at(self, key: bytes) -> None:
        i = self._idx(key)
        if self._bounds[i] != key:
            self._bounds.insert(i + 1, key)
            self._vals.insert(i + 1, self._vals[i])

    def modify(self, begin: bytes, end: Optional[bytes], fn) -> None:
        """Apply ``fn(old_value) -> new_value`` to every piece of
        [begin, end), splitting boundaries at begin/end (RangeMap::modify)."""
        self._split_at(begin)
        if end is not None:
            self._split_at(end)
        lo = bisect.bisect_left(self._bounds, begin)
        hi = (
            bisect.bisect_left(self._bounds, end)
            if end is not None
            else len(self._bounds)
        )
        for i in range(lo, hi):
            self._vals[i] = fn(self._vals[i])

    def coalesce(self) -> None:
        """Merge adjacent ranges with equal values (CoalescedKeyRangeMap)."""
        bounds, vals = [self._bounds[0]], [self._vals[0]]
        for b, v in zip(self._bounds[1:], self._vals[1:]):
            if v != vals[-1]:
                bounds.append(b)
                vals.append(v)
        self._bounds, self._vals = bounds, vals
