"""IKeyValueStore: local durable storage engines.

The analog of fdbserver/IKeyValueStore.h (:27-77): the storage server's
and txn-state's local engine seam. Engines here:

- ``KeyValueStoreMemory`` — the reference's memory engine
  (KeyValueStoreMemory.actor.cpp): all data in an ordered in-memory map;
  durability from an operation log in a DiskQueue, periodically compacted
  by writing a full snapshot entry and popping everything before it
  (:337 op-log, :580 snapshotting).
- the native B-tree engine (foundationdb_tpu/native) is its
  disk-resident sibling for real deployments — same interface.

Writes buffer in memory; ``commit()`` makes everything before it durable.
"""

from __future__ import annotations

import bisect

from ..runtime.serialize import BinaryReader, BinaryWriter
from .diskqueue import DiskQueue
from .versioned_map import merge_sorted_keys

_OP_SET = 0
_OP_CLEAR = 1
_SNAPSHOT = 2


class KeyValueStoreMemory:
    SNAPSHOT_AFTER_BYTES = 1 << 20  # op-log size that triggers a snapshot

    def __init__(self, disk, name: str):
        self.dq = DiskQueue(disk, name)
        self._keys: list[bytes] = []  # sorted
        self._map: dict[bytes, bytes] = {}
        self._ops = BinaryWriter()
        self._ops_count = 0
        self._oplog_bytes = 0  # op-log bytes since the last snapshot
        # key → was it present BEFORE its first touch this epoch? The
        # storage's TPU range index delta-merges from the EXACT diff
        # (present-before vs present-after), so add+clear-within-an-epoch
        # nets out instead of corrupting the index. Tracking is off until
        # the index consumer enables it (a real server with the index
        # disabled must not leak touched keys forever).
        self.track_dirty = False
        self.dirty_keys: dict = {}
        # sorted-index elements moved by inserts/merges — the bulk-ingest
        # regression counter (PR 14's RecvBuffer bytes_moved discipline):
        # per-key insort moves O(n) per NEW key, apply_epoch merges once
        self.keys_moved = 0

    # -- recovery --------------------------------------------------------------

    async def recover(self) -> None:
        entries = await self.dq.recover()
        for _off, payload in entries:
            r = BinaryReader(payload)
            kind = r.u8()
            if kind == _SNAPSHOT:
                self._map = {}
                n = r.u32()
                for _ in range(n):
                    k = r.bytes_()
                    self._map[k] = r.bytes_()
            else:
                self._apply_ops(r, kind)
                while r.remaining():
                    self._apply_ops(r, r.u8())
        self._keys = sorted(self._map)

    def _apply_ops(self, r: BinaryReader, kind: int) -> None:
        if kind == _OP_SET:
            # locals first: Python evaluates an assignment's RHS before
            # the subscript target, so inlining both reads SWAPPED
            # key/value on op-log replay (a reboot then served rows whose
            # key was the old value — found by the chaos soak's
            # ConsistencyCheck as replica divergence)
            k = r.bytes_()
            v = r.bytes_()
            self._map[k] = v
        elif kind == _OP_CLEAR:
            b, e = r.bytes_(), r.bytes_()
            for k in [k for k in self._map if b <= k < e]:
                del self._map[k]
        else:
            raise AssertionError(f"bad op {kind}")

    # -- writes ----------------------------------------------------------------

    def set(self, key: bytes, value: bytes) -> None:
        if key not in self._map:
            if self.track_dirty:
                self.dirty_keys.setdefault(key, False)
            i = bisect.bisect_left(self._keys, key)
            self.keys_moved += len(self._keys) - i
            self._keys.insert(i, key)
        self._map[key] = value
        self._ops.u8(_OP_SET).bytes_(key).bytes_(value)
        self._ops_count += 1

    def clear_range(self, begin: bytes, end: bytes) -> None:
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        for k in self._keys[lo:hi]:
            del self._map[k]
            if self.track_dirty:
                self.dirty_keys.setdefault(k, True)
        self.keys_moved += len(self._keys) - hi
        del self._keys[lo:hi]
        self._ops.u8(_OP_CLEAR).bytes_(begin).bytes_(end)
        self._ops_count += 1

    def apply_epoch(self, entries: dict, clears=()) -> None:
        """One durability epoch in a single call (ISSUE 15): range clears
        first, then the epoch's FINAL per-key entries (builders drop a
        set that a later clear in the same epoch overwrote, so this
        normalized order reproduces the in-order result; the op log
        records the same order for replay). A None entry is a point
        tombstone (atomic clear). The sorted key index merges ONCE per
        epoch — O(n + m) — instead of paying an O(n) insort per new key."""
        for b, e in clears:
            self.clear_range(b, e)
        new_keys: list = []
        dead: list = []
        for k, v in entries.items():
            if v is None:
                self._ops.u8(_OP_CLEAR).bytes_(k).bytes_(k + b"\x00")
                self._ops_count += 1
                if k in self._map:
                    del self._map[k]
                    dead.append(k)
                    if self.track_dirty:
                        self.dirty_keys.setdefault(k, True)
                continue
            if k not in self._map:
                if self.track_dirty:
                    self.dirty_keys.setdefault(k, False)
                new_keys.append(k)
            self._map[k] = v
            self._ops.u8(_OP_SET).bytes_(k).bytes_(v)
            self._ops_count += 1
        for k in dead:
            i = bisect.bisect_left(self._keys, k)
            self.keys_moved += len(self._keys) - i - 1
            del self._keys[i]
        if new_keys:
            new_keys.sort()
            self._keys, moved = merge_sorted_keys(self._keys, new_keys)
            self.keys_moved += moved

    async def commit(self) -> None:
        if self._ops_count:
            blob = self._ops.data()
            self._oplog_bytes += len(blob)
            self.dq.push(blob)
            self._ops = BinaryWriter()
            self._ops_count = 0
        await self.dq.commit()
        # snapshot when the op-log since the last snapshot dominates —
        # comparing against total queue bytes would re-snapshot the whole
        # dataset on every commit once it exceeds a fixed threshold
        if self._oplog_bytes > max(
            self.SNAPSHOT_AFTER_BYTES, self.dq.bytes_used - self._oplog_bytes
        ):
            await self._snapshot()

    async def _snapshot(self) -> None:
        w = BinaryWriter()
        w.u8(_SNAPSHOT).u32(len(self._map))
        for k in self._keys:
            w.bytes_(k).bytes_(self._map[k])
        offset = self.dq.push(w.data())
        await self.dq.commit()
        self.dq.pop(offset)
        await self.dq.commit()
        await self.dq.compact()
        self._oplog_bytes = 0

    # -- reads -----------------------------------------------------------------

    def take_dirty(self):
        """(added, removed): the exact key diff since the last call —
        keys absent before and present now, and vice versa. Keys that
        net out (add+clear, clear+re-add within the window) appear in
        neither."""
        d, self.dirty_keys = self.dirty_keys, {}
        added = [k for k, was in d.items() if not was and k in self._map]
        removed = [k for k, was in d.items() if was and k not in self._map]
        return added, removed

    def read_value(self, key: bytes):
        return self._map.get(key)

    def read_range(
        self, begin: bytes, end: bytes, limit: int = 1 << 30, reverse: bool = False
    ):
        lo = bisect.bisect_left(self._keys, begin)
        hi = bisect.bisect_left(self._keys, end)
        if reverse:
            # the LAST `limit` rows below `end` — O(limit), so a
            # reverse-limited storage read never materializes the shard
            ks = self._keys[max(lo, hi - limit) : hi]
            return [(k, self._map[k]) for k in reversed(ks)]
        out = []
        for k in self._keys[lo:hi]:
            out.append((k, self._map[k]))
            if len(out) >= limit:
                break
        return out

    def __len__(self) -> int:
        return len(self._map)
