"""Mutation wire types.

The analog of the reference's ``MutationRef`` (fdbclient/CommitTransaction.h:27-60):
a transaction's effects are a list of typed mutations; SET_VALUE / CLEAR_RANGE
are the structural ones, the rest are atomic read-modify-write ops applied at
the storage server (and coalesced client-side for read-your-writes).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class MutationType(enum.IntEnum):
    SET_VALUE = 0
    CLEAR_RANGE = 1
    ADD = 2
    AND = 3
    OR = 4
    XOR = 5
    APPEND_IF_FITS = 6
    MAX = 7
    MIN = 8
    SET_VERSIONSTAMPED_KEY = 9
    SET_VERSIONSTAMPED_VALUE = 10
    BYTE_MIN = 11
    BYTE_MAX = 12
    COMPARE_AND_CLEAR = 13


ATOMIC_OPS = frozenset(
    {
        MutationType.ADD,
        MutationType.AND,
        MutationType.OR,
        MutationType.XOR,
        MutationType.APPEND_IF_FITS,
        MutationType.MAX,
        MutationType.MIN,
        MutationType.BYTE_MIN,
        MutationType.BYTE_MAX,
        MutationType.COMPARE_AND_CLEAR,
    }
)

VERSIONSTAMP_OPS = frozenset(
    {MutationType.SET_VERSIONSTAMPED_KEY, MutationType.SET_VERSIONSTAMPED_VALUE}
)


@dataclass(frozen=True)
class Mutation:
    """For SET_VALUE / atomic ops: (type, key, value-or-operand).
    For CLEAR_RANGE: (type, begin, end)."""

    type: MutationType
    param1: bytes
    param2: bytes

    def is_atomic(self) -> bool:
        return self.type in ATOMIC_OPS

    def __repr__(self) -> str:
        return f"Mutation({self.type.name}, {self.param1!r}, {self.param2!r})"
