"""Key selectors: offset-relative keyspace navigation.

The analog of the reference's KeySelectorRef (fdbclient/FDBTypes.h:462)
and the four standard constructors every binding exposes
(fdb_c's FDB_KEYSEL_* macros). A selector (key, or_equal, offset) names
a position in the ordered keyspace relative to existing keys:

    base  = the last key <  `key`   (or_equal=False)
            the last key <= `key`   (or_equal=True)
    result= the key `offset` positions after base (offset may be <= 0)

Resolution clamps to the navigable keyspace: a position before the first
key resolves to b"" and a position past the last key resolves to
SELECTOR_END (b"\\xff" — the reference's behavior without system-key
access, NativeAPI.actor.cpp getKey's maxKey clamp). Keys at or above
SELECTOR_END (the system keyspace) are invisible to selector walks.

The reference normalizes or_equal away before resolving
(KeySelectorRef::removeOrEqual: "<= k" is "< keyAfter(k)"); everything
past the client API boundary — the storage getKey endpoint, the model
oracle — works on the normalized (key, offset) form, where resolution
over a sorted key list K is simply K[bisect_left(K, key) - 1 + offset].
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Iterable

# resolution clamp / system-keyspace boundary (maxKey without system access)
SELECTOR_END = b"\xff"


@dataclass(frozen=True)
class KeySelector:
    key: bytes
    or_equal: bool = False
    offset: int = 1

    @classmethod
    def last_less_than(cls, key: bytes) -> "KeySelector":
        return cls(key, False, 0)

    @classmethod
    def last_less_or_equal(cls, key: bytes) -> "KeySelector":
        return cls(key, True, 0)

    @classmethod
    def first_greater_than(cls, key: bytes) -> "KeySelector":
        return cls(key, True, 1)

    @classmethod
    def first_greater_or_equal(cls, key: bytes) -> "KeySelector":
        return cls(key, False, 1)

    # offset arithmetic: fGoE(k) + 1 names the key after the one fGoE(k)
    # names, etc. — the binding idiom for paging through the keyspace
    def __add__(self, n: int) -> "KeySelector":
        return KeySelector(self.key, self.or_equal, self.offset + n)

    def __sub__(self, n: int) -> "KeySelector":
        return KeySelector(self.key, self.or_equal, self.offset - n)

    def normalized(self) -> tuple[bytes, int]:
        """(key, offset) with or_equal removed: "<= k" ≡ "< k+\\x00"."""
        if self.or_equal:
            return self.key + b"\x00", self.offset
        return self.key, self.offset

    def __repr__(self) -> str:  # readable in workload error reports
        return (
            f"KeySelector({self.key!r}, or_equal={self.or_equal}, "
            f"offset={self.offset})"
        )


def as_selector(x) -> KeySelector:
    """Coerce a bare key to the selector naming it (firstGreaterOrEqual —
    what every binding does when a key is passed where a selector is due)."""
    if isinstance(x, KeySelector):
        return x
    return KeySelector.first_greater_or_equal(x)


def resolve(keys: Iterable[bytes], sel) -> bytes:
    """Reference-exact resolution against a fully known key list (the
    model oracle's path; the real path walks shards server-side).
    ``sel`` is a KeySelector or a normalized (key, offset) pair. ``keys``
    need not be pre-filtered: system keys (>= SELECTOR_END) are dropped,
    then the list is sorted."""
    k, off = sel.normalized() if isinstance(sel, KeySelector) else sel
    ks = sorted(key for key in keys if key < SELECTOR_END)
    i = bisect.bisect_left(ks, k) - 1 + off
    if i < 0:
        return b""
    if i >= len(ks):
        return SELECTOR_END
    return ks[i]
