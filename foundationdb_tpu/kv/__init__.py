"""Key-value core data structures shared by client and server.

The analog of the reference's common types layer (fdbclient/):
- mutations.py    — mutation wire types (fdbclient/CommitTransaction.h:27-60)
- atomic.py       — atomic-op apply functions (fdbclient/Atomic.h)
- versioned_map.py— multi-version ordered map, the storage server's in-memory
                    MVCC window (fdbclient/VersionedMap.h:31-68)
- keyrange_map.py — key-range → value map (fdbclient/KeyRangeMap.h:36)
- selector.py     — key selectors, offset-relative keyspace navigation
                    (fdbclient/FDBTypes.h:462 KeySelectorRef)
"""

from .mutations import Mutation, MutationType  # noqa: F401
from .versioned_map import (  # noqa: F401
    EpochVersionedMap,
    PinnedSnapshot,
    VersionedMap,
)
from .keyrange_map import KeyRangeMap  # noqa: F401
from .selector import SELECTOR_END, KeySelector, as_selector  # noqa: F401
