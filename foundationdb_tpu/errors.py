"""Error taxonomy — the analog of flow/error_definitions.h.

Typed exceptions replace the reference's numbered error codes; the subset
here is the one that crosses the client API (retryable vs not mirrors
fdb_error_predicate in bindings/c/fdb_c.cpp).
"""

from __future__ import annotations


class FdbError(Exception):
    retryable = False


class NotCommitted(FdbError):
    """Transaction conflicted with another (error 1020)."""

    retryable = True


class TransactionTooOld(FdbError):
    """Read snapshot fell out of the MVCC window (error 1007)."""

    retryable = True


class FutureVersion(FdbError):
    """Storage server not yet caught up to read version (error 1009)."""

    retryable = True


class GrvThrottled(FdbError):
    """GRV shed by proxy admission control (the analog of error 1911
    proxy_memory_limit_exceeded / the GRV throttle): the cluster is over
    capacity for this transaction's priority class (or this tenant's
    share) and the request was rejected at admission rather than queued
    into collapse. Retryable — clients back off (bounded; see
    Transaction.on_error) and resubmit."""

    retryable = True


class CommitUnknownResult(FdbError):
    """Connection to proxy lost mid-commit; txn may or may not have
    committed (error 1021). Retryable, but retries must be idempotent."""

    retryable = True


class KeyOutsideLegalRange(FdbError):
    pass


class WrongShardServer(FdbError):
    """Read sent to a storage server that doesn't (yet) own the shard
    (error 1037 wrong_shard_server) — the client invalidates its location
    cache and retries."""

    retryable = True


class AccessedUnreadable(FdbError):
    """Read of a key written with a versionstamp op this transaction
    (error 1036)."""


class TooManyWatches(FdbError):
    """Storage server is at its STORAGE_WATCH_LIMIT (error 1032
    too_many_watches). Retryable: the client backs off and re-registers —
    parked watches fire and drain continuously, so capacity returns."""

    retryable = True


class TransactionCancelled(FdbError):
    """Operation belonged to a transaction that was cancelled or reset
    (error 1025 transaction_cancelled). NOT retryable: the watch/future
    was deliberately abandoned by its owner; retrying would resurrect
    work the application explicitly discarded."""


class DatabaseShutdown(FdbError):
    pass
