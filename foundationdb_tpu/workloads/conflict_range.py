"""ConflictRange workload: oracle-checked conflict detection.

The analog of fdbserver/workloads/ConflictRange.actor.cpp (+
MemoryKeyValueStore.h): two transactions race — A snapshots then reads
random ranges, B writes random keys and commits, then A writes and commits.
A model predicts exactly whether A must conflict (B's committed writes
intersect A's reads). Both false conflicts and missed conflicts fail.
"""

from __future__ import annotations

from ..errors import NotCommitted
from . import Workload


class ConflictRangeWorkload(Workload):
    def __init__(self, db, rng, rounds=30, keyspace=40, prefix=b"cr/", **kw):
        super().__init__(db, rng, **kw)
        self.rounds = rounds
        self.keys = [prefix + b"%03d" % i for i in range(keyspace)]
        self.prefix = prefix
        self.stats = {"conflict": 0, "clean": 0}

    def _rand_range(self):
        i = self.rng.random_int(0, len(self.keys))
        j = self.rng.random_int(0, len(self.keys))
        i, j = min(i, j), max(i, j)
        return self.keys[i], self.keys[j]

    async def start(self):
        for rnd in range(self.rounds):
            # A starts and reads ranges
            a = self.db.transaction()
            a_reads = []
            for _ in range(self.rng.random_int(1, 4)):
                begin, end = self._rand_range()
                await a.get_range(begin, end)
                a_reads.append((begin, end))

            # B writes keys and commits
            b = self.db.transaction()
            b_writes = []
            for _ in range(self.rng.random_int(1, 4)):
                k = self.rng.random_choice(self.keys)
                b.set(k, b"b%d" % rnd)
                b_writes.append(k)
            await b.commit()

            # A writes something and tries to commit
            a.set(self.prefix + b"result", b"a%d" % rnd)
            must_conflict = any(
                begin <= k < end for k in b_writes for begin, end in a_reads
            )
            try:
                await a.commit()
                conflicted = False
            except NotCommitted:
                conflicted = True
            assert conflicted == must_conflict, (
                f"round {rnd}: predicted conflict={must_conflict}, "
                f"got {conflicted} (reads={a_reads}, writes={b_writes})"
            )
            self.stats["conflict" if conflicted else "clean"] += 1

    async def check(self) -> bool:
        # both outcomes must actually occur over the run, or the test
        # proved nothing (reference asserts the same via its metrics)
        return self.stats["conflict"] > 0 and self.stats["clean"] > 0
