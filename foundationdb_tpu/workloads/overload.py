"""Overload burst workload: drive admission control past capacity.

The chaos-soak arm for ISSUE 13 (Ratekeeper-grade admission control): a
short burst of greedy batch/default-class traffic from a handful of
tenants, offered well above whatever the Ratekeeper is granting, runs
CONCURRENTLY with the soak's correctness workloads. Self-checking:

- the burst makes progress (shed-don't-collapse: bounded-backoff retries
  must keep landing commits at the granted rate — zero goodput under its
  own overload is the collapse this PR removes);
- an immediate-class canary transaction issued DURING the burst
  completes (batch flood cannot starve the immediate class);
- any grv_throttled errors observed are the typed retryable shed path,
  never a hang.
"""

from __future__ import annotations

from ..errors import FdbError
from ..net.sim import BrokenPromise
from ..runtime.futures import spawn, wait_for_all
from ..runtime.loop import Cancelled, now
from . import Workload


class OverloadBurstWorkload(Workload):
    def __init__(
        self,
        db,
        rng,
        actors: int = 6,
        txns: int = 8,
        duration: float = 4.0,
        tenants: int = 3,
        prefix: bytes = b"overload/",
        **kw,
    ):
        super().__init__(db, rng, **kw)
        self.actors = actors
        self.txns = txns
        self.duration = duration
        self.tenants = max(tenants, 1)
        self.prefix = prefix
        self.commits = 0
        self.sheds = 0
        self.canary_done = False

    async def start(self):
        t_end = now() + self.duration

        async def flood(i: int):
            # tenant skew: tenant-0 is the hot tenant (double the actors
            # land on it), exercising the per-tenant fair-share buckets
            tenant = f"tenant-{(i // 2) % self.tenants if i % 2 else 0}"
            priority = "batch" if i % 2 else "default"
            rnd = self.rng.fork()
            done = 0
            while done < self.txns and now() < t_end:
                async def body(tr, i=i, done=done):
                    tr.set_priority(priority)
                    tr.set_tenant(tenant)
                    tr.set(
                        self.prefix + b"%d/%d/%d" % (self.client_id, i, done),
                        b"x",
                    )

                try:
                    # bounded attempts: a batch-class txn under full shed
                    # must abandon and count, not anchor the workload past
                    # the burst window
                    await self.db.run(body, max_retries=5)
                except Cancelled:
                    raise  # actor-cancelled-swallow
                except (FdbError, BrokenPromise):
                    self.sheds += 1
                else:
                    self.commits += 1
                done += 1
            return True

        async def canary():
            # immediate-class traffic DURING the burst: admission drains
            # immediate first, so this must complete however hard the
            # batch flood is shedding
            async def body(tr):
                tr.set_priority("immediate")
                tr.set(self.prefix + b"canary/%d" % self.client_id, b"ok")

            await self.db.run(body)
            self.canary_done = True
            return True

        await wait_for_all(
            [spawn(flood(i)) for i in range(self.actors)] + [spawn(canary())]
        )

    async def check(self) -> bool:
        # progress, not perfection: sheds are expected and healthy; zero
        # commits from the default-class half would mean collapse
        assert self.canary_done, "immediate-class canary starved by the burst"
        assert self.commits > 0, (
            f"overload burst made no progress (sheds={self.sheds})"
        )
        return True
