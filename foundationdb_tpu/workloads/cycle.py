"""Cycle workload: transactional pointer-chasing over a ring.

The analog of fdbserver/workloads/Cycle.actor.cpp: keys 0..n-1 hold "next"
pointers forming one cycle. Each transaction splices a node to a new position
(3 reads, 3 writes). Serializability means the permutation stays a single
n-cycle no matter how many transactions race; a lost update or phantom read
breaks it. The final check walks the ring in one snapshot.
"""

from __future__ import annotations

from . import Workload


def _key(prefix: bytes, i: int) -> bytes:
    return prefix + b"%06d" % i


class CycleWorkload(Workload):
    def __init__(self, db, rng, nodes=20, transactions=50, prefix=b"cycle/", **kw):
        super().__init__(db, rng, **kw)
        self.nodes = nodes
        self.transactions = transactions
        self.prefix = prefix
        self.retries = 0

    async def setup(self):
        if self.client_id != 0:
            return

        async def init(tr):
            for i in range(self.nodes):
                tr.set(_key(self.prefix, i), b"%06d" % ((i + 1) % self.nodes))

        await self.db.run(init)

    async def start(self):
        for _ in range(self.transactions):
            a = self.rng.random_int(0, self.nodes)

            async def splice(tr, a=a):
                ka = _key(self.prefix, a)
                b = int(await tr.get(ka))
                if b == a:
                    return  # degenerate (n=1 ring segment), nothing to do
                kb = _key(self.prefix, b)
                c = int(await tr.get(kb))
                if c in (a, b):
                    return
                kc = _key(self.prefix, c)
                d = int(await tr.get(kc))
                # splice b out of a→b→c→d and back in after c: a→c→b→d
                tr.set(ka, b"%06d" % c)
                tr.set(kc, b"%06d" % b)
                tr.set(kb, b"%06d" % d)

            tries = 0

            async def counted(tr):
                nonlocal tries
                tries += 1
                await splice(tr)

            await self.db.run(counted)
            self.retries += tries - 1

    async def check(self) -> bool:
        if self.client_id != 0:
            return True
        tr = self.db.transaction()
        rows = await tr.get_range(self.prefix, self.prefix + b"\xff")
        if len(rows) != self.nodes:
            return False
        nxt = {int(k[len(self.prefix):]): int(v) for k, v in rows}
        seen, i = set(), 0
        for _ in range(self.nodes):
            if i in seen:
                return False
            seen.add(i)
            i = nxt[i]
        return i == 0 and len(seen) == self.nodes
