"""Sideband workload: external (causal) consistency.

The analog of fdbserver/workloads/Sideband.actor.cpp: a mutator commits a
key, then tells a checker out-of-band. The checker's subsequently-started
transaction MUST see the key — if its GRV could lag the reported commit
version, causality is broken (the getLiveCommittedVersion guarantee the
proxy/master pair provides).
"""

from __future__ import annotations

from ..errors import CommitUnknownResult, FdbError
from ..runtime.futures import PromiseStream, StreamClosed
from . import Workload


class SidebandWorkload(Workload):
    def __init__(
        self, db, rng, messages=25, prefix=b"sideband/", checker_db=None, **kw
    ):
        super().__init__(db, rng, **kw)
        self.messages = messages
        self.prefix = prefix
        # the checker reads through its own client (and so its own proxy
        # choices) — causality must hold *across* clients, not just within
        # one client's GRV stream
        self.checker_db = checker_db or db
        self.stream: PromiseStream = PromiseStream()
        self.checked = 0

    async def _mutator(self):
        for i in range(self.messages):
            key = self.prefix + b"%04d" % i
            while True:
                tr = self.db.transaction()
                tr.set(key, b"sent")
                try:
                    version = await tr.commit()
                    break
                except CommitUnknownResult:
                    # did it land? A read that sees the key gives a read
                    # version ≥ the commit version — a valid (stronger)
                    # causality bound to report to the checker
                    async def probe(t):
                        return await t.get(key), await t.get_read_version()

                    got, rv = await self.db.run(probe)
                    if got == b"sent":
                        version = rv
                        break
                except FdbError as e:
                    await tr.on_error(e)
            self.stream.send((i, version))
        self.stream.close()

    async def _checker(self):
        while True:
            try:
                i, version = await self.stream.next()
            except StreamClosed:
                return
            tr = self.checker_db.transaction()
            got = await tr.get(self.prefix + b"%04d" % i)
            assert got == b"sent", (
                f"causality violation: message {i} committed at {version} "
                f"but invisible at read version {tr._read_version}"
            )
            assert tr._read_version >= version
            self.checked += 1

    async def start(self):
        from ..runtime.futures import spawn, wait_for_all

        await wait_for_all([spawn(self._mutator()), spawn(self._checker())])

    async def check(self) -> bool:
        return self.checked == self.messages
