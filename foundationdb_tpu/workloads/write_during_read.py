"""WriteDuringRead workload: RYW-semantics fuzz against a model.

The analog of fdbserver/workloads/WriteDuringRead.actor.cpp: one transaction
performs a random interleaving of reads and writes (sets, clears, range
clears, atomic ops, gets, range reads); every read is compared against an
in-memory model applying the same operations. After commit, the database
state must equal the model; after an abandoned transaction, it must not
change.
"""

from __future__ import annotations

from ..kv.atomic import apply_atomic
from ..kv.mutations import ATOMIC_OPS, MutationType
from . import Workload

OPS = list(ATOMIC_OPS - {MutationType.COMPARE_AND_CLEAR})


class WriteDuringReadWorkload(Workload):
    def __init__(self, db, rng, rounds=10, ops_per_round=30, keyspace=12,
                 prefix=b"wdr/", **kw):
        super().__init__(db, rng, **kw)
        self.rounds = rounds
        self.ops = ops_per_round
        self.keys = [prefix + b"%02d" % i for i in range(keyspace)]
        self.prefix = prefix
        self.model: dict[bytes, bytes] = {}

    def _rand_key(self) -> bytes:
        return self.rng.random_choice(self.keys)

    def _rand_range(self):
        a, b = self._rand_key(), self._rand_key()
        return (a, b) if a <= b else (b, a)

    async def _one_op(self, tr) -> None:
        r = self.rng.random01()
        if r < 0.25:
            k = self._rand_key()
            got = await tr.get(k)
            assert got == self.model.get(k), (k, got, self.model.get(k))
        elif r < 0.4:
            a, b = self._rand_range()
            got = await tr.get_range(a, b)
            want = sorted((k, v) for k, v in self.model.items() if a <= k < b)
            assert got == want, (a, b, got, want)
        elif r < 0.6:
            k, v = self._rand_key(), b"v%04d" % self.rng.random_int(0, 10000)
            tr.set(k, v)
            self.model[k] = v
        elif r < 0.7:
            a, b = self._rand_range()
            tr.clear_range(a, b)
            for k in [k for k in self.model if a <= k < b]:
                del self.model[k]
        elif r < 0.8:
            k = self._rand_key()
            tr.clear(k)
            self.model.pop(k, None)
        else:
            op = self.rng.random_choice(OPS)
            k = self._rand_key()
            param = bytes([self.rng.random_int(0, 256) for _ in range(2)])
            new = apply_atomic(op, self.model.get(k), param)
            tr.atomic_op(op, k, param)
            if new is None:
                self.model.pop(k, None)
            else:
                self.model[k] = new

    async def start(self):
        for rnd in range(self.rounds):
            committed_model = dict(self.model)
            tr = self.db.transaction()
            for _ in range(self.ops):
                await self._one_op(tr)
            if self.rng.coinflip(0.8):
                await tr.commit()
            else:
                self.model = committed_model  # abandoned txn changes nothing

    async def check(self) -> bool:
        tr = self.db.transaction()
        rows = await tr.get_range(self.prefix, self.prefix + b"\xff")
        return rows == sorted(self.model.items())
