"""Throughput workloads: ReadWrite (YCSB-style), BulkLoad, Throughput.

The analogs of fdbserver/workloads/ReadWrite.actor.cpp:1 (randomized
read/write mixes with latency sampling), BulkLoad.actor.cpp:1 (max-rate
sequential ingest) and Throughput.actor.cpp:1 (sustained mixed load with
steady-state measurement). These are the workloads behind the reference's
published numbers (documentation/sphinx/source/benchmarking.rst:53-97:
46K writes/s, 305K reads/s @ 0.6 ms, 107K 90/10 ops/s, one core) — the
repo's previous batteries checked correctness only; these measure.

Each workload runs unchanged against the simulated cluster (wall-clock =
cost of the Python+JAX pipeline; latencies in *sim* time = protocol cost)
and against a real TCP cluster (both wall) via tools/perf.py.
"""

from __future__ import annotations

import time

from ..runtime.futures import spawn, wait_for_all
from . import Workload
from ..runtime.loop import Cancelled


def _pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(len(sorted_vals) * p))]


class _Recorder:
    """Shared op/latency accounting across a workload's client actors."""

    def __init__(self, now_fn):
        self.now = now_fn  # model-time clock for latency samples
        self.reads = 0
        self.writes = 0
        self.commits = 0
        self.conflicts = 0
        self.read_lat: list[float] = []
        self.commit_lat: list[float] = []
        self.t0_wall = None
        self.t1_wall = None

    def start_clock(self):
        if self.t0_wall is None:
            self.t0_wall = time.perf_counter()

    def stop_clock(self):
        self.t1_wall = time.perf_counter()

    @property
    def wall(self) -> float:
        return (self.t1_wall or time.perf_counter()) - self.t0_wall

    def report(self) -> dict:
        ops = self.reads + self.writes
        rl = sorted(self.read_lat)
        cl = sorted(self.commit_lat)
        wall = max(self.wall, 1e-9)
        return {
            "ops": ops,
            "reads": self.reads,
            "writes": self.writes,
            "commits": self.commits,
            "conflicts": self.conflicts,
            "wall_s": round(wall, 3),
            "ops_per_s": round(ops / wall, 1),
            "reads_per_s": round(self.reads / wall, 1),
            "writes_per_s": round(self.writes / wall, 1),
            "txn_per_s": round(self.commits / wall, 1),
            "read_p50_ms": round(_pct(rl, 0.50) * 1000, 3),
            "read_p95_ms": round(_pct(rl, 0.95) * 1000, 3),
            "commit_p50_ms": round(_pct(cl, 0.50) * 1000, 3),
            "commit_p95_ms": round(_pct(cl, 0.95) * 1000, 3),
        }


class ReadWriteWorkload(Workload):
    """N concurrent client actors, each running transactions composed of
    ``reads_per_txn`` random gets + ``writes_per_txn`` random sets over a
    pre-populated uniform keyspace (ReadWrite.actor.cpp's
    actorCount/readsPerTransactionA shape). 90/10 = (9, 1); 50/50 = (5, 5);
    write-only = (0, 10) reproduces benchmarking.rst:53's concurrent
    writes; read-only = (10, 0) reproduces :67's concurrent reads."""

    def __init__(
        self,
        db,
        rng,
        actors=20,
        txns_per_actor=50,
        reads_per_txn=9,
        writes_per_txn=1,
        keyspace=10_000,
        value_len=16,
        prefix=b"rw/",
        now_fn=None,
        parallel_reads=False,
        priority=None,
        tenant=None,
        **kw,
    ):
        super().__init__(db, rng, **kw)
        self.actors = actors
        self.txns_per_actor = txns_per_actor
        self.reads_per_txn = reads_per_txn
        self.writes_per_txn = writes_per_txn
        self.keyspace = keyspace
        self.value_len = value_len
        self.prefix = prefix
        # issue each transaction's reads concurrently (the reference's
        # clients pipeline their gets; with the read coalescer this is
        # what collapses a txn's N gets into one multiGet hop)
        self.parallel_reads = parallel_reads
        # admission options (ISSUE 13): the overload drivers run this
        # shape per priority class / tenant; None = database defaults
        self.priority = priority
        self.tenant = tenant
        if now_fn is None:
            from ..runtime.loop import now as now_fn
        self.rec = _Recorder(now_fn)

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%08d" % i

    def _value(self) -> bytes:
        return b"v" * self.value_len

    async def setup(self):
        if self.client_id != 0:
            return
        # populate in chunks (one giant txn would blow batch limits)
        for lo in range(0, self.keyspace, 2000):
            hi = min(lo + 2000, self.keyspace)

            async def fill(tr, lo=lo, hi=hi):
                for i in range(lo, hi):
                    tr.set(self._key(i), self._value())

            await self.db.run(fill)

    async def _one_txn(self, rnd):
        rec = self.rec
        for attempt in range(20):
            tr = self.db.transaction(
                priority=self.priority, tenant=self.tenant
            )
            try:
                if self.parallel_reads and self.reads_per_txn > 1:
                    keys = [
                        self._key(rnd.random_int(0, self.keyspace))
                        for _ in range(self.reads_per_txn)
                    ]
                    t0 = rec.now()
                    futs = [spawn(tr.get(k)) for k in keys]
                    try:
                        await wait_for_all(futs)
                    except Cancelled:
                        raise  # actor-cancelled-swallow
                    except BaseException:
                        for f in futs:
                            f.cancel()
                        raise
                    dt = rec.now() - t0
                    rec.read_lat.extend([dt] * len(keys))
                else:
                    for _ in range(self.reads_per_txn):
                        k = self._key(rnd.random_int(0, self.keyspace))
                        t0 = rec.now()
                        await tr.get(k)
                        rec.read_lat.append(rec.now() - t0)
                for _ in range(self.writes_per_txn):
                    k = self._key(rnd.random_int(0, self.keyspace))
                    tr.set(k, self._value())
                if self.writes_per_txn or self.reads_per_txn:
                    t0 = rec.now()
                    await tr.commit()
                    if self.writes_per_txn:
                        rec.commit_lat.append(rec.now() - t0)
                rec.reads += self.reads_per_txn
                rec.writes += self.writes_per_txn
                rec.commits += 1
                return
            except Cancelled:
                raise  # actor-cancelled-swallow
            except Exception as e:
                rec.conflicts += 1
                await tr.on_error(e)

    async def start(self):
        self.rec.start_clock()

        async def client(cid):
            rnd = self.rng.fork()
            for _ in range(self.txns_per_actor):
                await self._one_txn(rnd)
            return True

        await wait_for_all(
            [spawn(client(c)) for c in range(self.actors)]
        )
        self.rec.stop_clock()

    async def check(self) -> bool:
        return self.rec.commits > 0


class BulkLoadWorkload(Workload):
    """Max-rate sequential ingest (BulkLoad.actor.cpp:1): W writer actors
    each append batches of ``keys_per_txn`` contiguous keys in disjoint
    ranges; metric = keys ingested per second."""

    def __init__(
        self,
        db,
        rng,
        actors=8,
        txns_per_actor=40,
        keys_per_txn=50,
        value_len=16,
        prefix=b"bulk/",
        now_fn=None,
        **kw,
    ):
        super().__init__(db, rng, **kw)
        self.actors = actors
        self.txns_per_actor = txns_per_actor
        self.keys_per_txn = keys_per_txn
        self.value_len = value_len
        self.prefix = prefix
        if now_fn is None:
            from ..runtime.loop import now as now_fn
        self.rec = _Recorder(now_fn)

    async def start(self):
        self.rec.start_clock()
        val = b"b" * self.value_len

        async def writer(w):
            rec = self.rec
            # globally unique writer index: concurrent client PROCESSES
            # (tools/perf.py --client-procs) must ingest disjoint ranges,
            # or the aggregate keys/s double-counts rewrites of the same
            # keys
            gw = self.client_id * self.actors + w
            for t in range(self.txns_per_actor):
                base = (gw * self.txns_per_actor + t) * self.keys_per_txn

                async def body(tr, base=base):
                    for i in range(self.keys_per_txn):
                        tr.set(self.prefix + b"%012d" % (base + i), val)

                t0 = rec.now()
                await self.db.run(body)
                rec.commit_lat.append(rec.now() - t0)
                rec.writes += self.keys_per_txn
                rec.commits += 1
            return True

        await wait_for_all([spawn(writer(w)) for w in range(self.actors)])
        self.rec.stop_clock()

    async def check(self) -> bool:
        # spot-verify the tail of THIS client's last writer range arrived
        tr = self.db.transaction()
        last = (
            ((self.client_id + 1) * self.actors * self.txns_per_actor)
            * self.keys_per_txn
            - 1
        )
        return (await tr.get(self.prefix + b"%012d" % last)) is not None


class ThroughputWorkload(ReadWriteWorkload):
    """Duration-based steady state (Throughput.actor.cpp:1): run the mixed
    transaction shape for ``duration`` seconds of model time (sim) or wall
    time (TCP) after a ramp-up, and report only the steady-state window —
    start-up transients don't pollute the measured rate."""

    def __init__(self, db, rng, duration=5.0, ramp=0.5, **kw):
        kw.setdefault("txns_per_actor", 10**9)  # bounded by time, not count
        super().__init__(db, rng, **kw)
        self.duration = duration
        self.ramp = ramp

    async def start(self):
        rec = self.rec
        t_end = rec.now() + self.ramp + self.duration
        ramp_until = rec.now() + self.ramp
        started = [False]

        async def client(cid):
            rnd = self.rng.fork()
            while rec.now() < t_end:
                if not started[0] and rec.now() >= ramp_until:
                    started[0] = True
                    # reset counters at steady state; wall clock restarts
                    rec.reads = rec.writes = rec.commits = rec.conflicts = 0
                    rec.read_lat.clear()
                    rec.commit_lat.clear()
                    rec.t0_wall = time.perf_counter()
                await self._one_txn(rnd)
            return True

        rec.start_clock()
        await wait_for_all(
            [spawn(client(c)) for c in range(self.actors)]
        )
        rec.stop_clock()
