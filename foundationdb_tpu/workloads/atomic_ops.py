"""AtomicOps workload: concurrent atomic RMWs must sum exactly.

The analog of fdbserver/workloads/AtomicOps.actor.cpp: N clients blind-
ADD random deltas to shared counters and log every committed delta under
a versionstamped key; the check asserts each counter equals the sum of
its logged deltas — a lost, double-applied, or reordered atomic breaks
the equality. Exercises the full pipeline's atomic handling: RYW
coalescing, proxy pass-through, storage apply, engine replay after
reboots."""

from __future__ import annotations

import struct

from . import Workload
from ..errors import CommitUnknownResult, FdbError
from ..kv.mutations import MutationType


class AtomicOpsWorkload(Workload):
    COUNTERS = b"atomic/ctr/"
    LOG = b"atomic/log/"

    def __init__(self, db, rng, transactions=25, counters=4, **kw):
        super().__init__(db, rng, **kw)
        self.transactions = transactions
        self.counters = counters
        self._seq = 0

    async def _one(self):
        ctr = self.COUNTERS + b"%02d" % self.rng.random_int(0, self.counters)
        delta = self.rng.random_int(-50, 51)
        while True:
            self._seq += 1
            marker = self.LOG + b"%d/%08d" % (self.client_id, self._seq)
            tr = self.db.transaction()
            tr.atomic_op(
                MutationType.ADD, ctr, struct.pack("<q", delta)
            )
            # the delta log rides the same txn: committed iff the ADD is
            tr.set(marker, ctr + b"|" + struct.pack("<q", delta))
            try:
                await tr.commit()
                return
            except CommitUnknownResult:
                async def probe(t, marker=marker):
                    return await t.get(marker)

                if await self.db.run(probe) is not None:
                    return  # landed; retrying would double-count
            except FdbError as e:
                await tr.on_error(e)

    async def start(self):
        for _ in range(self.transactions):
            await self._one()

    async def check(self) -> bool:
        if self.client_id != 0:
            return True

        async def read(tr):
            ctrs = await tr.get_range(self.COUNTERS, self.COUNTERS + b"\xff")
            logs = await tr.get_range(self.LOG, self.LOG + b"\xff")
            return ctrs, logs

        ctrs, logs = await self.db.run(read)
        want: dict[bytes, int] = {}
        for _k, v in logs:
            ctr, raw = v.rsplit(b"|", 1)
            want[ctr] = want.get(ctr, 0) + struct.unpack("<q", raw)[0]
        got = {
            k: struct.unpack("<q", v.ljust(8, b"\x00")[:8])[0]
            for k, v in ctrs
        }
        for ctr, total in want.items():
            if got.get(ctr, 0) != total:
                print(
                    f"AtomicOps: {ctr} = {got.get(ctr, 0)}, "
                    f"logged deltas sum to {total}"
                )
                return False
        # counters with no logged delta must not exist
        for k in got:
            if k not in want:
                print(f"AtomicOps: spurious counter {k}")
                return False
        return True
