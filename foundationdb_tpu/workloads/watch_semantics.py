"""Watch/feed semantics workloads — the ISSUE 16 oracles.

``WatchSemanticsWorkload`` is the exactness oracle for the notification
subsystem: each actor owns a DISJOINT key partition and mutates it
strictly sequentially, so the actor itself is a perfect model of its
partition — any watch that fires with a value the actor never wrote is a
phantom trigger, any committed change whose watch never fires is a lost
trigger, and replaying the partition's change feed must reproduce the
partition byte-for-byte against a transactional range read. (Disjoint
partitions + sequential ops make the within-version canonical order
unambiguous; overlapping writers would leave the byte-match oracle
underdetermined.) Runs under the full chaos soak: restart, rollback and
failover seeds must keep all three properties.

``WatchStormWorkload`` is the fan-out shape: many watches parked on few
keys, released by single commits — the storage fires whole versions as
one fan-out batch, and with frame batching the replies to one client
share super-frames. The 100K-storm acceptance run drives this class
directly (tools/soak.py ``watch_storm``)."""

from __future__ import annotations

from . import Workload
from ..runtime.futures import delay, spawn, timeout, wait_for_all


class WatchSemanticsWorkload(Workload):
    def __init__(
        self,
        db,
        rng,
        actors: int = 3,
        changes: int = 8,
        keys_per_actor: int = 4,
        feed_check: bool = True,
        **kw,
    ):
        super().__init__(db, rng, **kw)
        self.actors = actors
        self.changes = changes
        self.keys_per_actor = keys_per_actor
        self.feed_check = feed_check
        self.lost = 0
        self.phantom = 0
        self.fired = 0
        self.feed_mismatches: list = []

    def _prefix(self, actor: int) -> bytes:
        return b"wsem/%d/%d/" % (self.client_id, actor)

    def _key(self, actor: int, j: int) -> bytes:
        return self._prefix(actor) + b"k%02d" % j

    async def _actor(self, i: int) -> None:
        rng = self.rng.fork()
        # every value this actor ever ATTEMPTED to commit, per key: the
        # sole-writer discipline makes this a superset of the committed
        # values (unknown-result retries re-commit the same value), so a
        # fired value outside it is a phantom by construction
        legal: dict = {}
        for seq in range(self.changes):
            key = self._key(i, seq % self.keys_per_actor)
            # register the watch with the CURRENT value as baseline
            watch_fut = [None]

            async def register(tr):
                cur = await tr.get(key)
                watch_fut[0] = tr.watch(key)
                return cur

            baseline = await self.db.run(register)
            # commit a change guaranteed to differ from the baseline
            if rng.coinflip(0.25) and baseline is not None:
                newv = None  # clear

                async def change(tr):
                    tr.clear(key)
            else:
                newv = b"%s#%06d" % (key, seq)
                if newv == baseline:  # same seq re-landed: perturb
                    newv += b"'"

                async def change(tr):
                    tr.set(key, newv)

            legal.setdefault(key, {baseline}).add(newv)
            await self.db.run(change)
            # the committed change MUST fire the watch (generous bound:
            # chaos recoveries re-register client-side, but never lose it)
            sentinel = object()
            fired = await timeout(watch_fut[0], 60.0, default=sentinel)
            if fired is sentinel:
                self.lost += 1
                continue
            self.fired += 1
            # spurious fires re-report a legal value; a value this actor
            # never wrote is phantom data
            if fired not in legal[key]:
                self.phantom += 1
            await delay(rng.random01() * 0.05)

    async def _check_feed(self, actor: int) -> None:
        """Replay the partition's change feed from version 0 and compare
        against a transactional range read — byte-for-byte."""
        from ..errors import TransactionTooOld

        begin = self._prefix(actor)
        end = begin + b"\xff"
        feed = self.db.change_feed(begin, end, from_version=0)
        replayed: dict = {}
        last_version = 0
        try:
            while True:
                batches = await timeout(
                    spawn(feed.next_batches()), 5.0, default=None
                )
                if batches is None:
                    break  # caught up: long-poll outlived the quiesce
                for b in batches:
                    if b.version <= last_version:
                        self.feed_mismatches.append(
                            f"feed versions not increasing: {b.version} "
                            f"after {last_version}"
                        )
                    last_version = b.version
                    for cb, ce in b.clears:
                        for k in [k for k in replayed if cb <= k < ce]:
                            del replayed[k]
                    for k, v in b.sets:
                        replayed[k] = v
        except TransactionTooOld:
            # retention floor passed version 0 (legal on long chaos runs):
            # the byte-match oracle needs the full log — skip, don't fail
            return
        async def read(tr):
            return await tr.get_range(begin, end)

        actual = {k: v for k, v in await self.db.run(read)}
        if replayed != actual:
            self.feed_mismatches.append(
                f"actor {actor}: replay {sorted(replayed.items())!r} != "
                f"range read {sorted(actual.items())!r}"
            )

    async def start(self):
        await wait_for_all(
            [spawn(self._actor(i)) for i in range(self.actors)]
        )

    async def check(self) -> bool:
        if self.feed_check:
            for i in range(self.actors):
                await self._check_feed(i)
        ok = True
        if self.lost:
            print(f"WatchSemantics: {self.lost} LOST triggers")
            ok = False
        if self.phantom:
            print(f"WatchSemantics: {self.phantom} PHANTOM triggers")
            ok = False
        for m in self.feed_mismatches:
            print(f"WatchSemantics: feed mismatch — {m}")
            ok = False
        if self.fired < 1:
            print("WatchSemantics: nothing ever fired")
            ok = False
        return ok


class WatchStormWorkload(Workload):
    """Park ``watchers`` watches across ``keys`` keys from one client,
    release each key with a single commit, and require every watch to
    fire with the released value — the whole-version fan-out path."""

    def __init__(self, db, rng, watchers: int = 64, keys: int = 8, **kw):
        super().__init__(db, rng, **kw)
        self.watchers = watchers
        self.keys = keys
        self.unfired = -1
        self.wrong: list = []

    def _key(self, j: int) -> bytes:
        return b"wstorm/%d/k%04d" % (self.client_id, j % self.keys)

    async def start(self):
        async def park(tr):
            # baseline: absent (fresh namespace) — one registration RPC
            # per watcher, all parked until the release commit
            return [tr.watch(self._key(j)) for j in range(self.watchers)]

        futs = await self.db.run(park)

        async def release(tr):
            for j in range(self.keys):
                tr.set(self._key(j), b"released")

        await self.db.run(release)
        sentinel = object()
        self.unfired = 0
        # ONE shared deadline for the whole fan-out, not 60s per future:
        # the futures resolve concurrently, so waiting is O(slowest), and
        # a mass-loss pathology fails the check instead of outliving the
        # soak battery's sim-time budget
        from ..runtime.loop import now

        deadline = now() + 60.0
        for j, f in enumerate(futs):
            v = await timeout(f, max(0.1, deadline - now()), default=sentinel)
            if v is sentinel:
                self.unfired += 1
            elif v != b"released":
                self.wrong.append((self._key(j), v))

    async def check(self) -> bool:
        if self.unfired:
            print(f"WatchStorm: {self.unfired}/{self.watchers} never fired")
            return False
        if self.wrong:
            print(f"WatchStorm: wrong fire values {self.wrong[:5]!r}")
            return False
        return True
