"""KernelChaos — contended read-modify-write traffic that must stay
exactly correct while the conflict kernel faults, fails over to the native
backend, and re-promotes (conflict/faults.py + conflict/failover.py).

The oracle here is a client-side ledger: several actors increment shared
counter keys through an idempotent retry loop; every increment that is
KNOWN to have committed is tallied. At check time each counter must equal
its tally exactly:

- a **false commit** during failover / journal replay (two increments
  admitted over the same snapshot) loses an update and breaks the
  equality;
- **conservative extra aborts** (the allowed degradation mode) only cost
  retries, never correctness.

``commit_unknown_result`` (a proxy erroring a batch whose resolver faulted
mid-flight) is disambiguated with a per-attempt marker key written in the
same transaction — the standard idempotent-retry pattern — so the ledger
stays exact under chaos.

The check phase also asserts commit AVAILABILITY recovered: a final probe
transaction must commit, i.e. an injected device loss bends into bounded
stalls and failover, never the old permanent ``resolver backend failed``.
"""

from __future__ import annotations

from ..errors import CommitUnknownResult, FdbError
from ..runtime.futures import delay, spawn, wait_for_all
from ..runtime.loop import Cancelled
from . import Workload


class KernelChaosWorkload(Workload):
    PREFIX = b"kchaos/"

    def __init__(self, db, rng, keys=4, actors=3, increments=8, **kw):
        super().__init__(db, rng, **kw)
        self.keys = keys
        self.actors = actors
        self.increments = increments
        self.tally: dict[bytes, int] = {}
        self.unknown_results = 0
        self.aborts = 0

    def _key(self, i: int) -> bytes:
        return self.PREFIX + b"k%02d" % i

    async def setup(self) -> None:
        if self.client_id != 0:
            return

        async def init(tr):
            for i in range(self.keys):
                tr.set(self._key(i), b"0")

        await self.db.run(init)

    async def _marker_committed(self, marker: bytes) -> bool:
        async def read(tr):
            return await tr.get(marker)

        return await self.db.run(read) is not None

    async def _increment(self, key: bytes, marker: bytes) -> None:
        """One exactly-once increment: retried until it is KNOWN committed
        (marker present), bounded so a wedged cluster fails the workload
        instead of spinning it."""
        for _attempt in range(60):
            tr = self.db.transaction()
            try:
                v = int(await tr.get(key))
                tr.set(key, b"%d" % (v + 1))
                tr.set(marker, b"1")
                await tr.commit()
                self.tally[key] = self.tally.get(key, 0) + 1
                return
            except Cancelled:
                raise
            except CommitUnknownResult:
                # may or may not have applied: the marker decides, so an
                # unknown result can never double-count the ledger
                self.unknown_results += 1
                await delay(0.05)
                if await self._marker_committed(marker):
                    self.tally[key] = self.tally.get(key, 0) + 1
                    return
            except FdbError as e:
                self.aborts += 1
                await tr.on_error(e)  # re-raises if not retryable
        raise AssertionError(f"increment of {key!r} never committed")

    async def start(self) -> None:
        async def actor(aid: int, rng) -> None:
            for seq in range(self.increments):
                key = self._key(rng.random_int(0, self.keys - 1))
                marker = self.PREFIX + b"m/%03d/%03d/%03d" % (
                    self.client_id,
                    aid,
                    seq,
                )
                await self._increment(key, marker)

        await wait_for_all(
            [
                spawn(actor(a, self.rng.fork()))
                for a in range(self.actors)
            ]
        )

    async def check(self) -> bool:
        async def read_all(tr):
            return [await tr.get(self._key(i)) for i in range(self.keys)]

        vals = await self.db.run(read_all)
        for i, raw in enumerate(vals):
            want = self.tally.get(self._key(i), 0)
            got = int(raw) if raw is not None else 0
            assert got == want, (
                f"counter {self._key(i)!r}: value {got} != {want} known "
                f"commits — a false commit slipped through the kernel "
                f"failover path"
            )

        # availability recovered: one more commit must go through
        async def probe(tr):
            tr.set(self.PREFIX + b"probe", b"ok")

        await self.db.run(probe)
        return True
