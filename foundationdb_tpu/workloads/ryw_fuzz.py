"""RYW fuzz: random op sequences INSIDE one transaction, every read checked
against a transaction-local model mid-flight.

The analog of fdbserver/workloads/FuzzApiCorrectness.actor.cpp +
WriteDuringRead's RYW checking: the adversary for the read-your-writes
overlay (client/transaction.py) — write/clear/atomic-chain interleaved
with point reads, snapshot reads, and forward/reverse range reads with
limits, where every read must see (committed state + this txn's writes so
far). Also exercises the unreadable-range corner: reading a pending
versionstamped key must raise AccessedUnreadable, and reads elsewhere in
the transaction still work.

Commits are applied to the committed model via the same marker
disambiguation as ApiCorrectness; some transactions are abandoned
(reset) to check nothing leaks.
"""

from __future__ import annotations

import struct

from . import Workload
from ..errors import (
    AccessedUnreadable,
    CommitUnknownResult,
    NotCommitted,
    TransactionTooOld,
)
from ..kv.mutations import MutationType
from ._model import ModelStore
from .api_correctness import _ATOMICS


class RywFuzzWorkload(Workload):
    def __init__(
        self, db, rng, transactions=25, keys=24, ops_per_txn=10, **kw
    ):
        super().__init__(db, rng, **kw)
        self.transactions = transactions
        self.keys = keys
        self.ops_per_txn = ops_per_txn
        self.prefix = b"rywfuzz/c%d/" % self.client_id
        self.model = ModelStore()
        self._attempt = 0
        self.errors: list[str] = []

    def _key(self, i=None) -> bytes:
        if i is None:
            i = self.rng.random_int(0, self.keys)
        return self.prefix + b"k%04d" % i

    async def _fuzz_one(self) -> None:
        while True:
            self._attempt += 1
            attempt = self._attempt
            tr = self.db.transaction()
            local = self.model.copy()
            unreadable: set[bytes] = set()
            ok = await self._run_ops(tr, local, unreadable)
            if not ok:
                return  # a model mismatch was recorded; stop this txn
            if self.rng.coinflip(0.25):
                return  # abandoned transaction: must leave no trace
            marker = self.prefix + b"marker/%08d" % attempt
            tr.set(marker, b"x")
            local.set(marker, b"x")
            try:
                await tr.commit()
                committed = True
            except (NotCommitted, TransactionTooOld) as e:
                await tr.on_error(e)
                continue
            except CommitUnknownResult:
                # fence before probing (see ApiCorrectness._marker_exists:
                # a bare probe can read a GRV below the orphaned commit)
                async def fence(t):
                    t.set(self.prefix + b"fence", b"%d" % attempt)

                await self.db.run(fence)

                async def probe(t):
                    return await t.get(marker)

                committed = await self.db.run(probe) is not None
            if committed:
                # versionstamped keys land with the real stamp — the local
                # model can't predict them, so fold them in from the db
                self.model = local
                for body in unreadable:
                    self.model.clear_range(body, body + b"\xff")

                    async def sweep(t, body=body):
                        return await t.get_range(body, body + b"\xff")

                    for k, v in await self.db.run(sweep):
                        self.model.set(k, v)
                return
            # not committed: retry a fresh sequence

    async def _run_ops(self, tr, local, unreadable) -> bool:
        """Random ops; returns False when a mismatch was recorded."""
        for _ in range(1 + self.rng.random_int(0, self.ops_per_txn)):
            roll = self.rng.random01()
            if roll < 0.22:
                k, v = self._key(), b"v%d" % self.rng.random_int(0, 1 << 20)
                tr.set(k, v)
                local.set(k, v)
            elif roll < 0.32:
                k = self._key()
                tr.clear(k)
                local.clear(k)
            elif roll < 0.42:
                a = self.rng.random_int(0, self.keys)
                b = a + self.rng.random_int(0, max(2, self.keys // 3))
                tr.clear_range(self._key(a), self._key(b))
                local.clear_range(self._key(a), self._key(b))
            elif roll < 0.54:
                op = _ATOMICS[self.rng.random_int(0, len(_ATOMICS))]
                k = self._key()
                param = bytes(
                    self.rng.random_int(0, 256)
                    for _ in range(self.rng.random_choice([1, 4, 8]))
                )
                tr.atomic_op(op, k, param)
                local.atomic(op, k, param)
            elif roll < 0.60 and not unreadable:
                # pending versionstamped key: the literal placeholder key
                # is the unreadable WriteMap entry (the final key is
                # unknowable before commit)
                body = self.prefix + b"vs/%04d" % self.rng.random_int(0, 50)
                tr.set_versionstamped_key(
                    body + b"\x00" * 10 + struct.pack("<I", len(body)),
                    b"stamped",
                )
                unreadable.add(body)
            elif roll < 0.78:
                k = self._key()
                snapshot = self.rng.coinflip(0.3)
                got = await tr.get(k, snapshot=snapshot)
                want = local.get(k)
                if got != want:
                    self.errors.append(
                        f"in-txn get({k!r}, snap={snapshot}) = {got!r}, "
                        f"model {want!r}"
                    )
                    return False
            elif roll < 0.94:
                a = self.rng.random_int(0, self.keys)
                b = a + self.rng.random_int(1, max(2, self.keys // 2))
                lo, hi = self._key(a), self._key(b)
                reverse = self.rng.coinflip(0.4)
                limit = self.rng.random_choice([1, 2, 5, 64])
                got = await tr.get_range(lo, hi, limit=limit, reverse=reverse)
                want = local.get_range(lo, hi, limit=limit, reverse=reverse)
                if got != want:
                    self.errors.append(
                        f"in-txn range({lo!r},{hi!r},lim={limit},"
                        f"rev={reverse}) = {got} != {want}"
                    )
                    return False
            else:
                # unreadable corner: the pending versionstamped entry
                # lives at the literal placeholder key — a point read of
                # it, or a range read spanning it, MUST raise
                if unreadable:
                    body = next(iter(unreadable))
                    try:
                        if self.rng.coinflip():
                            await tr.get(body + b"\x00" * 10)
                            what = "point read"
                        else:
                            await tr.get_range(body, body + b"\xff", limit=64)
                            what = "range read"
                        self.errors.append(
                            f"{what} over unreadable {body!r} did not raise"
                        )
                        return False
                    except AccessedUnreadable:
                        pass
                    # a point read of the BARE body prefix is legal (it
                    # cannot be the stamped key) and must not throw
                    got = await tr.get(body)
                    want = local.get(body)
                    if got != want:
                        self.errors.append(
                            f"get({body!r}) near unreadable = {got!r}, "
                            f"model {want!r}"
                        )
                        return False
        return True

    async def start(self):
        for _ in range(self.transactions):
            await self._fuzz_one()
            if self.errors:
                return

    async def check(self) -> bool:
        async def sweep(tr):
            return await tr.get_range(
                self.prefix + b"k", self.prefix + b"k\xff"
            )

        got = await self.db.run(sweep)
        want = self.model.get_range(self.prefix + b"k", self.prefix + b"k\xff")
        if got != want:
            self.errors.append(
                f"final sweep: {got} != model {want}"
            )
        if self.errors:
            for e in self.errors[:5]:
                print("RywFuzz:", e)
        return not self.errors
