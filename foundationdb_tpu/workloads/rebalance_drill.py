"""Hot-prefix resolutionBalancing drill, shared by the test suite and
the driver's multichip dry run (one scenario, one maintained copy).

Drives all load into a prefix deep inside one resolver's partition,
waits for the balancer to move a boundary, then measures how post-move
traffic spreads. Returns (moves, gained_per_resolver)."""

from __future__ import annotations

from ..runtime.futures import delay
from ..runtime.loop import Cancelled


async def hot_prefix_rebalance(cluster, db, balancer, bursts=(150, 150)):
    async def burst(n):
        for i in range(n):
            tr = db.transaction()
            # confined to a hot prefix in resolver 1's half of the
            # keyspace (the static recruitment split is at 0x80)
            k = b"\xc0hot/%04d" % (i % 50)
            await tr.get(k)
            tr.set(k, b"v%d" % i)
            try:
                await tr.commit()
            except Cancelled:
                raise  # actor-cancelled-swallow
            except Exception:
                pass

    await burst(bursts[0])
    # let the balancer poll, split, and record the move
    for _ in range(12):
        await delay(0.5)
        if balancer.moves:
            break
    before = [int(r._c_txns.value) for r in cluster.resolvers]
    await burst(bursts[1])
    after = [int(r._c_txns.value) for r in cluster.resolvers]
    return balancer.moves, [a - b for b, a in zip(before, after)]
