"""QuietDatabase: wait for the cluster to settle before strict checks.

The analog of fdbserver/QuietDatabase.actor.cpp (waitForQuietDatabase):
before ConsistencyCheck, wait until data distribution has no in-flight
relocations and storage has caught up — otherwise the check races a
half-finished shard move and sees transient divergence.

Signals polled (the reference polls DD's MovingData/queue metrics and the
storage queue; this cluster's equivalents):
- the shard map is STABLE across two consecutive walks (no boundary or
  team changed — a relocation in flight changes one);
- every live member of every shard reports the shard fully readable
  (GET_SHARD_STATE — finishMoveKeys' own readiness poll);
- every storage server's durable version is within the configured lag of
  its current version (the storage-queue signal).
"""

from __future__ import annotations

from ..net.sim import Endpoint
from ..runtime.futures import delay, timeout
from ..server.interfaces import Tokens
from ..server.movekeys import walk_shards as _walk_shards
from ..runtime.loop import Cancelled


async def quiet_database(db, max_wait: float = 120.0, settle_polls: int = 2) -> None:
    """Park until the cluster is quiet; raises on timeout."""
    waited = 0.0
    prev = None
    stable = 0
    while waited < max_wait:
        try:
            shards = await _walk_shards(db)
            ok = True
            # every member readable for its whole shard
            for begin, end, team, _tags in shards:
                for addr in team:
                    r = await timeout(
                        db.client.request(
                            Endpoint(addr, Tokens.GET_SHARD_STATE),
                            (begin, end if end is not None else b"\xff\xff"),
                        ),
                        1.0,
                    )
                    if not r:
                        ok = False
                        break
                if not ok:
                    break
            if ok and shards == prev:
                stable += 1
                if stable >= settle_polls:
                    return
            else:
                stable = 0
            prev = shards
        except Cancelled:
            raise  # actor-cancelled-swallow
        except Exception:
            prev, stable = None, 0  # mid-recovery: start over
        await delay(1.0)
        waited += 1.0
    raise AssertionError(f"database did not quiet within {max_wait}s")
