"""Serializability: concurrent read-modify-write transactions whose commit
history must replay exactly against a model.

The analog of fdbserver/workloads/Serializability.actor.cpp, strengthened
into a total-order replay check: N clients race transactions over ONE
shared keyspace. Each transaction reads a random key set, then writes
values derived from everything it read, and records itself under a
versionstamped log key (the versionstamp IS the commit order). The check
phase replays the committed log in versionstamp order against a
ModelStore: at each step, every value the transaction claims to have read
must equal the model's current value — any snapshot that wasn't
serializable at its commit point (lost update, stale read admitted by the
resolver, write visible early) breaks the replay.
"""

from __future__ import annotations

import json
import struct
import zlib

from . import Workload
from ..errors import CommitUnknownResult, NotCommitted, TransactionTooOld
from ._model import ModelStore


class SerializabilityWorkload(Workload):
    PREFIX = b"ser/kv/"
    LOG = b"ser/log/"

    def __init__(self, db, rng, transactions=30, keys=12, **kw):
        super().__init__(db, rng, **kw)
        self.transactions = transactions
        self.keys = keys
        self._seq = 0

    def _key(self) -> bytes:
        return self.PREFIX + b"k%03d" % self.rng.random_int(0, self.keys)

    async def setup(self):
        if self.client_id != 0:
            return

        async def init(tr):
            for i in range(self.keys):
                tr.set(self.PREFIX + b"k%03d" % i, b"0")

        await self.db.run(init)

    async def _one_txn(self) -> None:
        n_reads = 1 + self.rng.random_int(0, 3)
        n_writes = 1 + self.rng.random_int(0, 2)
        read_keys = sorted({self._key() for _ in range(n_reads)})
        write_keys = sorted({self._key() for _ in range(n_writes)})
        while True:
            self._seq += 1
            seq = self._seq
            tr = self.db.transaction()
            try:
                reads = {}
                for k in read_keys:
                    v = await tr.get(k)
                    reads[k] = v.decode() if v is not None else None
                # crc32, not hash(): PYTHONHASHSEED would break seeded
                # reproducibility of the simulation
                digest = "%08x" % zlib.crc32(
                    repr(sorted(reads.items())).encode()
                )
                record = {
                    "client": self.client_id,
                    "seq": seq,
                    "reads": {k.decode(): v for k, v in reads.items()},
                    "writes": {},
                }
                for k in write_keys:
                    val = b"%s/%d/%d" % (digest.encode(), self.client_id, seq)
                    tr.set(k, val)
                    record["writes"][k.decode()] = val.decode()
                # versionstamped log key: commit order made durable
                placeholder = b"\x00" * 10
                log_key = (
                    self.LOG + placeholder + struct.pack("<I", len(self.LOG))
                )
                tr.set_versionstamped_key(
                    log_key, json.dumps(record).encode()
                )
                await tr.commit()
                return
            except (NotCommitted, TransactionTooOld) as e:
                await tr.on_error(e)
            except CommitUnknownResult:
                # the log record carries client+seq: if it landed, the
                # replay sees it exactly once; if not, we retry with a NEW
                # seq, so a duplicate can never masquerade as the same txn
                from ..runtime.futures import delay

                await delay(0.05)

    async def start(self):
        for _ in range(self.transactions):
            await self._one_txn()

    async def check(self) -> bool:
        if self.client_id != 0:
            return True  # one replayer sees every client's log

        async def read_log(tr):
            return await tr.get_range(self.LOG, self.LOG + b"\xff")

        rows = await self.db.run(read_log)
        model = ModelStore()
        for i in range(self.keys):
            model.set(self.PREFIX + b"k%03d" % i, b"0")
        seen = set()
        for log_key, blob in rows:  # key order == versionstamp order
            rec = json.loads(blob)
            ident = (rec["client"], rec["seq"])
            if ident in seen:
                print("Serializability: duplicate txn record", ident)
                return False
            seen.add(ident)
            for k, v in rec["reads"].items():
                got = model.get(k.encode())
                want = v.encode() if v is not None else None
                if got != want:
                    print(
                        f"Serializability: txn {ident} read {k}={v!r} but "
                        f"serial replay has {got!r}"
                    )
                    return False
            for k, v in rec["writes"].items():
                model.set(k.encode(), v.encode())
        return True
