"""RandomClogging workload: network fault injection during other workloads.

The analog of fdbserver/workloads/RandomClogging.actor.cpp over the
simulator's clogging API (fdbrpc/sim2.actor.cpp SimClogging:114): while
correctness workloads run, random process pairs get their traffic delayed.
Everything must still pass — the retry machinery, long-polls, and version
gates have to absorb arbitrary delay.
"""

from __future__ import annotations

from ..runtime.futures import delay
from . import Workload


class RandomCloggingWorkload(Workload):
    def __init__(self, db, rng, duration=5.0, interval=0.5, **kw):
        super().__init__(db, rng, **kw)
        self.duration = duration
        self.interval = interval
        self.clogs = 0

    async def start(self):
        sim = self.db.sim
        addrs = list(sim.processes)
        t_end = sim.loop.now() + self.duration
        while sim.loop.now() < t_end:
            a = self.rng.random_choice(addrs)
            b = self.rng.random_choice(addrs)
            if a != b:
                sim.clog_pair(a, b, self.rng.random01() * self.interval * 2)
                self.clogs += 1
            await delay(self.interval * self.rng.random01())
